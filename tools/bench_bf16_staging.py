"""bf16-staged end-to-end benchmark (VERDICT r3 item 5), interleaved A/B.

The end-to-end solve on this link is transfer-bound (fetch ~3.7 s vs
~0.1 s device solve in BENCH_r03): staging attrs in bfloat16 halves the
upload bytes. This tool measures exact-mode (f64 host rescore -> checksum
parity) f32-staged vs bf16-staged runs INTERLEAVED (the BENCH_MODES_r04
methodology, so link weather hits both equally), verifies both produce
IDENTICAL results query-for-query, and reports the bf16 tie-overflow
repair rate (bf16's coarser distances make boundary ties more frequent).

Writes a schema RunRecord (obs.run) to BENCH_BF16_r06.json — the
ledger-ingestible artifact form (python -m dmlp_tpu.report); the r04
ad-hoc shape is grandfathered. Env: BENCH_REPS (default 5), shape knobs
as in bench.py, BENCH_OUT.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import _env_int, make_workload  # noqa: E402


def main() -> int:
    import jax

    from dmlp_tpu.config import EngineConfig
    from dmlp_tpu.engine.single import SingleChipEngine
    from dmlp_tpu.io.report import format_results
    from dmlp_tpu.ops.pallas_distance import native_pallas_backend

    num_data = _env_int("BENCH_NUM_DATA", 200_000)
    num_queries = _env_int("BENCH_NUM_QUERIES", 10_000)
    num_attrs = _env_int("BENCH_NUM_ATTRS", 64)
    k = _env_int("BENCH_K", 32)
    reps = _env_int("BENCH_REPS", 5)
    out_path = os.environ.get("BENCH_OUT", "BENCH_BF16_r06.json")

    inp = make_workload(num_data, num_queries, num_attrs, k)
    use_pallas = native_pallas_backend()
    engines = {
        "f32": SingleChipEngine(EngineConfig(exact=True, dtype="float32",
                                             query_block=16384,
                                             use_pallas=use_pallas)),
        "bf16": SingleChipEngine(EngineConfig(exact=True, dtype="bfloat16",
                                              query_block=16384,
                                              use_pallas=use_pallas)),
    }
    names = list(engines)

    # Warmup (compile) + capture results for the parity check.
    results = {}
    for name in names:
        results[name] = engines[name].run(inp)
    parity = (format_results(results["f32"])
              == format_results(results["bf16"]))

    times: dict = {name: [] for name in names}
    repairs: dict = {name: [] for name in names}
    for rep in range(reps):
        order = names if rep % 2 == 0 else names[::-1]
        for name in order:
            t0 = time.perf_counter()
            engines[name].run(inp)
            times[name].append(round((time.perf_counter() - t0) * 1e3, 1))
            repairs[name].append(getattr(engines[name], "last_repairs", None))

    from dmlp_tpu.obs.run import RunRecord, current_device, round_from_name

    metrics = {"results_identical": bool(parity)}
    for name in names:
        metrics[f"{name}_median_ms"] = float(np.median(times[name]))
        metrics[f"{name}_min_ms"] = float(np.min(times[name]))
        metrics[f"{name}_times_ms"] = times[name]
        metrics[f"{name}_repairs"] = repairs[name]
        metrics[f"{name}_staged_attr_mb"] = round(
            (num_data + num_queries) * num_attrs
            * (2 if name == "bf16" else 4) / 1e6, 1)
    RunRecord(
        kind="bench", tool="tools.bench_bf16_staging",
        config={"note": "Exact-mode (f64 host rescore) end-to-end "
                        "engine.run(), f32-staged vs bf16-staged, "
                        "interleaved A/B reps (alternating order) on "
                        "the tunneled link; results_identical verifies "
                        "query-for-query parity, repairs counts "
                        "oracle-repair fallbacks.",
                "num_data": num_data, "num_queries": num_queries,
                "num_attrs": num_attrs, "k": k, "reps": reps,
                "use_pallas": use_pallas,
                "platform": jax.devices()[0].platform,
                "select": {n: getattr(engines[n], "_last_select", None)
                           for n in names}},
        metrics=metrics, device=current_device(),
        round=round_from_name(out_path)).write(out_path)
    print(json.dumps({n: {"median_ms": float(np.median(times[n])),
                          "min_ms": float(np.min(times[n]))}
                      for n in names} | {"identical": bool(parity)}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
