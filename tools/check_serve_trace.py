#!/usr/bin/env python
"""Validate a serve trace file (serve_trace_schema 1).

The check_trace-style gate for the replay/SLO harness: structural
field checks plus the paced-replay contract — ``t_ms`` offsets must be
non-negative and MONOTONIC non-decreasing (open-loop replay fires
requests at their offsets; a backwards offset would silently reorder
the offered-load schedule the trace claims to encode). Exit 0 when
valid, 1 with the problems named otherwise.

Usage::

    python tools/check_serve_trace.py inputs/serve_trace2.jsonl [--json]

``--json`` prints a pure-JSON verdict on stdout (narration to stderr),
following the tools/check_trace.py convention.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from dmlp_tpu.serve import client as sc  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("trace", help="serve trace JSONL file")
    ap.add_argument("--json", action="store_true",
                    help="pure-JSON verdict on stdout")
    args = ap.parse_args(argv)

    problems = []
    requests = 0
    span_ms = None
    try:
        # Parse leniently here (load_trace itself now raises on the
        # problems this tool exists to REPORT).
        with open(args.trace) as f:
            lines = [json.loads(ln) for ln in f if ln.strip()]
        if not lines:
            problems.append("empty file")
            header, reqs = {}, []
        else:
            header, reqs = lines[0], lines[1:]
            problems.extend(sc.validate_trace(header, reqs))
            requests = len(reqs)
            ts = [r["t_ms"] for r in reqs if isinstance(r, dict)
                  and isinstance(r.get("t_ms"), (int, float))]
            span_ms = max(ts) if ts else None
    except (OSError, ValueError) as e:
        problems.append(f"unreadable: {e}")

    verdict = {
        "trace": args.trace,
        "valid": not problems,
        "requests": requests,
        "span_ms": span_ms,
        "problems": problems,
    }
    if args.json:
        print(json.dumps(verdict, sort_keys=True))
        out = sys.stderr
    else:
        out = sys.stdout
    if problems:
        print(f"check_serve_trace: INVALID {args.trace}:", file=out)
        for p in problems[:10]:
            print(f"  - {p}", file=out)
        return 1
    print(f"check_serve_trace: OK {args.trace} ({requests} requests"
          + (f", span {span_ms} ms" if span_ms is not None else "")
          + ")", file=out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
