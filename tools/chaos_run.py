#!/usr/bin/env python
"""Chaos harness: prove byte-identical recovery under seeded fault
schedules (``make chaos-smoke``).

The resilience subsystem's contract is not "survives faults" but
"survives faults *without changing answers*" — recovery that perturbs
the contract checksums is a correctness bug wearing a safety vest. This
harness enforces that end to end:

1. **Golden run** — bench config 1 through the real CLI, fault-free:
   its stdout bytes are the reference.
2. **Seeded fault schedules** — three kinds, each randomized from a
   seeded PRNG (different seeds explore different fault placements,
   the same seed reproduces exactly):

   - ``straggler``  injected delays at staging/readback;
   - ``transient``  injected transient exceptions at staging/readback
     plus a corrupted parse payload (retry/backoff recovery);
   - ``oom``        simulated RESOURCE_EXHAUSTED driving the
     degradation ladder 1-3 rungs down (heuristic variant, streaming
     fold, or the float64 host oracle).

   Each faulted run must produce stdout **byte-identical** to the
   golden run, must actually FIRE faults (vacuous chaos is failure),
   and must surface its recovery in the metrics summary's
   ``resilience`` block and as ``resilience.*`` trace events.
3. **Deterministic replay** — one schedule runs twice; the two
   injection logs must be byte-identical.
4. **Train chaos** — a short ``--nan-guard`` train run with an
   injected NaN at one step must report a rollback AND finish with the
   same ``params_checksum`` + final loss as the fault-free run
   (step-identical recovery).
5. **Zero-fault overhead** — interleaved A/B pairs with the resilience
   layer disabled ($DMLP_TPU_RESILIENCE=0) vs enabled (no faults),
   recorded as ``resilience_overhead_pct`` in a ledger-ingestible
   RunRecord (the PR 5 ``--obs-overhead`` pattern).

Usage::

    python tools/chaos_run.py [--smoke] [--base-dir .]
        [--out outputs/chaos] [--record FILE] [--seed-base N]
        [--overhead-pairs N] [--no-train] [--timeout S]

Exit 0 when every invariant holds; 1 with a message naming the first
violation.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import re
import statistics
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CONFIG_ID = 1


def fail(msg: str):
    print(f"chaos_run: FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def _extract_ms(err_text: str):
    m = re.search(r"Time taken:\s*(\d+)", err_text)
    return int(m.group(1)) if m else None


def run_engine(input_path: str, extra_argv=None, env_extra=None,
               timeout_s: float = 300.0):
    """One engine CLI subprocess; returns (stdout bytes, stderr text)."""
    argv = [sys.executable, "-m", "dmlp_tpu"] + list(extra_argv or [])
    env = dict(os.environ)
    env.update(env_extra or {})
    with open(input_path, "rb") as stdin:
        proc = subprocess.run(argv, stdin=stdin, stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE, env=env,
                              timeout=timeout_s)
    if proc.returncode != 0:
        fail(f"engine exited {proc.returncode}: "
             f"{proc.stderr.decode()[-2000:]}")
    return proc.stdout, proc.stderr.decode()


# -- seeded schedule generators ---------------------------------------------

def make_schedule(kind: str, seed: int) -> dict:
    """A randomized-but-seeded fault schedule of one chaos kind. The
    PRNG draws the fault placement/intensity, so different seeds
    explore the space while any one seed replays exactly."""
    rng = random.Random(seed)
    if kind == "straggler":
        faults = [
            {"site": "single.stage_put", "kind": "delay",
             "ms": rng.randint(10, 60), "times": rng.randint(1, 3)},
            {"site": "single.fetch", "kind": "delay",
             "ms": rng.randint(10, 40), "times": 1},
        ]
    elif kind == "transient":
        faults = [
            {"site": "single.stage_put", "kind": "transient",
             "times": rng.randint(1, 2)},
            {"site": "single.fetch", "kind": "transient", "times": 1},
            {"site": "io.parse", "kind": "corrupt"},
        ]
    elif kind == "oom":
        # times = how deep the ladder steps from the prune top rung:
        # 1 -> dense fused, 2 -> tuned two-pass kernel,
        # 3 -> heuristic variant.
        faults = [{"site": "single.stage_put", "kind": "oom",
                   "times": rng.randint(1, 3)}]
    else:
        raise ValueError(f"unknown chaos kind {kind!r}")
    return {"schema": 1, "seed": seed, "faults": faults}


def check_faulted_run(kind: str, golden: bytes, out_b: bytes,
                      log_path: str, metrics_path: str, trace_path: str):
    """The per-schedule invariants: byte identity, non-vacuous firing,
    visible recovery."""
    if out_b != golden:
        fail(f"{kind}: faulted stdout differs from the golden run — "
             "recovery changed answers")
    with open(log_path) as f:
        log = json.load(f)["log"]
    fired = [e for e in log if e["fired"]]
    if not fired:
        fail(f"{kind}: schedule fired no faults — vacuous chaos")
    with open(metrics_path) as f:
        summary = [json.loads(ln) for ln in f if ln.strip()][-1]
    res = summary.get("resilience")
    if not isinstance(res, dict):
        fail(f"{kind}: metrics summary has no resilience block")
    if res["faults_injected"] < len(fired):
        fail(f"{kind}: stats report {res['faults_injected']} faults, "
             f"log shows {len(fired)}")
    if kind == "transient" and res["retries"] < 1:
        fail(f"{kind}: transient faults fired but retries == 0")
    if kind == "oom" and not res["degradations"]:
        fail(f"{kind}: oom fired but the ladder recorded no degradation")
    with open(trace_path) as f:
        names = {e.get("name", "") for e in json.load(f)["traceEvents"]}
    if not any(n.startswith("resilience.") for n in names):
        fail(f"{kind}: no resilience.* events in the trace — recovery "
             "was invisible")
    return {"kind": kind, "fired": len(fired),
            "retries": res["retries"],
            "degradations": res["degradations"]}


def measure_overhead(input_path: str, pairs: int, timeout_s: float):
    """Interleaved resilience off/on engine pairs, no faults — the
    zero-fault cost of the wrappers (PR 5 --obs-overhead pattern)."""
    times = {"off": [], "on": []}
    for rep in range(max(pairs, 1)):
        order = ("off", "on") if rep % 2 == 0 else ("on", "off")
        for arm in order:
            env = {"DMLP_TPU_RESILIENCE": "0"} if arm == "off" else {}
            _, err = run_engine(input_path, env_extra=env,
                                timeout_s=timeout_s)
            ms = _extract_ms(err)
            if ms is None:
                fail(f"overhead A/B: no timing line in the {arm} arm")
            times[arm].append(ms)
    med_off = statistics.median(times["off"])
    med_on = statistics.median(times["on"])
    out = {"engine_ms_resilience_off": times["off"],
           "engine_ms_resilience_on": times["on"]}
    if med_off <= 0:
        out["resilience_overhead_unavailable"] = \
            "off-arm median rounded to 0 ms"
    else:
        out["resilience_overhead_pct"] = round(
            (med_on - med_off) / med_off * 100.0, 2)
    return out


def run_train_chaos(out_dir: str, timeout_s: float):
    """Fault-free vs NaN-faulted --nan-guard train runs must agree
    bitwise on params and final loss (step-identical rollback)."""
    sched = {"schema": 1, "seed": 5, "faults": [
        {"site": "train.step", "kind": "nan", "when": {"step": 4}}]}
    sched_path = os.path.join(out_dir, "sched_train_nan.json")
    with open(sched_path, "w") as f:
        json.dump(sched, f)

    def run(tag: str, faults: bool) -> dict:
        rec = os.path.join(out_dir, f"train_{tag}.json")
        # Fresh checkpoint dir every invocation: a stale checkpoint from
        # a previous chaos run sits AHEAD of this run's steps, and a
        # rollback that restores it would jump the loop forward instead
        # of back (the loop refuses, but the harness must not set the
        # trap in the first place).
        import shutil
        ck_dir = os.path.join(out_dir, f"ck_{tag}")
        shutil.rmtree(ck_dir, ignore_errors=True)
        argv = [sys.executable, "-m", "dmlp_tpu.train.loop",
                "--steps", "6", "--batch", "128", "--dims", "16,32,10",
                "--mesh", "1,1", "--log-every", "3", "--ckpt-every", "2",
                "--checkpoint-dir", ck_dir,
                "--nan-guard", "--record", rec]
        env = dict(os.environ)
        if faults:
            argv += ["--faults", sched_path]
            env["DMLP_TPU_FAULT_LOG"] = os.path.join(
                out_dir, "train_fault_log.json")
        proc = subprocess.run(argv, stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE, env=env,
                              timeout=timeout_s)
        if proc.returncode != 0:
            fail(f"train {tag} exited {proc.returncode}: "
                 f"{proc.stderr.decode()[-2000:]}")
        with open(rec) as f:
            return json.load(f)

    plain = run("plain", faults=False)
    faulted = run("faulted", faults=True)
    pm, fm = plain["metrics"], faulted["metrics"]
    if fm.get("resilience", {}).get("rollbacks") != 1:
        fail(f"train chaos: expected exactly 1 rollback, got "
             f"{fm.get('resilience')}")
    if pm["params_checksum"] != fm["params_checksum"]:
        fail("train chaos: faulted run's params differ from fault-free "
             "(rollback was not step-identical)")
    if pm["loss"] != fm["loss"]:
        fail(f"train chaos: final loss differs "
             f"({pm['loss']} != {fm['loss']})")
    return {"rollbacks": 1, "params_checksum": pm["params_checksum"]}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true",
                    help="CI size: 1 overhead pair (full: 3)")
    ap.add_argument("--base-dir", default=".")
    ap.add_argument("--out", default="outputs/chaos",
                    help="schedule/log/trace artifact directory")
    ap.add_argument("--record", default=None, metavar="FILE",
                    help="append the chaos RunRecord (JSONL) to FILE — "
                         "the ledger-ingestible artifact")
    ap.add_argument("--seed-base", type=int, default=1000)
    ap.add_argument("--overhead-pairs", type=int, default=None)
    ap.add_argument("--no-train", action="store_true")
    ap.add_argument("--timeout", type=float, default=300.0)
    args = ap.parse_args(argv)

    os.makedirs(args.out, exist_ok=True)
    from dmlp_tpu.bench.configs import BENCH_CONFIGS
    from dmlp_tpu.bench.harness import ensure_input
    input_path = ensure_input(BENCH_CONFIGS[CONFIG_ID],
                              os.path.join(args.base_dir, "inputs"))

    print("chaos_run: golden (fault-free) run ...")
    golden, golden_err = run_engine(input_path, timeout_s=args.timeout)
    if _extract_ms(golden_err) is None:
        fail("golden run produced no 'Time taken' line")

    results = []
    replay_first = None
    for i, kind in enumerate(("straggler", "transient", "oom")):
        seed = args.seed_base + i
        sched = make_schedule(kind, seed)
        sched_path = os.path.join(args.out, f"sched_{kind}_{seed}.json")
        with open(sched_path, "w") as f:
            json.dump(sched, f, indent=1)
        log_path = os.path.join(args.out, f"log_{kind}.json")
        trace_path = os.path.join(args.out, f"trace_{kind}.json")
        metrics_path = os.path.join(args.out, f"metrics_{kind}.jsonl")
        if os.path.exists(metrics_path):
            os.remove(metrics_path)
        out_b, _ = run_engine(
            input_path,
            extra_argv=["--faults", sched_path, "--trace", trace_path,
                        "--metrics", metrics_path],
            env_extra={"DMLP_TPU_FAULT_LOG": log_path},
            timeout_s=args.timeout)
        r = check_faulted_run(kind, golden, out_b, log_path,
                              metrics_path, trace_path)
        print(f"chaos_run: {kind} ok — byte-identical, "
              f"{r['fired']} fault(s) fired, {r['retries']} retries, "
              f"degradations {r['degradations']}")
        results.append(r)
        if kind == "transient":
            replay_first = (sched_path, log_path)

    # Deterministic replay: same schedule + seed -> same injection log.
    sched_path, log_path = replay_first
    with open(log_path) as f:
        first_log = f.read()
    log2 = os.path.join(args.out, "log_transient_replay.json")
    out_b, _ = run_engine(input_path,
                          extra_argv=["--faults", sched_path],
                          env_extra={"DMLP_TPU_FAULT_LOG": log2},
                          timeout_s=args.timeout)
    if out_b != golden:
        fail("replay: stdout diverged")
    with open(log2) as f:
        second_log = f.read()
    if first_log != second_log:
        fail("replay: same schedule + seed produced a DIFFERENT "
             "injection log — injection is not deterministic")
    print("chaos_run: deterministic replay ok — injection logs "
          "byte-identical")

    train_summary = None
    if not args.no_train:
        train_summary = run_train_chaos(args.out, args.timeout)
        print("chaos_run: train NaN rollback ok — step-identical "
              f"(checksum {train_summary['params_checksum'][:16]}...)")

    pairs = args.overhead_pairs or (1 if args.smoke else 3)
    overhead = measure_overhead(input_path, pairs, args.timeout)
    print(f"chaos_run: zero-fault overhead "
          f"{overhead.get('resilience_overhead_pct', 'n/a')}% over "
          f"{pairs} interleaved pair(s) "
          f"(off {overhead['engine_ms_resilience_off']} ms, "
          f"on {overhead['engine_ms_resilience_on']} ms)")

    if args.record:
        from dmlp_tpu.obs.run import (RunRecord, current_device,
                                      round_from_name)
        RunRecord(
            kind="chaos", tool="tools.chaos_run",
            config={"config": CONFIG_ID, "seed_base": args.seed_base,
                    "smoke": args.smoke, "overhead_pairs": pairs},
            metrics={"byte_identical": True,
                     "replay_deterministic": True,
                     "schedules": results,
                     **({"train": train_summary} if train_summary
                        else {}),
                     **overhead},
            device=current_device(),
            round=round_from_name(args.record)).append_jsonl(args.record)
    print("chaos_run: all chaos invariants hold")
    return 0


if __name__ == "__main__":
    sys.exit(main())
