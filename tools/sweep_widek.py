"""Wide-k extraction-kernel tuning sweep (VERDICT r3 item 4).

SCALE_r03 showed the extraction solve degrading 1.64x from kcap 40 to 136
(98 -> 161 ms at 204800 x 10240 x 64) with the k=40-tuned defaults
(tq=128, tn=12800, ne=2, unroll=1). This sweep times the fenced kernel
(label-gather/sort epilogue included, like bench.py) across kcap in
{64, 136, 256, 512} x a variant grid over (tile_q, ne, unroll), so the
engine can pick per-kc tuning instead of one-size-fits-all.

Writes SWEEP_WIDEK_r{N}.jsonl: one schema-1 RunRecord (obs.run) per
line — config carries (kc, variant), metrics the fenced timing — plus a
final ``kind: "sweep_widek_summary"`` record with best_per_kc. Env:
BENCH_REPEATS (default 3), BENCH_OUT.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import (_env_int, make_workload, stage_extract_inputs,  # noqa: E402
                   time_fenced_solve_ms)


def main() -> int:
    from dmlp_tpu.engine.single import _extract_finalize
    from dmlp_tpu.ops.pallas_distance import native_pallas_backend
    from dmlp_tpu.ops.pallas_extract import BLOCK_ROWS, extract_topk

    if not native_pallas_backend():
        print("needs the native TPU backend", file=sys.stderr)
        return 1

    repeats = _env_int("BENCH_REPEATS", 3)
    out_path = os.environ.get("BENCH_OUT", "SWEEP_WIDEK_r06.jsonl")
    n, nq, na = 204800, 10240, 64
    inp = make_workload(n, nq, na, 32)
    q, d, lab, npad, qpad = stage_extract_inputs(inp)

    kcs = [int(x) for x in os.environ.get(
        "BENCH_KCS", "64,136,256,512").split(",")]
    # kc is padded to 8 by the engines; 136 is SCALE_r03's literal rung.
    variants = [
        {"tile_q": 128, "ne": 2, "unroll": 1},   # r3 default
        {"tile_q": 64, "ne": 2, "unroll": 1},
        {"tile_q": 256, "ne": 2, "unroll": 1},
        {"tile_q": 128, "ne": 4, "unroll": 1},
        {"tile_q": 64, "ne": 4, "unroll": 1},
        {"tile_q": 128, "ne": 2, "unroll": 2},
    ]
    if os.environ.get("BENCH_VARIANTS"):
        variants = json.loads(os.environ["BENCH_VARIANTS"])

    from dmlp_tpu.engine.single import round_up
    from dmlp_tpu.obs.run import RunRecord

    shape = {"num_data": n, "num_queries": nq, "num_attrs": na}
    if os.path.exists(out_path):
        os.remove(out_path)  # fresh sweep; append_jsonl accumulates below
    results = []
    for kc in kcs:
        kcp = round_up(kc, 8)
        for v in variants:
            def fn(q_, d_):
                od, oi, _ = extract_topk(q_, d_, n_real=n, kc=kcp,
                                         tile_n=BLOCK_ROWS, **v)
                return _extract_finalize(od, oi, lab, k=kcp).dists

            try:
                t0 = time.perf_counter()
                _ = float(fn(q, d)[0, 0])  # compile + fence
                compile_s = time.perf_counter() - t0
                metrics = {"ms": round(time_fenced_solve_ms(fn, q, d,
                                                            repeats), 1),
                           "compile_s": round(compile_s, 1)}
            except Exception as e:  # noqa: BLE001 — record, keep sweeping
                metrics = {"error": repr(e)[:200]}
            rec = {"kc": kcp, **v, **metrics}
            RunRecord(kind="sweep_widek", tool="tools/sweep_widek",
                      config={"kc": kcp, "variant": v, "shape": shape,
                              "repeats": repeats},
                      metrics=metrics).append_jsonl(out_path)
            print(json.dumps(rec), flush=True)
            results.append(rec)

    best = {}
    for rec in results:
        if "ms" in rec and rec["ms"] < best.get(rec["kc"], {}).get("ms", 1e18):
            best[rec["kc"]] = rec
    RunRecord(kind="sweep_widek_summary", tool="tools/sweep_widek",
              config={"shape": shape, "kcs": kcs},
              metrics={"best_per_kc": {str(k): v for k, v in best.items()}},
              ).append_jsonl(out_path)
    print(json.dumps({"best_per_kc": best}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
