#!/usr/bin/env bash
# capture_oracle.sh — record the reference oracle binaries' ground truth.
#
# The four stripped engines (/root/reference/benchmarks/bench_1..4, invoked
# at run_bench.sh:82-84) are the reference's ground truth, but they need a
# working Open MPI runtime (orted) and an x86-64 host — absent from the TPU
# container (SURVEY.md §2 #9, README "Ground-truth scope"). Run THIS script
# on any x86+OpenMPI host with the reference checkout; it:
#
#   1. regenerates the repo's seeded benchmark inputs 1-3 with the
#      reference's own generate_input.py (identical grammar + RNG draws;
#      shapes = dmlp_tpu/bench/configs.py BENCH_CONFIGS, seed 42),
#   2. runs bench_1 < input1, bench_2 < input2, bench_3 < input2,
#      bench_4 < input3 under `mpirun --timeout 300 --bind-to hwthread`
#      exactly like run_bench.sh (task counts configurable: the original
#      used 24/32/80/24 ranks over 2 SLURM nodes),
#   3. saves stdout (the per-query checksums), stderr (the `Time taken:`
#      line) and an ORACLE_GOLDEN.json manifest.
#
# Back in the repo, `python tools/oracle_diff.py ORACLE_GOLDEN.json` diffs
# the captured checksums against this framework's engines on the very same
# inputs — upgrading "parity vs our golden model" to "parity vs the
# reference binaries".
#
# Usage:
#   ./capture_oracle.sh [-r REF_DIR] [-o OUT_DIR] [-n "NP1 NP2 NP3 NP4"]
#     REF_DIR: reference checkout (default /root/reference)
#     OUT_DIR: capture directory (default ./oracle_capture)
#     NPi:     mpirun task count per config (default: nproc, capped at 24)
set -euo pipefail

REF_DIR=/root/reference
OUT_DIR=./oracle_capture
NPROCS=""
while getopts "r:o:n:" opt; do
  case $opt in
    r) REF_DIR=$OPTARG ;;
    o) OUT_DIR=$OPTARG ;;
    n) NPROCS=$OPTARG ;;
    *) echo "usage: $0 [-r REF_DIR] [-o OUT_DIR] [-n \"NP1 NP2 NP3 NP4\"]" >&2
       exit 2 ;;
  esac
done

command -v python3 >/dev/null || { echo "FATAL: python3 not found" >&2; exit 1; }
# No mpirun? Open MPI can still run a linked binary as an ISOLATED
# SINGLETON (one rank, no orted): OMPI_MCA_ess_singleton_isolated=1.
# Checksums are rank-count-independent (the stdout contract is
# deterministic; only wall time changes), so a singleton capture is
# valid ground truth for parity — it is how the capture ran inside the
# TPU container itself (ORACLE_GOLDEN.json, np=1).
MPIRUN_MODE=mpirun
command -v mpirun >/dev/null || {
  echo ">> mpirun not found — using isolated-singleton launch (np=1)";
  MPIRUN_MODE=singleton; }
[ -x "$REF_DIR/benchmarks/bench_1" ] || {
  echo "FATAL: $REF_DIR/benchmarks/bench_1 missing/not executable" >&2; exit 1; }

DEFAULT_NP=$(( $(nproc) < 24 ? $(nproc) : 24 ))
read -r NP1 NP2 NP3 NP4 <<< "${NPROCS:-$DEFAULT_NP $DEFAULT_NP $DEFAULT_NP $DEFAULT_NP}"
if [ "$MPIRUN_MODE" = singleton ]; then
  NP1=1; NP2=1; NP3=1; NP4=1
fi

mkdir -p "$OUT_DIR"

# --- 1. regenerate the seeded inputs (shapes = bench/configs.py) ---------
gen() { # name num_data num_queries num_attrs min max minK maxK labels
  local name=$1
  if [ ! -f "$OUT_DIR/$name" ]; then
    echo ">> generating $name ($2 x $3 x $4)"
    python3 "$REF_DIR/generate_input.py" \
      --num_data "$2" --num_queries "$3" --num_attrs "$4" \
      --min "$5" --max "$6" --minK "$7" --maxK "$8" --num_labels "$9" \
      --seed 42 --output "$OUT_DIR/$name"
  fi
}
gen input1.in 20000  1000  32 0.0 100.0 1 16 10
gen input2.in 100000 5000  64 0.0 100.0 1 32 10
gen input3.in 200000 10000 64 0.0 100.0 1 32 10

# --- 2. run each oracle binary exactly as run_bench.sh does --------------
run_cfg() { # cfg bench input np
  local cfg=$1 bench=$2 input=$3 np=$4
  local out="$OUT_DIR/oracle_${cfg}.out" err="$OUT_DIR/oracle_${cfg}.err"
  if [ -s "$out" ]; then
    echo ">> config $cfg cached ($out)"; return
  fi
  # Write to temp files and mv only on success: a timed-out or killed
  # run must not leave a truncated .out that a rerun would treat as a
  # valid cache (and ship as ground truth).
  if [ "$MPIRUN_MODE" = mpirun ]; then
    echo ">> config $cfg: mpirun -np $np $bench < $input"
    mpirun -np "$np" --timeout 300 --bind-to hwthread \
      "$REF_DIR/benchmarks/$bench" < "$OUT_DIR/$input" \
      > "$out.tmp" 2> "$err.tmp"
  else
    echo ">> config $cfg: isolated singleton $bench < $input"
    np=1
    OMPI_MCA_ess_singleton_isolated=1 \
      timeout 300 "$REF_DIR/benchmarks/$bench" < "$OUT_DIR/$input" \
      > "$out.tmp" 2> "$err.tmp"
  fi
  mv "$out.tmp" "$out"
  mv "$err.tmp" "$err"
  # Record how THIS config actually ran: the manifest reads these, so a
  # rerun on a different host/launcher that hits the cache cannot stamp
  # cached outputs with the new launcher's metadata.
  echo "$np $MPIRUN_MODE" > "$OUT_DIR/launch_$cfg"
}
run_cfg 1 bench_1 input1.in "$NP1"
run_cfg 2 bench_2 input2.in "$NP2"
run_cfg 3 bench_3 input2.in "$NP3"
run_cfg 4 bench_4 input3.in "$NP4"

# --- 3. manifest ---------------------------------------------------------
python3 - "$OUT_DIR" "$MPIRUN_MODE" "$NP1" "$NP2" "$NP3" "$NP4" <<'PY'
import hashlib, json, os, platform, re, subprocess, sys
out_dir, mode, *nps = sys.argv[1:]
sha = lambda p: hashlib.sha256(open(p, "rb").read()).hexdigest()
cfgs = {1: "input1.in", 2: "input2.in", 3: "input2.in", 4: "input3.in"}
if mode == "mpirun":
    launcher = subprocess.run(["mpirun", "--version"], capture_output=True,
                              text=True).stdout.splitlines()[0]
else:
    launcher = "isolated singleton (OMPI_MCA_ess_singleton_isolated=1)"
manifest = {"host": platform.platform(), "nproc": os.cpu_count(),
            "launch": launcher,
            "configs": {}}
for cfg, inp in cfgs.items():
    err = open(os.path.join(out_dir, f"oracle_{cfg}.err")).read()
    m = re.search(r"Time taken: (\d+) ms", err)
    outp = os.path.join(out_dir, f"oracle_{cfg}.out")
    lines = sorted(open(outp).read().splitlines())
    # Per-config launch sidecar (written at RUN time): cached outputs
    # keep their true np/launcher even if this manifest step reruns
    # under a different launcher.
    np_cfg, mode_cfg = int(nps[cfg - 1]), mode
    lp = os.path.join(out_dir, f"launch_{cfg}")
    if os.path.exists(lp):
        parts = open(lp).read().split()
        np_cfg, mode_cfg = int(parts[0]), parts[1]
    manifest["configs"][str(cfg)] = {
        "bench": f"bench_{cfg}", "input": inp,
        "input_sha256": sha(os.path.join(out_dir, inp)),
        "np": np_cfg, "launch_mode": mode_cfg,
        "time_taken_ms": int(m.group(1)) if m else None,
        "n_queries_reported": len(set(lines)),
        "checksums_sha256": hashlib.sha256(
            "\n".join(sorted(set(lines))).encode()).hexdigest(),
        "out_file": f"oracle_{cfg}.out",
    }
path = os.path.join(out_dir, "ORACLE_GOLDEN.json")
json.dump(manifest, open(path, "w"), indent=1)
print(f">> wrote {path}")
PY
echo "Done. Copy $OUT_DIR back into the repo and run:"
echo "  python tools/oracle_diff.py $OUT_DIR/ORACLE_GOLDEN.json"
