#!/usr/bin/env python
"""Fleet SLO bench: the p99-under-offered-load curve as ledger rounds.

Spawns N daemon replicas + the fleet router over a paced trace, then
replays the trace OPEN-LOOP (serve.client.replay_open_loop — requests
fire on the t_ms schedule regardless of completions, so daemon-side
queueing lands in the latency quantiles) at a sweep of offered-load
multipliers, ``--reps`` times per level. One RunRecord per level lands
in ``--metrics`` (kind "fleet" -> ``fleet/<level>/<metric>`` series,
gated by ``make perf-gate``); the router's closed-loop snapshot record
rides along under level "router".

Not part of ``make test`` (``make fleet-smoke`` is the CI gate); this
is the FLEET_rNN emitter. On a TPU host drop JAX_PLATFORMS and pass
``--replica-flags "--pallas --select extract"``.

Usage::

    JAX_PLATFORMS=cpu python tools/fleet_bench.py \
        --metrics FLEET_r14.jsonl [--replicas 2] [--reps 3] \
        [--speeds 1,2,4,8] [--trace inputs/serve_trace2.jsonl] \
        [--mesh-replica] [--replica-flags "..."]
"""

from __future__ import annotations

import argparse
import os
import shlex
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from dmlp_tpu.fleet import harness as fh     # noqa: E402
from dmlp_tpu.fleet import loadgen           # noqa: E402
from dmlp_tpu.io.grammar import parse_input_text  # noqa: E402
from dmlp_tpu.serve import client as sc      # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--trace", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "inputs", "serve_trace2.jsonl"))
    ap.add_argument("--metrics", required=True,
                    help="append fleet RunRecords (JSONL) here")
    ap.add_argument("--out", default="outputs/fleet_bench",
                    help="scratch dir for corpus/ready/logs")
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--mesh-replica", action="store_true",
                    help="make the last replica mesh-resident (2x1)")
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--speeds", default="1,2,4,8",
                    help="offered-load multipliers of the trace pace")
    ap.add_argument("--batch-cap", type=int, default=32)
    ap.add_argument("--replica-flags", default="",
                    help="extra daemon flags (quoted)")
    args = ap.parse_args(argv)

    out = os.path.abspath(args.out)
    os.makedirs(out, exist_ok=True)
    metrics_path = os.path.abspath(args.metrics)
    speeds = [float(s) for s in args.speeds.split(",") if s.strip()]

    header, reqs = sc.load_trace(args.trace)
    corpus_txt = sc.corpus_text(header)
    corpus_path = os.path.join(out, "corpus.in")
    with open(corpus_path, "w") as f:
        f.write(corpus_txt)
    corpus = parse_input_text(corpus_txt)
    golden = sc.golden_reference(corpus, header, reqs)
    warm = ",".join(f"{q}x{k}" for q, k in
                    sc.warm_buckets_for_trace(reqs, args.batch_cap))
    flags = shlex.split(args.replica_flags)

    replicas = []
    router = None
    router_record = os.path.join(out, "ROUTER_RECORD.jsonl")
    if os.path.exists(router_record):
        os.remove(router_record)
    try:
        for i in range(args.replicas):
            rflags = list(flags)
            env = None
            if args.mesh_replica and i == args.replicas - 1:
                rflags += ["--mesh", "2x1"]
                env = {"XLA_FLAGS":
                       "--xla_force_host_platform_device_count=2"}
            replicas.append(fh.spawn_replica(
                corpus_path, out, f"replica_{i}", warm,
                batch_cap=args.batch_cap, flags=rflags,
                env_extra=env))
        for fp in replicas:
            fh.await_replica(fp)
        router = fh.spawn_router(out, replicas, record=router_record)
        print(f"fleet_bench: router port={router.ready['port']} over "
              f"{args.replicas} replicas; warming done")

        # Correctness gate before any timing claim: one closed-loop
        # replay must be byte-identical to the golden oracle.
        res = sc.replay(router.ready["port"], header, reqs,
                        connections=3)
        if sc.contract_text([r.get("checksums", []) for r in res]) != \
                sc.contract_text(golden):
            print("fleet_bench: FAIL: routed replay differs from the "
                  "golden oracle", file=sys.stderr)
            return 1

        recs = loadgen.run_levels(
            router.ready["port"], header, reqs, speeds=speeds,
            reps=args.reps, replicas=args.replicas,
            trace=os.path.basename(args.trace))
        for rec in recs:
            rec.append_jsonl(metrics_path)
            print(f"fleet_bench: {rec.config['level']}: offered "
                  f"{rec.metrics.get('offered_qps')} qps -> p99 "
                  f"{rec.metrics.get('p99_ms')} ms "
                  f"(p50 {rec.metrics.get('p50_ms')}, errors "
                  f"{rec.metrics.get('errors')})")
        fh.drain_fleet(router, replicas)
        # The router's own closed-loop record joins the same JSONL.
        if os.path.exists(router_record):
            with open(router_record) as f, \
                    open(metrics_path, "a") as g:
                g.write(f.read())
    finally:
        fh.kill_all(replicas + ([router] if router else []))
    print(f"fleet_bench: wrote {metrics_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
