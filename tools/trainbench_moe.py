#!/usr/bin/env python
"""TRAINBENCH MoE: dense one-hot vs capacity+all-to-all dispatch A/B.

Fixed model/batch shape, both dispatches, interleaved reps with rotating
starts; records per-dispatch median/min step time and the a2a capacity.
Expert parallelism needs multiple devices, so this runs on the virtual
8-device CPU mesh (1 real TPU chip cannot host an ep axis) — dispatch-
relative numbers, like PIPEBENCH.

Writes a schema RunRecord (obs.run) — ledger-ingestible (python -m
dmlp_tpu.report); the r05 ad-hoc shape is grandfathered.

Usage: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python tools/trainbench_moe.py [--out TRAINBENCH_r06_moe.json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="TRAINBENCH_r06_moe.json")
    ap.add_argument("--reps", type=int, default=7)
    ap.add_argument("--dp", type=int, default=2)
    ap.add_argument("--ep", type=int, default=4)
    ap.add_argument("--experts", type=int, default=8)
    ap.add_argument("--hidden", type=int, default=512)
    ap.add_argument("--ffn", type=int, default=2048)
    ap.add_argument("--batch", type=int, default=4096)
    ap.add_argument("--cf", type=float, default=1.25,
                    help="a2a capacity factor")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from dmlp_tpu.train.experts import (a2a_batch_shardings, a2a_capacity,
                                        build_moe_state, make_ep_mesh,
                                        make_moe_a2a_train_step,
                                        make_moe_train_step)
    from dmlp_tpu.train.sharding import batch_shardings
    from dmlp_tpu.train.step import make_optimizer

    mesh = make_ep_mesh(args.dp, args.ep)
    opt = make_optimizer("sgd", 0.05)
    d_in, n_classes = 64, 16
    cap = a2a_capacity(args.batch, args.dp, args.ep, args.cf)

    rng = np.random.default_rng(0)
    x = rng.normal(size=(args.batch, d_in)).astype(np.float32)
    y = rng.integers(0, n_classes, args.batch).astype(np.int32)

    cells = {}
    for name in ("dense", "a2a"):
        state = build_moe_state(mesh, opt, d_in, args.hidden, args.ffn,
                                n_classes, args.experts, seed=3)
        if name == "dense":
            step = make_moe_train_step(mesh, opt, n_experts=args.experts,
                                       n_classes=n_classes)
            xs, ys = batch_shardings(mesh)
        else:
            step = make_moe_a2a_train_step(mesh, opt,
                                           n_experts=args.experts,
                                           n_classes=n_classes,
                                           capacity=cap)
            xs, ys = a2a_batch_shardings(mesh)
        xd = jax.device_put(jnp.asarray(x), xs)
        yd = jax.device_put(jnp.asarray(y), ys)
        state, m = step(state, xd, yd)   # compile + warm
        jax.block_until_ready(m["loss"])
        cells[name] = (state, step, xd, yd)

    samples = {k: [] for k in cells}
    order = list(cells)
    for r in range(args.reps):
        for name in (order if r % 2 == 0 else order[::-1]):
            state, step, xd, yd = cells[name]
            t0 = time.perf_counter()
            state, m = step(state, xd, yd)
            jax.block_until_ready(m["loss"])
            samples[name].append((time.perf_counter() - t0) * 1e3)
            cells[name] = (state, step, xd, yd)

    from dmlp_tpu.obs.run import RunRecord, current_device, round_from_name

    metrics: dict = {}
    for name, ts in samples.items():
        metrics[f"{name}_median_ms"] = float(np.median(ts))
        metrics[f"{name}_min_ms"] = float(np.min(ts))
        metrics[f"{name}_times_ms"] = [round(t, 3) for t in ts]
    metrics["a2a_vs_dense_pct"] = round(100.0 * (
        metrics["a2a_median_ms"] / metrics["dense_median_ms"] - 1), 1)
    rec = RunRecord(
        kind="train", tool="tools.trainbench_moe",
        config={"note": "dense one-hot vs capacity+all-to-all MoE "
                        "dispatch, interleaved reps with rotating "
                        "starts; virtual CPU mesh when 1 real chip "
                        "cannot host an ep axis — dispatch-relative "
                        "step times",
                "platform": jax.devices()[0].platform,
                "mesh": [args.dp, args.ep], "experts": args.experts,
                "hidden": args.hidden, "ffn": args.ffn,
                "batch": args.batch, "capacity_factor": args.cf,
                "capacity": cap, "reps": args.reps},
        metrics=metrics, device=current_device(),
        round=round_from_name(args.out))
    rec.write(args.out)
    print(json.dumps(json.loads(rec.to_json()), indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
