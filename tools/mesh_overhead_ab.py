#!/usr/bin/env python
"""Same-platform single-vs-mesh engine A/B (VERDICT r4 weak item 2 / next 7).

HARNESS_r04 showed config 3 (sharded, 8 virtual CPU devices) at 2.5x
config 2 (single engine) on the identical 100k x 5k x 64 input — but those
two configs ran on DIFFERENT platforms (config 2 = the real TPU chip,
config 3 = virtual CPU emulation), so the ratio conflated mesh-driver
overhead with the platform gap. This tool runs both engines (plus ring)
on the SAME platform and input, interleaved with rotating starts
(verify-skill methodology), and records per-engine median/min plus the
single-relative overhead — the decomposition VERDICT asked for.

Usage (CPU virtual mesh, config-3's venue):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python tools/mesh_overhead_ab.py [--input inputs/input2.in] \
      [--out MESH_OVERHEAD_r05.json] [--reps 5]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--input", default="inputs/input2.in")
    ap.add_argument("--out", default="MESH_OVERHEAD_r05.json")
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--mesh", default="4,2")
    args = ap.parse_args()

    import jax

    from dmlp_tpu.config import EngineConfig
    from dmlp_tpu.engine.sharded import RingEngine, ShardedEngine
    from dmlp_tpu.engine.single import SingleChipEngine
    from dmlp_tpu.io.grammar import parse_input
    from dmlp_tpu.parallel.mesh import make_mesh

    with open(args.input, "rb") as f:
        inp = parse_input(f)
    mesh_shape = tuple(int(d) for d in args.mesh.split(","))
    engines = {
        "single": SingleChipEngine(EngineConfig()),
        "sharded": ShardedEngine(EngineConfig(mode="sharded"),
                                 mesh=make_mesh(mesh_shape)),
        "ring": RingEngine(EngineConfig(mode="ring"),
                           mesh=make_mesh(mesh_shape)),
    }
    samples = {k: [] for k in engines}
    phases = {k: {} for k in engines}
    order = list(engines)
    for r in range(args.reps + 1):  # +1: first round is warmup, dropped
        seq = order if r % 2 == 0 else order[::-1]
        for name in seq:
            eng = engines[name]
            t0 = time.perf_counter()
            eng.run(inp)
            dt = (time.perf_counter() - t0) * 1e3
            if r > 0:
                samples[name].append(dt)
                for k, v in eng.last_phase_ms.items():
                    phases[name].setdefault(k, []).append(v)
    # median per phase across the timed reps (not just the last one)
    phases = {name: {k: round(float(np.median(v)), 1)
                     for k, v in ph.items()}
              for name, ph in phases.items()}

    rec = {"platform": jax.devices()[0].platform,
           "n_devices": len(jax.devices()),
           "input": args.input,
           "shape": [inp.params.num_data, inp.params.num_queries,
                     inp.params.num_attrs],
           "mesh": mesh_shape, "reps": args.reps, "engines": {}}
    for name, ts in samples.items():
        rec["engines"][name] = {"median_ms": float(np.median(ts)),
                                "min_ms": float(np.min(ts)),
                                "phases_ms": phases[name],
                                "select": engines[name]._last_select}
    s = rec["engines"]["single"]["median_ms"]
    for name in ("sharded", "ring"):
        rec["engines"][name]["vs_single_pct"] = round(
            100.0 * (rec["engines"][name]["median_ms"] / s - 1), 1)
    rec["conclusion"] = (
        "same-platform overhead of the mesh engines vs the single engine; "
        "the HARNESS_r04 config3/config2 2.5x ratio compared virtual-CPU "
        "(config 3) against real-TPU (config 2) and was platform gap, not "
        "mesh-driver overhead")
    with open(args.out, "w") as f:
        json.dump(rec, f, indent=1)
    print(json.dumps(rec["engines"], indent=1))
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
