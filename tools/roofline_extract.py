#!/usr/bin/env python
"""Roofline the on-chip extract solve (VERDICT r4 item 8; ISSUE 3 r6).

Targets EXACTLY the number BENCH records as device_solve_ms_extract
(97.2 ms in BENCH_r04): bench.stage_extract_inputs' f32-staged arrays,
kc = round_up(kmax + 8, 8), the fused kernel PLUS the label-gather +
composite-sort epilogue, timed by bench.time_fenced_solve_ms (the
dependent-readback fence — block_until_ready is unreliable over the
tunneled link). Floors:

1. MXU: the bare norm+matmul distance computation at the same shape and
   precision (HIGHEST), same fence — the kernel must do this matmul work.
2. HBM: the kernel's block sweep re-reads the dataset once per query
   tile: (Qpad/tq) * Npad * A * 4 bytes over the chip's HBM bandwidth.

Methodology (r6):

- The extraction term is MEASURED, not modeled: the kernel's own
  per-tile ``iters`` output is read back and folded into the counters
  block via obs.kernel_cost.extract_topk_cost(iters_total=...) — the
  RunRecord's flops are no longer a deterministic lower bound
  (ROADMAP item closed).
- The kernel is timed BOTH with the r6 threshold-gated block skipping
  (default) and with ``block_skip=False`` (the r5 kernel), interleaved
  in the same weather window, so the record carries an honest
  before/after kernel-only ms and %-of-roof pair for the optimization.
- The variant that ran resolves through the measured autotuner cache
  (dmlp_tpu.tune) when an entry exists — the record names it either way.

Writes one schema-1 RunRecord (obs.run). On a host with no TPU,
``--emit-unavailable`` writes an explicit-marker RunRecord (device,
why, and an interpret-mode parity + measured-iters demonstration at a
small shape) instead of failing silently — never a missing artifact.

``--fused`` (ISSUE 8) adds the fused distance→top-k megakernel arm
(ops.pallas_fused: the MXU tile gate + fused tune-cache namespace):
the fused kernel is timed INTERLEAVED with the ungated kernel in the
same weather window (kernel-only, dispatch-corrected), and the
RunRecord's counters block carries obs.kernel_cost.fused_topk_cost —
including the analytic HBM write+read the fusion eliminates vs the
materialize-then-reread two-pass pipeline
(``hbm_bytes_saved_vs_two_pass`` / ``hbm_traffic_reduction_x``). On a
no-TPU host, ``--fused --emit-unavailable`` writes the honest
fused-roofline-unavailable record: an interpret-mode proof that the
gate is a pure elision (fused vs ungated bit-identity, gate-zeroed
iters on a hopeless warm block) plus the on-hardware recipe.

Usage (DEFAULT env, real chip): python tools/roofline_extract.py
    [--out ROOFLINE_r06.json] [--n 204800 --q 10240 --a 64 --k 32]
    [--fused --out ROOFLINE_FUSED_r08.json]
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

HBM_GBPS = {"tpu v5 lite": 819.0, "v5e": 819.0}


def emit_unavailable(args, dev) -> int:
    """The honest no-TPU artifact: an explicit roofline_unavailable
    RunRecord carrying (1) why, (2) what the last real-chip measurement
    said, (3) an interpret-mode demonstration that the r6 block-skip
    kernel is output-identical and actually skips warm no-improve blocks
    (iters 0 vs 1), so the record is evidence, not just an excuse."""
    import numpy as np

    import jax.numpy as jnp
    from dmlp_tpu.obs.kernel_cost import extract_topk_cost
    from dmlp_tpu.obs.run import RunRecord
    from dmlp_tpu.ops.pallas_extract import extract_topk, resolve_variant

    n, nq, a, kc = 1024, 16, 8, 16
    rng = np.random.default_rng(0)
    d = jnp.asarray(rng.uniform(0, 100, (n, a)), jnp.float32)
    q = jnp.asarray(rng.uniform(0, 100, (nq, a)), jnp.float32)
    # Chunk 1 fresh, chunk 2 the same rows shifted FAR away: no chunk-2
    # candidate can beat any row's k-th best, so the skip gate must zero
    # chunk 2's loop count while the r5 kernel pays one full extraction
    # round per tile discovering the same nothing.
    d_far = d + 1000.0
    runs = {}
    for skip in (True, False):
        od1, oi1, it1 = extract_topk(q, d, n_real=n, kc=kc,
                                     interpret=True, block_skip=skip)
        od2, oi2, it2 = extract_topk(q, d_far, od1, oi1, n_real=n,
                                     id_base=n, kc=kc, interpret=True,
                                     block_skip=skip)
        runs[skip] = (np.asarray(od2), np.asarray(oi2),
                      int(np.asarray(it1).sum()),
                      int(np.asarray(it2).sum()))
    parity = (np.array_equal(runs[True][0], runs[False][0])
              and np.array_equal(runs[True][1], runs[False][1]))
    iters_total = runs[True][2] + runs[True][3]

    why = (
        f"no TPU reachable from this container (backend={dev.platform}); "
        "the before/after kernel-only timing needs the real chip. Last "
        "real-chip state (ROOFLINE_r05.json, v5e): 43.6 ms "
        "dispatch-corrected kernel vs 10.0 ms MXU floor = 22.9% of roof, "
        "the whole gap the 33.6 ms extraction while-loop over 13773 "
        "iters. The r6 kernel gates that loop per block (threshold "
        "prefilter) and resolves variants through the measured tuner "
        "cache — re-measure with `python -m dmlp_tpu.tune` + "
        "`python tools/roofline_extract.py` on hardware.")
    rec = RunRecord(
        kind="roofline", tool="tools/roofline_extract",
        config={"device": dev.platform, "shape": [args.n, args.q, args.a],
                "k": args.k, "requested_reps": args.reps},
        metrics={
            "roofline_unavailable": why,
            "before_after_unavailable": "kernel-only ms requires TPU",
            "cpu_interpret_check": {
                "shape": [n, nq, a], "kc": kc,
                "variant": resolve_variant(kc, n, nq, a),
                "block_skip_parity": bool(parity),
                "iters_chunk2_with_skip": runs[True][3],
                "iters_chunk2_without_skip": runs[False][3],
            },
        },
        counters=extract_topk_cost(nq, n, a, kc, iters_total=iters_total))
    rec.write(args.out)
    print(rec.to_json())
    return 0 if parity else 1


def emit_fused_unavailable(args, dev) -> int:
    """The honest no-TPU artifact for the fused megakernel: an explicit
    fused-roofline-unavailable RunRecord carrying (1) why, (2) an
    interpret-mode proof that the MXU tile gate is a PURE ELISION —
    fused vs ungated kernel bit-identical on fresh + warm folds, and a
    provably-hopeless warm block costs the fused kernel ZERO loop
    iterations even with the r6 block-skip prefilter off — and (3) the
    analytic HBM-traffic elimination vs the two-pass pipeline at the
    ROOFLINE_r05 dispatch shape, so the ~2x claim is a checked number
    in the ledger while the ms win awaits hardware."""
    import numpy as np

    import jax.numpy as jnp
    from dmlp_tpu.obs.kernel_cost import (fused_topk_cost,
                                          two_pass_equivalent_cost)
    from dmlp_tpu.obs.run import RunRecord
    from dmlp_tpu.ops.pallas_extract import extract_topk
    from dmlp_tpu.ops.pallas_fused import resolve_variant

    n, nq, a, kc = 1024, 16, 8, 16
    rng = np.random.default_rng(0)
    d = jnp.asarray(rng.uniform(0, 100, (n, a)), jnp.float32)
    q = jnp.asarray(rng.uniform(0, 100, (nq, a)), jnp.float32)
    d_far = d + 1000.0   # warm fold no candidate of which can insert
    runs = {}
    for gate in (True, False):
        od1, oi1, it1 = extract_topk(q, d, n_real=n, kc=kc,
                                     interpret=True, block_skip=False,
                                     mxu_gate=gate)
        od2, oi2, it2 = extract_topk(q, d_far, od1, oi1, n_real=n,
                                     id_base=n, kc=kc, interpret=True,
                                     block_skip=False, mxu_gate=gate)
        runs[gate] = (np.asarray(od2), np.asarray(oi2),
                      int(np.asarray(it1).sum()),
                      int(np.asarray(it2).sum()))
    parity = (np.array_equal(runs[True][0], runs[False][0])
              and np.array_equal(runs[True][1], runs[False][1]))
    gate_elides = runs[True][3] == 0 and runs[False][3] > 0
    iters_total = runs[True][2] + runs[True][3]

    # The acceptance number at the ROOFLINE_r05 dispatch shape: what the
    # fusion eliminates vs the materialize-then-reread two-pass pipeline.
    qb, b = args.q, args.n
    fused = fused_topk_cost(qb, b, args.a, kc)
    two = two_pass_equivalent_cost(qb, b, args.a, kc)

    why = (
        f"no TPU reachable from this container (backend={dev.platform}); "
        "the fused-vs-two-pass kernel-only ms needs the real chip. "
        "On hardware: `python -m dmlp_tpu.tune --kernel both` (sweep the "
        "fused namespace), then `python tools/roofline_extract.py --fused "
        "--reps 3` (interleaved fused/ungated same-weather arms), then "
        "`python -m dmlp_tpu.report` + `make perf-gate` to fold the _r08 "
        "round into the trajectory. Expected: the MXU gate converts the "
        "33.6 ms extraction term's warm no-improve blocks (ROOFLINE_r05: "
        "13773 iters at 22.9% of roof) from one VPU prefilter pass each "
        "into NOTHING — the matmul tile itself is skipped.")
    rec = RunRecord(
        kind="roofline", tool="tools/roofline_extract_fused",
        config={"device": dev.platform, "shape": [args.n, args.q, args.a],
                "k": args.k, "requested_reps": args.reps, "fused": True},
        metrics={
            "roofline_unavailable": why,
            "fused_vs_two_pass_ms_unavailable":
                "kernel-only ms requires TPU",
            "hbm_bytes_saved_vs_two_pass":
                fused["hbm_bytes_saved_vs_two_pass"],
            "hbm_traffic_reduction_x": fused["hbm_traffic_reduction_x"],
            "hbm_bytes_fused": fused["bytes_accessed"],
            "hbm_bytes_two_pass_equiv": two["bytes_accessed"],
            "cpu_interpret_check": {
                "shape": [n, nq, a], "kc": kc,
                "variant": resolve_variant(kc, n, nq, a),
                "fused_vs_ungated_parity": bool(parity),
                "gate_zeroes_hopeless_block_iters": bool(gate_elides),
                "iters_warm_block_gated": runs[True][3],
                "iters_warm_block_ungated": runs[False][3],
            },
        },
        counters=fused_topk_cost(nq, n, a, kc, iters_total=iters_total))
    rec.write(args.out)
    print(rec.to_json())
    return 0 if parity and gate_elides else 1


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="ROOFLINE_r06.json")
    ap.add_argument("--n", type=int, default=204800)
    ap.add_argument("--q", type=int, default=10240)
    ap.add_argument("--a", type=int, default=64)
    ap.add_argument("--k", type=int, default=32)
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--emit-unavailable", action="store_true",
                    help="on a non-TPU host, write the explicit "
                         "roofline-unavailable RunRecord (exit 0) "
                         "instead of failing")
    ap.add_argument("--fused", action="store_true",
                    help="add the fused-megakernel arm (ops.pallas_fused"
                         "): interleaved fused vs ungated kernel-only "
                         "timing + the analytic HBM-traffic elimination "
                         "(ISSUE 8); with --emit-unavailable, writes "
                         "the fused parity-proof marker record")
    args = ap.parse_args()
    if args.fused and args.out == "ROOFLINE_r06.json":
        args.out = "ROOFLINE_FUSED_r08.json"

    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    if dev.platform != "tpu":
        if args.emit_unavailable:
            return emit_fused_unavailable(args, dev) if args.fused \
                else emit_unavailable(args, dev)
        print(f"FATAL: roofline needs the real chip, got {dev.platform} "
              "(--emit-unavailable writes the explicit marker record)")
        return 1

    from bench import stage_extract_inputs, time_fenced_solve_ms
    from dmlp_tpu.engine.single import _extract_finalize, round_up
    from dmlp_tpu.io.grammar import KNNInput, Params
    from dmlp_tpu.ops.pallas_distance import _tile
    from dmlp_tpu.ops.pallas_extract import (BLOCK_ROWS, _resolve_variant,
                                             extract_topk)

    n, q, a = args.n, args.q, args.a
    rng = np.random.default_rng(0)
    inp = KNNInput(Params(n, q, a),
                   rng.integers(0, 10, n).astype(np.int32),
                   rng.uniform(0, 100, (n, a)),
                   np.full(q, args.k, np.int32),
                   rng.uniform(0, 100, (q, a)))
    kc = round_up(args.k + 8, 8)          # bench's device-solve width
    qd, dd, lab, npad, qpad = stage_extract_inputs(inp)

    trivial = jax.jit(lambda q_, d_: q_ + 1.0)

    # --- measured: bench-identical solve (kernel + sort epilogue) -------
    def solve_fn(q_, d_):
        od, oi, _ = extract_topk(q_, d_, n_real=n, kc=kc)
        return _extract_finalize(od, oi, lab, k=kc).dists

    # --- kernel only (no epilogue): isolates the sort term --------------
    def kernel_fn(q_, d_):
        od, _, _ = extract_topk(q_, d_, n_real=n, kc=kc)
        return od

    # --- the r5 kernel (block skipping off): the "before" of the A/B ----
    def kernel_noskip_fn(q_, d_):
        od, _, _ = extract_topk(q_, d_, n_real=n, kc=kc, block_skip=False)
        return od

    # --- the fused megakernel (MXU tile gate + fused tune namespace) ----
    def kernel_fused_fn(q_, d_):
        from dmlp_tpu.ops.pallas_fused import fused_topk
        od, _, _ = fused_topk(q_, d_, n_real=n, kc=kc)
        return od

    # --- MXU floor: bare fused distance matmul, same precision/fence ----
    @jax.jit
    def dist_only(q_, d_):
        cross = jax.lax.dot_general(
            q_, d_, (((1,), (1,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32)
        qn = jnp.sum(q_ * q_, -1, keepdims=True)
        dn = jnp.sum(d_ * d_, -1)[None, :]
        # reduce to (Q, 1): keeps the (Q, N) matrix out of HBM (the
        # kernel never writes it) but XLA cannot elide the matmul
        return jnp.min(jnp.maximum(qn + dn - 2.0 * cross, 0.0), axis=1,
                       keepdims=True)

    # The tunnel's per-dispatch overhead (~10-20 ms, does NOT amortize
    # across chained reps) swings with link weather minute to minute, so
    # the four measurements are INTERLEAVED round-robin and medianed —
    # they share weather, making the subtraction-based decomposition
    # meaningful (verify-skill methodology).
    fns = {"dispatch": trivial, "solve": solve_fn, "kernel": kernel_fn,
           "kernel_noskip": kernel_noskip_fn, "mxu": dist_only}
    if args.fused:
        fns["kernel_fused"] = kernel_fused_fn
    rounds = {k: [] for k in fns}
    for r in range(5):
        for name in (list(fns) if r % 2 == 0 else list(fns)[::-1]):
            rounds[name].append(
                time_fenced_solve_ms(fns[name], qd, dd, args.reps))
    med = {k: float(np.median(v)) for k, v in rounds.items()}
    dispatch_ms = med["dispatch"]
    solve_ms, kernel_ms, mxu_ms = med["solve"], med["kernel"], med["mxu"]
    noskip_ms = med["kernel_noskip"]

    # --- HBM floor (actual resolved tiles; kernel streams f32) ----------
    v = _resolve_variant(kc, npad, qpad, a)
    tq = _tile(qpad, v["tile_q"], 8)
    tn = _tile(npad, v.get("tile_n", BLOCK_ROWS), 128 * v["ne"])
    sweep_bytes = (qpad // tq) * npad * a * 4 + (npad // tn) * qpad * a * 4
    bw = next((g for k_, g in HBM_GBPS.items()
               if k_ in dev.device_kind.lower()), 819.0)
    hbm_floor_ms = sweep_bytes / (bw * 1e9) * 1e3

    # --- extraction-iteration diagnostics (measured, both kernels) ------
    _, _, iters = extract_topk(qd, dd, n_real=n, kc=kc)
    total_iters = int(np.asarray(iters).sum())
    _, _, iters_ns = extract_topk(qd, dd, n_real=n, kc=kc,
                                  block_skip=False)
    total_iters_noskip = int(np.asarray(iters_ns).sum())

    flops = 2.0 * npad * qpad * a
    # Single-dispatch chains (kernel, mxu, dispatch) are directly
    # comparable after subtracting the measured per-dispatch overhead.
    # The solve-vs-kernel difference (the sort epilogue's second
    # dispatch) sits BELOW tunnel noise — consecutive enqueues pipeline —
    # so the epilogue is reported raw, not as a corrected term.
    kernel_c = kernel_ms - dispatch_ms
    noskip_c = noskip_ms - dispatch_ms
    mxu_c = max(mxu_ms - dispatch_ms, 1e-6)
    floor = max(mxu_c, hbm_floor_ms)
    rec = {
        "device": dev.device_kind, "shape": [n, q, a],
        "k": args.k, "kc": kc, "variant": v,
        "tiles": {"tq": tq, "tn": tn},
        "dispatch_overhead_ms": round(dispatch_ms, 2),
        "raw_ms": {"solve_with_epilogue": round(solve_ms, 2),
                   "kernel_only": round(kernel_ms, 2),
                   "kernel_only_noskip": round(noskip_ms, 2),
                   "mxu_matmul": round(mxu_ms, 2)},
        # before = the r5 kernel (block_skip off), after = r6 (skip on);
        # interleaved same-weather medians, dispatch-corrected.
        "corrected": {
            "kernel_ms_before": round(noskip_c, 2),
            "kernel_ms": round(kernel_c, 2),
            "block_skip_speedup": round(noskip_c / max(kernel_c, 1e-6), 3),
            "mxu_floor_ms": round(mxu_c, 2),
            "extraction_term_ms_before": round(noskip_c - mxu_c, 2),
            "extraction_term_ms": round(kernel_c - mxu_c, 2),
            "pct_of_roof_before": round(
                100.0 * floor / max(noskip_c, 1e-6), 1),
            "pct_of_roof": round(100.0 * floor / max(kernel_c, 1e-6), 1),
        },
        "mxu_achieved_tflops_f32_highest": round(
            flops / (mxu_c * 1e-3) / 1e12, 1),
        "hbm_floor_ms": round(hbm_floor_ms, 2),
        "hbm_bw_gbps_assumed": bw,
        "sweep_gb": round(sweep_bytes / 1e9, 2),
        "extract_iters_total": total_iters,
        "extract_iters_total_noskip": total_iters_noskip,
    }
    if args.fused:
        from dmlp_tpu.obs.kernel_cost import fused_topk_cost
        from dmlp_tpu.ops.pallas_fused import fused_topk
        fused_c = med["kernel_fused"] - dispatch_ms
        _, _, it_f = fused_topk(qd, dd, n_real=n, kc=kc)
        fc = fused_topk_cost(qpad, npad, a, kc,
                             iters_total=int(np.asarray(it_f).sum()))
        rec["fused"] = {
            "kernel_ms_fused": round(fused_c, 2),
            "fused_vs_ungated_speedup": round(
                kernel_c / max(fused_c, 1e-6), 3),
            "pct_of_roof_fused": round(100.0 * floor / max(fused_c, 1e-6),
                                       1),
            "extract_iters_total_fused": int(np.asarray(it_f).sum()),
            "hbm_bytes_saved_vs_two_pass":
                fc["hbm_bytes_saved_vs_two_pass"],
            "hbm_traffic_reduction_x": fc["hbm_traffic_reduction_x"],
        }
    rec["verdict"] = (
        f"binding floor = {'MXU' if mxu_c > hbm_floor_ms else 'HBM'} "
        f"({floor:.1f} ms, dispatch-corrected) at HIGHEST-precision f32 "
        f"matmul ({rec['mxu_achieved_tflops_f32_highest']} TFLOP/s); "
        f"block-skip kernel {rec['corrected']['kernel_ms']} ms "
        f"({rec['corrected']['pct_of_roof']}% of roof) vs "
        f"{rec['corrected']['kernel_ms_before']} ms "
        f"({rec['corrected']['pct_of_roof_before']}%) without = "
        f"{rec['corrected']['block_skip_speedup']}x on the kernel; "
        f"measured extraction term "
        f"{rec['corrected']['extraction_term_ms']} ms over {total_iters} "
        f"iters ({total_iters_noskip} without skip); sort epilogue is "
        f"below tunnel noise (raw solve "
        f"{rec['raw_ms']['solve_with_epilogue']} vs kernel "
        f"{rec['raw_ms']['kernel_only']} ms); each dispatch adds "
        f"~{rec['dispatch_overhead_ms']} ms tunnel wall time")

    # One schema-1 RunRecord (obs.run); the counters block carries the
    # kernel cost model WITH the measured extraction term folded in
    # (obs.kernel_cost, iters_total) — measured, not a lower bound.
    from dmlp_tpu.obs.kernel_cost import extract_topk_cost
    from dmlp_tpu.obs.run import RunRecord
    record = RunRecord(
        kind="roofline", tool="tools/roofline_extract",
        config={"device": dev.device_kind, "shape": [n, q, a],
                "k": args.k, "kc": kc, "reps": args.reps},
        metrics=rec,
        counters=extract_topk_cost(qpad, npad, a, kc,
                                   iters_total=total_iters))
    record.write(args.out)
    print(record.to_json())
    return 0


if __name__ == "__main__":
    sys.exit(main())
