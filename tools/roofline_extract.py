#!/usr/bin/env python
"""Roofline the on-chip extract solve (VERDICT r4 item 8).

For the benchmark shape (200k x 10k x 64) at the ENGINE's own
configuration — dtype="auto" resolves to bf16 staging on TPU, whose
kcap = k + 96 + k/2 (resolve_kcap) — on the real chip:

1. FLOOR (MXU): time a bare norm+matmul distance computation at the same
   shape/precision (HIGHEST) — the achieved matmul rate bounds any fused
   kernel from below, since the extraction kernel must do exactly this
   matmul work.
2. FLOOR (HBM): bytes the kernel must stream — every query tile re-reads
   the full dataset: (Qb/tq) * B * A * 4 bytes — over the chip's HBM
   bandwidth (v5e ~819 GB/s).
3. MEASURED: the fenced extract solve (bench.time_fenced_solve_ms), plus
   the kernel's own iteration diagnostics (extract_topk's iters output)
   to size the VPU extraction term = measured - matmul floor.

Verdict: measured vs max(floors); the gap decomposes into the extraction
while-loop (VPU, scales with iterations) and scheduling overheads. Run in
the DEFAULT env (real TPU); CPU runs are refused (meaningless numbers).

Usage: python tools/roofline_extract.py [--out ROOFLINE_r05.json]
       [--n 204800 --q 10240 --a 64 --k 32]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

HBM_GBPS = {"tpu v5 lite": 819.0, "v5e": 819.0}


def fenced_ms(fn, reps=5):
    import jax
    outs = fn()
    jax.block_until_ready(outs)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        ts.append((time.perf_counter() - t0) * 1e3)
    return float(np.median(ts)), float(np.min(ts))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="ROOFLINE_r05.json")
    ap.add_argument("--n", type=int, default=204800)
    ap.add_argument("--q", type=int, default=10240)
    ap.add_argument("--a", type=int, default=64)
    ap.add_argument("--k", type=int, default=32)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    if dev.platform != "tpu":
        print(f"FATAL: roofline needs the real chip, got {dev.platform}")
        return 1

    from dmlp_tpu.config import EngineConfig
    from dmlp_tpu.engine.single import resolve_kcap
    from dmlp_tpu.ops.pallas_extract import BLOCK_ROWS, extract_topk
    from dmlp_tpu.ops.pallas_extract import _resolve_variant

    n, q, a = args.n, args.q, args.a
    cfg = EngineConfig(select="extract", use_pallas=True)
    # Mirror the ENGINE's benchmark configuration exactly: dtype="auto"
    # resolves to bfloat16 staging on TPU, so both the kcap (bf16 margin
    # 96 + k/2) and the staged array dtype must be bf16 — rooflining an
    # f32-fed kernel at a bf16 kcap would characterize a hybrid the
    # engine never runs.
    staging = cfg.resolve_dtype()
    kc = resolve_kcap(cfg, args.k, "extract", n, staging=staging)
    rng = np.random.default_rng(0)
    wire = jnp.bfloat16 if staging == "bfloat16" else jnp.float32
    d_dev = jnp.asarray(rng.uniform(0, 100, (n, a)).astype(np.float32),
                        wire)
    q_dev = jnp.asarray(rng.uniform(0, 100, (q, a)).astype(np.float32),
                        wire)

    # --- measured: one-shot whole-dataset kernel (resident data) --------
    def solve():
        od, oi, iters = extract_topk(q_dev, d_dev, n_real=n, id_base=0,
                                     kc=kc)
        return od, oi, iters

    med_ms, min_ms = fenced_ms(solve)
    _, _, iters = solve()
    iters = np.asarray(iters)
    total_iters = int(iters.sum())

    # --- MXU floor: bare fused distance matmul at the same precision ----
    @jax.jit
    def dist_only(qa, da):
        cross = jax.lax.dot_general(
            qa, da, (((1,), (1,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32)
        qn = jnp.sum(qa * qa, -1, keepdims=True)
        dn = jnp.sum(da * da, -1)[None, :]
        # cheap epilogue so XLA can't elide the matmul; sum keeps HBM
        # writeback of the (Q, N) matrix OUT of the floor (the kernel
        # never writes it either)
        return jnp.sum(jnp.maximum(qn + dn - 2.0 * cross, 0.0))

    mxu_med, mxu_min = fenced_ms(lambda: dist_only(q_dev, d_dev))

    # --- HBM floor ------------------------------------------------------
    # Use the tile sizes extract_topk ACTUALLY resolves (_tile snaps to a
    # divisor when the nominal tile doesn't divide the axis) — nominal
    # sizes understate the floor for non-dividing shapes.
    from dmlp_tpu.ops.pallas_distance import _tile
    v = _resolve_variant(kc, n)
    tq = _tile(q, v["tile_q"], 8)
    tn = _tile(n, BLOCK_ROWS, 128 * v["ne"])
    # The kernel upcasts staged bf16 to f32 BEFORE the pallas grid (the
    # astype materializes f32 copies in HBM), so the repeated block sweep
    # streams 4-byte elements regardless of the staging dtype (staging
    # only halves the host->device transfer, which is outside this solve).
    sweep_bytes = (q // tq) * n * a * 4 + (n // tn) * q * a * 4
    bw = next((g for k_, g in HBM_GBPS.items()
               if k_ in dev.device_kind.lower()), 819.0)
    hbm_floor_ms = sweep_bytes / (bw * 1e9) * 1e3

    flops = 2.0 * n * q * a
    rec = {
        "device": dev.device_kind, "shape": [n, q, a],
        "k": args.k, "kc": kc, "variant": v,
        "measured_solve_ms": {"median": round(med_ms, 2),
                              "min": round(min_ms, 2)},
        "mxu_floor_ms": {"median": round(mxu_med, 2),
                         "min": round(mxu_min, 2),
                         "achieved_tflops": round(
                             flops / (mxu_min * 1e-3) / 1e12, 1)},
        "hbm_floor_ms": round(hbm_floor_ms, 2),
        "hbm_bw_gbps_assumed": bw,
        "sweep_gb": round(sweep_bytes / 1e9, 2),
        "extract_iters_total": total_iters,
        "extract_iters_per_tile_sweep": round(
            total_iters / max(iters.shape[0], 1), 1),
        "extraction_term_ms": round(med_ms - mxu_med, 2),
        "pct_of_roof": round(100.0 * max(mxu_min, hbm_floor_ms) / med_ms,
                             1),
    }
    rec["verdict"] = (
        f"binding floor = "
        f"{'MXU' if mxu_min > hbm_floor_ms else 'HBM'} "
        f"({max(mxu_min, hbm_floor_ms):.1f} ms); kernel at "
        f"{rec['pct_of_roof']}% of roof; gap ~= extraction while-loop "
        f"({rec['extraction_term_ms']} ms over {total_iters} iterations)")
    with open(args.out, "w") as f:
        json.dump(rec, f, indent=1)
    print(json.dumps(rec, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
