#!/usr/bin/env python
"""Diff captured oracle-binary checksums against this framework's engines.

Consumes the ORACLE_GOLDEN.json + oracle_N.out files that
tools/capture_oracle.sh recorded on an x86+OpenMPI host (the only manual
step), re-generates the same seeded inputs with this repo's generator
(byte-identity is asserted via the manifest's input sha256 — the two
generators draw the same RNG sequence), runs the requested engine on each
input, and compares per-query checksum SETS (order-insensitive: the
reference's report loop is rank-serialized and the stripped binaries'
exact interleaving is theirs to choose; the contract is the per-query
checksum values, common.cpp:70).

Usage:
    python tools/oracle_diff.py oracle_capture/ORACLE_GOLDEN.json \
        [--engine golden|single] [--configs 1,2,3,4]

Exit 0 = every config's checksums match the reference binaries.
"""
from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def parse_checksum_lines(text: str) -> dict:
    out = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        # "Query <id> checksum: <c>"
        parts = line.split()
        if len(parts) == 4 and parts[0] == "Query" and parts[2] == "checksum:":
            out[int(parts[1])] = int(parts[3])
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("manifest")
    ap.add_argument("--engine", default="golden",
                    choices=["golden", "single"],
                    help="what to diff against the binaries: the portable "
                         "f64 golden model (default) or the JAX engine")
    ap.add_argument("--configs", default="1,2,3,4")
    args = ap.parse_args()

    from dmlp_tpu.bench.configs import BENCH_CONFIGS
    from dmlp_tpu.bench.harness import ensure_input
    from dmlp_tpu.io.grammar import parse_input
    from dmlp_tpu.io.report import format_results

    cap_dir = os.path.dirname(os.path.abspath(args.manifest))
    manifest = json.load(open(args.manifest))
    failures = 0
    for cfg_id in (int(c) for c in args.configs.split(",")):
        rec = manifest["configs"].get(str(cfg_id))
        if rec is None:
            print(f"config {cfg_id}: not in manifest, skipping")
            continue
        cfg = BENCH_CONFIGS[cfg_id if cfg_id != 3 else 2]
        inp_path = ensure_input(cfg, os.path.join(cap_dir, "repo_inputs"))
        got_sha = hashlib.sha256(open(inp_path, "rb").read()).hexdigest()
        if got_sha != rec["input_sha256"]:
            print(f"config {cfg_id}: INPUT MISMATCH — repo regeneration "
                  f"differs from the captured input ({got_sha[:12]} vs "
                  f"{rec['input_sha256'][:12]}); generators diverged")
            failures += 1
            continue
        oracle = parse_checksum_lines(
            open(os.path.join(cap_dir, rec["out_file"])).read())
        with open(inp_path, "rb") as f:
            parsed = parse_input(f)
        if args.engine == "golden":
            from dmlp_tpu.golden.fast import knn_golden_fast
            results = knn_golden_fast(parsed)
        else:
            from dmlp_tpu.config import EngineConfig
            from dmlp_tpu.engine.single import SingleChipEngine
            results = SingleChipEngine(
                EngineConfig(use_pallas=True)).run(parsed)
        ours = parse_checksum_lines(format_results(results, debug=False))
        missing = sorted(set(oracle) - set(ours))
        extra = sorted(set(ours) - set(oracle))
        diff = sorted(q for q in set(oracle) & set(ours)
                      if oracle[q] != ours[q])
        if missing or extra or diff:
            failures += 1
            print(f"config {cfg_id}: MISMATCH — missing {len(missing)}, "
                  f"extra {len(extra)}, differing {len(diff)} "
                  f"(first differing: {diff[:5]})")
        else:
            print(f"config {cfg_id}: OK — {len(oracle)} query checksums "
                  f"match bench_{cfg_id} (oracle "
                  f"{rec['time_taken_ms']} ms at np={rec['np']})")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
