"""Amortized per-op timings: each op runs R times inside ONE jitted
lax.fori_loop with a carry-dependent input perturbation (defeats CSE), so
per-dispatch/tunnel overhead — tens of ms on the axon link, which swamps
single-dispatch timings — divides out. This is the measurement that decides
where the seg-select solve's per-chunk ~78 ms actually goes."""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

R = 10


def amortized(make_body, *args, repeats=R):
    """Time one jit'd fori_loop of `repeats` body iterations; return ms/iter.

    make_body(eps, *args) -> scalar-reducible array; eps is a carry-derived
    scalar (0.0 in practice) folded into an input so iterations chain.
    """
    @jax.jit
    def loop(*a):
        def body(_, c):
            return make_body(c * 1e-30, *a)
        return jax.lax.fori_loop(0, repeats, body, jnp.float32(0.0))

    float(loop(*args))  # compile + warm
    t0 = time.perf_counter()
    out = float(loop(*args))
    return (time.perf_counter() - t0) / repeats * 1e3


def main() -> int:
    nq, a, k = 10240, 64, 40
    dblock = 51200
    nseg = dblock // 128
    s = min(nseg, k + 16)

    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.uniform(0, 100, (nq, a)), jnp.float32)
    d = jnp.asarray(rng.uniform(0, 100, (dblock, a)), jnp.float32)
    lab = jnp.asarray(rng.integers(0, 10, dblock, dtype=np.int32))
    ids = jnp.arange(dblock, dtype=jnp.int32)
    tile = jnp.abs(jnp.asarray(
        rng.standard_normal((nq, dblock)), jnp.float32)) * 100
    segmin = tile.reshape(nq, nseg, 128).min(axis=-1)
    seg_idx = jax.lax.top_k(-segmin, s)[1]
    cand = jnp.take_along_axis(
        tile.reshape(nq, nseg, 128), seg_idx[:, :, None], axis=1
    ).reshape(nq, s * 128)
    carry = jnp.zeros((nq, k), jnp.float32)
    float(jnp.sum(cand))

    out = {"shape": {"nq": nq, "a": a, "k": k, "dblock": dblock, "s": s},
           "repeats": R}

    out["matmul"] = amortized(
        lambda e, q, d: jnp.sum((q + e) @ d.T), q, d)

    from dmlp_tpu.ops.distance import masked_pairwise_sq_l2
    out["xla_dist_tile"] = amortized(
        lambda e, q, d, i: jnp.sum(masked_pairwise_sq_l2(q + e, d, i)),
        q, d, ids)

    from dmlp_tpu.ops.pallas_distance import (fused_dist_segmin,
                                              native_pallas_backend)
    native = native_pallas_backend()
    out["pallas_native"] = native

    def fused(e, q, d, i):
        t, sm = fused_dist_segmin(q + e, d, i, interpret=not native)
        return t[0, 0] + sm[0, 0]
    out["pallas_fused_dist_segmin"] = amortized(fused, q, d, ids)

    def fused_sum(e, q, d, i):
        t, sm = fused_dist_segmin(q + e, d, i, interpret=not native)
        return jnp.sum(sm)
    out["pallas_fused_dist_segmin_smsum"] = amortized(fused_sum, q, d, ids)

    out["segmin_reduce_from_tile"] = amortized(
        lambda e, t: jnp.sum((t + e).reshape(nq, nseg, 128).min(axis=-1)),
        tile)

    out["seg_topk_400_to_56"] = amortized(
        lambda e, sm: jnp.sum(jax.lax.top_k(-(sm + e), s)[0]), segmin)

    out["seg_gather"] = amortized(
        lambda e, t, si: jnp.sum(jnp.take_along_axis(
            (t + e).reshape(nq, nseg, 128), si[:, :, None], axis=1)),
        tile, seg_idx)

    out["label_gather"] = amortized(
        lambda e, l, si: jnp.sum(
            l.reshape(nseg, 128)[
                jnp.minimum(si, nseg - 1 + e.astype(jnp.int32))
            ].astype(jnp.float32)),
        lab, seg_idx)

    out["merge_topk_7208_to_40"] = amortized(
        lambda e, c, cd: jnp.sum(jax.lax.top_k(
            -jnp.concatenate([c, cd + e], axis=-1), k)[0]),
        carry, cand)

    out["full_tile_topk"] = amortized(
        lambda e, t: jnp.sum(jax.lax.top_k(-(t + e), k)[0]), tile)

    # Whole seg step (one chunk) amortized, pallas on and off.
    from dmlp_tpu.ops.topk import TopK, init_topk, make_block_step
    init = init_topk(nq, k)
    for use_pallas, name in ((native, "seg_step_pallas"),
                             (False, "seg_step_xla")):
        step = make_block_step("seg", k, use_pallas, jnp.float32)
        out[name] = amortized(
            lambda e, c0, q, d, l, i, _step=step: jnp.sum(
                _step(TopK(c0.dists + e, c0.labels, c0.ids),
                      q, d, l, i).dists),
            init, q, d, lab, ids)

    step_t = make_block_step("topk", k, False, jnp.float32)
    out["topk_step"] = amortized(
        lambda e, c0, q, d, l, i: jnp.sum(
            step_t(TopK(c0.dists + e, c0.labels, c0.ids),
                   q, d, l, i).dists),
        init, q, d, lab, ids)

    print(json.dumps(out, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
