#!/usr/bin/env python
"""Race-stress harness: hammer the live serving daemon under the
runtime race sanitizer and prove byte-identity survives concurrency.

The ``make race-smoke`` checker (wired into ``make test``). Phases,
every failure exits nonzero with the reason named:

1. **Sanitizer teeth** — a seeded lock-order inversion and a seeded
   sleep-under-lock on scratch locks MUST be caught by
   ``dmlp_tpu.check.racecheck`` before anything else runs: a sanitizer
   that can't see a planted bug proves nothing about a clean run.
2. **Concurrent stress** — an in-process ServeDaemon (telemetry session
   live: Sampler + export + scrape HTTP threads running, fault
   schedule installed) is hammered simultaneously by query workers,
   an ingest worker, a stats-op worker, and an OpenMetrics scrape
   worker over real sockets. Every lock in the serving/telemetry
   surface was created after install, so the sanitizer watches every
   acquisition order and every blocking call.
3. **Byte-identity under stress** — every stressed query response must
   equal the float64 golden oracle byte-for-byte. Concurrent ingests
   append rows FAR outside the query envelope (distance-dominated, can
   never enter a top-k), so the oracle over the original corpus stays
   exact whatever the interleaving; a post-stress replay then verifies
   the grown corpus against its own oracle, proving the ingests landed.
4. **Squeeze + drain** — the scheduled ``serve.admit`` oom fault sheds
   a sacrificial request (visible rejection), the daemon drains
   cleanly, and the sanitizer report over the whole stressed run must
   be EMPTY: zero inversions, zero blocking-under-lock.

``--json`` keeps stdout pure JSON (narration on stderr), the
``check_trace --json`` convention.

Usage::

    python tools/race_stress.py --out outputs/race [--json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# Install the sanitizer BEFORE any serving/telemetry import constructs
# a lock — everything created afterwards is tracked.
from dmlp_tpu.check import racecheck  # noqa: E402

racecheck.install()

import numpy as np  # noqa: E402

from dmlp_tpu.config import EngineConfig                   # noqa: E402
from dmlp_tpu.io.grammar import KNNInput, Params           # noqa: E402
from dmlp_tpu.obs.telemetry import validate_openmetrics    # noqa: E402
from dmlp_tpu.resilience import inject as rs_inject        # noqa: E402
from dmlp_tpu.serve import client as sc                    # noqa: E402
from dmlp_tpu.serve.daemon import ServeDaemon              # noqa: E402

CORPUS = dict(num_data=2000, num_queries=4, num_attrs=5, min_attr=0.0,
              max_attr=60.0, min_k=1, max_k=8, num_labels=4, seed=93)
HEADER = {"serve_trace_schema": 1, "corpus": CORPUS}
N_QUERY_WORKERS = 3
REQS_PER_WORKER = 12
N_INGESTS = 6
INGEST_ROWS = 5
#: ingested rows live FAR outside the query envelope: with k <= 8 and
#: 2000 near rows they can never enter a top-k, so the original-corpus
#: oracle stays byte-exact under any query/ingest interleaving
FAR_OFFSET = 1.0e6

_narr = sys.stdout


def fail(msg: str):
    print(f"race_stress: FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def say(msg: str) -> None:
    print(f"race_stress: {msg}", file=_narr)


def stress_requests():
    """(worker, idx) -> request dict; deterministic shapes + seeds."""
    out = []
    for w in range(N_QUERY_WORKERS):
        for i in range(REQS_PER_WORKER):
            out.append({"nq": 1 + (w * 7 + i) % 8,
                        "k": 1 + (w * 5 + i) % 8,
                        "seed": 50_000 + w * 1000 + i})
    return out


def prove_sanitizer_teeth() -> None:
    """Seeded inversion + seeded sleep-under-lock must be caught."""
    a = threading.Lock()
    b = threading.Lock()
    with a:
        with b:
            pass
    with b:
        with a:       # the planted inversion
            pass
    with a:
        time.sleep(0.001)   # the planted blocking-under-lock
    r = racecheck.report()
    if r["inversions"] != 1:
        fail(f"sanitizer missed the seeded inversion: {r}")
    if r["blocking_under_lock"] != 1:
        fail(f"sanitizer missed the seeded sleep-under-lock: {r}")
    racecheck.reset()
    say("sanitizer teeth OK: seeded inversion and sleep-under-lock "
        "both caught, state reset")


def main(argv=None) -> int:
    global _narr
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="outputs/race")
    ap.add_argument("--json", action="store_true",
                    help="pure-JSON verdict on stdout, narration on "
                         "stderr")
    args = ap.parse_args(argv)
    if args.json:
        _narr = sys.stderr
    out = os.path.abspath(args.out)
    os.makedirs(out, exist_ok=True)

    prove_sanitizer_teeth()

    corpus = __import__("dmlp_tpu.io.grammar", fromlist=[
        "parse_input_text"]).parse_input_text(sc.corpus_text(HEADER))
    reqs = stress_requests()
    golden = sc.golden_reference(corpus, HEADER, reqs)

    # One oom fault at serve.admit AFTER every stress request admitted:
    # the sacrificial post-stress request is the squeeze probe.
    schedule = rs_inject.FaultSchedule.from_dict(
        {"schema": 1, "seed": 3, "faults": [
            {"site": "serve.admit", "kind": "oom", "times": 1,
             "after": len(reqs)}]})
    rs_inject.install(schedule)

    warm = sc.warm_buckets_for_trace(reqs, batch_queries_cap=32)
    telem_path = os.path.join(out, "race_telemetry.prom")
    daemon = ServeDaemon(
        corpus, EngineConfig(), port=0, max_batch_queries=32,
        tick_s=0.001, telemetry_path=telem_path, telemetry_port=0,
        warm_buckets=warm)
    daemon.start()
    say(f"daemon up: port={daemon.port} "
        f"warm_buckets={len(daemon.engine.bucket_stats()['buckets'])} "
        f"locks_tracked={racecheck.report()['locks_created']}")
    http_port = daemon.session.http_port
    errors: list = []
    results: dict = {}
    stop_aux = threading.Event()
    stats_calls = [0]
    scrapes = [0]

    def query_worker(w: int):
        try:
            cli = sc.ServeClient(daemon.port)
            try:
                for i in range(REQS_PER_WORKER):
                    idx = w * REQS_PER_WORKER + i
                    req = reqs[idx]
                    q = sc.materialize_queries(req, HEADER)
                    ks = sc.request_ks(req)
                    resp = cli.query(q, ks=[int(v) for v in ks],
                                     req_id=str(idx))
                    if not resp.get("ok"):
                        errors.append(f"query {idx}: {resp}")
                        return
                    results[idx] = resp["checksums"]
            finally:
                cli.close()
        except Exception as e:
            errors.append(f"query worker {w}: {type(e).__name__}: {e}")

    def ingest_worker():
        try:
            rng = np.random.default_rng(7)
            cli = sc.ServeClient(daemon.port)
            try:
                for _ in range(N_INGESTS):
                    labels = rng.integers(
                        0, CORPUS["num_labels"], INGEST_ROWS)
                    rows = FAR_OFFSET + rng.uniform(
                        0.0, 1.0, (INGEST_ROWS, CORPUS["num_attrs"]))
                    r = cli.ingest([int(v) for v in labels], rows)
                    if not r.get("ok"):
                        errors.append(f"ingest: {r}")
                        return
            finally:
                cli.close()
        except Exception as e:
            errors.append(f"ingest worker: {type(e).__name__}: {e}")

    def stats_worker():
        try:
            cli = sc.ServeClient(daemon.port)
            try:
                while not stop_aux.is_set():
                    r = cli.stats()
                    if not r.get("ok"):
                        errors.append(f"stats: {r}")
                        return
                    stats_calls[0] += 1
            finally:
                cli.close()
        except Exception as e:
            errors.append(f"stats worker: {type(e).__name__}: {e}")

    def scrape_worker():
        try:
            while not stop_aux.is_set():
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{http_port}/metrics",
                        timeout=10) as r:
                    text = r.read().decode()
                if not text.rstrip().endswith("# EOF"):
                    errors.append("scrape: truncated exposition")
                    return
                scrapes[0] += 1
        except Exception as e:
            errors.append(f"scrape worker: {type(e).__name__}: {e}")

    workers = [threading.Thread(target=query_worker, args=(w,),
                                daemon=True)
               for w in range(N_QUERY_WORKERS)]
    workers.append(threading.Thread(target=ingest_worker, daemon=True))
    aux = [threading.Thread(target=stats_worker, daemon=True),
           threading.Thread(target=scrape_worker, daemon=True)]
    t0 = time.perf_counter()
    for t in workers + aux:
        t.start()
    for t in workers:
        t.join(timeout=600)
    stop_aux.set()
    for t in aux:
        t.join(timeout=60)
    wall_ms = (time.perf_counter() - t0) * 1e3
    if any(t.is_alive() for t in workers + aux):
        fail("stress worker hung")
    if errors:
        fail(f"{len(errors)} stress error(s): {errors[0]}")
    say(f"stress OK: {len(reqs)} queries x {N_QUERY_WORKERS} workers, "
        f"{N_INGESTS} concurrent ingests, {stats_calls[0]} stats ops, "
        f"{scrapes[0]} scrapes in {wall_ms:.0f} ms")

    # byte-identity of every stressed response vs the golden oracle
    got = [results.get(i) for i in range(len(reqs))]
    if any(g is None for g in got):
        fail("a stressed request produced no checksums")
    if sc.contract_text(got) != sc.contract_text(golden):
        bad = [i for i, (g, w) in enumerate(zip(got, golden)) if g != w]
        fail(f"stressed responses diverge from the golden oracle at "
             f"request(s) {bad[:5]}")
    say("byte-identity OK: every stressed response equals the golden "
        "oracle")

    # the scheduled squeeze sheds the sacrificial request FIRST: it is
    # eligible hit len(reqs)+1 at serve.admit, so it must run before
    # the grown-corpus replay adds admission hits of its own
    cli = sc.ServeClient(daemon.port)
    q = sc.materialize_queries({"nq": 2, "seed": 991}, HEADER)
    r = cli.query(q, k=3, req_id="squeezed")
    if r.get("ok") or "injected_squeeze" not in r.get("error", ""):
        fail(f"squeezed request was not shed: {r}")
    r = cli.query(q, k=3, req_id="after-squeeze")
    if not r.get("ok"):
        fail(f"request after the squeeze failed: {r}")
    say("squeeze OK: injected admission oom shed exactly one request")

    # the ingests actually landed: grown-corpus replay vs its own oracle
    grown_rows = N_INGESTS * INGEST_ROWS
    st = cli.stats()["stats"]
    if st["engine"]["corpus_rows"] != CORPUS["num_data"] + grown_rows:
        fail(f"expected {CORPUS['num_data'] + grown_rows} corpus rows "
             f"after ingest, daemon reports "
             f"{st['engine']['corpus_rows']}")
    rng = np.random.default_rng(7)
    far_labels, far_rows = [], []
    for _ in range(N_INGESTS):
        far_labels.append(rng.integers(0, CORPUS["num_labels"],
                                       INGEST_ROWS))
        far_rows.append(FAR_OFFSET + rng.uniform(
            0.0, 1.0, (INGEST_ROWS, CORPUS["num_attrs"])))
    grown = KNNInput(
        Params(CORPUS["num_data"] + grown_rows, 0, CORPUS["num_attrs"]),
        np.concatenate([corpus.labels,
                        np.concatenate(far_labels).astype(np.int32)]),
        np.vstack([corpus.data_attrs] + far_rows),
        np.zeros(0, np.int32), np.zeros((0, CORPUS["num_attrs"])))
    post = sc.replay(daemon.port, HEADER, reqs[:4], connections=2)
    if [r.get("checksums") for r in post] != \
            sc.golden_reference(grown, HEADER, reqs[:4]):
        fail("post-stress replay diverges from the grown-corpus oracle")
    say("ingest parity OK: grown-corpus replay matches its own oracle")
    cli.close()

    daemon.drain()
    rs_inject.uninstall()
    text = open(telem_path).read()
    problems = validate_openmetrics(text)
    if problems:
        fail(f"final telemetry snapshot invalid: {problems[:3]}")

    rep = racecheck.report()
    report_path = os.path.join(out, "RACE_STRESS.json")
    doc = {
        "race_stress_schema": 1,
        "requests": len(reqs), "ingests": N_INGESTS,
        "stats_ops": stats_calls[0], "scrapes": scrapes[0],
        "wall_ms": round(wall_ms, 1),
        "racecheck": rep,
    }
    with open(report_path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
    if not rep["ok"]:
        fail(f"sanitizer caught {len(rep['violations'])} violation(s) "
             f"in the real system: {rep['violations'][:3]} "
             f"(full report: {report_path})")
    say(f"sanitizer clean: {rep['locks_created']} locks tracked, "
        f"{rep['edges']} acquisition-order edges, 0 violations "
        f"({report_path})")
    if args.json:
        json.dump({"race_stress_schema": 1, "ok": True, **doc},
                  sys.stdout, indent=2)
        sys.stdout.write("\n")
    say("PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
