#!/usr/bin/env python
"""Compiled-program introspection smoke (`make hlo-smoke`): CI teeth
for the HLO-derived collective/memory ledger (obs.hlo) on CPU.

Four invariants, each a hard failure:

1. **Byte identity** — bench input 1 through the real CLI with
   ``--hlo-report`` must produce contract stdout byte-identical to the
   plain run for every engine mode exercised (sharded, ring, auto):
   introspection is pure observation.
2. **Hand-rolled engines reconcile** — the sharded engine's compiled
   all-gather bytes and the ring engine's compiled collective-permute
   bytes (while-loop trip counts folded in) must each reconcile against
   that engine's own analytic ``# check: comms-model`` records within
   :data:`dmlp_tpu.obs.hlo.COMMS_RATIO_BOUNDS` — the models stop being
   claims and become checked statements about the compiled program.
3. **The partitioner's schedule is real** — the auto (GSPMD) engine's
   report must name at least one compiler-chosen collective with
   nonzero bytes and per-mesh-axis attribution, and its ``gspmd_*``
   traffic records must reconcile exactly (the honest-but-empty comms
   block is gone).
4. **Ledger round-trip** — each ``--hlo-report`` RunRecord must ingest
   as a parsed ``hlo/<mode>/`` series family carrying
   ``collective_bytes_total`` > 0 for the distributed modes, and the
   memory leg must carry either ``hlo_peak_bytes`` (this CPU backend
   populates memory_analysis) or the explicit
   ``hlo_memory_unavailable`` marker — never silence.

Usage: JAX_PLATFORMS=cpu python tools/hlo_smoke.py --out outputs/hlo
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

MODES = ("sharded", "ring", "auto")


def fail(msg: str) -> None:
    print(f"hlo_smoke: FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def run_cli(input_path: str, flags: list, timeout_s: float = 300.0) -> str:
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                       " --xla_force_host_platform_device_count=8").strip()
    proc = subprocess.run(
        [sys.executable, "-m", "dmlp_tpu"] + flags,
        stdin=open(input_path), capture_output=True, text=True,
        env=env, timeout=timeout_s)
    if proc.returncode != 0:
        fail(f"CLI {' '.join(flags)} rc={proc.returncode}: "
             f"{proc.stderr[-800:]}")
    return proc.stdout


def check_doc(doc: dict, mode: str) -> dict:
    """Structural checks one mode's hlo RunRecord must satisfy."""
    if doc.get("kind") != "hlo":
        fail(f"{mode}: last record kind={doc.get('kind')!r}, not 'hlo'")
    comms = doc.get("comms") or {}
    rec = comms.get("reconcile") or {}
    if doc["metrics"].get("collective_bytes_total", 0) <= 0:
        fail(f"{mode}: no collective bytes in the compiled schedule")
    mem = rec.get("memory") or {}
    if "hlo_peak_bytes" not in mem and \
            "hlo_memory_unavailable" not in mem:
        fail(f"{mode}: memory leg has neither hlo_peak_bytes nor the "
             f"hlo_memory_unavailable marker: {sorted(mem)}")
    return rec


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="outputs/hlo")
    args = ap.parse_args(argv)
    os.makedirs(args.out, exist_ok=True)

    from dmlp_tpu.bench.configs import BENCH_CONFIGS
    from dmlp_tpu.bench.harness import ensure_input
    input_path = ensure_input(BENCH_CONFIGS[1], "inputs")

    # 1) byte identity per mode, plus the hlo RunRecord per mode
    reconciles = {}
    for mode in MODES:
        base = run_cli(input_path, ["--mode", mode])
        rep_path = os.path.join(args.out, f"HLO_{mode}.jsonl")
        if os.path.exists(rep_path):
            os.remove(rep_path)
        out = run_cli(input_path,
                      ["--mode", mode, "--hlo-report", rep_path])
        if out != base:
            fail(f"{mode}: --hlo-report changed contract stdout")
        with open(rep_path) as f:
            doc = json.loads(f.read().splitlines()[-1])
        reconciles[mode] = check_doc(doc, mode)
        print(f"hlo_smoke: {mode}: contract byte-identical, "
              f"{doc['metrics']['collective_bytes_total']} collective "
              f"bytes introspected")

    # 2) hand-rolled engines reconcile against their own models
    for mode, kind in (("sharded", "all-gather"),
                       ("ring", "collective-permute")):
        kinds = (reconciles[mode].get("comms_model") or {}).get("kinds",
                                                                {})
        ent = kinds.get(kind)
        if not ent:
            fail(f"{mode}: no {kind} leg in the comms reconcile: "
                 f"{sorted(kinds)}")
        if not ent.get("within_tolerance"):
            fail(f"{mode}: {kind} HLO bytes do not reconcile with the "
                 f"analytic model: {ent}")
        print(f"hlo_smoke: {mode}: {kind} model ratio "
              f"{ent['ratio']} within {ent['ratio_bounds']}")

    # 3) the partitioner's schedule named with per-axis bytes
    kinds = (reconciles["auto"].get("comms_model") or {}).get("kinds", {})
    named = [(k, e) for k, e in kinds.items()
             if e.get("hlo_bytes", 0) > 0]
    if not named:
        fail(f"auto: partitioner schedule empty: {kinds}")
    bad = [k for k, e in named
           if not (e.get("within_tolerance") or e.get("hlo_only"))]
    if bad:
        fail(f"auto: gspmd_* records do not reconcile for {bad}")
    with open(os.path.join(args.out, "HLO_auto.jsonl")) as f:
        doc = json.loads(f.read().splitlines()[-1])
    by_axis = (doc.get("comms") or {}).get("bytes_by_axis") or {}
    if not by_axis or sum(by_axis.values()) <= 0:
        fail(f"auto: no per-axis byte attribution: {by_axis}")
    print(f"hlo_smoke: auto: partitioner chose "
          f"{', '.join(k for k, _ in named)}; bytes by axis {by_axis}")

    # 4) ledger round-trip per mode
    from dmlp_tpu.obs.ledger import ingest_file
    for mode in MODES:
        entry = ingest_file(os.path.join(args.out, f"HLO_{mode}.jsonl"))
        if entry.get("status") != "parsed":
            fail(f"{mode}: ledger ingest: {entry}")
        series = {p["series"] for p in entry["points"]}
        want = f"hlo/{mode}/collective_bytes_total"
        if want not in series:
            fail(f"{mode}: series {want} missing: {sorted(series)}")
    print("hlo_smoke: ledger round-trip ok "
          "(hlo/<mode>/collective_bytes_total for "
          + ", ".join(MODES) + ")")
    print("hlo_smoke: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
