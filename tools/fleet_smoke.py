#!/usr/bin/env python
"""Fleet smoke: prove the serving fleet end to end on CPU.

The ``make fleet-smoke`` checker (wired into ``make test``). Eight
proofs over a REAL fleet — a plain resident replica + a MESH-RESIDENT
replica (``--mesh 2x1``, per-shard resident buffers with the
allgather merge as the micro-batch epilogue) behind the
``python -m dmlp_tpu.fleet`` router — every failure exits nonzero with
the reason named:

1. **Fleet ready** — both replicas warm their buckets and announce;
   the router probes them healthy.
2. **Routed byte-identity** — the committed paced trace
   (inputs/serve_trace2.jsonl) replayed closed-loop THROUGH the
   router: every response byte-identical to the float64 golden oracle,
   and both replicas actually served traffic.
3. **Compile-once across the fleet** — each replica's compile counter
   after the replay equals its ready-file value (the mesh-resident
   path included).
4. **Paced open-loop SLO curve** — the trace's t_ms schedule replayed
   open-loop at two offered-load multipliers; the per-level
   p50/p95/p99 land in fleet RunRecords that round-trip the perf
   ledger as gated ``fleet/<level>/...`` series.
5. **Wide-k multipass serving** — a separate extract-path daemon
   serves k past the kernel's single-pass window through the
   multipass driver against its RESIDENT chunks: response golden,
   bucket path "multipass", passes > 1, compile counter flat.
6. **Fleet ingest** — rows ingested once through the router fan out
   to EVERY replica; the next routed replay matches the golden oracle
   over the GROWN corpus with zero new solve compiles on either
   replica.
7. **Aggregated scrape** — the router's /metrics merges both
   replicas' live scrapes (counters summed, histograms bucket-wise,
   per-replica gauges) into one exposition that passes
   validate_openmetrics; tools/fleet_scrape.py agrees. Trace
   validation teeth: check_serve_trace accepts the committed trace
   and rejects a non-monotonic one.
8. **Fleet drain** — one in-band drain propagates router -> replicas;
   every process exits 0, no flight dumps.

Usage::

    python tools/fleet_smoke.py --out outputs/fleet \
        [--record outputs/fleet/FLEET_SMOKE.jsonl]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from dmlp_tpu.fleet import harness as fh                  # noqa: E402
from dmlp_tpu.fleet import loadgen                        # noqa: E402
from dmlp_tpu.io.grammar import KNNInput, Params, parse_input_text  # noqa: E402
from dmlp_tpu.obs.telemetry import validate_openmetrics   # noqa: E402
from dmlp_tpu.serve import client as sc                   # noqa: E402

TRACE_PATH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "inputs", "serve_trace2.jsonl")
BATCH_CAP = 32

WIDEK_CORPUS = dict(num_data=1408, num_queries=4, num_attrs=4,
                    min_attr=0.0, max_attr=60.0, min_k=1, max_k=8,
                    num_labels=5, seed=41)
WIDEK_HEADER = {"serve_trace_schema": 1, "corpus": WIDEK_CORPUS}
WIDEK_TRACE = [{"t_ms": 0, "nq": 2, "ks": [520, 600], "seed": 4100}]


def fail(msg: str):
    print(f"fleet_smoke: FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def say(msg: str) -> None:
    print(f"fleet_smoke: {msg}")


def scrape(port: int) -> str:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
        return r.read().decode()


def replica_stats(port: int) -> dict:
    cli = sc.ServeClient(port)
    try:
        return cli.stats()["stats"]
    finally:
        cli.close()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="outputs/fleet")
    ap.add_argument("--record", default=None)
    args = ap.parse_args(argv)
    out = os.path.abspath(args.out)
    os.makedirs(out, exist_ok=True)
    record = os.path.abspath(args.record) if args.record \
        else os.path.join(out, "FLEET_SMOKE.jsonl")
    if os.path.exists(record):
        os.remove(record)
    sc.clear_flight_dumps(out)

    header, reqs = sc.load_trace(TRACE_PATH)
    corpus_txt = sc.corpus_text(header)
    corpus_path = os.path.join(out, "corpus.in")
    with open(corpus_path, "w") as f:
        f.write(corpus_txt)
    corpus = parse_input_text(corpus_txt)
    golden = sc.golden_reference(corpus, header, reqs)
    warm = ",".join(f"{q}x{k}" for q, k in
                    sc.warm_buckets_for_trace(reqs, BATCH_CAP))

    # 1. fleet up: plain resident replica + mesh-resident replica
    ra = fh.spawn_replica(corpus_path, out, "replica_a", warm,
                          batch_cap=BATCH_CAP)
    rb = fh.spawn_replica(
        corpus_path, out, "replica_b", warm, batch_cap=BATCH_CAP,
        flags=["--mesh", "2x1"],
        env_extra={"XLA_FLAGS":
                   "--xla_force_host_platform_device_count=2"})
    procs = [ra, rb]
    router = None
    try:
        for fp in (ra, rb):
            try:
                fh.await_replica(fp)
            except RuntimeError as e:
                fail(str(e))
        router = fh.spawn_router(out, [ra, rb])
        procs.append(router)
        say(f"fleet ready: router port={router.ready['port']} over "
            f"plain(:{ra.ready['port']}) + mesh 2x1(:{rb.ready['port']})"
            f", cold starts {ra.ready['cold_start_compile_ms']} / "
            f"{rb.ready['cold_start_compile_ms']} ms")

        # 2. routed byte-identity
        res = sc.replay(router.ready["port"], header, reqs,
                        connections=3)
        bad = [r for r in res if not r.get("ok")]
        if bad:
            fail(f"routed replay had {len(bad)} failures: {bad[0]}")
        if sc.contract_text([r["checksums"] for r in res]) != \
                sc.contract_text(golden):
            fail("routed responses differ from the golden oracle")
        st = replica_stats(router.ready["port"])
        served = {r["replica"]: r["requests"] for r in st["replicas"]}
        if any(v == 0 for v in served.values()):
            fail(f"a replica served nothing: {served}")
        say(f"routed replay OK: {len(reqs)} requests golden-identical, "
            f"fanned {served}")

        # 3. compile-once per replica (mesh-resident included)
        for fp in (ra, rb):
            eng = replica_stats(fp.ready["port"])["engine"]
            if eng["compile_count"] != fp.ready["compile_count"]:
                fail(f"{fp.name} compile counter moved "
                     f"{fp.ready['compile_count']} -> "
                     f"{eng['compile_count']}")
        say("compile-once OK on both replicas")

        # 4. paced open-loop SLO levels -> gated fleet/ ledger series
        recs = loadgen.run_levels(router.ready["port"], header, reqs,
                                  speeds=[2.0, 8.0], reps=2,
                                  replicas=2, trace="serve_trace2")
        for rec in recs:
            if rec.metrics.get("errors"):
                fail(f"open-loop level {rec.config['level']} had "
                     f"errors: {rec.metrics}")
            if "p99_ms" not in rec.metrics:
                fail(f"level {rec.config['level']} recorded no p99")
            rec.append_jsonl(record)
        say("open-loop OK: "
            + "; ".join(f"{r.config['level']}: offered "
                        f"{r.metrics.get('offered_qps')} qps, p99 "
                        f"{r.metrics['p99_ms']} ms" for r in recs))

        # 5. wide-k multipass serving (extract-path daemon, resident
        # chunks, k past the single-pass window)
        wk_txt = sc.corpus_text(WIDEK_HEADER)
        wk_path = os.path.join(out, "widek_corpus.in")
        with open(wk_path, "w") as f:
            f.write(wk_txt)
        wk_corpus = parse_input_text(wk_txt)
        wk_golden = sc.golden_reference(wk_corpus, WIDEK_HEADER,
                                        WIDEK_TRACE)
        wd = fh.spawn_replica(
            wk_path, out, "replica_widek", "2x600", batch_cap=8,
            flags=["--pallas", "--select", "extract",
                   "--data-block", "512"])
        procs.append(wd)
        try:
            fh.await_replica(wd, timeout_s=600)
            wres = sc.replay(wd.ready["port"], WIDEK_HEADER,
                             WIDEK_TRACE, connections=1)
            if not wres[0].get("ok"):
                fail(f"wide-k request failed: {wres[0]}")
            if [r["checksums"] for r in wres] != wk_golden:
                fail("wide-k response differs from the golden oracle")
            weng = replica_stats(wd.ready["port"])["engine"]
            if "multipass" not in weng["paths"].values():
                fail(f"wide-k bucket did not take the multipass path: "
                     f"{weng['paths']}")
            if weng["compile_count"] != wd.ready["compile_count"]:
                fail("wide-k replay recompiled")
            sc.sigterm_drain(wd.proc, errlog=wd.errlog)
        finally:
            fh.kill_all([wd])
        say(f"wide-k multipass OK: k=600 served golden on the resident "
            f"chunks ({weng['paths']}), compile flat")

        # 6. fleet ingest fan-out
        import numpy as np
        rng = np.random.default_rng(5)
        m = 7
        newl = rng.integers(0, header["corpus"]["num_labels"],
                            m).astype(int)
        newa = rng.uniform(header["corpus"]["min_attr"],
                           header["corpus"]["max_attr"],
                           (m, header["corpus"]["num_attrs"]))
        cli = sc.ServeClient(router.ready["port"])
        r = cli.ingest([int(v) for v in newl], newa)
        cli.close()
        n0 = header["corpus"]["num_data"]
        if not r.get("ok") or r.get("corpus_rows") != n0 + m:
            fail(f"fleet ingest failed: {r}")
        for fp in (ra, rb):
            eng = replica_stats(fp.ready["port"])["engine"]
            if eng["corpus_rows"] != n0 + m:
                fail(f"{fp.name} missed the ingest fan-out: "
                     f"{eng['corpus_rows']}")
        grown = KNNInput(
            Params(n0 + m, 0, header["corpus"]["num_attrs"]),
            np.concatenate([corpus.labels, newl.astype(np.int32)]),
            np.vstack([corpus.data_attrs, newa]),
            np.zeros(0, np.int32),
            np.zeros((0, header["corpus"]["num_attrs"])))
        res2 = sc.replay(router.ready["port"], header, reqs[:6],
                         connections=2)
        want = sc.golden_reference(grown, header, reqs[:6])
        if sc.contract_text([r["checksums"] for r in res2]) != \
                sc.contract_text(want):
            fail("post-ingest routed replay differs from the grown-"
                 "corpus oracle")
        for fp in (ra, rb):
            eng = replica_stats(fp.ready["port"])["engine"]
            if eng["compile_count"] != fp.ready["compile_count"]:
                fail(f"{fp.name}: ingest recompiled a solve program")
        say("fleet ingest OK: fanned to both replicas, grown-corpus "
            "replay golden, zero new compiles")

        # 7. aggregated scrape + trace-validation teeth
        om = scrape(router.ready["telemetry_port"])
        errs = validate_openmetrics(om)
        if errs:
            fail(f"fleet scrape invalid: {errs[:3]}")
        for want_m in ("serve_requests_completed",
                       "fleet_request_latency_ms",
                       'replica="127.0.0.1:'):
            if want_m not in om:
                fail(f"fleet scrape missing {want_m!r}")
        tools = os.path.dirname(os.path.abspath(__file__))
        rc = subprocess.call(
            [sys.executable, os.path.join(tools, "fleet_scrape.py"),
             f"http://127.0.0.1:{ra.scrape_port}/metrics",
             f"http://127.0.0.1:{rb.scrape_port}/metrics",
             "--out", os.path.join(out, "fleet_scrape.prom")],
            stdout=subprocess.DEVNULL, env=fh._repo_env())
        if rc != 0:
            fail("tools/fleet_scrape.py rejected the replica scrapes")
        rc = subprocess.call(
            [sys.executable, os.path.join(tools, "check_serve_trace.py"),
             TRACE_PATH], stdout=subprocess.DEVNULL,
            env=fh._repo_env())
        if rc != 0:
            fail("check_serve_trace rejected the committed trace")
        bad_path = os.path.join(out, "bad_trace.jsonl")
        with open(bad_path, "w") as f:
            f.write(json.dumps(
                {"serve_trace_schema": 1,
                 "corpus": header["corpus"]}) + "\n")
            f.write('{"t_ms": 5, "nq": 1, "k": 1, "seed": 1}\n')
            f.write('{"t_ms": 3, "nq": 1, "k": 1, "seed": 2}\n')
        rc = subprocess.call(
            [sys.executable, os.path.join(tools, "check_serve_trace.py"),
             bad_path], stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL, env=fh._repo_env())
        if rc == 0:
            fail("check_serve_trace accepted a non-monotonic trace")
        say("aggregated scrape OK (valid, per-replica labels); trace "
            "validation has teeth")

        # 8. fleet drain
        try:
            fh.drain_fleet(router, [ra, rb])
        except RuntimeError as e:
            fail(str(e))
    finally:
        fh.kill_all(procs)
    flights = sc.flight_dumps(out)
    if flights:
        fail(f"orderly fleet drain left flight dumps: {flights}")
    say("fleet drain OK: router + both replicas exited 0, no flight "
        "dumps")

    from dmlp_tpu.obs.ledger import ingest_file
    entry = ingest_file(record)
    if entry["status"] != "parsed":
        fail(f"fleet RunRecords did not parse in the ledger: "
             f"{entry.get('error')}")
    series = {p["series"] for p in entry["points"]}
    for want_s in ("fleet/x2/p99_ms", "fleet/x8/p99_ms",
                   "fleet/x2/offered_qps"):
        if want_s not in series:
            fail(f"ledger series missing {want_s} "
                 f"(got {sorted(series)[:8]}...)")
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "perf_gate", os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "perf_gate.py"))
    pg = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(pg)
    if not pg.gated("fleet/x2/p99_ms"):
        fail("fleet/ series are not in the perf gate's prefixes")
    say(f"ledger round-trip OK: {len(entry['points'])} fleet/ points, "
        "p99-vs-offered-load gated")
    say("PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
