// Standalone cross-check of the result checksum semantics
// (the FNV-1a fold of reference common.cpp:57-71), used to validate
// dmlp_tpu.io.checksum. Build: g++ -O2 -o verify_checksum verify_checksum.cpp
// Prints checksums for a handful of (label, ids...) cases.
#include <cstdio>
#include <vector>

unsigned long long checksum_of(int label, const std::vector<int>& ids) {
    unsigned long long checksum = 1469598103934665603ULL;
    checksum ^= static_cast<unsigned long long>(label);
    checksum *= 1099511628211ULL;
    for (int idx : ids) {
        checksum ^= static_cast<unsigned long long>(idx + 1);
        checksum *= 1099511628211ULL;
    }
    return checksum;
}

int main() {
    printf("%llu\n", checksum_of(3, {}));
    printf("%llu\n", checksum_of(1, {0, 1, 2}));
    printf("%llu\n", checksum_of(0, {-1}));
    printf("%llu\n", checksum_of(-1, {}));
    printf("%llu\n", checksum_of(7, {41, 12, 3, -1, -1}));
    return 0;
}
