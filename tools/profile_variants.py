"""Benchmark alternative implementations of the seg-step's two hot ops
(segment gather, candidate merge top-k) and the one-big-chunk layout, to
pick the round-3 solve design empirically. Amortized in-jit loops as in
profile_amortized.py."""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

R = 10


def amortized(make_body, *args, repeats=R):
    @jax.jit
    def loop(*a):
        def body(_, c):
            return make_body(c * 1e-30, *a)
        return jax.lax.fori_loop(0, repeats, body, jnp.float32(0.0))

    float(loop(*args))
    t0 = time.perf_counter()
    float(loop(*args))
    return (time.perf_counter() - t0) / repeats * 1e3


def main() -> int:
    nq, a, k = 10240, 64, 40
    out = {}

    for dblock in (51200, 204800):
        nseg = dblock // 128
        s = min(nseg, k + 16)
        # The gather variants materialize a perturbed copy of the tile, so
        # the big block needs 2x tile bytes on device: halve the query
        # rows there (per-query costs are linear in nq; the variant
        # RANKING this tool exists for is unchanged) and generate on
        # device — a host f64 standard_normal of (10240, 204800) is
        # 16.8 GB of host RAM for no reason.
        nq_b = nq // 2 if dblock >= 204800 else nq
        tile = jnp.abs(jax.random.normal(
            jax.random.PRNGKey(0), (nq_b, dblock), jnp.float32)) * 100
        segmin = tile.reshape(nq_b, nseg, 128).min(axis=-1)
        seg_idx = jax.lax.top_k(-segmin, s)[1]
        cand = jnp.take_along_axis(
            tile.reshape(nq_b, nseg, 128), seg_idx[:, :, None], axis=1
        ).reshape(nq_b, s * 128)
        carry = jnp.zeros((nq_b, k), jnp.float32)
        float(jnp.sum(cand))
        tag = f"b{dblock}_q{nq_b}"  # nq in the key: cross-round
        # artifacts must not be conflated when the workload halves

        # seg_topk at this nseg
        out[f"{tag}/seg_topk_{nseg}_to_{s}"] = amortized(
            lambda e, sm: jnp.sum(jax.lax.top_k(-(sm + e), s)[0]), segmin)

        # gather variants
        out[f"{tag}/gather_take_along"] = amortized(
            lambda e, t, si: jnp.sum(jnp.take_along_axis(
                (t + e).reshape(nq_b, nseg, 128), si[:, :, None], axis=1)),
            tile, seg_idx)

        def gather_onehot(e, t, si):
            oh = (si[:, :, None] == jnp.arange(nseg)[None, None, :]
                  ).astype(jnp.float32)          # (nq_b, s, nseg)
            g = jax.lax.dot_general(
                oh, (t + e).reshape(nq_b, nseg, 128),
                (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)  # (nq_b, s, 128)
            return jnp.sum(g)
        out[f"{tag}/gather_onehot_matmul"] = amortized(
            gather_onehot, tile, seg_idx)

        # merge variants (carry + cand -> top k)
        out[f"{tag}/merge_direct"] = amortized(
            lambda e, c, cd: jnp.sum(jax.lax.top_k(
                -jnp.concatenate([c, cd + e], axis=-1), k)[0]),
            carry, cand)

        def merge_2stage(e, c, cd):
            c3 = (cd + e).reshape(nq_b, s, 128)
            t1 = jax.lax.top_k(-c3, k)[0]            # (nq, s, k)
            allc = jnp.concatenate([c, -t1.reshape(nq_b, s * k)], axis=-1)
            return jnp.sum(jax.lax.top_k(-allc, k)[0])
        out[f"{tag}/merge_2stage"] = amortized(merge_2stage, carry, cand)

        def merge_sortseg(e, c, cd):
            c3 = jax.lax.sort((cd + e).reshape(nq_b, s, 128), dimension=-1)
            t1 = c3[:, :, :k]
            allc = jnp.concatenate([c, t1.reshape(nq_b, s * k)], axis=-1)
            return jnp.sum(jax.lax.top_k(-allc, k)[0])
        out[f"{tag}/merge_sortseg"] = amortized(merge_sortseg, carry, cand)

    # End-to-end seg solve, one big chunk vs 4 chunks, via streaming_topk.
    from dmlp_tpu.ops.pallas_distance import native_pallas_backend
    from dmlp_tpu.ops.topk import streaming_topk
    native = native_pallas_backend()
    rng = np.random.default_rng(0)
    n = 204800
    q = jnp.asarray(rng.uniform(0, 100, (nq, a)), jnp.float32)
    d = jnp.asarray(rng.uniform(0, 100, (n, a)), jnp.float32)
    lab = jnp.asarray(rng.integers(0, 10, n, dtype=np.int32))
    ids = jnp.arange(n, dtype=jnp.int32)
    float(jnp.sum(d))
    import functools
    for db in (51200, 102400, 204800):
        # One-big-chunk at full Q needs ~2x the 8.4 GB live tile in HBM
        # (tile + selection temps) — compile OOM on a 16 GB chip; halve
        # the query rows there (linear in Q, ranking unchanged).
        qe = q[: nq // 2] if db >= 204800 else q
        fn = jax.jit(functools.partial(
            streaming_topk, k=k, data_block=db, select="seg",
            use_pallas=native))
        out[f"solve_seg_dblock{db}_q{qe.shape[0]}"] = amortized(
            lambda e, qq, d, l, i, _fn=fn: jnp.sum(
                _fn(qq + e, d, l, i).dists),
            qe, d, lab, ids, repeats=3)

    print(json.dumps(out, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
