#!/usr/bin/env python
"""Fleet chaos smoke: prove the self-healing fleet under seeded chaos.

The ``make fleet-chaos-smoke`` checker (wired into ``make test``).
Three seeded failure campaigns over REAL fleets on CPU — every served
response byte-identical to the float64 golden oracle throughout, every
process alive at drain time exiting 0, no flight dumps:

1. **Seeded replica kill mid-traffic** (supervised fleet, mesh 2x1
   replicas): one managed replica is SIGKILLed while a replay wave is
   in flight. Every response of the wave still matches the golden
   oracle (the router's bounded retry hides the crash), the supervisor
   detects the death, relaunches within its budget
   (``fleet.scale.{crashes,relaunches}`` non-vacuous), and the revived
   fleet serves golden again.
2. **Forced shard re-split under open-loop load**: far-row ingest
   through the router pushes both replicas past the capacity-padded
   buffer threshold while paced open-loop traffic keeps firing. The
   supervisor stages one split at a time — grown-layout replacement,
   checksum-verified corpus replay, atomic routing-table swap, drain
   of the old replica — until every replica runs the grown capacity
   (``fleet.reshard.splits`` >= 2). No request is lost: every wave
   response is either golden (far rows provably cannot enter any
   top-k, so the base oracle stays exact under every interleaving) or
   an explicit rejection; the post-split replay matches the
   grown-corpus oracle on the doubled layout.
3. **Injected ingest divergence** (static fleet + PR 7 fault site):
   one replica runs under a seeded ``serve.ingest`` transient fault —
   its first ingest is dropped, forking the corpus. The router reports
   the divergence to the client, the health prober's checksum
   comparison detects it, and the targeted delta re-ingest repairs it
   (``fleet.consistency.{divergences,repairs}`` non-vacuous); the
   repaired fleet answers the grown-corpus oracle byte-for-byte and
   drains rc 0.

4. **Warm compile-cache relaunch**: a replica is cold-started against
   an empty persistent compile cache (``--compile-cache``), drained,
   and relaunched against the now-populated cache. The relaunch must
   recover strictly faster (``fleet/chaos_warm_cache/
   cold_start_compile_ms`` is the warm number, gated lower-is-better),
   with a flat bucket ``compile_count`` and byte-identical golden
   replies from the warmed executables — the fleet cold-start story
   measured, not assumed.

Each campaign lands a ``fleet/chaos_*/...`` RunRecord; the file is
ingested by the perf ledger and the series are perf-gate-covered
(``FLEET_CHAOS_r15.jsonl`` is the committed round).

Usage::

    python tools/fleet_chaos_smoke.py --out outputs/fleet_chaos \
        [--record outputs/fleet_chaos/FLEET_CHAOS_SMOKE.jsonl]
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np                                         # noqa: E402

from dmlp_tpu.fleet import harness as fh                   # noqa: E402
from dmlp_tpu.io.grammar import KNNInput, Params, parse_input_text  # noqa: E402
from dmlp_tpu.obs.run import RunRecord, current_device     # noqa: E402
from dmlp_tpu.serve import client as sc                    # noqa: E402

BATCH_CAP = 16
BASE_CORPUS = dict(num_data=200, num_queries=4, num_attrs=4,
                   min_attr=0.0, max_attr=50.0, min_k=1, max_k=8,
                   num_labels=5, seed=42)
HEADER = {"serve_trace_schema": 1, "corpus": BASE_CORPUS}
REQS = [{"t_ms": t, "nq": nq, "k": k, "seed": 9000 + i}
        for i, (t, nq, k) in enumerate(
            [(0, 1, 3), (0, 2, 8), (30, 4, 5), (60, 1, 8), (90, 3, 3),
             (120, 2, 5), (150, 1, 5), (180, 4, 8), (210, 2, 3),
             (240, 1, 8), (270, 3, 5), (300, 2, 8)])]
FAR_OFFSET = 1e6      # ingested rows no top-k can reach: the base
#                       oracle stays exact under every interleaving


def fail(msg: str):
    print(f"fleet_chaos_smoke: FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def say(msg: str) -> None:
    print(f"fleet_chaos_smoke: {msg}")
    sys.stdout.flush()


def router_stats(port: int) -> dict:
    cli = sc.ServeClient(port)
    try:
        return cli.stats()["stats"]
    finally:
        cli.close()


def await_stats(port: int, pred, what: str, timeout_s: float = 300.0,
                proc=None, errlog: str = "") -> dict:
    deadline = time.monotonic() + timeout_s
    last = None
    while time.monotonic() < deadline:
        if proc is not None and proc.poll() is not None:
            fail(f"router died waiting for {what}; see {errlog}")
        try:
            last = router_stats(port)
            if pred(last):
                return last
        except (OSError, ValueError, KeyError):
            pass
        time.sleep(0.25)
    fail(f"timed out waiting for {what}; last stats: "
         f"{json.dumps(last)[:800] if last else None}")


def spawn_supervised_router(out: str, corpus_path: str, warm: str,
                            record: str):
    ready = os.path.join(out, "router_ready.json")
    errlog = os.path.join(out, "router.err")
    if os.path.exists(ready):
        os.remove(ready)
    cmd = [sys.executable, "-m", "dmlp_tpu.fleet",
           "--spawn-corpus", corpus_path,
           "--spawn-replicas", "2", "--max-replicas", "4",
           "--out-dir", out, "--spawn-warm", warm,
           "--spawn-batch-cap", str(BATCH_CAP),
           "--spawn-flags", "--mesh 2x1",
           "--relaunch-budget", "2",
           "--unhealthy-deadline-s", "15",
           "--reshard-threshold", "0.9",
           "--revive-probes", "2",
           "--health-interval-s", "0.2", "--poll-s", "0.3",
           "--port", "0", "--telemetry-port", "0",
           "--ready-file", ready, "--record", record]
    with open(errlog, "w") as ef:
        proc = subprocess.Popen(cmd, stderr=ef,
                                stdout=subprocess.DEVNULL,
                                env=fh._repo_env(), cwd=out)
    doc = sc.await_ready(proc, ready, timeout_s=600, errlog=errlog)
    return proc, doc, errlog


class TrafficWave(threading.Thread):
    """Background open-loop waves against the router; collects every
    response for the none-lost / all-golden-or-rejected audit."""

    def __init__(self, port: int, golden_per_req):
        super().__init__(daemon=True)
        self.port = port
        self.golden_per_req = golden_per_req
        self.stop_flag = threading.Event()
        self.waves = 0
        self.lost = 0
        self.transport_errors = []
        self.rejected = 0
        self.wrong = 0
        self.latencies = []

    def run(self):
        while not self.stop_flag.is_set():
            res = sc.replay_open_loop(self.port, HEADER, REQS,
                                      speed=2.0)
            self.waves += 1
            if len(res) != len(REQS):
                self.lost += len(REQS) - len(res)
            ok = [r for r in res if r.get("ok")]
            for r in res:
                if r.get("ok"):
                    self.latencies.append(r["client_ms"])
                    continue
                err = str(r.get("error", ""))
                if "rejected" in err or "draining" in err:
                    self.rejected += 1
                else:
                    self.transport_errors.append(err)
            # ok responses must be base-oracle golden regardless of
            # which replica (old layout or grown) answered.
            got = sc.contract_text([r["checksums"] for r in ok])
            want_ids = [i for i, r in enumerate(res) if r.get("ok")]
            want = sc.contract_text(
                [self.golden_per_req[i] for i in want_ids])
            if got != want:
                self.wrong += 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="outputs/fleet_chaos")
    ap.add_argument("--record", default=None)
    args = ap.parse_args(argv)
    out = os.path.abspath(args.out)
    os.makedirs(out, exist_ok=True)
    record = os.path.abspath(args.record) if args.record \
        else os.path.join(out, "FLEET_CHAOS_SMOKE.jsonl")
    if os.path.exists(record):
        os.remove(record)
    sc.clear_flight_dumps(out)
    # A previous run's ready files would make await_ready return a
    # dead process's port instantly — clear every stale one up front.
    for stale in os.listdir(out):
        if stale.endswith("_ready.json") or stale == "router_ready.json":
            os.remove(os.path.join(out, stale))
    device = current_device()

    corpus_txt = sc.corpus_text(HEADER)
    corpus_path = os.path.join(out, "corpus.in")
    with open(corpus_path, "w") as f:
        f.write(corpus_txt)
    corpus = parse_input_text(corpus_txt)
    golden = sc.golden_reference(corpus, HEADER, REQS)
    golden_text = sc.contract_text(golden)
    warm = ",".join(f"{q}x{k}" for q, k in
                    sc.warm_buckets_for_trace(REQS, BATCH_CAP))

    # ---- campaign 1 + 2 run on the SUPERVISED fleet -------------------------
    proc, ready, errlog = spawn_supervised_router(out, corpus_path,
                                                  warm, record)
    try:
        managed = ready.get("managed", [])
        if len(managed) != 2:
            fail(f"supervised router did not spawn 2 replicas: {ready}")
        st = router_stats(ready["port"])
        if st["healthy_replicas"] != 2:
            fail(f"fleet not healthy at ready: {st['replicas']}")
        say(f"supervised fleet ready: router :{ready['port']}, "
            f"mesh 2x1 replicas "
            f"{[m['replica'] for m in managed]}")

        # -- 1. seeded replica kill mid-traffic ------------------------------
        victim = managed[0]
        res_box = {}

        def replay_wave():
            res_box["res"] = sc.replay(ready["port"], HEADER, REQS,
                                       connections=3)

        t0 = time.perf_counter()
        wave = threading.Thread(target=replay_wave, daemon=True)
        wave.start()
        time.sleep(0.15)
        os.kill(victim["pid"], signal.SIGKILL)
        wave.join(timeout=300)
        if wave.is_alive():
            fail("replay wave wedged after the seeded kill")
        kill_ms = (time.perf_counter() - t0) * 1e3
        res = res_box["res"]
        bad = [r for r in res if not r.get("ok")]
        if bad:
            fail(f"seeded kill lost/failed {len(bad)} requests: "
                 f"{bad[0]}")
        if sc.contract_text([r["checksums"] for r in res]) \
                != golden_text:
            fail("responses during the seeded kill differ from the "
                 "golden oracle")
        st = await_stats(
            ready["port"],
            lambda s: (s["scale"]["crashes"] >= 1
                       and s["scale"]["relaunches"] >= 1
                       and s["healthy_replicas"] >= 2),
            "crash detection + relaunch", proc=proc, errlog=errlog)
        res2 = sc.replay(ready["port"], HEADER, REQS[:6],
                         connections=2)
        if any(not r.get("ok") for r in res2) or \
                sc.contract_text([r["checksums"] for r in res2]) \
                != sc.contract_text(golden[:6]):
            fail("revived fleet does not serve golden")
        say(f"seeded kill OK: {len(res)} in-flight requests all "
            f"golden, crash detected, relaunched "
            f"(budget left "
            f"{st['supervisor']['relaunch_budget_left']})")
        lat = sorted(r["client_ms"] for r in res)
        RunRecord(
            kind="fleet", tool="tools.fleet_chaos_smoke",
            config={"level": "chaos_kill", "replicas": 2,
                    "mode": "seeded_sigkill"},
            metrics={"requests": len(res), "errors": 0,
                     "wave_ms": round(kill_ms, 3),
                     "p99_ms": round(lat[int(len(lat) * 0.99) - 1], 3),
                     "crashes": st["scale"]["crashes"],
                     "relaunches": st["scale"]["relaunches"]},
            device=device).append_jsonl(record)

        # -- 2. forced shard re-split under open-loop load -------------------
        traffic = TrafficWave(ready["port"], golden)
        traffic.start()
        rng = np.random.default_rng(7)
        far_labels, far_rows = [], []
        n0 = BASE_CORPUS["num_data"]
        fill = 35                       # 200 -> 235 >= 0.9 * 256
        for lo in range(0, fill, 5):
            labs = [int(v) for v in rng.integers(
                0, BASE_CORPUS["num_labels"], 5)]
            rows = rng.uniform(FAR_OFFSET, FAR_OFFSET + 50.0,
                               (5, BASE_CORPUS["num_attrs"]))
            far_labels += labs
            far_rows.append(rows)
            cli = sc.ServeClient(ready["port"])
            r = cli.ingest(labs, rows)
            cli.close()
            if not r.get("ok"):
                fail(f"far-row fill ingest failed: {r}")
        st = await_stats(
            ready["port"],
            lambda s: (s["scale"]["splits"] >= 2
                       and s.get("supervisor")
                       and len(s["supervisor"]["managed"]) >= 2
                       and all(m["capacity"] and m["capacity"] >= 512
                               for m in s["supervisor"]["managed"])),
            "both replicas re-split to the grown layout",
            timeout_s=600, proc=proc, errlog=errlog)
        traffic.stop_flag.set()
        traffic.join(timeout=120)
        if traffic.lost:
            fail(f"open-loop traffic lost {traffic.lost} responses "
                 "across the split")
        if traffic.transport_errors:
            fail(f"open-loop traffic saw non-rejection errors: "
                 f"{traffic.transport_errors[:3]}")
        if traffic.wrong:
            fail(f"{traffic.wrong} open-loop waves were not "
                 "byte-identical to the base oracle")
        grown = KNNInput(
            Params(n0 + fill, 0, BASE_CORPUS["num_attrs"]),
            np.concatenate([corpus.labels,
                            np.asarray(far_labels, np.int32)]),
            np.vstack([corpus.data_attrs] + far_rows),
            np.zeros(0, np.int32),
            np.zeros((0, BASE_CORPUS["num_attrs"])))
        res3 = sc.replay(ready["port"], HEADER, REQS[:6],
                         connections=2)
        want = sc.golden_reference(grown, HEADER, REQS[:6])
        if any(not r.get("ok") for r in res3) or \
                sc.contract_text([r["checksums"] for r in res3]) \
                != sc.contract_text(want):
            fail("post-split replay differs from the grown-corpus "
                 "oracle")
        sup = st["supervisor"]
        resharded_rcs = [e for e in sup["retired"]
                         if e["reason"] == "reshard"]
        if any(e["rc"] != 0 for e in resharded_rcs):
            fail(f"a re-split old replica exited nonzero: "
                 f"{resharded_rcs}")
        say(f"forced re-split OK: {st['scale']['splits']} staged "
            f"splits to capacity 512 under {traffic.waves} open-loop "
            f"waves ({traffic.rejected} explicit rejections, 0 lost), "
            f"grown-corpus replay golden, old replicas drained rc 0")
        tl = sorted(traffic.latencies) or [0.0]
        RunRecord(
            kind="fleet", tool="tools.fleet_chaos_smoke",
            config={"level": "chaos_split", "replicas": 2,
                    "mode": "forced_resplit_open_loop"},
            metrics={"requests": traffic.waves * len(REQS),
                     "errors": 0, "lost": 0,
                     "explicit_rejections": traffic.rejected,
                     "p99_ms": round(tl[int(len(tl) * 0.99) - 1], 3),
                     "splits": st["scale"]["splits"],
                     "grown_capacity_rows": 512,
                     "ingested_rows": fill},
            device=device).append_jsonl(record)

        # -- drain the supervised fleet (campaign 1+2 teardown) --------------
        cli = sc.ServeClient(ready["port"])
        cli.drain()
        cli.close()
        rc = proc.wait(timeout=300)
        if rc != 0:
            fail(f"supervised router drain exited {rc} (managed "
                 f"replica nonzero?); see {errlog}")
        say("supervised drain OK: router + every managed replica "
            "exited 0")
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)

    # ---- campaign 3: injected ingest divergence (PR 7 fault site) ----------
    sched_path = os.path.join(out, "divergence_faults.json")
    with open(sched_path, "w") as f:
        json.dump({"schema": 1, "seed": 7, "faults": [
            {"site": "serve.ingest", "kind": "transient", "times": 1,
             "message": "seeded dropped ingest"}]}, f)
    ra = fh.spawn_replica(corpus_path, out, "replica_div_a", warm,
                          batch_cap=BATCH_CAP)
    rb = fh.spawn_replica(corpus_path, out, "replica_div_b", warm,
                          batch_cap=BATCH_CAP,
                          env_extra={"DMLP_TPU_FAULTS": sched_path})
    procs = [ra, rb]
    router = None
    try:
        for fp in (ra, rb):
            fh.await_replica(fp)
        ready_r = os.path.join(out, "router_div_ready.json")
        errlog_r = os.path.join(out, "router_div.err")
        cmd = [sys.executable, "-m", "dmlp_tpu.fleet",
               "--replicas",
               f"127.0.0.1:{ra.ready['port']},"
               f"127.0.0.1:{rb.ready['port']}",
               "--repair", "on", "--revive-probes", "2",
               "--health-interval-s", "0.2",
               "--port", "0", "--ready-file", ready_r,
               "--record", record]
        with open(errlog_r, "w") as ef:
            rproc = subprocess.Popen(cmd, stderr=ef,
                                     stdout=subprocess.DEVNULL,
                                     env=fh._repo_env(), cwd=out)
        router = fh.FleetProc("router_div", rproc, ready_r, errlog_r)
        router.ready = sc.await_ready(rproc, ready_r, timeout_s=120,
                                      errlog=errlog_r)
        procs.append(router)
        rng = np.random.default_rng(11)
        m = 6
        newl = [int(v) for v in rng.integers(
            0, BASE_CORPUS["num_labels"], m)]
        newa = rng.uniform(BASE_CORPUS["min_attr"],
                           BASE_CORPUS["max_attr"],
                           (m, BASE_CORPUS["num_attrs"]))
        cli = sc.ServeClient(router.ready["port"])
        r = cli.ingest(newl, newa)
        cli.close()
        if r.get("ok") or "diverged" not in str(r.get("error", "")):
            fail(f"seeded fault did not surface an ingest divergence: "
                 f"{r}")
        t_repair = time.perf_counter()
        st = await_stats(
            router.ready["port"],
            lambda s: (s["consistency"]["divergences"] >= 1
                       and s["consistency"]["repairs"] >= 1),
            "divergence detection + repair", timeout_s=120,
            proc=rproc, errlog=errlog_r)
        repair_ms = (time.perf_counter() - t_repair) * 1e3
        sigs = []
        for fp in (ra, rb):
            cli = sc.ServeClient(fp.ready["port"])
            doc = cli.call({"op": "corpus", "start": 0, "count": 0})
            cli.close()
            sigs.append((doc["corpus_rows"], doc["checksum"]))
        if sigs[0] != sigs[1] or sigs[0][0] != \
                BASE_CORPUS["num_data"] + m:
            fail(f"replicas did not converge after repair: {sigs}")
        grown = KNNInput(
            Params(BASE_CORPUS["num_data"] + m, 0,
                   BASE_CORPUS["num_attrs"]),
            np.concatenate([corpus.labels,
                            np.asarray(newl, np.int32)]),
            np.vstack([corpus.data_attrs, newa]),
            np.zeros(0, np.int32),
            np.zeros((0, BASE_CORPUS["num_attrs"])))
        res4 = sc.replay(router.ready["port"], HEADER, REQS[:8],
                         connections=2)
        want = sc.golden_reference(grown, HEADER, REQS[:8])
        if any(not r.get("ok") for r in res4) or \
                sc.contract_text([r["checksums"] for r in res4]) \
                != sc.contract_text(want):
            fail("post-repair replay differs from the grown-corpus "
                 "oracle")
        say(f"injected divergence OK: dropped ingest reported, "
            f"detected, and repaired in {repair_ms:.0f} ms "
            f"({st['consistency']['repaired_rows']} rows "
            "re-delivered), replay golden on the repaired fleet")
        RunRecord(
            kind="fleet", tool="tools.fleet_chaos_smoke",
            config={"level": "chaos_divergence", "replicas": 2,
                    "mode": "seeded_dropped_ingest"},
            metrics={"requests": len(res4), "errors": 0,
                     "divergences": st["consistency"]["divergences"],
                     "repairs": st["consistency"]["repairs"],
                     "repaired_rows":
                         st["consistency"]["repaired_rows"],
                     "repair_ms": round(repair_ms, 3)},
            device=device).append_jsonl(record)
        try:
            fh.drain_fleet(router, [ra, rb])
        except RuntimeError as e:
            fail(str(e))
    finally:
        fh.kill_all(procs)
    flights = sc.flight_dumps(out)
    if flights:
        fail(f"chaos campaigns left flight dumps: {flights}")
    say("divergence fleet drain OK: router + both replicas exited 0, "
        "no flight dumps")

    # ---- campaign 4: warm compile-cache relaunch ----------------------------
    import shutil
    ccdir = os.path.join(out, "compile_cache")
    shutil.rmtree(ccdir, ignore_errors=True)   # cold arm = empty cache
    colds, counts = [], []
    for gen in ("cold", "warm"):
        fp = fh.spawn_replica(corpus_path, out, f"replica_cc_{gen}",
                              warm, batch_cap=BATCH_CAP,
                              compile_cache=ccdir)
        try:
            fh.await_replica(fp)
            colds.append(fp.ready["cold_start_compile_ms"])
            counts.append(fp.ready["compile_count"])
            res5 = sc.replay(fp.ready["port"], HEADER, REQS[:4])
            if any(not r.get("ok") for r in res5) or \
                    sc.contract_text([r["checksums"] for r in res5]) \
                    != sc.contract_text(golden[:4]):
                fail(f"{gen}-cache replica does not serve golden")
            cli = sc.ServeClient(fp.ready["port"])
            cli.drain()
            cli.close()
            rc = fp.proc.wait(timeout=120)
            if rc != 0:
                fail(f"{gen}-cache replica drain exited {rc}; "
                     f"see {fp.errlog}")
        finally:
            fh.kill_all([fp])
    if counts[1] != counts[0]:
        fail(f"warm relaunch changed bucket compile_count: "
             f"{counts[0]} -> {counts[1]} (the cache must not alter "
             "which programs are built, only how fast)")
    if not (colds[1] < colds[0]):
        fail(f"warm relaunch did not recover faster: cold "
             f"{colds[0]} ms -> warm {colds[1]} ms (persistent "
             f"compile cache at {ccdir} had no effect)")
    say(f"warm-cache relaunch OK: cold start {colds[0]:.0f} ms -> "
        f"warm {colds[1]:.0f} ms "
        f"({100.0 * (1 - colds[1] / colds[0]):.0f}% faster recovery, "
        f"compile_count flat at {counts[0]}, warm replies golden)")
    RunRecord(
        kind="fleet", tool="tools.fleet_chaos_smoke",
        config={"level": "chaos_warm_cache", "replicas": 1,
                "mode": "persistent_compile_cache_relaunch"},
        metrics={"cold_start_compile_ms": colds[1],
                 "cold_start_compile_ms_cold": colds[0],
                 "warm_recovery_speedup":
                     round(colds[0] / max(colds[1], 1e-9), 3),
                 "compile_count": counts[1]},
        device=device).append_jsonl(record)

    # ---- ledger round-trip + gate coverage ----------------------------------
    from dmlp_tpu.obs.ledger import ingest_file
    entry = ingest_file(record)
    if entry["status"] != "parsed":
        fail(f"chaos RunRecords did not parse in the ledger: "
             f"{entry.get('error')}")
    series = {p["series"] for p in entry["points"]}
    for want_s in ("fleet/chaos_kill/p99_ms", "fleet/chaos_split/p99_ms",
                   "fleet/chaos_divergence/repair_ms",
                   "fleet/chaos_warm_cache/cold_start_compile_ms"):
        if want_s not in series:
            fail(f"ledger series missing {want_s} "
                 f"(got {sorted(series)[:8]}...)")
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "perf_gate", os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "perf_gate.py"))
    pg = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(pg)
    if not pg.gated("fleet/chaos_split/p99_ms"):
        fail("fleet/chaos_* series are not perf-gate covered")
    say(f"ledger round-trip OK: {len(entry['points'])} chaos points, "
        "p99 series gated")
    say("PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
