#!/usr/bin/env python
"""Tail-latency attribution over a merged fleet trace.

Decomposes the client-observed latency quantiles (p50/p95/p99) at each
offered-load level into per-phase contributions, names the dominant
phase per level (queue-bound vs solve-bound vs coalesce-bound ...),
and emits one ledger-ingestible ``kind="tailattrib"`` RunRecord per
level, so ``fleet/<level>/phase/<name>_p99_ms`` becomes a
round-over-round series ``tools/perf_gate.py`` gates like every other
``fleet/`` series (a queue-phase p99 creeping up round-over-round is
the predictive-autoscaling signal BEFORE the end-to-end SLO slips).

Method: the quantiles of a sum are not the sum of quantiles, so naive
"p99 of each phase" double-counts. Instead, for each quantile q the
tool takes the TAIL COHORT — the requests whose client-measured
latency is >= the q-th latency — and reports each phase's MEAN
duration over that cohort. The cohort means sum (plus the un-phased
residual) to roughly the cohort's mean latency, so the decomposition
is additive and the argmax phase is a meaningful "what is the tail
waiting on".

Input is ``tools/merge_traces.py --fleet`` output: the per-rid table
(client_ms, lag_ms, per-phase ms, level) is read from the embedded
``fleet.requests`` block. Levels come from the ``client.request``
spans' ``level`` arg (``serve.client.replay_open_loop(level=...)``);
rids without a level fall into the ``all`` pseudo-level.

Usage: python tools/tail_attrib.py MERGED.json [--record FILE]
       [--json] [--round N]
``--json`` prints ONE machine-readable verdict document on stdout
(narration to stderr). Exit 0 when every level attributes; 1 when no
level has an attributable (ok + fully-phased) request.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.merge_traces import FLEET_PHASES                     # noqa: E402

QUANTILES = (("p50", 0.5), ("p95", 0.95), ("p99", 0.99))


def _quantile(sorted_vals, q: float) -> float:
    """Nearest-rank-with-interpolation quantile of a sorted list."""
    if not sorted_vals:
        return float("nan")
    pos = q * (len(sorted_vals) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    return sorted_vals[lo] + (sorted_vals[hi] - sorted_vals[lo]) * (pos - lo)


def attribute_level(reqs) -> dict:
    """Per-quantile per-phase tail decomposition of one level's
    requests: ``reqs`` is a list of (client_ms, {phase: ms}) pairs."""
    lats = sorted(c for c, _ in reqs)
    out = {"n": len(reqs), "quantiles": {}}
    for qt, q in QUANTILES:
        thresh = _quantile(lats, q)
        cohort = [(c, ph) for c, ph in reqs if c >= thresh]
        entry = {"client_ms": round(thresh, 3),
                 "cohort_n": len(cohort), "phases": {}}
        for p in FLEET_PHASES:
            entry["phases"][p] = round(
                sum(ph.get(p, 0.0) for _, ph in cohort) / len(cohort), 3)
        entry["residual_ms"] = round(
            sum(c - sum(ph.get(p, 0.0) for p in FLEET_PHASES)
                for c, ph in cohort) / len(cohort), 3)
        entry["dominant"] = max(entry["phases"],
                                key=lambda p: entry["phases"][p])
        out["quantiles"][qt] = entry
    out["dominant_p99"] = out["quantiles"]["p99"]["dominant"]
    return out


def attribute(merged: dict) -> dict:
    """-> {level_tag: attribution} over the merged fleet trace doc."""
    table = (merged.get("fleet") or {}).get("requests")
    if table is None:
        raise SystemExit("tail_attrib: FAIL: input has no fleet.requests "
                         "block — is it merge_traces --fleet output?")
    from dmlp_tpu.fleet.loadgen import level_tag
    by_level: dict = {}
    for rid in sorted(table):
        ent = table[rid]
        cl = ent.get("client")
        if cl is None or not cl.get("ok"):
            continue
        phases = ent.get("phases", {})
        if not all(p in phases for p in FLEET_PHASES):
            continue          # unphased requests cannot be attributed
        lvl = (level_tag(float(cl["level"])) if "level" in cl else "all")
        # Attribution decomposes time spent IN the fleet, so the
        # client's pre-send pacing lag is excluded up front.
        by_level.setdefault(lvl, []).append(
            (cl["client_ms"] - cl.get("lag_ms", 0.0), phases))
    return {lvl: attribute_level(reqs)
            for lvl, reqs in sorted(by_level.items())}


def emit_records(levels: dict, record_path: str, trace_path: str,
                 round_: int = None) -> int:
    from dmlp_tpu.obs.run import RunRecord, current_device
    n = 0
    for lvl, att in levels.items():
        metrics = {"attributed_requests": att["n"]}
        for qt, entry in att["quantiles"].items():
            metrics[f"client_{qt}_ms"] = entry["client_ms"]
            metrics[f"residual_{qt}_ms"] = entry["residual_ms"]
            for p, v in entry["phases"].items():
                metrics[f"{p}_{qt}_ms"] = v
        RunRecord(kind="tailattrib", tool="tools.tail_attrib",
                  config={"level": lvl, "dominant_p99":
                          att["dominant_p99"],
                          "trace": os.path.basename(trace_path),
                          "quantiles": [qt for qt, _ in QUANTILES]},
                  metrics=metrics, round=round_,
                  device=current_device()).append_jsonl(record_path)
        n += 1
    return n


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("merged", help="merge_traces --fleet output JSON")
    ap.add_argument("--record", metavar="FILE", default=None,
                    help="append one kind='tailattrib' RunRecord per "
                         "level here (ledger-ingestible)")
    ap.add_argument("--json", action="store_true",
                    help="print the attribution document on stdout "
                         "(narration to stderr)")
    ap.add_argument("--round", type=int, default=None,
                    help="measurement round stamped into the records")
    args = ap.parse_args(argv)

    def say(msg):
        print(msg, file=sys.stderr if args.json else sys.stdout)

    with open(args.merged) as f:
        merged = json.load(f)
    levels = attribute(merged)
    if not levels:
        print("tail_attrib: FAIL: no attributable request (none ok "
              "with a complete phase set) in the merged trace",
              file=sys.stderr)
        return 1
    for lvl, att in levels.items():
        p99 = att["quantiles"]["p99"]
        say(f"tail_attrib: {lvl}: n={att['n']} p99={p99['client_ms']}ms "
            f"dominant={att['dominant_p99']} "
            f"(phases ms: {p99['phases']}, "
            f"residual {p99['residual_ms']}ms)")
    if args.record:
        n = emit_records(levels, args.record, args.merged,
                         round_=args.round)
        say(f"tail_attrib: appended {n} tailattrib record(s) -> "
            f"{args.record}")
    if args.json:
        json.dump({"levels": levels, "phases": list(FLEET_PHASES)},
                  sys.stdout, indent=1, sort_keys=True)
        sys.stdout.write("\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
