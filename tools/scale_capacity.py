"""Scale + capacity proof for the extraction solve (VERDICT r3 item 8).

Runs the fenced extract solve (sort epilogue included, bench.py scope) at
dataset rungs up to >= 4M x 10k x 64 — a shape whose dense (Q, N) f32
distance tile would be ~164 GB, an order of magnitude beyond HBM — and
records the device's peak_bytes_in_use alongside, proving the
O(N*A + Q*K) memory claim at a scale where the tile could never fit.
Also re-times the kcap=136 rung with the r4 tuned wide-k variant
(SCALE_r03: 160.8 ms with the one-size default).

Writes SCALE_r04.json. Env: BENCH_REPEATS (default 3), BENCH_OUT.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import (_env_int, make_workload, stage_extract_inputs,  # noqa: E402
                   time_fenced_solve_ms)


def main() -> int:
    import jax

    from dmlp_tpu.engine.single import _extract_finalize
    from dmlp_tpu.ops.pallas_distance import native_pallas_backend
    from dmlp_tpu.ops.pallas_extract import extract_topk, supports

    if not native_pallas_backend():
        print("needs the native TPU backend", file=sys.stderr)
        return 1

    repeats = _env_int("BENCH_REPEATS", 3)
    out_path = os.environ.get("BENCH_OUT", "SCALE_r04.json")
    nq, na = 10240, 64
    # Ordered by resident-set size: peak_bytes_in_use (where available) is
    # a process-lifetime high-water mark, so a later SMALLER rung would
    # otherwise report an earlier rung's peak as its own.
    rungs = [(204800, 40), (204800, 136), (1024000, 40), (4006400, 40)]

    runs = []
    for n, kc in rungs:
        inp = make_workload(n, nq, na, 32)
        q, d, lab, npad, qpad = stage_extract_inputs(inp)
        assert supports(qpad, npad, na, kc), (n, kc)

        def fn(q_, d_):
            od, oi, _ = extract_topk(q_, d_, n_real=n, kc=kc)
            return _extract_finalize(od, oi, lab, k=kc).dists

        ms = time_fenced_solve_ms(fn, q, d, repeats)

        stats = jax.devices()[0].memory_stats() or {}
        peak = stats.get("peak_bytes_in_use")
        dense_tile = qpad * npad * 4
        rec = {
            "num_data": n, "num_queries": nq, "num_attrs": na, "kcap": kc,
            "device_solve_ms": round(ms, 1),
            "qd_pairs_per_sec": round(n * nq / (ms / 1e3)),
            "peak_hbm_bytes": peak,
            "dense_tile_bytes": dense_tile,
            "peak_vs_dense_tile": (round(peak / dense_tile, 4)
                                   if peak else None),
        }
        runs.append(rec)
        print(json.dumps(rec), flush=True)
        del d, q, inp

    doc = {
        "note": "Fenced extract solve (sort epilogue included) vs dataset "
                "size; peak_bytes_in_use recorded per rung. The 4M rung's "
                "dense (Q, N) f32 tile would be ~164 GB — peak HBM stays "
                "at the O(N*A + Q*K) resident set, proving the capacity "
                "claim (survey §5.7) at a scale the tile could never fit. "
                "kcap=136 rung uses the r4 tuned wide-k variant "
                "(SCALE_r03 default-tuned: 160.8 ms).",
        "device": str(jax.devices()[0]),
        "runs": runs,
    }
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
