#!/usr/bin/env python
"""PIPEBENCH: GPipe vs interleaved (1F1B-interleaved) schedule A/B.

Runs both dp_pp schedules at fixed shape across small microbatch counts
(the regime VERDICT r4 item 6 targets: the GPipe bubble term
(S-1)/(M+S-1) is largest there), interleaved A/B with rotating starts
(the verify-skill methodology), and records per-config median/min step
times plus the analytic bubble fractions — one schema-1 RunRecord
(obs.run) with the sweep table under metrics, plus per-schedule
ppermute activation bytes from obs.comms.

Pipeline parallelism needs multiple devices; the container has ONE real
TPU chip, so this runs on the virtual 8-device CPU mesh (like harness
config 3) — schedule-relative numbers, not absolute TPU step times.

Usage: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python tools/pipebench.py [--out PIPEBENCH_r06.json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def build(schedule: str, mesh, n_micro: int, n_virtual: int, dims, batch,
          seed=0):
    from dmlp_tpu.train import pipeline as pl
    from dmlp_tpu.train.step import make_optimizer

    d_in, hidden, n_classes = dims
    opt = make_optimizer("sgd", 0.05, momentum=0.0)
    if schedule == "gpipe":
        # layers_per_stage = n_virtual * layers_per_chunk so both
        # schedules train the SAME total layer count per stage.
        state = pl.build_pp_state(mesh, opt, d_in, hidden, n_classes,
                                  2 * n_virtual, seed=seed)
        step = pl.make_pp_train_step(mesh, opt, n_micro=n_micro,
                                     n_classes=n_classes)
    else:
        state = pl.build_ppi_state(mesh, opt, d_in, hidden, n_classes,
                                   n_virtual=n_virtual, layers_per_chunk=2,
                                   seed=seed)
        step = pl.make_ppi_train_step(mesh, opt, n_micro=n_micro,
                                      n_virtual=n_virtual,
                                      n_classes=n_classes)
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(batch, d_in)).astype(np.float32))
    y = jnp.asarray(rng.integers(0, n_classes, batch).astype(np.int32))
    return state, step, x, y


def time_steps(state, step, x, y, reps: int):
    state, m = step(state, x, y)  # compile + warm
    jax.block_until_ready(m["loss"])
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        state, m = step(state, x, y)
        jax.block_until_ready(m["loss"])
        times.append((time.perf_counter() - t0) * 1e3)
    return times, state


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="PIPEBENCH_r06.json")
    ap.add_argument("--reps", type=int, default=7)
    ap.add_argument("--hidden", type=int, default=1024)
    ap.add_argument("--dp", type=int, default=2)
    ap.add_argument("--pp", type=int, default=4)
    ap.add_argument("--virtual", type=int, default=2)
    args = ap.parse_args()

    from dmlp_tpu.obs.comms import pipeline_ppermute_traffic
    from dmlp_tpu.train.pipeline import (bubble_fraction, make_pp_mesh,
                                         schedule_ticks)
    mesh = make_pp_mesh(args.dp, args.pp)
    dims = (64, args.hidden, 16)

    records = []
    for n_micro in (1, 2, 4):
        batch = args.dp * max(n_micro, 1) * 64
        cells = {}
        for sched in ("gpipe", "interleaved"):
            cells[sched] = build(sched, mesh, n_micro, args.virtual, dims,
                                 batch)
        samples = {s: [] for s in cells}
        order = list(cells)
        for r in range(args.reps):
            for s in (order if r % 2 == 0 else order[::-1]):
                st, step, x, y = cells[s]
                ts, st = time_steps(st, step, x, y, 1)
                cells[s] = (st, step, x, y)
                samples[s].extend(ts)
        rec = {"n_micro": n_micro, "stages": args.pp, "dp": args.dp,
               "virtual": args.virtual, "hidden": args.hidden,
               "batch": batch}
        for s, ts in samples.items():
            # Analytic activation-traffic accounting (obs.comms): the
            # schedule's ppermute bytes per step, fwd + bwd mirrored.
            ppermute = pipeline_ppermute_traffic(
                args.pp, n_micro, batch // args.dp // n_micro, args.hidden,
                schedule=s, n_virtual=args.virtual, n_groups=args.dp,
                count=2)
            rec[s] = {
                "median_ms": float(np.median(ts)),
                "min_ms": float(np.min(ts)),
                "ticks": schedule_ticks(s, n_micro, args.pp, args.virtual),
                "bubble_fraction": bubble_fraction(s, n_micro, args.pp,
                                                   args.virtual),
                "ppermute_bytes_per_step": ppermute.bytes_total,
            }
        rec["interleaved_vs_gpipe_pct"] = 100.0 * (
            rec["interleaved"]["median_ms"] / rec["gpipe"]["median_ms"] - 1)
        records.append(rec)
        print(json.dumps(rec))

    # One schema-1 RunRecord (obs.run), sweeps merged in place per hidden
    # size: re-running the tool for one sweep must not clobber the other
    # sweeps already in the artifact (the small-hidden sweep is overhead-
    # dominated, the large one compute-dominated — both belong).
    from dmlp_tpu.obs.run import RunRecord
    sweeps = {}
    if os.path.exists(args.out):
        try:
            prev = json.load(open(args.out))
            # RunRecord form nests sweeps under metrics; the grandfathered
            # pre-migration artifact held them at top level.
            sweeps.update(prev.get("metrics", prev).get("sweeps", {}))
        except (json.JSONDecodeError, OSError):
            pass
    sweeps[f"hidden_{args.hidden}"] = records
    RunRecord(
        kind="pipebench", tool="tools/pipebench",
        config={"platform": jax.devices()[0].platform,
                "n_devices": len(jax.devices()),
                "dp": args.dp, "pp": args.pp, "virtual": args.virtual,
                "reps": args.reps},
        metrics={
            "note": "virtual CPU mesh (1 real TPU chip cannot host a "
                    "pipeline); schedule-relative timings + analytic "
                    "bubble fractions and ppermute activation bytes "
                    "(obs.comms). Small hidden sizes are per-tick-"
                    "overhead dominated (emulated collectives; "
                    "interleaved loses); compute-dominated sweeps show "
                    "the bubble win.",
            "sweeps": sweeps,
        },
    ).write(args.out)
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
