#!/usr/bin/env python
"""Repair-rate sweep: measure the kcap margin's generality (VERDICT r4 #9).

The bf16 staging margin 96 + k/2 (engine.single.resolve_kcap) was
calibrated at one shape (200k x 10k x 64); the eps-aware hazard test +
oracle repair is the sound backstop, but the margin's generality across
shapes, k, staging dtypes, and distance-density regimes was asserted, not
measured. This sweep runs the engine across that grid recording
last_repairs / num_queries (the repair RATE — every run is still
checksum-exact by construction; what varies is how often the backstop has
to fire) plus the multi-pass counter for wide-k cells.

Data styles:
  uniform   — generator-style uniform draws (the benchmark regime)
  clustered — tight Gaussian clusters (dense distance spectra: the regime
              that eats margins; f32-cancellation fuzz heritage)
  intdup    — small integer grids (massive exact tie groups)

Runs on CPU (forced dtype="bfloat16" staging exercises the same margin
arithmetic; interpret-mode kernel) or TPU (native kernel + real MXU
rounding) — the platform is recorded per artifact.

Usage:
  JAX_PLATFORMS=cpu python tools/repair_rate_sweep.py \
      [--out REPAIR_SWEEP_r05_cpu.json] [--quick]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def make_case(style: str, n: int, nq: int, na: int, kmax: int, seed: int):
    from dmlp_tpu.io.grammar import KNNInput, Params
    rng = np.random.default_rng(seed)
    if style == "uniform":
        data = rng.uniform(0, 100, (n, na))
        queries = rng.uniform(0, 100, (nq, na))
    elif style == "clustered":
        nc = 16
        centers = rng.uniform(0, 100, (nc, na))
        data = centers[rng.integers(0, nc, n)] + rng.normal(
            0, 1e-3, (n, na))
        queries = centers[rng.integers(0, nc, nq)] + rng.normal(
            0, 1e-3, (nq, na))
    elif style == "intdup":
        data = rng.integers(0, 4, (n, na)).astype(np.float64)
        queries = rng.integers(0, 4, (nq, na)).astype(np.float64)
    else:
        raise ValueError(style)
    labels = rng.integers(0, 8, n).astype(np.int32)
    ks = rng.integers(max(1, kmax // 2), kmax + 1, nq).astype(np.int32)
    return KNNInput(Params(n, nq, na), labels, data, ks, queries)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="REPAIR_SWEEP_r05.json")
    ap.add_argument("--quick", action="store_true",
                    help="smaller grid (CI smoke)")
    args = ap.parse_args()

    import jax

    from dmlp_tpu.config import EngineConfig
    from dmlp_tpu.engine.single import SingleChipEngine

    platform = jax.devices()[0].platform
    # (n, nq, na, kmax): spans narrow/wide k, small/large attr counts,
    # and (on the full grid) the multi-pass wide-k regime.
    shapes = [(12800, 128, 8, 16), (12800, 128, 64, 40),
              (12800, 128, 64, 192)]
    if not args.quick:
        shapes += [(25600, 256, 16, 40), (12800, 128, 64, 768),
                   (25600, 128, 64, 1024)]
    styles = ["uniform", "clustered", "intdup"]
    dtypes = ["float32", "bfloat16"]

    records = []
    for n, nq, na, kmax in shapes:
        for style in styles:
            inp = make_case(style, n, nq, na, kmax, seed=n + kmax)
            for dtype in dtypes:
                eng = SingleChipEngine(EngineConfig(
                    select="extract", use_pallas=True, dtype=dtype))
                t0 = time.perf_counter()
                eng.run(inp)
                dt = (time.perf_counter() - t0) * 1e3
                rec = {"n": n, "nq": nq, "na": na, "kmax": kmax,
                       "style": style, "dtype": dtype,
                       "select": eng._last_select,
                       "repairs": int(eng.last_repairs),
                       "repair_rate": round(eng.last_repairs / nq, 4),
                       "mp_passes": int(eng.last_mp_passes),
                       "ms": round(dt)}
                records.append(rec)
                print(json.dumps(rec))

    agg = {}
    for r in records:
        key = f"{r['style']}/{r['dtype']}"
        agg.setdefault(key, []).append(r["repair_rate"])
    out = {"platform": platform,
           "margin": "resolve_kcap: bf16 exact margin = 96 + k/2, "
                     "f32 >= 8 slack",
           "note": "repair_rate = hazard-flagged queries / total (all runs "
                   "are checksum-exact regardless; rate measures how often "
                   "the oracle backstop fires, i.e. the margin's slack)",
           "records": records,
           "mean_rate_by_style_dtype": {k: round(float(np.mean(v)), 4)
                                        for k, v in sorted(agg.items())}}
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
