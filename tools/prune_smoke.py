#!/usr/bin/env python
"""Pruned two-stage solve smoke (`make prune-smoke`): CI teeth for the
bound-based scan pruning (ops.summaries) through the REAL engine CLI.

Four invariants, each a hard failure:

1. **Byte identity, pruning forced on** — a norm-banded corpus (block
   bands progressively offset, queries near band 0) solved with
   ``DMLP_TPU_PRUNE=1`` must produce contract stdout byte-identical to
   the ``DMLP_TPU_PRUNE=0`` dense run AND to the float64 golden model.
2. **Non-vacuity** — on that banded corpus the pruned arm must prune
   more than half the blocks and stream < 0.5x the dense bytes (read
   from the CLI metrics summary's ``prune`` block) — a pruned path
   that never prunes is an identical-code A/B masquerading as a
   feature.
3. **Observability** — the ``--telemetry`` OpenMetrics snapshot of the
   pruned run must carry the ``scan_bytes_streamed`` counter (and the
   ``prune_*`` family), so the scanned-bytes ledger series is scraped,
   not inferred.
4. **Ladder recovery** — under a seeded ``oom`` schedule at the
   staging site the solve must step the resilience ladder past the
   pruned rung (``prune -> fused``, after the top ``lowp`` rung steps
   first — visible in the metrics resilience block) and STILL produce
   byte-identical contract stdout.

With ``--record FILE`` the banded A/B also lands as a kind="prune"
RunRecord (ledger series ``prune/configbanded/...``), the committed
``PRUNE_rNN.jsonl``'s banded row.

Usage: JAX_PLATFORMS=cpu python tools/prune_smoke.py --out outputs/prune
       [--record outputs/prune/PRUNE_SMOKE.jsonl] [--reps 2]
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def fail(msg: str) -> None:
    print(f"prune_smoke: FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def build_banded_input(path: str):
    """Serialize a seeded norm-banded corpus to the input grammar:
    8 bands of 2048 rows offset by +50 each, queries near band 0 —
    blocks 1..7 provably cannot enter any top-k."""
    import numpy as np

    from dmlp_tpu.io.grammar import KNNInput, Params, format_input

    rng = np.random.default_rng(1301)
    n, nq, na, band = 16_384, 48, 8, 2048
    data = rng.uniform(0, 5, (n, na))
    for b in range(n // band):
        data[b * band:(b + 1) * band] += 50.0 * b
    inp = KNNInput(Params(n, nq, na),
                   rng.integers(0, 6, n).astype(np.int32), data,
                   rng.integers(1, 17, nq).astype(np.int32),
                   rng.uniform(0, 5, (nq, na)))
    with open(path, "w") as f:
        f.write(format_input(inp))
    return band


def run_cli(input_path: str, env_extra: dict, flags: list,
            timeout_s: float = 300.0):
    """One engine CLI run; returns (stdout, stderr, elapsed_ms)."""
    env = dict(os.environ)
    env.update(env_extra)
    argv = [sys.executable, "-m", "dmlp_tpu", "--select", "topk",
            "--data-block", "2048", "--warmup"] + flags
    with open(input_path, "rb") as stdin:
        t0 = time.perf_counter()
        proc = subprocess.run(argv, stdin=stdin, capture_output=True,
                              env=env, timeout=timeout_s)
        wall_ms = (time.perf_counter() - t0) * 1e3
    if proc.returncode != 0:
        fail(f"engine CLI exited {proc.returncode}: "
             f"{proc.stderr.decode()[-1500:]}")
    return proc.stdout, proc.stderr.decode(), wall_ms


def last_summary(metrics_path: str) -> dict:
    with open(metrics_path) as f:
        recs = [json.loads(ln) for ln in f if ln.strip()]
    summaries = [r for r in recs if r.get("event") == "summary"]
    if not summaries:
        fail(f"{metrics_path}: no summary record")
    return summaries[-1]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="outputs/prune")
    ap.add_argument("--record", default=None, metavar="FILE",
                    help="append the banded A/B as a kind=\"prune\" "
                         "RunRecord to FILE")
    ap.add_argument("--reps", type=int, default=2)
    args = ap.parse_args(argv)
    os.makedirs(args.out, exist_ok=True)

    from dmlp_tpu.golden.fast import knn_golden_fast
    from dmlp_tpu.io.grammar import parse_input_text
    from dmlp_tpu.io.report import format_results

    input_path = os.path.join(args.out, "banded.in")
    build_banded_input(input_path)
    with open(input_path) as f:
        inp = parse_input_text(f.read())
    golden = format_results(knn_golden_fast(inp)).encode()

    # -- arms: interleaved pruned/dense reps ---------------------------------
    times = {"pruned": [], "dense": []}
    outs = {"pruned": set(), "dense": set()}
    mpaths = {a: os.path.join(args.out, f"metrics_{a}.jsonl")
              for a in times}
    tel_path = os.path.join(args.out, "telemetry_pruned.prom")
    for p in list(mpaths.values()):
        if os.path.exists(p):
            os.remove(p)
    for rep in range(max(args.reps, 1)):
        order = ("dense", "pruned") if rep % 2 == 0 \
            else ("pruned", "dense")
        for arm in order:
            flags = ["--metrics", mpaths[arm]]
            if arm == "pruned":
                flags += ["--telemetry", tel_path]
            out_b, err, _ = run_cli(
                input_path,
                {"DMLP_TPU_PRUNE": "1" if arm == "pruned" else "0"},
                flags)
            outs[arm].add(out_b)
            import re
            m = re.search(r"Time taken:\s*(\d+)", err)
            if not m:
                fail(f"{arm}-arm run has no timing line")
            times[arm].append(int(m.group(1)))

    # 1. byte identity: arms vs each other and vs the f64 golden model
    if outs["pruned"] != {golden} or outs["dense"] != {golden}:
        fail("contract stdout differs between pruned/dense/golden — "
             "pruning changed answers")
    print("prune_smoke: pruned and dense arms byte-identical to the "
          "golden oracle")

    # 2. non-vacuity: > 0.5 of blocks pruned, < 0.5x bytes streamed
    pb = last_summary(mpaths["pruned"]).get("prune") or {}
    db = last_summary(mpaths["dense"]).get("prune") or {}
    if not pb or not db:
        fail("metrics summaries carry no prune block")
    frac = pb.get("pruned_fraction", 0)
    ratio = pb["scanned_bytes"] / max(db["scanned_bytes"], 1)
    if frac <= 0.5:
        fail(f"pruned fraction {frac} <= 0.5 on the banded corpus — "
             "vacuous pruning")
    if ratio >= 0.5:
        fail(f"pruned arm streamed {ratio:.3f}x the dense bytes "
             "(must be < 0.5)")
    print(f"prune_smoke: {pb['blocks_pruned']}/{pb['blocks_total']} "
          f"blocks pruned, scanned-bytes ratio {ratio:.3f}")

    # 3. the scanned-bytes counter is visible in the OpenMetrics scrape
    with open(tel_path) as f:
        prom = f.read()
    if "scan_bytes_streamed" not in prom:
        fail("scan_bytes_streamed missing from the OpenMetrics snapshot")
    if "prune_blocks_pruned" not in prom:
        fail("prune_blocks_pruned missing from the OpenMetrics snapshot")
    print("prune_smoke: scan.bytes_streamed + prune.* visible in the "
          "OpenMetrics scrape")

    # 4. ladder recovery: seeded ooms at staging walk the top rungs
    #    (lowp -> prune -> fused), output still byte-identical
    sched_path = os.path.join(args.out, "oom_schedule.json")
    with open(sched_path, "w") as f:
        json.dump({"schema": 1, "seed": 3, "faults": [
            {"site": "single.stage_put", "kind": "oom", "times": 2}]}, f)
    oom_metrics = os.path.join(args.out, "metrics_oom.jsonl")
    if os.path.exists(oom_metrics):
        os.remove(oom_metrics)
    out_b, _, _ = run_cli(input_path, {"DMLP_TPU_PRUNE": "1"},
                          ["--metrics", oom_metrics,
                           "--faults", sched_path])
    if out_b != golden:
        fail("oom-schedule run stdout differs from golden — ladder "
             "recovery changed answers")
    res = last_summary(oom_metrics).get("resilience") or {}
    degs = res.get("degradations") or []
    if "prune->fused" not in degs:
        fail(f"oom fired but the ladder recorded {degs!r}, expected a "
             "prune->fused step")
    print(f"prune_smoke: seeded oom recovered via {degs} with "
          "byte-identical output")

    # -- optional ledger record ----------------------------------------------
    if args.record:
        from dmlp_tpu.obs.run import RunRecord, round_from_name
        RunRecord(
            kind="prune", tool="tools.prune_smoke",
            config={"config_id": "banded", "input": "banded.in",
                    "num_data": inp.params.num_data,
                    "num_queries": inp.params.num_queries,
                    "num_attrs": inp.params.num_attrs,
                    "select": "topk", "data_block": 2048},
            metrics={
                "engine_ms_pruned": round(statistics.median(
                    times["pruned"])),
                "engine_ms_pruned_reps": times["pruned"],
                "engine_ms_dense": round(statistics.median(
                    times["dense"])),
                "engine_ms_dense_reps": times["dense"],
                "scanned_bytes_pruned": pb["scanned_bytes"],
                "scanned_bytes_dense": db["scanned_bytes"],
                "scanned_bytes_ratio": round(ratio, 4),
                "prune_blocks_total": pb["blocks_total"],
                "prune_blocks_pruned": pb["blocks_pruned"],
                "prune_ab_identical": True,
            },
            device="cpu" if os.environ.get("JAX_PLATFORMS") == "cpu"
            else None,
            round=round_from_name(args.record)).append_jsonl(args.record)
        print(f"prune_smoke: banded A/B recorded to {args.record}")

    print("prune_smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
