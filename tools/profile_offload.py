"""Offload step-time decomposition (VERDICT r3 item 7).

The r3 numbers: resident 68.5% MFU vs offload 54.5% (at 4x the batch).
This tool explains the gap with three fenced measurements at the SAME
shape:

1. ``resident``  — the regular train step, params+moments in HBM
   (pure-compute reference point);
2. ``stream``    — a transfer-only jit that round-trips the full
   params+moments pytree host DRAM -> HBM -> host DRAM, exactly the
   byte traffic the offload step adds, with no compute to hide it;
3. ``offload``   — the real in-jit offload step.

With perfect latency hiding, offload ~= max(resident, stream); with none,
offload ~= resident + stream. ``overlap_efficiency`` places the measured
step on that scale, and ``mfu_ceiling_stream`` is the best MFU any
scheduler could reach given the measured stream bandwidth — if the
measured offload MFU is at that ceiling, the gap is a hardware floor,
not scheduler headroom.

Writes OFFLOAD_DECOMP_r04.json. Env: TRAIN_DIMS/TRAIN_BATCH/TRAIN_STEPS/
TRAIN_DTYPE as in train.bench, BENCH_OUT.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _fence(tree) -> None:
    import jax
    jax.block_until_ready(tree)


def main() -> int:
    import jax
    import jax.numpy as jnp

    from dmlp_tpu.train.bench import _env_int
    from dmlp_tpu.train.data import teacher_batches
    from dmlp_tpu.train.loop import build_sharded_state
    from dmlp_tpu.train.metrics import (peak_flops_per_chip,
                                        throughput_metrics)
    from dmlp_tpu.train.sharding import batch_shardings, make_train_mesh
    from dmlp_tpu.train.step import (make_optimizer, make_train_step,
                                     supports_injit_offload)

    dims = tuple(int(d) for d in os.environ.get(
        "TRAIN_DIMS", "1024,8192,8192,1024").split(","))
    batch = _env_int("TRAIN_BATCH", 32768)
    steps = _env_int("TRAIN_STEPS", 30)
    dtype = os.environ.get("TRAIN_DTYPE", "bfloat16")
    out_path = os.environ.get("BENCH_OUT", "OFFLOAD_DECOMP_r04.json")
    cdtype = jnp.bfloat16 if dtype == "bfloat16" else None

    mesh = make_train_mesh(None)
    n_chips = mesh.devices.size
    optimizer = make_optimizer("sgd", 1e-2)
    xsh, ysh = batch_shardings(mesh)
    data = teacher_batches(dims[0], dims[-1], batch, seed=1)
    batches = []
    for _ in range(4):
        x, y = next(data)
        batches.append((jax.device_put(x, xsh), jax.device_put(y, ysh)))

    def timed_steps(step_fn, state):
        for i in range(3):
            state, m = step_fn(state, *batches[i % 4])
        jax.device_get(m["loss"])
        t0 = time.perf_counter()
        for i in range(steps):
            state, m = step_fn(state, *batches[i % 4])
        jax.device_get(m["loss"])
        return (time.perf_counter() - t0) / steps, state

    # 1. resident step at the SAME batch as offload.
    state_r = build_sharded_state(mesh, dims, optimizer, offload=False)
    dt_resident, state_r = timed_steps(make_train_step(optimizer, cdtype),
                                       state_r)
    tm_resident = throughput_metrics(state_r["params"], batch, dt_resident,
                                     n_chips)
    del state_r

    # 2. stream-only round trip of params + moments.
    state_h = build_sharded_state(mesh, dims, optimizer, offload=True)
    work = {"params": state_h["params"], "opt": state_h["opt"]}
    host_sh = jax.tree.map(lambda a: a.sharding, work)
    dev_sh = jax.tree.map(
        lambda a: a.sharding.with_memory_kind("device"), work)
    bytes_one_way = sum(a.size * a.dtype.itemsize
                        for a in jax.tree.leaves(work))

    def stream(w, eps):
        dev = jax.tree.map(jax.device_put, w, dev_sh)
        # Touch every leaf so neither copy can be elided.
        return jax.tree.map(lambda a: a + eps.astype(a.dtype), dev)

    stream_fn = jax.jit(stream, out_shardings=host_sh)
    eps = jnp.float32(0.0)
    w = stream_fn(work, eps)
    _fence(w)
    t0 = time.perf_counter()
    for _ in range(steps):
        w = stream_fn(w, eps)
    _fence(w)
    dt_stream = (time.perf_counter() - t0) / steps
    del w

    # 3. the real offload step (in-jit streaming on TPU runtimes).
    from dmlp_tpu.train.step import make_offload_train_step
    step_fn = make_offload_train_step(optimizer, cdtype, state_h)
    dt_offload, state_h = timed_steps(step_fn, state_h)
    tm_offload = throughput_metrics(state_h["params"], batch, dt_offload,
                                    n_chips)

    no_overlap = dt_resident + dt_stream
    perfect = max(dt_resident, dt_stream)
    overlap_eff = ((no_overlap - dt_offload) / (no_overlap - perfect)
                   if no_overlap > perfect else None)
    doc = {
        "note": "Offload decomposition at one shape: resident = compute "
                "reference, stream = transfer-only round trip of "
                "params+moments (no compute to hide it), offload = the "
                "real step. overlap_efficiency: 1.0 = perfect latency "
                "hiding (offload == max(resident, stream)), 0.0 = fully "
                "serial. mfu_ceiling_stream = resident MFU scaled by the "
                "best possible overlap given measured stream time.",
        "shape": {"dims": list(dims), "batch": batch, "steps": steps,
                  "dtype": dtype, "n_chips": int(n_chips),
                  "device_kind": getattr(jax.devices()[0], "device_kind",
                                         "?")},
        "injit_offload": bool(supports_injit_offload()),
        "resident_step_ms": round(dt_resident * 1e3, 2),
        "stream_roundtrip_ms": round(dt_stream * 1e3, 2),
        "offload_step_ms": round(dt_offload * 1e3, 2),
        "bytes_per_step_each_way": bytes_one_way,
        "stream_gb_per_s": round(2 * bytes_one_way / dt_stream / 1e9, 2),
        "no_overlap_ms": round(no_overlap * 1e3, 2),
        "perfect_overlap_ms": round(perfect * 1e3, 2),
        "overlap_efficiency": (round(overlap_eff, 3)
                               if overlap_eff is not None else None),
        "mfu_resident": round(tm_resident["mfu"], 4),
        "mfu_offload": round(tm_offload["mfu"], 4),
        "mfu_ceiling_stream": round(
            tm_resident["mfu"] * dt_resident / perfect, 4),
        "peak_tflops_per_chip": round(peak_flops_per_chip() / 1e12, 1),
    }
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1)
    print(json.dumps(doc))
    return 0


if __name__ == "__main__":
    sys.exit(main())
