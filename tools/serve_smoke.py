#!/usr/bin/env python
"""Serve smoke: prove the online serving layer end to end on CPU.

The ``make serve-smoke`` checker (wired into ``make test``). Seven
proofs, every failure exits nonzero with the reason named:

1. **Cold start + ready contract** — the real daemon subprocess
   (``python -m dmlp_tpu.serve``) warms every bucket the replay can
   hit, writes the ready file with ``cold_start_compile_ms`` and the
   compile count, and announces the port.
2. **Replay bit-identity** — a short mixed-(nq, k) trace replayed over
   concurrent connections; every response's checksums must equal the
   float64 golden oracle's byte-for-byte (micro-batch padding/bucketing
   and per-request slicing change nothing).
3. **Compile-once** — the daemon's compile counter after the replay
   equals the ready-file value: steady-state serving never recompiles.
4. **Live scrape** — ``--telemetry-port``'s GET /metrics passes the
   OpenMetrics validator and carries the serve metric families.
5. **Admission shedding** — a fault schedule injects a memory squeeze
   (``serve.admit`` oom) mid-stream: the squeezed request is REJECTED
   (visible in the registry), the next request succeeds, and the
   degradation ladder never fires.
6. **Incremental ingestion** — rows appended over the wire; the next
   replay matches the golden oracle over the GROWN corpus with zero
   new solve compiles.
7. **Graceful drain + ledger round-trip** — SIGTERM finishes in-flight
   work, flushes the final snapshot + serve RunRecord, exits 0, leaves
   NO flight dump; the record parses in obs.ledger as ``serve/...``
   series (the ``make perf-gate`` surface).

Usage::

    python tools/serve_smoke.py --out outputs/serve \
        [--record outputs/serve/SERVE_SMOKE.jsonl]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from dmlp_tpu.io.grammar import KNNInput, Params, parse_input_text  # noqa: E402
from dmlp_tpu.obs.telemetry import validate_openmetrics   # noqa: E402
from dmlp_tpu.serve import client as sc                   # noqa: E402

CORPUS = dict(num_data=3000, num_queries=8, num_attrs=6, min_attr=0.0,
              max_attr=80.0, min_k=1, max_k=12, num_labels=5, seed=77)
HEADER = {"serve_trace_schema": 1, "corpus": CORPUS}
TRACE = [{"t_ms": i * 2, "nq": nq, "k": k, "seed": 7000 + i}
         for i, (nq, k) in enumerate(
             [(1, 1), (3, 7), (8, 8), (9, 9), (2, 12), (7, 3),
              (16, 5), (17, 2), (5, 11), (4, 8)])]
BATCH_CAP = 32


def fail(msg: str):
    print(f"serve_smoke: FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def say(msg: str) -> None:
    print(f"serve_smoke: {msg}")


def warm_spec() -> str:
    return ",".join(f"{q}x{k}" for q, k in
                    sc.warm_buckets_for_trace(TRACE, BATCH_CAP))


def scrape(port: int) -> str:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
        return r.read().decode()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="outputs/serve")
    ap.add_argument("--record", default=None)
    args = ap.parse_args(argv)
    out = os.path.abspath(args.out)
    os.makedirs(out, exist_ok=True)
    record = os.path.abspath(args.record) if args.record \
        else os.path.join(out, "SERVE_SMOKE.jsonl")
    if os.path.exists(record):
        os.remove(record)

    corpus_txt = sc.corpus_text(HEADER)
    corpus_path = os.path.join(out, "corpus.in")
    with open(corpus_path, "w") as f:
        f.write(corpus_txt)
    corpus = parse_input_text(corpus_txt)
    golden = sc.golden_reference(corpus, HEADER, TRACE)

    # Injected memory squeeze: one oom fault at the admission site,
    # AFTER the replay's requests have all been admitted.
    faults_path = os.path.join(out, "squeeze_faults.json")
    with open(faults_path, "w") as f:
        json.dump({"schema": 1, "seed": 1, "faults": [
            {"site": "serve.admit", "kind": "oom", "times": 1,
             "after": len(TRACE)}]}, f)

    ready = os.path.join(out, "ready.json")
    telem = os.path.join(out, "serve_telemetry.prom")
    for stale in (ready, telem):
        if os.path.exists(stale):
            os.remove(stale)
    # Flight dumps left by a previous CRASHED run must not fail this
    # run's orderly-drain assertion.
    sc.clear_flight_dumps(out)
    errlog = os.path.join(out, "daemon.err")
    cmd = [sys.executable, "-m", "dmlp_tpu.serve",
           "--corpus", corpus_path, "--port", "0",
           "--ready-file", ready, "--warm-buckets", warm_spec(),
           "--max-batch-queries", str(BATCH_CAP),
           "--telemetry", telem, "--telemetry-port", "0",
           "--record", record, "--faults", faults_path,
           "--tick-ms", "2"]
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=repo + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    with open(errlog, "w") as ef:
        proc = subprocess.Popen(cmd, stderr=ef,
                                stdout=subprocess.DEVNULL, env=env,
                                cwd=out)
    try:
        try:
            rdoc = sc.await_ready(proc, ready, timeout_s=300,
                                  errlog=errlog)
        except RuntimeError as e:
            fail(str(e))
        if not rdoc.get("cold_start_compile_ms"):
            fail("ready file carries no cold_start_compile_ms")
        say(f"ready: port={rdoc['port']} "
            f"cold_start={rdoc['cold_start_compile_ms']} ms, "
            f"{rdoc['compile_count']} bucket compiles")

        # 2. replay bit-identity
        res = sc.replay(rdoc["port"], HEADER, TRACE, connections=3)
        bad = [r for r in res if not r.get("ok")]
        if bad:
            fail(f"replay had {len(bad)} failed responses: {bad[0]}")
        if sc.contract_text([r["checksums"] for r in res]) != \
                sc.contract_text(golden):
            fail("replay responses differ from the golden oracle")
        say(f"replay OK: {len(TRACE)} mixed-(nq, k) requests "
            "byte-identical to the golden oracle")

        # 3. compile-once — the counter AND the schedule: a recompile
        # landing on a different program can't hide behind a
        # coincidentally flat compile_count (obs.hlo fingerprints).
        cli = sc.ServeClient(rdoc["port"])
        stats = cli.stats()["stats"]
        if stats["engine"]["compile_count"] != rdoc["compile_count"]:
            fail(f"compile counter moved {rdoc['compile_count']} -> "
                 f"{stats['engine']['compile_count']}: a request "
                 "recompiled")
        sched0 = rdoc.get("hlo_schedule", {})
        if stats["engine"].get("hlo_schedule", {}) != sched0:
            fail(f"per-bucket HLO schedule changed across the replay: "
                 f"{sched0} -> {stats['engine'].get('hlo_schedule')}")
        say(f"compile-once OK: counter pinned at "
            f"{rdoc['compile_count']} and {len(sched0)} bucket "
            "fingerprint(s) unchanged across the replay")

        # 4. live scrape
        http_port = None
        text = open(telem).read() if os.path.exists(telem) else ""
        for ln in text.splitlines():
            if ln.startswith("telemetry_http_port"):
                http_port = int(float(ln.split()[-1]))
        if http_port is None:
            # fall back: scrape port gauge via stats is not exposed;
            # the snapshot file must carry it
            fail("telemetry snapshot carries no telemetry_http_port")
        om = scrape(http_port)
        errs = validate_openmetrics(om)
        if errs:
            fail(f"OpenMetrics validation: {errs[:3]}")
        for want in ("serve_requests_completed", "serve_queue_depth",
                     "serve_request_latency_ms"):
            if want not in om:
                fail(f"scrape missing {want}")
        say("live scrape OK: OpenMetrics valid with serve metrics")

        # 5. admission shedding under the injected squeeze
        q1 = sc.materialize_queries({"nq": 2, "seed": 9901}, HEADER)
        r = cli.query(q1, k=3, req_id="squeezed")
        if r.get("ok") or "injected_squeeze" not in r.get("error", ""):
            fail(f"squeezed request was not shed: {r}")
        r = cli.query(q1, k=3, req_id="after-squeeze")
        if not r.get("ok"):
            fail(f"request after the squeeze failed: {r}")
        om = scrape(http_port)
        if 'serve_rejected_total{key="injected_squeeze"}' not in om:
            fail("rejection not visible in the registry scrape")
        for ln in om.splitlines():
            if ln.startswith("resilience_degradations_total") \
                    and float(ln.split()[-1]) > 0:
                fail("the degradation ladder fired under the squeeze")
        say("admission OK: injected squeeze shed the request "
            "(visible in the registry), no ladder degradation")

        # 6. incremental ingestion
        import numpy as np
        rng = np.random.default_rng(5)
        newl = rng.integers(0, CORPUS["num_labels"], 7).astype(int)
        newa = rng.uniform(CORPUS["min_attr"], CORPUS["max_attr"],
                          (7, CORPUS["num_attrs"]))
        r = cli.ingest([int(v) for v in newl], newa)
        if not r.get("ok") or r["corpus_rows"] != CORPUS["num_data"] + 7:
            fail(f"ingest failed: {r}")
        grown = KNNInput(
            Params(CORPUS["num_data"] + 7, 0, CORPUS["num_attrs"]),
            np.concatenate([corpus.labels, newl.astype(np.int32)]),
            np.vstack([corpus.data_attrs, newa]),
            np.zeros(0, np.int32), np.zeros((0, CORPUS["num_attrs"])))
        res2 = sc.replay(rdoc["port"], HEADER, TRACE[:4], connections=2)
        want = sc.golden_reference(grown, HEADER, TRACE[:4])
        if [r["checksums"] for r in res2] != want:
            fail("post-ingest responses differ from the golden oracle "
                 "over the grown corpus")
        stats = cli.stats()["stats"]
        if stats["engine"]["compile_count"] != rdoc["compile_count"]:
            fail("ingestion recompiled a solve program")
        if stats["engine"].get("hlo_schedule", {}) != sched0:
            fail("ingestion changed a bucket's compiled-program "
                 "fingerprint")
        say("ingestion OK: grown-corpus replay golden-identical, "
            "zero new solve compiles, schedule fingerprints unchanged")
        cli.close()

        # 7. graceful drain
        try:
            sc.sigterm_drain(proc, errlog=errlog)
        except RuntimeError as e:
            fail(str(e))
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
    flights = sc.flight_dumps(out)
    if flights:
        fail(f"orderly drain left flight dumps: {flights}")
    if not os.path.exists(telem):
        fail("no final telemetry snapshot after drain")
    errs = validate_openmetrics(open(telem).read())
    if errs:
        fail(f"final snapshot invalid: {errs[:3]}")
    say("drain OK: exit 0, final snapshot valid, no flight dump")

    from dmlp_tpu.obs.ledger import ingest_file
    entry = ingest_file(record)
    if entry["status"] != "parsed":
        fail(f"serve RunRecord did not parse in the ledger: "
             f"{entry.get('error')}")
    series = {p["series"] for p in entry["points"]}
    for want in ("serve/requests_per_sec", "serve/request_latency_p50_ms",
                 "serve/cold_start_compile_ms"):
        if want not in series:
            fail(f"ledger series missing {want} "
                 f"(got {sorted(series)})")
    say(f"ledger round-trip OK: {len(entry['points'])} serve/ series "
        "points")
    say("PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
