#!/usr/bin/env python
"""Validate obs artifacts: a Perfetto span trace + a metrics JSONL.

The `make obs-smoke` checker: loads the trace JSON the engine CLI wrote
with ``--trace`` and the metrics JSONL from ``--metrics`` and asserts the
structural contract the obs subsystem promises —

- the trace is Chrome-trace JSON with a non-empty ``traceEvents`` list;
- every complete event (``ph: "X"``) has a name, numeric ``ts``/``dur``
  and ``pid``/``tid``;
- the expected engine phase spans are present (a solve span at minimum);
- every metrics line parses as JSON and carries the monotonic ``t_ms``;
- the final metrics record is a summary whose ``counters`` block carries
  either cost-analysis flops/bytes or the explicit
  ``counters_unavailable`` marker — never silence.

``--dist MERGED.json [--ranks N] [--json]`` instead validates a merged
multi-rank cluster trace (tools/merge_traces.py output): the expected
number of distinct rank pids, per-rank process metadata events and
clock-sync markers, per-rank spans including the contract
``dist.solve`` span, monotonic (sorted, non-negative) per-rank
timestamps after alignment, and — when the merge embedded a
``comms_reconcile`` block — agreement between each rank's traced
all-gather payload bytes and the analytic model (obs.comms): any rank
whose two numbers disagree FAILS the check (per-rank flagging of the
analytic-vs-traced reconciliation) — the `make obs-dist-smoke`
checker. The merge's per-rank ``straggler`` skew table (span-duration
skew vs the across-rank median, flagged ranks beyond the threshold) is
validated structurally and printed; ``--json`` emits the whole verdict
— ranks, spans per rank, the skew table, flagged stragglers — as one
machine-readable JSON document on stdout. Straggler flags REPORT, they
do not fail: emulated/CI ranks legitimately skew (sequential launch),
and the gate for real clusters is a policy call made downstream
(``--fail-on-straggler`` opts in).

``--fleet MERGED.json [--json] [--min-reconciled F]`` validates a
merged FLEET trace (tools/merge_traces.py --fleet output) — the
request-scoped causal contract:

- per-process structure: process_name metadata, a ``fleet.clock_sync``
  marker per process, non-negative monotonic aligned timestamps;
- rid uniqueness: at most one ``client.request`` and one
  ``fleet.route`` span per rid (a reused rid would stitch two requests
  into one causal tree);
- parentage: every ``fleet.hop`` and every rid-tagged
  ``serve.phase.*`` span hangs under a ``fleet.route`` root for its
  rid (no orphan subtrees), and no rid mixes query hops with ingest
  fan-out hops (no cross-op rid reuse);
- retry accounting: the number of attempt-numbered hop spans equals
  the route span's ``hops`` arg, and attempt >= 2 hops appear ONLY on
  requests whose route records >= 2 hops (retry spans on a
  non-retried request would be fabricated causality);
- per-rid phase order: each rid's replica phases start in the
  canonical queue -> coalesce -> solve -> finalize -> write order
  (``serve.phase.admission`` is exempt: it runs on the handler thread
  concurrent with the queue wait);
- ``slo.alert`` lifecycle (obs.slo transitions): every alert instant
  carries an objective id, a state, and its window; per (process,
  objective) the time-ordered transitions chain legally — each event's
  ``prev`` equals the previous event's ``state`` (starting from
  ``ok``) and every edge is one the hysteresis state machine can take
  (ok→pending, pending→firing, pending→ok, firing→ok). A tampered or
  out-of-order stream (a firing with no pending before it, a
  firing→pending shortcut, a mismatched prev) fails the check;
- when the merge embedded client-side reconcile verdicts: the
  reconciled fraction must reach ``--min-reconciled`` (default 0.9),
  and the merge's aggregate ``reconcile_residual_ms`` is printed with
  its budget verdict (informational — the budget marker never gates).

Exit 0 on success, 1 with a message naming the first violated invariant.

Usage: python tools/check_trace.py TRACE.json METRICS.jsonl
       python tools/check_trace.py --dist MERGED.json [--ranks N]
           [--json] [--fail-on-straggler]
       python tools/check_trace.py --fleet MERGED.json [--json]
           [--min-reconciled F]
"""

from __future__ import annotations

import json
import sys


def fail(msg: str) -> "None":
    print(f"check_trace: FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def check_trace(path: str) -> None:
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        fail(f"trace {path} unreadable: {e}")
    events = doc.get("traceEvents")
    if not isinstance(events, list) or not events:
        fail(f"trace {path}: traceEvents missing or empty")
    spans = [e for e in events if e.get("ph") == "X"]
    if not spans:
        fail(f"trace {path}: no complete ('X') span events")
    for e in spans:
        for key in ("name", "ts", "dur", "pid", "tid"):
            if key not in e:
                fail(f"trace {path}: span {e} missing {key!r}")
        if not isinstance(e["ts"], (int, float)) \
                or not isinstance(e["dur"], (int, float)) or e["dur"] < 0:
            fail(f"trace {path}: span {e['name']} has bad ts/dur")
    names = {e["name"] for e in spans}
    if not any(n.startswith("cli.solve") for n in names):
        fail(f"trace {path}: no cli.solve span (got {sorted(names)})")
    if not any(n.startswith(("single.", "sharded.")) for n in names):
        fail(f"trace {path}: no engine phase spans (got {sorted(names)})")
    print(f"check_trace: trace ok — {len(spans)} spans, "
          f"{len(names)} distinct names")


def check_metrics(path: str) -> None:
    try:
        with open(path) as f:
            lines = [ln for ln in f.read().splitlines() if ln.strip()]
    except OSError as e:
        fail(f"metrics {path} unreadable: {e}")
    if not lines:
        fail(f"metrics {path}: empty")
    records = []
    for i, ln in enumerate(lines):
        try:
            records.append(json.loads(ln))
        except json.JSONDecodeError as e:
            fail(f"metrics {path}: line {i + 1} is not JSON: {e}")
    for i, r in enumerate(records):
        if "t_ms" not in r:
            fail(f"metrics {path}: record {i + 1} missing monotonic t_ms")
    final = records[-1]
    if final.get("event") != "summary":
        fail(f"metrics {path}: final record is not the run summary "
             f"(got event={final.get('event')!r})")
    counters = final.get("counters")
    if not isinstance(counters, dict):
        fail(f"metrics {path}: summary has no counters block")
    if not counters.get("counters_unavailable") \
            and not ("flops" in counters and "bytes_accessed" in counters):
        fail(f"metrics {path}: counters block carries neither "
             f"flops/bytes_accessed nor the counters_unavailable marker: "
             f"{counters}")
    print(f"check_trace: metrics ok — {len(records)} records, counters "
          + ("unavailable (explicit)" if counters.get("counters_unavailable")
             else f"flops={counters['flops']:.4g} "
                  f"bytes={counters['bytes_accessed']:.4g}"))


def check_dist_trace(path: str, expect_ranks: int = None,
                     emit_json: bool = False,
                     fail_on_straggler: bool = False) -> None:
    """Structural contract of a merged multi-rank trace
    (tools/merge_traces.py output)."""
    # With --json, stdout carries ONLY the JSON document; the human
    # narration moves to stderr so consumers can json.loads(stdout).
    def say(msg: str) -> None:
        print(msg, file=sys.stderr if emit_json else sys.stdout)

    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        fail(f"merged trace {path} unreadable: {e}")
    events = doc.get("traceEvents")
    if not isinstance(events, list) or not events:
        fail(f"merged trace {path}: traceEvents missing or empty")

    spans_by_pid, meta_by_pid, sync_by_pid, ts_by_pid = {}, {}, {}, {}
    for e in events:
        pid = e.get("pid")
        if pid is None:
            fail(f"merged trace {path}: event {e} has no pid")
        ph = e.get("ph")
        if ph == "M":
            meta_by_pid.setdefault(pid, set()).add(e.get("name"))
            continue
        if "ts" in e:
            if not isinstance(e["ts"], (int, float)) or e["ts"] < 0:
                fail(f"merged trace {path}: pid {pid} event "
                     f"{e.get('name')} has bad ts {e.get('ts')!r} "
                     "(negative or non-numeric after alignment)")
            ts_by_pid.setdefault(pid, []).append(e["ts"])
        if ph == "X":
            spans_by_pid.setdefault(pid, []).append(e)
        elif ph == "i" and e.get("name") == "dist.clock_sync":
            sync_by_pid.setdefault(pid, 0)
            sync_by_pid[pid] += 1

    pids = sorted(spans_by_pid)
    n = expect_ranks if expect_ranks is not None \
        else doc.get("dist", {}).get("num_ranks", len(pids))
    # A merge that tolerated absent/truncated rank files embeds the
    # explicit rank_trace_missing marker (merge_traces); those ranks
    # are legitimately span-free — the marker, not silence, accounts
    # for them. Markers report, they never fail.
    marker = doc.get("dist", {}).get("rank_trace_missing") or {}
    missing = sorted(int(r) for r in marker.get("ranks", []))
    if missing:
        say(f"check_trace: note — rank trace(s) missing: {missing} "
            f"({marker.get('reasons', {})})")
    want_pids = [r for r in range(n) if r not in missing]
    if pids != want_pids:
        fail(f"merged trace {path}: expected rank pids {want_pids} "
             f"with spans (of {n} ranks, missing-marked {missing}), "
             f"got {pids}")
    for pid in pids:
        if "process_name" not in meta_by_pid.get(pid, set()):
            fail(f"merged trace {path}: rank {pid} has no process_name "
                 "metadata event")
        if pid not in sync_by_pid:
            fail(f"merged trace {path}: rank {pid} has no dist.clock_sync "
                 "marker (clock alignment unverifiable)")
        ts = ts_by_pid.get(pid, [])
        if any(b < a for a, b in zip(ts, ts[1:])):
            fail(f"merged trace {path}: rank {pid} timestamps are not "
                 "monotonic in the merged event order")
        names = {e["name"] for e in spans_by_pid[pid]}
        if "dist.solve" not in names:
            fail(f"merged trace {path}: rank {pid} has no dist.solve span "
                 f"(got {sorted(names)})")
    reconcile = doc.get("dist", {}).get("comms_reconcile")
    if reconcile:
        for rank, e in sorted(reconcile.items()):
            if e.get("match") is False:
                fail(f"merged trace {path}: rank {rank} analytic vs "
                     f"traced all-gather bytes disagree "
                     f"(traced {e.get('traced_bytes')} != analytic "
                     f"{e.get('analytic_bytes')}) — the comms model "
                     "(obs.comms) and the real payload have diverged")
            if "analytic_unavailable" in e:
                say(f"check_trace: note — rank {rank} comms "
                      f"reconciliation unavailable: "
                      f"{e['analytic_unavailable']}")
        ok_ranks = [r for r, e in reconcile.items() if e.get("match")]
        if ok_ranks:
            say(f"check_trace: comms reconcile ok — analytic == traced "
                  f"all-gather bytes for rank(s) {sorted(ok_ranks)}")
    counts = {pid: len(spans_by_pid[pid]) for pid in pids}

    # -- straggler/skew table (merge_traces.straggler_analysis) ---------
    straggler = doc.get("dist", {}).get("straggler")
    if isinstance(straggler, dict):
        if "straggler_unavailable" in straggler:
            say(f"check_trace: note — straggler analysis unavailable: "
                  f"{straggler['straggler_unavailable']}")
        else:
            per_rank = straggler.get("per_rank")
            if not isinstance(per_rank, dict) or \
                    sorted(int(r) for r in per_rank) != pids:
                fail(f"merged trace {path}: straggler block's rank set "
                     f"{sorted(per_rank or {})} does not match the "
                     f"trace's ranks {pids}")
            for rank, row in sorted(per_rank.items()):
                for key in ("span_busy_ms", "solve_ms",
                            "skew_vs_median"):
                    if key not in row:
                        fail(f"merged trace {path}: straggler row for "
                             f"rank {rank} missing {key!r}")
            flagged = straggler.get("flagged_ranks", [])
            table = {r: {"solve_ms": row["solve_ms"],
                         "skew": row["skew_vs_median"]}
                     for r, row in sorted(per_rank.items())}
            say(f"check_trace: straggler skew table (threshold "
                  f"{straggler.get('threshold')}x median "
                  f"{straggler.get('median_solve_ms')} ms): {table}")
            if flagged:
                msg = (f"rank(s) {flagged} beyond the straggler "
                       f"threshold")
                if fail_on_straggler:
                    fail(f"merged trace {path}: {msg}")
                say(f"check_trace: WARNING — {msg}")

    if emit_json:
        print(json.dumps({
            "trace": path, "ranks": n,
            "spans_per_rank": {str(p): counts[p] for p in pids},
            "clock": doc.get("clock"),
            "straggler": straggler,
            "comms_reconcile": doc.get("dist", {}).get("comms_reconcile"),
            "rank_trace_missing": marker or None,
        }, sort_keys=True))
    say(f"check_trace: merged trace ok — {n} ranks, spans per rank "
          f"{counts}")


_PHASE_ORDER = ("queue", "coalesce", "solve", "finalize", "write")


def check_fleet_trace(path: str, emit_json: bool = False,
                      min_reconciled: float = 0.9) -> None:
    """Request-scoped causal contract of a merged fleet trace
    (tools/merge_traces.py --fleet output); see module docstring."""
    def say(msg: str) -> None:
        print(msg, file=sys.stderr if emit_json else sys.stdout)

    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        fail(f"merged fleet trace {path} unreadable: {e}")
    events = doc.get("traceEvents")
    if not isinstance(events, list) or not events:
        fail(f"merged fleet trace {path}: traceEvents missing or empty")
    fleet = doc.get("fleet")
    if not isinstance(fleet, dict):
        fail(f"merged fleet trace {path}: no fleet block — was it "
             "merged with merge_traces --fleet?")

    # -- per-process structure ------------------------------------------
    procs = fleet.get("processes", {})
    meta_by_pid, sync_by_pid, ts_by_pid = {}, set(), {}
    client_spans, routes, hop_spans, phase_spans = {}, {}, {}, {}
    slo_alerts = {}                # (pid, objective) -> [instant...]
    for e in events:
        pid = e.get("pid")
        if pid is None:
            fail(f"{path}: event {e} has no pid")
        ph = e.get("ph")
        if ph == "M":
            meta_by_pid.setdefault(pid, set()).add(e.get("name"))
            continue
        if "ts" in e:
            if not isinstance(e["ts"], (int, float)) or e["ts"] < 0:
                fail(f"{path}: pid {pid} event {e.get('name')} has bad "
                     f"ts {e.get('ts')!r} (negative or non-numeric "
                     "after alignment)")
            ts_by_pid.setdefault(pid, []).append(e["ts"])
        if ph == "i" and e.get("name") == "fleet.clock_sync":
            sync_by_pid.add(pid)
        if ph == "i" and e.get("name") == "slo.alert":
            a = e.get("args", {})
            for req in ("objective", "state", "window"):
                if not a.get(req):
                    fail(f"{path}: pid {pid} slo.alert at ts "
                         f"{e.get('ts')} lacks {req!r} — alert "
                         "unattributable")
            slo_alerts.setdefault((pid, a["objective"]),
                                  []).append(e)
        if ph != "X":
            continue
        a = e.get("args", {})
        rid = a.get("rid")
        name = e.get("name", "")
        if name == "client.request" and rid:
            client_spans.setdefault(rid, []).append(e)
        elif name == "fleet.route" and rid:
            routes.setdefault(rid, []).append(e)
        elif name == "fleet.hop" and rid:
            hop_spans.setdefault(rid, []).append(e)
        elif name.startswith("serve.phase.") and rid:
            phase_spans.setdefault(rid, {}).setdefault(
                name[len("serve.phase."):], []).append(e)
    for pname, info in sorted(procs.items()):
        pid = info.get("pid")
        if "process_name" not in meta_by_pid.get(pid, set()):
            fail(f"{path}: process {pname} (pid {pid}) has no "
                 "process_name metadata event")
        if pid not in sync_by_pid:
            fail(f"{path}: process {pname} (pid {pid}) has no "
                 "fleet.clock_sync marker (alignment unverifiable)")
        ts = ts_by_pid.get(pid, [])
        if any(b < a for a, b in zip(ts, ts[1:])):
            fail(f"{path}: process {pname} timestamps are not "
                 "monotonic in the merged event order")

    # -- rid uniqueness + parentage -------------------------------------
    for rid, evs in sorted(client_spans.items()):
        if len(evs) > 1:
            fail(f"{path}: rid {rid!r} has {len(evs)} client.request "
                 "spans — rids must be unique per request")
    for rid, evs in sorted(routes.items()):
        if len(evs) > 1:
            fail(f"{path}: rid {rid!r} has {len(evs)} fleet.route "
                 "spans — rids must be unique per request")
    orphans = sorted(set(hop_spans) - set(routes))
    if orphans:
        fail(f"{path}: fleet.hop span(s) for rid(s) {orphans[:5]} have "
             "no fleet.route root — orphan causal subtree")
    orphans = sorted(set(phase_spans) - set(routes))
    if orphans:
        fail(f"{path}: serve.phase.* span(s) for rid(s) {orphans[:5]} "
             "have no fleet.route root — orphan causal subtree")

    # -- retry accounting -----------------------------------------------
    retried = []
    for rid, evs in sorted(routes.items()):
        route_args = evs[0].get("args", {})
        hops = hop_spans.get(rid, [])
        attempts = [h for h in hops
                    if "attempt" in h.get("args", {})]
        fanouts = [h for h in hops if h.get("args", {}).get("fanout")]
        if attempts and fanouts:
            fail(f"{path}: rid {rid!r} mixes query retry hops and "
                 "ingest fan-out hops — rid reused across ops")
        declared = route_args.get("hops")
        if declared is not None:
            if len(attempts) != int(declared):
                fail(f"{path}: rid {rid!r} route declares hops="
                     f"{declared} but carries {len(attempts)} "
                     "attempt-numbered fleet.hop span(s)")
            if int(declared) >= 2:
                retried.append(rid)
        if any(int(h["args"]["attempt"]) >= 2 for h in attempts) \
                and (declared is None or int(declared) < 2):
            fail(f"{path}: rid {rid!r} carries an attempt>=2 retry hop "
                 f"but its route records hops={declared!r} — retry "
                 "span on a non-retried request")

    # -- per-rid phase order --------------------------------------------
    for rid, phases in sorted(phase_spans.items()):
        starts = [(p, min(e["ts"] for e in phases[p]))
                  for p in _PHASE_ORDER if p in phases]
        for (pa, ta), (pb, tb) in zip(starts, starts[1:]):
            if tb < ta:
                fail(f"{path}: rid {rid!r} phase {pb!r} starts before "
                     f"{pa!r} ({tb} < {ta} us) — canonical phase order "
                     "violated")

    # -- slo.alert lifecycle --------------------------------------------
    # Legal hysteresis edges (obs.slo.SLOEvaluator.next_state): no
    # ok->firing jump, no firing->pending shortcut. Each objective's
    # stream must chain prev==last-state from "ok" in time order —
    # anything else is a tampered or reordered alert stream.
    legal = {("ok", "pending"), ("pending", "firing"),
             ("pending", "ok"), ("firing", "ok")}
    for (pid, objective), evs in sorted(slo_alerts.items()):
        evs.sort(key=lambda e: e.get("ts", 0))
        last = "ok"
        for e in evs:
            a = e.get("args", {})
            prev, state = a.get("prev"), a.get("state")
            if prev != last:
                fail(f"{path}: pid {pid} objective {objective!r} "
                     f"slo.alert at ts {e.get('ts')} claims prev="
                     f"{prev!r} but the stream's state was {last!r} "
                     "— out-of-order or tampered alert stream")
            if (prev, state) not in legal:
                fail(f"{path}: pid {pid} objective {objective!r} "
                     f"illegal slo transition {prev!r} -> {state!r} "
                     "(hysteresis lifecycle violated)")
            last = state

    # -- reconcile verdicts ---------------------------------------------
    reconcile = fleet.get("reconcile", {})
    frac = reconcile.get("fraction")
    if "reconcile_unavailable" in reconcile:
        say(f"check_trace: note — phase-sum reconcile unavailable: "
            f"{reconcile['reconcile_unavailable']}")
    elif frac is not None and frac < min_reconciled:
        fail(f"{path}: phase-sum reconcile fraction {frac} < "
             f"{min_reconciled} ({reconcile.get('n_reconciled')}/"
             f"{reconcile.get('n_requests')} requests within "
             f"tolerance; tol_abs={reconcile.get('tol_abs_ms')}ms "
             f"tol_rel={reconcile.get('tol_rel')})")
    # The aggregate residual is informational only — the budget marker
    # is NON-GATING by design (merge_traces.RESIDUAL_BUDGET_MS), so
    # print, never fail.
    if "reconcile_residual_ms" in reconcile:
        note = (f"check_trace: phase-sum residual median "
                f"{reconcile['reconcile_residual_ms']} ms "
                f"(budget {reconcile.get('residual_budget_ms')} ms)")
        if reconcile.get("residual_budget_exceeded"):
            note += " — BUDGET EXCEEDED (non-gating)"
        say(note)

    if emit_json:
        print(json.dumps({
            "trace": path,
            "processes": procs,
            "rids": len(routes),
            "client_spans": len(client_spans),
            "retried_rids": retried,
            "phased_rids": len(phase_spans),
            "slo_alerts": {f"{pid}:{obj}": len(evs)
                           for (pid, obj), evs
                           in sorted(slo_alerts.items())},
            "reconcile": reconcile or None,
            "clock": doc.get("clock"),
        }, sort_keys=True))
    say(f"check_trace: merged fleet trace ok — "
        f"{len(procs)} processes, {len(routes)} routed rid(s), "
        f"{len(retried)} retried, "
        f"{sum(len(v) for v in slo_alerts.values())} slo.alert(s) "
        f"across {len(slo_alerts)} objective stream(s), reconcile "
        f"{reconcile.get('n_reconciled')}/{reconcile.get('n_requests')}"
        f" (fraction {frac})")


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "--fleet":
        rest = argv[1:]
        emit_json = "--json" in rest
        if emit_json:
            rest.remove("--json")
        min_rec = 0.9
        if "--min-reconciled" in rest:
            i = rest.index("--min-reconciled")
            try:
                min_rec = float(rest[i + 1])
            except (IndexError, ValueError):
                print("check_trace: --min-reconciled expects a float",
                      file=sys.stderr)
                print(__doc__, file=sys.stderr)
                return 2
            del rest[i:i + 2]
        if len(rest) != 1:
            print(__doc__, file=sys.stderr)
            return 2
        check_fleet_trace(rest[0], emit_json=emit_json,
                          min_reconciled=min_rec)
        print("check_trace: all fleet-trace invariants hold",
              file=sys.stderr if emit_json else sys.stdout)
        return 0
    if argv and argv[0] == "--dist":
        rest = argv[1:]
        expect = None
        emit_json = "--json" in rest
        if emit_json:
            rest.remove("--json")
        fail_straggler = "--fail-on-straggler" in rest
        if fail_straggler:
            rest.remove("--fail-on-straggler")
        if "--ranks" in rest:
            i = rest.index("--ranks")
            try:
                expect = int(rest[i + 1])
            except (IndexError, ValueError):
                print("check_trace: --ranks expects an integer",
                      file=sys.stderr)
                print(__doc__, file=sys.stderr)
                return 2
            del rest[i:i + 2]
        if len(rest) != 1:
            print(__doc__, file=sys.stderr)
            return 2
        check_dist_trace(rest[0], expect_ranks=expect,
                         emit_json=emit_json,
                         fail_on_straggler=fail_straggler)
        print("check_trace: all merged-trace invariants hold",
              file=sys.stderr if emit_json else sys.stdout)
        return 0
    if len(argv) != 2:
        print(__doc__, file=sys.stderr)
        return 2
    check_trace(argv[0])
    check_metrics(argv[1])
    print("check_trace: all artifact invariants hold")
    return 0


if __name__ == "__main__":
    sys.exit(main())
