#!/usr/bin/env python
"""Validate obs artifacts: a Perfetto span trace + a metrics JSONL.

The `make obs-smoke` checker: loads the trace JSON the engine CLI wrote
with ``--trace`` and the metrics JSONL from ``--metrics`` and asserts the
structural contract the obs subsystem promises —

- the trace is Chrome-trace JSON with a non-empty ``traceEvents`` list;
- every complete event (``ph: "X"``) has a name, numeric ``ts``/``dur``
  and ``pid``/``tid``;
- the expected engine phase spans are present (a solve span at minimum);
- every metrics line parses as JSON and carries the monotonic ``t_ms``;
- the final metrics record is a summary whose ``counters`` block carries
  either cost-analysis flops/bytes or the explicit
  ``counters_unavailable`` marker — never silence.

Exit 0 on success, 1 with a message naming the first violated invariant.

Usage: python tools/check_trace.py TRACE.json METRICS.jsonl
"""

from __future__ import annotations

import json
import sys


def fail(msg: str) -> "None":
    print(f"check_trace: FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def check_trace(path: str) -> None:
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        fail(f"trace {path} unreadable: {e}")
    events = doc.get("traceEvents")
    if not isinstance(events, list) or not events:
        fail(f"trace {path}: traceEvents missing or empty")
    spans = [e for e in events if e.get("ph") == "X"]
    if not spans:
        fail(f"trace {path}: no complete ('X') span events")
    for e in spans:
        for key in ("name", "ts", "dur", "pid", "tid"):
            if key not in e:
                fail(f"trace {path}: span {e} missing {key!r}")
        if not isinstance(e["ts"], (int, float)) \
                or not isinstance(e["dur"], (int, float)) or e["dur"] < 0:
            fail(f"trace {path}: span {e['name']} has bad ts/dur")
    names = {e["name"] for e in spans}
    if not any(n.startswith("cli.solve") for n in names):
        fail(f"trace {path}: no cli.solve span (got {sorted(names)})")
    if not any(n.startswith(("single.", "sharded.")) for n in names):
        fail(f"trace {path}: no engine phase spans (got {sorted(names)})")
    print(f"check_trace: trace ok — {len(spans)} spans, "
          f"{len(names)} distinct names")


def check_metrics(path: str) -> None:
    try:
        with open(path) as f:
            lines = [ln for ln in f.read().splitlines() if ln.strip()]
    except OSError as e:
        fail(f"metrics {path} unreadable: {e}")
    if not lines:
        fail(f"metrics {path}: empty")
    records = []
    for i, ln in enumerate(lines):
        try:
            records.append(json.loads(ln))
        except json.JSONDecodeError as e:
            fail(f"metrics {path}: line {i + 1} is not JSON: {e}")
    for i, r in enumerate(records):
        if "t_ms" not in r:
            fail(f"metrics {path}: record {i + 1} missing monotonic t_ms")
    final = records[-1]
    if final.get("event") != "summary":
        fail(f"metrics {path}: final record is not the run summary "
             f"(got event={final.get('event')!r})")
    counters = final.get("counters")
    if not isinstance(counters, dict):
        fail(f"metrics {path}: summary has no counters block")
    if not counters.get("counters_unavailable") \
            and not ("flops" in counters and "bytes_accessed" in counters):
        fail(f"metrics {path}: counters block carries neither "
             f"flops/bytes_accessed nor the counters_unavailable marker: "
             f"{counters}")
    print(f"check_trace: metrics ok — {len(records)} records, counters "
          + ("unavailable (explicit)" if counters.get("counters_unavailable")
             else f"flops={counters['flops']:.4g} "
                  f"bytes={counters['bytes_accessed']:.4g}"))


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if len(argv) != 2:
        print(__doc__, file=sys.stderr)
        return 2
    check_trace(argv[0])
    check_metrics(argv[1])
    print("check_trace: all artifact invariants hold")
    return 0


if __name__ == "__main__":
    sys.exit(main())
