"""Interleaved A/B mode comparison: single vs sharded vs ring (one chip).

VERDICT r3 item 2: the r3 BENCH_MODES table measured each mode in its own
block, so link weather (the tunneled host link swings 2-4x) could masquerade
as a mode difference — ring looked 2.9x slower than sharded on a 1-device
mesh where both lower to near-identical programs. This tool measures the
modes INTERLEAVED (A/B/C/A/B/C..., rotating the starting mode each rep) and
reports per-mode median + spread, so slow-link intervals hit every mode
equally.

Writes one schema-1 RunRecord (obs.run) to BENCH_MODES_r{N}.json — the
versioned envelope every migrated emitter shares; the interleaved-rep
methodology and per-mode payload live in ``config``/``metrics``. Env:
BENCH_REPS (default 5), BENCH_NUM_DATA / BENCH_NUM_QUERIES /
BENCH_NUM_ATTRS / BENCH_K as in bench.py, BENCH_OUT.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import _env_int, make_workload  # noqa: E402


def main() -> int:
    import jax

    from dmlp_tpu.cli import make_engine
    from dmlp_tpu.config import EngineConfig
    from dmlp_tpu.ops.pallas_distance import native_pallas_backend

    num_data = _env_int("BENCH_NUM_DATA", 200_000)
    num_queries = _env_int("BENCH_NUM_QUERIES", 10_000)
    num_attrs = _env_int("BENCH_NUM_ATTRS", 64)
    k = _env_int("BENCH_K", 32)
    reps = _env_int("BENCH_REPS", 5)
    # r06+: RunRecord schema (the r04 artifact keeps its grandfathered
    # ad-hoc shape; this tool stopped emitting it)
    out_path = os.environ.get("BENCH_OUT", "BENCH_MODES_r06.json")

    inp = make_workload(num_data, num_queries, num_attrs, k)
    use_pallas = native_pallas_backend()
    modes = ["single", "sharded", "ring"]
    engines = {}
    for m in modes:
        cfg = EngineConfig(mode=m, exact=False, query_block=16384,
                           use_pallas=use_pallas)
        engines[m] = make_engine(cfg)

    # Warmup (compile) every mode before ANY timed rep, so compilation
    # never lands inside a measurement.
    compile_ms = {}
    for m in modes:
        t0 = time.perf_counter()
        engines[m].run(inp)
        compile_ms[m] = round((time.perf_counter() - t0) * 1e3, 1)

    times: dict = {m: [] for m in modes}
    for rep in range(reps):
        order = modes[rep % len(modes):] + modes[:rep % len(modes)]
        for m in order:
            t0 = time.perf_counter()
            engines[m].run(inp)
            times[m].append(round((time.perf_counter() - t0) * 1e3, 1))

    runs = []
    for m in modes:
        ts = np.asarray(times[m])
        runs.append({
            "mode": m,
            "median_ms": float(np.median(ts)),
            "min_ms": float(ts.min()),
            "max_ms": float(ts.max()),
            "times_ms": times[m],
            "select": getattr(engines[m], "_last_select", None),
            "phases_ms": {kk: round(v, 1) for kk, v in
                          getattr(engines[m], "last_phase_ms", {}).items()},
            "compile_plus_first_run_ms": compile_ms[m],
        })
    from dmlp_tpu.obs.run import RunRecord
    RunRecord(
        kind="bench_modes", tool="tools/bench_modes_ab",
        config={
            "shape": {"num_data": num_data, "num_queries": num_queries,
                      "num_attrs": num_attrs, "k": k},
            "platform": jax.devices()[0].platform,
            "device": str(jax.devices()[0]),
            "n_devices": len(jax.devices()),
            "interleaved_reps": reps,
            "use_pallas": use_pallas,
        },
        metrics={
            "note": "Interleaved A/B/C reps (rotating start), per-mode "
                    "median + spread — link weather hits every mode "
                    "equally (VERDICT r3 item 2). 1-device mesh for "
                    "sharded/ring unless more chips exist; end-to-end "
                    "engine.run() wall time (fast mode), tunneled host "
                    "link.",
            "runs": runs,
        },
    ).write(out_path)
    print(json.dumps({m: {"median_ms": r["median_ms"],
                          "spread": [r["min_ms"], r["max_ms"]]}
                      for m, r in zip(modes, runs)}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
