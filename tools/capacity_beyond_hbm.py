"""Beyond-HBM capacity proof: solve a dataset LARGER than device memory.

SCALE_r04's 4M rung proved the dense distance tile never materializes
(O(N*A + Q*K) working set). This tool proves the stronger streaming
claim — the long-context analog (survey §5.7) — by running the chunked
extract driver on a dataset whose f32 form EXCEEDS the chip's HBM: only
the in-flight chunks (bounded by engine.single.ChunkThrottle), the
queries, and the running (Q, K) lists are ever device-resident, so the
solve completes where any monolithic staging would OOM by construction.

Since ISSUE 13 the tool runs TWO arms and records ``scanned_bytes``
both ways: the dense arm (``DMLP_TPU_PRUNE=0``) streams every chunk;
the pruned arm lets the two-stage solve (ops.summaries) prove blocks
out of every top-k from resident summaries BEFORE their bytes move —
on a beyond-HBM corpus that is the difference between O(corpus) and
O(survivors) host->device traffic per solve. Both arms byte-identical
to each other and validated against the f64 oracle.

Shape (default): 72M x 64 f32 = 18.4 GB, ~1.09x HBM. Queries kept small
(2048) so the run is staging-bound, like a real larger-than-memory scan.
Data is generated directly as arrays (the text grammar at 64M rows is a
multi-GB file serving no purpose here); distribution matches the seeded
generator (uniform [0, 100], labels uniform 0..9).

``--cpu-smoke`` runs a small NORM-BANDED shape instead (blocks of
progressively offset coordinate bands, queries near band 0) so the
pruned-vs-dense scanned-bytes ratio is provable in CI on this CPU
container: the smoke FAILS unless the pruned arm scans < 0.5x the
dense bytes and both arms match the oracle checksum-for-checksum. The
full shape keeps the honest ``needs the native TPU backend`` bail-out.

Correctness: exact mode (f64 rescore + eps-hazard repair) end-to-end;
VALIDATE_QUERIES queries are solved by the vectorized f64 oracle and
diffed checksum-for-checksum, per arm.

Writes a schema RunRecord (obs.run) to CAPACITY_BEYOND_HBM_r13.json —
ledger-ingestible (python -m dmlp_tpu.report); the r04 ad-hoc shape is
grandfathered. Env: CAP_NUM_DATA, CAP_NUM_QUERIES, CAP_VALIDATE
(default 8), BENCH_OUT.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _solve_arm(inp, cfg, prune: bool):
    """One arm: (results, engine, solve_s) under DMLP_TPU_PRUNE=1/0."""
    from dmlp_tpu.engine.single import SingleChipEngine
    prev = os.environ.get("DMLP_TPU_PRUNE")
    os.environ["DMLP_TPU_PRUNE"] = "1" if prune else "0"
    try:
        eng = SingleChipEngine(cfg)
        t0 = time.perf_counter()
        results = eng.run(inp)
        return results, eng, time.perf_counter() - t0
    finally:
        if prev is None:
            os.environ.pop("DMLP_TPU_PRUNE", None)
        else:
            os.environ["DMLP_TPU_PRUNE"] = prev


def main(argv=None) -> int:
    import numpy as np

    import jax

    from dmlp_tpu.config import EngineConfig
    from dmlp_tpu.golden.fast import knn_golden_fast
    from dmlp_tpu.io.grammar import KNNInput, Params, subset_queries
    from dmlp_tpu.ops.pallas_distance import native_pallas_backend

    argv = list(sys.argv[1:] if argv is None else argv)
    cpu_smoke = "--cpu-smoke" in argv

    if not cpu_smoke and not native_pallas_backend():
        print("needs the native TPU backend", file=sys.stderr)
        return 1

    rng = np.random.default_rng(42)
    if cpu_smoke:
        # Small norm-banded shape: 8 blocks of 8192 rows, each band
        # offset by +60 per block so later blocks provably cannot hold
        # any near-band-0 query's top-k — the pruned arm must skip them.
        n = int(os.environ.get("CAP_NUM_DATA", 65_536))
        nq = int(os.environ.get("CAP_NUM_QUERIES", 256))
        nv = int(os.environ.get("CAP_VALIDATE", 8))
        na, k = 16, 32
        block = 8192
        out_path = os.environ.get("BENCH_OUT",
                                  "outputs/CAPACITY_PRUNE_SMOKE.json")
        data = rng.random((n, na), dtype=np.float32) * np.float32(10)
        for b in range(-(-n // block)):
            data[b * block:(b + 1) * block] += np.float32(60.0 * b)
        queries = rng.uniform(0, 10, (nq, na)).astype(np.float32)
        cfg = EngineConfig(dtype="float32", select="topk",
                           data_block=block)
    else:
        n = int(os.environ.get("CAP_NUM_DATA", 72_000_000))
        nq = int(os.environ.get("CAP_NUM_QUERIES", 2048))
        nv = int(os.environ.get("CAP_VALIDATE", 8))
        na, k = 64, 32
        out_path = os.environ.get("BENCH_OUT",
                                  "CAPACITY_BEYOND_HBM_r13.json")
        # f32 directly (rng.random supports dtype; rng.uniform does not
        # and would materialize a 2x-size f64 intermediate): this IS the
        # staged form; f64 originals at this scale would double host
        # memory for no benefit (the rescore casts gathered rows only).
        data = rng.random((n, na), dtype=np.float32) * np.float32(100)
        queries = rng.uniform(0, 100, (nq, na)).astype(np.float32)
        # margin 64 (kcap 96): at 72M-row density the rank-32 distance
        # gaps approach the f32 quantum, and a deeper window keeps the
        # (exact) eps-hazard test clear of mass repairs.
        cfg = EngineConfig(dtype="float32", use_pallas=True, margin=64)

    dev = jax.devices()[0]
    hbm_bytes = 0
    try:
        stats = dev.memory_stats() or {}
        hbm_bytes = int(stats.get("bytes_limit", 0))
    except Exception:
        pass
    if not hbm_bytes:
        hbm_bytes = int(15.75 * 2**30)  # v5e, memory_stats absent via tunnel

    t0 = time.perf_counter()
    labels = rng.integers(0, 10, n).astype(np.int32)
    ks = rng.integers(1, k + 1, nq).astype(np.int32)
    gen_s = time.perf_counter() - t0
    inp = KNNInput(Params(n, nq, na), labels, data, ks, queries)

    if cpu_smoke:
        # Untimed warm solve so BOTH timed arms see warm jit caches —
        # the repo's A/B discipline: a dense-first cold run would give
        # the pruned arm every compile for free and overstate its wall
        # win. (The full beyond-HBM shape stays single-shot: one extra
        # dense sweep there costs tens of minutes of staging; its
        # gated claim is the scanned-bytes ratio, and the record says
        # so via wall_time_basis.)
        _solve_arm(inp, cfg, prune=False)
    arms = {}
    for name, prune in (("dense", False), ("pruned", True)):
        results, eng, solve_s = _solve_arm(inp, cfg, prune)
        arms[name] = {
            "results": results, "solve_s": solve_s,
            "repairs": eng.last_repairs,
            "prune": dict(eng.last_prune or {}),
            "select": eng._last_select,
            "phases_ms": {m: round(v, 1)
                          for m, v in eng.last_phase_ms.items()},
        }

    # Arms must agree checksum-for-checksum, and both must match the
    # oracle on the validated subset.
    t0 = time.perf_counter()
    cross = sum(a.checksum() != b.checksum()
                for a, b in zip(arms["dense"]["results"],
                                arms["pruned"]["results"]))
    vidx = np.linspace(0, nq - 1, nv).astype(np.int64)
    golden = knn_golden_fast(subset_queries(inp, vidx))
    mismatches = cross
    for arm in arms.values():
        mismatches += sum(arm["results"][int(q)].checksum() != g.checksum()
                          for q, g in zip(vidx, golden))
    validate_s = time.perf_counter() - t0

    sb_d = arms["dense"]["prune"].get("scanned_bytes", 0)
    sb_p = arms["pruned"]["prune"].get("scanned_bytes", 0)
    ratio = round(sb_p / sb_d, 4) if sb_d else None

    from dmlp_tpu.obs.run import RunRecord, round_from_name

    dataset_bytes = n * na * 4
    solve_s = arms["dense"]["solve_s"]
    rec = RunRecord(
        kind="capacity", tool="tools.capacity_beyond_hbm",
        config={"note": "Chunked solve with a pruned-vs-dense scan A/B: "
                        "the pruned arm proves blocks out of every "
                        "top-k from resident summaries before their "
                        "bytes move (ops.summaries); arms checksum-"
                        "identical and oracle-validated. cpu_smoke "
                        "uses a norm-banded corpus so the ratio is "
                        "provable in CI; the full beyond-HBM shape "
                        "needs the native TPU backend.",
                "cpu_smoke": cpu_smoke,
                "num_data": n, "num_queries": nq, "num_attrs": na,
                "kmax": k, "select": arms["dense"]["select"],
                "dataset_bytes_f32": dataset_bytes,
                "hbm_bytes": hbm_bytes},
        metrics={
            "dataset_vs_hbm": round(dataset_bytes / hbm_bytes, 3),
            # Honest timing basis: warmed sequential arms in cpu-smoke,
            # cold single-shot on the full shape — wall times are
            # context, the gated claim is the scanned-bytes ratio.
            "wall_time_basis": ("warmed_sequential" if cpu_smoke
                                else "cold_single_shot"),
            "repairs": arms["dense"]["repairs"],
            "gen_s": round(gen_s, 1),
            "solve_wall_s": round(solve_s, 1),
            "solve_wall_s_pruned": round(arms["pruned"]["solve_s"], 1),
            "qd_pairs_per_sec_wall": int(n * nq / max(solve_s, 1e-9)),
            "scanned_bytes_dense": int(sb_d),
            "scanned_bytes_pruned": int(sb_p),
            "scanned_bytes_ratio": ratio,
            "blocks_pruned": arms["pruned"]["prune"].get(
                "blocks_pruned", 0),
            "blocks_total": arms["pruned"]["prune"].get(
                "blocks_total", 0),
            "phases_ms": arms["dense"]["phases_ms"],
            "validated_queries": nv,
            "validate_mismatches": int(mismatches),
            "validate_s": round(validate_s, 1),
        },
        device=str(getattr(dev, "device_kind", dev.platform)),
        round=round_from_name(out_path))
    d = os.path.dirname(out_path)
    if d:
        os.makedirs(d, exist_ok=True)
    rec.write(out_path)
    print(rec.to_json())
    if mismatches:
        return 1
    if cpu_smoke and (ratio is None or ratio >= 0.5):
        print(f"cpu-smoke: pruned arm scanned {ratio}x the dense bytes "
              "(must be < 0.5 on the banded corpus)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
