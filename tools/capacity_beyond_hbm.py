"""Beyond-HBM capacity proof: solve a dataset LARGER than device memory.

SCALE_r04's 4M rung proved the dense distance tile never materializes
(O(N*A + Q*K) working set). This tool proves the stronger streaming
claim — the long-context analog (survey §5.7) — by running the chunked
extract driver on a dataset whose f32 form EXCEEDS the chip's HBM: only
the in-flight chunks (bounded by engine.single.ChunkThrottle), the
queries, and the running (Q, K) lists are ever device-resident, so the
solve completes where any monolithic staging would OOM by construction.

Shape (default): 72M x 64 f32 = 18.4 GB, ~1.09x HBM. Queries kept small
(2048) so the run is staging-bound, like a real larger-than-memory scan.
Data is generated directly as arrays (the text grammar at 64M rows is a
multi-GB file serving no purpose here); distribution matches the seeded
generator (uniform [0, 100], labels uniform 0..9).

Correctness: exact mode (f64 rescore + eps-hazard repair) end-to-end;
additionally VALIDATE_QUERIES queries are solved by the vectorized f64
oracle over the full 64M rows and diffed checksum-for-checksum.

Writes a schema RunRecord (obs.run) to CAPACITY_BEYOND_HBM_r06.json —
ledger-ingestible (python -m dmlp_tpu.report); the r04 ad-hoc shape is
grandfathered. Env: CAP_NUM_DATA, CAP_NUM_QUERIES, CAP_VALIDATE
(default 8), BENCH_OUT.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import numpy as np

    import jax

    from dmlp_tpu.config import EngineConfig
    from dmlp_tpu.engine.single import SingleChipEngine
    from dmlp_tpu.golden.fast import knn_golden_fast
    from dmlp_tpu.io.grammar import KNNInput, Params, subset_queries
    from dmlp_tpu.ops.pallas_distance import native_pallas_backend

    if not native_pallas_backend():
        print("needs the native TPU backend", file=sys.stderr)
        return 1

    n = int(os.environ.get("CAP_NUM_DATA", 72_000_000))
    nq = int(os.environ.get("CAP_NUM_QUERIES", 2048))
    nv = int(os.environ.get("CAP_VALIDATE", 8))
    na, k = 64, 32
    out_path = os.environ.get("BENCH_OUT", "CAPACITY_BEYOND_HBM_r06.json")

    dev = jax.devices()[0]
    hbm_bytes = 0
    try:
        stats = dev.memory_stats() or {}
        hbm_bytes = int(stats.get("bytes_limit", 0))
    except Exception:
        pass
    if not hbm_bytes:
        hbm_bytes = int(15.75 * 2**30)  # v5e, memory_stats absent via tunnel

    t0 = time.perf_counter()
    rng = np.random.default_rng(42)
    # f32 directly (rng.random supports dtype; rng.uniform does not and
    # would materialize a 2x-size f64 intermediate): this IS the staged
    # form; f64 originals at this scale would double host memory for no
    # benefit (the rescore casts gathered candidate rows only).
    data = rng.random((n, na), dtype=np.float32) * np.float32(100)
    labels = rng.integers(0, 10, n).astype(np.int32)
    queries = rng.uniform(0, 100, (nq, na)).astype(np.float32)
    ks = rng.integers(1, k + 1, nq).astype(np.int32)
    gen_s = time.perf_counter() - t0
    inp = KNNInput(Params(n, nq, na), labels, data, ks, queries)

    # margin 64 (kcap 96): at 72M-row density the rank-32 distance gaps
    # approach the f32 quantum, and a deeper window keeps the (exact)
    # eps-hazard test clear of mass repairs.
    eng = SingleChipEngine(EngineConfig(dtype="float32", use_pallas=True,
                                        margin=64))
    t0 = time.perf_counter()
    results = eng.run(inp)
    solve_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    vidx = np.linspace(0, nq - 1, nv).astype(np.int64)
    golden = knn_golden_fast(subset_queries(inp, vidx))
    mismatches = sum(
        results[int(q)].checksum() != g.checksum()
        for q, g in zip(vidx, golden))
    validate_s = time.perf_counter() - t0

    from dmlp_tpu.obs.run import RunRecord, round_from_name

    dataset_bytes = n * na * 4
    rec = RunRecord(
        kind="capacity", tool="tools.capacity_beyond_hbm",
        config={"note": "Chunked extract solve of a dataset LARGER than "
                        "HBM: only in-flight chunks (window-throttled), "
                        "queries, and the running lists are "
                        "device-resident. Exact mode end-to-end; "
                        f"{nv} queries validated checksum-for-checksum "
                        "against the vectorized f64 oracle. wall_s is "
                        "staging-bound on the tunneled link.",
                "num_data": n, "num_queries": nq, "num_attrs": na,
                "kmax": k, "select": eng._last_select,
                "dataset_bytes_f32": dataset_bytes,
                "hbm_bytes": hbm_bytes},
        metrics={
            "dataset_vs_hbm": round(dataset_bytes / hbm_bytes, 3),
            "repairs": eng.last_repairs,
            "gen_s": round(gen_s, 1),
            "solve_wall_s": round(solve_s, 1),
            "qd_pairs_per_sec_wall": int(n * nq / solve_s),
            "phases_ms": {m: round(v, 1)
                          for m, v in eng.last_phase_ms.items()},
            "validated_queries": nv,
            "validate_mismatches": int(mismatches),
            "validate_s": round(validate_s, 1),
        },
        device=str(getattr(dev, "device_kind", dev.platform)),
        round=round_from_name(out_path))
    rec.write(out_path)
    print(rec.to_json())
    return 0 if mismatches == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
