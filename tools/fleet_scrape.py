#!/usr/bin/env python
"""Aggregate per-replica OpenMetrics scrapes into one fleet snapshot.

Counters sum, log-bucket histograms merge bucket-wise, gauges keep
per-replica ``{replica="..."}`` labels (dmlp_tpu.fleet.scrape); the
merged exposition is validated with
``obs.telemetry.validate_openmetrics`` before it is written — an
invalid aggregate exits 1.

Usage::

    python tools/fleet_scrape.py SRC [SRC ...] [--names N,N]
        [--out FILE] [--json]

``SRC`` is an ``http://host:port/metrics`` URL (live scrape) or a
snapshot file path (the daemon's ``--telemetry`` output). ``--json``
prints a pure-JSON verdict on stdout (narration to stderr), following
the tools/check_trace.py convention.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from dmlp_tpu.fleet import scrape as fscrape          # noqa: E402
from dmlp_tpu.obs.telemetry import validate_openmetrics  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("sources", nargs="+",
                    help="per-replica /metrics URLs or snapshot files")
    ap.add_argument("--names", default=None,
                    help="comma-separated replica names (default r0..)")
    ap.add_argument("--out", default=None,
                    help="write the merged exposition here")
    ap.add_argument("--json", action="store_true",
                    help="pure-JSON verdict on stdout")
    args = ap.parse_args(argv)

    names = args.names.split(",") if args.names else None
    if names and len(names) != len(args.sources):
        print("fleet_scrape: --names needs one name per source",
              file=sys.stderr)
        return 2
    merged, problems = fscrape.fleet_view(args.sources,
                                          replica_names=names)
    errors = validate_openmetrics(merged)
    verdict = {
        "sources": args.sources,
        "valid": not errors,
        "problems": problems,
        "validation_errors": errors,
        "families": sum(1 for ln in merged.splitlines()
                        if ln.startswith("# TYPE ")),
    }
    if args.out:
        with open(args.out, "w") as f:
            f.write(merged)
        verdict["out"] = args.out
    narrate = sys.stderr if args.json else sys.stdout
    if args.json:
        print(json.dumps(verdict, sort_keys=True))
    elif not args.out:
        sys.stdout.write(merged)
    for p in problems[:5]:
        print(f"fleet_scrape: note: {p}", file=narrate)
    if errors:
        print(f"fleet_scrape: INVALID merged exposition: {errors[:3]}",
              file=narrate)
        return 1
    print(f"fleet_scrape: OK: {verdict['families']} families from "
          f"{len(args.sources)} sources"
          + (f" -> {args.out}" if args.out else ""), file=narrate)
    return 0


if __name__ == "__main__":
    sys.exit(main())
