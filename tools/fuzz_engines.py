#!/usr/bin/env python
"""Randomized differential fuzz campaign (committed form of the r4 hunts).

Every case solves a random KNN instance with a randomly-configured engine
and diffs run() results against the strict float64 golden model —
checksum-level equality, so any algorithmic, padding, routing, staging,
or repair bug is a hard failure, not a tolerance judgement.

Axes (superset of FUZZ_r04's):
- data styles: duplicate-heavy integer grids, continuous uniform,
  CLUSTERED near-duplicates (the style that found the r4 f32
  cancellation hazard), huge magnitudes, mixed clusters+uniform, extreme
  aspect ratios (na=1 / single query / tiny n).
- k drawn over the FULL legal range [1, num_data] — exercises the
  heterogeneous-k router, the r5 MULTI-PASS wide-k extraction, and the
  wide-k f32 staging policy (staging_for_k).
- dtype auto | float32 | bfloat16; exact and fast modes; selects
  auto/extract (use_pallas on) and the streaming selects.
- engines: single, sharded, ring (the mesh engines need the virtual
  8-device CPU mesh).

Usage:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python tools/fuzz_engines.py --seeds 10000:10100 [--out FUZZ.json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def gen_case(seed: int):
    from dmlp_tpu.io.grammar import KNNInput, Params
    rng = np.random.default_rng(seed)
    style = rng.choice(["intdup", "uniform", "clustered", "huge",
                        "mixed", "aspect"])
    if style == "aspect":
        n = int(rng.choice([1, 2, 3, 9, 700]))
        nq = int(rng.choice([1, 2, 17]))
        na = int(rng.choice([1, 2, 8]))
    else:
        n = int(rng.integers(50, 2600))
        nq = int(rng.integers(1, 28))
        na = int(rng.integers(1, 9))
    if style == "intdup":
        data = rng.integers(0, 3, (n, na)).astype(np.float64)
        queries = rng.integers(0, 3, (nq, na)).astype(np.float64)
    elif style == "clustered":
        nc = int(rng.integers(1, 5))
        centers = rng.uniform(-5, 5, (nc, na))
        data = centers[rng.integers(0, nc, n)] + rng.normal(0, 1e-3, (n, na))
        queries = centers[rng.integers(0, nc, nq)] \
            + rng.normal(0, 1e-3, (nq, na))
    elif style == "huge":
        data = rng.uniform(0, 1e6, (n, na))
        queries = rng.uniform(0, 1e6, (nq, na))
    elif style == "mixed":
        c = rng.uniform(-10, 10, (1, na))
        half = n // 2
        data = np.concatenate([c + rng.normal(0, 1e-3, (half, na)),
                               rng.uniform(-20, 20, (n - half, na))])
        queries = rng.uniform(-20, 20, (nq, na))
    else:
        data = rng.uniform(-20, 20, (n, na))
        queries = rng.uniform(-20, 20, (nq, na))
    labels = rng.integers(0, int(rng.integers(1, 7)), n).astype(np.int32)
    # full legal k range, biased so wide-k (router/multipass) really fires
    if rng.random() < 0.35:
        ks = rng.integers(max(1, n // 2), n + 1, nq).astype(np.int32)
    else:
        ks = rng.integers(1, n + 1, nq).astype(np.int32)
    return style, KNNInput(Params(n, nq, na), labels, data, ks, queries)


def gen_config(seed: int):
    from dmlp_tpu.config import EngineConfig
    rng = np.random.default_rng(seed ^ 0x5EED)
    mode = rng.choice(["single", "single", "sharded", "ring"])
    dtype = rng.choice(["auto", "float32", "bfloat16"])
    exact = bool(rng.random() < 0.8)
    if rng.random() < 0.5:
        select, pallas = "extract", True
    else:
        select = rng.choice(["auto", "topk", "seg", "sort"])
        pallas = bool(rng.random() < 0.5)
    return EngineConfig(mode=mode, dtype=dtype, exact=exact,
                        select=select, use_pallas=pallas)


def run_case(seed: int):
    import jax

    from dmlp_tpu.engine.sharded import RingEngine, ShardedEngine
    from dmlp_tpu.engine.single import SingleChipEngine
    from dmlp_tpu.golden.reference import knn_golden
    from dmlp_tpu.parallel.mesh import make_mesh

    style, inp = gen_case(seed)
    cfg = gen_config(seed)
    # Fast mode's output IS the device f32 ordering — golden-checksum
    # parity is only promised there when f32 arithmetic is exact on the
    # data (integer grids); continuous styles run exact mode, like the
    # committed fast-mode tests.
    if style != "intdup" and not cfg.exact:
        import dataclasses
        cfg = dataclasses.replace(cfg, exact=True)
    if cfg.mode != "single" and len(jax.devices()) < 8:
        import dataclasses
        cfg = dataclasses.replace(cfg, mode="single")
    if cfg.mode == "single":
        eng = SingleChipEngine(cfg)
    else:
        cls = ShardedEngine if cfg.mode == "sharded" else RingEngine
        shape = [(4, 2), (2, 4), (8, 1), (1, 8)][seed % 4]
        eng = cls(cfg, mesh=make_mesh(shape))
    got = eng.run(inp)
    want = knn_golden(inp)
    ok = all(g.checksum() == w.checksum() for g, w in zip(got, want)) \
        and len(got) == len(want)
    return {"seed": seed, "style": str(style), "mode": cfg.mode,
            "dtype": str(cfg.dtype), "exact": cfg.exact,
            "select": str(cfg.select), "pallas": cfg.use_pallas,
            "n": inp.params.num_data, "nq": inp.params.num_queries,
            "kmax": int(inp.ks.max()), "ok": ok,
            "mp_passes": getattr(eng, "last_mp_passes", 0),
            "hetk": getattr(eng, "last_hetk", None) is not None,
            "repairs": int(getattr(eng, "last_repairs", 0))}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seeds", default="10000:10100",
                    help="lo:hi seed range")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    lo, hi = (int(x) for x in args.seeds.split(":"))

    t0 = time.time()
    results, failures = [], []
    for seed in range(lo, hi):
        r = run_case(seed)
        results.append(r)
        if not r["ok"]:
            failures.append(r)
            print("FAIL", json.dumps(r))
        elif (seed - lo) % 10 == 0:
            print(f"{seed - lo + 1}/{hi - lo} ok "
                  f"(mp={sum(x['mp_passes'] > 1 for x in results)}, "
                  f"hetk={sum(x['hetk'] for x in results)}, "
                  f"repaired={sum(x['repairs'] > 0 for x in results)})",
                  flush=True)
    summary = {
        "seeds": f"{lo}:{hi}", "cases": len(results),
        "failures": len(failures), "failed": failures,
        "minutes": round((time.time() - t0) / 60, 1),
        "coverage": {
            "multipass_cases": sum(r["mp_passes"] > 1 for r in results),
            "hetk_routed_cases": sum(r["hetk"] for r in results),
            "repaired_cases": sum(r["repairs"] > 0 for r in results),
            "by_mode": {m: sum(r["mode"] == m for r in results)
                        for m in ("single", "sharded", "ring")},
            "by_dtype": {d: sum(r["dtype"] == d for r in results)
                         for d in ("auto", "float32", "bfloat16")},
        },
    }
    print(json.dumps(summary, indent=1))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(summary, f, indent=1)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
