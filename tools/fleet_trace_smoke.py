#!/usr/bin/env python
"""Fleet tracing smoke: request-scoped tracing proven end to end.

The ``make fleet-trace-smoke`` checker (wired into ``make test``).
Five proofs over a real 2-replica fleet on CPU — every failure exits
nonzero with the reason named:

1. **Untraced golden arm** — the committed paced trace
   (inputs/serve_trace2.jsonl) replayed open-loop through an UNTRACED
   fleet: zero errors, contract checksums golden-identical.
2. **Traced arm byte-identity** — the same fleet topology with
   ``--trace`` on both replicas + the router and a sync-stamped client
   Tracer, replayed at x2 and x8 offered load with rid-stamped
   requests: zero errors, every response echoes its rid, and the
   contract checksums are byte-identical to the untraced arm AND the
   golden oracle (tracing must never perturb the contract channel).
3. **Causal merge** — ``tools/merge_traces.py --fleet`` aligns the
   four trace files on their ``fleet.clock_sync`` markers and
   stitches per-rid causal trees; a designated x8 request must be
   reconstructable end-to-end (client fire -> route -> hop -> queue ->
   coalesce -> solve -> finalize -> write) and its replica phase sum
   must reconcile against the client-measured latency within the
   documented tolerance.
4. **Validation teeth** — ``tools/check_trace.py --fleet --json``
   passes (rid uniqueness, span parentage, retry-hop accounting,
   canonical phase order, reconcile fraction >= 0.9) on the merged
   trace, and REJECTS a tampered copy carrying a fabricated attempt-2
   retry hop on a non-retried request.
5. **Tail attribution -> gated ledger** — ``tools/tail_attrib.py``
   decomposes the per-level p50/p95/p99 into per-phase contributions,
   names the dominant phase per level, and its ``tailattrib``
   RunRecords round-trip the perf ledger as gated
   ``fleet/<level>/phase/<name>`` series.

Usage::

    python tools/fleet_trace_smoke.py --out outputs/fleet_trace \
        [--record outputs/fleet_trace/TAILATTRIB.jsonl] [--round N]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from dmlp_tpu.fleet import harness as fh                  # noqa: E402
from dmlp_tpu.io.grammar import parse_input_text          # noqa: E402
from dmlp_tpu.obs import trace as obs_trace               # noqa: E402
from dmlp_tpu.serve import client as sc                   # noqa: E402

TRACE_PATH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "inputs", "serve_trace2.jsonl")
BATCH_CAP = 32
PHASES = ("queue", "coalesce", "solve", "finalize", "write")


def fail(msg: str):
    print(f"fleet_trace_smoke: FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def say(msg: str) -> None:
    print(f"fleet_trace_smoke: {msg}")


def _spawn_fleet(corpus_path: str, out: str, warm: str,
                 traced: bool):
    """-> (replicas, router) in ``out`` (traced: --trace on all)."""
    reps = []
    for i in range(2):
        flags = (["--trace",
                  os.path.join(out, f"trace-replica{i:02d}.json")]
                 if traced else None)
        reps.append(fh.spawn_replica(corpus_path, out, f"replica{i:02d}",
                                     warm, batch_cap=BATCH_CAP,
                                     flags=flags))
    for fp in reps:
        fh.await_replica(fp)
    rflags = (["--trace", os.path.join(out, "trace-router.json")]
              if traced else None)
    router = fh.spawn_router(out, reps, flags=rflags)
    return reps, router


def _replay(router, header, reqs, speed, rid_prefix=None):
    res = sc.replay_open_loop(router.ready["port"], header, reqs,
                              speed=speed, rid_prefix=rid_prefix,
                              level=speed if rid_prefix else None)
    bad = [r for r in res if not r.get("ok")]
    if bad:
        fail(f"open-loop x{speed:g} replay had {len(bad)} failures "
             f"(rid_prefix={rid_prefix!r}): {bad[0]}")
    return res


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="outputs/fleet_trace")
    ap.add_argument("--record", default=None)
    ap.add_argument("--round", type=int, default=None,
                    help="measurement round stamped into the "
                         "tailattrib records")
    args = ap.parse_args(argv)
    out = os.path.abspath(args.out)
    record = os.path.abspath(args.record) if args.record \
        else os.path.join(out, "TAILATTRIB.jsonl")
    tools = os.path.dirname(os.path.abspath(__file__))
    udir = os.path.join(out, "untraced")
    tdir = os.path.join(out, "traced")
    for d in (udir, tdir):
        os.makedirs(d, exist_ok=True)
    if os.path.exists(record):
        os.remove(record)
    sc.clear_flight_dumps(udir)
    sc.clear_flight_dumps(tdir)

    header, reqs = sc.load_trace(TRACE_PATH)
    corpus_txt = sc.corpus_text(header)
    corpus_path = os.path.join(out, "corpus.in")
    with open(corpus_path, "w") as f:
        f.write(corpus_txt)
    golden = sc.contract_text(sc.golden_reference(
        parse_input_text(corpus_txt), header, reqs))
    warm = ",".join(f"{q}x{k}" for q, k in
                    sc.warm_buckets_for_trace(reqs, BATCH_CAP))

    # 1. untraced golden arm
    reps, router = _spawn_fleet(corpus_path, udir, warm, traced=False)
    try:
        res_u = _replay(router, header, reqs, 2.0)
        fh.drain_fleet(router, reps)
    finally:
        fh.kill_all(reps + [router])
    if any("rid" in r for r in res_u):
        fail("untraced responses carry a rid key")
    cs_u = sc.contract_text([r["checksums"] for r in res_u])
    if cs_u != golden:
        fail("untraced arm responses differ from the golden oracle")
    say(f"untraced arm OK: {len(reqs)} responses golden-identical")

    # 2. traced arm: --trace fleet + sync-stamped client tracer
    reps, router = _spawn_fleet(corpus_path, tdir, warm, traced=True)
    client_tracer = obs_trace.install(obs_trace.Tracer())
    client_tracer.sync_instant("fleet.clock_sync")
    try:
        res_x2 = _replay(router, header, reqs, 2.0, rid_prefix="x2-")
        res_x8 = _replay(router, header, reqs, 8.0, rid_prefix="x8-")
        client_tracer.write(os.path.join(tdir, "trace-client.json"),
                            process_name="client")
        fh.drain_fleet(router, reps)
    finally:
        if obs_trace.active() is client_tracer:
            obs_trace.uninstall()
        fh.kill_all(reps + [router])
    for prefix, res in (("x2-", res_x2), ("x8-", res_x8)):
        for i, r in enumerate(res):
            if r.get("rid") != f"{prefix}{i}":
                fail(f"response {i} did not echo its rid: "
                     f"{r.get('rid')!r} != {prefix}{i!r}")
            if "hops" in r and int(r["hops"]) < 2:
                fail(f"rid {prefix}{i}: hops={r['hops']} surfaced on "
                     "a non-retried request")
    for tag, res in (("x2", res_x2), ("x8", res_x8)):
        if sc.contract_text([r["checksums"] for r in res]) != golden:
            fail(f"traced {tag} responses differ from the golden "
                 "oracle — tracing perturbed the contract channel")
    say("traced arm OK: x2 + x8 rid-echoed, checksums byte-identical "
        "to the untraced arm and the golden oracle")

    # 3. causal merge + end-to-end reconstruction of one x8 request
    merged_path = os.path.join(tdir, "trace-fleet-merged.json")
    rc = subprocess.call(
        [sys.executable, os.path.join(tools, "merge_traces.py"),
         tdir, "--fleet", "-o", merged_path], env=fh._repo_env())
    if rc != 0:
        fail("merge_traces --fleet failed")
    with open(merged_path) as f:
        merged = json.load(f)
    fleet = merged["fleet"]
    if sorted(fleet["processes"]) != ["client", "replica00",
                                      "replica01", "router"]:
        fail(f"merge missed a process: {sorted(fleet['processes'])}")
    probe = "x8-0"
    ent = fleet["requests"].get(probe)
    if not ent:
        fail(f"rid {probe} absent from the merged per-rid table")
    if not ent.get("client") or not ent.get("route") \
            or not ent.get("hops"):
        fail(f"rid {probe} causal tree incomplete: {ent}")
    missing = [p for p in PHASES if p not in ent.get("phases", {})]
    if missing:
        fail(f"rid {probe} lacks phase span(s) {missing}: {ent}")
    if ent.get("reconciled") is not True:
        fail(f"rid {probe} failed the phase-sum reconcile: {ent}")
    say(f"causal merge OK: {probe} reconstructed client->route->hop->"
        f"{'->'.join(PHASES)} (client {ent['client']['client_ms']} ms, "
        f"phase sum {ent['phase_sum_ms']} ms, residual "
        f"{ent['residual_ms']} ms)")

    # 4. check_trace --fleet passes; a tampered trace fails
    cp = subprocess.run(
        [sys.executable, os.path.join(tools, "check_trace.py"),
         "--fleet", merged_path, "--json", "--min-reconciled", "0.9"],
        capture_output=True, text=True, env=fh._repo_env())
    if cp.returncode != 0:
        fail(f"check_trace --fleet rejected the merged trace: "
             f"{cp.stderr.strip()[-500:]}")
    verdict = json.loads(cp.stdout)
    if verdict["rids"] < 2 * len(reqs):
        fail(f"check verdict covers {verdict['rids']} rids, expected "
             f">= {2 * len(reqs)}")
    tampered = dict(merged)
    tampered["traceEvents"] = list(merged["traceEvents"]) + [{
        "name": "fleet.hop", "ph": "X", "ts": 1.0, "dur": 1.0,
        "pid": 1, "tid": 0,
        "args": {"rid": probe, "attempt": 2, "replica": "fake",
                 "outcome": "ok"}}]
    tampered_path = os.path.join(tdir, "trace-tampered.json")
    with open(tampered_path, "w") as f:
        json.dump(tampered, f)
    rc = subprocess.call(
        [sys.executable, os.path.join(tools, "check_trace.py"),
         "--fleet", tampered_path], stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL, env=fh._repo_env())
    if rc == 0:
        fail("check_trace --fleet accepted a fabricated retry hop on "
             "a non-retried request")
    say(f"validation teeth OK: merged trace passes "
        f"({verdict['rids']} rids, reconcile fraction "
        f"{verdict['reconcile'].get('fraction')}), tampered trace "
        "rejected")

    # 5. tail attribution -> gated fleet/<level>/phase/ ledger series
    cmd = [sys.executable, os.path.join(tools, "tail_attrib.py"),
           merged_path, "--record", record, "--json"]
    if args.round is not None:
        cmd += ["--round", str(args.round)]
    cp = subprocess.run(cmd, capture_output=True, text=True,
                        env=fh._repo_env())
    if cp.returncode != 0:
        fail(f"tail_attrib failed: {cp.stderr.strip()[-500:]}")
    att = json.loads(cp.stdout)["levels"]
    if sorted(att) != ["x2", "x8"]:
        fail(f"tail_attrib levels {sorted(att)} != ['x2', 'x8']")
    for lvl, a in att.items():
        if a["dominant_p99"] not in PHASES:
            fail(f"{lvl}: dominant phase {a['dominant_p99']!r} is not "
                 "a known phase")
    from dmlp_tpu.obs.ledger import ingest_file
    entry = ingest_file(record)
    if entry["status"] != "parsed":
        fail(f"tailattrib records did not parse in the ledger: "
             f"{entry.get('error')}")
    series = {p["series"] for p in entry["points"]}
    for want_s in ("fleet/x8/phase/queue_p99_ms",
                   "fleet/x8/phase/solve_p99_ms",
                   "fleet/x2/phase/coalesce_p99_ms"):
        if want_s not in series:
            fail(f"ledger series missing {want_s} "
                 f"(got {sorted(series)[:8]}...)")
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "perf_gate", os.path.join(tools, "perf_gate.py"))
    pg = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(pg)
    if not pg.gated("fleet/x8/phase/queue_p99_ms"):
        fail("fleet/<level>/phase/ series are not in the perf gate's "
             "prefixes")
    doms = {lvl: a["dominant_p99"] for lvl, a in sorted(att.items())}
    say(f"tail attribution OK: dominant phases {doms}, "
        f"{len(entry['points'])} gated ledger points -> {record}")

    flights = sc.flight_dumps(udir) + sc.flight_dumps(tdir)
    if flights:
        fail(f"orderly drains left flight dumps: {flights}")
    say("PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
