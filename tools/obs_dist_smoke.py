#!/usr/bin/env python
"""Distributed-observability smoke: 2-process CPU cluster, traced end to end.

The `make obs-dist-smoke` driver. Spawns a real 2-process jax.distributed
(Gloo) CPU cluster running the contract entry point with ``--trace``
(tests/test_distributed_contract.py's spawn pattern), then:

- asserts process 0's stdout is byte-identical to the golden oracle's and
  carries the ``Time taken`` stderr line — tracing must not perturb the
  contract channels;
- merges the per-rank ``trace-rank<NN>.json`` files with
  tools/merge_traces.py (clock-sync alignment + per-rank span
  cross-check);
- validates the merged trace's structural contract with
  tools/check_trace.py --dist (distinct rank pids, metadata + clock-sync
  events, monotonic per-rank timestamps, dist.solve spans).

Some jax builds (including this container's) cannot run multi-process
computations on the CPU backend at all — the same root cause failing the
seed suite's 2-process contract tests. When the cluster dies with that
exact signature, the smoke falls back to EMULATED ranks: N independent
single-process contract runs, each writing its rank file via the
DMLP_TPU_TRACE_RANK override — the per-rank artifact/merge/validate
chain is then still exercised end to end (clearly labeled in the
output); the collective path itself is covered by the real-cluster form
wherever the backend supports it.

Usage: JAX_PLATFORMS=cpu python tools/obs_dist_smoke.py [--dir outputs/dist_obs]
Exit 0 on success.
"""

from __future__ import annotations

import argparse
import os
import shutil
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


#: the error signature of a jax build whose CPU backend cannot run
#: multi-process computations (the seed suite's 2-process contract
#: failures share this root cause) — detected here AND by
#: tests/test_obs_dist.py, which imports these helpers
MULTIPROC_UNSUPPORTED = "Multiprocess computations aren't implemented"


def cluster_env(devices_per_proc: int = 2) -> dict:
    """Subprocess environment for a virtual-CPU cluster rank (the
    test-suite recipe: strip the axon TPU hook, pin CPU devices)."""
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={devices_per_proc}")
    return env


def spawn_traced_cluster(input_path: str, trace_dir: str, procs: int = 2,
                         timeout: float = 240.0,
                         devices_per_proc: int = 2):
    """Spawn a real ``procs``-rank jax.distributed (Gloo) CPU cluster
    running the traced contract entry point; returns the Popen list and
    their (stdout, stderr) pairs."""
    env = cluster_env(devices_per_proc)
    port = _free_port()
    ps = [subprocess.Popen(
        [sys.executable, "-m", "dmlp_tpu.distributed",
         "--input", str(input_path),
         "--coordinator", f"localhost:{port}",
         "--processes", str(procs), "--process-id", str(pid),
         "--trace", str(trace_dir)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env, cwd=REPO)
        for pid in range(procs)]
    return ps, [p.communicate(timeout=timeout) for p in ps]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--dir", default=os.path.join("outputs", "dist_obs"))
    ap.add_argument("--procs", type=int, default=2)
    ap.add_argument("--timeout", type=float, default=240.0)
    args = ap.parse_args(argv)

    from dmlp_tpu.golden.reference import knn_golden
    from dmlp_tpu.io.datagen import generate_input_text
    from dmlp_tpu.io.grammar import parse_input_text
    from dmlp_tpu.io.report import format_results

    trace_dir = os.path.abspath(args.dir)
    if os.path.isdir(trace_dir):
        shutil.rmtree(trace_dir)
    os.makedirs(trace_dir)

    text = generate_input_text(211, 23, 5, -4, 4, 1, 12, 4, seed=9)
    input_path = os.path.join(trace_dir, "smoke.in")
    with open(input_path, "w") as f:
        f.write(text)
    want = format_results(knn_golden(parse_input_text(text)))

    procs, outs = spawn_traced_cluster(input_path, trace_dir,
                                       procs=args.procs,
                                       timeout=args.timeout)
    errs = "\n".join(o[1].decode()[-2000:] for o in outs)
    if any(p.returncode != 0 for p in procs):
        if MULTIPROC_UNSUPPORTED not in errs:
            print(f"obs_dist_smoke: FAIL: a rank exited nonzero:\n{errs}",
                  file=sys.stderr)
            return 1
        # This jax build cannot run ANY multi-process computation on CPU
        # (the seed suite's 2-process contract tests fail the same way).
        # Emulate the ranks so the tracing/merge/validate chain is still
        # smoke-tested; the real-cluster form above runs wherever the
        # backend supports it.
        print("obs_dist_smoke: CPU backend lacks multi-process "
              "computations (known jax drift); falling back to "
              f"{args.procs} EMULATED single-process ranks")
        for pid in range(args.procs):
            e = dict(cluster_env(2), DMLP_TPU_TRACE_RANK=str(pid),
                     DMLP_TPU_TRACE_RANKS=str(args.procs))
            proc = subprocess.run(
                [sys.executable, "-m", "dmlp_tpu.distributed",
                 "--input", input_path, "--trace", trace_dir],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=e,
                cwd=REPO, timeout=args.timeout)
            if proc.returncode != 0:
                print(f"obs_dist_smoke: FAIL: emulated rank {pid} exited "
                      f"{proc.returncode}:\n"
                      f"{proc.stderr.decode()[-2000:]}", file=sys.stderr)
                return 1
            if proc.stdout.decode() != want:
                print(f"obs_dist_smoke: FAIL: emulated rank {pid} stdout "
                      "diverged from the golden oracle", file=sys.stderr)
                return 1
    else:
        if outs[0][0].decode() != want:
            print("obs_dist_smoke: FAIL: traced cluster stdout diverged "
                  "from the golden oracle", file=sys.stderr)
            return 1
        if "Time taken:" not in outs[0][1].decode():
            print("obs_dist_smoke: FAIL: contract stderr line missing",
                  file=sys.stderr)
            return 1
    print("obs_dist_smoke: contract channels ok (stdout golden-identical)")

    merged = os.path.join(trace_dir, "trace-merged.json")
    for tool_argv in (
            [sys.executable, os.path.join(REPO, "tools", "merge_traces.py"),
             trace_dir, "-o", merged],
            [sys.executable, os.path.join(REPO, "tools", "check_trace.py"),
             "--dist", merged, "--ranks", str(args.procs)]):
        proc = subprocess.run(tool_argv, cwd=REPO)
        if proc.returncode != 0:
            return proc.returncode

    # Straggler path: the checker's --json verdict must carry the
    # per-rank skew table the merge embedded (every rank present, skew
    # vs the across-rank median computed) — the smoke-level proof that
    # the straggler detector runs on every merged trace.
    import json
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "check_trace.py"),
         "--dist", merged, "--ranks", str(args.procs), "--json"],
        cwd=REPO, stdout=subprocess.PIPE)
    if proc.returncode != 0:
        return proc.returncode
    verdict = json.loads(proc.stdout.decode())
    straggler = verdict.get("straggler") or {}
    per_rank = straggler.get("per_rank") or {}
    if sorted(int(r) for r in per_rank) != list(range(args.procs)):
        print(f"obs_dist_smoke: FAIL: straggler skew table missing or "
              f"incomplete in the --json verdict: {straggler}",
              file=sys.stderr)
        return 1
    print(f"obs_dist_smoke: straggler skew table ok — "
          f"{ {r: row.get('skew_vs_median') for r, row in sorted(per_rank.items())} }")
    print(f"obs_dist_smoke: ok — {args.procs}-rank traced run merged and "
          f"validated under {trace_dir}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
