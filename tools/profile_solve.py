"""Decompose the device KNN solve into per-op timings on the real chip.

VERDICT r2 weak #1: the fenced device-solve number (1616 ms) contradicts the
"transfer-bound" narrative. This script times each op of the "seg" selection
step in isolation (matmul, fused pallas dist+segmin, segment top_k, segment
gather, candidate merge top_k) at the exact benchmark shape, so the dominant
cost is measured, not guessed. Output: one JSON object to stdout; commit as
PROFILE_r03.json.

Every timing is fenced by a dependent scalar readback (block_until_ready is
unreliable over tunneled PJRT links).
"""

from __future__ import annotations

import functools
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def fence(x) -> float:
    return float(jnp.ravel(x)[0])


def timeit(fn, *args, repeats=3):
    out = fn(*args)
    fence(out[0] if isinstance(out, (tuple, list)) else out)
    t0 = time.perf_counter()
    for _ in range(repeats):
        out = fn(*args)
    fence(out[0] if isinstance(out, (tuple, list)) else out)
    return (time.perf_counter() - t0) / repeats * 1e3


def main() -> int:
    n, nq, a, k = 204800, 10240, 64, 40
    dblock = 51200
    nseg = dblock // 128
    s = min(nseg, k + 16)

    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.uniform(0, 100, (nq, a)), jnp.float32)
    d = jnp.asarray(rng.uniform(0, 100, (dblock, a)), jnp.float32)
    lab = jnp.asarray(rng.integers(0, 10, dblock, dtype=np.int32))
    ids = jnp.arange(dblock, dtype=jnp.int32)
    fence(jnp.sum(d))

    out = {"shape": {"n": n, "nq": nq, "a": a, "k": k, "dblock": dblock,
                     "nseg": nseg, "s": s}}

    # 1. Raw cross-term matmul (the MXU floor).
    mm = jax.jit(lambda q, d: q @ d.T)
    out["matmul_ms"] = timeit(mm, q, d)

    # 2. Fused pallas dist+segmin (one pass over the tile).
    from dmlp_tpu.ops.pallas_distance import (fused_dist_segmin,
                                              native_pallas_backend)
    native = native_pallas_backend()
    out["pallas_native"] = native
    fd = functools.partial(fused_dist_segmin, interpret=not native)
    out["fused_dist_segmin_ms"] = timeit(fd, q, d, ids)

    # 3. XLA dist tile alone (unfused norm expansion) for comparison.
    from dmlp_tpu.ops.distance import masked_pairwise_sq_l2
    dist_xla = jax.jit(lambda q, d, i: masked_pairwise_sq_l2(q, d, i))
    out["xla_dist_tile_ms"] = timeit(dist_xla, q, d, ids)

    tile, segmin = fd(q, d, ids)
    fence(tile)

    # 4. Segment-min reduce from a resident tile (XLA second pass).
    segred = jax.jit(
        lambda t: t.reshape(nq, nseg, 128).min(axis=-1))
    out["segmin_reduce_ms"] = timeit(segred, tile)

    # 5. top_k over segment minima -> segment indices.
    seg_topk = jax.jit(lambda sm: jax.lax.top_k(-sm, s))
    out["seg_topk_ms"] = timeit(lambda sm: seg_topk(sm)[0], segmin)

    _, seg_idx = seg_topk(segmin)
    fence(seg_idx)

    # 6. Segment gather (take_along_axis on (nq, nseg, 128)).
    gat = jax.jit(lambda t, si: jnp.take_along_axis(
        t.reshape(nq, nseg, 128), si[:, :, None], axis=1
    ).reshape(nq, s * 128))
    out["seg_gather_ms"] = timeit(gat, tile, seg_idx)

    cand = gat(tile, seg_idx)
    fence(cand)

    # 6b. Label/id gather from (nseg, 128) by (nq, s).
    lgat = jax.jit(
        lambda l, si: l.reshape(nseg, 128)[si].reshape(nq, s * 128))
    out["label_gather_ms"] = timeit(lgat, lab, seg_idx)

    # 7. Candidate merge top_k over (nq, s*128 + k).
    carry = jnp.zeros((nq, k), jnp.float32)
    mtk = jax.jit(lambda c, cd: jax.lax.top_k(
        -jnp.concatenate([c, cd], axis=-1), k))
    out["merge_topk_ms"] = timeit(lambda c, cd: mtk(c, cd)[0], carry, cand)

    # 7b. Straight full top_k over the whole tile (the "topk" select cost).
    ftk = jax.jit(lambda t: jax.lax.top_k(-t, k))
    out["full_tile_topk_ms"] = timeit(lambda t: ftk(t)[0], tile)

    # 7c. approx_max_k over the tile (recall-configurable alternative).
    atk = jax.jit(lambda t: jax.lax.approx_max_k(-t, k,
                                                 recall_target=0.99))
    out["approx_topk_ms"] = timeit(lambda t: atk(t)[0], tile)

    # 8. The whole seg step end-to-end at one chunk, then the full 4-chunk
    #    streaming solve (what bench.py's device_solve measures).
    from dmlp_tpu.ops.topk import init_topk, make_block_step, streaming_topk
    step = make_block_step("seg", k, native, jnp.float32)
    stepj = jax.jit(lambda c, q, da, dl, di: step(c, q, da, dl, di))
    init = init_topk(nq, k)
    out["seg_step_ms"] = timeit(
        lambda c, q, da, dl, di: stepj(c, q, da, dl, di).dists,
        init, q, d, lab, ids)

    dfull = jnp.asarray(rng.uniform(0, 100, (n, a)), jnp.float32)
    labf = jnp.asarray(rng.integers(0, 10, n, dtype=np.int32))
    idsf = jnp.arange(n, dtype=jnp.int32)
    fence(jnp.sum(dfull))
    solve = jax.jit(functools.partial(
        streaming_topk, k=k, data_block=dblock, select="seg",
        use_pallas=native))
    out["streaming_solve_seg_ms"] = timeit(
        lambda q, d, l, i: solve(q, d, l, i).dists, q, dfull, labf, idsf)

    solve_topk = jax.jit(functools.partial(
        streaming_topk, k=k, data_block=dblock, select="topk"))
    out["streaming_solve_topk_ms"] = timeit(
        lambda q, d, l, i: solve_topk(q, d, l, i).dists, q, dfull, labf, idsf)

    print(json.dumps(out, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
