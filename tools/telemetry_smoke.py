#!/usr/bin/env python
"""Telemetry smoke: prove the live-telemetry layer end to end on CPU.

The ``make telemetry-smoke`` checker (wired into ``make test``). Five
proofs, every failure exits nonzero with the reason named:

1. **Contract byte-identity** — bench config 1 runs through the real
   CLI in interleaved ``--telemetry`` OFF/ON pairs (order alternating
   per pair, the BENCH_MODES_r04 weather methodology); every run's
   stdout must be byte-identical to the plain run's. Telemetry is
   stderr/filesystem-only by construction; this proves it.
2. **OpenMetrics validity** — the final ON-arm snapshot file passes the
   structural validator (obs.telemetry.validate_openmetrics) and
   carries the honest ``mem_stats_unavailable`` gauge (this container's
   CPU backend reports no allocator stats — the marker IS the proof
   the gauge tells the truth).
3. **Peak-HBM reconcile** — an in-process engine run under a live
   session: the analytic model (obs.memwatch.resident_bytes_model)
   must agree with the measured watermark within the basis's
   documented ratio bounds, OR the explicit ``mem_stats_unavailable``
   marker must be present (backend reports nothing at all). On this
   container the ``live_arrays`` basis measures; on TPU,
   ``memory_stats``.
4. **Flight recorder** — a fault schedule drives retries to exhaustion
   inside the CLI; the run must fail AND leave a ``FLIGHT_*.json``
   post-mortem containing recent span events.
5. **Ledger ingestion** — the overhead + reconcile numbers serialize
   as ONE RunRecord (kind "telemetry", raw per-arm sample lists) that
   round-trips through obs.ledger as a parsed ``telemetry/...`` series
   — the `make perf-gate` surface for telemetry-overhead and peak-HBM
   regressions.

Usage::

    python tools/telemetry_smoke.py --out outputs/telemetry \
        [--record outputs/telemetry/TELEMETRY_SMOKE.jsonl] [--pairs 2]
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from dmlp_tpu.bench.configs import BENCH_CONFIGS            # noqa: E402
from dmlp_tpu.bench.harness import (_extract_ms, ensure_input,  # noqa: E402
                                    run_engine)


def fail(msg: str):
    print(f"telemetry_smoke: FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def say(msg: str) -> None:
    print(f"telemetry_smoke: {msg}")


def run_ab(cfg, input_path, out_dir, pairs: int, telemetry_path: str):
    """Interleaved OFF/ON engine runs; returns (times dict, outputs
    dict of stdout text sets)."""
    times = {"off": [], "on": []}
    outputs = {"off": set(), "on": set()}
    for rep in range(pairs):
        order = ("off", "on") if rep % 2 == 0 else ("on", "off")
        for arm in order:
            flags = ["--telemetry", telemetry_path] if arm == "on" \
                else None
            out_path, err_path = run_engine(
                cfg, input_path, out_dir, obs_flags=flags)
            with open(out_path) as f:
                outputs[arm].add(f.read())
            with open(err_path) as f:
                ms = _extract_ms(f.read())
            if ms is None:
                fail(f"no timing line in the {arm}-arm run")
            times[arm].append(ms)
    return times, outputs


def check_openmetrics(path: str) -> None:
    from dmlp_tpu.obs.telemetry import validate_openmetrics
    with open(path) as f:
        text = f.read()
    problems = validate_openmetrics(text)
    if problems:
        fail(f"OpenMetrics snapshot invalid: {problems[:5]}")
    if "mem_stats_unavailable" not in text:
        fail("snapshot lacks the mem.stats_unavailable gauge — the "
             "honest-marker contract for backends without "
             "memory_stats")
    if "span_latency_ms" not in text:
        fail("snapshot lacks span-derived latency histograms")
    say(f"OpenMetrics snapshot valid ({len(text.splitlines())} lines)")


def reconcile_in_process(cfg, input_path):
    """In-process solve under a live session: analytic model vs
    measured watermark. Returns the reconcile dict."""
    from dmlp_tpu.cli import make_engine
    from dmlp_tpu.config import EngineConfig
    from dmlp_tpu.io.grammar import parse_input
    from dmlp_tpu.obs import memwatch, telemetry

    with open(input_path, "rb") as f:
        inp = parse_input(f)
    engine = make_engine(EngineConfig(mode=cfg.mode,
                                      use_pallas=cfg.use_pallas,
                                      select=cfg.select))
    sess = telemetry.start(handle_signals=False)
    try:
        engine.run(inp)
        model = engine.last_mem_model
        if model is None:
            fail("engine.last_mem_model unset under a live session")
        rec = memwatch.reconcile(model, sess.sampler.measured_peak())
    finally:
        sess.close()
    if "mem_stats_unavailable" in rec:
        say(f"peak-HBM reconcile: explicit marker "
            f"({rec['mem_stats_unavailable']!r}) — backend reports no "
            "memory basis")
    elif not rec["within_tolerance"]:
        fail(f"analytic peak-HBM model disagrees with the measured "
             f"watermark beyond the documented {rec['basis']} bounds: "
             f"{json.dumps(rec)}")
    else:
        say(f"peak-HBM reconcile OK: model {rec['model_bytes']} B vs "
            f"measured {rec['measured_bytes']} B "
            f"({rec['basis']}, ratio {rec['ratio']} within "
            f"{rec['ratio_bounds']})")
    return rec


def check_flight_recorder(cfg, input_path, out_dir: str) -> None:
    """Retries-to-exhaustion under a fault schedule must leave a
    FLIGHT_*.json with recent span events."""
    import subprocess

    schedule = {"schema": 1, "seed": 7, "faults": [
        {"site": "single.stage_put", "kind": "transient", "times": 8}]}
    sched_path = os.path.join(out_dir, "flight_faults.json")
    with open(sched_path, "w") as f:
        json.dump(schedule, f)
    tel_path = os.path.join(out_dir, "flight_telemetry.prom")
    for stale in os.listdir(out_dir):
        if stale.startswith("FLIGHT_"):
            os.remove(os.path.join(out_dir, stale))
    with open(input_path, "rb") as stdin:
        proc = subprocess.run(
            [sys.executable, "-m", "dmlp_tpu",
             "--telemetry", tel_path, "--faults", sched_path],
            stdin=stdin, capture_output=True, timeout=300)
    if proc.returncode == 0:
        fail("faulted run unexpectedly succeeded — the flight-recorder "
             "trigger never fired")
    flights = [f for f in os.listdir(out_dir) if f.startswith("FLIGHT_")]
    if not flights:
        fail("no FLIGHT_*.json next to the telemetry file after a "
             "retries-exhausted fault")
    with open(os.path.join(out_dir, flights[0])) as f:
        doc = json.load(f)
    kinds = {e["kind"] for e in doc.get("events", [])}
    if not doc.get("events"):
        fail(f"flight artifact {flights[0]} has no events")
    if "fault" not in kinds:
        fail(f"flight artifact lacks the fault event (kinds: {kinds})")
    say(f"flight recorder OK: {flights[0]} with "
        f"{len(doc['events'])} events (kinds {sorted(kinds)}), "
        f"reason={doc['reason']!r}")


def emit_record(record_path: str, cfg, times, rec, overhead_pct):
    import dataclasses

    from dmlp_tpu.obs.run import RunRecord, round_from_name

    metrics = {
        "engine_ms_telemetry_off": round(statistics.median(times["off"])),
        "engine_ms_telemetry_off_reps": times["off"],
        "engine_ms_telemetry_on": round(statistics.median(times["on"])),
        "engine_ms_telemetry_on_reps": times["on"],
        "peak_hbm_model_bytes": rec["model_bytes"],
    }
    if overhead_pct is not None:
        metrics["telemetry_overhead_pct"] = round(overhead_pct, 2)
    else:
        metrics["telemetry_overhead_unavailable"] = \
            "off-arm median rounded to 0 ms"
    if "measured_bytes" in rec:
        metrics["peak_hbm_measured_bytes"] = rec["measured_bytes"]
        metrics["peak_hbm_model_vs_measured_pct"] = rec["delta_pct"]
        config_basis = rec["basis"]
    else:
        # Numeric marker so the ledger creates a visible
        # `telemetry/.../mem_stats_unavailable` series (string metrics
        # never become series points — a marker must not vanish from
        # the report); the human reason rides as a separate string.
        metrics["mem_stats_unavailable"] = 1
        metrics["mem_stats_unavailable_reason"] = \
            rec["mem_stats_unavailable"]
        config_basis = "unavailable"
    record = RunRecord(
        kind="telemetry", tool="tools.telemetry_smoke",
        config={**dataclasses.asdict(cfg), "watermark_basis": config_basis},
        metrics=metrics, device="cpu",
        round=round_from_name(record_path))
    record.append_jsonl(record_path)
    return record_path


def check_ledger_roundtrip(record_path: str) -> None:
    from dmlp_tpu.obs.ledger import ingest_file
    entry = ingest_file(record_path)
    if entry["status"] != "parsed":
        fail(f"telemetry RunRecord did not parse in the ledger: "
             f"{entry.get('error')}")
    series = {p["series"] for p in entry["points"]}
    want_sub = ("engine_ms_telemetry_on", "peak_hbm_model_bytes")
    for w in want_sub:
        if not any(w in s for s in series):
            fail(f"ledger series missing {w} (got {sorted(series)})")
    trialed = [p for p in entry["points"]
               if p.get("trials") and "telemetry_on" in p["series"]]
    if not trialed:
        fail("the on-arm engine_ms series carries no raw trial "
             "samples — the gate needs them")
    say(f"ledger ingestion OK: family={entry['family']}, "
        f"{len(entry['points'])} series points, raw trials attached")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="outputs/telemetry")
    ap.add_argument("--record", default=None,
                    help="append the smoke's RunRecord (JSONL) here")
    ap.add_argument("--pairs", type=int, default=2,
                    help="interleaved OFF/ON pairs")
    ap.add_argument("--config", type=int, default=1)
    args = ap.parse_args(argv)

    os.makedirs(args.out, exist_ok=True)
    cfg = BENCH_CONFIGS[args.config]
    if cfg.procs > 1:
        fail("telemetry smoke drives the single-process engine CLI")
    input_path = ensure_input(cfg, "inputs")

    # 1. contract byte-identity across interleaved OFF/ON pairs
    tel_path = os.path.join(args.out,
                            f"telemetry_config{args.config}.prom")
    times, outputs = run_ab(cfg, input_path, args.out, args.pairs,
                            tel_path)
    if len(outputs["off"]) != 1 or outputs["off"] != outputs["on"]:
        fail("stdout MISMATCH between telemetry-on and telemetry-off "
             "runs — the contract channel is not byte-identical")
    say(f"contract stdout byte-identical across {args.pairs} "
        f"interleaved pair(s)")

    med_off = statistics.median(times["off"])
    med_on = statistics.median(times["on"])
    overhead = ((med_on - med_off) / med_off * 100.0) if med_off > 0 \
        else None
    if overhead is not None:
        say(f"telemetry overhead {overhead:+.1f}% (median {med_off} -> "
            f"{med_on} ms; raw samples ride in the record — on this "
            "shared container the point estimate is weather)")

    # 2. OpenMetrics validity of the ON-arm snapshot
    check_openmetrics(tel_path)

    # 3. analytic peak-HBM model vs measured watermark
    rec = reconcile_in_process(cfg, input_path)

    # 4. flight recorder on a retries-exhausted fault
    check_flight_recorder(cfg, input_path, args.out)

    # 5. RunRecord + ledger round-trip
    if args.record:
        path = emit_record(args.record, cfg, times, rec, overhead)
        check_ledger_roundtrip(path)

    say("OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
