#!/usr/bin/env python
"""Wide-k multi-pass extract vs streaming fallback A/B (VERDICT r4 #2).

The r4 engine dropped ALL-wide-k inputs (every query's k beyond the
kernel's 512-slot window) to the streaming selects; r5 runs the kernel in
floor-raised multi-passes. This measures the payoff at the VERDICT's
shape (200k x 1k x 64, k=4096): the multipass engine vs an engine forced
onto the streaming select, interleaved reps, identical input, both
checksum-validated against each other.

Run in the DEFAULT env (real chip). CPU works too (interpret kernel) but
the numbers then measure the interpreter, not the kernel.

Usage: python tools/widek_speedup.py [--out WIDEK_MP_r05.json]
       [--n 204800 --q 1024 --a 64 --k 4096] [--reps 3]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="WIDEK_MP_r05.json")
    ap.add_argument("--n", type=int, default=204800)
    ap.add_argument("--q", type=int, default=1024)
    ap.add_argument("--a", type=int, default=64)
    ap.add_argument("--k", type=int, default=4096)
    ap.add_argument("--reps", type=int, default=3)
    args = ap.parse_args()

    import jax

    from dmlp_tpu.config import EngineConfig
    from dmlp_tpu.engine.single import SingleChipEngine
    from dmlp_tpu.io.grammar import KNNInput, Params

    rng = np.random.default_rng(9)
    n, q, a, k = args.n, args.q, args.a, args.k
    inp = KNNInput(Params(n, q, a),
                   rng.integers(0, 10, n).astype(np.int32),
                   rng.uniform(0, 100, (n, a)),
                   np.full(q, k, np.int32),
                   rng.uniform(0, 100, (q, a)))

    engines = {
        # multipass: select="extract" + wide k routes through the
        # floor-raised passes (hetk has no bulk to keep)
        "extract_multipass": SingleChipEngine(
            EngineConfig(select="extract", use_pallas=True)),
        # the r4 behavior: streaming select (what the input used to get)
        "streaming": SingleChipEngine(
            EngineConfig(select="seg", use_pallas=True)),
    }

    results = {}
    samples = {name: [] for name in engines}
    order = list(engines)
    for r in range(args.reps + 1):  # warmup round dropped
        for name in (order if r % 2 == 0 else order[::-1]):
            eng = engines[name]
            t0 = time.perf_counter()
            res = eng.run(inp)
            dt = (time.perf_counter() - t0) * 1e3
            if r > 0:
                samples[name].append(dt)
            results[name] = res

    # cross-validate: both paths must produce identical checksums
    cs_a = [r.checksum() for r in results["extract_multipass"]]
    cs_b = [r.checksum() for r in results["streaming"]]
    assert cs_a == cs_b, "paths disagree — BUG"

    rec = {"platform": jax.devices()[0].platform,
           "shape": [n, q, a], "k": k,
           "mp_passes": engines["extract_multipass"].last_mp_passes,
           "repairs": {name: int(e.last_repairs)
                       for name, e in engines.items()},
           "checksums_identical": True, "engines": {}}
    for name, ts in samples.items():
        rec["engines"][name] = {
            "median_ms": float(np.median(ts)),
            "min_ms": float(np.min(ts)),
            "phases_ms": {p: round(v, 1) for p, v in
                          engines[name].last_phase_ms.items()},
            "select": engines[name]._last_select}
    rec["multipass_vs_streaming_pct"] = round(100.0 * (
        rec["engines"]["extract_multipass"]["median_ms"]
        / rec["engines"]["streaming"]["median_ms"] - 1), 1)
    with open(args.out, "w") as f:
        json.dump(rec, f, indent=1)
    print(json.dumps(rec, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
