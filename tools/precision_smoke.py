#!/usr/bin/env python
"""Low-precision first-pass smoke (`make precision-smoke`): CI teeth
for the bf16 first pass + bound-carrying exact rescore, through the
REAL engine CLI.

Four invariants, each a hard failure:

1. **Byte identity, bf16 forced** — a norm-banded corpus solved with
   ``DMLP_TPU_PRECISION=bf16`` must produce contract stdout
   byte-identical to the ``DMLP_TPU_PRECISION=f32`` kill-switch run
   AND to the float64 golden model.
2. **Non-vacuity** — the bf16 arm's metrics summary must carry a
   ``precision`` block reporting ``active == "bf16"`` with a strictly
   positive ``kcap_inflation`` (the candidate window really widened by
   the lowp_eps margin); the f32 arm must report ``active == "f32"``
   with zero inflation. A "bf16" arm that silently ran f32 is an
   identical-code A/B masquerading as a feature.
3. **Ladder recovery** — under a seeded ``oom`` schedule the solve
   must step off the top ``lowp`` rung (``lowp -> prune`` in the
   metrics resilience block) and STILL produce byte-identical
   contract stdout: the degraded pass gives up the low-precision dot,
   never the answers.
4. **Kill switch** — the f32 arm's run IS the kill-switch path
   (``DMLP_TPU_PRECISION=f32`` under the same config), so invariant 1
   doubles as its regression test.

With ``--record FILE`` the bf16/f32 A/B also lands as a
kind="precision" RunRecord (ledger series ``precision/configbanded/
...``), the committed ``PRECISION_rNN.jsonl``'s banded row.

Usage: JAX_PLATFORMS=cpu python tools/precision_smoke.py \
       --out outputs/precision [--record .../PRECISION_SMOKE.jsonl]
       [--reps 2]
"""

from __future__ import annotations

import argparse
import json
import os
import re
import statistics
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def fail(msg: str) -> None:
    print(f"precision_smoke: FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def build_banded_input(path: str):
    """Seeded norm-banded corpus (the prune_smoke shape): 8 bands of
    2048 rows offset by +50, queries near band 0 — big enough that the
    extract path runs real multi-chunk solves, banded so the pruned
    stage the lowp rung rides stays non-vacuous too."""
    import numpy as np

    from dmlp_tpu.io.grammar import KNNInput, Params, format_input

    rng = np.random.default_rng(1807)
    n, nq, na, band = 16_384, 48, 8, 2048
    data = rng.uniform(0, 5, (n, na))
    for b in range(n // band):
        data[b * band:(b + 1) * band] += 50.0 * b
    inp = KNNInput(Params(n, nq, na),
                   rng.integers(0, 6, n).astype(np.int32), data,
                   rng.integers(1, 17, nq).astype(np.int32),
                   rng.uniform(0, 5, (nq, na)))
    with open(path, "w") as f:
        f.write(format_input(inp))


def run_cli(input_path: str, env_extra: dict, flags: list,
            timeout_s: float = 300.0, warmup: bool = True):
    """One engine CLI run; returns (stdout, stderr, wall_ms).
    ``warmup=False`` for fault-schedule runs — a warmup solve would
    consume the seeded fault before the measured solve sees it."""
    env = dict(os.environ)
    env.update(env_extra)
    argv = [sys.executable, "-m", "dmlp_tpu", "--select", "extract",
            "--data-block", "2048"] + (["--warmup"] if warmup else []) \
        + flags
    with open(input_path, "rb") as stdin:
        t0 = time.perf_counter()
        proc = subprocess.run(argv, stdin=stdin, capture_output=True,
                              env=env, timeout=timeout_s)
        wall_ms = (time.perf_counter() - t0) * 1e3
    if proc.returncode != 0:
        fail(f"engine CLI exited {proc.returncode}: "
             f"{proc.stderr.decode()[-1500:]}")
    return proc.stdout, proc.stderr.decode(), wall_ms


def last_summary(metrics_path: str) -> dict:
    with open(metrics_path) as f:
        recs = [json.loads(ln) for ln in f if ln.strip()]
    summaries = [r for r in recs if r.get("event") == "summary"]
    if not summaries:
        fail(f"{metrics_path}: no summary record")
    return summaries[-1]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="outputs/precision")
    ap.add_argument("--record", default=None, metavar="FILE",
                    help="append the bf16/f32 A/B as a "
                         "kind=\"precision\" RunRecord to FILE")
    ap.add_argument("--reps", type=int, default=2)
    args = ap.parse_args(argv)
    os.makedirs(args.out, exist_ok=True)

    from dmlp_tpu.golden.fast import knn_golden_fast
    from dmlp_tpu.io.grammar import parse_input_text
    from dmlp_tpu.io.report import format_results

    input_path = os.path.join(args.out, "banded.in")
    build_banded_input(input_path)
    with open(input_path) as f:
        inp = parse_input_text(f.read())
    golden = format_results(knn_golden_fast(inp)).encode()

    # -- arms: interleaved bf16/f32 reps -------------------------------------
    times = {"bf16": [], "f32": []}
    outs = {"bf16": set(), "f32": set()}
    mpaths = {a: os.path.join(args.out, f"metrics_{a}.jsonl")
              for a in times}
    for p in list(mpaths.values()):
        if os.path.exists(p):
            os.remove(p)
    for rep in range(max(args.reps, 1)):
        order = ("f32", "bf16") if rep % 2 == 0 else ("bf16", "f32")
        for arm in order:
            out_b, err, _ = run_cli(
                input_path, {"DMLP_TPU_PRECISION": arm},
                ["--metrics", mpaths[arm]])
            outs[arm].add(out_b)
            m = re.search(r"Time taken:\s*(\d+)", err)
            if not m:
                fail(f"{arm}-arm run has no timing line")
            times[arm].append(int(m.group(1)))

    # 1/4. byte identity: forced-bf16 vs the f32 kill switch vs golden
    if outs["bf16"] != {golden} or outs["f32"] != {golden}:
        fail("contract stdout differs between bf16/f32/golden — the "
             "low-precision pass changed answers")
    print("precision_smoke: bf16 and f32 arms byte-identical to the "
          "golden oracle")

    # 2. non-vacuity: the bf16 arm really ran bf16 with a widened window
    prec = {a: last_summary(mpaths[a]).get("precision") or {}
            for a in times}
    if not prec["bf16"] or not prec["f32"]:
        fail("metrics summaries carry no precision block")
    if prec["bf16"].get("active") != "bf16":
        fail(f"forced-bf16 arm reports active="
             f"{prec['bf16'].get('active')!r} — the A/B is vacuous")
    if not prec["bf16"].get("kcap_inflation", 0) > 0:
        fail("bf16 arm reports zero kcap inflation — the lowp_eps "
             "margin never reached the candidate window")
    if prec["f32"].get("active") != "f32" \
            or prec["f32"].get("kcap_inflation", 0) != 0:
        fail(f"f32 kill-switch arm reports {prec['f32']!r}")
    print(f"precision_smoke: bf16 arm active with kcap "
          f"{prec['f32'].get('kcap')} -> {prec['bf16'].get('kcap')} "
          f"(+{prec['bf16'].get('kcap_inflation')})")

    # 3. ladder recovery: seeded oom steps lowp -> prune, output intact
    sched_path = os.path.join(args.out, "oom_schedule.json")
    with open(sched_path, "w") as f:
        json.dump({"schema": 1, "seed": 7, "faults": [
            {"site": "single.stage_put", "kind": "oom", "times": 1}]}, f)
    oom_metrics = os.path.join(args.out, "metrics_oom.jsonl")
    if os.path.exists(oom_metrics):
        os.remove(oom_metrics)
    out_b, _, _ = run_cli(input_path, {"DMLP_TPU_PRECISION": "bf16"},
                          ["--metrics", oom_metrics,
                           "--faults", sched_path], warmup=False)
    if out_b != golden:
        fail("oom-schedule run stdout differs from golden — ladder "
             "recovery changed answers")
    res = last_summary(oom_metrics).get("resilience") or {}
    degs = res.get("degradations") or []
    if "lowp->prune" not in degs:
        fail(f"oom fired but the ladder recorded {degs!r}, expected a "
             "lowp->prune step")
    oom_prec = last_summary(oom_metrics).get("precision") or {}
    if oom_prec.get("active") == "bf16":
        fail("degraded run still reports an active bf16 pass — the "
             "lowp rung never actually stepped off")
    print(f"precision_smoke: seeded oom recovered via {degs} with "
          "byte-identical output")

    # -- optional ledger record ----------------------------------------------
    if args.record:
        from dmlp_tpu.obs.run import RunRecord, round_from_name
        RunRecord(
            kind="precision", tool="tools.precision_smoke",
            config={"config_id": "banded", "input": "banded.in",
                    "num_data": inp.params.num_data,
                    "num_queries": inp.params.num_queries,
                    "num_attrs": inp.params.num_attrs,
                    "select": "extract", "data_block": 2048},
            metrics={
                "engine_ms_bf16": round(statistics.median(
                    times["bf16"])),
                "engine_ms_bf16_reps": times["bf16"],
                "engine_ms_f32": round(statistics.median(
                    times["f32"])),
                "engine_ms_f32_reps": times["f32"],
                "precision_kcap_bf16": prec["bf16"].get("kcap"),
                "precision_kcap_f32": prec["f32"].get("kcap"),
                "precision_kcap_inflation":
                    prec["bf16"].get("kcap_inflation"),
                "precision_ab_identical": True,
            },
            device="cpu" if os.environ.get("JAX_PLATFORMS") == "cpu"
            else None,
            round=round_from_name(args.record)).append_jsonl(args.record)
        print(f"precision_smoke: banded A/B recorded to {args.record}")

    print("precision_smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
