#!/usr/bin/env python
"""Tie-adversarial differential fuzz: golden model vs the REAL binaries.

Requires the reference checkout's stripped engines, launched in-container
via Open MPI's isolated-singleton mode (no orted needed). Generates
tie-heavy adversarial instances (integer duplicate grids, k = n,
near-duplicate clusters, plus continuous controls), runs each through
bench_1..4 AND the golden model, and diffs the checksum sets.

This is the experiment that MEASURED the reference's true tie semantics
(r5): selection ties break to the larger id, label-free — bench_1/2/3
match the golden model exactly under that comparator, while bench_4
breaks report ties id-ASCENDING, disagreeing with its own siblings (its
mismatches are recorded per-case, expected, and counted separately).
On tie-free inputs (every graded benchmark input) all five
implementations coincide.

Usage:
  python tools/fuzz_vs_binaries.py [--seeds 3000:3100]
      [--ref /root/reference] [--out TIE_SEMANTICS_r05.json]
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def gen(seed: int):
    from dmlp_tpu.io.grammar import KNNInput, Params, format_input, \
        parse_input_text
    rng = np.random.default_rng(seed)
    style = ["intdup", "uniform", "k_eq_n", "clustered"][seed % 4]
    n = int(rng.integers(3, 120))
    nq = int(rng.integers(1, 12))
    na = int(rng.integers(1, 6))
    if style == "intdup":
        data = rng.integers(0, 3, (n, na)).astype(np.float64)
        queries = rng.integers(0, 3, (nq, na)).astype(np.float64)
    elif style == "clustered":
        c = rng.uniform(-5, 5, (1, na))
        data = c + rng.normal(0, 1e-3, (n, na))
        queries = c + rng.normal(0, 1e-3, (nq, na))
    else:
        data = rng.uniform(-9, 9, (n, na))
        queries = rng.uniform(-9, 9, (nq, na))
    data, queries = data.round(6), queries.round(6)
    labels = rng.integers(0, int(rng.integers(1, 5)), n).astype(np.int32)
    ks = (np.full(nq, n, np.int32) if style == "k_eq_n"
          else rng.integers(1, n + 1, nq).astype(np.int32))
    return style, parse_input_text(format_input(
        KNNInput(Params(n, nq, na), labels, data, ks, queries)))


def lines(s: str):
    return sorted(l for l in s.splitlines() if l.strip())


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seeds", default="3000:3100")
    ap.add_argument("--ref", default="/root/reference")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    lo, hi = (int(x) for x in args.seeds.split(":"))

    from dmlp_tpu.golden.reference import knn_golden
    from dmlp_tpu.io.grammar import format_input
    from dmlp_tpu.io.report import format_results

    env = dict(os.environ, OMPI_MCA_ess_singleton_isolated="1")
    benches = {b: os.path.join(args.ref, "benchmarks", b)
               for b in ("bench_1", "bench_2", "bench_3", "bench_4")}
    for b, p in benches.items():
        if not os.path.exists(p):
            print(f"FATAL: {p} missing (need the reference checkout)")
            return 1

    mismatch = {b: 0 for b in benches}
    tie_cases = 0
    cases = 0
    for seed in range(lo, hi):
        style, inp = gen(seed)
        text = format_input(inp).encode()
        want = lines(format_results(knn_golden(inp)))
        cases += 1
        per_case = {}
        for b, p in benches.items():
            r = subprocess.run([p], input=text, capture_output=True,
                               env=env, timeout=120)
            per_case[b] = lines(r.stdout.decode()) == want
            if not per_case[b]:
                mismatch[b] += 1
        if not all(per_case.values()):
            tie_cases += 1
            if any(not per_case[b] for b in ("bench_1", "bench_2",
                                             "bench_3")):
                print(f"UNEXPECTED b1-3 mismatch seed={seed} style={style} "
                      f"{dict(per_case)}")
    summary = {
        "seeds": f"{lo}:{hi}", "cases": cases,
        "golden_mismatches_per_binary": mismatch,
        "semantics": "selection + report ties -> larger id (label-free); "
                     "vote ties -> larger label",
        "note": "bench_4 breaks report ties id-ASC — measured to disagree "
                "with bench_1/2/3 on the same inputs; its mismatch count "
                "is the tie-case count, not a golden defect. b1/2/3 "
                "mismatches should be 0.",
    }
    print(json.dumps(summary, indent=1))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(summary, f, indent=1)
    return 1 if any(mismatch[b] for b in ("bench_1", "bench_2",
                                          "bench_3")) else 0


if __name__ == "__main__":
    sys.exit(main())
