"""Offload-policy ladder benchmark: MFU at each host-DRAM offload level.

OFFLOAD_DECOMP_r04.json showed the "all" level (params + moments streamed
both ways, ~1.34 GB/step) is bounded by the ~5 GB/s host DMA path. The
"params" level (train.loop.resolve_offload_level) keeps moments
HBM-resident and halves the stream bytes — this tool measures the whole
ladder at the same shape so the capacity-vs-speed trade is a recorded
fact, not a claim:

    none    params    all        <- offload level
    most HBM ........ least HBM
    fastest ......... stream-bound

Writes a schema RunRecord (obs.run) to TRAINBENCH_r06_ladder.json —
ledger-ingestible (python -m dmlp_tpu.report); the r04 ad-hoc shape is
grandfathered. Env: TRAIN_DIMS, TRAIN_BATCH, TRAIN_STEPS, TRAIN_DTYPE,
BENCH_OUT as in train.bench.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import jax
    import jax.numpy as jnp

    from dmlp_tpu.train.bench import _env_int
    from dmlp_tpu.train.data import teacher_batches
    from dmlp_tpu.train.loop import build_sharded_state
    from dmlp_tpu.train.metrics import (peak_flops_per_chip,
                                        throughput_metrics)
    from dmlp_tpu.train.sharding import batch_shardings, make_train_mesh
    from dmlp_tpu.train.step import (make_offload_train_step, make_optimizer,
                                     make_train_step, supports_injit_offload)

    dims = tuple(int(d) for d in os.environ.get(
        "TRAIN_DIMS", "1024,8192,8192,1024").split(","))
    batch = _env_int("TRAIN_BATCH", 32768)
    steps = _env_int("TRAIN_STEPS", 30)
    dtype = os.environ.get("TRAIN_DTYPE", "bfloat16")
    out_path = os.environ.get("BENCH_OUT", "TRAINBENCH_r06_ladder.json")
    cdtype = jnp.bfloat16 if dtype == "bfloat16" else None

    mesh = make_train_mesh(None)
    n_chips = mesh.devices.size
    optimizer = make_optimizer("sgd", 1e-2)
    xsh, ysh = batch_shardings(mesh)
    data = teacher_batches(dims[0], dims[-1], batch, seed=1)
    batches = []
    for _ in range(4):
        x, y = next(data)
        batches.append((jax.device_put(x, xsh), jax.device_put(y, ysh)))

    def timed(step_fn, state):
        for i in range(3):
            state, m = step_fn(state, *batches[i % 4])
        jax.device_get(m["loss"])
        t0 = time.perf_counter()
        for i in range(steps):
            state, m = step_fn(state, *batches[i % 4])
        jax.device_get(m["loss"])
        return (time.perf_counter() - t0) / steps, state

    def host_bytes(state, level):
        leaves = []
        if level in ("params", "all"):
            leaves += jax.tree.leaves(state["params"])
        if level == "all":
            leaves += jax.tree.leaves(state["opt"])
        return sum(a.size * a.dtype.itemsize for a in leaves)

    rows = []
    for level in ("none", "params", "all"):
        state = build_sharded_state(mesh, dims, optimizer, offload=level)
        if level == "none":
            step_fn = make_train_step(optimizer, cdtype)
        else:
            step_fn = make_offload_train_step(optimizer, cdtype, state)
        dt, state = timed(step_fn, state)
        tm = throughput_metrics(state["params"], batch, dt, n_chips)
        rows.append({
            "offload": level,
            "step_time_ms": round(dt * 1e3, 2),
            "mfu": round(tm["mfu"], 4),
            "samples_per_sec_per_chip": round(
                tm["samples_per_sec_per_chip"], 1),
            "streamed_bytes_each_way": host_bytes(state, level),
        })
        print(json.dumps(rows[-1]), flush=True)
        del state

    from dmlp_tpu.obs.run import RunRecord, round_from_name

    metrics: dict = {}
    for row in rows:
        lvl = row["offload"]
        for key in ("step_time_ms", "mfu", "samples_per_sec_per_chip",
                    "streamed_bytes_each_way"):
            metrics[f"{lvl}_{key}"] = row[key]
    RunRecord(
        kind="train", tool="tools.bench_offload_ladder",
        config={"note": "Host-DRAM offload ladder at one shape (same "
                        "batch for every level): 'params' keeps "
                        "optimizer moments HBM-resident, halving the "
                        "per-step stream bytes of 'all'; streamed_bytes "
                        "is the one-way host->HBM traffic per step.",
                "dims": list(dims), "batch": batch, "steps": steps,
                "dtype": dtype, "n_chips": int(n_chips),
                "injit_offload": bool(supports_injit_offload()),
                "peak_tflops_per_chip":
                    round(peak_flops_per_chip() / 1e12, 1)},
        metrics=metrics,
        device=str(getattr(jax.devices()[0], "device_kind",
                           jax.devices()[0].platform)),
        round=round_from_name(out_path)).write(out_path)
    print(json.dumps({"written": out_path}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
