#!/usr/bin/env python
"""Regression sentinel: fail CI when a tracked perf series regresses.

The ``make perf-gate`` checker. Builds the perf ledger (dmlp_tpu.obs
.ledger) over the repo root and compares, for every GATED series, the
latest round against the previous one:

- a series gates only when the comparison is QUALIFIED: both rounds on
  the same device, both carrying >= MIN_TRIALS per-trial samples (the
  noise band needs raw trials — a single-shot number on the tunneled
  link measures weather, not the engine);
- a qualified regression beyond the noise band
  (``compare_points(...)["regressed"]``) FAILS the gate, naming the
  series, rounds, medians, and band;
- unqualified comparisons (``insufficient_trials``,
  ``device_mismatch``) and improvements are REPORTED, never failed —
  honest markers instead of silent skips or false alarms.

Gated series are the timing series with per-trial evidence: the
harness suite (``harness/config*/engine_ms``) and any RunRecord
series whose points carry trials (new ``*_r06+`` rounds are
ledger-ingestible by construction, so landing a regressed round at
the repo root trips the gate with no extra wiring).

Usage: python tools/perf_gate.py [--root .] [--json] [--min-rounds 2]
Exit 0 = no qualified regression; 1 = at least one; 2 = usage.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dmlp_tpu.obs.ledger import build_ledger, series_deltas  # noqa: E402

#: series-name prefixes the gate acts on (timing series with trials);
#: everything else in the ledger is report-only. Legacy families
#: (harness/, bench/, trainbench/) double as the CONTINUED names of the
#: migrated RunRecord emitters (obs.ledger._runrecord_series_name), so
#: the r05->r06 transition keeps its round-over-round comparison; the
#: "{kind}:" prefixes catch RunRecord series with no legacy ancestor.
GATED_PREFIXES = ("harness/", "bench:", "bench/", "trainbench/", "serve/",
                  "fleet/", "slo/",
                  "train:", "engine:", "roofline:", "capacity:",
                  "telemetry/", "prune/", "precision/", "auto/", "hlo/")


def gated(series: str, better: str = "lower") -> bool:
    return (series.startswith(GATED_PREFIXES)
            and better in ("lower", "higher"))


def run_gate(root: str = ".", min_rounds: int = 2,
             ledger: dict = None) -> dict:
    """-> {"regressions": [...], "improvements": [...], "unqualified":
    [...], "checked": N} over every multi-round series. Pass a
    pre-built ``ledger`` (e.g. the LEDGER.json ``dmlp_tpu.report
    --out`` wrote) to skip re-parsing every artifact."""
    if ledger is None:
        ledger = build_ledger(root)
    out = {"regressions": [], "improvements": [], "unqualified": [],
           "within_noise": [], "checked": 0,
           "coverage": ledger["coverage"]}
    for cmp in series_deltas(ledger, min_rounds=min_rounds):
        pts = ledger["series"].get(cmp["series"], [])
        better = pts[-1].get("better", "lower") if pts else "lower"
        if not gated(cmp["series"], better):
            continue
        out["checked"] += 1
        if cmp.get("marker"):
            out["unqualified"].append(cmp)
        elif cmp.get("regressed"):
            out["regressions"].append(cmp)
        elif cmp.get("improved"):
            out["improvements"].append(cmp)
        else:
            out["within_noise"].append(cmp)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--root", default=".",
                    help="directory scanned for perf artifacts")
    ap.add_argument("--ledger", default=None, metavar="LEDGER.json",
                    help="gate a pre-built ledger document "
                         "(dmlp_tpu.report --out) instead of "
                         "re-parsing the artifacts")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable verdict on stdout")
    ap.add_argument("--min-rounds", type=int, default=2)
    args = ap.parse_args(argv)

    ledger = None
    if args.ledger:
        with open(args.ledger) as f:
            ledger = json.load(f)
    res = run_gate(args.root, min_rounds=args.min_rounds, ledger=ledger)
    if args.json:
        print(json.dumps(res, indent=1, sort_keys=True))
    else:
        for cmp in res["unqualified"]:
            print(f"perf_gate: note — {cmp['series']} "
                  f"r{cmp['prev_round']}→r{cmp['cur_round']}: "
                  f"{cmp.get('delta_pct', 'n/a')}% ({cmp['marker']})")
        for cmp in res["improvements"]:
            print(f"perf_gate: improved — {cmp['series']} "
                  f"r{cmp['prev_round']}→r{cmp['cur_round']}: "
                  f"{cmp['delta_pct']:+.1f}% beyond ±{cmp['noise_band']}")
        for cmp in res["within_noise"]:
            print(f"perf_gate: ok — {cmp['series']} "
                  f"r{cmp['prev_round']}→r{cmp['cur_round']}: "
                  f"{cmp.get('delta_pct', 0):+.1f}% within "
                  f"±{cmp['noise_band']}")
    if res["regressions"]:
        for cmp in res["regressions"]:
            print(f"perf_gate: FAIL: {cmp['series']} regressed "
                  f"r{cmp['prev_round']}→r{cmp['cur_round']}: median "
                  f"{cmp['median_prev']} → {cmp['median_cur']} "
                  f"({cmp['delta_pct']:+.1f}%), beyond the noise band "
                  f"±{cmp['noise_band']}", file=sys.stderr)
        return 1
    print(f"perf_gate: {res['checked']} gated series checked — "
          f"{len(res['regressions'])} regressions, "
          f"{len(res['improvements'])} improvements, "
          f"{len(res['unqualified'])} unqualified "
          "(insufficient trials / device mismatch)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
