#!/usr/bin/env python
"""SLO smoke: prove the streaming SLO engine end to end on CPU.

The ``make slo-smoke`` checker (wired into ``make test``). Two parts,
every failure exits nonzero with the reason named:

**Part 1 — seeded breach, deterministic clock.** An in-process
``SLOEvaluator`` over a private registry is driven with a fake clock:
a healthy plateau, an 8-sample latency spike, then recovery. Exactly
one alert cycle must fire — ``ok -> pending -> firing -> ok`` — with
the ``slo.alert`` flight events captured in a ``FLIGHT_slo_breach_*``
post-mortem dump and the ``slo_*`` gauge families present in a clean
OpenMetrics exposition.

**Part 2 — predictive-vs-reactive ramp A/B over a REAL fleet.** A
``serve.solve`` delay fault (the chaos harness's straggler-solve
site) gives each replica a deterministic sleep-bound service-time
floor, so on this single-core container a second replica genuinely
doubles fleet capacity. After calibrating the real per-replica
capacity, the same escalating open-loop ramp (mid level ~1.15x one
replica's capacity, hot level ~1.6x) is replayed against two
supervised fleets that declare the same two objectives — the
CUSTOMER objective (``fleet.request_latency_ms p99 < T over 60s``)
and a tighter internal CANARY (``p95 < T_low``):

* the **reactive** arm (watermark policy, threshold pinned out of
  reach) rides one replica into the hot level: the customer p99
  objective must reach ``firing`` — the breach alert, traced as
  ``slo.alert`` instants that ``tools/check_trace.py --fleet``
  validates after the causal merge;
* the **predictive** arm follows the canary's burn rate
  (``--slo-objective``): the mid level burns the canary, the policy
  scales to two replicas during the lead window, and the hot level
  lands with the customer objective never leaving ``ok``.

Both arms first replay a closed-loop slice byte-identical to the
float64 golden oracle (observability must not perturb the contract
channel). Each arm lands one kind="slo" RunRecord; the ledger must
round-trip them as gated ``slo/<arm>/...`` series.

Usage::

    python tools/slo_smoke.py --out outputs/slo \
        [--record outputs/slo/SLO_SMOKE.jsonl] [--round 17]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import subprocess
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from dmlp_tpu.fleet import harness as fh                  # noqa: E402
from dmlp_tpu.fleet import loadgen                        # noqa: E402
from dmlp_tpu.io.grammar import parse_input_text          # noqa: E402
from dmlp_tpu.obs import slo as slomod                    # noqa: E402
from dmlp_tpu.obs import telemetry                        # noqa: E402
from dmlp_tpu.obs.ledger import ingest_file               # noqa: E402
from dmlp_tpu.obs.telemetry import validate_openmetrics   # noqa: E402
from dmlp_tpu.serve import client as sc                   # noqa: E402

import perf_gate                                          # noqa: E402

# -- part-2 capacity model ----------------------------------------------------
# The injected straggler-solve delay makes each replica's micro-batch
# consumer sleep D_MS per batch: nominal per-replica capacity is
# BATCH_CAP queries per D_MS, i.e. ~213 q/s — sleep-bound, so a
# second replica genuinely adds fleet throughput even on one CPU
# core (the real solve + router + client CPU share stays well under
# the core at every ramp level).
D_MS = 150
BATCH_CAP = 32
NQ = 16                       # queries per request (2 requests/batch)
K = 8
C0 = BATCH_CAP * 1000.0 / D_MS

CORPUS = dict(num_data=2048, num_queries=NQ, num_attrs=8,
              min_attr=0.0, max_attr=60.0, min_k=1, max_k=16,
              num_labels=10, seed=1717)
HEADER = {"serve_trace_schema": 1, "corpus": CORPUS}

MID_FACTOR = 1.15             # mid level: x calibrated capacity
HOT_FACTOR = 1.60             # hot level: x calibrated capacity
RAMP_S = 10.0                 # seconds of send per level (at x1)
LEAD_SETTLE_S = 20.0          # mid -> hot gap both arms get
OBJ_ID = "fleet.request_latency_ms:p99"
CANARY_ID = "fleet.request_latency_ms:p95"


def fail(msg: str):
    print(f"slo_smoke: FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def say(msg: str) -> None:
    print(f"slo_smoke: {msg}")


# -- part 1: seeded breach on a deterministic clock ---------------------------

def part1_seeded_breach(out: str) -> None:
    p1 = os.path.join(out, "part1")
    os.makedirs(p1, exist_ok=True)
    sc.clear_flight_dumps(p1)
    session = telemetry.start(
        path=os.path.join(p1, "telemetry.prom"), flight_dir=p1,
        handle_signals=False)
    try:
        clock = {"t": 0.0}
        reg = telemetry.Registry()
        ev = slomod.SLOEvaluator(
            ["svc.latency_ms p99 < 100 over 60s"], reg,
            fast_s=10.0, sub_s=1.0, for_ticks=2, clear_ticks=3,
            time_fn=lambda: clock["t"], flight_dump=True)
        h = reg.histogram("svc.latency_ms", unit="ms")  # check: allow-metric-name — smoke-local series
        obj = "svc.latency_ms:p99"

        def drive(until_s: float, value_ms: float) -> None:
            while clock["t"] < until_s:
                clock["t"] += 0.5
                h.observe(value_ms)
                ev.tick()

        drive(20.0, 10.0)                 # healthy plateau
        if ev.state(obj) != "ok" or ev.transitions:
            fail(f"healthy plateau not ok: state={ev.state(obj)} "
                 f"transitions={ev.transitions}")
        drive(26.0, 500.0)                # the seeded breach
        if ev.state(obj) != "firing":
            fail(f"seeded breach did not fire: {ev.snapshot()}")
        om_hot = reg.to_openmetrics()
        drive(100.0, 10.0)                # recovery + window drain
        if ev.state(obj) != "ok":
            fail(f"breach did not clear by t=100: {ev.snapshot()}")

        edges = [(t["prev"], t["state"]) for t in ev.transitions]
        if edges != [("ok", "pending"), ("pending", "firing"),
                     ("firing", "ok")]:
            fail(f"expected exactly one alert cycle, got {edges}")
        if ev.alert_cycles(obj) != 1:
            fail(f"alert_cycles != 1: {ev.alert_cycles(obj)}")

        probs = validate_openmetrics(om_hot)
        if probs:
            fail(f"mid-breach exposition invalid: {probs}")
        for fam in ("slo_state", "slo_firing", "slo_burn_rate_fast",
                    "slo_trend_slope_ms_per_s"):
            if fam not in om_hot:
                fail(f"family {fam} missing from the mid-breach "
                     "exposition")
        firing_lines = [ln for ln in om_hot.splitlines()
                        if ln.startswith("slo_firing")
                        and ln.rstrip().endswith(" 1")]
        if not firing_lines:
            fail("slo_firing gauge not 1 while firing")

        dumps = glob.glob(os.path.join(p1, "FLIGHT_slo_breach_*.json"))
        if len(dumps) != 1:
            fail(f"expected exactly one breach flight dump, got "
                 f"{dumps}")
        with open(dumps[0]) as f:
            doc = json.load(f)
        alerts = [e for e in doc.get("events", [])
                  if e.get("name") == "slo.alert"]
        if not alerts:
            fail(f"flight dump {dumps[0]} holds no slo.alert events")
    finally:
        session.close()
    say("part 1 OK: seeded breach fired exactly one alert cycle "
        "(ok->pending->firing->ok), flight dump + slo_* exposition "
        "captured")


# -- part 2: the ramp A/B over a real fleet -----------------------------------

def synth_trace(rate_qps: float, dur_s: float, seed0: int) -> list:
    """Evenly paced open-loop trace at ``rate_qps`` queries/s, NQ
    queries (k=K) per request."""
    req_rate = rate_qps / NQ
    n = max(int(round(dur_s * req_rate)), 1)
    return [{"t_ms": int(round(i * 1000.0 / req_rate)), "nq": NQ,
             "ks": [K] * NQ, "seed": seed0 + i} for i in range(n)]


def write_faults(out: str) -> str:
    path = os.path.join(out, "faults.json")
    with open(path, "w") as f:
        json.dump({"schema": 1, "seed": 1, "faults": [
            {"site": "serve.solve", "kind": "delay", "ms": D_MS,
             "times": 10 ** 9, "prob": 1.0}]}, f)
    return path


def calibrate(out: str, corpus_path: str, warm: str,
              faults: str) -> dict:
    """Measure the fault-slowed serving path THROUGH the router (the
    same topology the ramp arms run): healthy p95/p99 and the real
    saturated single-replica fleet capacity the ramp levels are
    scaled from — daemon-direct numbers overestimate what the routed
    path can carry."""
    proc, doc, armdir, errlog = spawn_arm(
        "calib", out, corpus_path, warm,
        spawn_flags=f"--faults {faults}", slo_specs=[],
        policy_args=["--policy", "reactive",
                     "--scale-high", "1000000000"],
        router_args=[])
    port = doc["port"]
    try:
        healthy = synth_trace(0.4 * C0, 3.0, 100_000)
        hm = loadgen.run_level(port, HEADER, healthy, 1.0)
        sat = synth_trace(2.5 * C0, 4.0, 200_000)
        sm = loadgen.run_level(port, HEADER, sat, 1.0)
    finally:
        drain_arm("calib", proc, port, errlog)
    if hm.get("errors") or hm.get("rejected"):
        fail(f"calibration healthy level had failures: {hm}")
    if sm.get("errors") or sm.get("rejected"):
        fail(f"calibration saturation level had failures: {sm}")
    c_real = float(sm.get("achieved_qps") or 0.0)
    if not (0.3 * C0 <= c_real <= 1.2 * C0):
        fail(f"calibrated capacity {c_real} q/s outside "
             f"[{0.3 * C0}, {1.2 * C0}] — the serve.solve delay "
             "fault is not bounding service time as designed")
    if hm["p95_ms"] > 1200.0:
        fail(f"healthy p95 {hm['p95_ms']} ms — the box is too loaded "
             "for a meaningful SLO baseline")
    return {"c_real": c_real, "h_p95": hm["p95_ms"],
            "h_p99": hm["p99_ms"]}


def spawn_arm(arm: str, out: str, corpus_path: str, warm: str,
              spawn_flags: str, slo_specs: list, policy_args: list,
              router_args: list):
    armdir = os.path.join(out, arm)
    os.makedirs(armdir, exist_ok=True)
    ready = os.path.join(armdir, "router_ready.json")
    errlog = os.path.join(armdir, "router.err")
    if os.path.exists(ready):
        os.remove(ready)
    cmd = [sys.executable, "-m", "dmlp_tpu.fleet",
           "--spawn-corpus", corpus_path,
           "--spawn-replicas", "1", "--max-replicas", "2",
           "--out-dir", armdir, "--spawn-warm", warm,
           "--spawn-batch-cap", str(BATCH_CAP),
           "--spawn-flags", spawn_flags,
           "--poll-s", "0.25", "--health-interval-s", "0.25",
           # The ramp is a CONTROLLED overload: pin the self-healing
           # reflexes (shard re-split, hung-replica relaunch) out of
           # reach so the only capacity change is the one the scaling
           # policy under test makes.
           "--reshard-threshold", "10",
           "--unhealthy-deadline-s", "120",
           "--port", "0", "--ready-file", ready,
           "--telemetry-port", "0"]
    for spec in slo_specs:
        cmd += ["--slo", spec]
    cmd += policy_args + router_args
    with open(errlog, "w") as ef:
        proc = subprocess.Popen(cmd, stderr=ef,
                                stdout=subprocess.DEVNULL,
                                env=fh._repo_env(), cwd=armdir)
    doc = sc.await_ready(proc, ready, timeout_s=900, errlog=errlog)
    return proc, doc, armdir, errlog


def router_stats(port: int) -> dict:
    cli = sc.ServeClient(port)
    try:
        return cli.stats()["stats"]
    finally:
        cli.close()


def drain_arm(arm: str, proc, port: int, errlog: str) -> None:
    cli = sc.ServeClient(port)
    try:
        cli.drain()
    finally:
        cli.close()
    try:
        rc = proc.wait(timeout=180)
    except subprocess.TimeoutExpired:
        proc.kill()
        fail(f"{arm} arm router did not exit after drain; "
             f"see {errlog}")
    if rc != 0:
        tail = ""
        if os.path.exists(errlog):
            tail = open(errlog).read()[-500:]
        fail(f"{arm} arm router exited {rc}: {tail}")


def run_arm(arm: str, proc, doc, errlog: str, ramp_reqs: list,
            hot_speed: float, golden_txt: str, golden_reqs: list,
            predictive: bool) -> dict:
    port = doc["port"]

    # contract channel first: closed-loop slice vs the golden oracle
    # (connections=1 keeps the good-latency slice under the canary
    # threshold — warm-up traffic must not pre-trip the policy).
    res = sc.replay(port, HEADER, golden_reqs, connections=1)
    bad = [r for r in res if not r.get("ok")]
    if bad:
        fail(f"{arm} arm closed-loop replay failed: {bad[0]}")
    if sc.contract_text([r["checksums"] for r in res]) != golden_txt:
        fail(f"{arm} arm responses differ from the golden oracle")

    steps = loadgen.run_ramp(port, HEADER, ramp_reqs, [1.0])
    mid = steps[0]
    if mid["metrics"].get("errors") or mid["metrics"].get("rejected"):
        fail(f"{arm} arm mid level had failures: {mid['metrics']}")

    # the lead window the predictive policy is buying: it must land
    # its scale-up HERE, before the hot level arrives.
    t0 = time.monotonic()
    if predictive:
        while True:
            st = router_stats(port)
            if int(st.get("healthy_replicas") or 0) >= 2:
                break
            if proc.poll() is not None:
                fail(f"{arm} arm router died mid-ramp; see {errlog}")
            if time.monotonic() - t0 > 150:
                fail(f"{arm} arm: predictive scale-up not ready "
                     f"within 150s; stats={json.dumps(st)[:500]}")
            time.sleep(1.0)
        say(f"{arm} arm: scale-up ready "
            f"{round(time.monotonic() - t0, 1)}s into the lead "
            "window")
    pad = LEAD_SETTLE_S - (time.monotonic() - t0)
    if pad > 0:
        time.sleep(pad)

    steps += loadgen.run_ramp(port, HEADER, ramp_reqs, [hot_speed])
    hot = steps[-1]
    if hot["metrics"].get("errors") or hot["metrics"].get("rejected"):
        fail(f"{arm} arm hot level had failures: {hot['metrics']}")

    om = ""
    tport = doc.get("telemetry_port")
    if tport:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{tport}/metrics", timeout=10) as r:
            om = r.read().decode()
        probs = validate_openmetrics(om)
        if probs:
            fail(f"{arm} arm /metrics invalid: {probs}")
        for fam in ("slo_state", "slo_burn_rate_fast"):
            if fam not in om:
                fail(f"{arm} arm /metrics lacks {fam}")
    st = router_stats(port)
    scale = st.get("scale") or {}
    for reflex in ("relaunches", "splits", "crashes"):
        if scale.get(reflex):
            fail(f"{arm} arm: self-healing reflex {reflex}="
                 f"{scale[reflex]} moved during the controlled ramp")
    drain_arm(arm, proc, port, errlog)
    return {"steps": steps, "stats": st, "mid": mid, "hot": hot}


def check_reactive_trace(armdir: str) -> dict:
    tools = os.path.dirname(os.path.abspath(__file__))
    merged = os.path.join(armdir, "trace-fleet-merged.json")
    rc = subprocess.call(
        [sys.executable, os.path.join(tools, "merge_traces.py"),
         armdir, "--fleet", "-o", merged], env=fh._repo_env())
    if rc != 0:
        fail("merge_traces --fleet failed on the reactive arm")
    cp = subprocess.run(
        [sys.executable, os.path.join(tools, "check_trace.py"),
         "--fleet", merged, "--json"],
        capture_output=True, text=True, env=fh._repo_env())
    if cp.returncode != 0:
        fail(f"check_trace --fleet rejected the reactive-arm trace: "
             f"{cp.stderr.strip()[-500:]}")
    verdict = json.loads(cp.stdout)
    alerts = verdict.get("slo_alerts") or {}
    n = sum(int(v) for v in alerts.values()) if isinstance(
        alerts, dict) else int(alerts or 0)
    if n < 2:
        fail(f"merged reactive trace carries {n} slo.alert events, "
             "expected the pending+firing pair at least")
    return verdict


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="outputs/slo")
    ap.add_argument("--record", default=None)
    ap.add_argument("--round", type=int, default=17)
    args = ap.parse_args(argv)
    out = os.path.abspath(args.out)
    os.makedirs(out, exist_ok=True)
    record = os.path.abspath(args.record) if args.record \
        else os.path.join(out, "SLO_SMOKE.jsonl")
    if os.path.exists(record):
        os.remove(record)

    part1_seeded_breach(out)

    # -- part 2 setup ---------------------------------------------------------
    corpus_txt = sc.corpus_text(HEADER)
    corpus_path = os.path.join(out, "corpus.in")
    with open(corpus_path, "w") as f:
        f.write(corpus_txt)
    corpus = parse_input_text(corpus_txt)
    faults = write_faults(out)
    probe = synth_trace(C0, 1.0, 1)
    warm = ",".join(f"{q}x{k}" for q, k in
                    sc.warm_buckets_for_trace(probe, BATCH_CAP))

    cal = calibrate(out, corpus_path, warm, faults)
    c_real = cal["c_real"]
    t_low = round(max(2.0 * cal["h_p95"], 150.0), 1)
    t_obj = round(cal["h_p99"] + 2400.0, 1)
    hot_speed = round(HOT_FACTOR / MID_FACTOR, 3)
    ramp_reqs = synth_trace(MID_FACTOR * c_real, RAMP_S, 300_000)
    golden_reqs = ramp_reqs[:8]
    golden_txt = sc.contract_text(
        sc.golden_reference(corpus, HEADER, golden_reqs))
    say(f"calibrated: capacity {c_real} q/s (nominal {C0}), healthy "
        f"p95 {cal['h_p95']} / p99 {cal['h_p99']} ms -> objective "
        f"p99<{t_obj}ms, canary p95<{t_low}ms; ramp x1="
        f"{round(MID_FACTOR * c_real, 1)} q/s, hot x{hot_speed}")

    slo_specs = [f"fleet.request_latency_ms p99 < {t_obj} over 60s",
                 f"fleet.request_latency_ms p95 < {t_low} over 60s"]

    # reactive arm: watermark policy pinned out of reach — the lagging
    # baseline that rides one replica into the breach. Fully traced.
    rdir_flags = None
    proc, doc, armdir, errlog = spawn_arm(
        "reactive", out, corpus_path, warm,
        spawn_flags=f"--faults {faults} --trace "
                    f"{os.path.join(out, 'reactive', 'trace-replica00.json')}",
        slo_specs=slo_specs,
        policy_args=["--policy", "reactive",
                     "--scale-high", "1000000000"],
        router_args=["--trace",
                     os.path.join(out, "reactive",
                                  "trace-router.json")])
    rdir_flags = armdir
    reactive = run_arm("reactive", proc, doc, errlog, ramp_reqs,
                       hot_speed, golden_txt, golden_reqs,
                       predictive=False)

    # predictive arm: follows the canary's burn rate for the lead.
    proc, doc, armdir, errlog = spawn_arm(
        "predictive", out, corpus_path, warm,
        spawn_flags=f"--faults {faults}",
        slo_specs=slo_specs,
        policy_args=["--policy", "predictive",
                     "--slo-objective", CANARY_ID,
                     "--lead-time-s", "15"],
        router_args=[])
    predictive = run_arm("predictive", proc, doc, errlog, ramp_reqs,
                         hot_speed, golden_txt, golden_reqs,
                         predictive=True)

    # -- the A/B contract -----------------------------------------------------
    recs = {}
    for arm, res in (("reactive", reactive),
                     ("predictive", predictive)):
        rec = loadgen.ramp_record(arm, OBJ_ID, res["steps"],
                                  replicas=1, trace="slo_ramp",
                                  tool="tools.slo_smoke")
        rec.round = args.round
        rec.append_jsonl(record)
        recs[arm] = rec

    rm, pm = recs["reactive"].metrics, recs["predictive"].metrics
    if rm["breach_cycles"] < 1 or rm["worst_state_level"] != 2:
        fail(f"reactive arm never fired the breach: {rm}")
    if rm["replicas_final"] != 1:
        fail(f"reactive arm scaled ({rm}) — the watermark was "
             "supposed to stay out of reach")
    if reactive["hot"]["metrics"]["p99_ms"] <= t_obj:
        fail(f"reactive hot p99 {reactive['hot']['metrics']['p99_ms']}"
             f" ms under the {t_obj} ms objective — the ramp is not "
             "saturating one replica")
    if pm["breach_cycles"] != 0 or pm["worst_state_level"] != 0:
        fail(f"predictive arm burned the customer objective: {pm}")
    if pm["max_burn_fast"] > 1.0:
        fail(f"predictive arm customer burn rate over budget: {pm}")
    if pm["replicas_final"] != 2:
        fail(f"predictive arm did not scale to 2 replicas: {pm}")
    ups = predictive["stats"].get("scale", {}).get("up", 0)
    if not ups:
        fail(f"predictive arm recorded no scale-up: "
             f"{predictive['stats'].get('scale')}")
    if predictive["hot"]["metrics"]["p99_ms"] >= t_obj:
        fail(f"predictive hot p99 "
             f"{predictive['hot']['metrics']['p99_ms']} ms breached "
             f"{t_obj} ms despite the scale-up")
    say(f"A/B OK: reactive hot p99 "
        f"{reactive['hot']['metrics']['p99_ms']} ms on 1 replica "
        f"(firing), predictive hot p99 "
        f"{predictive['hot']['metrics']['p99_ms']} ms on 2 "
        f"(ok, scale-ups {ups})")

    verdict = check_reactive_trace(rdir_flags)
    say(f"trace OK: merged reactive-arm trace passes check_trace "
        f"--fleet with slo_alerts={verdict.get('slo_alerts')}")

    # -- ledger round-trip ----------------------------------------------------
    entry = ingest_file(record)
    if entry.get("status") != "parsed":
        fail(f"ledger could not parse {record}: {entry}")
    series = {p["series"] for p in entry.get("points", [])}
    for want in ("slo/reactive/breach_cycles",
                 "slo/predictive/breach_cycles",
                 "slo/predictive/peak_p99_ms"):
        if want not in series:
            fail(f"series {want} missing from the ledger ingest: "
                 f"{sorted(series)}")
        if not perf_gate.gated(want):
            fail(f"series {want} is not perf-gated")
    say(f"ledger OK: {len(series)} slo/ series ingested and gated "
        f"from {os.path.basename(record)} (round {args.round})")
    say("PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
