"""Run the full bench harness (configs 1-5) and record HARNESS_r{N}.json.

The committed artifact for VERDICT r2 item 3: every config's checksum
PASS/FAIL + oracle/engine times, with the engine path flags the config
pinned (use_pallas/select), run on whatever platform the environment
provides (real TPU for the single-chip configs under axon; virtual CPU
mesh for the mesh/multi-process configs).
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dmlp_tpu.bench.configs import BENCH_CONFIGS  # noqa: E402
from dmlp_tpu.bench.harness import run_config  # noqa: E402


def main() -> int:
    args = [a for a in sys.argv[1:] if a != "--force-oracle"]
    force = "--force-oracle" in sys.argv[1:]  # re-time the oracle (e.g.
    # after oracle-speed changes) instead of reusing the cached .err
    round_tag = args[0] if args else "r03"
    results = []
    for cid, cfg in sorted(BENCH_CONFIGS.items()):
        # reps=3 + median: the tunneled link's throughput swings make a
        # single-shot engine time weather, not measurement (the recorded
        # artifact keeps all rep times for transparency).
        res = run_config(cid, base_dir=".", timeout_s=580.0,
                         force_oracle=force, reps=3)
        res.update({"mode": cfg.mode, "use_pallas": cfg.use_pallas,
                    "select": cfg.select, "procs": cfg.procs,
                    "virtual_devices": cfg.virtual_devices,
                    "shape": [cfg.num_data, cfg.num_queries, cfg.num_attrs]})
        results.append(res)
        print(json.dumps(res), flush=True)
    ok = all(r["checksums_match"] for r in results)
    with open(f"HARNESS_{round_tag}.json", "w") as f:
        json.dump({"all_pass": ok, "configs": results}, f, indent=1)
    print(f"HARNESS_{round_tag}.json written, all_pass={ok}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
