#!/usr/bin/env python
"""Merge per-rank cluster traces into one Perfetto-loadable trace.

``python -m dmlp_tpu.distributed --trace DIR`` leaves one
``trace-rank<NN>.json`` per rank (obs.dist_trace), each with its own clock
epoch (``time.perf_counter`` is per-process) and its rank as the Perfetto
``pid``. This tool:

1. loads every rank file in DIR (the rank set must be contiguous 0..N-1
   and match each file's recorded ``num_ranks`` — a missing rank means a
   crashed or unstarted process, which the merge must fail on, not paper
   over);
2. aligns clocks: every rank stamped a ``dist.clock_sync`` instant
   immediately after the cluster barrier released it, so shifting each
   rank's timestamps by (reference sync − its sync) puts all ranks on a
   common timeline to ~barrier-release accuracy. Rank 0's sync is the
   reference; per-rank offsets are recorded in the merged ``dist`` block;
3. cross-checks span counts per rank: every rank must carry spans at all,
   and the per-rank count of ``dist.solve`` spans (the contract solve —
   dispatched identically on every rank) must agree across ranks;
4. reconciles comms accounting per rank: each
   ``dist.allgather_candidates`` span carries the REAL payload bytes
   (``nbytes``) plus the gathered shapes; the merge recomputes the
   analytic expectation (obs.comms.host_allgather_candidates_traffic —
   the same model the engines' one-shot comms records use) and embeds a
   per-rank traced-vs-analytic table in the merged ``dist`` block
   (``comms_reconcile``); ``tools/check_trace.py --dist`` fails on any
   rank whose two numbers disagree. Pre-r6 traces without the shape
   args get an explicit ``analytic_unavailable`` marker, not a failure;
5. analyzes per-rank span-duration skew (the straggler detector): each
   rank's total ``dist.solve`` duration vs the across-rank median,
   flagging ranks beyond ``--straggler-threshold`` (default 1.5x) in
   the merged ``dist.straggler`` block. Rank files from MIXED clock
   domains (the trace metadata's ``clock.source`` — "monotonic" raw
   per-process tracers vs an already-"synced" merged doc) are refused
   with an explicit ``straggler_unavailable`` marker instead of
   nonsense skew numbers;
6. writes one merged Chrome-trace JSON, events sorted by aligned ``ts``
   (per-rank monotonicity is then checkable by tools/check_trace.py
   --dist), stamped ``clock.source: "synced"``, with distinct pids so
   ui.perfetto.dev renders one process track per rank.

Usage: python tools/merge_traces.py DIR [-o MERGED.json] [--no-align]
       [--straggler-threshold X]
Exit 0 on success; 1 with a message naming the violated invariant.

**Fleet mode** (``--fleet``): merge one serving fleet's rid-tagged
traces instead of dist rank files. Inputs in DIR are
``trace-router.json`` (required), ``trace-replicaNN.json`` (>= 1), and
optionally ``trace-client.json`` (the loadgen-side tracer). Fleet
processes share no barrier, so alignment uses each process's
``fleet.clock_sync`` instant — a back-to-back (perf_counter, wall
clock) pair — against the router's: localhost processes share the wall
clock, so the recovered offsets are sub-millisecond. Merged pids are
reassigned (client 0, router 1, replica i -> 10+i) and every span is
stitched by its ``rid`` arg into a per-request causal tree (client
fire -> route -> hops -> replica phases). When the client trace is
present the merge reconciles, per rid, the replica-side phase sum
(queue + coalesce + solve + finalize + write) against the
client-measured scheduled-fire latency minus its recorded pacing lag:

    residual_ms = client_ms - lag_ms - phase_sum_ms

The residual is the un-phased remainder (connect/parse/router relay +
clock-rate noise), so the tolerance is one-sided-wide:
``-tol_clock_ms <= residual <= tol_abs_ms + tol_rel * client_ms``.
Durations are clock-OFFSET invariant, so the check survives imperfect
alignment; ``tol_clock_ms`` only absorbs perf_counter rate noise on
the negative side. The ``serve.phase.admission`` span runs on the
handler thread CONCURRENT with the queue wait and is therefore
reported but EXCLUDED from the sum. Verdicts are embedded per rid in
the merged ``fleet`` block for ``tools/check_trace.py --fleet``. The
MEDIAN residual over reconciled rids is recorded as
``reconcile_residual_ms`` alongside ``residual_budget_ms``
(RESIDUAL_BUDGET_MS); exceeding the budget stamps a non-gating
``residual_budget_exceeded`` marker rather than failing the merge.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def fail(msg: str):
    print(f"merge_traces: FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def load_rank_files(trace_dir: str):
    """-> (docs, missing): (rank, doc) pairs sorted by rank, plus a
    {rank: reason} table for ranks whose file is absent or
    unreadable/truncated. A missing rank is the EXPECTED artifact of
    the failure being diagnosed (a crashed or hung process is exactly
    when you need the surviving ranks' trace) — so the merge records
    the explicit ``rank_trace_missing`` marker and proceeds instead of
    refusing. At least one readable rank file is still required, and a
    file whose embedded rank disagrees with its name still fails (that
    is corruption of identity, not absence)."""
    paths = sorted(glob.glob(os.path.join(trace_dir, "trace-rank*.json")))
    if not paths:
        fail(f"no trace-rank*.json files in {trace_dir}")
    docs = []
    missing = {}
    for p in paths:
        m = re.search(r"trace-rank(\d+)\.json$", p)
        if not m:
            continue
        frank = int(m.group(1))
        try:
            with open(p) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            missing[frank] = f"unreadable or truncated: {e}"
            continue
        dist = doc.get("dist") or {}
        rank = dist.get("rank", frank)
        if rank != frank:
            fail(f"{p}: embedded rank {rank} != filename rank {frank}")
        docs.append((rank, doc))
    if not docs:
        fail(f"no readable trace-rank*.json in {trace_dir} "
             f"(all {len(missing)} candidate file(s) truncated?)")
    docs.sort()
    present = {r for r, _ in docs}
    want_n = docs[0][1].get("dist", {}).get(
        "num_ranks", max(present | set(missing)) + 1)
    beyond = sorted(r for r in present if r >= want_n)
    if beyond:
        fail(f"rank(s) {beyond} exceed the recorded num_ranks {want_n} "
             "(inconsistent trace metadata)")
    for r in range(want_n):
        if r not in present and r not in missing:
            missing[r] = "file missing (crashed or never started?)"
    return docs, missing


def sync_ts(doc, rank: int) -> float:
    """The rank's barrier-aligned clock-sync timestamp (us)."""
    ts = (doc.get("dist") or {}).get("clock_sync_ts_us")
    if ts is not None:
        return float(ts)
    for e in doc.get("traceEvents", []):
        if e.get("ph") == "i" and e.get("name") == "dist.clock_sync":
            return float(e["ts"])
    fail(f"rank {rank}: no dist.clock_sync event — was the trace written "
         "by dmlp_tpu.distributed --trace (obs.dist_trace)?")


_AG_SHAPE_KEYS = ("ranks", "r_shards", "qpad", "kcap")


def reconcile_comms(docs) -> dict | None:
    """Per-rank traced-vs-analytic byte table for the candidate
    all-gather, or None when no rank traced one (single-process or
    emulated runs dispatch no host all-gather — absence is normal, not
    a violation).

    The analytic side deliberately uses the MODEL'S OWN per-candidate
    itemsizes (obs.comms defaults: f64 dists + i32 labels + i32 ids),
    not the itemsizes the span recorded from the live arrays — the
    traced ``nbytes`` comes from the real buffers, so if the
    implementation's dtypes ever drift from what obs.comms assumes, the
    two sides disagree and the check FLAGS it instead of following the
    drift. (Shapes still come from the span: they are structural — the
    same r/qpad/kcap every dist.* span of the solve shares.) The span's
    recorded itemsizes ride along as a diagnostic on mismatch. Import
    of the analytic model is lazy and failure maps to an explicit
    marker: the merge must work from a bare checkout."""
    per_rank = {}
    for rank, doc in docs:
        spans = [e for e in doc.get("traceEvents", [])
                 if e.get("ph") == "X"
                 and e.get("name") == "dist.allgather_candidates"]
        if not spans:
            continue
        entry: dict = {"spans": len(spans),
                       "traced_bytes": sum(int(e.get("args", {})
                                               .get("nbytes", 0))
                                           for e in spans)}
        analytic = 0
        marker = None
        for e in spans:
            a = e.get("args", {})
            if not all(k in a for k in _AG_SHAPE_KEYS):
                marker = ("span args lack shape fields "
                          "(pre-r6 trace — re-record to reconcile)")
                break
            try:
                from dmlp_tpu.obs.comms import \
                    host_allgather_candidates_traffic
                t = host_allgather_candidates_traffic(
                    int(a["ranks"]), int(a["r_shards"]), int(a["qpad"]),
                    int(a["kcap"]))
                analytic += t.bytes_out_per_device
            except Exception as exc:
                marker = f"analytic model unavailable ({exc})"
                break
        if marker is not None:
            entry["analytic_unavailable"] = marker
        else:
            entry["analytic_bytes"] = analytic
            entry["match"] = analytic == entry["traced_bytes"]
            if not entry["match"]:
                entry["span_itemsizes"] = [
                    e.get("args", {}).get("itemsizes") for e in spans]
        per_rank[str(rank)] = entry
    return per_rank or None


def _rank_clock_source(doc) -> str:
    """The rank file's declared clock domain; pre-r6 traces (no clock
    metadata) are per-process monotonic by construction."""
    src = (doc.get("clock") or {}).get("source")
    if src is None:
        src = (doc.get("dist") or {}).get("clock_source")
    return src or "monotonic"


def straggler_analysis(docs, threshold: float = 1.5) -> dict:
    """Per-rank span-duration skew table — the straggler detector.

    Durations (``dur``) are clock-OFFSET invariant, so the skew metric
    compares each rank's total ``dist.solve`` time (the contract solve
    every rank dispatches identically) and total span-busy time against
    the across-rank median; a rank whose solve time exceeds
    ``threshold`` x the median is flagged. Ranks from MIXED clock
    domains (one trace already merge-aligned/"synced", another raw
    "monotonic" — their timestamps AND tick provenance differ) are
    refused with an explicit ``straggler_unavailable`` marker instead
    of a nonsense table.
    """
    domains = {rank: _rank_clock_source(doc) for rank, doc in docs}
    if len(set(domains.values())) > 1:
        return {"straggler_unavailable":
                f"mixed clock domains {domains} — re-record all ranks "
                "with one tracer generation before skew-comparing"}
    per_rank = {}
    solve_ms = {}
    for rank, doc in docs:
        busy = solve = 0.0
        last_end = 0.0
        for e in doc.get("traceEvents", []):
            if e.get("ph") != "X":
                continue
            dur = float(e.get("dur", 0.0))
            busy += dur
            last_end = max(last_end, float(e.get("ts", 0.0)) + dur)
            if e.get("name") == "dist.solve":
                solve += dur
        solve_ms[rank] = solve / 1e3
        per_rank[str(rank)] = {"span_busy_ms": round(busy / 1e3, 3),
                               "solve_ms": round(solve / 1e3, 3),
                               "last_span_end_ms":
                                   round(last_end / 1e3, 3)}
    import statistics
    med = statistics.median(solve_ms.values())
    flagged = []
    for rank in sorted(solve_ms):
        skew = (solve_ms[rank] / med) if med > 0 else None
        per_rank[str(rank)]["skew_vs_median"] = \
            round(skew, 3) if skew is not None else None
        if skew is not None and skew > threshold:
            flagged.append(rank)
    return {"threshold": threshold, "clock_source": domains[docs[0][0]],
            "median_solve_ms": round(med, 3), "per_rank": per_rank,
            "flagged_ranks": flagged}


def merge(trace_dir: str, align: bool = True,
          straggler_threshold: float = 1.5) -> dict:
    docs, missing = load_rank_files(trace_dir)
    if missing:
        print(f"merge_traces: WARNING: rank trace(s) missing or "
              f"truncated: { {r: missing[r] for r in sorted(missing)} } "
              "— merging the surviving ranks with the explicit "
              "rank_trace_missing marker", file=sys.stderr)
    offsets = {}
    if align:
        ref = sync_ts(docs[0][1], 0)
        offsets = {rank: ref - sync_ts(doc, rank) for rank, doc in docs}

    events = []
    span_counts = {}
    solve_counts = {}
    for rank, doc in docs:
        off = offsets.get(rank, 0.0)
        n_spans = 0
        for e in doc.get("traceEvents", []):
            e = dict(e)
            if "ts" in e:
                e["ts"] = e["ts"] + off
            events.append(e)
            if e.get("ph") == "X":
                n_spans += 1
                if e.get("name") == "dist.solve":
                    solve_counts[rank] = solve_counts.get(rank, 0) + 1
        span_counts[rank] = n_spans
        if n_spans == 0:
            fail(f"rank {rank}: zero spans — tracing was installed but "
                 "nothing recorded")
    # Cross-check only the SURVIVING ranks: a missing rank already
    # carries its marker; divergence among present ranks is still a
    # different-program error.
    if len(set(solve_counts.get(r, 0) for r, _ in docs)) > 1:
        fail(f"per-rank dist.solve span counts disagree: {solve_counts} "
             "(every rank runs the same contract solve; a mismatch means "
             "a rank died mid-run or traced a different program)")

    # Rebase so the merged timeline starts at 0: alignment shifts a
    # rank's pre-barrier events negative relative to the reference
    # rank's epoch, and downstream consumers (check_trace --dist) hold
    # timestamps non-negative.
    stamped = [e["ts"] for e in events if "ts" in e]
    base = min(stamped) if stamped else 0.0
    if base < 0:
        for e in events:
            if "ts" in e:
                e["ts"] -= base
    # Stable sort by aligned ts, metadata (M) events first per pid so
    # Perfetto names tracks before their first slice arrives.
    events.sort(key=lambda e: (0 if e.get("ph") == "M" else 1,
                               e.get("ts", 0.0)))
    dist_block = {
        "num_ranks": len(docs) + len(missing),
        "aligned": bool(align),
        "clock_offsets_us": {str(r): offsets.get(r, 0.0)
                             for r, _ in docs},
        "span_counts": {str(r): span_counts[r] for r, _ in docs},
    }
    if missing:
        dist_block["rank_trace_missing"] = {
            "ranks": sorted(missing),
            "reasons": {str(r): missing[r] for r in sorted(missing)},
        }
    reconcile = reconcile_comms(docs)
    if reconcile is not None:
        dist_block["comms_reconcile"] = reconcile
        bad = [r for r, e in reconcile.items() if e.get("match") is False]
        if bad:
            # Embedded for check_trace --dist to FAIL on; the merge
            # itself still writes the artifact (the mismatch is the
            # finding, and the trace is the evidence).
            print(f"merge_traces: WARNING: analytic vs traced all-gather "
                  f"bytes disagree for rank(s) {bad}: "
                  f"{ {r: reconcile[r] for r in bad} }", file=sys.stderr)
    straggler = straggler_analysis(docs, threshold=straggler_threshold)
    dist_block["straggler"] = straggler
    if straggler.get("flagged_ranks"):
        print(f"merge_traces: WARNING: rank(s) "
              f"{straggler['flagged_ranks']} exceed "
              f"{straggler['threshold']}x the median dist.solve time "
              f"(median {straggler['median_solve_ms']} ms) — straggler/"
              "skew suspects", file=sys.stderr)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        # Post-alignment, all ranks share one timeline; downstream skew
        # consumers key on this (a re-merge of this doc must not
        # re-align or mix it with raw monotonic rank files).
        "clock": {"source": "synced" if align else "monotonic"},
        "dist": dist_block,
    }


# -- fleet mode ---------------------------------------------------------------

#: the replica-side request phases the reconcile sums; one rid's phases
#: tile ITS OWN wall time (solve is the full micro-batch interval,
#: attributed to every coalesced rid) — never sum across rids.
FLEET_PHASES = ("queue", "coalesce", "solve", "finalize", "write")

#: budget for the MEDIAN per-request residual (client_ms - lag_ms -
#: phase_sum_ms) across reconciled rids. The residual is real un-phased
#: work — connect/parse/router relay — measured at ~9 ms on the CPU
#: reference fleet; 20 ms leaves 2x headroom before the marker trips.
#: The budget is NON-GATING: exceeding it stamps
#: ``residual_budget_exceeded`` in the reconcile block (surfaced by
#: ``check_trace --fleet``) so round-over-round residual creep is
#: visible, but never fails the merge.
RESIDUAL_BUDGET_MS = 20.0


def fleet_sync(doc, pname: str):
    """-> (ts_us, unix_us) of the process's fleet.clock_sync marker."""
    for e in doc.get("traceEvents", []):
        if e.get("ph") == "i" and e.get("name") == "fleet.clock_sync":
            a = e.get("args", {})
            if "unix_ms" not in a:
                fail(f"{pname}: fleet.clock_sync lacks unix_ms — "
                     "re-record with Tracer.sync_instant")
            return float(e["ts"]), float(a["unix_ms"]) * 1e3
    fail(f"{pname}: no fleet.clock_sync event — was the process started "
         "with --trace (router/replica) or a sync-stamped client Tracer?")


def _load_fleet_docs(trace_dir: str):
    """-> [(pname, new_pid, doc)] — router required, >=1 replica
    required, client optional (reconcile degrades to a marker)."""
    procs = []
    cpath = os.path.join(trace_dir, "trace-client.json")
    if os.path.exists(cpath):
        try:
            with open(cpath) as f:
                procs.append(("client", 0, json.load(f)))
        except (OSError, json.JSONDecodeError) as e:
            fail(f"{cpath}: unreadable or truncated: {e}")
    rpath = os.path.join(trace_dir, "trace-router.json")
    if not os.path.exists(rpath):
        fail(f"no trace-router.json in {trace_dir} (fleet mode needs "
             "the router started with --trace)")
    try:
        with open(rpath) as f:
            procs.append(("router", 1, json.load(f)))
    except (OSError, json.JSONDecodeError) as e:
        fail(f"{rpath}: unreadable or truncated: {e}")
    reps = sorted(glob.glob(os.path.join(trace_dir,
                                         "trace-replica*.json")))
    if not reps:
        fail(f"no trace-replica*.json in {trace_dir}")
    for i, p in enumerate(reps):
        name = re.sub(r"^trace-|\.json$", "", os.path.basename(p))
        try:
            with open(p) as f:
                procs.append((name, 10 + i, json.load(f)))
        except (OSError, json.JSONDecodeError) as e:
            fail(f"{p}: unreadable or truncated: {e}")
    return procs


def _stitch_rids(events) -> dict:
    """Per-rid causal table from the ALIGNED merged event stream."""
    table: dict = {}

    def ent(rid):
        return table.setdefault(rid, {"phases": {}, "hops": []})

    for e in events:
        if e.get("ph") != "X":
            continue
        a = e.get("args", {})
        name = e.get("name", "")
        dur_ms = float(e.get("dur", 0.0)) / 1e3
        if name == "client.request" and a.get("rid"):
            ent(a["rid"])["client"] = {
                "client_ms": round(dur_ms, 3),
                "lag_ms": float(a.get("lag_ms", 0.0)),
                "ok": bool(a.get("ok")),
                "hops": int(a.get("hops", 1)),
                **({"level": a["level"]} if "level" in a else {})}
        elif name == "fleet.route" and a.get("rid"):
            ent(a["rid"])["route"] = {
                "outcome": a.get("outcome"),
                **({"hops": int(a["hops"])} if "hops" in a else {})}
        elif name == "fleet.hop" and a.get("rid"):
            ent(a["rid"])["hops"].append(
                {"replica": a.get("replica"),
                 "outcome": a.get("outcome"),
                 **({"attempt": int(a["attempt"])}
                    if "attempt" in a else {}),
                 **({"fanout": True} if a.get("fanout") else {})})
        elif name.startswith("serve.phase.") and a.get("rid"):
            ph = name[len("serve.phase."):]
            d = ent(a["rid"])["phases"]
            d[ph] = round(d.get(ph, 0.0) + dur_ms, 3)
    return table


def reconcile_fleet(table: dict, have_client: bool, tol_abs_ms: float,
                    tol_rel: float, tol_clock_ms: float) -> dict:
    """Phase-sum vs client-latency verdicts (see module docstring)."""
    block = {"tol_abs_ms": tol_abs_ms, "tol_rel": tol_rel,
             "tol_clock_ms": tol_clock_ms,
             "phases_summed": list(FLEET_PHASES)}
    if not have_client:
        block["reconcile_unavailable"] = (
            "no trace-client.json — replay with a sync-stamped client "
            "Tracer (serve.client.replay_open_loop rid_prefix) to "
            "reconcile phase sums against client latency")
        return block
    n = n_ok = 0
    residuals = []
    for rid in sorted(table):
        ent = table[rid]
        cl = ent.get("client")
        if cl is None or not cl["ok"]:
            continue          # rejected/unrouted: nothing to reconcile
        n += 1
        phases = ent["phases"]
        if not all(p in phases for p in FLEET_PHASES):
            ent["reconciled"] = False
            ent["reconcile_gap"] = sorted(
                p for p in FLEET_PHASES if p not in phases)
            continue
        phase_sum = sum(phases[p] for p in FLEET_PHASES)
        residual = cl["client_ms"] - cl["lag_ms"] - phase_sum
        ent["phase_sum_ms"] = round(phase_sum, 3)
        ent["residual_ms"] = round(residual, 3)
        ent["reconciled"] = (
            -tol_clock_ms <= residual
            <= tol_abs_ms + tol_rel * cl["client_ms"])
        n_ok += bool(ent["reconciled"])
        if ent["reconciled"]:
            residuals.append(residual)
    block.update(n_requests=n, n_reconciled=n_ok,
                 fraction=round(n_ok / n, 4) if n else None)
    # The residual used to be silent (each rid carried its own but no
    # aggregate) — surface the median so creep is visible per round.
    if residuals:
        residuals.sort()
        m = len(residuals) // 2
        med = residuals[m] if len(residuals) % 2 else \
            (residuals[m - 1] + residuals[m]) / 2.0
        block["reconcile_residual_ms"] = round(med, 3)
        block["residual_budget_ms"] = RESIDUAL_BUDGET_MS
        if med > RESIDUAL_BUDGET_MS:
            block["residual_budget_exceeded"] = True  # non-gating
    return block


def merge_fleet(trace_dir: str, tol_abs_ms: float = 75.0,
                tol_rel: float = 0.25,
                tol_clock_ms: float = 10.0) -> dict:
    procs = _load_fleet_docs(trace_dir)
    have_client = any(p[0] == "client" for p in procs)
    ref_ts, ref_unix = fleet_sync(
        next(d for n, _, d in procs if n == "router"), "router")
    offsets = {}
    events = []
    span_counts = {}
    for pname, pid, doc in procs:
        ts_p, unix_p = fleet_sync(doc, pname)
        off = ref_ts - ts_p + (unix_p - ref_unix)
        offsets[pname] = off
        n_spans = 0
        for e in doc.get("traceEvents", []):
            e = dict(e)
            e["pid"] = pid
            if "ts" in e:
                e["ts"] = e["ts"] + off
            events.append(e)
            n_spans += e.get("ph") == "X"
        span_counts[pname] = n_spans
        if n_spans == 0 and pname != "client":
            fail(f"{pname}: zero spans — tracing was installed but "
                 "nothing recorded")
    stamped = [e["ts"] for e in events if "ts" in e]
    base = min(stamped) if stamped else 0.0
    if base < 0:
        for e in events:
            if "ts" in e:
                e["ts"] -= base
    events.sort(key=lambda e: (0 if e.get("ph") == "M" else 1,
                               e.get("ts", 0.0)))
    table = _stitch_rids(events)
    reconcile = reconcile_fleet(table, have_client, tol_abs_ms,
                                tol_rel, tol_clock_ms)
    frac = reconcile.get("fraction")
    if frac is not None and frac < 1.0:
        bad = [r for r in sorted(table)
               if table[r].get("reconciled") is False]
        print(f"merge_traces: WARNING: {len(bad)} request(s) fail the "
              f"phase-sum reconcile (fraction {frac}): e.g. "
              f"{ {r: table[r] for r in bad[:3]} }", file=sys.stderr)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "clock": {"source": "synced"},
        "fleet": {
            "processes": {n: {"pid": p, "spans": span_counts[n]}
                          for n, p, _ in procs},
            "clock_offsets_us": {n: round(o, 1)
                                 for n, o in offsets.items()},
            "requests": table,
            "reconcile": reconcile,
        },
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("trace_dir", help="directory holding trace-rank*.json")
    ap.add_argument("-o", "--out", default=None,
                    help="merged output path (default DIR/trace-merged.json)")
    ap.add_argument("--no-align", action="store_true",
                    help="keep each rank's raw clock (skip the "
                         "clock-sync offset alignment)")
    ap.add_argument("--straggler-threshold", type=float, default=1.5,
                    help="flag ranks whose dist.solve time exceeds this "
                         "multiple of the across-rank median")
    ap.add_argument("--fleet", action="store_true",
                    help="merge a serving fleet's rid-tagged traces "
                         "(trace-router.json + trace-replicaNN.json "
                         "[+ trace-client.json]) instead of dist ranks")
    ap.add_argument("--tol-abs-ms", type=float, default=75.0,
                    help="fleet reconcile: absolute residual budget "
                         "(connect/parse/relay overhead per request)")
    ap.add_argument("--tol-rel", type=float, default=0.25,
                    help="fleet reconcile: residual budget as a "
                         "fraction of the client-measured latency")
    ap.add_argument("--tol-clock-ms", type=float, default=10.0,
                    help="fleet reconcile: allowed NEGATIVE residual "
                         "(perf_counter rate noise across processes)")
    args = ap.parse_args(argv)

    if args.fleet:
        out_path = args.out or os.path.join(args.trace_dir,
                                            "trace-fleet-merged.json")
        doc = merge_fleet(args.trace_dir, tol_abs_ms=args.tol_abs_ms,
                          tol_rel=args.tol_rel,
                          tol_clock_ms=args.tol_clock_ms)
    else:
        out_path = args.out or os.path.join(args.trace_dir,
                                            "trace-merged.json")
        doc = merge(args.trace_dir, align=not args.no_align,
                    straggler_threshold=args.straggler_threshold)
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, out_path)
    if args.fleet:
        fb = doc["fleet"]
        rec = fb["reconcile"]
        print(f"merge_traces: merged fleet "
              f"{sorted(fb['processes'])} -> {out_path} "
              f"({len(fb['requests'])} rid(s), offsets us: "
              f"{fb['clock_offsets_us']}, reconciled: "
              f"{rec.get('n_reconciled')}/{rec.get('n_requests')})")
        return 0
    d = doc["dist"]
    print(f"merge_traces: merged {d['num_ranks']} ranks -> {out_path} "
          f"(spans per rank: {d['span_counts']}, offsets us: "
          f"{ {k: round(v, 1) for k, v in d['clock_offsets_us'].items()} })")
    return 0


if __name__ == "__main__":
    sys.exit(main())
