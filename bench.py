"""Driver benchmark: times the TPU-native KNN solve, prints ONE JSON line.

Workload: the reference's headline benchmark shape — brute-force KNN
classification (survey §6). The timed region matches the reference's
(common.cpp:122-131 brackets Engine::KNN after stdin ingest): everything
the reference's timed call does — distribution (host staging + transfer,
the scatter analog), device solve, and result finalization — via the same
``engine.run()`` pipeline for every mode. Parsing/generation is outside;
compile is excluded via a warmup call (XLA compiles once per shape; the
reference pays no JIT either).

Baseline: a blocked NumPy (BLAS f32) implementation of the same solve on the
host CPU — the portable stand-in for the reference's CPU/MPI engine, whose
published numbers do not exist and whose binaries cannot run here (survey §6).
``vs_baseline`` is the speedup ratio baseline_ms / engine_ms (>1 = faster).

Env overrides: BENCH_NUM_DATA, BENCH_NUM_QUERIES, BENCH_NUM_ATTRS, BENCH_K,
BENCH_REPEATS, BENCH_MODE (single|sharded|ring).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def _env_int(name: str, default: int) -> int:
    return int(os.environ.get(name, default))


def make_workload(num_data: int, num_queries: int, num_attrs: int, k: int,
                  seed: int = 42):
    """Synthetic workload with the generator's distribution
    (generate_input.py:13-21: uniform attrs, uniform labels, fixed seed) —
    built as arrays directly; text parsing is outside the timed region anyway.
    """
    rng = np.random.default_rng(seed)
    data_attrs = rng.uniform(0.0, 100.0, (num_data, num_attrs))
    query_attrs = rng.uniform(0.0, 100.0, (num_queries, num_attrs))
    labels = rng.integers(0, 10, num_data, dtype=np.int32)
    ks = np.full(num_queries, k, dtype=np.int32)
    from dmlp_tpu.io.grammar import KNNInput, Params
    return KNNInput(Params(num_data, num_queries, num_attrs), labels,
                    data_attrs, ks, query_attrs)


def time_baseline_ms(inp, k: int, sample_queries: int = 1024,
                     block: int = 256) -> float:
    """Blocked NumPy KNN solve time, measured on a query subsample and
    scaled linearly to the full query count (matmul cost is linear in Q)."""
    d = inp.data_attrs.astype(np.float32)
    dn = (d * d).sum(axis=1)
    qs = min(sample_queries, inp.params.num_queries)
    q = inp.query_attrs[:qs].astype(np.float32)

    t0 = time.perf_counter()
    for q0 in range(0, qs, block):
        qb = q[q0:q0 + block]
        dist = (qb * qb).sum(axis=1)[:, None] + dn[None, :] - 2.0 * (qb @ d.T)
        idx = np.argpartition(dist, kth=min(k, dist.shape[1] - 1), axis=1)[:, :k]
        lab = inp.labels[idx]
        # majority vote per row (same O() work as the engine's vote)
        for r in range(lab.shape[0]):
            np.bincount(lab[r], minlength=10).argmax()
    elapsed = (time.perf_counter() - t0) * 1e3
    return elapsed * (inp.params.num_queries / qs)


def time_engine_ms(inp, mode: str, repeats: int) -> float:
    import jax
    from dmlp_tpu.cli import make_engine
    from dmlp_tpu.config import EngineConfig

    from dmlp_tpu.ops.pallas_distance import native_pallas_backend
    use_pallas = os.environ.get("BENCH_PALLAS", "1") == "1" \
        and native_pallas_backend()
    cfg = EngineConfig(mode=mode, exact=False, dtype="float32",
                       query_block=2048, use_pallas=use_pallas)
    engine = make_engine(cfg)

    run = engine.run  # same pipeline for every mode -> comparable numbers
    run(inp)  # warmup: compile + first dispatch
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        run(inp)
        times.append((time.perf_counter() - t0) * 1e3)
    return float(np.median(times))


def main() -> int:
    num_data = _env_int("BENCH_NUM_DATA", 200_000)
    num_queries = _env_int("BENCH_NUM_QUERIES", 10_000)
    num_attrs = _env_int("BENCH_NUM_ATTRS", 64)
    k = _env_int("BENCH_K", 32)
    repeats = _env_int("BENCH_REPEATS", 3)
    mode = os.environ.get("BENCH_MODE", "single")

    inp = make_workload(num_data, num_queries, num_attrs, k)
    engine_ms = time_engine_ms(inp, mode, repeats)
    baseline_ms = time_baseline_ms(inp, k)

    pairs_per_s = num_data * num_queries / (engine_ms / 1e3)
    print(json.dumps({
        "metric": "knn_solve_ms",
        "value": round(engine_ms, 3),
        "unit": "ms",
        "vs_baseline": round(baseline_ms / engine_ms, 3),
        "baseline_ms": round(baseline_ms, 1),
        "qd_pairs_per_sec": round(pairs_per_s),
        "shape": {"num_data": num_data, "num_queries": num_queries,
                  "num_attrs": num_attrs, "k": k, "mode": mode},
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
