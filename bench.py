"""Driver benchmark: times the TPU-native KNN solve, prints ONE JSON line.

Workload: the reference's headline benchmark shape — brute-force KNN
classification (survey §6). The timed region matches the reference's
(common.cpp:122-131 brackets Engine::KNN after stdin ingest): everything
the reference's timed call does — distribution (host staging + transfer,
the scatter analog), device solve, and result finalization — via the same
``engine.run()`` pipeline for every mode. Parsing/generation is outside;
compile is excluded via a warmup call (XLA compiles once per shape; the
reference pays no JIT either).

Baseline: a blocked NumPy (BLAS f32) implementation of the same solve on the
host CPU — the portable stand-in for the reference's CPU/MPI engine, whose
published numbers do not exist and whose binaries cannot run here (survey §6).
``vs_baseline`` is the speedup ratio baseline_ms / engine_ms (>1 = faster).

Env overrides: BENCH_NUM_DATA, BENCH_NUM_QUERIES, BENCH_NUM_ATTRS, BENCH_K,
BENCH_REPEATS, BENCH_MODE (single|sharded|ring).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def _env_int(name: str, default: int) -> int:
    return int(os.environ.get(name, default))


def make_workload(num_data: int, num_queries: int, num_attrs: int, k: int,
                  seed: int = 42):
    """Synthetic workload with the generator's distribution
    (generate_input.py:13-21: uniform attrs, uniform labels, fixed seed) —
    built as arrays directly; text parsing is outside the timed region anyway.
    """
    rng = np.random.default_rng(seed)
    data_attrs = rng.uniform(0.0, 100.0, (num_data, num_attrs))
    query_attrs = rng.uniform(0.0, 100.0, (num_queries, num_attrs))
    labels = rng.integers(0, 10, num_data, dtype=np.int32)
    ks = np.full(num_queries, k, dtype=np.int32)
    from dmlp_tpu.io.grammar import KNNInput, Params
    return KNNInput(Params(num_data, num_queries, num_attrs), labels,
                    data_attrs, ks, query_attrs)


def time_baseline_ms(inp, k: int, sample_queries: int = 1024,
                     block: int = 256) -> float:
    """Blocked NumPy KNN solve time, measured on a query subsample and
    scaled linearly to the full query count (matmul cost is linear in Q) —
    reported as ``baseline_ms_est`` because of that extrapolation. The vote
    is a vectorized batched bincount, so the baseline is a fair BLAS
    implementation, not a Python-loop strawman."""
    d = inp.data_attrs.astype(np.float32)
    dn = (d * d).sum(axis=1)
    qs = min(sample_queries, inp.params.num_queries)
    q = inp.query_attrs[:qs].astype(np.float32)
    num_labels = int(inp.labels.max()) + 1 if inp.params.num_data else 1

    t0 = time.perf_counter()
    for q0 in range(0, qs, block):
        qb = q[q0:q0 + block]
        # In-place epilogue: the broadcast form's (b, N) temporaries cost
        # ~10x the sgemm at this shape (see golden.fast) — the baseline
        # should be the best honest CPU implementation, not a strawman.
        dist = qb @ d.T
        dist *= -2.0
        dist += (qb * qb).sum(axis=1)[:, None]
        dist += dn[None, :]
        idx = np.argpartition(dist, kth=min(k, dist.shape[1] - 1), axis=1)[:, :k]
        lab = inp.labels[idx]
        counts = np.zeros((lab.shape[0], num_labels), np.int64)
        rows = np.broadcast_to(np.arange(lab.shape[0])[:, None], lab.shape)
        np.add.at(counts, (rows, lab), 1)
        counts.argmax(axis=1)
    elapsed = (time.perf_counter() - t0) * 1e3
    return elapsed * (inp.params.num_queries / qs)


def stage_extract_inputs(inp):
    """Stage (queries, data, labels) padded to whole extract tiles on the
    device, fenced. Shared by bench.py and the tools/ sweep/scale harnesses
    so padding and staging scope can't silently diverge between artifacts."""
    import jax.numpy as jnp

    from dmlp_tpu.engine.single import round_up
    from dmlp_tpu.ops.pallas_extract import BLOCK_ROWS, QUERY_TILE

    n, a = inp.data_attrs.shape
    nq = inp.params.num_queries
    npad = round_up(n, BLOCK_ROWS)
    qpad = round_up(nq, QUERY_TILE)
    d = jnp.zeros((npad, a), jnp.float32).at[:n].set(
        jnp.asarray(inp.data_attrs, jnp.float32))
    q = jnp.zeros((qpad, a), jnp.float32).at[:nq].set(
        jnp.asarray(inp.query_attrs, jnp.float32))
    lab = jnp.asarray(inp.labels, jnp.int32)
    float(jnp.sum(d))  # fence staging
    return q, d, lab, npad, qpad


def time_fenced_solve_ms(fn, q, d, repeats: int) -> float:
    """Fenced repeat-timing of a jitted solve ``fn(q, d) -> (Q, K) dists``:
    compile + fence, warm the eager perturbation chain (its tiny kernels
    compile on first use — ~1.2 s over the remote-compile tunnel, the r2
    mismeasurement), then time ``repeats`` chained dispatches bounded by a
    dependent scalar readback (block_until_ready is unreliable over
    tunneled PJRT links). Shared by bench.py and tools/."""
    r = fn(q, d)
    _ = float(r[0, 0])           # compile + fence
    r = fn(q + 0.0 * r[0, 0], d)
    _ = float(r[0, 0])           # warm the perturbation chain
    t0 = time.perf_counter()
    for _i in range(repeats):
        r = fn(q + 0.0 * r[0, 0], d)  # chain dependency
    _ = float(r[0, 0])
    return (time.perf_counter() - t0) / repeats * 1e3


def _time_extract_solve_ms(inp, repeats: int, use_pallas: bool):
    """Fenced on-chip time of the fused extraction solve (select="extract",
    ops.pallas_extract): one call over the whole padded dataset — the
    distance tile never reaches HBM. The timed region includes the
    label-gather + composite-sort epilogue (engine.single._extract_finalize)
    so the number is scope-comparable with the seg/topk streaming folds,
    which carry labels and merge inside the fold. None when the kernel
    can't run here."""
    from dmlp_tpu.engine.single import _extract_finalize, round_up
    from dmlp_tpu.ops.pallas_extract import BLOCK_ROWS, QUERY_TILE, extract_topk
    from dmlp_tpu.ops.pallas_extract import supports as extract_supports

    n, a = inp.data_attrs.shape
    nq = inp.params.num_queries
    k = round_up(int(inp.ks.max()) + 8, 8)
    # Gate BEFORE staging: on the tunneled link the padded upload is
    # multi-second, not worth paying just to return None. Padding matches
    # stage_extract_inputs (whole extraction blocks / query tiles —
    # awkward sizes otherwise tile degenerately, config.resolve_granule).
    if not (use_pallas
            and extract_supports(round_up(nq, QUERY_TILE),
                                 round_up(n, BLOCK_ROWS), a, k)):
        return None
    q, d, lab, npad, qpad = stage_extract_inputs(inp)

    def fn(q_, d_):
        od, oi, _ = extract_topk(q_, d_, n_real=n, kc=k)
        return _extract_finalize(od, oi, lab, k=k).dists

    return round(time_fenced_solve_ms(fn, q, d, repeats), 1)


def time_device_solve_ms(inp, repeats: int, use_pallas: bool) -> dict:
    """On-chip solve time alone: arrays pre-staged, chained dispatches,
    fenced by a dependent scalar readback (block_until_ready is unreliable
    over tunneled PJRT links). Reported alongside the end-to-end number
    because on this host link the end-to-end solve is transfer-bound: the
    decomposition is what shows where engineering effort lands.
    """
    import functools

    import jax
    import jax.numpy as jnp

    from dmlp_tpu.engine.single import round_up
    from dmlp_tpu.ops.pallas_distance import _tile
    from dmlp_tpu.ops.topk import streaming_topk

    n, a = inp.data_attrs.shape
    nq = inp.params.num_queries
    k = round_up(int(inp.ks.max()) + 8, 8)
    out = {}
    selects = tuple(
        s for s in (t.strip() for t in os.environ.get(
            "BENCH_DEVICE_SOLVE_SELECTS", "extract,seg").split(","))
        if s in ("extract", "seg", "topk", "sort"))
    for select in selects:
        if select == "extract":
            ms = _time_extract_solve_ms(inp, repeats, use_pallas)
            if ms is not None:
                out["device_solve_ms_extract"] = ms
                # Which variant actually ran (tuner cache entry when one
                # exists for this device/shape/kc, else the heuristic) —
                # artifacts must say what they measured.
                from dmlp_tpu.ops.pallas_extract import (BLOCK_ROWS,
                                                         QUERY_TILE,
                                                         resolve_variant)
                out["extract_variant"] = resolve_variant(
                    k, round_up(n, BLOCK_ROWS), round_up(nq, QUERY_TILE),
                    a)
            continue
        pallas = use_pallas and select == "seg"
        granule = 1024 if pallas else 128
        npad = round_up(n, granule)
        qpad = round_up(nq, 1024)
        if select == "seg":
            # One chunk if the live (Q, B) f32 tile fits the HBM budget:
            # seg's selection + merge cost is ~independent of chunk size,
            # so fewer chunks amortize it (measured 395 -> ~245 ms at r3).
            dmax = max((9 << 30) // (qpad * 4), granule)
            dblock = _tile(npad, min(npad, dmax), granule)
        else:
            # topk/sort concat the whole (Q, B) tile into the merge, so
            # their live footprint is ~3x the tile — keep chunks small.
            dblock = _tile(npad, 51200, granule)
        d = jnp.zeros((npad, a), jnp.float32).at[:n].set(
            jnp.asarray(inp.data_attrs, jnp.float32))
        lab = jnp.full(npad, -1, jnp.int32).at[:n].set(jnp.asarray(inp.labels))
        ids = jnp.where(jnp.arange(npad) < n,
                        jnp.arange(npad, dtype=jnp.int32), -1)
        q = jnp.zeros((qpad, a), jnp.float32).at[:nq].set(
            jnp.asarray(inp.query_attrs, jnp.float32))
        fn = jax.jit(functools.partial(streaming_topk, k=k,
                                       data_block=dblock, select=select,
                                       use_pallas=pallas))
        float(jnp.sum(d))  # fence staging
        r = fn(q, d, lab, ids)
        _ = float(r.dists[0, 0])  # compile + fence
        # Warm the perturbation chain too: `q + 0.0 * r.dists[0, 0]` is
        # eager op-by-op dispatch whose tiny kernels compile on first use —
        # ~1.2 s over the remote-compile tunnel, which inflated the round-2
        # number to 1616 ms (reproduced: first call 1692 ms, repeats ~400).
        r = fn(q + 0.0 * r.dists[0, 0], d, lab, ids)
        _ = float(r.dists[0, 0])  # fence warmup
        t0 = time.perf_counter()
        for _i in range(repeats):
            r = fn(q + 0.0 * r.dists[0, 0], d, lab, ids)  # chain dependency
        _ = float(r.dists[0, 0])  # fence
        out[f"device_solve_ms_{select}"] = round(
            (time.perf_counter() - t0) / repeats * 1e3, 1)
    return out


def time_engine_ms(inp, mode: str, repeats: int):
    """Median engine.run() wall time, plus a record of which code path
    actually ran (select strategy, pallas on/off, phase breakdown) — the
    round-1 bench silently fell back off the fused path and the JSON gave
    no way to see it."""
    from dmlp_tpu.cli import make_engine
    from dmlp_tpu.config import EngineConfig

    from dmlp_tpu.ops.pallas_distance import native_pallas_backend
    pallas_native = native_pallas_backend()
    use_pallas = os.environ.get("BENCH_PALLAS", "1") == "1" and pallas_native
    exact = os.environ.get("BENCH_EXACT", "0") == "1"
    # BENCH_DTYPE=bfloat16 stages attrs in bf16 — halves the upload bytes
    # that dominate the end-to-end on this link; pair with BENCH_EXACT=1
    # for checksum parity (f64 host rescore; tie-overflow repairs are
    # reported in path.repairs).
    dtype = os.environ.get("BENCH_DTYPE", "float32")
    # query_block 16384 lets the pipelined driver fold every query block in
    # one dispatch per chunk (the HBM tile budget still caps the live tile).
    cfg = EngineConfig(mode=mode, exact=exact, dtype=dtype,
                       query_block=16384, use_pallas=use_pallas)
    engine = make_engine(cfg)

    run = engine.run  # same pipeline for every mode -> comparable numbers
    run(inp)  # warmup: compile + first dispatch
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        run(inp)
        times.append((time.perf_counter() - t0) * 1e3)
    path = {
        "select": getattr(engine, "_last_select", cfg.select),
        "use_pallas": use_pallas,
        "pallas_native": pallas_native,
        "exact": exact,
        "dtype": cfg.resolve_dtype(),
        "repairs": getattr(engine, "last_repairs", None),
        "phases_ms": {name: round(ms, 1) for name, ms in
                      getattr(engine, "last_phase_ms", {}).items()},
    }
    return float(np.median(times)), path


def main() -> int:
    num_data = _env_int("BENCH_NUM_DATA", 200_000)
    num_queries = _env_int("BENCH_NUM_QUERIES", 10_000)
    num_attrs = _env_int("BENCH_NUM_ATTRS", 64)
    k = _env_int("BENCH_K", 32)
    repeats = _env_int("BENCH_REPEATS", 3)
    mode = os.environ.get("BENCH_MODE", "single")

    if mode == "train":
        from dmlp_tpu.train.bench import train_bench
        print(json.dumps(train_bench()))
        return 0

    inp = make_workload(num_data, num_queries, num_attrs, k)
    engine_ms, path = time_engine_ms(inp, mode, repeats)
    if os.environ.get("BENCH_DEVICE_SOLVE", "1") == "1":
        path["phases_ms"].update(
            time_device_solve_ms(inp, repeats, path["use_pallas"]))
    baseline_ms = time_baseline_ms(inp, k)

    pairs_per_s = num_data * num_queries / (engine_ms / 1e3)
    out = {
        "metric": "knn_solve_ms",
        "value": round(engine_ms, 3),
        "unit": "ms",
        "vs_baseline": round(baseline_ms / engine_ms, 3),
        "baseline_ms_est": round(baseline_ms, 1),
        # What the baseline IS (VERDICT r4 weak #4: the bare ratio invited
        # over-reading): a measured same-host BLAS argpartition KNN solve,
        # query-subsampled and linearly extrapolated — NOT the reference's
        # MPI binaries (for those see vs_reference_binary below).
        "baseline_kind": "host_cpu_blas_knn_extrapolated",
        "qd_pairs_per_sec": round(pairs_per_s),
        "shape": {"num_data": num_data, "num_queries": num_queries,
                  "num_attrs": num_attrs, "k": k, "mode": mode},
        "path": path,
    }
    # MEASURED reference-binary comparison, when a capture exists for this
    # shape (tools/capture_oracle.sh; bench_4's 200k x 10k x 64 config).
    # NOT an exact workload match: input3's per-query k is uniform in
    # [1, 32] while this bench fixes k=32 for EVERY query, so the engine
    # side solves the strictly harder workload and the multiple below is
    # conservative (ADVICE r5). The harness config 1-4 path compares
    # sha256-pinned identical inputs; this one trades that exactness for
    # a same-shape annotation. Still the real thing the estimated ratio
    # above is not: the reference's own stripped engine, run in THIS
    # container via isolated-singleton Open MPI, checksum-parity-verified
    # against this framework (oracle_capture/ORACLE_GOLDEN.json,
    # tools/oracle_diff.py).
    if (num_data, num_queries, num_attrs, k) == (200_000, 10_000, 64, 32):
        from dmlp_tpu.bench.harness import reference_binary_fields
        out.update(reference_binary_fields(
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "oracle_capture", "ORACLE_GOLDEN.json"),
            4, engine_ms))
    # Promote the fenced on-chip number: `value` includes host<->device
    # transfers, which on a tunneled link (10-50 MB/s measured) swing 2-4x
    # with link weather; the device solve is the architecture-bound,
    # run-to-run-comparable metric.
    dev = {k_: v for k_, v in path["phases_ms"].items()
           if k_.startswith("device_solve_ms_")}
    if dev:
        out["device_solve_ms"] = min(dev.values())
        out["device_qd_pairs_per_sec"] = round(
            num_data * num_queries / (out["device_solve_ms"] / 1e3))
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
