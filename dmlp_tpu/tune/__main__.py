"""CLI for the measured extract-kernel autotuner.

Regenerate the variant cache on the current backend::

    python -m dmlp_tpu.tune [--n 204800 --q 10240 --a 64 --k 32]
                            [--kc 40 --kc 136 ...] [--reps 3]
                            [--out PATH] [--record RUNRECORD.json]

The sweep measures at the CHUNKED dispatch shape the engines actually
use (plan_chunks on the extract granule) and merges winners into the
cache file (``$DMLP_TPU_TUNE_CACHE`` or
``~/.cache/dmlp_tpu/extract_variants.json``) keyed by (kernel, device
kind, data-rows bucket, kc, dtype, precision). Existing entries for
other keys are kept. ``--kernel extract|fused|both|prune_score|all``
(default both) picks which kernel's variant space to sweep — the fused
megakernel (ops.pallas_fused) caches under its own namespace, and
``prune_score`` sweeps the HOST block-scoring chunk that
ops.summaries.resolve_score_variant reads (satellite of the
low-precision first pass: the measured tiling replaces the guessed
_SCORE_BLOCK_CHUNK default). ``--precision f32|bf16|both`` (default
f32) re-sweeps the device kernels per first-pass dot precision — a
bf16 pass changes the MXU pass count per tile, so the winning tiles
differ and persist under the precision key axis (cache schema 3).

``--smoke`` runs a tiny-shape sweep (CPU interpret mode works) over a
4-variant slice PER KERNEL — the ``make tune-smoke`` CI gate that
proves the measure -> pick -> persist -> reload pipeline (fused sweep
included) and validates the cache schema end-to-end. ``--validate
PATH`` just schema-checks an existing cache file and exits.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="dmlp_tpu.tune", description=__doc__)
    ap.add_argument("--n", type=int, default=204800)
    ap.add_argument("--q", type=int, default=10240)
    ap.add_argument("--a", type=int, default=64)
    ap.add_argument("--k", type=int, action="append", default=None,
                    help="workload k (repeatable); kc derives via "
                         "resolve_kcap with float32 staging")
    ap.add_argument("--kc", type=int, action="append", default=None,
                    help="candidate-list width to tune directly "
                         "(repeatable; overrides --k derivation)")
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--kernel",
                    choices=("extract", "fused", "both",
                             "prune_score", "all"),
                    default="both",
                    help="which kernel's variant space to sweep (the "
                         "fused megakernel caches under its own "
                         "namespace; prune_score sweeps the host "
                         "block-scoring chunk; all = every kernel)")
    ap.add_argument("--precision", choices=("f32", "bf16", "both"),
                    default="f32",
                    help="first-pass dot precision(s) to sweep the "
                         "device kernels at — winners persist under "
                         "the cache's precision key axis (prune_score "
                         "is host f64 and ignores this)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default=None,
                    help="cache file (default: the lookup path — "
                         "$DMLP_TPU_TUNE_CACHE or ~/.cache/dmlp_tpu/"
                         "extract_variants.json)")
    ap.add_argument("--record", default=None,
                    help="also write one schema-1 RunRecord (obs.run) "
                         "summarizing the sweep")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny-shape 4-variant sweep (CPU CI gate)")
    ap.add_argument("--validate", metavar="PATH", default=None,
                    help="schema-check an existing cache file and exit")
    ap.add_argument("--compile-cache", metavar="DIR", default=None,
                    help="persistent XLA compilation cache "
                         "(utils.compile_cache; $DMLP_TPU_COMPILE_CACHE "
                         "is the ambient form) — a sweep compiles every "
                         "variant once, so a re-sweep against the same "
                         "dir skips straight to the run-time "
                         "measurements")
    args = ap.parse_args(argv)

    from dmlp_tpu.utils.compile_cache import enable_from_flag
    enable_from_flag(args.compile_cache)

    from dmlp_tpu.tune.cache import (VariantCache, cache_path,
                                     clear_lookup_memo)

    if args.validate:
        try:
            with open(args.validate) as f:
                doc = json.load(f)
            VariantCache.validate_doc(doc)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"tune: INVALID cache {args.validate}: {e}",
                  file=sys.stderr)
            return 1
        print(f"tune: cache ok — {len(doc['entries'])} entries "
              f"({args.validate})")
        return 0

    from dmlp_tpu.tune.sweep import (smoke_space, sweep_extract,
                                     sweep_prune_score)

    if args.smoke:
        n, nq, a = 1024, 16, 8
        kcs = [16]
        reps = 1
        space_fn = smoke_space
    else:
        n, nq, a = args.n, args.q, args.a
        reps = args.reps
        space_fn = None
        if args.kc:
            kcs = sorted(set(args.kc))
        else:
            from dmlp_tpu.config import EngineConfig
            from dmlp_tpu.engine.single import resolve_kcap
            ks = args.k or [32]
            kcs = sorted({resolve_kcap(EngineConfig(), k, "extract",
                                       1 << 30, staging="float32")
                          for k in ks})

    out_path = args.out or cache_path()
    kernels = {"both": ("extract", "fused"),
               "all": ("extract", "fused", "prune_score")}.get(
        args.kernel, (args.kernel,))
    precisions = ("f32", "bf16") if args.precision == "both" \
        else (args.precision,)
    print(f"tune: sweeping {'+'.join(kernels)} variants at n={n} q={nq} "
          f"a={a} kcs={kcs} reps={reps} "
          f"precisions={'+'.join(precisions)} -> {out_path}",
          flush=True)
    kwargs = {} if space_fn is None else {"space_fn": space_fn}
    winners, rows = [], []
    for kern in kernels:
        if kern == "prune_score":
            # Host f64 scoring has no first-pass precision axis.
            w, r = sweep_prune_score(n, nq, a, reps=reps,
                                     seed=args.seed, out=sys.stdout)
            winners += w
            rows += r
            continue
        for prec in precisions:
            w, r = sweep_extract(n, nq, a, kcs, reps=reps,
                                 seed=args.seed, out=sys.stdout,
                                 kernel=kern, precision=prec, **kwargs)
            winners += w
            rows += r
    if not winners:
        print("tune: FAIL — no variant measured for any kc",
              file=sys.stderr)
        return 1

    import os

    from dmlp_tpu.tune.cache import _current_device_kind
    kind = _current_device_kind()
    try:
        cache = VariantCache.load(out_path) if os.path.exists(out_path) \
            else VariantCache()
    except Exception:
        cache = VariantCache()  # unreadable/stale-schema file: rebuild
    for w in winners:
        cache.put(kind, w["b"], w["kc"], w["variant"], a=a,
                  dtype="float32",
                  kernel={"fused": "fused_topk",
                          "prune_score": "prune_score"}.get(
                      w["kernel"], "extract_topk"),
                  precision=w.get("precision", "f32"),
                  measured_ms=w["measured_ms"],
                  swept=w["swept"], shape=(w["qb"], w["b"], a))
    cache.save(out_path)
    clear_lookup_memo()  # this process sees its own fresh winners
    VariantCache.validate_doc(cache.to_dict())

    if args.record:
        from dmlp_tpu.obs.run import RunRecord
        RunRecord(kind="tune", tool="dmlp_tpu.tune",
                  config={"n": n, "q": nq, "a": a, "kcs": list(kcs),
                          "reps": reps, "device_kind": kind,
                          "smoke": bool(args.smoke)},
                  metrics={"winners": winners, "sweep_rows": rows},
                  artifacts={"cache": out_path}).write(args.record)

    print(json.dumps({"device_kind": kind, "cache": out_path,
                      "entries": len(cache.entries),
                      "winners": [{"kernel": w["kernel"], "kc": w["kc"],
                                   "b": w["b"], "variant": w["variant"],
                                   "precision": w.get("precision",
                                                      "f32")}
                                  for w in winners]}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
