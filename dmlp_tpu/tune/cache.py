"""Persisted variant cache for the measured autotuner.

One small versioned JSON file maps (kernel, device kind, data-rows shape
bucket, kc, dtype, precision) -> the fastest measured kernel variant.
The file is written by the sweep (``python -m dmlp_tpu.tune``) and read
on the hot path by ``ops.pallas_extract._resolve_variant`` (kernel
"extract_topk") and ``ops.pallas_fused._resolve_variant`` (kernel
"fused_topk") through :func:`lookup_variant`. Schema 2 added the
per-entry kernel namespace: the fused megakernel's MXU gate shifts
which tiles win, so the two kernels sweep and cache independently.
Schema 3 added the first-pass precision axis: a bf16 dot spends one
MXU pass per tile where HIGHEST-precision f32 spends ~3, which moves
the compute/traffic balance — and hence the winning tile — so the two
precisions sweep and cache independently. Old files still LOAD:
schema-1 keys upgrade to the extract namespace, schema-1 AND schema-2
keys take the "f32" precision suffix in memory (every pre-schema-3
measurement WAS an f32-pass measurement); saves always write schema 3.

Design constraints, in order:

- **Absent cache == today.** When the file does not exist the lookup
  returns None without importing jax or touching a backend — CPU/CI
  resolution stays bit-identical to the frozen heuristics (and a read
  can never accidentally dial the remote TPU just to learn the device
  kind; the kind is only needed once a file with entries exists).
- **Keys are buckets, not exact shapes.** Data-row and attribute-width
  counts bucket to the next power of two: the variant ranking moves
  with the block-sweep regime (how many blocks amortize the warm-up)
  and with the VMEM footprint `a` drives, not with every ±5% of rows,
  and exact-shape keys would make every new dataset a cache miss. kc
  is already discrete (resolve_kcap rounds to 8) and keys directly.
- **A cache entry must never disable the kernel.** The envelope is
  validated on load (schema/kernel), each entry is re-validated at
  lookup (one corrupt entry misses itself, it does not poison the
  file's other winners), ne-alignment is re-checked against the
  concrete ``b``, and the resolver re-runs the full supports gate
  (VMEM included) on a cache hit — anything that fails falls through
  to the heuristic instead of erroring or flipping supports() False.
"""

from __future__ import annotations

import contextlib
import json
import os
import time
from typing import Any, Dict, Optional, Tuple

#: bump on any backward-incompatible cache field change (2: per-entry
#: kernel namespace — extract_topk vs the fused megakernel; 3: the
#: first-pass precision key axis — f32 vs bf16 winners cached apart)
CACHE_SCHEMA = 3

#: legal first-pass precision key segments (config.EngineConfig
#: .precision resolved; int8 is the gated ROADMAP follow-on)
_PRECISIONS = ("f32", "bf16")

#: the schema-2 envelope family; per-entry keys carry the concrete kernel
_KERNEL_FAMILY = "pallas_topk"
#: legal per-entry kernel namespaces ("prune_score" is the pruned
#: two-stage solve's block-scoring pass — ops.summaries resolves its
#: block-chunk tiling through the same contract)
_KERNELS = ("extract_topk", "fused_topk", "prune_score")
#: the schema-1 envelope value (extract-only caches; lenient load)
_KERNEL_V1 = "extract_topk"

#: legal extraction-candidates-per-pass values (quarter layout: ne must
#: divide the block into whole 128-lane sub-blocks)
_NE_CHOICES = (1, 2, 4, 8)


def cache_path() -> str:
    """The cache file location: ``$DMLP_TPU_TUNE_CACHE`` wins, else
    ``~/.cache/dmlp_tpu/extract_variants.json``."""
    env = os.environ.get("DMLP_TPU_TUNE_CACHE")
    if env:
        return env
    return os.path.join(os.path.expanduser("~"), ".cache", "dmlp_tpu",
                        "extract_variants.json")


def shape_bucket(b: int) -> int:
    """Data-row count -> power-of-two bucket (the smallest power of two
    >= b). 12800 and 16000 share a bucket; 12800 and 51200 do not."""
    if b <= 1:
        return 1
    return 1 << (b - 1).bit_length()


def _key(kernel: str, device_kind: str, b_bucket: int, a_bucket: int,
         kc: int, dtype: str, precision: str = "f32") -> str:
    return (f"{kernel}|{device_kind}|b{b_bucket}|a{a_bucket}|kc{kc}"
            f"|{dtype}|{precision}")


def validate_variant(v: Any) -> bool:
    """Structural sanity of one variant dict (no jax, no shape context):
    tile_q a positive multiple of 8, ne a legal quarter count, unroll a
    small positive int, optional tile_n a positive multiple of 128*ne."""
    if not isinstance(v, dict):
        return False
    tq, ne, unroll = v.get("tile_q"), v.get("ne"), v.get("unroll", 1)
    if not (isinstance(tq, int) and tq > 0 and tq % 8 == 0):
        return False
    if ne not in _NE_CHOICES:
        return False
    if not (isinstance(unroll, int) and 1 <= unroll <= 8):
        return False
    tn = v.get("tile_n")
    if tn is not None and not (isinstance(tn, int) and tn > 0
                               and tn % (128 * ne) == 0):
        return False
    return True


def variant_fits(v: Dict[str, Any], b: int, kc: int) -> bool:
    """Alignment gate for a concrete dispatch: the variant's ne must tile
    ``b`` into whole 128-lane sub-blocks and kc must fit one block (the
    fresh-seed slice reads the first kc columns). The VMEM bound is
    enforced downstream by supports()/extract_topk with this same
    variant — this gate only rejects what could not even tile."""
    if b % (128 * v["ne"]) != 0:
        return False
    tn = v.get("tile_n")
    if tn is not None and kc > tn:
        return False
    return True


class VariantCache:
    """In-memory form of the cache file; save()/load() round-trip it."""

    def __init__(self, entries: Optional[Dict[str, Dict]] = None,
                 created_unix: Optional[float] = None):
        self.entries: Dict[str, Dict] = dict(entries or {})
        self.created_unix = (time.time() if created_unix is None
                             else created_unix)

    # -- mutation ------------------------------------------------------------
    def put(self, device_kind: str, b: int, kc: int, variant: Dict, *,
            a: int, dtype: str = "float32",
            kernel: str = "extract_topk", precision: str = "f32",
            measured_ms: Optional[float] = None,
            swept: Optional[int] = None,
            shape: Optional[Tuple[int, int, int]] = None) -> str:
        """Record the winning ``variant`` for (kernel, device, bucket(b),
        bucket(a), kc, dtype, precision); returns the entry key. ``a``
        (the swept attribute width) is part of the key: the VMEM
        footprint — and hence which variants even fit — scales with it.
        ``precision`` is the first-pass dot precision the measurement
        ran at (MXU passes per tile differ, so winners do too). Raises
        ValueError on a variant that fails structural validation — a
        sweep must never persist a variant the hot path would have to
        reject — or on an unknown kernel namespace or precision."""
        if not validate_variant(variant):
            raise ValueError(f"invalid variant {variant!r}")
        if kernel not in _KERNELS:
            raise ValueError(f"unknown kernel namespace {kernel!r}")
        if precision not in _PRECISIONS:
            raise ValueError(f"unknown precision {precision!r}")
        key = _key(kernel, device_kind, shape_bucket(b), shape_bucket(a),
                   kc, dtype, precision)
        entry: Dict[str, Any] = {"variant": dict(variant),
                                 "created_unix": time.time()}
        if measured_ms is not None:
            entry["measured_ms"] = round(float(measured_ms), 4)
        if swept is not None:
            entry["swept"] = int(swept)
        if shape is not None:
            entry["shape"] = list(shape)
        self.entries[key] = entry
        return key

    # -- read ----------------------------------------------------------------
    def get(self, device_kind: str, b: int, kc: int, *, a: int,
            dtype: str = "float32", kernel: str = "extract_topk",
            precision: str = "f32") -> Optional[Dict]:
        """The cached variant for (kernel, device, bucket(b), bucket(a),
        kc, dtype, precision), after per-entry validation and the
        per-dispatch alignment gate — None on miss, corrupt entry, or
        misfit."""
        e = self.entries.get(
            _key(kernel, device_kind, shape_bucket(b), shape_bucket(a),
                 kc, dtype, precision))
        if not isinstance(e, dict):
            return None
        v = e.get("variant")
        if not validate_variant(v) or not variant_fits(v, b, kc):
            return None
        return dict(v)

    # -- persistence ---------------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        return {"schema": CACHE_SCHEMA, "kernel": _KERNEL_FAMILY,
                "created_unix": self.created_unix, "entries": self.entries}

    def save(self, path: Optional[str] = None) -> str:
        path = path or cache_path()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.to_dict(), f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
        return path

    @staticmethod
    def validate_doc(doc: Any) -> None:
        """Raise ValueError naming the first schema violation (the
        tune-smoke CI gate calls this on the file it just wrote).
        Accepts schema 3 (kernel-namespaced, precision-suffixed keys)
        and grandfathered schema-1 (extract-only) / schema-2
        (no precision axis) files."""
        if not isinstance(doc, dict):
            raise ValueError("cache is not a JSON object")
        schema = doc.get("schema")
        if schema not in (1, 2, CACHE_SCHEMA):
            raise ValueError(f"cache schema {schema!r} not in "
                             f"(1, 2, {CACHE_SCHEMA}) "
                             "(regenerate with python -m dmlp_tpu.tune)")
        want_kernel = _KERNEL_V1 if schema == 1 else _KERNEL_FAMILY
        if doc.get("kernel") != want_kernel:
            raise ValueError(f"cache kernel {doc.get('kernel')!r} != "
                             f"{want_kernel!r}")
        entries = doc.get("entries")
        if not isinstance(entries, dict):
            raise ValueError("cache entries block missing or not a dict")
        for key, e in entries.items():
            if schema >= 2 and key.split("|", 1)[0] not in _KERNELS:
                raise ValueError(f"entry {key!r} has no kernel namespace")
            if schema == CACHE_SCHEMA \
                    and key.rsplit("|", 1)[-1] not in _PRECISIONS:
                raise ValueError(f"entry {key!r} has no precision "
                                 "suffix")
            if not isinstance(e, dict) or not validate_variant(
                    e.get("variant")):
                raise ValueError(f"entry {key!r} carries an invalid "
                                 f"variant: {e!r}")

    @classmethod
    def load(cls, path: Optional[str] = None) -> "VariantCache":
        """Load with ENVELOPE validation only (schema/kernel/entries
        shape) — raises on an unreadable or wrong-schema file, but a
        single corrupt ENTRY does not poison the rest: per-entry
        validation happens at ``get()``, so the file's other winners
        stay live. Old files load LENIENTLY so a tuned machine keeps
        its winners across a schema bump (the next sweep re-saves at
        the current schema): schema-1 keys (extract-only, pre-fused)
        upgrade to the extract_topk namespace, and schema-1/2 keys
        (pre-precision-axis) take the "f32" suffix — every measurement
        they carry was an f32-pass measurement, so the upgrade changes
        the key, never the meaning. The strict whole-file check (every
        entry valid) is :meth:`validate_doc` — the ``--validate`` CI
        gate."""
        path = path or cache_path()
        with open(path) as f:
            doc = json.load(f)
        if isinstance(doc, dict) and doc.get("schema") == 1 \
                and doc.get("kernel") == _KERNEL_V1 \
                and isinstance(doc.get("entries"), dict):
            entries = {f"{_KERNEL_V1}|{k}|f32": e
                       for k, e in doc["entries"].items()}
            return cls(entries=entries,
                       created_unix=doc.get("created_unix"))
        if isinstance(doc, dict) and doc.get("schema") == 2 \
                and doc.get("kernel") == _KERNEL_FAMILY \
                and isinstance(doc.get("entries"), dict):
            entries = {f"{k}|f32": e for k, e in doc["entries"].items()}
            return cls(entries=entries,
                       created_unix=doc.get("created_unix"))
        if not isinstance(doc, dict) or doc.get("schema") != CACHE_SCHEMA \
                or doc.get("kernel") != _KERNEL_FAMILY \
                or not isinstance(doc.get("entries"), dict):
            raise ValueError(
                f"{path}: not a schema-{CACHE_SCHEMA} {_KERNEL_FAMILY} "
                "variant cache (regenerate with python -m dmlp_tpu.tune)")
        return cls(entries=doc["entries"],
                   created_unix=doc.get("created_unix"))


# -- hot-path lookup (memoized, never raises) --------------------------------
_memo: Dict[str, Optional[VariantCache]] = {}
_device_kind_memo: Dict[str, str] = {}

#: >0 = lookups disabled (the degradation ladder's "heuristic" rung:
#: after a device OOM the first thing to give back is a swept variant's
#: larger tiles — resilience.degrade enters this context for the
#: retried solve, and resolution falls to the bit-identical heuristic).
_suppress_depth = 0


@contextlib.contextmanager
def suppressed():
    """Context manager disabling cache lookups for its duration."""
    global _suppress_depth
    _suppress_depth += 1
    try:
        yield
    finally:
        _suppress_depth -= 1


def clear_lookup_memo() -> None:
    """Drop the per-process cache/device memo (tests, or after a sweep
    rewrites the file mid-process)."""
    _memo.clear()
    _device_kind_memo.clear()


def _current_device_kind() -> str:
    """The backend's device kind ("TPU v5 lite", "cpu", ...), memoized.
    Only called once a cache file with entries exists — a missing cache
    must never be the thing that initializes a backend."""
    kind = _device_kind_memo.get("kind")
    if kind is None:
        try:
            import jax
            d = jax.devices()[0]
            kind = d.device_kind if d.platform == "tpu" else d.platform
        except Exception:
            kind = "unknown"
        _device_kind_memo["kind"] = kind
    return kind


def lookup_variant(kc: int, b: int, a: Optional[int] = None,
                   dtype: str = "float32",
                   device_kind: Optional[str] = None,
                   path: Optional[str] = None,
                   kernel: str = "extract_topk",
                   precision: str = "f32") -> Optional[Dict]:
    """The hot-path read: cached variant for this dispatch, or None.

    ``kernel`` selects the namespace ("extract_topk" | "fused_topk" —
    the fused megakernel sweeps and caches separately); ``precision``
    the first-pass-dot key axis (f32 and bf16 winners cached apart —
    the MXU pass count per tile differs). Never raises;
    returns None when ``a`` is unknown (the attribute width is part of
    the key — every real dispatch site knows it), the cache file is
    absent, unreadable, schema-invalid, keyed for a different device
    kind, the matched entry is corrupt, or its variant cannot tile this
    ``b`` (alignment rejection) — the caller then uses the
    deterministic heuristic."""
    if _suppress_depth or a is None:
        return None
    path = path or cache_path()
    if path not in _memo:
        if not os.path.exists(path):
            _memo[path] = None
        else:
            try:
                _memo[path] = VariantCache.load(path)
            except Exception:
                _memo[path] = None
    cache = _memo[path]
    if cache is None or not cache.entries:
        return None
    if device_kind is None:
        device_kind = _current_device_kind()
    return cache.get(device_kind, b, kc, a=a, dtype=dtype, kernel=kernel,
                     precision=precision)
