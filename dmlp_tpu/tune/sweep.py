"""Fenced measured sweep over the extract/fused kernel variant spaces.

For each requested (kernel, shape, kc) the sweep enumerates every
variant the kernel can actually tile — ``tile_q`` x ``tile_n`` x ``ne``
x ``unroll``, gated by ``ops.pallas_extract.variant_supports`` so the
sweep can never persist a variant the hot path would reject — times each
with the dependent-readback fence the bench tools share
(block_until_ready is unreliable over tunneled PJRT links), and records
the winner in the variant cache (:mod:`dmlp_tpu.tune.cache`) under that
kernel's namespace. The fused megakernel (ops.pallas_fused) shares the
tile space but sweeps separately: its MXU gate turns warm no-improve
blocks into one VPU bound pass, which shifts the block-size trade-off
the winner encodes.

Two honesty rules carried over from the bench methodology:

- compile + the eager perturbation chain are warmed OUT of the timed
  region (the r2 mismeasurement: the chain's tiny kernels compile on
  first use, ~1.2 s over a remote-compile tunnel);
- a variant that fails to compile (Mosaic tiling edge) is skipped and
  counted, never silently dropped — the summary names how much of the
  space was actually measured.

The sweep also probes kc padding: timing the winner at kc+8 records
whether a wider running list would be cheaper per candidate
(``kc_pad_probe_ms`` in the cache entry, informational — engines keep
the semantic kc that resolve_kcap derived from the workload's k).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["variant_space", "sweep_extract", "sweep_prune_score",
           "smoke_space"]

_TQ_CHOICES = (32, 64, 128, 256)
_NE_CHOICES = (2, 4, 8)
_UNROLL_CHOICES = (1, 2)


def variant_space(qb: int, b: int, a: int, kc: int,
                  tile_n_targets: Optional[Sequence[int]] = None
                  ) -> List[Dict]:
    """Every variant the kernel can tile at this dispatch shape.

    ``tile_n`` candidates default to whole-block fractions of the
    kernel's default block (full, half, quarter), snapped per-ne to the
    128*ne lane granule; degenerate/duplicate resolutions collapse."""
    from dmlp_tpu.ops.pallas_extract import BLOCK_ROWS, variant_supports

    targets = tuple(tile_n_targets or
                    (BLOCK_ROWS, BLOCK_ROWS // 2, BLOCK_ROWS // 4))
    out: List[Dict] = []
    seen = set()
    for ne in _NE_CHOICES:
        gran = 128 * ne
        for tn_t in targets:
            tn = max(gran, tn_t - tn_t % gran)
            for tq in _TQ_CHOICES:
                for unroll in _UNROLL_CHOICES:
                    v = {"tile_q": tq, "tile_n": tn, "ne": ne,
                         "unroll": unroll}
                    key = (tq, tn, ne, unroll)
                    if key in seen:
                        continue
                    seen.add(key)
                    if variant_supports(qb, b, a, kc, v):
                        out.append(v)
    return out


def smoke_space(qb: int, b: int, a: int, kc: int) -> List[Dict]:
    """A ~4-variant slice of the space for the CPU CI smoke: one per ne
    choice plus an unroll=2 point — enough to exercise the measure ->
    pick -> persist -> reload pipeline without minutes of interpret-mode
    emulation."""
    space = variant_space(qb, b, a, kc)
    picked: List[Dict] = []
    for ne in _NE_CHOICES:
        for v in space:
            if v["ne"] == ne and v["unroll"] == 1:
                picked.append(v)
                break
    for v in space:
        if v["unroll"] == 2:
            picked.append(v)
            break
    return picked[:4]


def _fenced_ms(fn, q, d, reps: int) -> float:
    """bench.time_fenced_solve_ms methodology, local so the package does
    not depend on the repo-root driver script: compile + fence, warm the
    perturbation chain, then time ``reps`` chained dispatches bounded by
    a dependent scalar readback."""
    r = fn(q, d)
    _ = float(r[0, 0])
    r = fn(q + 0.0 * r[0, 0], d)
    _ = float(r[0, 0])
    t0 = time.perf_counter()
    for _i in range(reps):
        r = fn(q + 0.0 * r[0, 0], d)
    _ = float(r[0, 0])
    return (time.perf_counter() - t0) / reps * 1e3


def time_variant_ms(q, d, n_real: int, kc: int, v: Dict, reps: int,
                    interpret: bool, warm_folds: int = 1,
                    kernel: str = "extract",
                    precision: str = "f32") -> float:
    """Fenced time of one kernel variant at the staged arrays: one FRESH
    dispatch plus ``warm_folds`` carry folds over the same block. The
    engines' hot path is a chunk chain — one cold fold, then warm folds
    where the running lists gate most blocks out (the block skip's —
    and for ``kernel="fused"``, the MXU gate's — whole win) — so
    ranking variants on the cold dispatch alone would pick winners at
    an operating point the chain mostly doesn't run; the 1-cold +
    1-warm chain weights both regimes. Raises whatever the compile
    raises — the sweep catches and skips."""
    from dmlp_tpu.ops.pallas_extract import extract_topk

    b = d.shape[0]
    kw = dict(kc=kc, interpret=interpret, tile_q=v["tile_q"],
              tile_n=v["tile_n"], ne=v["ne"], unroll=v["unroll"],
              mxu_gate=kernel == "fused", precision=precision)

    def fn(q_, d_):
        od, oi, _it = extract_topk(q_, d_, n_real=n_real, **kw)
        for w in range(1, warm_folds + 1):
            od, oi, _it = extract_topk(q_, d_, od, oi, n_real=n_real,
                                       id_base=w * b, **kw)
        return od
    return _fenced_ms(fn, q, d, reps)


def sweep_extract(n: int, nq: int, a: int, kcs: Sequence[int],
                  reps: int = 3, seed: int = 0,
                  space_fn=variant_space, out=None,
                  kernel: str = "extract", precision: str = "f32",
                  ) -> Tuple[List[Dict], List[Dict]]:
    """Measure the variant space at BOTH dispatch shapes the engines use
    for an (n, nq, a) workload and return (winners, detail rows).

    Two timed ``b`` points per kc (deduped when they coincide):

    - the CHUNKED shape (plan_chunks on the extract granule) — what
      engine.single._solve_extract dispatches per staged chunk;
    - the WHOLE padded dataset — what the multipass resident passes,
      bench's device-solve path, and tools/roofline_extract.py
      dispatch. Without this point the documented on-hardware recipe
      (tune, then roofline) would resolve the roofline's b=npad
      dispatch in a bucket the sweep never keyed and silently fall
      back to the heuristic.

    Queries pad to whole query tiles. ``kernel`` ("extract" | "fused")
    selects which kernel the variants drive; winners persist under that
    kernel's cache namespace. ``precision`` ("f32" | "bf16") selects
    the first-pass dot dtype the variants are timed WITH — a bf16
    first pass changes MXU pass count and hence which tile shapes win,
    so winners carry the precision and persist under that key axis of
    the cache (schema 3). ``winners`` is a list of
    {"kernel", "kc", "b", "qb", "variant", "precision", "measured_ms",
    "swept", "skipped_compile", "kc_pad_probe_ms"?} records — one per
    (kc, b point) that measured at least one variant.
    """
    import numpy as np

    import jax.numpy as jnp
    from dmlp_tpu.engine.single import plan_chunks, round_up
    from dmlp_tpu.ops.pallas_distance import native_pallas_backend
    from dmlp_tpu.ops.pallas_extract import QUERY_TILE, BLOCK_ROWS

    log = (lambda *_: None) if out is None else \
        (lambda *a_: print(*a_, file=out, flush=True))
    npad, _nchunks, chunk_rows = plan_chunks(n, BLOCK_ROWS, None)
    qpad = round_up(max(nq, 1), QUERY_TILE)
    interpret = not native_pallas_backend()
    b_points = sorted({chunk_rows, npad})

    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.uniform(0.0, 100.0, (qpad, a)), jnp.float32)

    winners: List[Dict] = []
    rows: List[Dict] = []
    for b in b_points:
        d = jnp.asarray(rng.uniform(0.0, 100.0, (b, a)), jnp.float32)
        n_real = min(n, b)
        float(jnp.sum(d))  # fence staging
        for kc in kcs:
            space = space_fn(qpad, b, a, kc)
            best: Optional[Dict] = None
            best_ms = float("inf")
            skipped = 0
            for v in space:
                try:
                    ms = time_variant_ms(q, d, n_real, kc, v, reps,
                                         interpret, kernel=kernel,
                                         precision=precision)
                except Exception as e:  # Mosaic tiling edge: skip, count
                    skipped += 1
                    rows.append({"kernel": kernel, "kc": kc, "b": b,
                                 "variant": v, "error": str(e)[:200]})
                    continue
                rows.append({"kernel": kernel, "kc": kc, "b": b,
                             "variant": v, "ms": round(ms, 3)})
                log(f"  {kernel} b={b} kc={kc} {v} -> {ms:.2f} ms")
                if ms < best_ms:
                    best, best_ms = v, ms
            if best is None:
                log(f"  {kernel} b={b} kc={kc}: no variant measured "
                    f"({skipped} compile-skipped of {len(space)})")
                continue
            entry = {"kernel": kernel, "kc": kc, "b": b, "qb": qpad,
                     "variant": best, "precision": precision,
                     "measured_ms": best_ms,
                     "swept": len(space) - skipped,
                     "skipped_compile": skipped}
            # kc-padding probe: the winner at kc+8 — informational only.
            try:
                entry["kc_pad_probe_ms"] = round(
                    time_variant_ms(q, d, n_real, kc + 8, best, reps,
                                    interpret, kernel=kernel,
                                    precision=precision), 3)
            except Exception:
                pass
            winners.append(entry)
            log(f"  {kernel} b={b} kc={kc}: winner {best} at "
                f"{best_ms:.2f} ms "
                f"({entry['swept']} measured, {skipped} skipped)")
    return winners, rows


#: host block-chunk candidates for the prune_score sweep — the slab
#: width block_bounds/piece_bounds vectorize over; bounded above so the
#: (Q, chunk, P, A) f64 temp stays tens of MB at bench-scale q counts
_CHUNK_CHOICES = (32, 64, 128, 256, 512)


def sweep_prune_score(n: int, nq: int, a: int, reps: int = 3,
                      seed: int = 0, out=None,
                      chunks: Sequence[int] = _CHUNK_CHOICES,
                      ) -> Tuple[List[Dict], List[Dict]]:
    """Measured sweep of the HOST block-scoring chunk (the
    ``prune_score`` tune-cache namespace ops.summaries.
    resolve_score_variant reads): time prune_mask's bound computation —
    block_bounds plus, with the split format, piece_bounds at the
    halved chunk — per block-chunk candidate over summaries built at
    the engines' extract-granule block layout, and return (winners,
    rows) in the sweep_extract record shape.

    The chunk trades f64 slab temp size against numpy dispatch count:
    too small and the per-chunk einsum overhead dominates, too large
    and the (Q, chunk, A) temp falls out of cache. Winners key at the
    EXACT lookup point resolve_score_variant uses — kc=8 (a fixed
    namespace tag, not a candidate width) and b=n_blocks — with
    ``variant = {"tile_q": chunk, "ne": 1, "unroll": 1}``. Host f64
    scoring has no low-precision first pass, so winners always carry
    precision "f32" (the only key the resolver looks under).
    """
    import numpy as np

    from dmlp_tpu.ops.pallas_extract import BLOCK_ROWS
    from dmlp_tpu.ops.summaries import (PIECES, block_bounds,
                                        build_summaries, piece_bounds)

    log = (lambda *_: None) if out is None else \
        (lambda *a_: print(*a_, file=out, flush=True))
    rng = np.random.default_rng(seed)
    attrs = rng.uniform(0.0, 100.0, (n, a)).astype(np.float32)
    ranges = [(i, min(i + BLOCK_ROWS, n))
              for i in range(0, n, BLOCK_ROWS)]
    summ = build_summaries(attrs, ranges)
    q = rng.uniform(0.0, 100.0, (nq, a))

    def _time_chunk(chunk: int) -> float:
        def run():
            block_bounds(q, summ, block_chunk=chunk)
            if summ.pcounts is not None:
                piece_bounds(q, summ,
                             block_chunk=max(1, chunk // PIECES))
        run()  # warm allocator / page-fault the summary arrays
        t0 = time.perf_counter()
        for _ in range(reps):
            run()
        return (time.perf_counter() - t0) / reps * 1e3

    winners: List[Dict] = []
    rows: List[Dict] = []
    best, best_ms = None, float("inf")
    for chunk in sorted(set(int(c) for c in chunks)):
        v = {"tile_q": chunk, "ne": 1, "unroll": 1}
        ms = _time_chunk(chunk)
        rows.append({"kernel": "prune_score", "kc": 8,
                     "b": summ.n_blocks, "variant": v,
                     "ms": round(ms, 3)})
        log(f"  prune_score blocks={summ.n_blocks} chunk={chunk} "
            f"-> {ms:.2f} ms")
        if ms < best_ms:
            best, best_ms = v, ms
    if best is not None:
        winners.append({"kernel": "prune_score", "kc": 8,
                        "b": summ.n_blocks, "qb": nq, "variant": best,
                        "precision": "f32", "measured_ms": best_ms,
                        "swept": len(rows), "skipped_compile": 0})
        log(f"  prune_score blocks={summ.n_blocks}: winner {best} at "
            f"{best_ms:.2f} ms ({len(rows)} measured)")
    return winners, rows
