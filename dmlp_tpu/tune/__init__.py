"""Measured kernel autotuning (ISSUE 3 tentpole).

The extract kernel's variant space — ``tile_q`` (query rows per tile),
``tile_n`` (data rows per block), ``ne`` (extraction candidates per loop
pass), ``unroll`` (rounds per loop-condition sync) — was frozen by two
hand-tuned heuristics measured on ONE shape on ONE chip
(ops.pallas_extract.tuned_variant). This package replaces the frozen
constants with *measured, cached* selection:

- :mod:`dmlp_tpu.tune.sweep` times every legal variant with the fenced
  dependent-readback methodology the bench tools share and picks the
  fastest;
- :mod:`dmlp_tpu.tune.cache` persists winners in a small versioned JSON
  cache keyed by (device kind, data-rows shape bucket, kc, dtype);
- :func:`lookup_variant` is the hot-path read:
  ``ops.pallas_extract._resolve_variant`` consults it first and falls
  back to the deterministic heuristic when there is no entry — an
  absent cache (CPU, CI, fresh hardware) keeps today's behavior
  bit-identical.

Cache location: ``$DMLP_TPU_TUNE_CACHE`` if set, else
``~/.cache/dmlp_tpu/extract_variants.json``. Regenerate on new hardware
with ``python -m dmlp_tpu.tune`` (see README "Autotuning").
"""

from __future__ import annotations

from dmlp_tpu.tune.cache import (CACHE_SCHEMA, VariantCache, cache_path,
                                 clear_lookup_memo, lookup_variant,
                                 shape_bucket)

__all__ = ["CACHE_SCHEMA", "VariantCache", "cache_path",
           "clear_lookup_memo", "lookup_variant", "shape_bucket"]
