"""Drop-in engine CLI: reads the input grammar on stdin, writes results.

The TPU-native equivalent of ``mpirun ./engine < input`` (reference
common.cpp:81-135 + run_bench.sh:82-84): stdout carries per-query results
(checksums, or the -DDEBUG listing with ``--debug``), stderr carries the
``Time taken: <ms> ms`` contract line. No mpirun: one process drives the
device mesh.

Observability (dmlp_tpu.obs) is opt-in and leaves both contract channels
byte-identical: ``--trace FILE`` writes a Perfetto-loadable span trace,
``--metrics FILE`` appends JSONL records whose final summary carries XLA
cost-analysis counters (or an explicit ``counters_unavailable`` marker)
and collective-traffic accounting; ``--counters`` prints a roofline
summary to stderr after the contract line.

Usage::

    python -m dmlp_tpu [--mode single|sharded|ring|auto] [--debug] [--fast]
                       [--engine jax|golden|auto] [--phase-times]
                       [--compile-cache DIR] [--hlo-report FILE]
                       [--trace FILE] [--metrics FILE] [--counters] < input.in
"""

from __future__ import annotations

import argparse
import dataclasses
import sys
from typing import IO, Optional, Sequence

from dmlp_tpu.config import EngineConfig
from dmlp_tpu.io.grammar import parse_input
from dmlp_tpu.io.report import format_results
from dmlp_tpu.obs.trace import span as obs_span
from dmlp_tpu.utils.timing import EngineTimer


def parse_mesh_arg(parser, value):
    """Validate an R,C mesh flag (argparse usage error, not a traceback)."""
    if not value:
        return None
    parts = value.split(",")
    if len(parts) != 2 or not all(p.strip().lstrip("-").isdigit()
                                  for p in parts):
        parser.error(f"--mesh expects R,C (two integers), got {value!r}")
    r, c = int(parts[0]), int(parts[1])
    if r <= 0 or c <= 0:
        parser.error(f"--mesh axes must be positive, got {value!r}")
    return (r, c)


def make_engine(config: EngineConfig, stderr=None):
    """Engine registry (lazy imports keep CLI start light).

    An explicit mesh_shape that needs more devices than this host has
    falls back to the auto-factorized mesh with a stderr warning — bench
    configs carry mesh hints sized for their target topology (the
    run_bench.sh task-count analog), and a portable harness must still run
    (degraded, visibly) on smaller hosts.
    """
    if config.mode == "single":
        from dmlp_tpu.engine.single import SingleChipEngine
        return SingleChipEngine(config)
    if config.mode in ("sharded", "ring", "auto"):
        if config.mode == "sharded":
            from dmlp_tpu.engine.sharded import ShardedEngine as cls
        elif config.mode == "auto":
            from dmlp_tpu.engine.auto import AutoShardedEngine as cls
        else:
            from dmlp_tpu.engine.ring import RingEngine as cls
        if config.mesh_shape is not None:
            import jax
            r, c = config.mesh_shape
            if r * c > len(jax.devices()):
                import sys as _sys
                (stderr or _sys.stderr).write(
                    f"warning: mesh {r},{c} needs {r * c} devices, have "
                    f"{len(jax.devices())}; using auto mesh\n")
                config = dataclasses.replace(config, mesh_shape=None)
        return cls(config)
    raise ValueError(f"unknown mode {config.mode!r}")


def _emit_metrics(path: str, args, inp, timer: EngineTimer, phase_ms: dict,
                  counters: Optional[dict], comms: Optional[dict],
                  extract_impl: Optional[str] = None,
                  mem_model: Optional[dict] = None,
                  prune: Optional[dict] = None,
                  precision: Optional[dict] = None) -> None:
    """Append per-phase records + one run summary to the metrics JSONL.

    The summary is the contract record: it always carries a ``counters``
    block — either cost-analysis flops/bytes or the explicit
    ``counters_unavailable`` marker — never silence."""
    from dmlp_tpu.obs.run import SCHEMA_VERSION
    from dmlp_tpu.utils.metrics_log import MetricsLogger

    with MetricsLogger(path=path) as mlog:
        for name, ms in phase_ms.items():
            mlog.log(event="phase", name=name, ms=round(ms, 3))
        summary = {
            "event": "summary", "schema": SCHEMA_VERSION,
            "mode": args.mode, "engine": args.engine,
            "exact": not args.fast,
            "elapsed_ms": round(timer.elapsed_ms, 3),
            "num_data": inp.params.num_data,
            "num_queries": inp.params.num_queries,
            "num_attrs": inp.params.num_attrs,
            "counters": counters if counters is not None
            else {"counters_unavailable": True},
        }
        if comms is not None:
            summary["comms"] = comms
        if extract_impl is not None:
            # Which top-k kernel the solve actually dispatched ("fused"
            # | "extract") — the bench harness's fused A/B reads this to
            # refuse recording a vacuous (never-dispatched-fused) pair.
            summary["extract_impl"] = extract_impl
        if mem_model is not None:
            # The analytic peak-HBM model + measured watermark
            # reconcile (obs.memwatch) — the mem block carries the
            # explicit mem_stats_unavailable marker where the backend
            # reports no memory, never silence.
            summary["mem"] = mem_model
        if prune is not None:
            # Scanned-bytes + prune accounting of the pruned two-stage
            # solve (ops.summaries.note_scan) — the bench --prune-ab
            # harness and `make prune-smoke` read these per arm.
            summary["prune"] = prune
        if precision is not None:
            # First-pass precision record (engine.last_precision:
            # active/configured precision, kcap, window inflation) —
            # the bench --precision-ab harness reads this per arm to
            # refuse recording a vacuous (never-cast-bf16) pair, and
            # `make precision-smoke` asserts the inflation is visible.
            summary["precision"] = precision
        # Recovery is never silent: when the resilience layer did
        # anything (or a fault schedule was installed, even if nothing
        # fired), the summary carries the counters the chaos harness
        # asserts against.
        from dmlp_tpu.resilience import inject as rs_inject
        from dmlp_tpu.resilience import stats as rs_stats
        if rs_stats.any_activity() or rs_inject.active() is not None:
            summary["resilience"] = rs_stats.snapshot()
        mlog.log(**summary)


def _emit_counters_stderr(counters: Optional[dict], elapsed_ms: float,
                          stderr: IO) -> None:
    """The --counters human summary (after the contract line)."""
    if not counters or counters.get("counters_unavailable"):
        stderr.write("counters: unavailable (no analyzable dispatches "
                     "on this backend)\n")
        return
    from dmlp_tpu.obs.counters import roofline
    stderr.write(f"counters: flops={counters['flops']:.4e} "
                 f"hbm_bytes={counters['bytes_accessed']:.4e} "
                 f"dispatches={counters['dispatches_recorded']}\n")
    rl = roofline(counters["flops"], counters["bytes_accessed"],
                  elapsed_ms / 1e3)
    if "achieved_flops_per_s" in rl:
        line = f"roofline: {rl['achieved_flops_per_s']:.4e} FLOP/s achieved"
        if "utilization_vs_peak" in rl:
            line += (f", {rl['utilization_vs_peak'] * 100:.3f}% of "
                     f"{rl['peak_flops_per_chip']:.3g} peak")
        if "arithmetic_intensity" in rl:
            line += f", {rl['arithmetic_intensity']:.2f} FLOP/B"
        stderr.write(line + "\n")


def main(argv: Optional[Sequence[str]] = None,
         stdin: Optional[IO] = None,
         stdout: Optional[IO] = None,
         stderr: Optional[IO] = None) -> int:
    parser = argparse.ArgumentParser(prog="dmlp_tpu", description=__doc__)
    parser.add_argument("--mode", default="single",
                        choices=["single", "sharded", "ring", "auto"],
                        help="engine: 'auto' is the compiler-sharded "
                             "(GSPMD) engine — pure jit + NamedSharding "
                             "constraints instead of hand-rolled "
                             "collectives (engine.auto)")
    parser.add_argument("--mesh", default=None, metavar="R,C",
                        help="mesh shape (data x query axes) for the "
                             "sharded/ring/auto engines; default "
                             "auto-factorizes all devices "
                             "(MPI_Dims_create analog)")
    parser.add_argument("--engine", default="jax",
                        choices=["jax", "golden", "auto"],
                        help="'golden' runs the NumPy oracle (differential "
                             "testing reference); 'auto' is shorthand for "
                             "the jax engine with --mode auto")
    parser.add_argument("--debug", action="store_true",
                        help="human-readable output (the -DDEBUG build)")
    parser.add_argument("--fast", action="store_true",
                        help="skip the float64 host rescore (f32 ordering)")
    parser.add_argument("--device-full", action="store_true",
                        help="vote + report ordering on device too")
    parser.add_argument("--data-block", type=int, default=None,
                        help="data rows per inner step (default: per-select)")
    parser.add_argument("--query-block", type=int, default=1024)
    parser.add_argument("--dtype", default="auto",
                        choices=["auto", "float32", "bfloat16"],
                        help="staging/distance dtype; auto = bfloat16 on "
                             "TPU in exact mode (results unchanged: f64 "
                             "rescore), float32 elsewhere")
    parser.add_argument("--select", default="auto",
                        choices=["auto", "sort", "topk", "seg", "extract"],
                        help="device k-selection strategy")
    parser.add_argument("--phase-times", action="store_true",
                        help="per-phase ms breakdown on stderr (extension)")
    parser.add_argument("--pallas", action="store_true",
                        help="fused Pallas kernels (implies extract "
                             "selection on large inputs)")
    parser.add_argument("--profile", metavar="DIR", default=None,
                        help="write a jax.profiler trace of the solve to "
                             "DIR (survey §5.1 observability gap)")
    parser.add_argument("--trace", metavar="FILE", default=None,
                        help="write a Perfetto/Chrome-trace JSON of the "
                             "run's phase spans to FILE (obs.trace; load "
                             "at ui.perfetto.dev)")
    parser.add_argument("--metrics", metavar="FILE", default=None,
                        help="append JSONL metrics to FILE; the final "
                             "summary record carries cost-analysis "
                             "counters and collective-traffic accounting")
    parser.add_argument("--hlo-report", metavar="FILE", default=None,
                        help="append one kind='hlo' RunRecord to FILE: "
                             "the compiled program's collective "
                             "schedule, memory_analysis and cost "
                             "(obs.hlo HloReport per executable) plus "
                             "the three-way reconcile vs the analytic "
                             "comms/memwatch models and any trace; "
                             "implies dispatch recording. Contract "
                             "channels stay byte-identical")
    parser.add_argument("--counters", action="store_true",
                        help="print an XLA cost-analysis + roofline "
                             "summary to stderr (extension; implies "
                             "counter collection)")
    parser.add_argument("--warmup", action="store_true",
                        help="run the solve once untimed first, so the "
                             "timed region excludes XLA compilation (the "
                             "reference engine pays no JIT)")
    parser.add_argument("--compile-cache", metavar="DIR", default=None,
                        help="persistent XLA compilation cache dir (best "
                             "effort; later runs reuse on-disk "
                             "executables); $DMLP_TPU_COMPILE_CACHE is "
                             "the ambient form (flag wins)")
    parser.add_argument("--sanitize", action="store_true",
                        help="wrap the solve in "
                             "jax.transfer_guard('disallow') + "
                             "jax.checking_leaks (dmlp_tpu.check."
                             "sanitize): implicit host syncs and tracer "
                             "leaks raise instead of silently "
                             "serializing; $DMLP_TPU_SANITIZE=1 "
                             "enables it too. Output is byte-identical "
                             "on a clean program.")
    parser.add_argument("--faults", metavar="FILE", default=None,
                        help="deterministic fault-injection schedule "
                             "(JSON; dmlp_tpu.resilience.inject) — the "
                             "chaos harness's knob; $DMLP_TPU_FAULTS "
                             "sets it too. Recovery must keep stdout "
                             "byte-identical (make chaos-smoke)")
    parser.add_argument("--telemetry", metavar="FILE", default=None,
                        help="live telemetry (obs.telemetry): "
                             "periodically rewrite FILE as an "
                             "OpenMetrics snapshot (metrics registry + "
                             "device-memory watermarks + span "
                             "latencies), and arm the crash flight "
                             "recorder (FLIGHT_*.json next to FILE on "
                             "crash/fatal fault/SIGTERM). Contract "
                             "channels stay byte-identical")
    parser.add_argument("--telemetry-port", type=int, default=None,
                        metavar="PORT",
                        help="opt-in localhost HTTP endpoint serving "
                             "the OpenMetrics text on GET /metrics "
                             "(0 = ephemeral port; implies the "
                             "telemetry session)")
    args = parser.parse_args(argv)

    stdin = stdin or sys.stdin
    stdout = stdout or sys.stdout
    stderr = stderr or sys.stderr

    tracer = probe = telemetry_session = None
    from dmlp_tpu.resilience import inject as rs_inject
    from dmlp_tpu.resilience import stats as rs_stats
    rs_stats.reset()   # resets the registry's resilience.* counters too
    if args.telemetry or args.telemetry_port is not None:
        from dmlp_tpu.obs import telemetry
        telemetry_session = telemetry.start(path=args.telemetry,
                                            port=args.telemetry_port)
    if args.trace:
        from dmlp_tpu.obs import trace as obs_trace
        tracer = obs_trace.install(
            obs_trace.Tracer(annotate=bool(args.profile)))
    if args.metrics or args.counters or args.hlo_report:
        from dmlp_tpu.obs import counters as obs_counters
        probe = obs_counters.install()
    schedule = rs_inject.install_from_env(args.faults)
    try:
        return _run_cli(parser, args, stdin, stdout, stderr, tracer, probe)
    except Exception:
        # The whole reason the flight recorder exists: the last N
        # spans/events/metric deltas survive the crash as a
        # FLIGHT_*.json post-mortem artifact. Exception, NOT
        # BaseException: a usage error's SystemExit (parser.error) is
        # not a crash and must not leave a misleading FLIGHT artifact;
        # external kills are the SIGTERM handler's job.
        if telemetry_session is not None:
            from dmlp_tpu.obs import telemetry
            telemetry.dump_on_crash("crash")
        raise
    finally:
        if schedule is not None:
            rs_inject.write_log_if_requested()
            rs_inject.uninstall()
        if tracer is not None:
            from dmlp_tpu.obs import trace as obs_trace
            obs_trace.uninstall()
        if probe is not None:
            from dmlp_tpu.obs import counters as obs_counters
            obs_counters.uninstall()
        if telemetry_session is not None:
            telemetry_session.close()


def _run_cli(parser, args, stdin, stdout, stderr, tracer, probe) -> int:
    mesh_shape = parse_mesh_arg(parser, args.mesh)
    from dmlp_tpu.utils.compile_cache import enable_from_flag
    enable_from_flag(args.compile_cache)  # before any compile
    if args.engine == "auto":
        # --engine auto == the jax engine with the compiler-sharded
        # mode; keep the summary-record fields consistent.
        args.engine, args.mode = "jax", "auto"
    config = EngineConfig(mode=args.mode, debug=args.debug,
                          exact=not args.fast, data_block=args.data_block,
                          query_block=args.query_block, dtype=args.dtype,
                          select=args.select, use_pallas=args.pallas,
                          mesh_shape=mesh_shape)

    timer = EngineTimer()
    with timer.phase("parse"), obs_span("cli.parse"):
        inp = parse_input(stdin)

    # Only the solve is timed, matching the reference's timed region
    # (common.cpp:122-131 brackets Engine::KNN after ingest).
    from dmlp_tpu.check.sanitize import maybe_sanitized
    engine = None
    if args.engine == "golden":
        timer.start()
        from dmlp_tpu.golden.reference import knn_golden
        with obs_span("cli.solve", engine="golden"):
            results = knn_golden(inp)
    else:
        engine = make_engine(config, stderr=stderr)
        solve = engine.run_device_full if args.device_full else engine.run
        if args.warmup:
            with timer.phase("warmup_compile"), \
                    obs_span("cli.warmup_compile"), \
                    maybe_sanitized(force=args.sanitize):
                solve(inp)
            if probe is not None:
                # The warmup solve recorded the same dispatches the timed
                # solve is about to; without a reset every counter would
                # double and the roofline (counters / timed elapsed)
                # would overstate achieved FLOP/s ~2x.
                probe.reset()
        import contextlib
        profile_cm = contextlib.nullcontext()
        if args.profile:
            import jax
            profile_cm = jax.profiler.trace(args.profile)
        timer.start()
        with profile_cm, obs_span("cli.solve", mode=args.mode,
                                  engine="jax"), \
                maybe_sanitized(force=args.sanitize):
            results = solve(inp)
    with obs_span("cli.format_results"):
        text = format_results(results, debug=config.debug)
    timer.stop()

    stdout.write(text)
    stderr.write(timer.stderr_line())
    if args.phase_times:
        for name, ms in timer.phase_ms.items():
            stderr.write(f"phase {name}: {ms:.1f} ms\n")

    # -- observability epilogue (outside the timed region; contract
    # channels above are already written and stay byte-identical) --------
    if probe is not None or tracer is not None:
        phase_ms = dict(timer.phase_ms)
        if engine is not None:
            phase_ms.update(getattr(engine, "last_phase_ms", {}))
        counters = None
        if probe is not None:
            with obs_span("cli.collect_counters"):
                counters = probe.collect()
        hlo_rep_auto = None
        if args.hlo_report and engine is not None \
                and hasattr(engine, "comms_from_hlo"):
            # Derive the GSPMD engine's real comms record from the
            # compiled program BEFORE summarizing, so the metrics
            # comms block and the hlo reconcile see the same traffic.
            with obs_span("cli.hlo_derive_comms"):
                hlo_rep_auto = engine.comms_from_hlo()
        comms = None
        if engine is not None and getattr(engine, "last_comms", None):
            from dmlp_tpu.obs.comms import summarize
            comms = summarize(engine.last_comms)
        mem_model = None
        if (args.metrics or args.hlo_report) and engine is not None:
            # Only _emit_metrics consumes the reconcile; a
            # --counters/--trace-only run must not pay the
            # live-array enumeration for a discarded result.
            # Analytic peak-HBM model + watermark reconcile
            # (obs.memwatch): against the telemetry sampler's tracked
            # peak when a session ran, else a one-shot basis — with
            # the explicit marker where the backend reports nothing.
            from dmlp_tpu.obs import memwatch, telemetry
            try:
                model = memwatch.model_for_engine(engine, inp)
                sess = telemetry.session()
                measured = (sess.sampler.measured_peak() if sess
                            else memwatch.measured_watermark())
                mem_model = memwatch.reconcile(model, measured)
            except Exception:  # check: no-retry — obs never fails a run
                mem_model = None
        if args.metrics:
            _emit_metrics(args.metrics, args, inp, timer, phase_ms,
                          counters, comms,
                          extract_impl=getattr(engine, "last_extract_impl",
                                               None)
                          if engine is not None else None,
                          mem_model=mem_model,
                          prune=getattr(engine, "last_prune", None)
                          if engine is not None else None,
                          precision=getattr(engine, "last_precision",
                                            None)
                          if engine is not None else None)
        if args.counters:
            _emit_counters_stderr(counters, timer.elapsed_ms, stderr)
        if args.hlo_report and probe is not None:
            with obs_span("cli.hlo_report"):
                _emit_hlo_report(args, engine, probe, tracer, mem_model,
                                 hlo_rep_auto)
        if tracer is not None:
            tracer.write(args.trace)
    return 0


def _emit_hlo_report(args, engine, probe, tracer, mem_model,
                     hlo_rep_auto) -> None:
    """Append the kind='hlo' RunRecord: per-executable HloReports for
    every recorded dispatch signature + the three-way reconcile (HLO vs
    analytic comms models vs traced spans vs the memwatch mem block).
    Entirely outside the timed region; never raises into the run."""
    try:
        from dmlp_tpu.obs import hlo as obs_hlo
        from dmlp_tpu.obs.run import (RunRecord, current_device,
                                      round_from_name)
        reports, skipped = obs_hlo.probe_reports(probe)
        if hlo_rep_auto is not None and not any(
                rep.fingerprint == hlo_rep_auto.fingerprint
                for rep, _c, _s in reports):
            reports.append((hlo_rep_auto, 1, "auto.solve"))
        mesh_axes = None
        if engine is not None and getattr(engine, "mesh", None) \
                is not None:
            mesh_axes = dict(zip(engine.mesh.axis_names,
                                 engine.mesh.devices.shape))
        doc = obs_hlo.build_report_doc(
            reports, skipped=skipped,
            traffics=getattr(engine, "last_comms", None)
            if engine is not None else None,
            events=tracer.events() if tracer is not None else None,
            mem_block=mem_model, mesh_axes=mesh_axes)
        rec = RunRecord(
            kind="hlo", tool="dmlp_tpu.cli",
            config={"mode": args.mode, "engine": args.engine,
                    "exact": not args.fast,
                    **({"mesh": list(mesh_axes.values())}
                       if mesh_axes else {})},
            metrics=obs_hlo.flat_metrics(doc),
            comms=doc,
            device=current_device(),
            round=round_from_name(args.hlo_report))
        rec.append_jsonl(args.hlo_report)
    except Exception as e:  # check: no-retry — obs never fails a run
        import sys as _sys
        _sys.stderr.write(f"warning: --hlo-report failed: "
                          f"{type(e).__name__}: {e}\n")


if __name__ == "__main__":
    sys.exit(main())
