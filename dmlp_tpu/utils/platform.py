"""Platform bootstrap: honor JAX_PLATFORMS=cpu in this container.

This container routes JAX to a tunneled TPU via an ``axon`` sitecustomize
hook that registers an extra PJRT backend factory at interpreter start; that
factory wins over ``JAX_PLATFORMS=cpu``, so virtual-device CPU runs (the
multi-chip test/dry-run path, survey §4) would silently land on the one real
chip. Call :func:`honor_cpu_request` before the first backend touch to drop
the hook when the caller asked for CPU.
"""

from __future__ import annotations

import os


def honor_cpu_request() -> None:
    """If JAX_PLATFORMS=cpu is set, make sure it wins (idempotent).

    Must run before any JAX backend is initialized; a no-op otherwise.
    """
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() != "cpu":
        return
    import jax
    from jax._src import xla_bridge as _xb

    jax.config.update("jax_platforms", "cpu")
    _xb._backend_factories.pop("axon", None)
