from dmlp_tpu.utils.timing import EngineTimer, format_time_taken  # noqa: F401
