"""Timing: the stderr metrics contract + per-phase timers.

The reference's only observability is rank 0 bracketing ``Engine::KNN`` with
steady_clock and printing ``Time taken: <ms> ms`` to stderr
(common.cpp:122-131); run_bench.sh greps that line (run_bench.sh:40-41).
The same contract line is kept byte-identical here, and per-phase timers
(device-fenced with ``block_until_ready``) are added on top — the survey §5.1
gap. JSON metrics live in dmlp_tpu.utils.metrics_log.
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, Iterator


def format_time_taken(elapsed_ms: float) -> str:
    """The exact stderr line of common.cpp:130 (integer milliseconds)."""
    return f"Time taken: {int(elapsed_ms)} ms\n"


class EngineTimer:
    """Wall-clock + named-phase timer.

    Phases are fenced by the caller (pass device arrays through
    ``jax.block_until_ready`` before closing a phase) so device async
    dispatch doesn't misattribute time.
    """

    def __init__(self) -> None:
        self._start: float | None = None
        self._elapsed_ms: float = 0.0
        self.phase_ms: Dict[str, float] = {}

    def start(self) -> None:
        self._start = time.perf_counter()

    def stop(self) -> float:
        assert self._start is not None, "timer not started"
        self._elapsed_ms = (time.perf_counter() - self._start) * 1e3
        return self._elapsed_ms

    @property
    def elapsed_ms(self) -> float:
        return self._elapsed_ms

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.phase_ms[name] = self.phase_ms.get(name, 0.0) + \
                (time.perf_counter() - t0) * 1e3

    def stderr_line(self) -> str:
        return format_time_taken(self._elapsed_ms)
