"""Persistent XLA compilation cache — the shared seam.

PR 10 grew ``enable_persistent_compile_cache`` inside ``serve/engine.py``
for the daemon's AOT warm-up, which left every other entry point — batch
CLI runs, the train loop, and (worst) each fleet replica
relaunch/re-split — recompiling from scratch; under chaos the recompile
is the dominant term in recovery time. This module is the one shared
opt-in every surface routes through:

- engine CLI: ``python -m dmlp_tpu --compile-cache DIR``
- train loop: ``python -m dmlp_tpu.train --compile-cache DIR``
- serve daemon: ``python -m dmlp_tpu.serve --compile-cache DIR``
  (unchanged; ``serve.engine`` re-exports this function)
- fleet: ``ReplicaSpec`` threads the flag through every spawn,
  supervisor relaunch, and autoscale re-split, so a replacement
  replica warms its executables from disk and ``cold_start_compile_ms``
  drops on every restart after the first.

``$DMLP_TPU_COMPILE_CACHE`` is the ambient form of the same opt-in
(flag wins when both are set) so harnesses can warm a whole process
tree without editing each spawn site.

Everything here is best-effort by design: the cache is purely an
optimization, and a jax build without the knob (or an unwritable
directory) must never fail a run — callers get ``False`` and proceed
cold.
"""

from __future__ import annotations

import os
from typing import Optional

#: ambient opt-in; the explicit --compile-cache flag wins when both set
ENV_VAR = "DMLP_TPU_COMPILE_CACHE"


def enable_persistent_compile_cache(directory: str) -> bool:
    """Best-effort ``jax_compilation_cache_dir`` opt-in (the persistent
    compilation cache, when this jax build ships it): process restarts
    then reuse on-disk XLA executables, shrinking the cold-start number
    the warm-up records. Returns True when enabled."""
    import jax
    try:
        jax.config.update("jax_compilation_cache_dir", directory)
        try:
            # Default threshold skips programs that compile "fast"; the
            # fleet's warm-start win is the SUM of many such programs,
            # so cache them all.
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              0.0)
        except Exception:  # check: no-retry — older knob spelling only
            pass
        return True
    except Exception:  # check: no-retry — cache is an optimization only
        return False


def resolve_cache_dir(flag: Optional[str] = None) -> Optional[str]:
    """The effective cache directory: the explicit flag when given, else
    ``$DMLP_TPU_COMPILE_CACHE`` when set non-empty, else None. Read per
    call (no import-time snapshot) so spawned subprocesses and tests can
    flip the env without re-imports."""
    if flag:
        return flag
    env = os.environ.get(ENV_VAR)
    return env if env else None


def enable_from_flag(flag: Optional[str] = None) -> Optional[str]:
    """Resolve flag/env and enable the cache when either names a
    directory. Returns the directory actually enabled (created if
    missing), or None when no opt-in / the enable failed — callers log
    or ignore, they never fail."""
    directory = resolve_cache_dir(flag)
    if not directory:
        return None
    try:
        os.makedirs(directory, exist_ok=True)
    except OSError:
        return None
    return directory if enable_persistent_compile_cache(directory) \
        else None
