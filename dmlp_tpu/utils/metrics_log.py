"""Structured JSON metrics (survey §5.5 gap).

The reference's observability is two text channels: results on stdout,
one ``Time taken`` line on stderr. That contract stays untouched
(utils.timing); this logger adds the optional structured channel the
driver metadata asks for — one JSON object per line, appendable to a file
or any stream.
"""

from __future__ import annotations

import json
import sys
from typing import IO, Optional


class MetricsLogger:
    """Writes one JSON line per record; values must be JSON-serializable."""

    def __init__(self, path: Optional[str] = None, stream: Optional[IO] = None):
        if path is not None:
            self._fh: IO = open(path, "a")
            self._owns = True
        else:
            self._fh = stream if stream is not None else sys.stderr
            self._owns = False

    def log(self, **record) -> None:
        self._fh.write(json.dumps(record, sort_keys=True) + "\n")
        self._fh.flush()

    def close(self) -> None:
        if self._owns:
            self._fh.close()
