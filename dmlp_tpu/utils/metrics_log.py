"""Structured JSON metrics (survey §5.5 gap).

The reference's observability is two text channels: results on stdout,
one ``Time taken`` line on stderr. That contract stays untouched
(utils.timing); this logger adds the optional structured channel the
driver metadata asks for — one JSON object per line, appendable to a file
or any stream. Every record carries a monotonic ``t_ms`` (milliseconds
since the logger was created) so interleaved emitters stay orderable
without trusting wall-clock. Richer run-level artifacts belong in
dmlp_tpu.obs.run.RunRecord; this stays the line-per-event channel.
"""

from __future__ import annotations

import json
import sys
import time
from typing import IO, Optional


class MetricsLogger:
    """Writes one JSON line per record; values must be JSON-serializable.

    Usable as a context manager (closes an owned file on exit)::

        with MetricsLogger(path="metrics.jsonl") as log:
            log.log(step=1, loss=0.5)
    """

    def __init__(self, path: Optional[str] = None, stream: Optional[IO] = None):
        if path is not None:
            self._fh: IO = open(path, "a")
            self._owns = True
        else:
            self._fh = stream if stream is not None else sys.stderr
            self._owns = False
        self._t0 = time.monotonic()

    def log(self, **record) -> None:
        record.setdefault(
            "t_ms", round((time.monotonic() - self._t0) * 1e3, 3))
        try:
            line = json.dumps(record, sort_keys=True)
        except TypeError as e:
            # A raw TypeError mid-run names neither the record nor the
            # offending key; rebuild the message so the emitter is fixable
            # from the traceback alone.
            bad = [k for k, v in record.items() if not _serializable(v)]
            raise TypeError(
                f"MetricsLogger record has non-JSON-serializable "
                f"value(s) for key(s) {bad or sorted(record)}: {e}"
            ) from None
        self._fh.write(line + "\n")
        self._fh.flush()

    def close(self) -> None:
        if self._owns:
            self._fh.close()

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _serializable(value) -> bool:
    try:
        json.dumps(value)
        return True
    except TypeError:
        return False
