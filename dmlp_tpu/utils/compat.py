"""JAX version compatibility shims.

The engines and train steps target the current ``jax.shard_map`` API;
older jaxlib ships the same primitive as
``jax.experimental.shard_map.shard_map`` with the replication check
spelled ``check_rep`` instead of ``check_vma``. Every shard_map call in
the tree goes through :func:`shard_map` so the sharded/ring engines, the
pipeline, and the MoE step run on both API generations — on a current
jax this delegates straight to ``jax.shard_map`` with zero behavior
change.
"""

from __future__ import annotations

import jax


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` on current jax; the experimental spelling (with
    ``check_vma`` mapped onto its older ``check_rep`` name) otherwise."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma)


def axis_size(axis_name) -> int:
    """``jax.lax.axis_size`` where it exists; older jax spells the same
    query ``psum(1, axis)`` (a compile-time constant, no collective is
    actually emitted)."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def host_memory_kind() -> str:
    """The host-DRAM memory kind this backend addresses: "pinned_host"
    on TPU runtimes; older XLA:CPU exposes only "unpinned_host". The
    offload paths place host-resident leaves with this kind instead of
    hard-coding the TPU spelling."""
    try:
        kinds = {m.kind for m in jax.devices()[0].addressable_memories()}
        if "pinned_host" in kinds:
            return "pinned_host"
        for k in sorted(kinds):
            if "host" in k:
                return k
    except Exception:
        pass
    return "pinned_host"


def tpu_compiler_params(**kwargs):
    """``pltpu.CompilerParams`` across its rename (older jax:
    ``TPUCompilerParams``). Unknown kwargs on the older class are
    dropped rather than fatal — they are tuning hints, not semantics."""
    from jax.experimental.pallas import tpu as pltpu
    cls = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams")
    try:
        return cls(**kwargs)
    except TypeError:
        import dataclasses
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in kwargs.items() if k in known})
