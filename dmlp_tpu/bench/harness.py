"""Benchmark orchestration: generate -> oracle (cached) -> engine -> compare.

The TPU-native run_bench.sh. Per config (configs.py):

1. regenerate the canonical input if missing (seeded, byte-stable —
   inputs/inputN.in, the reference's missing inputs protocol, survey §6);
2. run the oracle once and cache outputs/test_N.{out,err}
   (run_bench.sh:79-84's cache), using the fast-exact golden model in
   place of the unrunnable x86 oracle binaries;
3. run the engine via the real CLI entry (same stdin/stdout/stderr
   contract as `mpirun ./engine < input`), writing outputs/tmp.{out,err};
4. diff the checksum channel (correctness) and compare the `Time taken`
   lines (performance) in run_bench.sh:29-72's report format.
"""

from __future__ import annotations

import json
import os
import re
import statistics
import sys
import time
from typing import Optional, TextIO

from dmlp_tpu.bench.configs import BENCH_CONFIGS, BenchConfig


def _extract_ms(err_text: str) -> Optional[int]:
    m = re.search(r"Time taken:\s*(\d+)", err_text)
    return int(m.group(1)) if m else None


def compare_times(bench_err: str, engine_err: str, out: TextIO) -> Optional[float]:
    """The compare_times report (run_bench.sh:29-72); returns percent diff
    (positive = engine slower), or None if a timing line is missing."""
    bench_ms = _extract_ms(bench_err)
    engine_ms = _extract_ms(engine_err)
    if bench_ms is None or engine_ms is None:
        out.write("Error: Could not extract timing information from .err files.\n")
        return None
    out.write("\n=== Performance Comparison ===\n")
    out.write(f"Benchmark time: {bench_ms} ms\n")
    out.write(f"Engine time:    {engine_ms} ms\n")
    diff = engine_ms - bench_ms
    if bench_ms == 0:
        # Oracle rounded to 0 ms — a percentage would be meaningless (and
        # claiming 0% would falsely declare parity).
        out.write(f"Difference:     +{diff} ms (oracle < 1 ms; no %)\n")
        out.write("==============================\n\n")
        return None
    percent = (engine_ms - bench_ms) / bench_ms * 100.0
    if percent > 0:
        out.write(f"Difference:     +{abs(diff)} ms ({percent:.2f}% slower)\n")
    elif percent < 0:
        out.write(f"Difference:     -{abs(diff)} ms ({abs(percent):.2f}% "
                  "faster) \U0001f389\U0001f389\U0001f389\n")
    else:
        out.write("Difference:     0 ms (No difference)\n")
    out.write("==============================\n\n")
    return percent


def ensure_input(cfg: BenchConfig, inputs_dir: str) -> str:
    """Generate the config's seeded input if not cached; returns the path."""
    from dmlp_tpu.io.datagen import generate_input_text

    os.makedirs(inputs_dir, exist_ok=True)
    path = os.path.join(inputs_dir, cfg.input_name)
    if not os.path.exists(path):
        text = generate_input_text(cfg.num_data, cfg.num_queries,
                                   cfg.num_attrs, cfg.min_attr, cfg.max_attr,
                                   cfg.min_k, cfg.max_k, cfg.num_labels,
                                   seed=cfg.seed)
        with open(path, "w") as f:
            f.write(text)
    return path


def ensure_oracle(cfg: BenchConfig, input_path: str, outputs_dir: str,
                  out: TextIO, force: bool = False) -> tuple[str, str]:
    """Run the golden oracle (cached) for a config; returns (.out, .err) paths."""
    from dmlp_tpu.golden.fast import knn_golden_fast
    from dmlp_tpu.io.grammar import parse_input
    from dmlp_tpu.io.report import format_results
    from dmlp_tpu.utils.timing import format_time_taken

    os.makedirs(outputs_dir, exist_ok=True)
    out_path = os.path.join(outputs_dir, f"test_{cfg.config_id}.out")
    err_path = os.path.join(outputs_dir, f"test_{cfg.config_id}.err")
    if os.path.exists(err_path) and os.path.exists(out_path) and not force:
        out.write("Output found in cache. Skipping...\n")
        return out_path, err_path
    with open(input_path, "rb") as f:  # binary -> native parser dispatch
        inp = parse_input(f)
    t0 = time.perf_counter()
    stats: dict = {}
    results = knn_golden_fast(inp, stats=stats)
    elapsed_ms = (time.perf_counter() - t0) * 1e3
    if stats.get("fallbacks"):
        out.write(f"oracle: {stats['fallbacks']} queries took the strict "
                  "fallback path\n")
    with open(out_path, "w") as f:
        f.write(format_results(results))
    with open(err_path, "w") as f:
        f.write(format_time_taken(elapsed_ms))
    return out_path, err_path


class EngineTimeout(RuntimeError):
    """The engine subprocess exceeded the harness timeout and was killed."""


def _engine_flags(cfg: BenchConfig, effective_mode: str) -> list:
    """Engine path flags shared by the single- and multi-process runners —
    one place to wire new BenchConfig knobs (the r2 harness silently
    benched the default path because these never reached the argv)."""
    argv = []
    if cfg.mesh_shape is not None and effective_mode != "single":
        argv += ["--mesh", f"{cfg.mesh_shape[0]},{cfg.mesh_shape[1]}"]
    if cfg.use_pallas:
        argv.append("--pallas")
    if cfg.select != "auto":
        argv += ["--select", cfg.select]
    return argv


def _config_env(cfg: BenchConfig, env: Optional[dict]) -> Optional[dict]:
    """Subprocess environment for a config: ``virtual_devices`` forces the
    CPU platform with that many virtual devices (and strips the axon TPU
    hook, which would otherwise claim the chip at interpreter start)."""
    if not cfg.virtual_devices:
        return env
    e = dict(env if env is not None else os.environ)
    # Strip only the axon sitecustomize entry — dmlp_tpu itself may be
    # importable solely via PYTHONPATH (it is not pip-installed here).
    parts = [p for p in e.get("PYTHONPATH", "").split(os.pathsep)
             if p and ".axon_site" not in p]
    if parts:
        e["PYTHONPATH"] = os.pathsep.join(parts)
    else:
        e.pop("PYTHONPATH", None)
    e["JAX_PLATFORMS"] = "cpu"
    e["PALLAS_AXON_POOL_IPS"] = ""
    # Append to (not replace) any caller/CI XLA_FLAGS; ours goes last so a
    # stale device-count flag from the environment cannot override it.
    prior = e.get("XLA_FLAGS", "")
    mine = f"--xla_force_host_platform_device_count={cfg.virtual_devices}"
    e["XLA_FLAGS"] = f"{prior} {mine}".strip()
    return e


def run_engine(cfg: BenchConfig, input_path: str, outputs_dir: str,
               mode: Optional[str] = None, fast: bool = False,
               warmup: bool = True, timeout_s: float = 300.0,
               env: Optional[dict] = None,
               obs_flags: Optional[list] = None) -> tuple[str, str]:
    """Run the engine CLI as a subprocess over a real pipe, under a kill
    timeout; returns (tmp.out, tmp.err) paths.

    A subprocess + timeout mirrors the reference's hang protection
    (``mpirun --timeout 300``, run_bench.sh:82) — one wedged jit must fail
    its config, not block the whole suite. Defaults to exact (f64-parity)
    mode — the harness exists to prove checksum parity, like the
    reference's oracle diff; ``fast=True`` drops the host rescore for
    pure-device timing at the cost of f32 ordering. ``cfg.mesh_shape``
    (run_bench.sh's task-count analog) is passed through as ``--mesh``.
    ``obs_flags`` (e.g. ``["--trace", path]``) ride through to the engine
    CLI — the per-config observability capture.
    """
    import subprocess
    import sys

    argv = [sys.executable, "-m", "dmlp_tpu", "--mode", mode or cfg.mode]
    argv += _engine_flags(cfg, mode or cfg.mode)
    if fast:
        argv.append("--fast")
    if warmup:
        argv.append("--warmup")
    if obs_flags:
        argv += list(obs_flags)
    env = _config_env(cfg, env)
    with open(input_path, "rb") as stdin:
        proc = subprocess.Popen(argv, stdin=stdin, stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, env=env)
        try:
            out_b, err_b = proc.communicate(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.communicate()
            raise EngineTimeout(
                f"engine exceeded {timeout_s:.0f}s timeout (killed), "
                f"cf. mpirun --timeout at run_bench.sh:82")
    if proc.returncode != 0:
        raise RuntimeError(f"engine CLI exited {proc.returncode}: "
                           f"{err_b.decode()[-2000:]}")
    tmp_out = os.path.join(outputs_dir, "tmp.out")
    tmp_err = os.path.join(outputs_dir, "tmp.err")
    with open(tmp_out, "wb") as f:
        f.write(out_b)
    with open(tmp_err, "wb") as f:
        f.write(err_b)
    return tmp_out, tmp_err


def run_engine_multiproc(cfg: BenchConfig, input_path: str, outputs_dir: str,
                         timeout_s: float = 300.0,
                         env: Optional[dict] = None) -> tuple[str, str]:
    """Run the engine as a real ``cfg.procs``-process jax.distributed
    (Gloo) cluster under the kill timeout — the harness-owned form of the
    reference's 2-node mpirun (run_bench.sh:82-84). Process 0's stdout is
    the canonical results channel (grader-diffed); all processes must exit
    0 within the timeout or the config fails."""
    import concurrent.futures as cf
    import socket
    import subprocess
    import sys

    def launch_once():
        # NOTE: probe-then-rebind has an inherent TOCTOU window (another
        # process can grab the ephemeral port before the coordinator binds
        # it); kept because jax.distributed offers no bind-then-hand-off
        # API. The caller retries once on a bind failure.
        with socket.socket() as s:
            s.bind(("localhost", 0))
            port = s.getsockname()[1]

        argv0 = [sys.executable, "-m", "dmlp_tpu.distributed",
                 "--input", input_path,
                 "--coordinator", f"localhost:{port}",
                 "--processes", str(cfg.procs), "--warmup"]
        argv0 += _engine_flags(cfg, cfg.mode)
        procs = [subprocess.Popen(argv0 + ["--process-id", str(pid)],
                                  stdout=subprocess.PIPE,
                                  stderr=subprocess.PIPE, env=env)
                 for pid in range(cfg.procs)]
        # Drain every process concurrently under ONE cluster deadline:
        # sequential communicate(timeout) would leave later processes' pipes
        # undrained (a stalled collective once ~64 KiB of Gloo/JAX stderr
        # backs up) and would multiply the worst-case wall clock by N.
        with cf.ThreadPoolExecutor(len(procs)) as ex:
            futs = [ex.submit(p.communicate) for p in procs]
            done, pending = cf.wait(futs, timeout=timeout_s)
            if pending:
                for proc in procs:
                    proc.kill()
                outs = [f.result() for f in futs]  # drains after the kills
                raise EngineTimeout(
                    f"{cfg.procs}-process cluster exceeded {timeout_s:.0f}s "
                    f"timeout (killed), cf. mpirun --timeout at "
                    f"run_bench.sh:82")
            outs = [f.result() for f in futs]
        for pid, proc in enumerate(procs):
            if proc.returncode != 0:
                raise RuntimeError(
                    f"process {pid} exited {proc.returncode}: "
                    f"{outs[pid][1].decode()[-2000:]}")
        return outs

    env = _config_env(cfg, env)
    try:
        outs = launch_once()
    except RuntimeError as e:
        # One retry with a fresh port when the TOCTOU race above landed
        # (the coordinator loses its probed port to another process).
        if not isinstance(e, EngineTimeout) \
                and "ddress already in use" in str(e):
            outs = launch_once()
        else:
            raise
    tmp_out = os.path.join(outputs_dir, "tmp.out")
    tmp_err = os.path.join(outputs_dir, "tmp.err")
    with open(tmp_out, "wb") as f:
        f.write(outs[0][0])                      # proc-0 canonical stdout
    with open(tmp_err, "wb") as f:
        f.write(outs[0][1])
    return tmp_out, tmp_err


def run_config(config_id: int, base_dir: str = ".",
               mode: Optional[str] = None, fast: bool = False,
               force_oracle: bool = False, out: Optional[TextIO] = None,
               timeout_s: float = 300.0, env: Optional[dict] = None,
               reps: int = 1, trace_dir: Optional[str] = None,
               counters: bool = False,
               record_path: Optional[str] = None,
               profile_dir: Optional[str] = None,
               obs_overhead: bool = False,
               fused_ab: bool = False,
               prune_ab: bool = False,
               precision_ab: bool = False,
               auto_ab: bool = False,
               telemetry_dir: Optional[str] = None) -> dict:
    """Full benchmark flow for one config; returns a result summary dict.

    ``reps`` > 1 runs the engine subprocess that many times and reports
    the MEDIAN engine time (all runs' times recorded as engine_ms_reps;
    checksums verified on every run). The reference protocol is
    single-shot (one mpirun each, run_bench.sh:82-84) over a quiet SLURM
    interconnect; this chip sits behind a tunneled link whose throughput
    swings up to 30x within minutes (BENCH_MODES_r04.json), so a
    single-shot engine time measures weather, not the engine. Deviation
    documented here and visible in the artifact.

    Observability (dmlp_tpu.obs): ``trace_dir`` captures a per-config
    Perfetto trace + metrics JSONL from the engine subprocess
    (trace_configN.json / metrics_configN.jsonl; the LAST rep's trace
    wins, every rep's metrics append); ``counters`` adds the engine's
    stderr roofline summary; ``record_path`` appends one versioned
    RunRecord per config — the schema replacing ad-hoc BENCH_*.json.
    Single-process configs only (a multi-process cluster would collide
    on the artifact files).

    ``profile_dir`` requests a per-config on-device ``jax.profiler`` XLA
    capture (the engine CLI's ``--profile``) into
    ``profile_dir/profile_configN/``, linked from that config's
    RunRecord artifacts. Real-TPU runs only: a config forced onto the
    virtual-CPU platform (``cfg.virtual_devices``) or an environment
    pinned to CPU records the explicit ``profile_unavailable`` marker
    instead of a capture — never a silently absent artifact.

    ``obs_overhead`` SELF-MEASURES the observability layer's cost: the
    engine runs in interleaved pairs — tracing+counters OFF then ON
    (order alternating per pair, the BENCH_MODES_r04 weather
    methodology) — and the result records ``obs_overhead_pct``
    (median-on vs median-off engine time) plus both raw sample lists,
    so the obs layer's own overhead becomes a tracked ledger series
    instead of a "<2%, trust us" claim. Single-process configs only;
    failures record the explicit ``obs_overhead_unavailable`` marker.

    ``fused_ab`` A/B-measures the fused distance→top-k megakernel
    (ops.pallas_fused) against the two-pass pipeline it replaces: the
    engine runs in interleaved ``DMLP_TPU_FUSED=1`` / ``=0`` pairs
    (same alternating-order weather methodology), BOTH arms' stdout
    must be byte-identical (the fused kernel's contract), and the
    result records ``engine_ms_fused`` / ``engine_ms_two_pass``
    medians with raw per-arm sample lists — the fused win (or loss)
    becomes a gated ledger series (`tools/perf_gate.py`), not a prose
    claim. Single-process configs only; failures and byte mismatches
    record the explicit ``fused_ab_unavailable`` / identity fields.
    """
    import sys

    out = out or sys.stdout
    cfg = BENCH_CONFIGS[config_id]
    if cfg.timeout_s is not None:
        timeout_s = cfg.timeout_s   # per-config override (configs.py)
    inputs_dir = os.path.join(base_dir, "inputs")
    outputs_dir = os.path.join(base_dir, "outputs")

    # One cpu-pinned verdict for both the profile marker and the
    # RunRecord device field: virtual-device configs and JAX_PLATFORMS=
    # cpu environments run the engine on CPU; anything else is the real
    # backend, which the parent must NOT probe (jax.devices() here could
    # dial the TPU while engine subprocesses own it) — so device stays
    # unset rather than guessed, and the ledger's device_mismatch guard
    # treats it as unspecified.
    cpu_pinned = bool(cfg.virtual_devices) or (
        (env if env is not None else os.environ)
        .get("JAX_PLATFORMS", "") == "cpu")

    obs_flags: list = []
    if counters:
        obs_flags.append("--counters")
    if telemetry_dir:
        # Per-config live-telemetry capture (obs.telemetry): the engine
        # subprocess rewrites an OpenMetrics snapshot while it runs;
        # the final file is linked from the config's RunRecord
        # artifacts like the trace/metrics pair.
        os.makedirs(telemetry_dir, exist_ok=True)
        obs_flags += ["--telemetry",
                      os.path.join(telemetry_dir,
                                   f"telemetry_config{config_id}.prom")]
    if trace_dir:
        os.makedirs(trace_dir, exist_ok=True)
        obs_flags += ["--trace",
                      os.path.join(trace_dir, f"trace_config{config_id}.json"),
                      "--metrics",
                      os.path.join(trace_dir,
                                   f"metrics_config{config_id}.jsonl")]
    profile: Optional[tuple] = None   # ("path", p) | ("unavailable", why)
    if profile_dir:
        if cpu_pinned:
            profile = ("unavailable", "cpu platform (virtual devices or "
                       "JAX_PLATFORMS=cpu) — on-device XLA capture needs "
                       "the real TPU")
            out.write(f"Config {config_id}: note — --profile is a no-op "
                      "on CPU; recording profile_unavailable\n")
        else:
            pdir = os.path.join(profile_dir, f"profile_config{config_id}")
            os.makedirs(pdir, exist_ok=True)
            obs_flags += ["--profile", pdir]
            profile = ("path", pdir)
    if obs_flags and cfg.procs > 1:
        out.write(f"Config {config_id}: note — observability capture "
                  "applies to single-process configs only; skipping\n")
        obs_flags = []
        if profile is not None and profile[0] == "path":
            profile = ("unavailable", "multi-process config")

    input_path = ensure_input(cfg, inputs_dir)
    oracle_out, oracle_err = ensure_oracle(cfg, input_path, outputs_dir, out,
                                           force=force_oracle)
    with open(oracle_out) as f:
        want = f.read()
    if cfg.procs > 1 and (mode or fast):
        out.write(f"Config {config_id}: note — --mode/--fast do not apply "
                  "to multi-process configs (the cluster runs the full "
                  "exact contract pipeline)\n")
    n_reps = max(reps, 1)
    # Checksums are verified on every rep except in single-process --fast
    # mode, where f64-oracle diffs are documented as expected output.
    check_reps = not (fast and cfg.procs == 1)
    rep_ms: list = []
    got = ee = None
    for _rep in range(n_reps):
        try:
            if cfg.procs > 1:
                engine_out, engine_err = run_engine_multiproc(
                    cfg, input_path, outputs_dir, timeout_s=timeout_s,
                    env=env)
            else:
                engine_out, engine_err = run_engine(
                    cfg, input_path, outputs_dir, mode=mode, fast=fast,
                    timeout_s=timeout_s, env=env, obs_flags=obs_flags)
        except (EngineTimeout, RuntimeError) as e:
            if got is not None:
                # Later-rep flake on the swinging link: keep the earlier
                # good reps instead of failing a config that already
                # produced a verified result (the reason reps exist).
                out.write(f"Config {config_id}: rep {_rep + 1}/{n_reps} "
                          f"failed ({e}); keeping {len(rep_ms)} good "
                          "rep(s)\n")
                break
            kind = "TIMEOUT" if isinstance(e, EngineTimeout) else "ERROR"
            out.write(f"Config {config_id}: {kind} ({e})\n")
            res = {"config": config_id, "checksums_match": False,
                   "oracle_ms": None, "engine_ms": None,
                   "percent_vs_oracle": None}
            if kind == "TIMEOUT":
                # Explicit marker, PR 5 convention: markers record an
                # honest non-result and never gate — a hung config must
                # not fail the whole bench run, only document itself.
                res["timeout"] = True          # legacy spelling
                res["timed_out"] = True        # the marker consumers key on
            else:
                res["error"] = str(e)
            if profile is not None and profile[0] == "path":
                # A killed/errored engine wrote no capture: record the
                # explicit marker (never a silently absent artifact) and
                # drop the pre-created empty capture dir.
                try:
                    os.rmdir(profile[1])
                except OSError:
                    pass
                profile = ("unavailable",
                           f"engine run failed ({kind.lower()})")
            if record_path:
                _append_run_record(record_path, cfg, res, trace_dir,
                                   profile=profile, cpu_pinned=cpu_pinned)
            return res
        with open(engine_out) as f:
            got_r = f.read()
        with open(engine_err) as f:
            ee_r = f.read()
        if check_reps and got_r != want:
            # A mismatching run's timing must not enter the median — the
            # artifact would otherwise carry a number derived from wrong
            # output with only checksums_match hinting at it.
            got, ee = got_r, ee_r
            rep_ms = []
            break
        got, ee = got_r, ee_r
        ms = _extract_ms(ee_r)
        if ms is not None:
            rep_ms.append(ms)

    checksums_match = want == got
    status = "PASS" if checksums_match else "FAIL"
    out.write(f"Config {config_id}: checksums {status} "
              f"({cfg.num_queries} queries)\n")

    with open(oracle_err) as f:
        oe = f.read()
    percent = compare_times(oe, ee, out)  # human report: last run
    oracle_ms = _extract_ms(oe)
    res = {"config": config_id, "checksums_match": checksums_match,
           "oracle_ms": oracle_ms, "engine_ms": _extract_ms(ee),
           "percent_vs_oracle": percent}
    if len(rep_ms) > 1:
        import statistics
        res["engine_ms"] = round(statistics.median(rep_ms))
        res["engine_ms_reps"] = rep_ms
        if oracle_ms:
            res["percent_vs_oracle"] = (
                (res["engine_ms"] - oracle_ms) / oracle_ms * 100.0)
    # MEASURED reference-binary baseline, when a capture exists for this
    # config (tools/capture_oracle.sh ran bench_1..4 in-container via
    # isolated-singleton Open MPI; configs 1-4 map 1:1 onto the captured
    # workloads; config 5's input has no captured binary counterpart).
    # (checksums_match gate: a wrong-output run's timing must not carry a
    # reference-binary multiple either.)
    if config_id in (1, 2, 3, 4) and res["engine_ms"] \
            and res["checksums_match"]:
        res.update(reference_binary_fields(
            os.path.join(base_dir, "oracle_capture", "ORACLE_GOLDEN.json"),
            config_id, res["engine_ms"]))
    if profile is not None and profile[0] == "path" \
            and not os.listdir(profile[1]):
        # The engine accepted --profile but wrote nothing (e.g. the
        # backend rejected the capture): an explicit marker, not a
        # RunRecord pointing at an empty directory.
        profile = ("unavailable", "engine wrote no capture")
    if obs_overhead:
        res.update(_measure_obs_overhead(
            cfg, input_path, outputs_dir, out, mode=mode, fast=fast,
            timeout_s=timeout_s, env=env, pairs=n_reps))
    if fused_ab:
        res.update(_measure_fused_ab(
            cfg, input_path, outputs_dir, out, mode=mode, fast=fast,
            timeout_s=timeout_s, env=env, pairs=n_reps,
            oracle_want=want if check_reps else None))
    if prune_ab:
        prune_res = _measure_prune_ab(
            cfg, input_path, outputs_dir, out, mode=mode, fast=fast,
            timeout_s=timeout_s, env=env, pairs=n_reps,
            oracle_want=want if check_reps else None)
        res.update(prune_res)
        if record_path:
            # A dedicated kind="prune" RunRecord so the A/B lands in
            # the ledger's ``prune/configN/...`` family (gated by
            # tools/perf_gate.py) alongside the plain bench record.
            import dataclasses as _dc

            from dmlp_tpu.obs.run import RunRecord, round_from_name
            RunRecord(kind="prune", tool="dmlp_tpu.bench",
                      config=_dc.asdict(cfg), metrics=dict(prune_res),
                      device="cpu" if cpu_pinned else None,
                      round=round_from_name(record_path)
                      ).append_jsonl(record_path)
    if precision_ab:
        prec_res = _measure_precision_ab(
            cfg, input_path, outputs_dir, out, mode=mode, fast=fast,
            timeout_s=timeout_s, env=env, pairs=n_reps,
            oracle_want=want if check_reps else None)
        res.update(prec_res)
        if record_path:
            # A dedicated kind="precision" RunRecord so the A/B lands
            # in the ledger's ``precision/configN/...`` family (gated
            # by tools/perf_gate.py) alongside the plain bench record.
            import dataclasses as _dc

            from dmlp_tpu.obs.run import RunRecord, round_from_name
            RunRecord(kind="precision", tool="dmlp_tpu.bench",
                      config=_dc.asdict(cfg), metrics=dict(prec_res),
                      device="cpu" if cpu_pinned else None,
                      round=round_from_name(record_path)
                      ).append_jsonl(record_path)
    if auto_ab:
        auto_res = _measure_auto_ab(
            cfg, input_path, outputs_dir, out, fast=fast,
            timeout_s=timeout_s, env=env, pairs=n_reps,
            oracle_want=want if check_reps else None)
        res.update(auto_res)
        if record_path:
            # A dedicated kind="auto" RunRecord so the compiler-vs-
            # hand-rolled A/B lands in the ledger's ``auto/configN/...``
            # family (gated by tools/perf_gate.py) alongside the plain
            # bench record.
            import dataclasses as _dc

            from dmlp_tpu.obs.run import RunRecord, round_from_name
            RunRecord(kind="auto", tool="dmlp_tpu.bench",
                      config=_dc.asdict(cfg), metrics=dict(auto_res),
                      device="cpu" if cpu_pinned else None,
                      round=round_from_name(record_path)
                      ).append_jsonl(record_path)
    if record_path:
        _append_run_record(record_path, cfg, res, trace_dir,
                           profile=profile, cpu_pinned=cpu_pinned,
                           telemetry_dir=telemetry_dir)
    return res


def _measure_obs_overhead(cfg: BenchConfig, input_path: str,
                          outputs_dir: str, out: TextIO,
                          mode: Optional[str], fast: bool,
                          timeout_s: float, env: Optional[dict],
                          pairs: int) -> dict:
    """Interleaved obs-on/obs-off engine timings -> the
    ``obs_overhead_pct`` fields (see run_config docstring). "On" means
    the full opt-in capture stack: span tracing + metrics JSONL +
    cost-analysis counters, exactly what ``--trace/--metrics/
    --counters`` enable. Never raises: any failed run yields the
    explicit ``obs_overhead_unavailable`` marker instead."""
    import statistics

    if cfg.procs > 1:
        return {"obs_overhead_unavailable": "multi-process config "
                "(observability capture is single-process only)"}
    on_flags = ["--trace",
                os.path.join(outputs_dir,
                             f"obs_overhead_trace_c{cfg.config_id}.json"),
                "--metrics",
                os.path.join(outputs_dir,
                             f"obs_overhead_metrics_c{cfg.config_id}.jsonl"),
                "--counters"]
    times: dict = {"off": [], "on": []}
    try:
        for rep in range(max(pairs, 1)):
            order = ("off", "on") if rep % 2 == 0 else ("on", "off")
            for arm in order:
                _, err_path = run_engine(
                    cfg, input_path, outputs_dir, mode=mode, fast=fast,
                    timeout_s=timeout_s, env=env,
                    obs_flags=on_flags if arm == "on" else None)
                with open(err_path) as f:
                    ms = _extract_ms(f.read())
                if ms is None:
                    return {"obs_overhead_unavailable":
                            f"no timing line in the {arm}-arm run"}
                times[arm].append(ms)
    except (EngineTimeout, RuntimeError) as e:
        return {"obs_overhead_unavailable":
                f"engine run failed during the A/B: {e}"}
    med_off = statistics.median(times["off"])
    med_on = statistics.median(times["on"])
    if med_off <= 0:
        return {"obs_overhead_unavailable":
                "off-arm median rounded to 0 ms (a percentage would "
                "be meaningless)", "engine_ms_obs_off": times["off"],
                "engine_ms_obs_on": times["on"]}
    pct = (med_on - med_off) / med_off * 100.0
    out.write(f"Config {cfg.config_id}: obs overhead "
              f"{pct:+.1f}% (median {med_off} -> {med_on} ms over "
              f"{len(times['off'])} interleaved pair(s))\n")
    return {"obs_overhead_pct": round(pct, 2),
            "engine_ms_obs_off": times["off"],
            "engine_ms_obs_on": times["on"]}


def _measure_fused_ab(cfg: BenchConfig, input_path: str,
                      outputs_dir: str, out: TextIO,
                      mode: Optional[str], fast: bool,
                      timeout_s: float, env: Optional[dict],
                      pairs: int, oracle_want: Optional[str]) -> dict:
    """Interleaved fused-megakernel vs two-pass engine timings (see
    run_config docstring): ``DMLP_TPU_FUSED=1`` against ``=0``, order
    alternating per pair so both arms share link weather. Three results
    ride in the record:

    - ``engine_ms_fused`` / ``engine_ms_two_pass`` medians plus the raw
      ``*_reps`` lists (the ledger's per-trial evidence — the fused win
      becomes a gated series, `tools/perf_gate.py`);
    - ``fused_ab_pct``: median fused vs two-pass (negative = fused
      faster);
    - ``fused_ab_identical``: every fused-arm stdout byte-equal to every
      two-pass-arm stdout (and to the oracle when the run is in exact
      mode) — the megakernel's bit-identity contract, CHECKED per run,
      not assumed. A mismatch marks the A/B unavailable (a wrong-output
      arm's timing must not become a ledger point).

    The A/B is never VACUOUS: both arms run with ``--metrics``
    (symmetric, so the tiny cost-probe overhead cancels in the
    comparison) and the fused arm's summary must report
    ``extract_impl == "fused"`` — a config whose dispatch shape the
    fused kernel does not support (or that never takes an extract-kernel
    path at all) records the explicit ``fused_ab_vacuous`` marker
    instead of an identical-code pair masquerading as a gated series.

    Never raises: failures record ``fused_ab_unavailable``."""
    import json
    import statistics

    if cfg.procs > 1:
        return {"fused_ab_unavailable": "multi-process config (the A/B "
                "drives the single-process engine CLI)"}
    base_env = dict(env if env is not None else os.environ)
    times: dict = {"fused": [], "two_pass": []}
    outputs: dict = {"fused": set(), "two_pass": set()}
    impls: dict = {"fused": set(), "two_pass": set()}
    arm_env = {"fused": "1", "two_pass": "0"}
    metrics_paths = {
        arm: os.path.join(outputs_dir,
                          f"fused_ab_metrics_{arm}_c{cfg.config_id}.jsonl")
        for arm in arm_env}
    for mpath in metrics_paths.values():
        if os.path.exists(mpath):   # metrics JSONL appends; start clean
            os.remove(mpath)
    try:
        for rep in range(max(pairs, 1)):
            order = ("two_pass", "fused") if rep % 2 == 0 \
                else ("fused", "two_pass")
            for arm in order:
                e = dict(base_env)
                e["DMLP_TPU_FUSED"] = arm_env[arm]
                out_path, err_path = run_engine(
                    cfg, input_path, outputs_dir, mode=mode, fast=fast,
                    timeout_s=timeout_s, env=e,
                    obs_flags=["--metrics", metrics_paths[arm]])
                with open(out_path) as f:
                    outputs[arm].add(f.read())
                with open(err_path) as f:
                    ms = _extract_ms(f.read())
                if ms is None:
                    return {"fused_ab_unavailable":
                            f"no timing line in the {arm}-arm run"}
                times[arm].append(ms)
    except (EngineTimeout, RuntimeError) as e:
        return {"fused_ab_unavailable":
                f"engine run failed during the A/B: {e}"}
    metrics_err = None
    for arm, mpath in metrics_paths.items():
        try:
            with open(mpath) as f:
                for line in f:
                    rec = json.loads(line)
                    if rec.get("event") == "summary":
                        impls[arm].add(rec.get("extract_impl"))
        except (OSError, ValueError) as e:
            metrics_err = f"{arm}-arm metrics channel unreadable: {e}"
    identical = (len(outputs["fused"]) == 1
                 and outputs["fused"] == outputs["two_pass"]
                 and (oracle_want is None
                      or outputs["fused"] == {oracle_want}))
    if not identical:
        return {"fused_ab_unavailable":
                "fused/two-pass stdout MISMATCH — bit-identity contract "
                "violated; timings withheld", "fused_ab_identical": False}
    if metrics_err is not None or not impls["fused"]:
        # The vacuity check below needs a parsed summary per arm; an
        # unreadable/empty metrics channel is an INFRASTRUCTURE failure,
        # not evidence about which kernel the config dispatches — report
        # it as unavailable, never as vacuous (timings withheld: an A/B
        # whose arms we cannot attribute must not become a gated series).
        return {"fused_ab_identical": True,
                "fused_ab_unavailable": metrics_err
                or "no engine summary parsed from the A/B metrics "
                   "channel — cannot attribute the arms to kernels"}
    if impls["fused"] != {"fused"}:
        # Identical arms AND the fused arm never dispatched the fused
        # kernel: the pair measured the same code twice. An honest
        # marker, not a ledger series (timings withheld).
        return {"fused_ab_vacuous": True,
                "fused_ab_identical": True,
                "fused_ab_unavailable":
                    "the DMLP_TPU_FUSED=1 arm dispatched "
                    f"{sorted(str(i) for i in impls['fused'])} (not the "
                    "fused kernel) — this config's solve never takes "
                    "the fused path; an identical-code A/B must not "
                    "become a gated series"}
    med_f = statistics.median(times["fused"])
    med_t = statistics.median(times["two_pass"])
    res = {"fused_ab_identical": True,
           "fused_ab_impls": {a_: sorted(str(i) for i in v)
                              for a_, v in impls.items()},
           "engine_ms_fused": round(med_f),
           "engine_ms_fused_reps": times["fused"],
           "engine_ms_two_pass": round(med_t),
           "engine_ms_two_pass_reps": times["two_pass"]}
    if med_t > 0:
        pct = (med_f - med_t) / med_t * 100.0
        res["fused_ab_pct"] = round(pct, 2)
        out.write(f"Config {cfg.config_id}: fused A/B {pct:+.1f}% "
                  f"(median {med_t} -> {med_f} ms over "
                  f"{len(times['fused'])} interleaved pair(s), "
                  "byte-identical)\n")
    return res


def _measure_prune_ab(cfg: BenchConfig, input_path: str,
                      outputs_dir: str, out: TextIO,
                      mode: Optional[str], fast: bool,
                      timeout_s: float, env: Optional[dict],
                      pairs: int, oracle_want: Optional[str]) -> dict:
    """Interleaved pruned vs dense engine timings: ``DMLP_TPU_PRUNE=1``
    against ``=0``, order alternating per pair (the repo's A/B
    weathering methodology). The record carries:

    - ``engine_ms_pruned`` / ``engine_ms_dense`` medians plus raw
      ``*_reps`` lists (ledger per-trial evidence -> a gated
      ``prune/configN/...`` series);
    - ``scanned_bytes_pruned`` / ``scanned_bytes_dense`` /
      ``scanned_bytes_ratio`` from the engines' scan accounting
      (ops.summaries.note_scan via the CLI metrics summary) — the
      bytes claim as a checked number, both ways;
    - ``prune_ab_identical``: every pruned-arm stdout byte-equal to
      every dense-arm stdout (and the oracle in exact mode) — the
      pruned solve's byte-identity contract, CHECKED per run;
    - ``prune_ab_vacuous`` when the pruned arm pruned zero blocks
      (e.g. a uniform corpus, where no block is provably out of every
      top-k): the timings/bytes still record — a ratio of 1.0 on a
      shape pruning cannot help is an honest measurement, unlike the
      fused A/B's identical-code case — but the flag says so.

    Never raises: failures record ``prune_ab_unavailable``."""
    import json
    import statistics

    if cfg.procs > 1:
        return {"prune_ab_unavailable": "multi-process config (the A/B "
                "drives the single-process engine CLI)"}
    base_env = dict(env if env is not None else os.environ)
    arm_env = {"pruned": "1", "dense": "0"}
    times: dict = {a: [] for a in arm_env}
    outputs: dict = {a: set() for a in arm_env}
    metrics_paths = {
        arm: os.path.join(outputs_dir,
                          f"prune_ab_metrics_{arm}_c{cfg.config_id}.jsonl")
        for arm in arm_env}
    for mpath in metrics_paths.values():
        if os.path.exists(mpath):   # metrics JSONL appends; start clean
            os.remove(mpath)
    try:
        for rep in range(max(pairs, 1)):
            order = ("dense", "pruned") if rep % 2 == 0 \
                else ("pruned", "dense")
            for arm in order:
                e = dict(base_env)
                e["DMLP_TPU_PRUNE"] = arm_env[arm]
                out_path, err_path = run_engine(
                    cfg, input_path, outputs_dir, mode=mode, fast=fast,
                    timeout_s=timeout_s, env=e,
                    obs_flags=["--metrics", metrics_paths[arm]])
                with open(out_path) as f:
                    outputs[arm].add(f.read())
                with open(err_path) as f:
                    ms = _extract_ms(f.read())
                if ms is None:
                    return {"prune_ab_unavailable":
                            f"no timing line in the {arm}-arm run"}
                times[arm].append(ms)
    except (EngineTimeout, RuntimeError) as e:
        return {"prune_ab_unavailable":
                f"engine run failed during the A/B: {e}"}
    identical = (len(outputs["pruned"]) == 1
                 and outputs["pruned"] == outputs["dense"]
                 and (oracle_want is None
                      or outputs["pruned"] == {oracle_want}))
    if not identical:
        return {"prune_ab_unavailable":
                "pruned/dense stdout MISMATCH — byte-identity contract "
                "violated; timings withheld", "prune_ab_identical": False}
    prune_blocks: dict = {}
    for arm, mpath in metrics_paths.items():
        try:
            with open(mpath) as f:
                for line in f:
                    rec = json.loads(line)
                    if rec.get("event") == "summary" \
                            and isinstance(rec.get("prune"), dict):
                        prune_blocks[arm] = rec["prune"]
        except (OSError, ValueError) as e:
            return {"prune_ab_identical": True,
                    "prune_ab_unavailable":
                        f"{arm}-arm metrics channel unreadable: {e}"}
    if set(prune_blocks) != set(arm_env):
        return {"prune_ab_identical": True,
                "prune_ab_unavailable":
                    "no scan-accounting block in the A/B metrics "
                    "channel — cannot attribute scanned bytes to arms"}
    med_p = statistics.median(times["pruned"])
    med_d = statistics.median(times["dense"])
    sb_p = int(prune_blocks["pruned"].get("scanned_bytes", 0))
    sb_d = int(prune_blocks["dense"].get("scanned_bytes", 0))
    res = {"prune_ab_identical": True,
           "engine_ms_pruned": round(med_p),
           "engine_ms_pruned_reps": times["pruned"],
           "engine_ms_dense": round(med_d),
           "engine_ms_dense_reps": times["dense"],
           "scanned_bytes_pruned": sb_p,
           "scanned_bytes_dense": sb_d,
           "prune_blocks_total": prune_blocks["pruned"].get(
               "blocks_total"),
           "prune_blocks_pruned": prune_blocks["pruned"].get(
               "blocks_pruned", 0)}
    if sb_d:
        res["scanned_bytes_ratio"] = round(sb_p / sb_d, 4)
    if not res["prune_blocks_pruned"]:
        res["prune_ab_vacuous"] = True
    if med_d > 0:
        pct = (med_p - med_d) / med_d * 100.0
        res["prune_ab_pct"] = round(pct, 2)
        out.write(f"Config {cfg.config_id}: prune A/B {pct:+.1f}% "
                  f"(median {med_d} -> {med_p} ms, scanned bytes "
                  f"{sb_d} -> {sb_p}, "
                  f"{res['prune_blocks_pruned']}/"
                  f"{res['prune_blocks_total']} blocks pruned, "
                  "byte-identical)\n")
    return res


def _measure_precision_ab(cfg: BenchConfig, input_path: str,
                          outputs_dir: str, out: TextIO,
                          mode: Optional[str], fast: bool,
                          timeout_s: float, env: Optional[dict],
                          pairs: int, oracle_want: Optional[str]) -> dict:
    """Interleaved bf16-first-pass vs f32 engine timings:
    ``DMLP_TPU_PRECISION=bf16`` against ``=f32``, order alternating per
    pair (the repo's A/B weathering methodology). The record carries:

    - ``engine_ms_bf16`` / ``engine_ms_f32`` medians plus raw
      ``*_reps`` lists (ledger per-trial evidence -> a gated
      ``precision/configN/...`` series);
    - ``precision_ab_identical``: every bf16-arm stdout byte-equal to
      every f32-arm stdout (and the oracle in exact mode) — the
      low-precision pass's byte-identity contract (lowp_eps-inflated
      windows + unchanged f64 rescore), CHECKED per run, not assumed.
      A mismatch withholds the timings: a wrong-output arm must never
      become a ledger point;
    - ``precision_kcap_f32`` / ``precision_kcap_bf16`` /
      ``precision_kcap_inflation`` from the engines' per-arm
      ``precision`` summary blocks (engine.last_precision) — the
      window-inflation cost of the bound, as a checked number.

    The A/B is never VACUOUS: the bf16 arm's summary must report
    ``active == "bf16"`` — a fast-mode run (precision resolves f32
    when there is no rescore backstop) or an engine without the lowp
    rung records the explicit ``precision_ab_unavailable`` marker
    instead of an identical-code pair masquerading as a gated series.

    Never raises: failures record ``precision_ab_unavailable``."""
    import json
    import statistics

    if cfg.procs > 1:
        return {"precision_ab_unavailable": "multi-process config (the "
                "A/B drives the single-process engine CLI)"}
    base_env = dict(env if env is not None else os.environ)
    arm_env = {"bf16": "bf16", "f32": "f32"}
    times: dict = {a: [] for a in arm_env}
    outputs: dict = {a: set() for a in arm_env}
    metrics_paths = {
        arm: os.path.join(
            outputs_dir,
            f"precision_ab_metrics_{arm}_c{cfg.config_id}.jsonl")
        for arm in arm_env}
    for mpath in metrics_paths.values():
        if os.path.exists(mpath):   # metrics JSONL appends; start clean
            os.remove(mpath)
    try:
        for rep in range(max(pairs, 1)):
            order = ("f32", "bf16") if rep % 2 == 0 \
                else ("bf16", "f32")
            for arm in order:
                e = dict(base_env)
                e["DMLP_TPU_PRECISION"] = arm_env[arm]
                out_path, err_path = run_engine(
                    cfg, input_path, outputs_dir, mode=mode, fast=fast,
                    timeout_s=timeout_s, env=e,
                    obs_flags=["--metrics", metrics_paths[arm]])
                with open(out_path) as f:
                    outputs[arm].add(f.read())
                with open(err_path) as f:
                    ms = _extract_ms(f.read())
                if ms is None:
                    return {"precision_ab_unavailable":
                            f"no timing line in the {arm}-arm run"}
                times[arm].append(ms)
    except (EngineTimeout, RuntimeError) as e:
        return {"precision_ab_unavailable":
                f"engine run failed during the A/B: {e}"}
    identical = (len(outputs["bf16"]) == 1
                 and outputs["bf16"] == outputs["f32"]
                 and (oracle_want is None
                      or outputs["bf16"] == {oracle_want}))
    if not identical:
        return {"precision_ab_unavailable":
                "bf16/f32 stdout MISMATCH — byte-identity contract "
                "violated; timings withheld",
                "precision_ab_identical": False}
    prec_blocks: dict = {}
    for arm, mpath in metrics_paths.items():
        try:
            with open(mpath) as f:
                for line in f:
                    rec = json.loads(line)
                    if rec.get("event") == "summary" \
                            and isinstance(rec.get("precision"), dict):
                        prec_blocks[arm] = rec["precision"]
        except (OSError, ValueError) as e:
            return {"precision_ab_identical": True,
                    "precision_ab_unavailable":
                        f"{arm}-arm metrics channel unreadable: {e}"}
    if set(prec_blocks) != set(arm_env):
        return {"precision_ab_identical": True,
                "precision_ab_unavailable":
                    "no precision block in the A/B metrics channel — "
                    "cannot attribute the arms to first-pass dtypes"}
    if prec_blocks["bf16"].get("active") != "bf16":
        # Identical arms AND the bf16 arm never cast: the pair measured
        # the same code twice. An honest marker, not a ledger series.
        return {"precision_ab_vacuous": True,
                "precision_ab_identical": True,
                "precision_ab_unavailable":
                    "the DMLP_TPU_PRECISION=bf16 arm ran with active "
                    f"precision {prec_blocks['bf16'].get('active')!r} "
                    "(fast mode, or an engine without the lowp rung) — "
                    "an identical-code A/B must not become a gated "
                    "series"}
    med_b = statistics.median(times["bf16"])
    med_f = statistics.median(times["f32"])
    res = {"precision_ab_identical": True,
           "engine_ms_bf16": round(med_b),
           "engine_ms_bf16_reps": times["bf16"],
           "engine_ms_f32": round(med_f),
           "engine_ms_f32_reps": times["f32"]}
    for arm in arm_env:
        kcap = prec_blocks[arm].get("kcap")
        if kcap is not None:
            res[f"precision_kcap_{arm}"] = kcap
    infl = prec_blocks["bf16"].get("kcap_inflation")
    if infl is not None:
        res["precision_kcap_inflation"] = infl
    if med_f > 0:
        pct = (med_b - med_f) / med_f * 100.0
        res["precision_ab_pct"] = round(pct, 2)
        out.write(f"Config {cfg.config_id}: precision A/B {pct:+.1f}% "
                  f"(median {med_f} -> {med_b} ms over "
                  f"{len(times['bf16'])} interleaved pair(s), kcap "
                  f"{res.get('precision_kcap_f32', '?')} -> "
                  f"{res.get('precision_kcap_bf16', '?')}, "
                  "byte-identical)\n")
    return res


def _measure_auto_ab(cfg: BenchConfig, input_path: str,
                     outputs_dir: str, out: TextIO,
                     fast: bool, timeout_s: float, env: Optional[dict],
                     pairs: int, oracle_want: Optional[str]) -> dict:
    """Interleaved compiler-sharded vs hand-rolled engine timings: the
    GSPMD engine (``--mode auto``) against BOTH hand-written merges
    (``--mode sharded`` all-gather, ``--mode ring``), arm order
    alternating per rep (the repo's A/B weathering methodology). The
    record carries:

    - ``engine_ms_auto`` / ``engine_ms_sharded`` / ``engine_ms_ring``
      medians plus raw ``*_reps`` lists (ledger per-trial evidence ->
      a gated ``auto/configN/...`` series) and the headline
      ``auto_ab_pct_vs_sharded`` / ``auto_ab_pct_vs_ring`` deltas;
    - ``compile_ms_*``: each arm's ``warmup_compile`` phase (the
      ``--warmup`` solve that pays XLA compilation, reported via
      ``--phase-times``) — the compile-time side of the A/B, split out
      so a GSPMD partitioner that searches longer for its schedule is
      charged visibly rather than hidden in an untimed warmup;
    - ``auto_ab_identical``: every arm's stdout byte-equal to every
      other arm's (and the oracle in exact mode) — the auto engine's
      core contract, CHECKED per run, not assumed. A mismatch
      withholds the timings: a wrong-output arm must never become a
      ledger point;
    - ``auto_ab_degenerate_mesh``: honest marker when the config pins
      no multi-device mesh — on a 1-device CPU container all three
      arms compile 1x1-mesh programs with no cross-shard merge at
      all, so the timings compare jit overheads, not collective
      schedules (the TPU round owns the qualified claim);
    - ``auto_hlo_bytes_*`` / ``auto_hlo_collectives_auto``: each arm's
      compiled-program collective bytes (CLI ``--hlo-report``, obs.hlo)
      — the A/B compares communication volume, not just wall time, and
      the auto arm's entry names which collectives GSPMD actually chose
      (``auto_hlo_unavailable`` marker when introspection failed).

    Never raises: failures record ``auto_ab_unavailable``."""
    import re as _re
    import statistics

    if cfg.procs > 1:
        return {"auto_ab_unavailable": "multi-process config (the A/B "
                "drives the single-process engine CLI)"}
    arms = ("auto", "sharded", "ring")
    times: dict = {a: [] for a in arms}
    compile_ms: dict = {a: [] for a in arms}
    outputs: dict = {a: set() for a in arms}
    hlo_paths = {a: os.path.join(
        outputs_dir, f"hlo_auto_ab_{a}_config{cfg.config_id}.jsonl")
        for a in arms}
    try:
        for rep in range(max(pairs, 1)):
            order = arms if rep % 2 == 0 else tuple(reversed(arms))
            for arm in order:
                out_path, err_path = run_engine(
                    cfg, input_path, outputs_dir, mode=arm, fast=fast,
                    timeout_s=timeout_s, env=env,
                    obs_flags=["--phase-times",
                               "--hlo-report", hlo_paths[arm]])
                with open(out_path) as f:
                    outputs[arm].add(f.read())
                with open(err_path) as f:
                    err_text = f.read()
                ms = _extract_ms(err_text)
                if ms is None:
                    return {"auto_ab_unavailable":
                            f"no timing line in the {arm}-arm run"}
                times[arm].append(ms)
                m = _re.search(r"phase warmup_compile:\s*([0-9.]+) ms",
                               err_text)
                if m:
                    compile_ms[arm].append(round(float(m.group(1)), 1))
    except (EngineTimeout, RuntimeError) as e:
        return {"auto_ab_unavailable":
                f"engine run failed during the A/B: {e}"}
    identical = (all(len(outputs[a]) == 1 for a in arms)
                 and outputs["auto"] == outputs["sharded"]
                 == outputs["ring"]
                 and (oracle_want is None
                      or outputs["auto"] == {oracle_want}))
    if not identical:
        return {"auto_ab_unavailable":
                "auto/sharded/ring stdout MISMATCH — byte-identity "
                "contract violated; timings withheld",
                "auto_ab_identical": False}
    med = {a: statistics.median(times[a]) for a in arms}
    res: dict = {"auto_ab_identical": True}
    for a in arms:
        res[f"engine_ms_{a}"] = round(med[a])
        res[f"engine_ms_{a}_reps"] = times[a]
        if compile_ms[a]:
            res[f"compile_ms_{a}"] = round(
                statistics.median(compile_ms[a]))
            res[f"compile_ms_{a}_reps"] = compile_ms[a]
    for rival in ("sharded", "ring"):
        if med[rival] > 0:
            res[f"auto_ab_pct_vs_{rival}"] = round(
                (med["auto"] - med[rival]) / med[rival] * 100.0, 2)
    # Communication-volume side of the A/B: each arm's compiled-program
    # collective bytes, and the auto arm's partitioner-chosen schedule
    # (introspection runs outside the CLI's timed region, so the
    # timings above are unaffected).
    import json as _json
    for a in arms:
        try:
            with open(hlo_paths[a]) as f:
                hdoc = _json.loads(f.read().splitlines()[-1])
            res[f"auto_hlo_bytes_{a}"] = \
                hdoc["metrics"]["collective_bytes_total"]
            if a == "auto":
                res["auto_hlo_collectives_auto"] = sorted(
                    (hdoc["comms"].get("collective_totals") or {}))
        except Exception as e:
            res.setdefault("auto_hlo_unavailable", {})[a] = \
                f"{type(e).__name__}: {e}"
    if not cfg.virtual_devices or cfg.virtual_devices <= 1:
        res["auto_ab_degenerate_mesh"] = True
    else:
        # The mesh is N virtual devices on ONE CPU: every delta here
        # (notably GSPMD's partitioning/compile cost) measures the
        # emulated platform, not a TPU slice's ICI schedule.
        res["auto_ab_virtual_mesh_devices"] = cfg.virtual_devices
    def _pct(rival: str) -> str:
        v = res.get(f"auto_ab_pct_vs_{rival}")
        return f"{v:+.1f}%" if v is not None else "n/a"

    out.write(f"Config {cfg.config_id}: auto A/B {_pct('sharded')} vs "
              f"sharded, {_pct('ring')} vs ring (medians sharded "
              f"{res['engine_ms_sharded']} / ring "
              f"{res['engine_ms_ring']} -> auto "
              f"{res['engine_ms_auto']} ms over {len(times['auto'])} "
              f"interleaved rep(s), compile "
              f"{res.get('compile_ms_sharded', '?')} / "
              f"{res.get('compile_ms_ring', '?')} -> "
              f"{res.get('compile_ms_auto', '?')} ms, byte-identical"
              + (", DEGENERATE 1x1 mesh"
                 if res.get("auto_ab_degenerate_mesh") else "")
              + ")\n")
    return res


def _append_run_record(record_path: str, cfg: BenchConfig, res: dict,
                       trace_dir: Optional[str],
                       profile: Optional[tuple] = None,
                       cpu_pinned: bool = False,
                       telemetry_dir: Optional[str] = None) -> None:
    """One versioned RunRecord per config run (obs.run) — the uniform
    artifact new bench emitters share instead of private BENCH_* shapes.
    ``profile`` is ("path", dir) to link an on-device capture from the
    artifacts block, or ("unavailable", why) for the explicit marker."""
    import dataclasses

    from dmlp_tpu.obs.run import RunRecord

    artifacts = {}
    metrics = dict(res)
    failed = bool(res.get("timeout") or res.get("error"))
    if trace_dir and cfg.procs == 1 and not failed:
        # Only paths that actually exist, and only for completed runs: a
        # timed-out/killed engine never wrote its trace, and a RunRecord
        # pointing at a missing (or stale earlier-rep) file would
        # mislead every consumer.
        candidates = {
            "trace": os.path.join(
                trace_dir, f"trace_config{cfg.config_id}.json"),
            "metrics": os.path.join(
                trace_dir, f"metrics_config{cfg.config_id}.jsonl"),
        }
        artifacts = {k: p for k, p in candidates.items()
                     if os.path.exists(p)}
    if telemetry_dir and cfg.procs == 1 and not failed:
        tpath = os.path.join(telemetry_dir,
                             f"telemetry_config{cfg.config_id}.prom")
        if os.path.exists(tpath):
            artifacts["telemetry"] = tpath
    if profile is not None:
        if profile[0] == "path" and not failed:
            artifacts["profile"] = profile[1]
        else:
            metrics["profile_unavailable"] = profile[1]
    from dmlp_tpu.obs.run import round_from_name
    # Schema-2 envelope fields the ledger keys on. Device is only
    # recorded when the harness KNOWS it (the cpu_pinned verdict from
    # run_config: virtual devices OR a JAX_PLATFORMS=cpu environment);
    # the parent must not touch jax.devices() to find out — that can
    # dial the TPU while engine subprocesses own it.
    device = "cpu" if cpu_pinned else None
    RunRecord(kind="bench", tool="dmlp_tpu.bench",
              config=dataclasses.asdict(cfg), metrics=metrics,
              artifacts=artifacts, device=device,
              round=round_from_name(record_path)).append_jsonl(record_path)


def run_serve(base_dir: str = ".", trace_path: Optional[str] = None,
              reps: int = 2, record_path: Optional[str] = None,
              timeout_s: float = 600.0, connections: int = 4,
              max_batch_queries: int = 64,
              extra_flags: Optional[list] = None,
              out: TextIO = sys.stdout) -> dict:
    """Serve mode: replay a recorded mixed-(nq, k) query trace against
    the real daemon (``python -m dmlp_tpu.serve`` subprocess) in
    interleaved gate-carry ON/OFF arms, and emit ONE schema-2 RunRecord
    (kind "serve" -> ledger ``serve/...`` series) with sustained
    request/query throughput, client-side latency quantiles, raw
    per-arm sample lists, and the warm-up A/B's gated-block fractions.

    Hard assertions, not best-effort: every response must match the
    float64 golden oracle byte-for-byte, both arms must match each
    other, the daemon's compile counter must not move between ready
    and drain (no per-request recompilation), and SIGTERM must drain
    to exit 0 with no flight-recorder dump."""
    import subprocess

    from dmlp_tpu.io.grammar import parse_input_text
    from dmlp_tpu.obs.run import RunRecord, round_from_name
    from dmlp_tpu.serve import client as serve_client

    trace_path = trace_path or os.path.join(base_dir, "inputs",
                                            "serve_trace1.jsonl")
    header, reqs = serve_client.load_trace(trace_path)
    outputs = os.path.join(base_dir, "outputs", "serve_bench")
    os.makedirs(outputs, exist_ok=True)
    corpus_txt = serve_client.corpus_text(header)
    corpus_path = os.path.abspath(os.path.join(outputs, "corpus.in"))
    with open(corpus_path, "w") as f:
        f.write(corpus_txt)
    corpus = parse_input_text(corpus_txt)
    golden = serve_client.golden_reference(corpus, header, reqs)
    golden_text = serve_client.contract_text(golden)
    # Warm every shape bucket the replay can hit BEFORE ready — only
    # then is the compile-counter assertion below meaningful.
    warm = serve_client.warm_buckets_for_trace(reqs, max_batch_queries)
    warm_spec = ",".join(f"{nq}x{k}" for nq, k in warm)

    arm_results: dict = {"on": [], "off": []}
    cold_ms: list = []
    res: dict = {"trace": trace_path, "requests": len(reqs),
                 "queries": int(sum(int(r["nq"]) for r in reqs)),
                 "checksums_match": True}
    for rep in range(max(reps, 1)):
        # Interleave arm order per rep (the repo's A/B weathering
        # methodology: neither arm systematically runs first).
        arms = ("on", "off") if rep % 2 == 0 else ("off", "on")
        for arm in arms:
            tag = f"rep{rep}_{arm}"
            ready = os.path.join(outputs, f"ready_{tag}.json")
            errlog = os.path.join(outputs, f"daemon_{tag}.err")
            if os.path.exists(ready):
                os.remove(ready)
            # --telemetry arms the session (and hence the flight
            # recorder, whose dump dir is the snapshot file's dir) so
            # the no-flight-dump drain assertion below has teeth.
            cmd = [sys.executable, "-m", "dmlp_tpu.serve",
                   "--corpus", corpus_path, "--port", "0",
                   "--ready-file", ready, "--gate-carry", arm,
                   "--warm-buckets", warm_spec,
                   "--max-batch-queries", str(max_batch_queries),
                   "--telemetry",
                   os.path.join(outputs, f"telemetry_{tag}.prom"),
                   "--tick-ms", "2"] + list(extra_flags or [])
            # A crash in a PREVIOUS invocation may have left flight
            # dumps here; clear them or the no-dump assertion below
            # would fail every later orderly run forever.
            serve_client.clear_flight_dumps(outputs)
            with open(errlog, "w") as ef:
                proc = subprocess.Popen(cmd, stderr=ef,
                                        stdout=subprocess.DEVNULL)
            try:
                ready_doc = serve_client.await_ready(
                    proc, ready, timeout_s=timeout_s, errlog=errlog)
                port = ready_doc["port"]
                t0 = time.perf_counter()
                responses = serve_client.replay(
                    port, header, reqs, connections=connections)
                wall_s = time.perf_counter() - t0
                bad = [r for r in responses if not r.get("ok")]
                if bad:
                    raise RuntimeError(
                        f"serve replay ({tag}): {len(bad)} failed "
                        f"responses, first: {bad[0]}")
                text = serve_client.contract_text(
                    [r["checksums"] for r in responses])
                if text != golden_text:
                    res["checksums_match"] = False
                    raise RuntimeError(
                        f"serve replay ({tag}): responses differ from "
                        "the golden oracle")
                cli = serve_client.ServeClient(port)
                stats = cli.stats()["stats"]
                cli.close()
                if stats["engine"]["compile_count"] != \
                        ready_doc["compile_count"]:
                    raise RuntimeError(
                        f"serve replay ({tag}): compile count moved "
                        f"{ready_doc['compile_count']} -> "
                        f"{stats['engine']['compile_count']} — a "
                        "request recompiled")
                serve_client.sigterm_drain(proc, errlog=errlog)
                flights = serve_client.flight_dumps(outputs)
                if flights:
                    raise RuntimeError(
                        f"orderly drain left flight dumps: {flights}")
                arm_results[arm].append({
                    "wall_s": wall_s,
                    "requests_per_sec": len(reqs) / wall_s,
                    "queries_per_sec": res["queries"] / wall_s,
                    "latency_ms": sorted(r["client_ms"]
                                         for r in responses),
                    "gated_fraction":
                        stats["engine"]["last_gated_fraction"],
                })
                cold_ms.append(ready_doc["cold_start_compile_ms"])
            finally:
                if proc.poll() is None:
                    proc.kill()
                    proc.wait(timeout=30)

    def _q(sorted_ms: list, q: float) -> float:
        return sorted_ms[min(int(q * (len(sorted_ms) - 1) + 0.5),
                             len(sorted_ms) - 1)]

    metrics: dict = {
        "requests": len(reqs), "trace_queries": res["queries"],
        "cold_start_compile_ms": statistics.median(cold_ms),
        "cold_start_compile_ms_reps": cold_ms,
        "connections": connections,
    }
    for arm, runs in arm_results.items():
        rps = [round(r["requests_per_sec"], 3) for r in runs]
        lat = sorted(ms for r in runs for ms in r["latency_ms"])
        metrics[f"requests_per_sec_carry_{arm}"] = statistics.median(rps)
        metrics[f"requests_per_sec_carry_{arm}_reps"] = rps
        metrics[f"queries_per_sec_carry_{arm}"] = statistics.median(
            [round(r["queries_per_sec"], 3) for r in runs])
        metrics[f"request_latency_p50_ms_carry_{arm}"] = round(
            _q(lat, 0.50), 3)
        metrics[f"request_latency_p95_ms_carry_{arm}"] = round(
            _q(lat, 0.95), 3)
        metrics[f"carry_{arm}_latency_ms"] = [round(v, 3) for v in lat]
        gf = [r["gated_fraction"] for r in runs
              if r["gated_fraction"] is not None]
        if gf:
            metrics[f"gated_fraction_carry_{arm}"] = round(
                statistics.median(gf), 6)
            metrics[f"gated_fraction_carry_{arm}_reps"] = gf
    res.update(metrics)
    out.write(
        f"Serve bench: {len(reqs)} requests x {reps} rep(s)/arm, "
        f"carry-on {metrics['requests_per_sec_carry_on']} req/s vs "
        f"carry-off {metrics['requests_per_sec_carry_off']} req/s, "
        f"p50 {metrics['request_latency_p50_ms_carry_on']} ms, "
        "all arms byte-identical to the golden oracle\n")
    if record_path:
        RunRecord(
            kind="serve", tool="dmlp_tpu.bench",
            config={"trace": os.path.basename(trace_path),
                    "corpus": header["corpus"],
                    "connections": connections, "reps": reps,
                    "flags": list(extra_flags or [])},
            metrics=metrics, round=round_from_name(record_path),
            artifacts={"trace": trace_path},
            device=_serve_device()).append_jsonl(record_path)
    res["ok"] = True
    return res


def _serve_device() -> Optional[str]:
    """Device kind for the serve RunRecord envelope — subprocesses did
    the solving, so only report what the environment pins (touching
    jax.devices() here could dial a TPU the daemons owned)."""
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        return "cpu"
    return None


def reference_binary_fields(cap_path: str, config_id: int,
                            engine_ms: float) -> dict:
    """Annotation fields comparing an engine time against the captured
    reference-binary run for ``config_id`` — shared by this harness and
    bench.py so the capture-schema handling cannot drift. Best-effort by
    contract: returns {} (never raises, never partial fields) when the
    capture is absent, unreadable, or malformed — the annotation must not
    be able to discard a completed benchmark result."""
    import json as _json
    try:
        with open(cap_path) as f:
            ref = _json.load(f)["configs"][str(config_id)]
        ref_ms = float(ref["time_taken_ms"])  # validate; store raw below
        ref_np = int(ref["np"])
    except (OSError, KeyError, TypeError, ValueError,
            _json.JSONDecodeError):
        return {}
    # `not (ref_ms > 0)` also rejects NaN (NaN <= 0 is False) — a NaN
    # multiple would serialize as invalid strict JSON downstream.
    if not engine_ms or not (ref_ms > 0):
        return {}
    return {"reference_binary_ms": ref["time_taken_ms"],
            "reference_binary_np": ref_np,
            "vs_reference_binary": round(ref_ms / engine_ms, 1)}


def main(argv=None) -> int:
    import argparse
    import sys

    p = argparse.ArgumentParser(prog="dmlp_tpu.bench", description=__doc__)
    p.add_argument("config", help="1|2|3|4|5|all|serve ('serve' "
                                  "replays --serve-trace against the "
                                  "resident daemon)")
    p.add_argument("--mode", default=None,
                   choices=[None, "single", "sharded", "ring", "auto"])
    p.add_argument("--fast", action="store_true",
                   help="drop the f64 host rescore (f32 ordering; checksum "
                        "diffs vs the f64 oracle are then expected)")
    p.add_argument("--force-oracle", action="store_true")
    p.add_argument("--base-dir", default=".")
    p.add_argument("--timeout", type=float, default=300.0,
                   help="per-config engine kill timeout in seconds "
                        "(mpirun --timeout 300 analog)")
    p.add_argument("--reps", type=int, default=1,
                   help="engine runs per config; >1 reports the median "
                        "(de-weathers the tunneled link; the reference "
                        "protocol is single-shot)")
    p.add_argument("--trace", metavar="DIR", default=None, dest="trace_dir",
                   help="per-config observability capture: the engine "
                        "subprocess writes DIR/trace_configN.json "
                        "(Perfetto) + DIR/metrics_configN.jsonl")
    p.add_argument("--metrics", metavar="FILE", default=None,
                   help="append one versioned RunRecord (obs.run) per "
                        "config to FILE — the uniform bench artifact")
    p.add_argument("--counters", action="store_true",
                   help="engine subprocesses print XLA cost-analysis + "
                        "roofline summaries on stderr")
    p.add_argument("--telemetry", metavar="DIR", default=None,
                   dest="telemetry_dir",
                   help="per-config live-telemetry capture: the engine "
                        "subprocess rewrites DIR/telemetry_configN.prom "
                        "as an OpenMetrics snapshot (obs.telemetry), "
                        "linked from the config's RunRecord artifacts")
    p.add_argument("--profile", metavar="DIR", default=None,
                   dest="profile_dir",
                   help="per-config on-device jax.profiler capture into "
                        "DIR/profile_configN (real-TPU runs; CPU configs "
                        "record the profile_unavailable marker), linked "
                        "from the config's RunRecord artifacts")
    p.add_argument("--obs-overhead", action="store_true",
                   help="self-measure the observability layer: run "
                        "interleaved engine pairs with tracing+counters "
                        "off vs on and record obs_overhead_pct in the "
                        "config's RunRecord (single-process configs)")
    p.add_argument("--fused-ab", action="store_true",
                   help="A/B the fused distance→top-k megakernel: run "
                        "interleaved DMLP_TPU_FUSED=1/0 engine pairs, "
                        "verify the arms byte-identical, and record "
                        "engine_ms_fused / engine_ms_two_pass (+ raw "
                        "rep lists) in the config's RunRecord "
                        "(single-process configs)")
    p.add_argument("--prune-ab", action="store_true",
                   help="A/B the pruned two-stage solve: run "
                        "interleaved DMLP_TPU_PRUNE=1/0 engine pairs, "
                        "verify the arms byte-identical, and record "
                        "engine_ms_pruned / engine_ms_dense plus "
                        "scanned-bytes both ways (+ raw rep lists) as "
                        "a kind=\"prune\" RunRecord per config "
                        "(single-process configs)")
    p.add_argument("--precision-ab", action="store_true",
                   help="A/B the low-precision first pass: run "
                        "interleaved DMLP_TPU_PRECISION=bf16/f32 "
                        "engine pairs, verify the arms byte-identical "
                        "(and vs the oracle in exact mode), and record "
                        "engine_ms_bf16 / engine_ms_f32 plus the "
                        "kcap window inflation (+ raw rep lists) as a "
                        "kind=\"precision\" RunRecord per config "
                        "(single-process configs)")
    p.add_argument("--auto-ab", action="store_true",
                   help="A/B the compiler-sharded engine: run "
                        "interleaved --mode auto / sharded / ring "
                        "engine arms, verify all three byte-identical "
                        "(and vs the oracle in exact mode), and record "
                        "engine_ms_auto / engine_ms_sharded / "
                        "engine_ms_ring plus each arm's "
                        "warmup-compile split (+ raw rep lists) as a "
                        "kind=\"auto\" RunRecord per config "
                        "(single-process configs)")
    p.add_argument("--serve-trace", metavar="FILE", default=None,
                   help="recorded query trace for the serve mode "
                        "(default inputs/serve_trace1.jsonl)")
    p.add_argument("--serve-connections", type=int, default=4,
                   help="concurrent replay connections (micro-batching "
                        "coalesces across them)")
    p.add_argument("--serve-flags", default="",
                   help="extra daemon flags, space-separated (e.g. "
                        "'--pallas --data-block 12800')")
    args = p.parse_args(argv)

    if args.config == "serve":
        res = run_serve(base_dir=args.base_dir,
                        trace_path=args.serve_trace,
                        reps=args.reps, record_path=args.metrics,
                        timeout_s=args.timeout,
                        connections=args.serve_connections,
                        extra_flags=args.serve_flags.split() or None)
        return 0 if res.get("ok") else 1

    ids = list(BENCH_CONFIGS) if args.config == "all" else [int(args.config)]
    ok = True
    for cid in ids:
        res = run_config(cid, base_dir=args.base_dir, mode=args.mode,
                         fast=args.fast, force_oracle=args.force_oracle,
                         timeout_s=args.timeout, reps=args.reps,
                         trace_dir=args.trace_dir, counters=args.counters,
                         record_path=args.metrics,
                         profile_dir=args.profile_dir,
                         obs_overhead=args.obs_overhead,
                         fused_ab=args.fused_ab,
                         prune_ab=args.prune_ab,
                         precision_ab=args.precision_ab,
                         auto_ab=args.auto_ab,
                         telemetry_dir=args.telemetry_dir)
        # `timed_out` is a marker, not a verdict (markers never gate):
        # the config's RunRecord documents the hang; a wrong checksum
        # still fails the run.
        ok = ok and (res["checksums_match"] or res.get("timed_out", False))
    return 0 if ok else 1


if __name__ == "__main__":
    import sys
    sys.exit(main())
