"""Benchmark harness — the TPU-native run_bench.sh (reference L4).

Replaces SLURM + mpirun + stripped oracle binaries (run_bench.sh:77-162)
with: a config registry (configs 1-4, like the reference's hardcoded
hardware/input combos), seeded input regeneration (the canonical inputs are
missing upstream — survey §6), the portable golden oracle with output
caching (the analog of outputs/test_N.{out,err} caching at
run_bench.sh:79-84), checksum diffing, and the same compare_times report.
"""

from dmlp_tpu.bench.configs import BENCH_CONFIGS, BenchConfig  # noqa: F401
from dmlp_tpu.bench.harness import run_config  # noqa: F401
