"""Benchmark config registry — configs 1-4, like run_bench.sh:77-123.

The reference maps each config to SLURM hardware constraints, an MPI task
count, and a canonical input (missing from the snapshot, so regenerated
seeded — survey §6 "step 0"). Here each config maps to generator
parameters, an engine mode, and a mesh hint; scale steps up like the
BASELINE.json ladder (CPU-ish -> v4-8 -> v4-32 -> v5p analog). Sizes are
chosen so config 1 finishes quickly anywhere and config 4 stresses a real
chip; the oracle is the golden model, cached after first run.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class BenchConfig:
    """One benchmark configuration (the analog of a run_bench.sh block)."""

    config_id: int
    # generator args (generate_input.py grammar, seeded)
    num_data: int
    num_queries: int
    num_attrs: int
    min_attr: float
    max_attr: float
    min_k: int
    max_k: int
    num_labels: int
    seed: int
    input_name: str          # shared inputs, like input2.in serving configs 2+3
    mode: str = "single"     # engine mode to benchmark
    mesh_shape: Optional[Tuple[int, int]] = None


BENCH_CONFIGS: Dict[int, BenchConfig] = {
    1: BenchConfig(1, 20_000, 1_000, 32, 0.0, 100.0, 1, 16, 10, 42,
                   "input1.in"),
    2: BenchConfig(2, 100_000, 5_000, 64, 0.0, 100.0, 1, 32, 10, 42,
                   "input2.in"),
    3: BenchConfig(3, 100_000, 5_000, 64, 0.0, 100.0, 1, 32, 10, 42,
                   "input2.in", mode="sharded", mesh_shape=(4, 2)),
    4: BenchConfig(4, 200_000, 10_000, 64, 0.0, 100.0, 1, 32, 10, 42,
                   "input3.in"),
}
