"""Benchmark config registry — configs 1-4, like run_bench.sh:77-123.

The reference maps each config to SLURM hardware constraints, an MPI task
count, and a canonical input (missing from the snapshot, so regenerated
seeded — survey §6 "step 0"). Here each config maps to generator
parameters, an engine mode, and a mesh hint; scale steps up like the
BASELINE.json ladder (CPU-ish -> v4-8 -> v4-32 -> v5p analog). Sizes are
chosen so config 1 finishes quickly anywhere and config 4 stresses a real
chip; the oracle is the golden model, cached after first run.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class BenchConfig:
    """One benchmark configuration (the analog of a run_bench.sh block).

    ``use_pallas``/``select`` choose the engine's device path — the r2
    harness always benched the default (slow) path, the exact failure
    class the round-1 bench hit; the large configs now pin the flagship
    kernels on. ``virtual_devices`` runs the engine subprocess on that
    many virtual CPU devices (JAX_PLATFORMS=cpu): the mesh configs need
    more devices than a 1-chip TPU host offers, so they validate the
    shard_map path at relative (not chip-absolute) timing, exactly like
    the multi-chip tests (survey §4). ``procs`` > 1 spawns a real
    N-process jax.distributed (Gloo) cluster through
    ``python -m dmlp_tpu.distributed`` — the mpirun-across-nodes analog
    (run_bench.sh:82-84)."""

    config_id: int
    # generator args (generate_input.py grammar, seeded)
    num_data: int
    num_queries: int
    num_attrs: int
    min_attr: float
    max_attr: float
    min_k: int
    max_k: int
    num_labels: int
    seed: int
    input_name: str          # shared inputs, like input2.in serving configs 2+3
    mode: str = "single"     # engine mode to benchmark
    mesh_shape: Optional[Tuple[int, int]] = None
    use_pallas: bool = False
    select: str = "auto"
    virtual_devices: int = 0  # 0 = whatever platform the env provides
    procs: int = 1            # jax.distributed process count
    # Per-config engine kill timeout override (seconds); None = the
    # harness-wide --timeout. A config that blows it records the
    # explicit `timed_out` marker in its RunRecord (markers never
    # gate) and the rest of the bench run proceeds.
    timeout_s: Optional[float] = None


BENCH_CONFIGS: Dict[int, BenchConfig] = {
    1: BenchConfig(1, 20_000, 1_000, 32, 0.0, 100.0, 1, 16, 10, 42,
                   "input1.in"),
    2: BenchConfig(2, 100_000, 5_000, 64, 0.0, 100.0, 1, 32, 10, 42,
                   "input2.in", use_pallas=True),
    3: BenchConfig(3, 100_000, 5_000, 64, 0.0, 100.0, 1, 32, 10, 42,
                   "input2.in", mode="sharded", mesh_shape=(4, 2),
                   virtual_devices=8),
    4: BenchConfig(4, 200_000, 10_000, 64, 0.0, 100.0, 1, 32, 10, 42,
                   "input3.in", use_pallas=True),
    # Config 5: the run_bench.sh multi-node analog — a real 2-process
    # Gloo cluster, 4 virtual devices per process, proc-0 stdout diffed.
    5: BenchConfig(5, 50_000, 2_000, 32, 0.0, 100.0, 1, 24, 10, 42,
                   "input5.in", mode="sharded", procs=2,
                   virtual_devices=4),
}
