"""Admission control: shed load BEFORE the allocator OOMs.

The batch engines react to memory pressure after the fact (the
resilience ladder catches RESOURCE_EXHAUSTED and degrades). A serving
daemon can do better: the analytic peak-HBM model (obs.memwatch) knows
what a micro-batch of a given shape bucket will make resident, and the
telemetry sampler knows the live watermark — so the admission decision
compares ``watermark + batch_bytes`` against the budget and REJECTS
when the headroom is gone, before any allocation happens. A rejected
request is a visible counter (``serve.rejected``) and a clean protocol
error; the ladder stays the backstop for surprises, not the first
responder.

The injected memory squeeze (``make serve-smoke``'s chaos arm) drives
this path deterministically: an ``oom`` fault at the ``serve.admit``
site (resilience.inject) makes the controller behave as if the
watermark had swallowed the budget — the daemon must shed, the
rejection must land in the registry, and the degradation ladder must
stay untouched.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from dmlp_tpu.obs import memwatch, telemetry
from dmlp_tpu.resilience import inject as rs_inject
from dmlp_tpu.resilience.retry import classify

#: decision verdicts
ACCEPT = "accept"
REJECT = "reject"


class AdmissionController:
    """Per-request accept/reject decisions for the serving daemon.

    ``budget_bytes``: the device-memory budget admission defends.
    ``None`` = auto: the backend's reported per-device ``bytes_limit``
    sum when available, else memory-based shedding is OFF (an explicit
    marker in :meth:`snapshot` — never a silent guess on backends that
    report nothing, like this container's CPU).
    """

    def __init__(self, engine, budget_bytes: Optional[int] = None,
                 max_queue_queries: int = 4096,
                 max_request_queries: int = 1024,
                 max_k: Optional[int] = None,
                 batch_queries_cap: Optional[int] = None):
        self.engine = engine
        self.budget_bytes = (budget_bytes if budget_bytes is not None
                             else self._auto_budget())
        self.max_queue_queries = max_queue_queries
        self.max_request_queries = max_request_queries
        #: the batcher's per-micro-batch query cap — memory pricing is
        #: against the COALESCED batch this request may join, not the
        #: lone request (64 small admits must not OOM as one batch)
        self.batch_queries_cap = batch_queries_cap or max_request_queries
        self.max_k = min(max_k or engine.max_k, engine.max_k)
        self.draining = False
        self._lock = threading.Lock()

    @staticmethod
    def _auto_budget() -> Optional[int]:
        """Device 0's reported ``bytes_limit`` — the resident engine is
        single-chip, so summing every device's limit would overstate
        the budget by the host's chip count and admission would keep
        accepting while the one solve device OOMs."""
        stats = memwatch.device_memory_stats()
        if not stats or not stats[0]:
            return None
        limit = stats[0].get("bytes_limit")
        return int(limit) if limit else None

    # -- the memory model ------------------------------------------------------

    def batch_bytes(self, nq: int, kmax: int) -> int:
        """Marginal resident bytes a micro-batch of this shape bucket
        adds on top of the resident corpus — the per-bucket terms of
        ``memwatch.serve_engine_model`` at the engine's own
        ``bucket_plan`` (the one kcap derivation), so the pricing
        cannot drift from what the solve allocates."""
        eng = self.engine
        qpad, _kb, kcap = eng.bucket_plan(nq, kmax)
        terms = memwatch.serve_engine_model(
            eng.capacity_rows, eng.num_attrs, staging=eng._staging,
            qpad=qpad, kcap=kcap)["terms"]
        return int(terms["query_blocks"] + terms["topk_carries"])

    def _resident_model_bytes(self) -> int:
        """The corpus-only model total, cached — it only moves when the
        extract chunks stage (every other input is fixed at engine
        construction), and decide() runs under the batcher's queue
        lock, so rebuilding the dict per request is pure hot-path
        waste."""
        chunks_staged = self.engine._chunks is not None
        cached = getattr(self, "_model_cache", None)
        if cached is not None and cached[0] == chunks_staged:
            return cached[1]
        model = memwatch.resident_bytes_model(
            "serve", capacity_rows=self.engine.capacity_rows,
            na=self.engine.num_attrs, staging=self.engine._staging,
            extract_chunks=(self.engine._ex_nchunks
                            if chunks_staged else 0),
            chunk_rows=self.engine._ex_chunk_rows)
        total = int(model["total_bytes"])
        self._model_cache = (chunks_staged, total)
        return total

    def headroom_bytes(self) -> Optional[int]:
        """Budget minus the max of (live watermark, modeled resident
        set); None when no budget basis exists."""
        if self.budget_bytes is None:
            return None
        sess = telemetry.session()
        measured = (sess.sampler.measured_peak() if sess
                    else memwatch.measured_watermark())
        used = int(measured.get("bytes", 0) or 0)
        used = max(used, self._resident_model_bytes())
        return self.budget_bytes - used

    # -- the decision ----------------------------------------------------------

    def decide(self, nq: int, kmax: int, queued_queries: int,
               queued_kmax: int = 0) -> Dict[str, Any]:
        """One admission decision; returns ``{"verdict", "reason",
        ...}`` and records it in the registry either way.
        ``queued_queries``/``queued_kmax`` describe the work already
        admitted and waiting: the memory check prices the micro-batch
        this request would actually COALESCE into (bounded by the
        batcher's cap), not the request alone."""
        reg = telemetry.registry()
        verdict, reason = ACCEPT, "ok"
        if self.draining:
            verdict, reason = REJECT, "draining"
        elif nq < 1 or nq > self.max_request_queries:
            verdict, reason = REJECT, "shape"
        elif kmax < 1 or kmax > self.max_k:
            verdict, reason = REJECT, "k_too_large"
        elif queued_queries + nq > self.max_queue_queries:
            verdict, reason = REJECT, "queue_full"
        else:
            squeeze = False
            try:
                rs_inject.fire("serve.admit", nq=nq, k=kmax)
            except Exception as e:
                # An injected RESOURCE_EXHAUSTED here IS the memory
                # squeeze: treat the budget as swallowed. Anything else
                # is a real bug and must propagate.
                if classify(e) != "oom":
                    raise
                squeeze = True
            if squeeze:
                verdict, reason = REJECT, "injected_squeeze"
            elif self.budget_bytes is not None:
                # Priced only when a budget exists: decide() runs under
                # the batcher's queue lock, and a no-budget backend
                # (memory shedding off) must not pay the model per
                # request for a comparison that can never fire.
                headroom = self.headroom_bytes()
                eff_nq = min(queued_queries + nq,
                             max(self.batch_queries_cap, nq))
                need = self.batch_bytes(eff_nq, max(kmax, queued_kmax))
                reg.gauge("serve.headroom_bytes").set(headroom)
                if need > headroom:
                    verdict, reason = REJECT, "memory"
        if verdict == ACCEPT:
            reg.counter("serve.admitted").inc()
        else:
            reg.counter("serve.rejected").inc(label=reason)
        return {"verdict": verdict, "reason": reason, "nq": nq, "k": kmax}

    def snapshot(self) -> Dict[str, Any]:
        reg = telemetry.registry()
        return {
            "budget_bytes": self.budget_bytes,
            "memory_shedding": self.budget_bytes is not None,
            "headroom_bytes": self.headroom_bytes(),
            "max_k": self.max_k,
            "max_request_queries": self.max_request_queries,
            "max_queue_queries": self.max_queue_queries,
            "admitted": reg.counter("serve.admitted").total(),
            "rejected": reg.counter("serve.rejected").by_label(),
            "draining": self.draining,
        }
