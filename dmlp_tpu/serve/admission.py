"""Admission control: shed load BEFORE the allocator OOMs.

The batch engines react to memory pressure after the fact (the
resilience ladder catches RESOURCE_EXHAUSTED and degrades). A serving
daemon can do better: the analytic peak-HBM model (obs.memwatch) knows
what a micro-batch of a given shape bucket will make resident, and the
telemetry sampler knows the live watermark — so the admission decision
compares ``watermark + batch_bytes`` against the budget and REJECTS
when the headroom is gone, before any allocation happens. A rejected
request is a visible counter (``serve.rejected``) and a clean protocol
error; the ladder stays the backstop for surprises, not the first
responder.

The injected memory squeeze (``make serve-smoke``'s chaos arm) drives
this path deterministically: an ``oom`` fault at the ``serve.admit``
site (resilience.inject) makes the controller behave as if the
watermark had swallowed the budget — the daemon must shed, the
rejection must land in the registry, and the degradation ladder must
stay untouched.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from dmlp_tpu.obs import memwatch, telemetry
from dmlp_tpu.resilience import inject as rs_inject
from dmlp_tpu.resilience.retry import classify

#: decision verdicts
ACCEPT = "accept"
REJECT = "reject"


class AdmissionController:
    """Per-request accept/reject decisions for the serving daemon.

    ``budget_bytes``: the device-memory budget admission defends.
    ``None`` = auto: the backend's reported per-device ``bytes_limit``
    sum when available, else memory-based shedding is OFF (an explicit
    marker in :meth:`snapshot` — never a silent guess on backends that
    report nothing, like this container's CPU).
    """

    def __init__(self, engine, budget_bytes: Optional[int] = None,
                 max_queue_queries: int = 4096,
                 max_request_queries: int = 1024,
                 max_k: Optional[int] = None,
                 batch_queries_cap: Optional[int] = None):
        self.engine = engine
        self.budget_bytes = (budget_bytes if budget_bytes is not None
                             else self._auto_budget())
        self.max_queue_queries = max_queue_queries
        self.max_request_queries = max_request_queries
        #: the batcher's per-micro-batch query cap — memory pricing is
        #: against the COALESCED batch this request may join, not the
        #: lone request (64 small admits must not OOM as one batch)
        self.batch_queries_cap = batch_queries_cap or max_request_queries
        self.max_k = min(max_k or engine.max_k, engine.max_k)
        self.draining = False
        # Guards the memoized resident-model total: decide_queued()
        # (under the batcher's queue lock) and snapshot() (handler
        # threads, no batcher lock) both read it — without the guard
        # two threads could interleave the (chunks_staged, total)
        # check-then-write. Leaf lock: nothing is acquired under it.
        self._lock = threading.Lock()
        self._model_cache = None

    @staticmethod
    def _auto_budget() -> Optional[int]:
        """Device 0's reported ``bytes_limit`` — the resident engine is
        single-chip, so summing every device's limit would overstate
        the budget by the host's chip count and admission would keep
        accepting while the one solve device OOMs."""
        stats = memwatch.device_memory_stats()
        if not stats or not stats[0]:
            return None
        limit = stats[0].get("bytes_limit")
        return int(limit) if limit else None

    # -- the memory model ------------------------------------------------------

    def batch_bytes(self, nq: int, kmax: int) -> int:
        """Marginal resident bytes a micro-batch of this shape bucket
        adds on top of the resident corpus — every resident engine
        prices its own per-bucket terms at its own ``bucket_plan``
        (ResidentServingCore.batch_model_bytes: the one kcap
        derivation, so pricing cannot drift from what the solve
        allocates; term names differ between the single-chip and mesh
        models, which is why the engine owns the sum)."""
        return int(self.engine.batch_model_bytes(nq, kmax))

    def _resident_model_bytes(self) -> int:
        """The corpus-only model total, cached — it only moves when
        the engine's resident_state_key changes (lazy stagings: extract
        chunks, the wide-k multipass concat, the mesh monolithic
        layout), so rebuilding the model per request is pure hot-path
        waste. The memo is read both under the batcher's queue lock
        (decide_queued) and from handler threads (snapshot), hence its
        own guard."""
        # The engine names its own invalidation state (chunk staging,
        # the wide-k multipass concat, the mesh monolithic layout —
        # each a resident allocation the floor must follow).
        state = self.engine.resident_state_key()
        with self._lock:
            cached = self._model_cache
        if cached is not None and cached[0] == state:
            return cached[1]
        total = int(self.engine.resident_model_bytes())
        with self._lock:
            self._model_cache = (state, total)
        return total

    def headroom_bytes(self) -> Optional[int]:
        """Budget minus the max of (live watermark, modeled resident
        set); None when no budget basis exists."""
        if self.budget_bytes is None:
            return None
        sess = telemetry.session()
        measured = (sess.sampler.measured_peak() if sess
                    else memwatch.measured_watermark())
        used = int(measured.get("bytes", 0) or 0)
        used = max(used, self._resident_model_bytes())
        return self.budget_bytes - used

    # -- the decision ----------------------------------------------------------

    def precheck(self, nq: int, kmax: int) -> Optional[Dict[str, Any]]:
        """The request-local half of admission: shape/k caps plus the
        ``serve.admit`` injection hook. Reads no queue state — and MAY
        BLOCK (an injected straggler ``delay`` fault sleeps here), so
        the batcher calls it OUTSIDE its queue lock (check rule R703:
        a sleep under the lock would stall every submitter and the
        consumer). Returns a rejection dict, or None to proceed to the
        queue-state checks. Counters are recorded by decide_queued —
        exactly once per decision."""
        if nq < 1 or nq > self.max_request_queries:
            return {"verdict": REJECT, "reason": "shape"}
        if kmax < 1 or kmax > self.max_k:
            return {"verdict": REJECT, "reason": "k_too_large"}
        try:
            rs_inject.fire("serve.admit", nq=nq, k=kmax)
        except Exception as e:
            # An injected RESOURCE_EXHAUSTED here IS the memory
            # squeeze: treat the budget as swallowed. Anything else
            # is a real bug and must propagate.
            if classify(e) != "oom":
                raise
            return {"verdict": REJECT, "reason": "injected_squeeze"}
        return None

    def decide_queued(self, nq: int, kmax: int, queued_queries: int,
                      queued_kmax: int = 0,
                      prechecked: Optional[Dict[str, Any]] = None
                      ) -> Dict[str, Any]:
        """The queue-state half: draining, queue depth, and the memory
        pricing of the micro-batch this request would COALESCE into
        (bounded by the batcher's cap; ``queued_queries``/
        ``queued_kmax`` describe the admitted-and-waiting work). Pure
        state reads and arithmetic — safe under the batcher's queue
        lock, which is what makes decision + enqueue atomic (two
        concurrent submits must not both price against the same queue
        state). ``prechecked`` is :meth:`precheck`'s verdict; the one
        admitted/rejected counter bump per decision happens here."""
        reg = telemetry.registry()
        verdict, reason = ACCEPT, "ok"
        if self.draining:
            verdict, reason = REJECT, "draining"
        elif prechecked is not None:
            verdict, reason = prechecked["verdict"], prechecked["reason"]
        elif queued_queries + nq > self.max_queue_queries:
            verdict, reason = REJECT, "queue_full"
        elif self.budget_bytes is not None:
            # Priced only when a budget exists: a no-budget backend
            # (memory shedding off) must not pay the model per
            # request for a comparison that can never fire.
            headroom = self.headroom_bytes()
            eff_nq = min(queued_queries + nq,
                         max(self.batch_queries_cap, nq))
            need = self.batch_bytes(eff_nq, max(kmax, queued_kmax))
            reg.gauge("serve.headroom_bytes").set(headroom)
            if need > headroom:
                verdict, reason = REJECT, "memory"
        if verdict == ACCEPT:
            reg.counter("serve.admitted").inc()
        else:
            reg.counter("serve.rejected").inc(label=reason)
        return {"verdict": verdict, "reason": reason, "nq": nq, "k": kmax}

    def decide(self, nq: int, kmax: int, queued_queries: int,
               queued_kmax: int = 0) -> Dict[str, Any]:
        """One standalone admission decision (tests, non-batcher
        callers): precheck + queue-state checks in order. The batcher
        composes the halves itself so the blocking half runs outside
        its queue lock."""
        return self.decide_queued(
            nq, kmax, queued_queries, queued_kmax=queued_kmax,
            prechecked=self.precheck(nq, kmax))

    def snapshot(self) -> Dict[str, Any]:
        reg = telemetry.registry()
        return {
            "budget_bytes": self.budget_bytes,
            "memory_shedding": self.budget_bytes is not None,
            "headroom_bytes": self.headroom_bytes(),
            "max_k": self.max_k,
            "max_request_queries": self.max_request_queries,
            "max_queue_queries": self.max_queue_queries,
            "admitted": reg.counter("serve.admitted").total(),
            "rejected": reg.counter("serve.rejected").by_label(),
            "draining": self.draining,
        }
