"""The serving daemon: parse once, stage once, compile once, serve.

``python -m dmlp_tpu.serve --corpus FILE`` builds a
:class:`~dmlp_tpu.serve.engine.ResidentEngine` over the corpus file's
data section, warms the shape buckets derived from its query section
(plus ``--warm-buckets``), and serves the line-JSON protocol
(:mod:`dmlp_tpu.serve.protocol`) on a localhost TCP port. Telemetry is
the PR 9 substrate unchanged: ``--telemetry-port`` is the live
OpenMetrics scrape surface, per-request latency lands in the
registry's log-bucket histograms, and ``--record`` appends
ledger-ingestible serve RunRecords (kind "serve" -> ``serve/...``
series, gated by ``make perf-gate``).

Shutdown contract (the graceful-drain satellite): SIGTERM (or an
in-band ``drain`` op) stops admission ("draining" rejections), lets
the batcher finish every in-flight and queued micro-batch, appends the
final RunRecord, flushes the final telemetry snapshot, and exits 0 —
an orderly drain leaves NO flight-recorder dump (crashes still do).
"""

from __future__ import annotations

import json
import os
import socketserver
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from dmlp_tpu.config import EngineConfig
from dmlp_tpu.io.grammar import KNNInput
from dmlp_tpu.obs import telemetry
from dmlp_tpu.obs import trace as obs_trace
from dmlp_tpu.serve import protocol
from dmlp_tpu.serve.admission import AdmissionController
from dmlp_tpu.serve.batching import MicroBatcher, Request
from dmlp_tpu.serve.engine import ResidentEngine


def default_warm_buckets(corpus: KNNInput) -> List[Tuple[int, int]]:
    """Warm-up shapes: the corpus file's own query section is the
    operator's declaration of expected traffic — bucket every (count,
    k) it contains, plus the smallest bucket as the floor."""
    out = [(1, 1)]
    nq = corpus.params.num_queries
    if nq:
        out.append((nq, int(corpus.ks.max())))
        out.append((1, int(corpus.ks.min())))
    return out


class _Handler(socketserver.StreamRequestHandler):
    """One connection: requests answered strictly in line order."""

    def handle(self):  # noqa: D102 (socketserver API)
        daemon: ServeDaemon = self.server.daemon
        while True:
            # Bounded read: readline(cap + 1) never buffers more than
            # the cap, so an oversized request line cannot balloon the
            # daemon's memory before rejection. A cap-exceeding read
            # has lost line framing — reject and drop the connection.
            raw = self.rfile.readline(protocol.MAX_LINE_BYTES + 1)
            if not raw:
                break
            if len(raw) > protocol.MAX_LINE_BYTES:
                self.wfile.write(protocol.encode(
                    {"ok": False,
                     "error": "request line exceeds the size cap"}))
                break
            try:
                line = raw.decode("utf-8", errors="strict").strip()
            except UnicodeDecodeError:
                self.wfile.write(protocol.encode(
                    {"ok": False, "error": "request is not UTF-8"}))
                continue
            if not line:
                continue
            # In-flight accounting brackets the RESPONSE WRITE, not
            # just the solve: drain() waits for it, so a drained
            # request's response actually reaches the client before
            # the process exits (handler threads are daemonized).
            daemon._track_inflight(+1)
            try:
                try:
                    resp = daemon.handle_line(line)
                except protocol.ProtocolError as e:
                    resp = {"ok": False, "error": str(e)}
                except Exception as e:  # check: no-retry — the
                    # connection survives a bad request; solve-path
                    # crashes are already surfaced per-request by the
                    # batcher
                    resp = {"ok": False,
                            "error": f"{type(e).__name__}: {e}"}
                w0 = (time.perf_counter()
                      if obs_trace.sinks_active() else 0.0)
                self.wfile.write(protocol.encode(resp))
                self.wfile.flush()
                if w0:
                    rid = resp.get("rid", "")
                    obs_trace.complete_at(
                        "serve.phase.write", w0, time.perf_counter(),
                        **({"rid": rid} if rid else {}))
            finally:
                daemon._track_inflight(-1)
            if resp.get("draining"):
                break


class _Server(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True


class ServeDaemon:
    """Lifecycle owner: engine + admission + batcher + TCP server +
    telemetry session + drain choreography."""

    def __init__(self, corpus: KNNInput, config: EngineConfig = None,
                 port: int = 0, capacity: Optional[int] = None,
                 gate_carry: bool = True,
                 budget_bytes: Optional[int] = None,
                 max_batch_queries: int = 1024,
                 max_queue_queries: int = 4096,
                 max_k: Optional[int] = None,
                 tick_s: float = 0.002,
                 telemetry_path: Optional[str] = None,
                 telemetry_port: Optional[int] = None,
                 record_path: Optional[str] = None,
                 snapshot_every_s: float = 0.0,
                 warm_buckets: Optional[List[Tuple[int, int]]] = None,
                 mesh_shape: Optional[Tuple[int, int]] = None,
                 mesh_merge: str = "allgather",
                 trace_path: Optional[str] = None,
                 objectives: Optional[List[Any]] = None):
        self.corpus = corpus
        self.record_path = record_path
        self.snapshot_every_s = snapshot_every_s
        # Request tracing opt-in: install a process-wide Tracer and
        # stamp the clock-sync marker the fleet merge aligns this
        # process's spans on. Written at drain/close.
        self.trace_path = trace_path
        self._tracer = None
        if trace_path:
            self._tracer = obs_trace.install(obs_trace.Tracer())
            self._tracer.sync_instant("fleet.clock_sync")
        self.session = None
        if telemetry_path or telemetry_port is not None:
            # handle_signals stays ON (the session owns the handler);
            # the daemon registers the clean-drain hook so an orderly
            # SIGTERM drains instead of dumping a flight artifact.
            self.session = telemetry.start(path=telemetry_path,
                                           port=telemetry_port)
        # The registry is process-global but stats()/snapshot_record()
        # divide by THIS daemon's uptime: zero the serve.* counters so
        # a second daemon lifetime in one process (tests, in-process
        # embedding) doesn't inherit the first one's counts and feed
        # inflated requests_per_sec into the ledger.
        telemetry.registry().reset(prefix="serve")
        if mesh_shape is not None:
            # Mesh-resident replica: the corpus held sharded-resident
            # across the mesh (dmlp_tpu.fleet) — same batcher/admission
            # surface, so everything below is engine-agnostic. Lazy
            # import: the fleet package layers on serve, not vice versa.
            from dmlp_tpu.fleet.mesh_engine import MeshResidentEngine
            self.engine = MeshResidentEngine(
                corpus, config or EngineConfig(mode="sharded"),
                mesh_shape=mesh_shape, capacity=capacity,
                merge=mesh_merge, gate_carry=gate_carry)
        else:
            self.engine = ResidentEngine(corpus,
                                         config or EngineConfig(),
                                         capacity=capacity,
                                         gate_carry=gate_carry)
        self.admission = AdmissionController(
            self.engine, budget_bytes=budget_bytes,
            max_queue_queries=max_queue_queries,
            max_request_queries=max_batch_queries, max_k=max_k,
            batch_queries_cap=max_batch_queries)
        self.batcher = MicroBatcher(self.engine, self.admission,
                                    max_batch_queries=max_batch_queries,
                                    tick_s=tick_s)
        # SLO objective plumb-through: string specs ("serve.request_
        # latency_ms p99 < 50 over 1m") or Objective instances. The
        # evaluator binds windowed rings onto the registry histograms;
        # it is constructed AFTER the serve.* reset above so the bound
        # histogram is the one this lifetime observes into. Ticked by
        # run_until_drained(); in-process embeddings tick it directly.
        self.slo = None
        if objectives:
            from dmlp_tpu.obs import slo as obs_slo
            objs = [obs_slo.parse_objective(o) if isinstance(o, str)
                    else o for o in objectives]
            self.slo = obs_slo.SLOEvaluator(objs, telemetry.registry())
        self._warm = (warm_buckets if warm_buckets is not None
                      else default_warm_buckets(corpus))
        self._drain_event = threading.Event()
        self._inflight = 0
        self._inflight_cond = threading.Condition()
        self._server = _Server(("127.0.0.1", port), _Handler)
        self._server.daemon = self
        self.port = self._server.server_address[1]
        self._server_thread: Optional[threading.Thread] = None
        self._t_ready: Optional[float] = None
        self.warmup_ms: Dict[str, float] = {}
        self._sigterm_prev = None
        self._sigterm_handler = None
        if self.session is not None:
            self.session.set_sigterm_drain(self._drain_event.set)
        else:
            import signal
            import weakref
            # The handler must hold the drain event WEAKLY: a strong
            # closure over self registered in the signal module would
            # pin this daemon's engine — resident device buffers
            # included — for the process lifetime (several daemons per
            # process tear down in arbitrary order, so prev-handler
            # restoration alone cannot unpin), silently inflating the
            # live-array watermark every later admission decision reads.
            ev_ref = weakref.ref(self._drain_event)

            def _on_sigterm(signum, frame, _ev_ref=ev_ref):
                ev = _ev_ref()
                if ev is not None:
                    ev.set()
            try:
                self._sigterm_prev = signal.signal(signal.SIGTERM,
                                                   _on_sigterm)
                self._sigterm_handler = _on_sigterm
            except ValueError:
                pass    # not the main thread (tests): drain op only

    # -- startup ---------------------------------------------------------------

    def start(self) -> None:
        """Warm the buckets, then open for traffic."""
        self.warmup_ms = self.engine.warmup(self._warm)
        self.batcher.start()
        self._server_thread = threading.Thread(
            target=self._server.serve_forever, name="serve-accept",
            daemon=True)
        self._server_thread.start()
        self._t_ready = time.monotonic()
        telemetry.registry().gauge("serve.ready").set(1)

    def write_ready_file(self, path: str) -> None:
        stats = self.engine.bucket_stats()
        doc = {
            "port": self.port, "pid": os.getpid(),
            "cold_start_compile_ms": self.engine.cold_start_compile_ms,
            "compile_count": self.engine.compile_count,
            "buckets": stats["buckets"],
            # per-bucket compiled-stream fingerprints at readiness: the
            # smoke re-reads this map at drain — a flat compile_count
            # with a CHANGED fingerprint would mean a recompile landed
            # on a different program (obs.hlo schedule identity).
            "hlo_schedule": stats.get("hlo_schedule", {}),
            "warmup_ms": self.warmup_ms,
        }
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        os.replace(tmp, path)

    # -- request plumbing ------------------------------------------------------

    def _track_inflight(self, delta: int) -> None:
        with self._inflight_cond:
            self._inflight += delta
            if self._inflight <= 0:
                self._inflight_cond.notify_all()

    def _wait_inflight_drained(self, timeout: float = 30.0) -> None:
        deadline = time.monotonic() + timeout
        with self._inflight_cond:
            while self._inflight > 0:
                left = deadline - time.monotonic()
                if left <= 0:
                    return      # give up, don't wedge the drain
                self._inflight_cond.wait(timeout=left)

    def handle_line(self, line: str) -> Dict[str, Any]:
        obj = protocol.parse_request(line, self.corpus.params.num_attrs)
        if isinstance(obj, dict):                 # control ops
            if obj.get("op") == "stats":
                return {"ok": True, "stats": self.stats()}
            self._drain_event.set()               # "drain"
            return {"ok": True, "draining": True}
        req: Request = obj
        self.batcher.submit(req)
        req.done.wait()
        if req.kind == "ingest":
            return protocol.ingest_response(req)
        if req.kind == "corpus":
            return protocol.corpus_response(req)
        return protocol.query_response(req)

    def stats(self) -> Dict[str, Any]:
        reg = telemetry.registry()
        eng = self.engine
        elapsed = (time.monotonic() - self._t_ready) \
            if self._t_ready else 0.0
        done = reg.counter("serve.requests_completed").total()
        out = {
            "protocol": protocol.PROTOCOL_VERSION,
            "engine": eng.bucket_stats(),
            # The fleet prober's consistency probe: rows + rolling
            # checksum + ingest epoch, comparable across replicas
            # whatever their resident layouts.
            "corpus": eng.corpus_state(),
            "admission": self.admission.snapshot(),
            "requests_completed": done,
            "queries_completed":
                reg.counter("serve.queries_completed").total(),
            "batches": self.batcher.batches,
            "uptime_s": round(elapsed, 3),
            "requests_per_sec": round(done / elapsed, 3) if elapsed
            else None,
        }
        h = reg.get("serve.request_latency_ms")
        if h is not None and h.count:
            out["request_latency_ms"] = {
                "p50": round(h.quantile(0.5), 3),
                "p95": round(h.quantile(0.95), 3),
                "p99": round(h.quantile(0.99), 3),
                "count": h.count,
            }
        if self.slo is not None:
            try:
                out["slo"] = self.slo.snapshot()
            except Exception:  # check: no-retry
                pass
        return out

    # -- ledger records --------------------------------------------------------

    def snapshot_record(self):
        """The serving state as a ledger-ingestible RunRecord (kind
        "serve" -> ``serve/<metric>`` series; requests_per_sec is
        higher-better, latency quantiles lower-better)."""
        from dmlp_tpu.obs.run import RunRecord, current_device
        reg = telemetry.registry()
        eng = self.engine
        elapsed = (time.monotonic() - self._t_ready) \
            if self._t_ready else 0.0
        done = reg.counter("serve.requests_completed").total()
        metrics: Dict[str, Any] = {
            "cold_start_compile_ms": eng.cold_start_compile_ms,
            "compile_count": eng.compile_count,
            "warm_buckets": len(eng.bucket_stats()["buckets"]),
            "admitted_total": reg.counter("serve.admitted").total(),
            "rejected_total": reg.counter("serve.rejected").total(),
            "batches_total": reg.counter("serve.batches").total(),
        }
        if elapsed and done:
            metrics["requests_per_sec"] = round(done / elapsed, 3)
            metrics["queries_per_sec"] = round(
                reg.counter("serve.queries_completed").total() / elapsed,
                3)
        h = reg.get("serve.request_latency_ms")
        if h is not None and h.count:
            metrics["request_latency_p50_ms"] = round(h.quantile(0.5), 3)
            metrics["request_latency_p95_ms"] = round(h.quantile(0.95), 3)
            metrics["request_latency_p99_ms"] = round(h.quantile(0.99), 3)
            metrics["request_count"] = h.count
        if eng.last_gated_fraction is not None:
            metrics["gate_gated_fraction"] = round(
                eng.last_gated_fraction, 6)
        stats = eng.bucket_stats()
        return RunRecord(
            kind="serve", tool="dmlp_tpu.serve",
            config={"corpus_rows": eng.n_real,
                    "capacity_rows": eng.capacity_rows,
                    "num_attrs": eng.num_attrs,
                    "gate_carry": eng.gate_carry,
                    "mode": ("mesh_resident" if hasattr(eng, "mesh")
                             else "resident"),
                    "buckets": stats["buckets"],
                    # per-bucket compiled-stream HLO fingerprints
                    # (obs.hlo; the schedule-identity side of the
                    # compile-once contract) — {} where the engine has
                    # no AOT stream handle to introspect
                    "hlo_schedule": stats.get("hlo_schedule", {})},
            metrics=metrics, device=current_device())

    def _append_record(self) -> None:
        if self.record_path:
            try:
                self.snapshot_record().append_jsonl(self.record_path)
            except Exception:  # check: no-retry — records never kill
                pass           # the drain

    # -- run / drain -----------------------------------------------------------

    def run_until_drained(self) -> None:
        """Block until a drain is requested (SIGTERM or the in-band
        op), then drain and shut down cleanly."""
        next_snap = (time.monotonic() + self.snapshot_every_s
                     if self.snapshot_every_s else None)
        while not self._drain_event.wait(timeout=0.2):
            if self.slo is not None:
                try:
                    self.slo.tick()
                except Exception:  # check: no-retry — SLO evaluation
                    pass           # never takes down the serve loop
            if next_snap is not None and time.monotonic() >= next_snap:
                self._append_record()
                next_snap = time.monotonic() + self.snapshot_every_s
        self.drain()

    def _restore_sigterm(self) -> None:
        """Undo the SIGTERM hook — only when it is still OURS (another
        daemon may have registered over us; clobbering its handler
        would break that daemon's drain)."""
        if self._sigterm_handler is None:
            return
        import signal
        try:
            if signal.getsignal(signal.SIGTERM) is self._sigterm_handler:
                signal.signal(signal.SIGTERM,
                              self._sigterm_prev or signal.SIG_DFL)
        except ValueError:
            pass
        self._sigterm_handler = None
        self._sigterm_prev = None

    def drain(self) -> None:
        """The orderly shutdown: shed new work, finish queued work,
        flush records + final telemetry snapshot, close. No flight
        dump — this is not a crash."""
        self.admission.draining = True
        telemetry.registry().gauge("serve.ready").set(0)
        self._server.shutdown()
        self.batcher.stop(drain=True)
        # The batcher completed every queued request; now wait for the
        # daemonized connection handlers to WRITE those responses — a
        # drain that exits mid-write loses the response on the floor.
        self._wait_inflight_drained()
        self._append_record()
        self._write_trace()
        if self.session is not None:
            self.session.set_sigterm_drain(None)
            self.session.close()     # writes the final snapshot
        self._restore_sigterm()
        self._server.server_close()

    def _write_trace(self) -> None:
        if self._tracer is None:
            return
        try:
            self._tracer.write(self.trace_path,
                               process_name=f"serve:{self.port}")
        except Exception:  # check: no-retry — traces never kill a drain
            pass
        if obs_trace.active() is self._tracer:
            obs_trace.uninstall()
        self._tracer = None

    def close(self) -> None:
        """Abrupt teardown for tests (no drain semantics)."""
        self._drain_event.set()
        self.admission.draining = True
        self._server.shutdown()
        self.batcher.stop(drain=False)
        self._write_trace()
        if self.session is not None:
            self.session.set_sigterm_drain(None)
            self.session.close()
        self._restore_sigterm()
        self._server.server_close()
