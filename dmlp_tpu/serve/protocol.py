"""The daemon's wire protocol: newline-delimited JSON over TCP.

One request object per line, one response object per line, strictly in
per-connection order (concurrency comes from multiple connections —
the micro-batcher coalesces across all of them). Shapes:

- ``{"op": "query", "id"?, "k": K | "ks": [...], "queries": [[...]]}``
  -> ``{"id", "ok": true, "labels": [...], "checksums": [...],
  "latency_ms"}`` (+ ``"neighbors"``/``"dists"`` with ``"debug": true``).
  ``checksums`` are the engines' contract FNV-1a values — the replay
  client reassembles the exact contract stdout (``Query N checksum:
  C``) and byte-compares it against the golden oracle.
- ``{"op": "ingest", "labels": [...], "rows": [[...]], "start"?: S}``
  -> ``{"ok": true, "corpus_rows": N}``; capacity overflow is a clean
  ``ok: false`` with the reason. ``start`` makes the write an
  IDEMPOTENT row-write keyed by global row id (``start <= corpus
  rows``; re-delivering the same rows at the same positions is a
  no-op) — the fleet's consistency repair and re-shard replay speak
  this form; plain appends omit it.
- ``{"op": "corpus", "start": S, "count": C}`` -> ``{"ok": true,
  "start": S, "labels": [...], "rows": [[...]], "corpus_rows": N,
  "checksum": H, "epoch": E}`` — the consistency/replay read side:
  host rows ``[S, S+C)`` (clamped; ``count`` capped at
  ``CORPUS_FETCH_MAX`` per line) plus the live corpus signature.
  ``count: 0`` is the cheap signature probe.
- ``{"op": "stats"}`` -> engine/admission/registry snapshot (now
  including the ``corpus`` signature block the fleet prober compares
  across replicas).
- ``{"op": "drain"}`` -> acknowledges and initiates the graceful
  drain (the in-band SIGTERM).

Rejections and errors are ``{"ok": false, "error": "..."}`` — the
connection stays usable.

Tracing envelope: every request MAY carry ``"rid"`` (an opaque
request-id string the client stamps at fire time) and ``"trace"`` (a
small dict of client-side context, e.g. the scheduled-fire wall
clock). Both are optional and advisory: a replica echoes a non-empty
``rid`` back in the response and tags its internal phase spans with
it, the fleet router annotates its routing spans with it, and
``tools/merge_traces.py --fleet`` stitches the per-process spans into
one causal tree keyed on it. Requests without ``rid`` serve exactly
as before, responses without it are byte-identical to the pre-rid
wire format, and no clock is read for it unless a trace sink is
installed — the contract channel never sees the difference.
"""

from __future__ import annotations

import json
from typing import Any, Dict

import numpy as np

from dmlp_tpu.serve.batching import Request

#: protocol schema version, echoed in hello/stats
PROTOCOL_VERSION = 1

#: request-line size cap. The daemon's connection handler enforces it
#: AT THE READ (``readline(cap + 1)``) so an oversized line never
#: buffers past the cap; the re-check in parse_request covers
#: non-socket callers.
MAX_LINE_BYTES = 64 << 20

#: per-request row cap of the ``corpus`` read op (bounds one response
#: line; replay loops page through larger ranges)
CORPUS_FETCH_MAX = 65536


class ProtocolError(ValueError):
    """A malformed request line (the response names the defect)."""


def _is_int(v) -> bool:
    """A real JSON integer — bool is an int subclass in Python, and
    ``{"k": true}`` silently served as k=1 is not the ProtocolError
    the parser promises for malformed requests."""
    return isinstance(v, int) and not isinstance(v, bool)


def parse_request(line: str, num_attrs: int) -> Request:
    """One wire line -> a validated :class:`Request` (op "query" |
    "ingest") or a control dict for "stats"/"drain". Raises
    :class:`ProtocolError` with a client-presentable message."""
    if len(line) > MAX_LINE_BYTES:
        raise ProtocolError("request line exceeds the size cap")
    try:
        obj = json.loads(line)
    except json.JSONDecodeError as e:
        raise ProtocolError(f"bad JSON: {e}") from None
    if not isinstance(obj, dict):
        raise ProtocolError("request must be a JSON object")
    op = obj.get("op", "query")
    if op in ("stats", "drain"):
        return obj
    req_id = str(obj.get("id", ""))
    rid = str(obj.get("rid", "") or "")
    if op == "query":
        queries = obj.get("queries")
        if not isinstance(queries, list) or not queries:
            raise ProtocolError("query op needs a non-empty 'queries' "
                                "list of attribute rows")
        try:
            q = np.asarray(queries, np.float64)
        except (TypeError, ValueError):
            raise ProtocolError("'queries' rows must be numeric and "
                                "rectangular") from None
        if q.ndim != 2 or q.shape[1] != num_attrs:
            raise ProtocolError(
                f"'queries' must be (nq, {num_attrs}), got {q.shape}")
        ks = obj.get("ks")
        if ks is None:
            k = obj.get("k")
            if not _is_int(k) or k < 1:
                raise ProtocolError("need 'k' (positive int) or 'ks'")
            ks_arr = np.full(len(q), k, np.int32)
        else:
            if (not isinstance(ks, list) or len(ks) != len(q)
                    or not all(_is_int(v) and v >= 1 for v in ks)):
                raise ProtocolError("'ks' must list one positive int "
                                    "per query row")
            ks_arr = np.asarray(ks, np.int32)
        return Request(kind="query", req_id=req_id, rid=rid,
                       query_attrs=q, ks=ks_arr,
                       debug=bool(obj.get("debug")))
    if op == "ingest":
        rows = obj.get("rows")
        labels = obj.get("labels")
        if not isinstance(rows, list) or not rows:
            raise ProtocolError("ingest op needs a non-empty 'rows' list")
        try:
            attrs = np.asarray(rows, np.float64)
        except (TypeError, ValueError):
            raise ProtocolError("'rows' must be numeric and "
                                "rectangular") from None
        if attrs.ndim != 2 or attrs.shape[1] != num_attrs:
            raise ProtocolError(
                f"'rows' must be (m, {num_attrs}), got {attrs.shape}")
        if (not isinstance(labels, list) or len(labels) != len(rows)
                or not all(_is_int(v) for v in labels)):
            raise ProtocolError("'labels' must list one int per row")
        start = obj.get("start")
        if start is not None and (not _is_int(start) or start < 0):
            raise ProtocolError("'start' must be a non-negative int "
                                "(the global row id of the first row)")
        return Request(kind="ingest", req_id=req_id, rid=rid,
                       labels=np.asarray(labels, np.int32), attrs=attrs,
                       start=start)
    if op == "corpus":
        start = obj.get("start", 0)
        count = obj.get("count", 0)
        if not _is_int(start) or start < 0:
            raise ProtocolError("corpus op 'start' must be a "
                                "non-negative int")
        if not _is_int(count) or count < 0:
            raise ProtocolError("corpus op 'count' must be a "
                                "non-negative int")
        return Request(kind="corpus", req_id=req_id, rid=rid,
                       start=start, count=min(count, CORPUS_FETCH_MAX))
    raise ProtocolError(f"unknown op {op!r}")


def _rid_echo(req: Request, out: Dict[str, Any]) -> Dict[str, Any]:
    """Echo a non-empty rid; rid-less responses keep the exact pre-rid
    key set (the traced/untraced byte-identity contract)."""
    if req.rid:
        out["rid"] = req.rid
    return out


def query_response(req: Request, debug: bool = False) -> Dict[str, Any]:
    """The completed query Request -> its wire response."""
    if req.error is not None:
        return _rid_echo(req, {"id": req.req_id, "ok": False,
                               "error": req.error})
    out: Dict[str, Any] = {
        "id": req.req_id, "ok": True,
        "labels": [int(r.predicted_label) for r in req.results],
        "checksums": [int(r.checksum()) for r in req.results],
        "latency_ms": round(req.latency_ms, 3),
    }
    if debug or req.debug:
        out["neighbors"] = [[int(i) for i in r.neighbor_ids]
                            for r in req.results]
        out["dists"] = [[float(d) for d in r.neighbor_dists]
                        for r in req.results]
    return _rid_echo(req, out)


def ingest_response(req: Request) -> Dict[str, Any]:
    if req.error is not None:
        return _rid_echo(req, {"id": req.req_id, "ok": False,
                               "error": req.error})
    return _rid_echo(req, {"id": req.req_id, "ok": True,
                           "corpus_rows": int(req.corpus_rows)})


def corpus_response(req: Request) -> Dict[str, Any]:
    """The completed ``corpus`` read -> its wire response (payload is
    assembled on the batcher thread, so the rows and the signature are
    one consistent snapshot — never torn by a concurrent ingest)."""
    if req.error is not None:
        return _rid_echo(req, {"id": req.req_id, "ok": False,
                               "error": req.error})
    return _rid_echo(req, {"id": req.req_id, "ok": True,
                           **(req.payload or {})})


def encode(obj: Dict[str, Any]) -> bytes:
    return (json.dumps(obj, separators=(",", ":"),
                       sort_keys=True) + "\n").encode()
