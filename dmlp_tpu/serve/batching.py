"""Continuous micro-batching: coalesce whatever is queued each tick.

Requests arrive one at a time (tiny nq each); the MXU wants full
tiles. The :class:`MicroBatcher` drains the admission queue each tick
into ONE padded micro-batch — variable (nq, k) requests concatenate,
the combined shape buckets to the engine's power-of-two jit-cache
buckets, and per-request results slice back out bit-identically (the
solo solve over the same corpus produces the same bytes; both equal
the golden oracle by the finalize/repair contract).

Single consumer thread: the engine (and its ingest path) is driven by
exactly one thread, so resident-buffer updates never race a solve.
Requests complete through a per-request event; connection handlers
block on it and write the response.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np

from dmlp_tpu.obs import telemetry
from dmlp_tpu.obs import trace as obs_trace
from dmlp_tpu.obs.trace import span as obs_span
from dmlp_tpu.resilience import inject as rs_inject
from dmlp_tpu.serve.admission import ACCEPT, AdmissionController
from dmlp_tpu.serve.engine import ResidentEngine

#: default batcher tick: how long a lone request waits for company
TICK_S = 0.002


@dataclasses.dataclass
class Request:
    """One admitted unit of work. ``kind`` is "query" | "ingest" |
    "corpus"; non-query requests execute standalone between
    micro-batches (the one batcher thread serializes them against
    solves — a ``corpus`` read can therefore never observe a torn
    ingest)."""

    kind: str
    req_id: str = ""
    rid: str = ""                                 # trace request id ("" =
    #                                               untraced; never invented
    #                                               server-side)
    query_attrs: Optional[np.ndarray] = None      # (nq, na) float64
    ks: Optional[np.ndarray] = None               # (nq,) int32
    labels: Optional[np.ndarray] = None           # ingest: (m,) int32
    attrs: Optional[np.ndarray] = None            # ingest: (m, na) f64
    start: Optional[int] = None                   # ingest row-write /
    #                                               corpus read offset
    count: Optional[int] = None                   # corpus read length
    debug: bool = False                           # echo neighbors/dists
    t_enqueue: float = dataclasses.field(default_factory=time.monotonic)
    # Same instant in the tracer's clock domain: request-phase spans
    # (queue/coalesce/...) are cross-thread intervals stitched from
    # perf_counter reads via trace.complete_at.
    t_enqueue_pc: float = dataclasses.field(
        default_factory=time.perf_counter)
    done: threading.Event = dataclasses.field(
        default_factory=threading.Event)
    results: Optional[List] = None                # QueryResults (local ids)
    error: Optional[str] = None
    latency_ms: Optional[float] = None
    corpus_rows: Optional[int] = None             # ingest outcome
    payload: Optional[Dict[str, Any]] = None      # corpus outcome

    @property
    def nq(self) -> int:
        return 0 if self.ks is None else len(self.ks)

    def complete(self, results=None, error=None, corpus_rows=None) -> None:
        self.results = results
        self.error = error
        self.corpus_rows = corpus_rows
        self.latency_ms = (time.monotonic() - self.t_enqueue) * 1e3
        self.done.set()


class MicroBatcher:
    """The admission queue + the one batch-execution thread."""

    def __init__(self, engine: ResidentEngine,
                 admission: AdmissionController,
                 max_batch_queries: int = 1024,
                 tick_s: float = TICK_S):
        self.engine = engine
        self.admission = admission
        self.max_batch_queries = max_batch_queries
        self.tick_s = tick_s
        self._queue: deque = deque()
        self._queued_queries = 0
        self._queued_kmax = 0     # max k among queued query requests
        self._cond = threading.Condition()
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self.batches = 0
        # perf_counter at which the consumer woke for the current
        # collect cycle — the queue-wait / coalesce-wait boundary for
        # phase spans. Consumer-thread-private.
        self._wake_pc = 0.0

    # -- producer side ---------------------------------------------------------

    def submit(self, req: Request) -> Dict[str, Any]:
        """Admission decision + enqueue; returns the decision dict.
        Rejected requests complete immediately with the reason.

        Two-phase admission: the request-local half (shape caps + the
        ``serve.admit`` injection hook, which may SLEEP for a straggler
        fault) runs before the queue lock; only the queue-state half —
        pure reads + arithmetic — runs under it, keeping decision +
        enqueue atomic without a blocking call under the lock (check
        rule R703 enforces this split statically)."""
        if req.kind == "query":
            kmax = int(req.ks.max()) if req.nq else 0
            a0 = time.perf_counter() if obs_trace.sinks_active() else 0.0
            pre = self.admission.precheck(req.nq, kmax)
            with self._cond:
                decision = self.admission.decide_queued(
                    req.nq, kmax, self._queued_queries,
                    queued_kmax=self._queued_kmax, prechecked=pre)
                if decision["verdict"] == ACCEPT:
                    self._queue.append(req)
                    self._queued_queries += req.nq
                    self._queued_kmax = max(self._queued_kmax, kmax)
                    telemetry.registry().gauge("serve.queue_depth").set(
                        self._queued_queries)
                    self._cond.notify()
            if a0:
                # Runs on the handler thread CONCURRENTLY with the
                # queue wait, so it is reported for attribution but
                # excluded from the phase sum the merge reconciles
                # (see tools/merge_traces.py --fleet).
                args = {"rid": req.rid} if req.rid else {}
                obs_trace.complete_at("serve.phase.admission", a0,
                                      time.perf_counter(),
                                      verdict=decision["verdict"], **args)
            if decision["verdict"] != ACCEPT:
                req.complete(error=f"rejected: {decision['reason']}")
            return decision
        # Ingest + corpus reads ride the same queue (serialized against
        # solves) but skip the per-query admission gates; capacity
        # errors surface at execution.
        with self._cond:
            if self.admission.draining:
                req.complete(error="rejected: draining")
                return {"verdict": "reject", "reason": "draining"}
            self._queue.append(req)
            self._cond.notify()
        return {"verdict": ACCEPT, "reason": "ok"}

    # -- consumer side ---------------------------------------------------------

    def start(self) -> None:
        # The whole check-then-spawn is one critical section: two
        # concurrent start() calls (or start() racing stop()) must not
        # each observe `_thread is None` and spawn TWO consumer loops —
        # the single-consumer invariant is what lets the engine run
        # lock-free. `_stop` is likewise guarded state (the consumer
        # reads it under the lock in _collect/_run_loop).
        with self._cond:
            if self._thread is not None:
                return
            self._stop = False
            t = self._thread = threading.Thread(
                target=self._run_loop, name="serve-batcher",
                daemon=True)
            # started inside the guard so a racing stop() can never
            # grab an un-started handle (join would raise); the new
            # consumer just blocks on the lock until we release
            t.start()

    def stop(self, drain: bool = True, timeout: float = 60.0) -> None:
        """Stop the batcher thread. ``drain=True`` finishes everything
        already queued first (the SIGTERM path); ``drain=False`` fails
        queued requests with a shutdown error."""
        with self._cond:
            self._stop = True
            if not drain:
                while self._queue:
                    self._queue.popleft().complete(error="shutdown")
                self._queued_queries = 0
                self._queued_kmax = 0   # the queue is empty: a stale
                #            kmax would over-price the next admission
                #            if this batcher is ever restarted
            self._cond.notify_all()
            t = self._thread
            self._thread = None     # handle handoff under the lock;
            #                         the join itself must NOT hold it
            #                         (the consumer needs the lock to
            #                         finish draining — check R703)
        if t is not None:
            t.join(timeout=timeout)

    def _collect(self) -> List[Request]:
        """Block for work, then drain the queue up to the batch cap —
        the 'coalesce whatever is queued each tick' core. A lone
        request waits one tick for company before solving solo."""
        with self._cond:
            while not self._queue and not self._stop:
                self._cond.wait(timeout=0.1)
            if not self._queue:
                return []
            self._wake_pc = time.perf_counter()
            if not self._stop and self.tick_s > 0 \
                    and self._queued_queries < self.max_batch_queries:
                self._cond.wait(timeout=self.tick_s)
            batch: List[Request] = []
            total = 0
            while self._queue:
                head = self._queue[0]
                if head.kind != "query":
                    if batch:
                        break          # solve what we have first
                    self._queue.popleft()
                    return [head]      # ingest/corpus execute standalone
                if batch and total + head.nq > self.max_batch_queries:
                    break
                self._queue.popleft()
                batch.append(head)
                total += head.nq
            self._queued_queries -= total
            if self._queued_queries == 0:
                self._queued_kmax = 0   # conservative: only reset when
                #                         nothing queued remains
            telemetry.registry().gauge("serve.queue_depth").set(
                self._queued_queries)
            return batch

    def _run_loop(self) -> None:
        while True:
            batch = self._collect()
            if not batch:
                with self._cond:
                    if self._stop and not self._queue:
                        return
                continue
            if batch[0].kind == "ingest":
                self._execute_ingest(batch[0])
            elif batch[0].kind == "corpus":
                self._execute_corpus(batch[0])
            else:
                self._execute_batch(batch)

    def _phase(self, name: str, t0: float, t1: float, rid: str,
               **args) -> None:
        """One request-phase span through the complete_at seam (tracer
        AND the PR 9 telemetry observer, so ``serve.phase.*.ms``
        histograms stay live); rid-tagged when the request carried
        one. Callers gate on sinks_active()."""
        if rid:
            args["rid"] = rid
        obs_trace.complete_at(name, t0, max(t0, t1), **args)

    def _execute_ingest(self, req: Request) -> None:
        e0 = 0.0
        if obs_trace.sinks_active():
            e0 = time.perf_counter()
            # check: allow-concurrency=R702 — _wake_pc is written in
            # _collect and read here, both only on the batcher thread
            # (_run is the sole caller of either); the write holds
            # _cond only because _collect already does.
            self._phase("serve.phase.queue", req.t_enqueue_pc,
                        max(req.t_enqueue_pc, self._wake_pc), req.rid,
                        kind="ingest")
        try:
            # The fleet chaos harness's dropped-ingest site: a
            # transient fault here fails THIS replica's ingest before
            # any state is touched — the router reports the divergence
            # and the consistency repairer must re-deliver the rows.
            rs_inject.fire("serve.ingest", rows=int(len(req.labels)),
                           start=-1 if req.start is None
                           else int(req.start))
            rows = self.engine.ingest(req.labels, req.attrs,
                                      start=req.start)
            req.complete(corpus_rows=rows)
        except Exception as e:  # check: no-retry — surfaced to the client
            req.complete(error=f"{type(e).__name__}: {e}")
        if e0:
            self._phase("serve.phase.ingest", e0, time.perf_counter(),
                        req.rid, ok=req.error is None)

    def _execute_corpus(self, req: Request) -> None:
        """Serve one ``corpus`` read on the batcher thread: the rows
        and the signature are one snapshot (no ingest can interleave)."""
        e0 = 0.0
        if obs_trace.sinks_active():
            e0 = time.perf_counter()
            # check: allow-concurrency=R702 — batcher-thread-only read
            # (see _execute_ingest).
            self._phase("serve.phase.queue", req.t_enqueue_pc,
                        max(req.t_enqueue_pc, self._wake_pc), req.rid,
                        kind="corpus")
        try:
            state = self.engine.corpus_state()
            labels, attrs = self.engine.corpus_slice(req.start or 0,
                                                     req.count or 0)
            req.payload = {
                "start": max(0, min(int(req.start or 0), state["rows"])),
                "labels": [int(v) for v in labels],
                "rows": [[float(x) for x in row] for row in attrs],
                "corpus_rows": state["rows"],
                "checksum": state["checksum"],
                "epoch": state["epoch"],
            }
            req.complete()
        except Exception as e:  # check: no-retry — surfaced to the client
            req.complete(error=f"{type(e).__name__}: {e}")
        if e0:
            self._phase("serve.phase.corpus", e0, time.perf_counter(),
                        req.rid, ok=req.error is None)

    def _execute_batch(self, batch: List[Request]) -> None:
        reg = telemetry.registry()
        total = sum(r.nq for r in batch)
        q = np.concatenate([r.query_attrs for r in batch])
        ks = np.concatenate([r.ks for r in batch])
        qpad, _ = self.engine.bucket_shape(
            total, int(ks.max()) if total else 1)
        tracing = obs_trace.sinks_active()
        rids = ",".join(r.rid for r in batch if r.rid) if tracing else ""
        t0 = time.perf_counter()
        try:
            # The chaos harness's straggler-solve site: a delay fault
            # here slows THIS replica's serialized batch execution (the
            # single consumer sleeps while the core stays idle), which
            # is how tools/slo_smoke.py emulates accelerator-bound
            # service times on a CPU-only container; a transient fault
            # fails the whole batch visibly (serve.batch_errors).
            rs_inject.fire("serve.solve", requests=len(batch),
                           queries=total)
            with obs_span("serve.micro_batch", requests=len(batch),
                          queries=total, qpad=qpad,
                          **({"rids": rids} if rids else {})):
                if rids:
                    # Single consumer thread: the engine reads this
                    # inside solve_batch to rid-tag its internal spans.
                    self.engine.trace_rids = rids
                try:
                    results = self.engine.solve_batch(q, ks)
                finally:
                    if rids:
                        self.engine.trace_rids = None
        except Exception as e:  # check: no-retry — batch fails visibly,
            reg.counter("serve.batch_errors").inc()  # daemon survives
            msg = f"{type(e).__name__}: {e}"
            for r in batch:
                r.complete(error=msg)
            return
        t1 = time.perf_counter()
        ms = (t1 - t0) * 1e3
        with self._cond:
            # handler threads read `batches` through daemon.stats()
            # while this consumer increments it — guard the write so
            # the field has one discipline (reads are single int loads)
            self.batches += 1
        reg.counter("serve.batches").inc()
        reg.histogram("serve.batch_latency_ms", unit="ms").observe(ms)
        reg.histogram("serve.batch_queries").observe(total)
        reg.gauge("serve.batch_fill").set(round(total / max(qpad, 1), 6))
        off = 0
        for r in batch:
            sub = results[off:off + r.nq]
            # Re-anchor query ids to the request (byte-identical to the
            # solo solve of the same request over the same corpus).
            local = [dataclasses.replace(qr, query_id=qr.query_id - off)
                     for qr in sub]
            off += r.nq
            r.complete(results=local)
            reg.counter("serve.requests_completed").inc()
            reg.counter("serve.queries_completed").inc(r.nq)
            reg.histogram("serve.request_latency_ms", unit="ms").observe(
                (time.monotonic() - r.t_enqueue) * 1e3,
                exemplar=r.rid or None)
            if tracing:
                # Per-request phase decomposition. queue ends when the
                # consumer woke (clamped: a request that arrived during
                # the coalesce tick has zero queue wait); coalesce runs
                # to solve start; the full batch solve interval is
                # attributed to EVERY coalesced request (documented
                # overlap — the phases of one rid tile its wall time,
                # they do not sum across rids).
                # check: allow-concurrency=R702 — batcher-thread-only
                # read (see _execute_ingest).
                q1 = min(max(self._wake_pc, r.t_enqueue_pc), t0)
                self._phase("serve.phase.queue", r.t_enqueue_pc, q1,
                            r.rid)
                self._phase("serve.phase.coalesce", q1, t0, r.rid,
                            requests=len(batch))
                self._phase("serve.phase.solve", t0, t1, r.rid,
                            queries=total, qpad=qpad)
                self._phase("serve.phase.finalize", t1,
                            time.perf_counter(), r.rid)
