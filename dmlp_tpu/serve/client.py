"""Replay client + recorded query-trace format.

A serve trace is a JSONL file: one header line, then one request line
per wire request. Query attribute values are NOT embedded — each line
carries a seed, and both the client and the verifier materialize the
same rows from it (``numpy.random.default_rng``), so a committed trace
stays a few hundred bytes while the replay is bit-deterministic::

    {"serve_trace_schema": 1,
     "corpus": {"num_data": ..., "num_queries": ..., "num_attrs": ...,
                "min_attr": ..., "max_attr": ..., "min_k": ...,
                "max_k": ..., "num_labels": ..., "seed": ...},
     "note": "..."}
    {"t_ms": 0, "nq": 3, "k": 5, "seed": 101}
    {"t_ms": 4, "nq": 1, "ks": [9], "seed": 102}
    ...

The ``corpus`` block is :func:`dmlp_tpu.io.datagen.generate_input_text`
kwargs — the daemon host regenerates the exact corpus file from it.
``replay`` drives the requests over N concurrent connections (the
micro-batcher coalesces across them) and returns per-request client
latencies + responses; :func:`golden_reference` computes the oracle
checksums every response must match byte-for-byte.
"""

from __future__ import annotations

import json
import socket
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from dmlp_tpu.io.grammar import KNNInput, Params
from dmlp_tpu.obs import trace as obs_trace

TRACE_SCHEMA = 1


class ServeClient:
    """One line-JSON connection to the daemon."""

    def __init__(self, port: int, host: str = "127.0.0.1",
                 timeout_s: float = 600.0):
        self._sock = socket.create_connection((host, port),
                                              timeout=timeout_s)
        self._rfile = self._sock.makefile("rb")

    def call(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        self._sock.sendall((json.dumps(obj) + "\n").encode())
        line = self._rfile.readline()
        if not line:
            raise ConnectionError("daemon closed the connection")
        return json.loads(line)

    def query(self, queries, k=None, ks=None, req_id: str = "",
              debug: bool = False) -> Dict[str, Any]:
        obj: Dict[str, Any] = {"op": "query", "id": req_id,
                               "queries": np.asarray(queries).tolist()}
        if ks is not None:
            obj["ks"] = [int(v) for v in ks]
        else:
            obj["k"] = int(k)
        if debug:
            obj["debug"] = True
        return self.call(obj)

    def ingest(self, labels, rows) -> Dict[str, Any]:
        return self.call({"op": "ingest",
                          "labels": [int(v) for v in labels],
                          "rows": np.asarray(rows).tolist()})

    def stats(self) -> Dict[str, Any]:
        return self.call({"op": "stats"})

    def drain(self) -> Dict[str, Any]:
        return self.call({"op": "drain"})

    def close(self) -> None:
        try:
            self._rfile.close()
            self._sock.close()
        except OSError:
            pass


# -- trace format --------------------------------------------------------------

def validate_trace(header: Dict[str, Any],
                   requests: List[Dict[str, Any]]) -> List[str]:
    """Structural validation of a loaded serve trace; returns a list
    of problems (empty = valid). Beyond the per-line field checks, the
    ``t_ms`` offsets must be non-negative and MONOTONIC non-decreasing
    in file order: paced open-loop replay fires requests at their
    offsets, and a trace whose offsets run backwards would silently
    reorder the offered-load schedule it claims to encode —
    ``tools/check_serve_trace.py`` is the CLI gate."""
    problems: List[str] = []
    if not isinstance(header, dict):
        return [f"header must be a JSON object, got "
                f"{type(header).__name__}"]
    if header.get("serve_trace_schema") != TRACE_SCHEMA:
        problems.append(
            f"header is not serve_trace_schema={TRACE_SCHEMA}")
    corpus = header.get("corpus")
    if not isinstance(corpus, dict):
        problems.append("header carries no corpus block")
    else:
        for key in ("num_data", "num_attrs", "min_attr", "max_attr",
                    "num_labels"):
            if key not in corpus:
                problems.append(f"corpus block missing {key!r}")
    prev_t = None
    for i, r in enumerate(requests, 1):
        if not isinstance(r, dict):
            problems.append(f"request line {i} must be a JSON object")
            continue
        if "nq" not in r or "seed" not in r \
                or ("k" not in r and "ks" not in r):
            problems.append(f"request line {i} needs nq, seed, and k|ks")
            continue
        if not isinstance(r["nq"], int) or r["nq"] < 1:
            problems.append(f"request line {i}: nq must be a positive "
                            "int")
        ks = r.get("ks", [r.get("k")])
        if not isinstance(ks, list):
            problems.append(f"request line {i}: 'ks' must be a list")
        elif not all(isinstance(v, int) and not isinstance(v, bool)
                     and v >= 1 for v in ks):
            problems.append(f"request line {i}: k|ks must be positive "
                            "ints")
        t = r.get("t_ms")
        if t is not None:
            if not isinstance(t, (int, float)) or t < 0:
                problems.append(f"request line {i}: t_ms must be a "
                                "non-negative number")
            elif prev_t is not None and t < prev_t:
                problems.append(
                    f"request line {i}: t_ms {t} < previous {prev_t} — "
                    "offsets must be monotonic non-decreasing")
            else:
                prev_t = t
    return problems


def load_trace(path: str) -> Tuple[Dict[str, Any], List[Dict[str, Any]]]:
    with open(path) as f:
        lines = [json.loads(ln) for ln in f if ln.strip()]
    if not lines or not isinstance(lines[0], dict) \
            or lines[0].get("serve_trace_schema") != TRACE_SCHEMA:
        raise ValueError(f"{path}: not a serve_trace_schema="
                         f"{TRACE_SCHEMA} file")
    header, reqs = lines[0], lines[1:]
    problems = validate_trace(header, reqs)
    if problems:
        raise ValueError(f"{path}: {problems[0]}"
                         + (f" (+{len(problems) - 1} more)"
                            if len(problems) > 1 else ""))
    return header, reqs


def materialize_queries(req: Dict[str, Any],
                        header: Dict[str, Any]) -> np.ndarray:
    """The request line's deterministic query rows (client and
    verifier call this with the same line -> same bytes)."""
    c = header["corpus"]
    rng = np.random.default_rng(int(req["seed"]))
    return rng.uniform(c["min_attr"], c["max_attr"],
                       (int(req["nq"]), int(c["num_attrs"])))


def request_ks(req: Dict[str, Any]) -> np.ndarray:
    if "ks" in req:
        return np.asarray(req["ks"], np.int32)
    return np.full(int(req["nq"]), int(req["k"]), np.int32)


def corpus_text(header: Dict[str, Any]) -> str:
    """Regenerate the trace's corpus file content (the daemon input)."""
    from dmlp_tpu.io.datagen import generate_input_text
    c = dict(header["corpus"])
    return generate_input_text(
        c["num_data"], c.get("num_queries", 8), c["num_attrs"],
        c["min_attr"], c["max_attr"], c.get("min_k", 1),
        c.get("max_k", 8), c["num_labels"], seed=c.get("seed", 42))


# -- replay --------------------------------------------------------------------

def replay(port: int, header: Dict[str, Any],
           requests: List[Dict[str, Any]], connections: int = 4,
           pace: bool = False) -> List[Dict[str, Any]]:
    """Replay the trace over ``connections`` concurrent connections
    (round-robin assignment, per-connection order preserved). Returns
    one dict per request IN TRACE ORDER: the wire response plus the
    client-measured ``client_ms`` latency. ``pace=True`` honors the
    trace's ``t_ms`` offsets; the default replays as fast as the
    daemon admits (the sustained-throughput measurement)."""
    out: List[Optional[Dict[str, Any]]] = [None] * len(requests)
    lanes: List[List[int]] = [[] for _ in range(max(connections, 1))]
    for i in range(len(requests)):
        lanes[i % len(lanes)].append(i)
    t0 = time.monotonic()

    def lane_worker(lane: List[int]) -> None:
        cli = ServeClient(port)
        try:
            for i in lane:
                req = requests[i]
                if pace and "t_ms" in req:
                    delay = req["t_ms"] / 1e3 - (time.monotonic() - t0)
                    if delay > 0:
                        time.sleep(delay)
                q = materialize_queries(req, header)
                ks = request_ks(req)
                t = time.perf_counter()
                resp = cli.query(q, ks=[int(v) for v in ks],
                                 req_id=str(i))
                resp["client_ms"] = round(
                    (time.perf_counter() - t) * 1e3, 3)
                out[i] = resp
        finally:
            cli.close()

    threads = [threading.Thread(target=lane_worker, args=(lane,),
                                daemon=True)
               for lane in lanes if lane]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return [r if r is not None else {"ok": False, "error": "no response"}
            for r in out]


def replay_open_loop(port: int, header: Dict[str, Any],
                     requests: List[Dict[str, Any]], speed: float = 1.0,
                     host: str = "127.0.0.1",
                     timeout_s: float = 600.0,
                     rid_prefix: Optional[str] = None,
                     level: Optional[float] = None
                     ) -> List[Dict[str, Any]]:
    """Paced OPEN-LOOP replay: every request fires AT its trace
    ``t_ms`` offset (divided by ``speed`` — ``speed=2`` offers 2× the
    trace's load) on its own connection, REGARDLESS of completions —
    the closed-loop replay's lanes throttle the client to the daemon's
    pace, which silently caps offered load at achieved load and hides
    queueing. Here latency is measured from the SCHEDULED fire time,
    so queue delay (daemon-side and client-side dispatch lag, reported
    separately as ``lag_ms``) lands in ``client_ms`` — the number a
    p99-under-offered-load claim is actually about.

    Query payloads are pre-materialized and pre-encoded before the
    clock starts so the fire loop does no per-request numeric work.
    Returns one dict per request in trace order: the wire response (or
    an ``ok: false`` error for connection failures) plus ``client_ms``
    and ``lag_ms``.

    ``rid_prefix`` turns on request tracing: every payload carries
    ``rid = f"{rid_prefix}{i}"`` (pre-encoded) plus a ``trace`` context
    stamped AT FIRE TIME — the pre-encoded body is held open (no
    closing brace) so the fire loop appends the stamped tail by byte
    concatenation, never re-encoding the query rows. Each response
    additionally emits a ``client.request`` span (scheduled fire ->
    response parsed, i.e. exactly ``client_ms``) on the process's
    trace sinks, rid-tagged, with ``level`` attached for tail
    attribution."""
    traced = bool(rid_prefix)
    payloads = []
    for i, req in enumerate(requests):
        q = materialize_queries(req, header)
        ks = request_ks(req)
        obj = {"op": "query", "id": str(i), "queries": q.tolist(),
               "ks": [int(v) for v in ks]}
        if traced:
            obj["rid"] = f"{rid_prefix}{i}"
            # sans closing brace: the fire-time trace tail completes it
            payloads.append(json.dumps(obj)[:-1].encode())
        else:
            payloads.append((json.dumps(obj) + "\n").encode())
    out: List[Optional[Dict[str, Any]]] = [None] * len(requests)
    t0 = time.monotonic() + 0.05    # small runway so request 0 is paced
    t0_wall = time.time() + (t0 - time.monotonic())

    def worker(i: int) -> None:
        off_s = float(requests[i].get("t_ms", 0)) / 1e3 \
            / max(speed, 1e-9)
        sched = t0 + off_s
        delay = sched - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        lag_ms = (time.monotonic() - sched) * 1e3
        data = payloads[i]
        if traced:
            data += (',"trace":{"sched_unix_ms":%.3f,"lag_ms":%.3f}}\n'
                     % ((t0_wall + off_s) * 1e3, lag_ms)).encode()
        try:
            with socket.create_connection((host, port),
                                          timeout=timeout_s) as sock:
                sock.sendall(data)
                with sock.makefile("rb") as rf:
                    line = rf.readline()
            if not line:
                raise ConnectionError("daemon closed the connection")
            resp = json.loads(line)
        except (OSError, ValueError) as e:
            resp = {"ok": False, "error": f"{type(e).__name__}: {e}"}
        resp["client_ms"] = round((time.monotonic() - sched) * 1e3, 3)
        resp["lag_ms"] = round(lag_ms, 3)
        if traced and obs_trace.sinks_active():
            # Recover the scheduled fire instant in the tracer's
            # perf_counter domain (both clocks are CLOCK_MONOTONIC
            # rates): the span IS client_ms, queue lag included.
            t1p = time.perf_counter()
            t0p = t1p - (time.monotonic() - sched)
            args = {"rid": f"{rid_prefix}{i}",
                    "lag_ms": round(lag_ms, 3),
                    "ok": bool(resp.get("ok")),
                    "hops": int(resp.get("hops", 1))}
            if level is not None:
                args["level"] = level
            obs_trace.complete_at("client.request", t0p, t1p, **args)
        out[i] = resp

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(len(requests))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return [r if r is not None
            else {"ok": False, "error": "no response"} for r in out]


def warm_buckets_for_trace(requests: List[Dict[str, Any]],
                           batch_queries_cap: int
                           ) -> List[Tuple[int, int]]:
    """The (nq, k) warm set covering every shape bucket a replay of
    ``requests`` can hit: a coalesced micro-batch buckets by its
    COMBINED query count, so this is the cross product of qpad buckets
    up to the batch cap with the trace's k buckets. The compile-once
    assertion (counter flat between ready and drain) is only
    meaningful against this set — both the bench harness and the
    smoke derive it here."""
    from dmlp_tpu.serve.engine import k_bucket, query_bucket
    kbs = sorted({k_bucket(int(max(r["ks"]) if "ks" in r else r["k"]))
                  for r in requests})
    qpads, qp = [], 8
    while qp <= query_bucket(batch_queries_cap):
        qpads.append(qp)
        qp *= 2
    return [(qp, kb) for qp in qpads for kb in kbs]


# -- daemon lifecycle (shared by the bench harness and the smoke) --------------

def clear_flight_dumps(directory: str) -> List[str]:
    """Remove stale ``FLIGHT_*.json`` post-mortems from ``directory``
    and return their names. Callers that assert 'an orderly drain left
    no flight dump' MUST call this first: a crash in a previous run
    would otherwise fail every later clean run forever."""
    import os
    stale = [p for p in os.listdir(directory)
             if p.startswith("FLIGHT_") and p.endswith(".json")]
    for p in stale:
        os.remove(os.path.join(directory, p))
    return stale


def flight_dumps(directory: str) -> List[str]:
    import os
    return [p for p in os.listdir(directory) if p.startswith("FLIGHT_")]


def await_ready(proc, ready_path: str, timeout_s: float = 300.0,
                errlog: str = "") -> Dict[str, Any]:
    """Block until the daemon subprocess writes its ready file; raise
    (naming the stderr log) if it dies or times out first."""
    import json as _json
    import os
    deadline = time.monotonic() + timeout_s
    while not os.path.exists(ready_path):
        if proc.poll() is not None:
            raise RuntimeError(
                "serve daemon died before ready"
                + (f"; see {errlog}" if errlog else ""))
        if time.monotonic() > deadline:
            raise RuntimeError(
                f"serve daemon not ready after {timeout_s}s")
        time.sleep(0.05)
    with open(ready_path) as f:
        return _json.load(f)


def sigterm_drain(proc, timeout_s: float = 60.0,
                  errlog: str = "") -> None:
    """SIGTERM the daemon and require the orderly-drain contract:
    exit code 0 within the timeout."""
    import signal
    proc.send_signal(signal.SIGTERM)
    rc = proc.wait(timeout=timeout_s)
    if rc != 0:
        raise RuntimeError(
            f"serve daemon drain exited {rc}"
            + (f"; see {errlog}" if errlog else ""))


# -- verification --------------------------------------------------------------

def golden_reference(corpus: KNNInput, header: Dict[str, Any],
                     requests: List[Dict[str, Any]]
                     ) -> List[List[int]]:
    """Per-request golden checksum lists for the trace against
    ``corpus`` — the byte-identity oracle for every replay arm."""
    from dmlp_tpu.golden.fast import knn_golden_fast
    out: List[List[int]] = []
    for req in requests:
        q = materialize_queries(req, header)
        ks = request_ks(req)
        inp = KNNInput(Params(corpus.params.num_data, len(ks),
                              corpus.params.num_attrs),
                       corpus.labels, corpus.data_attrs, ks, q)
        out.append([int(r.checksum()) for r in knn_golden_fast(inp)])
    return out


def contract_text(checksum_lists: List[List[int]]) -> str:
    """Flatten per-request checksums into the engines' contract stdout
    form (global query ids in trace order) — the thing two replay arms
    and the golden oracle must agree on byte-for-byte."""
    lines = []
    gid = 0
    for cs in checksum_lists:
        for c in cs:
            lines.append(f"Query {gid} checksum: {c}")
            gid += 1
    return "\n".join(lines) + ("\n" if lines else "")
