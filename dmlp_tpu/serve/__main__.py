"""``python -m dmlp_tpu.serve`` — the resident serving daemon CLI.

Usage::

    python -m dmlp_tpu.serve --corpus FILE [--port 0]
        [--capacity ROWS] [--max-k K] [--max-batch-queries N]
        [--max-queue-queries N] [--tick-ms MS] [--gate-carry on|off]
        [--hbm-budget BYTES|auto] [--pallas] [--select auto|...]
        [--dtype auto|float32|bfloat16] [--data-block N]
        [--warm-buckets NQxK,NQxK,...] [--compile-cache DIR]
        [--telemetry FILE] [--telemetry-port PORT] [--record FILE]
        [--snapshot-every-s S] [--ready-file PATH] [--faults FILE]

The corpus file is the standard input grammar; its data section
becomes the resident corpus, its query section seeds the warm-up
buckets. The daemon prints ``dmlp_tpu.serve: ready port=P`` on stderr
(and writes ``--ready-file``) once every warm bucket is compiled, then
serves until SIGTERM / an in-band ``drain`` op — which finishes
in-flight micro-batches, flushes the final telemetry snapshot and
serve RunRecord, and exits 0 with no flight dump.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional, Sequence, Tuple


def _parse_warm_buckets(spec: str) -> List[Tuple[int, int]]:
    out: List[Tuple[int, int]] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        try:
            nq, k = part.lower().split("x")
            out.append((int(nq), int(k)))
        except ValueError:
            raise SystemExit(
                f"--warm-buckets entries are NQxK, got {part!r}")
    return out


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="dmlp_tpu.serve",
                                description=__doc__)
    p.add_argument("--corpus", required=True,
                   help="input-grammar file; data section = resident "
                        "corpus, query section = warm-up shapes")
    p.add_argument("--port", type=int, default=0,
                   help="TCP port (0 = ephemeral; announced on stderr "
                        "and in --ready-file)")
    p.add_argument("--capacity", type=int, default=None,
                   help="ingest ceiling in rows (default: corpus rows "
                        "rounded to the next power of two)")
    p.add_argument("--max-k", type=int, default=None,
                   help="largest per-query k admitted (default: the "
                        "engine's serving cap)")
    p.add_argument("--max-batch-queries", type=int, default=1024)
    p.add_argument("--max-queue-queries", type=int, default=4096)
    p.add_argument("--tick-ms", type=float, default=2.0,
                   help="micro-batch coalescing tick")
    p.add_argument("--gate-carry", choices=["on", "off"], default="on",
                   help="cross-request fused-gate warm-up (hot-block "
                        "fold ordering); results are byte-identical "
                        "either way")
    p.add_argument("--hbm-budget", default="auto",
                   help="admission memory budget in bytes ('auto' = "
                        "backend bytes_limit when reported, else "
                        "memory shedding off)")
    p.add_argument("--pallas", action="store_true",
                   help="extract-kernel resident path where supported")
    p.add_argument("--select", default="auto",
                   choices=["auto", "sort", "topk", "seg", "extract"])
    p.add_argument("--dtype", default="auto",
                   choices=["auto", "float32", "bfloat16"])
    p.add_argument("--precision", default="auto",
                   choices=["auto", "f32", "bf16"],
                   help="first-pass dot precision for the extract-path "
                        "kernels; bf16 widens candidate windows by the "
                        "analytic lowp_eps bound and keeps responses "
                        "byte-identical via the f64 rescore + repair "
                        "(plan frozen at startup; $DMLP_TPU_PRECISION "
                        "=f32 is the live kill switch). Fleet replicas "
                        "inherit this through --spawn-flags.")
    p.add_argument("--data-block", type=int, default=None)
    p.add_argument("--warm-buckets", default=None, metavar="NQxK,...",
                   help="extra shape buckets to compile before ready")
    p.add_argument("--mesh", default=None, metavar="RxC",
                   help="serve MESH-RESIDENT: hold the corpus sharded "
                        "across an RxC device mesh "
                        "(dmlp_tpu.fleet.mesh_engine; per-shard "
                        "resident buffers, allgather/ring merge as the "
                        "micro-batch epilogue)")
    p.add_argument("--mesh-merge",
                   choices=["allgather", "ring", "auto"],
                   default="allgather",
                   help="candidate-merge collective for --mesh "
                        "('auto' hands the cross-shard merge to the "
                        "GSPMD partitioner — engine.auto's merge "
                        "point as the micro-batch epilogue)")
    p.add_argument("--compile-cache", metavar="DIR", default=None,
                   help="persistent XLA compilation cache dir (best "
                        "effort; restarts then reuse executables); "
                        "$DMLP_TPU_COMPILE_CACHE is the ambient form "
                        "(flag wins)")
    p.add_argument("--telemetry", metavar="FILE", default=None)
    p.add_argument("--telemetry-port", type=int, default=None,
                   metavar="PORT")
    p.add_argument("--record", metavar="FILE", default=None,
                   help="append serve RunRecords (ledger serve/ "
                        "series) here — final on drain, periodic with "
                        "--snapshot-every-s")
    p.add_argument("--snapshot-every-s", type=float, default=0.0)
    p.add_argument("--trace", metavar="FILE", default=None,
                   help="write a Chrome-trace JSON of request-phase "
                        "spans here on drain (rid-tagged; merge the "
                        "fleet's files with tools/merge_traces.py "
                        "--fleet)")
    p.add_argument("--slo", action="append", default=None,
                   metavar="SPEC",
                   help="declare an SLO objective (repeatable), e.g. "
                        "'serve.request_latency_ms p99 < 50 over 1m' "
                        "or 'serve.requests_completed/serve.admitted "
                        "availability > 0.999 over 5m'; evaluated "
                        "continuously (dmlp_tpu.obs.slo) — transitions "
                        "emit slo.alert trace/flight events and the "
                        "slo_* OpenMetrics family")
    p.add_argument("--ready-file", metavar="PATH", default=None)
    p.add_argument("--faults", metavar="FILE", default=None,
                   help="fault-injection schedule "
                        "(dmlp_tpu.resilience.inject; the serve.admit "
                        "oom fault is the injected memory squeeze)")
    args = p.parse_args(argv)

    # The actual DMLP_TPU_RACECHECK=1 install happens in
    # dmlp_tpu/serve/__init__.py — which `python -m dmlp_tpu.serve`
    # executes BEFORE this module, i.e. before the serving imports
    # create any locks. This call is an idempotent backstop for
    # embedders who import __main__.main directly;
    # DMLP_TPU_RACECHECK_OUT collects the verdict at drain (the
    # `make race-smoke` harness reads it).
    from dmlp_tpu.check import racecheck
    racecheck.install_from_env()

    from dmlp_tpu.config import EngineConfig
    from dmlp_tpu.io.grammar import parse_input
    from dmlp_tpu.resilience import inject as rs_inject
    from dmlp_tpu.serve.daemon import ServeDaemon
    from dmlp_tpu.utils.compile_cache import enable_from_flag

    # --compile-cache wins; $DMLP_TPU_COMPILE_CACHE is the ambient form
    # (fleet harnesses warm a whole replica tree through the env).
    enable_from_flag(args.compile_cache)
    budget = None
    if args.hbm_budget != "auto":
        budget = int(args.hbm_budget)
    with open(args.corpus) as f:
        corpus = parse_input(f)
    warm = None
    if args.warm_buckets:
        warm = _parse_warm_buckets(args.warm_buckets)
        from dmlp_tpu.serve.daemon import default_warm_buckets
        warm = default_warm_buckets(corpus) + warm
    config = EngineConfig(dtype=args.dtype, select=args.select,
                          use_pallas=args.pallas,
                          data_block=args.data_block,
                          precision=args.precision)
    mesh_shape = None
    if args.mesh:
        try:
            r, c = args.mesh.lower().split("x")
            mesh_shape = (int(r), int(c))
        except ValueError:
            raise SystemExit(f"--mesh is RxC, got {args.mesh!r}")
    schedule = rs_inject.install_from_env(args.faults)
    daemon = ServeDaemon(
        corpus, config, port=args.port, capacity=args.capacity,
        gate_carry=args.gate_carry == "on", budget_bytes=budget,
        max_batch_queries=args.max_batch_queries,
        max_queue_queries=args.max_queue_queries, max_k=args.max_k,
        tick_s=args.tick_ms / 1e3, telemetry_path=args.telemetry,
        telemetry_port=args.telemetry_port, record_path=args.record,
        snapshot_every_s=args.snapshot_every_s, warm_buckets=warm,
        mesh_shape=mesh_shape, mesh_merge=args.mesh_merge,
        trace_path=args.trace, objectives=args.slo)
    try:
        daemon.start()
        sys.stderr.write(f"dmlp_tpu.serve: ready port={daemon.port} "
                         f"cold_start_compile_ms="
                         f"{daemon.engine.cold_start_compile_ms}\n")
        sys.stderr.flush()
        if args.ready_file:
            daemon.write_ready_file(args.ready_file)
        daemon.run_until_drained()
        racecheck.write_report_if_requested()
        sys.stderr.write("dmlp_tpu.serve: drained clean\n")
        return 0
    except Exception:
        if daemon.session is not None:
            from dmlp_tpu.obs import telemetry
            telemetry.dump_on_crash("serve_crash")
        raise
    finally:
        if schedule is not None:
            rs_inject.write_log_if_requested()
            rs_inject.uninstall()


if __name__ == "__main__":
    sys.exit(main())
