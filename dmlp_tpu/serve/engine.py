"""Resident serving engine: stage once, compile once per shape bucket.

:class:`ResidentEngine` is the serving daemon's solve core. It differs
from the batch :class:`~dmlp_tpu.engine.single.SingleChipEngine` in
exactly the ways a persistent server needs, and nowhere else — the
candidates -> host-float64 finalize -> boundary-hazard repair
pipeline (the byte-identity contract with the golden oracle) is
inherited unchanged:

- **Resident corpus behind a row-count mask.** The corpus is staged to
  device ONCE at construction, padded to a power-of-two capacity
  (``tune.cache.shape_bucket``); rows beyond ``n_real`` carry the
  engines' standard ``id = -1`` sentinel, which every select path
  already masks to +inf. :meth:`ingest` appends rows by
  ``dynamic_update_slice`` into the fixed-shape buffer (row counts
  bucketed so the tiny update program compiles once per bucket) — the
  SOLVE programs see the same static shapes before and after, so
  ingestion never recompiles them.
- **Per-bucket compile-once solves.** Requests bucket to power-of-two
  (qpad, k) shape buckets. The streaming path is lowered and compiled
  AHEAD of time per bucket (``jit(...).lower(...).compile()``); the
  extract path's kernels compile on the bucket's first dispatch
  (:meth:`warmup` front-loads both before the first request).
  :attr:`compile_count` counts bucket builds — a replay whose buckets
  were all warmed must leave it unchanged, the serving layer's
  no-per-request-recompilation proof.
- **Cross-request fused-gate warm-up.** The extract path folds the
  SAME resident chunks for every request, so the MXU gate's
  effectiveness per chunk is a stable, learnable property. With
  ``gate_carry`` on, chunks fold in descending historical-winner order
  ("hot blocks first"): each query's k-th-best thresholds — the gate's
  input — tighten after the first folds, so later (cold) blocks gate
  out. The carried state is the per-chunk winner histogram, never a
  threshold itself: within a request thresholds still only tighten, so
  the fold is sound in any order, and the boundary-hazard repair makes
  ties at the candidate boundary exact either way — carry on and off
  are byte-identical by construction (and proven in the A/B).
- **Wide-k multipass buckets.** A k-bucket whose candidate width
  exceeds the extraction kernel's single-pass window routes through
  the batch engine's multi-pass extraction driver AGAINST THE RESIDENT
  CHUNKS (:meth:`ResidentEngine._solve_resident_multipass`): no
  staging per request, floor-chained resident re-sweeps over a cached
  concatenation, and the driver's stall/shortfall hazards feed run()'s
  exact repair — byte-identical to the solo multipass solve.
"""

from __future__ import annotations

import functools
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dmlp_tpu.config import EngineConfig
from dmlp_tpu.engine.single import (_BF16_AUTO_K_CAP, ChunkThrottle,
                                    SingleChipEngine, _extract_finalize,
                                    _topk_blocks, active_precision,
                                    fit_blocks, np_staging_dtype,
                                    plan_chunks, resilient_get, resolve_kcap,
                                    round_up, stage_put)
from dmlp_tpu.io.grammar import KNNInput, Params
from dmlp_tpu.io.report import QueryResult
from dmlp_tpu.obs import telemetry
from dmlp_tpu.obs.trace import span as obs_span
from dmlp_tpu.ops.topk import TopK
from dmlp_tpu.resilience import degrade as rs_degrade
from dmlp_tpu.tune.cache import shape_bucket


class CapacityError(RuntimeError):
    """An ingest would exceed the resident buffer's capacity (the
    daemon surfaces this as a rejected ingest, never a crash)."""


class RequestShapeError(ValueError):
    """A request shape the resident engine cannot serve (k beyond the
    serving cap) — admission rejects these before the solve."""


def query_bucket(nq: int, granule: int = 8) -> int:
    """Query-count -> power-of-two jit-cache bucket (>= ``granule``;
    the extract path's granule is the kernel's QUERY_TILE)."""
    return max(shape_bucket(max(nq, 1)), granule)


def k_bucket(kmax: int) -> int:
    """Per-request max-k -> power-of-two bucket. Candidate width (and
    hence the compiled program) derives from the BUCKET, so every k in
    (bucket/2, bucket] shares one compiled solve."""
    return shape_bucket(max(kmax, 1))


@functools.partial(jax.jit, donate_argnums=(0,))
def _update_rows_2d(buf, blk, start):
    return jax.lax.dynamic_update_slice(
        buf, blk, (start, jnp.zeros((), jnp.int32)))


@functools.partial(jax.jit, donate_argnums=(0,))
def _update_rows_1d(buf, blk, start):
    return jax.lax.dynamic_update_slice(buf, blk, (start,))


class _Bucket:
    """One (qpad, k-bucket) shape bucket: the resolved candidate width,
    the chosen path, and the AOT-compiled streaming program."""

    __slots__ = ("qpad", "kb", "kcap", "path", "qb", "nqb", "stream",
                 "hlo")

    def __init__(self, qpad: int, kb: int, kcap: int, path: str,
                 qb: int, nqb: int):
        self.qpad, self.kb, self.kcap = qpad, kb, kcap
        self.path = path          # "extract" | "multipass" | "stream"
        self.qb, self.nqb = qb, nqb
        self.stream = None        # AOT-compiled _topk_blocks, when built
        self.hlo = None           # {"fingerprint", "collective_bytes"}
        # of the compiled stream (obs.hlo), stamped at compile time in
        # the batcher thread — stats handlers read it lock-free

    @property
    def key(self) -> str:
        return f"q{self.qpad}k{self.kb}"


class ResidentServingCore:
    """The serving surface shared by BOTH resident engines (the
    single-chip :class:`ResidentEngine` and the mesh
    :class:`~dmlp_tpu.fleet.mesh_engine.MeshResidentEngine`):
    compile-once bucket bookkeeping, warm-up, the corpus max-sq-norm
    cache, and the memory-model hooks the admission controller and
    obs.memwatch read. Single-sourced here so a fix to any of them
    cannot silently miss the other engine.

    Subclass contract: ``bucket_shape``/``_build_bucket``/``max_k``/
    ``solve_batch`` plus the resident state the hooks read; the
    subclass implements :meth:`mem_model` (its analytic per-device
    model, batch terms included iff ``nq > 0``) and
    :meth:`batch_model_bytes` (the marginal per-batch terms — the
    term names differ per model), and names its cache-invalidation
    state in :meth:`resident_state_key`.
    """

    #: comma-joined rids of the micro-batch currently in solve_batch —
    #: set/cleared by the batcher (single consumer thread, plain
    #: attribute) so solve-internal spans can self-tag; None (the
    #: class default) whenever untraced.
    trace_rids: Optional[str] = None

    def _rid_args(self) -> Dict[str, Any]:
        """Span-args rider carrying the current batch's rids — empty
        (and allocation-only) when untraced."""
        t = self.trace_rids
        return {"rids": t} if t else {}

    def _bucket_entry(self, nq: int, kmax: int):
        """The bucket for (nq, kmax), building (and counting) it on
        first use — warm-up pre-drives this so steady-state serving
        takes the dict hit only."""
        if kmax > self.max_k:
            raise RequestShapeError(
                f"k={kmax} beyond the serving cap {self.max_k}")
        key = self.bucket_shape(nq, kmax)
        entry = self._buckets.get(key)
        if entry is None:
            t0 = time.perf_counter()
            entry = self._build_bucket(*key)
            self._buckets[key] = entry
            ms = (time.perf_counter() - t0) * 1e3
            self.bucket_compile_ms[entry.key] = round(ms, 3)
            self.compile_count += 1
            reg = telemetry.registry()
            reg.counter("serve.bucket_compiles").inc(label=entry.key)
            reg.histogram("serve.bucket_compile_ms", unit="ms").observe(ms)
        return entry

    def warmup(self, buckets) -> Dict[str, float]:
        """Drive one synthetic micro-batch through every (nq, k) in
        ``buckets`` BEFORE serving: compiles the bucket programs and
        the shared epilogue jits, and records
        ``serve.cold_start_compile_ms`` — the startup SLO is a number,
        not a hope. Returns per-bucket wall ms."""
        t0 = time.perf_counter()
        per: Dict[str, float] = {}
        seen = set()
        for nq, k in buckets:
            # Clamp to the serving cap ONLY — k > n_real is a legal
            # request shape (sentinel padding, golden-identical), so a
            # requested warm bucket above the corpus row count must
            # warm THAT k-bucket, not silently a smaller one.
            k = max(1, min(int(k), self.max_k))
            nq = max(1, int(nq))
            key = self.bucket_shape(nq, k)
            if key in seen:
                continue
            seen.add(key)
            tb = time.perf_counter()
            idx = np.arange(nq) % self.n_real
            q = self._host_attrs[:self.n_real][idx]
            ks = np.full(nq, k, np.int32)
            with obs_span("serve.warmup_bucket", qpad=key[0], kb=key[1]):
                self.solve_batch(q, ks)
            per[f"q{key[0]}k{key[1]}"] = round(
                (time.perf_counter() - tb) * 1e3, 3)
        self.cold_start_compile_ms = round(
            (time.perf_counter() - t0) * 1e3, 3)
        reg = telemetry.registry()
        reg.gauge("serve.cold_start_compile_ms").set(
            self.cold_start_compile_ms)
        reg.gauge("serve.warm_buckets").set(len(self._buckets))
        return per

    # -- corpus signature (fleet consistency checking reads this) -----------

    def _sig_init(self) -> None:
        """Seed the rolling corpus signature: per-row 64-bit hashes +
        a position-keyed fold (fleet.consistency). A pure function of
        (global row id, label, attribute bits) — every resident engine
        layout reports the same signature for the same corpus, which is
        what makes cross-replica comparison meaningful."""
        from dmlp_tpu.fleet import consistency as ccs
        n = self.n_real
        self._row_hash = np.zeros(len(self._host_labels), np.uint64)
        fold = 0
        if n:
            self._row_hash[:n] = ccs.row_hashes(
                self._host_labels[:n], self._host_attrs[:n])
            fold = ccs.fold_terms(0, self._row_hash[:n])
        # One tuple, assigned atomically: stats handlers read it while
        # the batcher thread ingests — a torn (rows, checksum) pair
        # would manufacture spurious divergences at the router.
        self._corpus_sig = (n, fold, 0)

    def _sig_update(self, start: int, end: int) -> None:
        """Fold rows ``[start, end)``'s new content in (O(m): subtract
        the old terms, add the new — idempotent overwrites are exact
        no-ops) and bump the ingest epoch."""
        from dmlp_tpu.fleet import consistency as ccs
        n0, fold, epoch = self._corpus_sig
        new_h = ccs.row_hashes(self._host_labels[start:end],
                               self._host_attrs[start:end])
        fold = ccs.fold_replace(fold, start,
                                self._row_hash[start:end], new_h)
        self._row_hash[start:end] = new_h
        self._corpus_sig = (max(n0, end), fold, epoch + 1)

    def corpus_state(self) -> Dict[str, int]:
        """The live corpus signature block the daemon exposes in
        ``stats`` (and the ``corpus`` wire op echoes): row count,
        rolling checksum, ingest epoch."""
        n, fold, epoch = self._corpus_sig
        return {"rows": n, "checksum": fold, "epoch": epoch}

    def corpus_slice(self, start: int, count: int):
        """Host rows ``[start, start+count)`` clamped to the resident
        row count — the ``corpus`` wire op's data source (executed on
        the batcher thread, so it never races an ingest)."""
        n = self._corpus_sig[0]
        start = max(0, min(int(start), n))
        end = max(start, min(start + int(count), n))
        return (self._host_labels[start:end].copy(),
                self._host_attrs[start:end].copy())

    # -- corpus max squared norm (boundary-eps / multipass floors) ----------

    def _dn_max(self) -> float:
        if self._dn_max_cache is None:
            a = self._host_attrs[:self.n_real]
            self._dn_max_cache = float(
                np.einsum("na,na->n", a, a).max()) if self.n_real \
                else 0.0
        return self._dn_max_cache

    def _note_ingested_norms(self, attrs: np.ndarray) -> None:
        """Append-only ingest keeps the cache incremental: the max
        squared norm only grows."""
        if self._dn_max_cache is not None and len(attrs):
            nn = np.einsum("ma,ma->m", attrs, attrs).max()
            self._dn_max_cache = max(self._dn_max_cache, float(nn))

    # -- memory-model hooks (admission + memwatch read these) ---------------

    def mem_model(self, nq: int = 0, kmax: int = 0):
        raise NotImplementedError

    def batch_model_bytes(self, nq: int, kmax: int) -> int:
        raise NotImplementedError

    def resident_state_key(self):
        """The resident-state tuple whose change invalidates a cached
        resident-floor total (admission memoizes on it)."""
        raise NotImplementedError

    def resident_model_bytes(self) -> int:
        """Per-device resident floor (corpus terms only, no batch)."""
        return int(self.mem_model(0, 0)["total_bytes"])


class ResidentEngine(ResidentServingCore, SingleChipEngine):
    """Compile-once resident engine for the serving daemon.

    ``corpus`` supplies the data side (its query section, if any, is
    ignored here — the daemon uses it to seed warm-up). ``capacity``
    is the ingest ceiling in rows (default: the corpus row count's
    power-of-two bucket, i.e. free headroom to the next boundary).
    """

    def __init__(self, corpus: KNNInput, config: EngineConfig = None,
                 capacity: Optional[int] = None, gate_carry: bool = True):
        super().__init__(config or EngineConfig())
        cfg = self.config
        n = corpus.params.num_data
        na = corpus.params.num_attrs
        if n < 1:
            raise ValueError("resident corpus must have at least one row")
        cap = capacity or shape_bucket(n)
        if cap < n:
            raise ValueError(f"capacity {cap} < corpus rows {n}")
        self.num_attrs = na
        self.gate_carry = bool(gate_carry)
        # First-pass precision PLAN, frozen at construction like every
        # other resident shape decision: bucket kcaps, the staged
        # summary-eps constants, and the active cast (engine.single
        # .active_precision clamps to this) all derive from ONE plan,
        # so an env flip mid-serve can disable the bf16 pass (windows
        # merely stay wider than needed) but can never run it against
        # windows that were planned f32.
        self._precision_plan = cfg.resolve_precision()

        # -- plan the streaming layout once, at capacity shape ---------------
        self._stream_select = cfg.resolve_streaming_select(
            round_up(cap, 8))
        granule = cfg.resolve_granule(self._stream_select)
        self._data_block = fit_blocks(
            cap, cfg.resolve_data_block(self._stream_select),
            granule=granule)
        self.capacity_rows = round_up(cap, self._data_block)

        # -- extract-path eligibility + chunk plan (chunks stage lazily) -----
        self._extract_ok = (cfg.use_pallas and cfg.resolve_select(
            round_up(cap, 8)) == "extract")
        if self._extract_ok:
            eg = cfg.resolve_granule("extract")
            _, self._ex_nchunks, self._ex_chunk_rows = plan_chunks(
                self.capacity_rows, eg, cfg.data_block)
            self._ex_rows = self._ex_nchunks * self._ex_chunk_rows
            from dmlp_tpu.ops.pallas_distance import native_pallas_backend
            self._interpret = not native_pallas_backend()
        else:
            self._ex_nchunks = self._ex_chunk_rows = self._ex_rows = 0
            self._interpret = True
        self._chunks: Optional[List] = None
        host_rows = max(self.capacity_rows, self._ex_rows)

        # -- host originals (float64 finalize rescore reads these) -----------
        self._host_attrs = np.zeros((host_rows, na), np.float64)
        self._host_attrs[:n] = corpus.data_attrs
        self._host_labels = np.full(host_rows, -1, np.int32)
        self._host_labels[:n] = corpus.labels
        self.n_real = n
        self._sig_init()

        # -- the resident staged corpus (the streaming paths' view) ----------
        sdt = np_staging_dtype(self._staging)
        attrs = np.zeros((self.capacity_rows, na), sdt)
        attrs[:n] = corpus.data_attrs
        ids = np.full(self.capacity_rows, -1, np.int32)
        ids[:n] = np.arange(n, dtype=np.int32)
        with obs_span("serve.stage_resident", rows=self.capacity_rows,
                      na=na):
            self._d_attrs = stage_put(attrs, self._staging)
            self._d_labels = jax.device_put(
                self._host_labels[:self.capacity_rows])
            self._d_ids = jax.device_put(ids)

        # -- bucket registry + compile bookkeeping ---------------------------
        self._buckets: Dict[Tuple[int, int], _Bucket] = {}
        self._ingest_shapes: set = set()
        self.compile_count = 0
        self.cold_start_compile_ms: Optional[float] = None
        self.bucket_compile_ms: Dict[str, float] = {}
        # Wide-k multipass residency: the concatenated resident chunks
        # (passes 2+ re-sweep it whole) and the corpus max-sq-norm the
        # floor chain scales by — both invalidated/updated on ingest.
        self._mp_full = None
        self._dn_max_cache: Optional[float] = None
        # Cross-request gate state: per-chunk winner histogram + last
        # batch's gated-tile stats (pending device scalar, tile count).
        self._block_hits = np.zeros(max(self._ex_nchunks, 1), np.int64)
        self._pending_gate: Optional[Tuple] = None
        self.last_gated_fraction: Optional[float] = None
        # Pruned two-stage solve state (ops.summaries): host f64 block
        # summaries at extract-chunk granularity + their device-resident
        # conservative f32 copies, built with the chunks and rebuilt
        # per touched block on ingest (a stale summary is silent
        # unsoundness — the one failure mode the repair cannot catch).
        self._summ = None
        self._summ_dev = None
        self.summary_rebuilds = 0
        self.last_prune_fraction: Optional[float] = None
        reg = telemetry.registry()
        reg.gauge("serve.corpus_rows").set(n)
        reg.gauge("serve.capacity_rows").set(self.capacity_rows)
        reg.gauge("serve.gate.carry_enabled").set(int(self.gate_carry))

    # -- shape buckets --------------------------------------------------------

    @property
    def query_granule(self) -> int:
        if self._extract_ok:
            from dmlp_tpu.ops.pallas_extract import QUERY_TILE
            return QUERY_TILE
        return 8

    @property
    def max_k(self) -> int:
        """Largest per-query k this resident engine serves: the staging
        dtype's safe cap (bf16 margins blow past the resident layout
        beyond it) and the corpus capacity."""
        cap = self.capacity_rows
        if self._staging == "bfloat16":
            cap = min(cap, _BF16_AUTO_K_CAP)
        return cap

    def bucket_shape(self, nq: int, kmax: int) -> Tuple[int, int]:
        return (query_bucket(nq, self.query_granule), k_bucket(kmax))

    def _kcap_for(self, kb: int) -> int:
        return resolve_kcap(self.config, kb, self._stream_select,
                            self.capacity_rows, staging=self._staging,
                            precision=self._precision_plan)

    def bucket_plan(self, nq: int, kmax: int) -> Tuple[int, int, int]:
        """(qpad, k-bucket, kcap) for a request/batch shape — the ONE
        derivation of the candidate width the solve will allocate;
        admission pricing and the memwatch model read it from here so
        they cannot drift from what _build_bucket compiles."""
        qpad, kb = self.bucket_shape(nq, kmax)
        return qpad, kb, self._kcap_for(kb)

    def _build_bucket(self, qpad: int, kb: int) -> _Bucket:
        cfg = self.config
        kcap = self._kcap_for(kb)
        qb = min(1 << max(min(cfg.query_block, qpad).bit_length() - 1, 3),
                 qpad)
        nqb = qpad // qb
        path = "stream"
        if self._extract_ok and kcap <= 512:
            from dmlp_tpu.ops import pallas_fused
            kern, _ = pallas_fused.resolve_topk_kernel(
                qpad, self._ex_chunk_rows, self.num_attrs, kcap)
            if kern is not None:
                path = "extract"
                self._ensure_chunks()
        elif self._extract_ok and kcap > self._MP_KC \
                and -(-kcap // self._MP_KC) <= self._MP_MAX_PASSES:
            # Wide-k serving (ROADMAP item (d)): kcap past the kernel's
            # single-pass window routes through the multi-pass
            # extraction driver against the RESIDENT chunks.
            from dmlp_tpu.ops import pallas_fused
            kern, _ = pallas_fused.resolve_topk_kernel(
                qpad, self._ex_chunk_rows, self.num_attrs, self._MP_KC)
            if kern is not None:
                path = "multipass"
                self._ensure_chunks()
        entry = _Bucket(qpad, kb, kcap, path, qb, nqb)
        if path == "stream":
            self._compile_stream(entry)
        return entry

    def _compile_stream(self, entry: _Bucket) -> None:
        """AOT lower+compile the bucket's streaming program (the
        cold-start satellite: compilation happens ahead of the first
        request, not on it)."""
        cfg = self.config
        sh = jax.ShapeDtypeStruct
        na = self.num_attrs
        entry.stream = _topk_blocks.lower(
            sh((self.capacity_rows, na), self._dtype),
            sh((self.capacity_rows,), jnp.int32),
            sh((self.capacity_rows,), jnp.int32),
            sh((entry.nqb, entry.qb, na), self._dtype),
            k=entry.kcap, data_block=self._data_block,
            select=self._stream_select,
            use_pallas=cfg.use_pallas).compile()
        try:
            # Schedule identity for the compile-once contract: the
            # smoke asserts the per-bucket fingerprint (not just
            # compile_count) is unchanged between ready and drain — a
            # recompile that lands on a DIFFERENT program can't hide
            # behind a coincidentally flat counter.
            from dmlp_tpu.obs import hlo as obs_hlo
            rep = obs_hlo.report_for(entry.stream,
                                     label=f"serve.{entry.key}")
            entry.hlo = {"fingerprint": rep.fingerprint,
                         "collective_bytes": sum(
                             t["bytes_moved"]
                             for t in rep.totals.values())}
        except Exception:  # check: no-retry — obs never fails serving
            entry.hlo = None

    # -- resident chunk staging (extract path) --------------------------------

    def _ensure_chunks(self) -> None:
        if self._chunks is not None or not self._extract_ok:
            return
        sdt = np_staging_dtype(self._staging)
        cr = self._ex_chunk_rows
        chunks = []
        with obs_span("serve.stage_chunks", chunks=self._ex_nchunks,
                      chunk_rows=cr):
            for c in range(self._ex_nchunks):
                a = np.zeros((cr, self.num_attrs), sdt)
                lo = c * cr
                hi = min(lo + cr, self.n_real)
                if hi > lo:
                    a[:hi - lo] = self._host_attrs[lo:hi]
                chunks.append(stage_put(a, self._staging))
        self._chunks = chunks
        self._build_summaries()

    # -- resident block summaries (pruned two-stage solve, stage 0) -----------

    def _chunk_span(self, c: int) -> Tuple[int, int]:
        cr = self._ex_chunk_rows
        return c * cr, min(c * cr + cr, self.n_real)

    def _build_summaries(self) -> None:
        """Stage 0 at ingest granularity: one summary block per
        resident extract chunk, host f64 + device-resident f32 copies
        (tiny: O(blocks * a))."""
        from dmlp_tpu.ops import summaries as osum
        if not self._extract_ok or self._ex_nchunks <= 1 \
                or not osum.prune_enabled():
            return
        with obs_span("serve.summary_build", blocks=self._ex_nchunks):
            self._summ = osum.build_summaries(
                self._host_attrs,
                [self._chunk_span(c) for c in range(self._ex_nchunks)])
            self._stage_summaries()
        telemetry.registry().gauge("prune.summary_blocks").set(
            self._ex_nchunks)

    def _stage_summaries(self) -> None:
        from dmlp_tpu.engine.finalize import (EPS_CANCEL_COEF, LOWP_COEF,
                                              EPS_REL_BF16, EPS_REL_F32)
        from dmlp_tpu.ops import summaries as osum
        dev = osum.stage_summaries(self._summ)
        rel = EPS_REL_BF16 if self._staging == "bfloat16" else EPS_REL_F32
        # score_blocks widens thresholds by eps_rel*sqrt(thr*scale) +
        # eps_cancel*scale with scale = qn + dn_max; lowp_eps is
        # LOWP_COEF*scale, so folding the plan's coefficient into the
        # staged eps_cancel scalar composes the bf16 first-pass bound
        # additively — exactly prune_mask's precision widening. Plan-
        # level (not per-rung): on the f32 rungs the extra slack only
        # keeps a few more blocks, never drops one.
        dev["eps_rel"] = jax.device_put(np.float32(rel))
        dev["eps_cancel"] = jax.device_put(
            np.float32(EPS_CANCEL_COEF * (self.num_attrs + 2)
                       + LOWP_COEF[self._precision_plan]))
        self._summ_dev = dev

    def _rebuild_summary_blocks(self, blocks) -> None:
        """Ingest invalidation: rebuild EXACTLY the touched blocks'
        summaries from their current host rows, then restage the
        device copies — the incremental counterpart of
        _restage_chunk, counted so tests can assert the invalidation
        actually happened."""
        from dmlp_tpu.ops import summaries as osum
        if self._summ is None:
            return
        blocks = list(blocks)
        for c in blocks:
            lo, hi = self._chunk_span(c)
            osum.update_block(self._summ, c, self._host_attrs[lo:hi],
                              lo_hi=(lo, hi))
        self._stage_summaries()
        self.summary_rebuilds += len(blocks)
        telemetry.registry().counter("prune.summary_rebuilds").inc(
            len(blocks))

    def _restage_chunk(self, c: int) -> None:
        sdt = np_staging_dtype(self._staging)
        cr = self._ex_chunk_rows
        lo = c * cr
        hi = min(lo + cr, self.n_real)
        a = np.zeros((cr, self.num_attrs), sdt)
        if hi > lo:
            a[:hi - lo] = self._host_attrs[lo:hi]
        self._chunks[c] = stage_put(a, self._staging)

    # -- incremental ingestion ------------------------------------------------

    def ingest(self, labels, attrs, start: Optional[int] = None) -> int:
        """Write rows into the resident corpus behind the row-count
        mask; returns the new row count. ``start=None`` appends;
        ``start <= n_real`` writes at that global row position — an
        IDEMPOTENT row-write keyed by global row id (re-delivering the
        same rows at the same positions changes nothing, including the
        corpus signature), which is what makes the fleet's
        consistency-repair re-ingest safe to race a normal fan-out.
        The solve programs' shapes are untouched (no recompilation);
        the fixed-shape row update itself compiles once per
        power-of-two row-count bucket."""
        labels = np.asarray(labels, np.int32).reshape(-1)
        attrs = np.asarray(attrs, np.float64)
        if attrs.ndim != 2 or attrs.shape[1] != self.num_attrs:
            raise ValueError(
                f"ingest rows must be (m, {self.num_attrs}), "
                f"got {attrs.shape}")
        m = attrs.shape[0]
        if m != labels.shape[0]:
            raise ValueError("labels/attrs row-count mismatch")
        if m == 0:
            return self.n_real
        at = self.n_real if start is None else int(start)
        if at < 0 or at > self.n_real:
            raise ValueError(
                f"ingest start {at} beyond resident rows "
                f"{self.n_real} (row-writes may overwrite or append, "
                "never leave gaps)")
        end = at + m
        new_n = max(self.n_real, end)
        if end > self.capacity_rows:
            raise CapacityError(
                f"ingest of {m} rows at {at} exceeds capacity "
                f"{self.capacity_rows} (resident: {self.n_real})")
        with obs_span("serve.ingest", rows=m, corpus_rows=new_n):
            self._host_attrs[at:end] = attrs
            self._host_labels[at:end] = labels
            self.n_real = new_n
            # Bucketed fixed-shape device update, rebuilt from host
            # state so the pad region rewrites what is already there.
            mpad = min(shape_bucket(m), self.capacity_rows - at)
            mpad = max(mpad, m)
            if (mpad, "u") not in self._ingest_shapes:
                self._ingest_shapes.add((mpad, "u"))
                telemetry.registry().counter(
                    "serve.ingest_compiles").inc(label=str(mpad))
            sdt = np_staging_dtype(self._staging)
            blk = np.ascontiguousarray(
                self._host_attrs[at:at + mpad], sdt)
            rng = np.arange(at, at + mpad, dtype=np.int32)
            blk_ids = np.where(rng < new_n, rng, -1).astype(np.int32)
            blk_labels = self._host_labels[at:at + mpad]
            s = jax.device_put(np.int32(at))
            self._d_attrs = _update_rows_2d(
                self._d_attrs, stage_put(blk, self._staging), s)
            self._d_labels = _update_rows_1d(
                self._d_labels, jax.device_put(blk_labels), s)
            self._d_ids = _update_rows_1d(
                self._d_ids, jax.device_put(blk_ids), s)
            if self._chunks is not None:
                cr = self._ex_chunk_rows
                touched = range(at // cr, -(-end // cr))
                for c in touched:
                    self._restage_chunk(c)
                # The summaries of exactly the touched blocks must
                # rebuild with the rows — a stale summary could keep a
                # block pruned whose NEW rows belong in a top-k.
                self._rebuild_summary_blocks(touched)
                # Wide-k residency: the cached chunk concatenation is
                # stale the moment a chunk restages (same shapes, so
                # the rebuild never recompiles).
                self._mp_full = None
            # Overwrites can only RAISE the cached max-sq-norm (the
            # old row's norm may linger) — conservative: a too-large
            # dn_max only widens the boundary-repair eps, never
            # narrows it, so exactness is unaffected.
            self._note_ingested_norms(attrs)
            self._sig_update(at, end)
        reg = telemetry.registry()
        reg.counter("serve.ingested_rows").inc(m)
        reg.gauge("serve.corpus_rows").set(new_n)
        return new_n

    # -- resident solves ------------------------------------------------------

    def _batch_input(self, query_attrs: np.ndarray,
                     ks: np.ndarray) -> KNNInput:
        """A micro-batch as a KNNInput over the resident corpus (host
        views feed the float64 finalize/repair exactly as a solo solve
        over the same corpus would)."""
        nq = len(ks)
        return KNNInput(
            Params(self.n_real, nq, self.num_attrs),
            self._host_labels[:self.n_real],
            self._host_attrs[:self.n_real],
            np.asarray(ks, np.int32),
            np.asarray(query_attrs, np.float64))

    def _solve_resident_stream(self, inp: KNNInput,
                               entry: _Bucket) -> Tuple[TopK, int]:
        if entry.stream is None:
            # An extract-path bucket degraded to streaming: build the
            # fallback program once (counted honestly as a compile).
            t0 = time.perf_counter()
            self._compile_stream(entry)
            self.compile_count += 1
            telemetry.registry().counter("serve.bucket_compiles").inc(
                label=entry.key + "_stream_fallback")
            self.bucket_compile_ms[entry.key + "_stream_fallback"] = \
                round((time.perf_counter() - t0) * 1e3, 3)
        nq = inp.params.num_queries
        na = self.num_attrs
        q = np.zeros((entry.qpad, na), np.float32)
        q[:nq] = inp.query_attrs
        q_blocks = stage_put(q.reshape(entry.nqb, entry.qb, na),
                             self._staging)
        self._last_select = self._stream_select
        with obs_span("serve.solve_stream", qpad=entry.qpad,
                      kcap=entry.kcap, **self._rid_args()) as sp:
            out: TopK = entry.stream(self._d_attrs, self._d_labels,
                                     self._d_ids, q_blocks)
            sp.fence(out.dists)
        # The AOT streaming program scans the whole resident buffer by
        # construction (static shapes): a dense scan, recorded as such.
        from dmlp_tpu.ops.summaries import note_scan
        dense = self.n_real * na * self._staging_itemsize()
        note_scan(self, scanned_bytes=dense, dense_bytes=dense,
                  blocks_total=1, blocks_pruned=0)
        return TopK(out.dists.reshape(entry.qpad, -1),
                    out.labels.reshape(entry.qpad, -1),
                    out.ids.reshape(entry.qpad, -1)), entry.qpad

    def _prune_survivors(self, inp: KNNInput, entry: _Bucket, q_dev):
        """Stage 1 per micro-batch: score the RESIDENT summaries on
        device (ops.summaries.score_blocks — compiled once per bucket
        shape) and read back the tiny (blocks,) survivor mask. Active
        on the ladder's top ``prune`` rung in exact mode only; returns
        (mask, stats) or (None, None) for a dense fold."""
        from dmlp_tpu.obs import counters as obs_counters
        from dmlp_tpu.ops import summaries as osum
        if (self._summ_dev is None
                or self._degrade_rung not in ("lowp", "prune")
                or not self.config.exact or not osum.prune_enabled()):
            return None, None
        nq = inp.params.num_queries
        ks = np.ones(entry.qpad, np.int32)
        ks[:nq] = inp.ks
        qvalid = np.zeros(entry.qpad, bool)
        qvalid[:nq] = True
        sd = self._summ_dev
        args = (q_dev, jax.device_put(qvalid), jax.device_put(ks),
                sd["counts"], sd["nmin"], sd["nmax"], sd["lo"],
                sd["hi"], sd["dn_max"], sd["eps_rel"], sd["eps_cancel"])
        with obs_span("serve.prune_score", blocks=self._ex_nchunks,
                      qpad=entry.qpad, **self._rid_args()):
            obs_counters.record_dispatch(osum.score_blocks, args,
                                         site="serve.prune_score")
            mask = osum.score_blocks(*args)
            # Deliberate tiny fence: the (blocks,) mask decides WHICH
            # resident chunks the folds dispatch over, so the host must
            # read it before enqueueing them — O(blocks) bytes, priced
            # by the analytic score model, nothing like a result fetch.
            keep = np.asarray(
                jax.device_get(mask))  # check: allow-host-sync
        total = int(np.count_nonzero(
            self._summ.counts[:self._ex_nchunks] > 0))
        pruned = total - int(np.count_nonzero(keep))
        if not keep.any():
            return None, None   # belt: score_blocks keeps >= 1 block
        return keep, {"blocks_total": total, "blocks_pruned": pruned}

    def _solve_resident_extract(self, inp: KNNInput, entry: _Bucket
                                ) -> Optional[Tuple[TopK, int]]:
        from dmlp_tpu.ops import pallas_fused
        from dmlp_tpu.ops.summaries import note_scan
        kern, impl = pallas_fused.resolve_topk_kernel(
            entry.qpad, self._ex_chunk_rows, self.num_attrs, entry.kcap,
            rung=self._degrade_rung)
        if kern is None:
            return None
        nq = inp.params.num_queries
        na = self.num_attrs
        prec = active_precision(self)  # plan-clamped; outside the jits
        q = np.zeros((entry.qpad, na), np.float32)
        q[:nq] = inp.query_attrs
        q_dev = stage_put(q, self._staging)
        cr = self._ex_chunk_rows
        order = self._chunk_order()
        survivors, prune_stats = self._prune_survivors(inp, entry, q_dev)
        if survivors is not None:
            # Survivor ∩ hot-first order: the winner-histogram sort
            # stays the fold order, pruned chunks simply drop out.
            order = [c for c in order if survivors[c]]
        od = oi = None
        gz = None
        ntiles = 0
        scanned = 0
        item = self._staging_itemsize()
        throttle = ChunkThrottle()
        self._last_select = "extract"
        self.last_extract_impl = impl
        with obs_span("serve.solve_extract", qpad=entry.qpad,
                      kcap=entry.kcap, impl=impl,
                      carry=self.gate_carry, scheduled=len(order),
                      **self._rid_args()):
            for c in order:
                lo = c * cr
                nr = min(self.n_real - lo, cr)
                if nr <= 0:
                    continue
                od, oi, iters = kern(q_dev, self._chunks[c], od, oi,
                                     n_real=nr, id_base=lo, kc=entry.kcap,
                                     interpret=self._interpret,
                                     precision=prec)
                scanned += nr * na * item
                z = jnp.sum(iters == 0)
                gz = z if gz is None else gz + z
                ntiles += int(np.prod(iters.shape))
                throttle.tick(od)
                telemetry.sample_memory_now()
        if od is None:
            # Every scheduled chunk was empty (cannot happen with a
            # sound mask, the belt above): fall back to a dense fold.
            return None
        self._pending_gate = (gz, ntiles)
        note_scan(self, scanned_bytes=scanned,
                  dense_bytes=self.n_real * na * item,
                  blocks_total=(prune_stats or {}).get(
                      "blocks_total", -(-self.n_real // cr)),
                  blocks_pruned=(prune_stats or {}).get(
                      "blocks_pruned", 0))
        self.last_prune_fraction = self.last_prune["pruned_fraction"]
        top = _extract_finalize(od, oi, self._d_labels, k=entry.kcap)
        return top, entry.qpad

    # -- wide-k multipass serving (ROADMAP item (d)) --------------------------

    def _resident_full(self):
        """The resident chunks as ONE device array for the multipass
        resident sweeps — concatenated lazily, cached across requests,
        invalidated on ingest. Same shapes every rebuild, so the concat
        compiles once (covered by the wide bucket's warm-up)."""
        if self._mp_full is None:
            self._mp_full = self._chunks[0] if self._ex_nchunks == 1 \
                else jnp.concatenate(self._chunks, axis=0)
        return self._mp_full

    def _solve_resident_multipass(self, inp: KNNInput, entry: _Bucket
                                  ) -> Optional[Tuple[TopK, int]]:
        """k past the kernel's single-pass window, served on the
        existing multi-pass extraction driver (engine.single
        ._solve_extract_multipass) against the RESIDENT chunks: pass 1
        folds the resident chunk buffers (no staging), passes 2+
        re-sweep the cached resident concatenation with the on-device
        floor chain (``_mp_floor``), and ``_mp_merge`` dedups and
        composite-sorts to the bucket width. The driver's two loss
        modes (tie-plateau stall / eps-window shortfall) set
        ``_mp_hazard`` exactly like the batch engine, and run()'s
        boundary repair makes them exact — byte-identical to the solo
        multipass solve and the golden oracle."""
        from dmlp_tpu.engine.single import _mp_floor, _mp_merge
        from dmlp_tpu.ops import pallas_fused
        from dmlp_tpu.ops.summaries import note_scan
        kc = self._MP_KC
        kcap = entry.kcap
        if self._chunks is None or -(-kcap // kc) > self._MP_MAX_PASSES:
            return None
        kern, impl = pallas_fused.resolve_topk_kernel(
            entry.qpad, self._ex_chunk_rows, self.num_attrs, kc,
            rung=self._degrade_rung)
        if kern is None:
            return None
        full_rows = self._ex_nchunks * self._ex_chunk_rows
        kern_full, _impl_full = pallas_fused.resolve_topk_kernel(
            entry.qpad, full_rows, self.num_attrs, kc,
            rung=self._degrade_rung)
        if kern_full is None:
            return None
        npasses = -(-kcap // kc)
        nq = inp.params.num_queries
        na = self.num_attrs
        n = self.n_real
        cr = self._ex_chunk_rows
        prec = active_precision(self)  # plan-clamped; outside the jits
        q = np.zeros((entry.qpad, na), np.float32)
        q[:nq] = inp.query_attrs
        q_dev = stage_put(q, self._staging)
        self._last_select = "extract"
        self.last_extract_impl = impl
        od = oi = None
        throttle = ChunkThrottle()
        with obs_span("serve.solve_multipass", qpad=entry.qpad,
                      kcap=kcap, passes=npasses, impl=impl,
                      **self._rid_args()):
            for c in range(self._ex_nchunks):
                lo = c * cr
                nr = min(n - lo, cr)
                if nr <= 0:
                    continue
                od, oi, _its = kern(q_dev, self._chunks[c], od, oi,
                                    n_real=nr, id_base=lo, kc=kc,
                                    interpret=self._interpret,
                                    precision=prec)
                throttle.tick(od)
                telemetry.sample_memory_now()
            if od is None:
                return None
            ods, ois = [od], [oi]
            qn_host = np.zeros(entry.qpad, np.float64)
            qn_host[:nq] = np.einsum("qa,qa->q", inp.query_attrs,
                                     inp.query_attrs)
            qn_dev = jax.device_put(np.asarray(qn_host, np.float32))
            dn_dev = jax.device_put(np.float32(self._dn_max()))
            d_full = self._resident_full()
            fds = []
            for _p in range(1, npasses):
                floor_dev, fd = _mp_floor(ods[-1], qn_dev, dn_dev,
                                          staging=self._staging, na=na,
                                          precision=prec)
                fds.append(fd)
                od, oi, _its = kern_full(q_dev, d_full, n_real=n,
                                         id_base=0, kc=kc,
                                         interpret=self._interpret,
                                         floor=floor_dev,
                                         precision=prec)
                throttle.tick(od)
                ods.append(od)
                ois.append(oi)
            fds.append(_mp_floor(ods[-1], qn_dev, dn_dev,
                                 staging=self._staging, na=na,
                                 precision=prec)[1])
            top, valid = _mp_merge(jnp.concatenate(ods, axis=1),
                                   jnp.concatenate(ois, axis=1),
                                   self._d_labels, kcap=kcap)
        self.last_mp_passes = len(ods)
        # The multipass plan re-sweeps the whole resident corpus: a
        # dense scan by design, staged bytes counted once.
        dense = n * na * self._staging_itemsize()
        note_scan(self, scanned_bytes=dense, dense_bytes=dense,
                  blocks_total=self._ex_nchunks, blocks_pruned=0)
        # One fence: fd chain (stall check) + final valid counts
        # (shortfall check) — run()'s repair makes both exact.
        fetched = resilient_get([valid] + fds)
        valid_h, fd_h = fetched[0], fetched[1:]
        stalled = np.zeros(entry.qpad, bool)
        for prev, cur in zip(fd_h, fd_h[1:]):
            stalled |= np.isfinite(cur) & (cur <= prev)
        needed = np.minimum(inp.ks.astype(np.int64), n)
        shortfall = np.asarray(valid_h)[:nq] < needed
        self._mp_hazard = stalled[:nq] | shortfall
        telemetry.registry().counter("serve.multipass_batches").inc()
        return top, entry.qpad

    def _chunk_order(self) -> List[int]:
        """Fold order over the resident chunks: hottest (most past
        winners) first when gate carry-over is on, natural otherwise.
        Stable sort: cold chunks keep their natural relative order."""
        with obs_span("serve.fold_schedule", chunks=self._ex_nchunks,
                      carry=self.gate_carry, **self._rid_args()):
            idx = range(self._ex_nchunks)
            if not self.gate_carry:
                return list(idx)
            return list(np.argsort(-self._block_hits[:self._ex_nchunks],
                                   kind="stable"))

    # -- SingleChipEngine seam overrides --------------------------------------

    def _solve(self, inp: KNNInput) -> Tuple[TopK, int]:
        self.last_phase_ms = {}
        self._pending_iters = []
        self.last_extract_impl = None
        self.last_prune = None
        if inp.params.num_data != self.n_real:
            raise ValueError(
                f"resident solve got a foreign corpus "
                f"({inp.params.num_data} rows, resident {self.n_real}) — "
                "build micro-batches with _batch_input/solve_batch")
        nq = inp.params.num_queries
        kmax = int(inp.ks.max()) if nq else 1
        entry = self._bucket_entry(nq, kmax)
        if entry.path == "extract" and self._degrade_rung != "streaming":
            out = self._solve_resident_extract(inp, entry)
            if out is not None:
                return out
        if entry.path == "multipass" and self._degrade_rung != "streaming":
            out = self._solve_resident_multipass(inp, entry)
            if out is not None:
                return out
        return self._solve_resident_stream(inp, entry)

    def _solve_segments(self, inp: KNNInput, allow_multipass: bool = True):
        # No hetk routing on the resident paths: one segment per
        # micro-batch keeps the per-request slicing trivial. Wide-k
        # buckets route through _solve_resident_multipass inside
        # _solve (which sets _mp_hazard for run()'s exact repair).
        self.last_hetk = None
        self._mp_hazard = None
        self.last_mp_passes = 0
        top, qpad = self._solve(inp)
        return [(top, qpad, None, self._last_select)]

    def run(self, inp: KNNInput) -> List[QueryResult]:
        # No staging_for_k swap (the parent flips bf16->f32 staging for
        # wide k, which would mismatch the resident buffers): max_k
        # already refuses the shapes that swap existed for.
        kmax = int(inp.ks.max()) if inp.params.num_queries else 0
        if kmax > self.max_k:
            raise RequestShapeError(
                f"k={kmax} beyond the serving cap {self.max_k}")
        return rs_degrade.run_ladder(self, inp, self._run)

    # -- the serving entry ----------------------------------------------------

    def solve_batch(self, query_attrs, ks) -> List[QueryResult]:
        """One coalesced micro-batch end to end: pad/bucket, solve on
        the compiled bucket program, float64-finalize + repair, update
        the cross-request gate state. Results carry query ids
        0..nq-1 in batch order — the batcher slices per request."""
        inp = self._batch_input(np.asarray(query_attrs, np.float64),
                                np.asarray(ks, np.int32))
        self._pending_gate = None
        results = self.run(inp)
        self._after_batch(results)
        return results

    def _after_batch(self, results: List[QueryResult]) -> None:
        if self._pending_gate is not None:
            gz, ntiles = self._pending_gate
            self._pending_gate = None
            try:
                gated = int(jax.device_get(gz))  # check: allow-host-sync
                frac = gated / max(ntiles, 1)
                self.last_gated_fraction = frac
                reg = telemetry.registry()
                reg.gauge("serve.gate.gated_fraction").set(round(frac, 6))
                reg.counter("serve.gate.tiles_total").inc(ntiles)
                reg.counter("serve.gate.tiles_gated").inc(gated)
            except Exception:  # check: no-retry — stats never fail a batch
                pass
        if self.gate_carry and self._ex_nchunks and results:
            ids = np.concatenate(
                [np.asarray(r.neighbor_ids, np.int64) for r in results])
            ids = ids[ids >= 0]
            if ids.size:
                hits = np.bincount(ids // self._ex_chunk_rows,
                                   minlength=self._ex_nchunks)
                self._block_hits[:len(hits)] += hits

    # -- memory-model hooks (ResidentServingCore contract) --------------------

    def mem_model(self, nq: int = 0, kmax: int = 0):
        """The analytic per-device model at this engine's OWN
        bucket_plan (the one kcap derivation — no drift between model
        and solve); batch terms included iff ``nq > 0``."""
        from dmlp_tpu.obs import memwatch
        qpad = kcap = 0
        if nq > 0:
            qpad, _kb, kcap = self.bucket_plan(nq, max(kmax, 1))
        return memwatch.serve_engine_model(
            self.capacity_rows, self.num_attrs, staging=self._staging,
            qpad=qpad, kcap=kcap,
            extract_chunks=(self._ex_nchunks if self._chunks else 0),
            chunk_rows=self._ex_chunk_rows,
            summary_blocks=(self._ex_nchunks
                            if self._summ_dev is not None else 0),
            multipass_rows=(self._ex_nchunks * self._ex_chunk_rows
                            if self._mp_full is not None else 0))

    def batch_model_bytes(self, nq: int, kmax: int) -> int:
        terms = self.mem_model(nq, kmax)["terms"]
        return int(terms["query_blocks"] + terms["topk_carries"])

    def resident_state_key(self):
        # The floor moves when the extract chunks stage and when the
        # wide-k multipass concat materializes (a SECOND corpus copy).
        return (self._chunks is not None, self._mp_full is not None)

    # -- introspection --------------------------------------------------------

    def bucket_stats(self) -> Dict[str, object]:
        # Snapshot the bucket table FIRST: handler threads call this
        # through daemon.stats() while the batcher thread may be
        # inserting a new bucket — iterating the live dict twice could
        # raise "dict changed size" or return paths/buckets from two
        # different states. list() of a dict is a single atomic read
        # under the GIL; the engine stays single-writer.
        entries = list(self._buckets.values())
        # Same single-read discipline for last_prune: the batcher
        # thread resets it to None at the start of every solve, so an
        # isinstance check followed by a second attribute read could
        # straddle that write and dict(None)-crash a stats handler.
        lp = self.last_prune
        return {
            "buckets": sorted(e.key for e in entries),
            "paths": {e.key: e.path for e in entries},
            # bucket key -> compiled-stream HLO fingerprint (obs.hlo;
            # only stream-path buckets have one): the schedule-identity
            # map the compile-once assertions compare.
            "hlo_schedule": {e.key: e.hlo["fingerprint"]
                             for e in entries if e.hlo},
            "compile_count": self.compile_count,
            "bucket_compile_ms": dict(self.bucket_compile_ms),
            "cold_start_compile_ms": self.cold_start_compile_ms,
            "corpus_rows": self.n_real,
            "capacity_rows": self.capacity_rows,
            "gate_carry": self.gate_carry,
            "last_gated_fraction": self.last_gated_fraction,
            "extract_chunks": self._ex_nchunks if self._chunks else 0,
            "summary_blocks": self._ex_nchunks if self._summ else 0,
            "summary_rebuilds": self.summary_rebuilds,
            "last_prune_fraction": self.last_prune_fraction,
            "last_prune": dict(lp) if isinstance(lp, dict) else None,
            "precision_plan": self._precision_plan,
            "last_precision": dict(self.last_precision)
            if isinstance(self.last_precision, dict) else None,
        }


# Hoisted to utils.compile_cache (the batch CLI, train loop, and fleet
# spawn paths need the same opt-in); re-exported here so serve embedders
# and `serve/__main__.py` keep importing it from this module unchanged.
from dmlp_tpu.utils.compile_cache import (  # noqa: E402,F401
    enable_persistent_compile_cache)
