"""Online serving layer — the millions-of-users path (ROADMAP).

Everything else in the repo is batch: one stdin parse, one solve, exit —
every request pays parse + staging + jit warm-up. This package is the
persistent daemon (``python -m dmlp_tpu.serve``) that pays those costs
ONCE and then serves query streams at throughput:

- :mod:`dmlp_tpu.serve.engine` — :class:`ResidentEngine`: the corpus is
  parsed and staged once into a capacity-padded resident device buffer
  behind a row-count mask (incremental ingestion appends rows with NO
  recompilation), and each engine path is compiled once per
  power-of-two (qpad, k) shape bucket (``tune.cache.shape_bucket`` is
  the template) — ahead of the first request when warmed, with the
  compile counter proving steady-state serving never recompiles.
- :mod:`dmlp_tpu.serve.batching` — continuous micro-batching: an
  admission queue coalesces whatever is queued each tick into one
  padded micro-batch so the MXU sees full tiles; per-request results
  are sliced back out bit-identically to the solo solve.
- :mod:`dmlp_tpu.serve.admission` — admission control reads memory
  headroom from the analytic peak-HBM model (obs.memwatch) vs the
  telemetry sampler's live watermark and sheds load BEFORE the
  allocator OOMs (the resilience ladder stays a backstop, not the
  first responder).
- :mod:`dmlp_tpu.serve.daemon` / :mod:`~dmlp_tpu.serve.protocol` — the
  line-JSON TCP daemon with live telemetry (``--telemetry-port`` is
  the scrape surface), periodic ledger-ingestible serve RunRecords,
  and a graceful SIGTERM drain (in-flight micro-batches finish, the
  final snapshot flushes, no flight-recorder dump on an orderly exit).
- :mod:`dmlp_tpu.serve.client` — the replay client + recorded-trace
  format the bench harness and ``make serve-smoke`` drive.

Responses are byte-identical to the float64 golden oracle on every
path: the resident solves reuse the engines' candidates -> host-f64
finalize -> boundary-hazard repair pipeline unchanged.
"""

# Race-sanitizer hook BEFORE the imports below pull in batching/
# admission/engine (and transitively obs.telemetry + resilience.stats),
# whose import creates module-level locks: `python -m dmlp_tpu.serve`
# executes this __init__ first, so this is the earliest point where
# DMLP_TPU_RACECHECK=1 can wrap the lock factories and have EVERY
# serving-surface lock tracked (telemetry's import-time globals are
# retrofitted by install either way).
import os as _os

if _os.environ.get("DMLP_TPU_RACECHECK") == "1":
    from dmlp_tpu.check import racecheck as _racecheck

    _racecheck.install()

from dmlp_tpu.serve.admission import AdmissionController  # noqa: F401
from dmlp_tpu.serve.batching import MicroBatcher, Request  # noqa: F401
from dmlp_tpu.serve.engine import (CapacityError, ResidentEngine,  # noqa: F401
                                   k_bucket, query_bucket)
