"""Serving fleet — both axes of scale on top of the serving layer.

The PR 10 daemon is a single-chip server: serveable corpus is capped by
one chip's HBM and throughput by one replica. This package is the
fleet layer that removes both caps, plus the honest-latency harness
the ROADMAP calls for:

- :mod:`dmlp_tpu.fleet.mesh_engine` — :class:`MeshResidentEngine`:
  the resident serving engine over the 2D mesh. The corpus is staged
  ONCE into per-shard capacity-padded resident chunk buffers
  (``P("data", None)``), per-(shard, chunk) block summaries stay
  resident for the pruned two-stage solve, and every micro-batch runs
  the mesh engines' chunk-fold programs with the existing
  allgather/ring candidate merge as the epilogue — serveable corpus
  size passes one chip's HBM while every response stays byte-identical
  to the solo solve and the golden oracle. Ingest routes rows to their
  owning shard's buffers with zero solve recompilation (chunk arrays
  and ``[n, toff, shard_rows]`` scalars are data inputs, never shapes).
- :mod:`dmlp_tpu.fleet.router` — the thin line-JSON TCP front end
  fanning requests across N daemon replicas: per-replica health/drain
  awareness, bounded retry-on-replica-failure via the resilience
  classification (queries only — they are idempotent reads; admission
  sheds propagate as explicit rejections, never retries), ingest
  fan-out to every replica, and one aggregated fleet OpenMetrics view
  over the per-replica telemetry scrapes.
- :mod:`dmlp_tpu.fleet.scrape` — the OpenMetrics merge: counters sum,
  log-bucket histograms merge bucket-wise, gauges keep per-replica
  labels; the merged exposition passes ``validate_openmetrics``.
- :mod:`dmlp_tpu.fleet.loadgen` — the open-loop SLO harness: paced
  replay fires requests ON SCHEDULE regardless of completions (queue
  delay lands in the measured latency), swept over offered-load
  multipliers into ledger-gated ``fleet/<level>/...`` RunRecords — the
  p99-under-offered-load curve, not just closed-loop throughput.
- :mod:`dmlp_tpu.fleet.autoscale` — the self-healing half's lifecycle
  owner: a router-side supervisor that spawns/retires replica daemons
  against the probed load, detects crashed/hung replicas
  (process-exit + probe-dead deadline), relaunches within a bounded
  budget, and degrades to a smaller fleet when it runs out.
- :mod:`dmlp_tpu.fleet.reshard` — the staged shard re-split: when
  ingest approaches a replica's capacity-padded buffer limit, a
  grown-layout replacement is spawned, the corpus replayed into it
  checksum-verified, the routing table swapped, and the old replica
  drained — growth past the fixed resident layout with zero dropped
  or wrong responses.
- :mod:`dmlp_tpu.fleet.consistency` — checksum-driven repair: rolling
  per-engine corpus signatures (layout-independent), divergence
  diagnosis across replicas, and targeted delta re-ingest via
  idempotent global-row-id-keyed writes; unrepairable divergence
  escalates to quarantine.

``python -m dmlp_tpu.fleet`` runs the router — static over existing
replicas, or SUPERVISED (``--spawn-corpus``) where it owns the whole
replica lifecycle (see :mod:`dmlp_tpu.fleet.__main__`); ``make
fleet-smoke`` proves the serving stack end to end against the golden
oracle and ``make fleet-chaos-smoke`` proves the self-healing under
seeded kills, injected ingest divergence, and a forced re-split.
"""

# Same early racecheck hook as dmlp_tpu.serve: `python -m dmlp_tpu.fleet`
# executes this __init__ before the router/engine imports create any
# serving locks, so DMLP_TPU_RACECHECK=1 tracks the full fleet surface.
from dmlp_tpu.check import racecheck as _racecheck

_racecheck.install_from_env()
