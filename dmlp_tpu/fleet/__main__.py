"""``python -m dmlp_tpu.fleet`` — the fleet front-end router CLI.

Two modes:

**Static** (PR 14): route over an existing replica set::

    python -m dmlp_tpu.fleet --replicas H:P,H:P[,...]
        [--scrape-ports Q,Q,...] [--port 0] [--ready-file PATH]
        [--telemetry-port PORT] [--record FILE]
        [--health-interval-s S] [--request-timeout-s S]
        [--revive-probes N] [--repair on|off]

**Supervised** (the self-healing fleet): the router SPAWNS and owns its
replicas — crash detection with bounded relaunch, load-driven
auto-scaling between ``--min-replicas`` and ``--max-replicas``, and
the staged shard re-split when ingest approaches a replica's capacity
buffer::

    python -m dmlp_tpu.fleet --spawn-corpus FILE
        [--spawn-replicas N] [--max-replicas N] [--out-dir DIR]
        [--spawn-warm NQxK,...] [--spawn-batch-cap N]
        [--spawn-flags "--mesh 2x1 ..."] [--spawn-capacity ROWS]
        [--relaunch-budget N] [--reshard-threshold F]
        [--scale-high F] [--scale-low F] [--poll-s S] ...

Either way the router fans the daemon wire protocol (queries
load-balanced with bounded retry-on-replica-failure and revive
hysteresis, ingest fanned out to every replica with checksum-driven
consistency repair, stats aggregated) across the replicas;
``--telemetry-port`` serves the merged fleet OpenMetrics view with
per-replica scrape-freshness gauges. Prints ``dmlp_tpu.fleet: ready
port=P replicas=N`` on stderr (and writes ``--ready-file``), then
routes until SIGTERM or an in-band ``drain`` op — which stops the
supervisor FIRST (no relaunch storm), propagates the drain to every
replica, finishes in-flight relays, appends the final fleet RunRecord,
and exits 0 only when every managed replica also exited 0.
"""

from __future__ import annotations

import argparse
import json
import os
import shlex
import signal
import sys
from typing import List, Optional, Sequence, Tuple


def _parse_replicas(spec: str) -> List[Tuple[str, int]]:
    out: List[Tuple[str, int]] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        try:
            host, port = part.rsplit(":", 1)
            out.append((host or "127.0.0.1", int(port)))
        except ValueError:
            raise SystemExit(
                f"--replicas entries are HOST:PORT, got {part!r}")
    if not out:
        raise SystemExit("--replicas lists no endpoints")
    return out


def _parse_ports(spec: Optional[str], n: int) -> List[Optional[int]]:
    if not spec:
        return [None] * n
    out: List[Optional[int]] = []
    for part in spec.split(","):
        part = part.strip()
        out.append(int(part) if part and part != "-" else None)
    if len(out) != n:
        raise SystemExit("--scrape-ports needs one entry per replica "
                         "('-' for none)")
    return out


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="dmlp_tpu.fleet",
                                description=__doc__)
    p.add_argument("--replicas", default=None, metavar="H:P,H:P",
                   help="existing daemon replica endpoints (static "
                        "mode; omit with --spawn-corpus)")
    p.add_argument("--scrape-ports", default=None, metavar="Q,Q",
                   help="per-replica telemetry ports for the "
                        "aggregated fleet scrape ('-' skips one)")
    p.add_argument("--port", type=int, default=0,
                   help="front-end TCP port (0 = ephemeral)")
    p.add_argument("--ready-file", metavar="PATH", default=None)
    p.add_argument("--telemetry-port", type=int, default=None,
                   metavar="PORT",
                   help="serve the merged fleet OpenMetrics view here "
                        "(0 = ephemeral, announced in the ready file)")
    p.add_argument("--record", metavar="FILE", default=None,
                   help="append the final fleet-router RunRecord here")
    p.add_argument("--trace", metavar="FILE", default=None,
                   help="write the router's Chrome-trace JSON "
                        "(rid-tagged route/hop spans) here on drain; "
                        "merge with the replicas' --trace files via "
                        "tools/merge_traces.py --fleet")
    p.add_argument("--health-interval-s", type=float, default=1.0)
    p.add_argument("--request-timeout-s", type=float, default=600.0)
    p.add_argument("--revive-probes", type=int, default=1,
                   help="consecutive healthy probes before a marked-"
                        "down replica routes again (flap hysteresis)")
    p.add_argument("--repair", choices=["on", "off"], default="on",
                   help="checksum-driven consistency repair of "
                        "divergent replicas (targeted delta re-ingest)")
    # -- supervised mode -------------------------------------------------------
    p.add_argument("--spawn-corpus", metavar="FILE", default=None,
                   help="supervise mode: spawn replicas over this "
                        "corpus file instead of fanning over --replicas")
    p.add_argument("--spawn-replicas", type=int, default=2,
                   help="initial (and minimum) supervised fleet size")
    p.add_argument("--max-replicas", type=int, default=None,
                   help="auto-scaling ceiling (default: initial + 2)")
    p.add_argument("--out-dir", metavar="DIR", default=".",
                   help="supervised replicas' scratch dir (ready "
                        "files, telemetry snapshots, stderr logs)")
    p.add_argument("--spawn-warm", metavar="NQxK,...", default="1x1",
                   help="warm-bucket spec passed to spawned replicas")
    p.add_argument("--spawn-batch-cap", type=int, default=32)
    p.add_argument("--spawn-flags", default="",
                   help="extra daemon flags for spawned replicas "
                        "(quoted; '--mesh RxC' auto-sets XLA_FLAGS)")
    p.add_argument("--spawn-capacity", type=int, default=None,
                   help="explicit --capacity for spawned replicas")
    p.add_argument("--relaunch-budget", type=int, default=3,
                   help="total crashed-replica relaunches before "
                        "degrading to a smaller fleet")
    p.add_argument("--unhealthy-deadline-s", type=float, default=20.0,
                   help="probe-dead seconds before a hung replica is "
                        "treated as crashed")
    p.add_argument("--reshard-threshold", type=float, default=0.9,
                   help="corpus rows / capacity ratio that triggers "
                        "the staged shard re-split (<=0 disables)")
    p.add_argument("--scale-high", type=float, default=4.0)
    p.add_argument("--scale-low", type=float, default=0.25)
    p.add_argument("--poll-s", type=float, default=0.5,
                   help="supervisor watch interval")
    # -- SLO objectives + predictive autoscaling -------------------------------
    p.add_argument("--slo", action="append", default=None,
                   metavar="SPEC",
                   help="fleet-level SLO objective (repeatable), e.g. "
                        "'fleet.request_latency_ms p99 < 50 over 1m'; "
                        "availability specs sample good/total from the "
                        "merged fleet scrape. Evaluated in the health "
                        "loop (dmlp_tpu.obs.slo); transitions emit "
                        "slo.alert events and the slo_* gauge family")
    p.add_argument("--slo-trend", default=None, metavar="M,M",
                   help="extra metrics to track Theil-Sen latency "
                        "slopes for (slo.trend.* gauges)")
    p.add_argument("--policy", choices=["reactive", "predictive"],
                   default="reactive",
                   help="supervised auto-scaling policy: 'reactive' = "
                        "in-flight watermarks; 'predictive' = scale on "
                        "the SLO burn rate / trend-projected crossing "
                        "(falls back to reactive without signals)")
    p.add_argument("--slo-objective", default=None, metavar="ID",
                   help="objective id the predictive policy follows "
                        "(default: the first --slo latency objective)")
    p.add_argument("--lead-time-s", type=float, default=10.0,
                   help="predictive policy scales up when the trend "
                        "projects an SLO crossing within this horizon")
    args = p.parse_args(argv)

    # Idempotent backstop (the real install runs in fleet/__init__,
    # before any serving lock exists).
    from dmlp_tpu.check import racecheck
    racecheck.install_from_env()

    from dmlp_tpu.fleet.router import FleetRouter

    supervised = args.spawn_corpus is not None
    if not supervised and not args.replicas:
        raise SystemExit("need --replicas (static) or --spawn-corpus "
                         "(supervised)")

    if supervised:
        replicas: List[Tuple[str, int]] = []
        scrape_ports: List[Optional[int]] = []
    else:
        replicas = _parse_replicas(args.replicas)
        scrape_ports = _parse_ports(args.scrape_ports, len(replicas))
    if args.policy == "predictive" and not args.slo:
        raise SystemExit("--policy predictive needs at least one --slo "
                         "objective to read burn/trend signals from")
    trend = ([m.strip() for m in args.slo_trend.split(",") if m.strip()]
             if args.slo_trend else None)
    router = FleetRouter(replicas, scrape_ports=scrape_ports,
                         port=args.port,
                         health_interval_s=args.health_interval_s,
                         request_timeout_s=args.request_timeout_s,
                         telemetry_port=args.telemetry_port,
                         revive_probes=args.revive_probes,
                         repair=args.repair == "on",
                         allow_empty=supervised,
                         trace_path=args.trace,
                         objectives=args.slo,
                         slo_trend_metrics=trend)
    supervisor = None
    if supervised:
        from dmlp_tpu.fleet.autoscale import FleetSupervisor, ReplicaSpec
        os.makedirs(args.out_dir, exist_ok=True)
        spec = ReplicaSpec(args.spawn_corpus, args.out_dir,
                           warm_spec=args.spawn_warm,
                           batch_cap=args.spawn_batch_cap,
                           flags=shlex.split(args.spawn_flags),
                           capacity=args.spawn_capacity)
        supervisor = FleetSupervisor(
            router, spec,
            min_replicas=args.spawn_replicas,
            max_replicas=(args.max_replicas
                          if args.max_replicas is not None
                          else args.spawn_replicas + 2),
            relaunch_budget=args.relaunch_budget,
            poll_s=args.poll_s,
            unhealthy_deadline_s=args.unhealthy_deadline_s,
            scale_high=args.scale_high, scale_low=args.scale_low,
            reshard_threshold=(args.reshard_threshold
                               if args.reshard_threshold > 0 else None),
            policy=args.policy, slo=router.slo,
            slo_objective=args.slo_objective,
            lead_time_s=args.lead_time_s)
        supervisor.start()
    try:
        signal.signal(signal.SIGTERM,
                      lambda s, f: router.request_drain())
    except ValueError:
        pass   # not the main thread (embedders): drain op only
    router.start()
    sys.stderr.write(f"dmlp_tpu.fleet: ready port={router.port} "
                     f"replicas={len(router.replicas)}"
                     f"{' (supervised)' if supervised else ''}\n")
    sys.stderr.flush()
    if args.ready_file:
        doc = {"port": router.port, "pid": os.getpid(),
               "replicas": [r.name for r in router.replica_list()],
               "telemetry_port": getattr(router, "telemetry_port",
                                         None)}
        if supervisor is not None:
            doc["managed"] = supervisor.snapshot()["managed"]
        tmp = args.ready_file + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        os.replace(tmp, args.ready_file)
    # Wait for the drain signal OURSELVES (not run_until_drained): the
    # supervisor must stop BEFORE the drain propagates, or it would
    # read the fleet-wide orderly shutdown as a mass crash and spend
    # its relaunch budget resurrecting the replicas being drained.
    while not router._drain_event.wait(timeout=0.2):
        pass
    child_rcs = []
    if supervisor is not None:
        supervisor.stop()
    router.drain()
    if supervisor is not None:
        child_rcs = supervisor.wait_children()
    if args.record:
        from dmlp_tpu.obs.run import RunRecord, current_device
        stats = router.stats()
        metrics = {
            "healthy_replicas": stats["healthy_replicas"],
            "requests_total": sum(stats["requests"].values()),
            "retries_total": sum(stats["retries"].values()),
            "rejected_total": sum(stats["rejected"].values()),
            "divergences": stats["consistency"]["divergences"],
            "repairs": stats["consistency"]["repairs"],
            "relaunches": stats["scale"]["relaunches"],
            "splits": stats["scale"]["splits"],
        }
        lat = stats.get("request_latency_ms")
        if lat:
            metrics["request_latency_p50_ms"] = lat["p50"]
            metrics["request_latency_p99_ms"] = lat["p99"]
            metrics["request_count"] = lat["count"]
        RunRecord(kind="fleet", tool="dmlp_tpu.fleet",
                  config={"level": "router",
                          "replicas": len(router.replicas),
                          "supervised": supervised,
                          "mode": "closed_loop"},
                  metrics=metrics,
                  device=current_device()).append_jsonl(args.record)
    racecheck.write_report_if_requested()
    bad = [c for c in child_rcs if c["rc"] != 0]
    if supervisor is not None:
        # Orderly retirements BEFORE the drain (scale-down, re-shard
        # swap-outs) are held to the same rc-0 contract; seeded/real
        # crashes are excluded — those are the failures the supervisor
        # already absorbed by relaunching or degrading.
        bad += [e for e in supervisor.snapshot()["retired"]
                if not str(e["reason"]).startswith("crash")
                and e["rc"] != 0]
    if bad:
        sys.stderr.write(f"dmlp_tpu.fleet: drained, but managed "
                         f"replica(s) exited nonzero: {bad}\n")
        return 1
    sys.stderr.write("dmlp_tpu.fleet: drained clean\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
