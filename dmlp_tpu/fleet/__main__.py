"""``python -m dmlp_tpu.fleet`` — the fleet front-end router CLI.

Usage::

    python -m dmlp_tpu.fleet --replicas H:P,H:P[,...]
        [--scrape-ports Q,Q,...] [--port 0] [--ready-file PATH]
        [--telemetry-port PORT] [--record FILE]
        [--health-interval-s S] [--request-timeout-s S]

Fans the daemon wire protocol (queries load-balanced with bounded
retry-on-replica-failure, ingest fanned out to every replica, stats
aggregated) across the given daemon replicas; ``--telemetry-port``
serves the merged fleet OpenMetrics view (per-replica scrapes +
router counters). Prints ``dmlp_tpu.fleet: ready port=P replicas=N``
on stderr (and writes ``--ready-file``), then routes until SIGTERM or
an in-band ``drain`` op — which propagates the drain to every replica,
finishes in-flight relays, appends the final fleet RunRecord, and
exits 0.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
from typing import List, Optional, Sequence, Tuple


def _parse_replicas(spec: str) -> List[Tuple[str, int]]:
    out: List[Tuple[str, int]] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        try:
            host, port = part.rsplit(":", 1)
            out.append((host or "127.0.0.1", int(port)))
        except ValueError:
            raise SystemExit(
                f"--replicas entries are HOST:PORT, got {part!r}")
    if not out:
        raise SystemExit("--replicas lists no endpoints")
    return out


def _parse_ports(spec: Optional[str], n: int) -> List[Optional[int]]:
    if not spec:
        return [None] * n
    out: List[Optional[int]] = []
    for part in spec.split(","):
        part = part.strip()
        out.append(int(part) if part and part != "-" else None)
    if len(out) != n:
        raise SystemExit("--scrape-ports needs one entry per replica "
                         "('-' for none)")
    return out


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="dmlp_tpu.fleet",
                                description=__doc__)
    p.add_argument("--replicas", required=True, metavar="H:P,H:P",
                   help="daemon replica endpoints to fan across")
    p.add_argument("--scrape-ports", default=None, metavar="Q,Q",
                   help="per-replica telemetry ports for the "
                        "aggregated fleet scrape ('-' skips one)")
    p.add_argument("--port", type=int, default=0,
                   help="front-end TCP port (0 = ephemeral)")
    p.add_argument("--ready-file", metavar="PATH", default=None)
    p.add_argument("--telemetry-port", type=int, default=None,
                   metavar="PORT",
                   help="serve the merged fleet OpenMetrics view here "
                        "(0 = ephemeral, announced in the ready file)")
    p.add_argument("--record", metavar="FILE", default=None,
                   help="append the final fleet-router RunRecord here")
    p.add_argument("--health-interval-s", type=float, default=1.0)
    p.add_argument("--request-timeout-s", type=float, default=600.0)
    args = p.parse_args(argv)

    # Idempotent backstop (the real install runs in fleet/__init__,
    # before any serving lock exists).
    from dmlp_tpu.check import racecheck
    racecheck.install_from_env()

    from dmlp_tpu.fleet.router import FleetRouter

    replicas = _parse_replicas(args.replicas)
    scrape_ports = _parse_ports(args.scrape_ports, len(replicas))
    router = FleetRouter(replicas, scrape_ports=scrape_ports,
                         port=args.port,
                         health_interval_s=args.health_interval_s,
                         request_timeout_s=args.request_timeout_s,
                         telemetry_port=args.telemetry_port)
    try:
        signal.signal(signal.SIGTERM,
                      lambda s, f: router.request_drain())
    except ValueError:
        pass   # not the main thread (embedders): drain op only
    router.start()
    sys.stderr.write(f"dmlp_tpu.fleet: ready port={router.port} "
                     f"replicas={len(replicas)}\n")
    sys.stderr.flush()
    if args.ready_file:
        doc = {"port": router.port, "pid": os.getpid(),
               "replicas": [r.name for r in router.replicas],
               "telemetry_port": getattr(router, "telemetry_port",
                                         None)}
        tmp = args.ready_file + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        os.replace(tmp, args.ready_file)
    router.run_until_drained()
    if args.record:
        from dmlp_tpu.obs.run import RunRecord, current_device
        stats = router.stats()
        metrics = {
            "healthy_replicas": stats["healthy_replicas"],
            "requests_total": sum(stats["requests"].values()),
            "retries_total": sum(stats["retries"].values()),
            "rejected_total": sum(stats["rejected"].values()),
        }
        lat = stats.get("request_latency_ms")
        if lat:
            metrics["request_latency_p50_ms"] = lat["p50"]
            metrics["request_latency_p99_ms"] = lat["p99"]
            metrics["request_count"] = lat["count"]
        RunRecord(kind="fleet", tool="dmlp_tpu.fleet",
                  config={"level": "router",
                          "replicas": len(replicas),
                          "mode": "closed_loop"},
                  metrics=metrics,
                  device=current_device()).append_jsonl(args.record)
    racecheck.write_report_if_requested()
    sys.stderr.write("dmlp_tpu.fleet: drained clean\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
