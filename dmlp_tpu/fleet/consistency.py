"""Replica-consistency checking and checksum-driven repair.

The fleet's ingest fan-out is best-effort: a replica that crashes (or
whose ingest faults) mid-fan-out silently forks the fleet corpus — the
router REPORTS the divergence but, before this module, could not fix
it. This module closes that loop in three pieces:

- **Corpus signature.** Every resident engine maintains a rolling
  corpus checksum: a 64-bit per-row hash (FNV-1a over the row's label
  + float64 attribute bits) combined with a position mix (splitmix64
  of the global row id) and folded by wrapping 64-bit sum. The fold is
  *incremental* — an ingest of ``m`` rows updates it in O(m) — and
  *overwrite-capable* — re-writing row ``i`` subtracts the old term
  and adds the new one, so idempotent row-writes keyed by global row
  id leave the signature unchanged. Crucially it is a pure function of
  (row position, label, attribute bits): a plain ResidentEngine and a
  MeshResidentEngine holding the same corpus report the SAME
  signature, whatever their device layouts.
- **Diagnosis** (:func:`diagnose`): given every healthy replica's
  ``(rows, checksum)``, pick the reference — the largest row count
  (ingest is append-monotone, so the ahead replica carries rows the
  laggard can be given; truncating an ahead replica would destroy
  data), ties broken by the most-agreed signature — and name the
  divergent replicas.
- **Repair** (:func:`repair_replica`): targeted re-ingest of the
  delta. The lagging replica's missing rows ``[t.rows, ref.rows)`` are
  fetched from the reference over the wire (the daemon's ``corpus``
  op) and pushed into the laggard as ``ingest`` requests with an
  explicit ``start`` — idempotent row-writes, so a repair racing a
  normal fan-out converges instead of double-appending. The loop
  re-checks signatures between rounds (ingest may still be flowing)
  and succeeds only when rows AND checksum match. Two divergence
  shapes are *unrepairable* by construction and escalate instead:
  equal rows with different checksums (content corruption — the delta
  is unknown) and a target ahead of every reference; the router
  quarantines such a replica (marked down, never revived) rather than
  serve two truths.

Pure numpy + wire helpers; the router's health prober drives
:func:`diagnose`/:func:`repair_replica` (see
``FleetRouter._consistency_tick``) and the re-shard choreography
reuses :func:`repair_replica` as its replay-and-verify primitive.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

_FNV_OFFSET = np.uint64(0xCBF29CE484222325)
_FNV_PRIME = np.uint64(0x100000001B3)
_MASK = (1 << 64) - 1


def row_hashes(labels, attrs) -> np.ndarray:
    """Per-row 64-bit FNV-1a over (attribute float64 bits, label) —
    position-independent; :func:`fold_terms` adds the position mix.
    Vectorized over rows (uint64 multiply wraps, which IS the hash)."""
    a = np.ascontiguousarray(np.asarray(attrs, np.float64))
    a = a.reshape(len(a), -1).view(np.uint64)
    lab = np.asarray(labels, np.int64).astype(np.uint64)
    h = np.full(len(lab), _FNV_OFFSET, np.uint64)
    for j in range(a.shape[1]):
        h = (h ^ a[:, j]) * _FNV_PRIME
    return (h ^ lab) * _FNV_PRIME


def _position_mix(start: int, count: int) -> np.ndarray:
    """splitmix64 finalizer over global row ids ``[start, start+count)``
    — decorrelates positions so swapped rows change the fold."""
    z = (np.arange(start, start + count, dtype=np.uint64)
         + np.uint64(0x9E3779B97F4A7C15))
    z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return z ^ (z >> np.uint64(31))


def fold_terms(start: int, hashes: np.ndarray) -> int:
    """The rows' contribution to the corpus fold: wrapping 64-bit sum
    of ``hash * mix(position)``. Absent rows contribute 0 (their stored
    hash is 0), so the fold over ``[0, n)`` is exactly the sum of the
    written rows' terms."""
    if len(hashes) == 0:
        return 0
    terms = np.asarray(hashes, np.uint64) * _position_mix(start,
                                                          len(hashes))
    return int(np.sum(terms, dtype=np.uint64))


def fold_replace(fold: int, start: int, old_hashes: np.ndarray,
                 new_hashes: np.ndarray) -> int:
    """Incremental fold update for rows ``[start, start+m)`` changing
    from ``old_hashes`` to ``new_hashes`` (wrapping arithmetic, so an
    idempotent overwrite — old == new — is exactly a no-op)."""
    return (fold - fold_terms(start, old_hashes)
            + fold_terms(start, new_hashes)) & _MASK


def corpus_fold(labels, attrs) -> int:
    """The from-scratch signature of a whole corpus (tests cross-check
    the engines' incremental folds against this)."""
    return fold_terms(0, row_hashes(labels, attrs))


# -- diagnosis ----------------------------------------------------------------

def diagnose(sigs: List[Tuple[str, Dict[str, Any]]]
             ) -> Optional[Dict[str, Any]]:
    """``sigs`` is ``[(replica_name, {"rows", "checksum"}), ...]`` for
    every healthy replica. Returns None when all agree, else a verdict
    ``{"reference", "rows", "checksum", "divergent": [names]}``.
    Reference: max rows first (append-monotone ingest — the ahead
    replica holds the repair material), then the most-populated
    signature group, then name order (deterministic)."""
    groups: Dict[Tuple[int, int], List[str]] = {}
    for name, sig in sigs:
        key = (int(sig["rows"]), int(sig["checksum"]))
        groups.setdefault(key, []).append(name)
    if len(groups) <= 1:
        return None
    ref_key = max(groups,
                  key=lambda k: (k[0], len(groups[k]),
                                 sorted(groups[k])[0]))
    divergent = [n for k, names in groups.items() if k != ref_key
                 for n in names]
    return {"reference": sorted(groups[ref_key])[0],
            "rows": ref_key[0], "checksum": ref_key[1],
            "divergent": sorted(divergent)}


# -- wire helpers (the daemon protocol, replica-side) -------------------------

def _call(rep, obj: Dict[str, Any], timeout_s: float = 60.0
          ) -> Optional[Dict[str, Any]]:
    """One control-plane request to a replica (``probe=True`` — repair
    traffic is not client traffic); None on transport/JSON failure."""
    from dmlp_tpu.serve.protocol import encode
    try:
        return json.loads(rep.call(encode(obj), timeout_s=timeout_s,
                                   probe=True))
    except (OSError, ValueError):
        return None


def corpus_state_via_wire(rep) -> Optional[Dict[str, int]]:
    """A replica's live (rows, checksum, epoch) via the ``corpus`` op
    with ``count=0`` — the cheap state read repair loops poll."""
    doc = _call(rep, {"op": "corpus", "start": 0, "count": 0})
    if not doc or not doc.get("ok"):
        return None
    return {"rows": int(doc["corpus_rows"]),
            "checksum": int(doc["checksum"]),
            "epoch": int(doc.get("epoch", 0))}


def repair_replica(ref, target, fetch_rows: int = 2048,
                   max_rounds: int = 8) -> Dict[str, Any]:
    """Targeted delta re-ingest from ``ref`` into ``target`` until
    their corpus signatures match. Returns ``{"repaired": bool,
    "replayed_rows", "rounds", "reason"?}``; never raises — transport
    failures report as unrepaired (the caller escalates)."""
    # The server clamps corpus reads at CORPUS_FETCH_MAX; clamp here
    # too with the SAME definition — the protocol's — so the paging
    # arithmetic and the wire cap cannot drift.
    from dmlp_tpu.serve.protocol import CORPUS_FETCH_MAX
    fetch_rows = min(int(fetch_rows), CORPUS_FETCH_MAX)
    replayed = 0
    rounds = 0
    for _round in range(max(max_rounds, 1)):
        s = corpus_state_via_wire(ref)
        t = corpus_state_via_wire(target)
        if s is None or t is None:
            return {"repaired": False, "replayed_rows": replayed,
                    "rounds": rounds,
                    "reason": "replica unreachable during repair"}
        if t["rows"] == s["rows"]:
            if t["checksum"] == s["checksum"]:
                return {"repaired": True, "replayed_rows": replayed,
                        "rounds": rounds}
            return {"repaired": False, "replayed_rows": replayed,
                    "rounds": rounds,
                    "reason": "checksum mismatch at equal row counts "
                              "(content divergence — delta unknown)"}
        if t["rows"] > s["rows"]:
            return {"repaired": False, "replayed_rows": replayed,
                    "rounds": rounds,
                    "reason": "target ahead of reference"}
        rounds += 1
        at = t["rows"]
        while at < s["rows"]:
            doc = _call(ref, {"op": "corpus", "start": at,
                              "count": min(fetch_rows, s["rows"] - at)})
            if not doc or not doc.get("ok") or not doc.get("rows"):
                break     # source moved/unreachable: re-diagnose
            push = _call(target, {"op": "ingest",
                                  "labels": doc["labels"],
                                  "rows": doc["rows"], "start": at})
            if not push or not push.get("ok"):
                return {"repaired": False, "replayed_rows": replayed,
                        "rounds": rounds,
                        "reason": "delta re-ingest rejected: "
                                  f"{(push or {}).get('error')}"}
            m = len(doc["rows"])
            at += m
            replayed += m
    return {"repaired": False, "replayed_rows": replayed,
            "rounds": rounds,
            "reason": f"no convergence after {max_rounds} rounds "
                      "(ingest outrunning repair?)"}
