"""Staged shard re-split: grow a replica's resident layout on ingest.

The resident engines stage the corpus ONCE into a capacity-padded
layout fixed for the process lifetime — the mesh-resident engine's
per-shard chunk buffers especially so (ROADMAP follow-on (c): "growing
past capacity needs a staged shard split; the fleet router's drain
choreography gives the window"). Ingest that approaches the buffer
limit therefore cannot be absorbed in place; it needs a NEW layout.
This module choreographs exactly that, with zero dropped and zero
wrong responses:

1. **Spawn the replacement** from the supervisor's spec with a GROWN
   capacity (next power-of-two at ``grow_factor`` × the old one) — for
   a mesh replica this re-plans ``shard_rows``/chunk counts, i.e. the
   shard split proper. The replacement is NOT yet in the routing table:
   it holds the base corpus file only.
2. **Replay the delta, checksum-verified.** The ingested rows the base
   file lacks are paged out of the OLD replica (the ``corpus`` wire op)
   and pushed into the replacement as idempotent ``start``-keyed
   row-writes — :func:`fleet.consistency.repair_replica` IS the replay
   loop, re-checking the rolling corpus signature between rounds so
   ingest racing the replay just extends the catch-up (bounded).
3. **Swap.** The verified replacement enters the routing table; the old
   replica is marked DRAINING (no new queries or ingest fan-out reach
   it — its corpus freezes), a FINAL catch-up copies any rows that
   landed in the add→drain window, and only then is the old replica
   removed and drained (exits 0). Queries racing the swap are answered
   by whichever replica is live — both byte-identical by construction,
   because the swap only happens checksum-verified. The router's
   consistency prober backstops the last sliver (an ingest fan-out
   completing on the old replica between the final verify and the
   table removal is repaired from the other fleet members like any
   divergence).

Failure at any stage backs out: the replacement is killed, the old
replica keeps serving at its old capacity, and
``fleet.reshard.failures`` records the attempt. Success records
``fleet.reshard.splits`` / ``replayed_rows`` / ``catchup_rounds`` and
the retired replica's exit code rides the supervisor snapshot.
"""

from __future__ import annotations

from typing import Any, Dict

from dmlp_tpu.fleet import consistency as ccs
from dmlp_tpu.fleet.router import Replica
from dmlp_tpu.obs import telemetry
from dmlp_tpu.obs.trace import instant as obs_instant
from dmlp_tpu.tune.cache import shape_bucket


def grown_capacity(capacity_rows: int, rows: int,
                   grow_factor: int = 2) -> int:
    """The replacement layout's row capacity: the next power-of-two
    covering ``grow_factor`` × the old capacity (and always the
    current row count + headroom)."""
    return shape_bucket(max(int(capacity_rows) * max(grow_factor, 2),
                            int(rows) + 1))


def needs_resplit(rows: int, capacity_rows: int,
                  threshold: float = 0.9) -> bool:
    """Has ingest approached the capacity-padded buffer limit?"""
    return capacity_rows > 0 and rows >= threshold * capacity_rows


def execute_resplit(supervisor, mr, grow_factor: int = 2,
                    max_catchup: int = 8) -> Dict[str, Any]:
    """Split ``mr`` (a :class:`fleet.autoscale.ManagedReplica`): spawn
    the grown replacement, replay + verify, swap, drain the old one.
    Returns a result dict (``ok``, and on success ``capacity``,
    ``replayed_rows``, ``rounds``, ``old_rc``)."""
    reg = telemetry.registry()
    router = supervisor.router
    old_rep = mr.replica
    sig = old_rep.last_corpus or ccs.corpus_state_via_wire(old_rep)
    if sig is None:
        reg.counter("fleet.reshard.failures").inc(label="source_gone")
        return {"ok": False, "reason": "source replica unreachable"}
    old_cap = old_rep.capacity_rows or int(sig["rows"])
    new_cap = grown_capacity(old_cap, sig["rows"],
                             grow_factor=grow_factor)
    name = f"{mr.name}_g{mr.generation + 1}"
    obs_instant("fleet.reshard.begin", replica=mr.name,
                rows=sig["rows"], old_capacity=old_cap,
                new_capacity=new_cap)
    try:
        fp = supervisor.spawn_proc(name, capacity=new_cap)
    except Exception as e:  # check: no-retry — a failed spawn leaves
        # the old replica serving at its old capacity (recorded; the
        # next threshold crossing retries with a fresh process)
        reg.counter("fleet.reshard.failures").inc(label="spawn")
        return {"ok": False, "reason": f"spawn failed: {e}"}
    new_rep = Replica("127.0.0.1", fp.ready["port"],
                      scrape_port=fp.scrape_port)
    replayed = 0
    rounds = 0
    try:
        # 2. replay the delta into the (not yet routed) replacement,
        # looping until rows AND rolling checksum match the source.
        res = ccs.repair_replica(old_rep, new_rep,
                                 max_rounds=max_catchup)
        replayed += res["replayed_rows"]
        rounds += res["rounds"]
        if not res["repaired"]:
            # Back out: the unregistered replacement must DIE here —
            # it is in neither the routing table nor the supervisor's
            # managed set, so nothing else would ever reap it (and the
            # next threshold crossing would spawn another).
            fp.proc.kill()
            try:
                fp.proc.wait(timeout=30)
            except Exception:  # check: no-retry — kernel owns it now
                pass
            reg.counter("fleet.reshard.failures").inc(label="replay")
            return {"ok": False,
                    "reason": f"replay did not verify: "
                              f"{res.get('reason')}"}
        # 3. the swap: replacement IN, old replica frozen (draining
        # stops both query routing and ingest fan-out to it), final
        # catch-up over the frozen corpus, then out through the drain
        # choreography.
        new_mr = supervisor.register(fp, capacity=new_cap,
                                     generation=mr.generation + 1)
        old_rep.mark(draining=True)
        final = ccs.repair_replica(old_rep, new_rep,
                                   max_rounds=max_catchup)
        replayed += final["replayed_rows"]
        rounds += final["rounds"]
        if not final["repaired"]:
            # Back out: the replacement leaves the table, the old
            # replica resumes (its corpus is intact — nothing was
            # written to it).
            supervisor.retire(new_mr, drain=True,
                              reason="reshard_backout")
            old_rep.mark(draining=False)
            reg.counter("fleet.reshard.failures").inc(label="verify")
            return {"ok": False,
                    "reason": f"final verify failed: "
                              f"{final.get('reason')}"}
    except Exception:
        fp.proc.kill()
        raise
    old_rc = supervisor.retire(mr, drain=True, reason="reshard")
    reg.counter("fleet.reshard.splits").inc()
    reg.counter("fleet.reshard.replayed_rows").inc(replayed)
    reg.counter("fleet.reshard.catchup_rounds").inc(max(rounds, 1))
    telemetry.registry().gauge("fleet.reshard.capacity_rows").set(
        new_cap)
    obs_instant("fleet.reshard.swap", old=mr.name, new=name,
                capacity=new_cap, replayed_rows=replayed,
                old_rc=old_rc)
    telemetry.flight_event("fleet.reshard.split", old=mr.name,
                           new=name, capacity=new_cap)
    return {"ok": True, "replica": name, "capacity": new_cap,
            "replayed_rows": replayed, "rounds": rounds,
            "old_rc": old_rc}
