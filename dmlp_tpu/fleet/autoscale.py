"""Supervised replica auto-scaling: the fleet's lifecycle owner.

PR 14's router fans traffic across a FIXED replica set: a crashed
replica stays a hole in the table forever, and the operator is the
scaling policy. This module is the router-side supervisor the ROADMAP
follow-on (b) calls for — ``resilience/supervise.py`` (heartbeat +
deadline + bounded relaunch for the batch cluster) lifted to the fleet
layer, where the health prober IS the heartbeat:

- **Crash detection.** Each managed replica is watched two ways: its
  PROCESS (``poll()`` — a SIGKILLed or crashed daemon is seen within
  one supervisor tick) and its PROBE HEALTH (a replica marked down by
  the router's prober for longer than ``unhealthy_deadline_s`` is a
  hung interpreter — the process-level sibling of supervise.py's
  cluster deadline; heartbeat threads beat through livelocks, probes
  do not answer through them).
- **Bounded relaunch.** A crashed/hung replica is removed from the
  routing table, killed if still alive, and relaunched from the spec —
  bounded by ``relaunch_budget`` across the supervisor's lifetime.
  Exhausted budget DEGRADES to a smaller fleet instead of crash-
  looping: the event is recorded (``fleet.scale.degraded`` gauge +
  flight event), the router keeps serving from the replicas that
  remain, and byte-identity is untouched (every replica serves the
  same corpus).
- **Scaling policy.** The offered-load estimate is derived from the
  router's existing per-replica load tracking (mean in-flight relays
  per available replica, windowed); ``target_replicas`` is a pure
  function of the window (unit-testable), scale-up spawns + registers
  a replica, scale-down retires one via the existing drain
  choreography (mark draining -> in-band drain -> wait exit 0) — the
  same path the re-shard swap uses.
- **Staged shard re-split trigger.** When a replica's probed corpus
  occupancy crosses ``reshard_threshold`` of its capacity, the
  supervisor hands it to ``fleet/reshard.py`` (one split in flight at
  a time — staged, never a thundering re-stage of the whole fleet).

Every transition is a ``fleet.scale.*`` registry counter and an
``obs.trace`` event; the router's ``stats`` exposes the supervisor
snapshot (per-replica generation/pid/capacity, retired exit codes,
remaining budget) so the chaos harness can assert the choreography
from outside the process.
"""

from __future__ import annotations

import math
import os
import subprocess
import threading
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional

from dmlp_tpu.fleet import harness as fh
from dmlp_tpu.fleet.router import FleetRouter, Replica
from dmlp_tpu.obs import telemetry
from dmlp_tpu.obs.trace import instant as obs_instant


class ReplicaSpec:
    """How to spawn one replica daemon (the supervisor's template).

    ``flags`` carrying ``--mesh RxC`` automatically get the
    ``XLA_FLAGS`` host-device-count override a CPU container needs for
    an R*C virtual mesh (merged with any caller-provided env)."""

    def __init__(self, corpus_path: str, out_dir: str,
                 warm_spec: str = "1x1", batch_cap: int = 32,
                 flags: Optional[List[str]] = None,
                 env_extra: Optional[Dict[str, str]] = None,
                 capacity: Optional[int] = None,
                 compile_cache: Optional[str] = None):
        self.corpus_path = os.path.abspath(corpus_path)
        self.out_dir = os.path.abspath(out_dir)
        self.warm_spec = warm_spec
        self.batch_cap = int(batch_cap)
        self.flags = list(flags or [])
        self.env_extra = dict(env_extra or {})
        self.capacity = capacity
        # Shared persistent XLA compile cache for every spawn from this
        # template: relaunches, scale-ups, and capacity re-splits all
        # reuse the first generation's executables (their bucket shapes
        # are identical by construction), so the fleet's cold-start
        # compile time pays once. $DMLP_TPU_COMPILE_CACHE is the
        # ambient form (inherited env) when no explicit dir is given.
        self.compile_cache = (os.path.abspath(compile_cache)
                              if compile_cache else None)

    def _env(self) -> Dict[str, str]:
        env = dict(self.env_extra)
        if "--mesh" in self.flags and "XLA_FLAGS" not in env:
            try:
                r, c = self.flags[
                    self.flags.index("--mesh") + 1].lower().split("x")
                n = int(r) * int(c)
            except (IndexError, ValueError):
                n = 0
            if n > 1:
                base = os.environ.get("XLA_FLAGS", "")
                env["XLA_FLAGS"] = (base + " " if base else "") + \
                    f"--xla_force_host_platform_device_count={n}"
        return env

    def spawn(self, name: str,
              capacity: Optional[int] = None) -> fh.FleetProc:
        flags = list(self.flags)
        cap = capacity or self.capacity
        if cap:
            flags += ["--capacity", str(int(cap))]
        return fh.spawn_replica(self.corpus_path, self.out_dir, name,
                                self.warm_spec,
                                batch_cap=self.batch_cap, flags=flags,
                                env_extra=self._env(),
                                compile_cache=self.compile_cache)


class ManagedReplica:
    """One supervised replica: the spawned process + its routing-table
    entry + lifecycle bookkeeping."""

    def __init__(self, name: str, proc: fh.FleetProc, replica: Replica,
                 capacity: Optional[int] = None, generation: int = 0):
        self.name = name
        self.proc = proc
        self.replica = replica
        self.capacity = capacity
        self.generation = generation
        self.retiring = False

    def snapshot(self) -> Dict[str, Any]:
        return {"name": self.name, "replica": self.replica.name,
                "pid": self.proc.proc.pid,
                "port": self.proc.ready.get("port"),
                "capacity": self.capacity,
                "generation": self.generation,
                "retiring": self.retiring}


def target_replicas(window: List[float], current: int, minimum: int,
                    maximum: int, high: float, low: float) -> int:
    """The PURE scaling policy: median of the load window (mean
    in-flight per available replica) against the high/low watermarks.
    One step at a time, clamped to [minimum, maximum]."""
    if not window:
        return current
    med = sorted(window)[len(window) // 2]
    if med > high and current < maximum:
        return current + 1
    if med < low and current > minimum:
        return current - 1
    return current


def predictive_target_replicas(signals: Dict[str, Any], current: int,
                               minimum: int, maximum: int, *,
                               lead_time_s: float = 10.0,
                               down_margin: float = 0.5) -> int:
    """The PURE predictive policy over one SLO objective's live
    signals (``obs.slo.SLOEvaluator.signals``): scale on the LEADING
    indicators — error-budget burn and latency slope — not on queue
    depth, which only moves once the SLO is already slipping.

    Scale UP one step when the fast-window burn rate exceeds budget
    (burn > 1: the objective is being violated right now) OR the
    Theil–Sen latency slope projects the threshold crossing within
    ``lead_time_s`` (``projected_s`` — the time a replica spawn +
    warm takes is exactly the lead this buys). Scale DOWN one step
    only when the picture is unambiguously calm: zero burn on BOTH
    windows, non-positive slope, and the fast-window quantile under
    ``down_margin`` of the threshold — the hysteresis gap between the
    up and down conditions is what keeps flat load from oscillating
    (a flat series trips neither side, so the decision is a fixed
    point). One step at a time, clamped to [minimum, maximum]."""
    burn_fast = float(signals.get("burn_fast", 0.0))
    burn_slow = float(signals.get("burn_slow", 0.0))
    slope = float(signals.get("slope_ms_per_s", 0.0))
    projected = float(signals.get("projected_s", math.inf))
    p_fast = signals.get("p_fast")
    threshold = signals.get("threshold")
    if math.isnan(slope):
        slope = 0.0
    if current < maximum and (burn_fast > 1.0
                              or projected <= lead_time_s):
        return current + 1
    calm = burn_fast == 0.0 and burn_slow == 0.0 and slope <= 0.0
    if calm and isinstance(p_fast, (int, float)) \
            and isinstance(threshold, (int, float)) \
            and not math.isnan(p_fast) \
            and p_fast < down_margin * float(threshold) \
            and current > minimum:
        return current - 1
    return current


class FleetSupervisor:
    """Spawns, watches, scales, re-splits, and retires the managed
    replica fleet behind one :class:`FleetRouter`."""

    def __init__(self, router: FleetRouter,
                 spec: Optional[ReplicaSpec] = None, *,
                 min_replicas: int = 1, max_replicas: int = 4,
                 relaunch_budget: int = 3, poll_s: float = 0.5,
                 unhealthy_deadline_s: float = 20.0,
                 scale_high: float = 4.0, scale_low: float = 0.25,
                 scale_window: int = 6,
                 load_fn: Optional[Callable[[], float]] = None,
                 reshard_threshold: Optional[float] = None,
                 grow_factor: int = 2,
                 ready_timeout_s: float = 600.0,
                 drain_timeout_s: float = 120.0,
                 policy: str = "reactive",
                 slo: Optional[Any] = None,
                 slo_objective: Optional[str] = None,
                 lead_time_s: float = 10.0):
        if policy not in ("reactive", "predictive"):
            raise ValueError(f"scaling policy {policy!r}")
        self.router = router
        self.spec = spec
        self.min_replicas = int(min_replicas)
        self.max_replicas = max(int(max_replicas), self.min_replicas)
        self.relaunch_budget = int(relaunch_budget)
        self.poll_s = poll_s
        self.unhealthy_deadline_s = unhealthy_deadline_s
        self.scale_high = scale_high
        self.scale_low = scale_low
        self.scale_window = max(int(scale_window), 1)
        self.load_fn = load_fn
        self.reshard_threshold = reshard_threshold
        self.grow_factor = max(int(grow_factor), 2)
        self.ready_timeout_s = ready_timeout_s
        self.drain_timeout_s = drain_timeout_s
        #: "reactive" (watermarks over the in-flight window — the
        #: fallback and A/B arm) or "predictive" (obs.slo burn + slope
        #: signals; reverts to reactive while signals are absent)
        self.policy = policy
        self.slo = slo
        if slo_objective is None and slo is not None:
            # Default to the first declared latency objective — the
            # burn/slope signal set the predictive policy consumes.
            slo_objective = next(
                (o.name for o in getattr(slo, "objectives", [])
                 if getattr(o, "kind", "") == "latency"), None)
        self.slo_objective = slo_objective
        self.lead_time_s = float(lead_time_s)
        self._lock = threading.Lock()     # guards managed/retired lists
        self.managed: List[ManagedReplica] = []
        self.retired: List[Dict[str, Any]] = []
        self.degraded = False
        self._seq = 0
        self._load_window: Deque[float] = deque(maxlen=self.scale_window)
        self._resharding = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        router.supervisor = self

    # -- introspection ---------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            managed = [m.snapshot() for m in self.managed]
            retired = list(self.retired)
        return {"managed": managed, "retired": retired,
                "relaunch_budget_left": self.relaunch_budget,
                "degraded": self.degraded,
                "min_replicas": self.min_replicas,
                "max_replicas": self.max_replicas}

    def _managed_list(self) -> List[ManagedReplica]:
        with self._lock:
            return list(self.managed)

    # -- spawn / register / retire ---------------------------------------------

    def _next_name(self) -> str:
        with self._lock:
            self._seq += 1
            return f"replica_s{self._seq:02d}"

    def spawn_proc(self, name: str,
                   capacity: Optional[int] = None) -> fh.FleetProc:
        """Spawn one replica daemon from the spec and block until it
        is ready (scrape port announced). The re-shard choreography
        calls this to stage a replacement BEFORE it enters the table."""
        if self.spec is None:
            raise RuntimeError("supervisor has no ReplicaSpec to "
                               "spawn from")
        fp = self.spec.spawn(name, capacity=capacity)
        fh.await_replica(fp, timeout_s=self.ready_timeout_s)
        return fp

    def register(self, fp: fh.FleetProc,
                 capacity: Optional[int] = None,
                 generation: int = 0) -> ManagedReplica:
        """Enter a ready replica into the routing table + the managed
        set."""
        rep = self.router.add_replica("127.0.0.1", fp.ready["port"],
                                      scrape_port=fp.scrape_port)
        mr = ManagedReplica(fp.name, fp, rep, capacity=capacity,
                            generation=generation)
        with self._lock:
            self.managed.append(mr)
        telemetry.registry().gauge("fleet.scale.replicas").set(
            len(self._managed_list()))
        return mr

    def spawn_one(self, capacity: Optional[int] = None,
                  generation: int = 0) -> ManagedReplica:
        fp = self.spawn_proc(self._next_name(), capacity=capacity)
        return self.register(fp, capacity=capacity or
                             (self.spec.capacity if self.spec else None),
                             generation=generation)

    def retire(self, mr: ManagedReplica, drain: bool = True,
               reason: str = "scale_down") -> Optional[int]:
        """The drain choreography: mark + remove from the table, wait
        for the daemon's orderly exit (0). Returns the exit code."""
        mr.retiring = True
        self.router.remove_replica(mr.replica.name, drain=drain)
        rc: Optional[int] = None
        try:
            rc = mr.proc.proc.wait(timeout=self.drain_timeout_s)
        except subprocess.TimeoutExpired:
            mr.proc.proc.kill()
            rc = mr.proc.proc.wait(timeout=30)
        with self._lock:
            if mr in self.managed:
                self.managed.remove(mr)
            self.retired.append({"name": mr.name, "rc": rc,
                                 "reason": reason,
                                 "generation": mr.generation})
        telemetry.registry().gauge("fleet.scale.replicas").set(
            len(self._managed_list()))
        obs_instant("fleet.scale.retire", replica=mr.name, rc=rc,
                    reason=reason)
        return rc

    # -- lifecycle -------------------------------------------------------------

    def start(self, initial: Optional[int] = None) -> None:
        """Spawn the initial fleet (``min_replicas`` by default), then
        start the watch thread."""
        for _ in range(initial if initial is not None
                       else self.min_replicas):
            self.spawn_one()
        self._stop.clear()
        self._thread = threading.Thread(target=self._watch_loop,
                                        name="fleet-supervisor",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        """Stop watching (no more relaunches) — call BEFORE the router
        drains, or the supervisor would read the drain as a mass crash
        and relaunch the fleet it is trying to shut down."""
        self._stop.set()
        t = self._thread
        self._thread = None
        if t is not None:
            t.join(timeout=30)

    def wait_children(self, timeout_s: float = 120.0
                      ) -> List[Dict[str, Any]]:
        """After the router's drain propagated: collect every managed
        replica's exit code (the all-rc-0 contract)."""
        out = []
        for mr in self._managed_list():
            try:
                rc = mr.proc.proc.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                mr.proc.proc.kill()
                rc = mr.proc.proc.wait(timeout=30)
            out.append({"name": mr.name, "rc": rc})
        return out

    # -- the watch loop --------------------------------------------------------

    def _watch_loop(self) -> None:
        while not self._stop.wait(timeout=self.poll_s):
            try:
                self.poll_once()
            except Exception as e:  # check: no-retry — the supervisor
                # must outlive any single poll failure; the event is
                # recorded, the next tick re-evaluates from scratch
                obs_instant("fleet.scale.poll_error",
                            error=f"{type(e).__name__}: {e}")

    def poll_once(self) -> None:
        """One supervision tick: crash detection -> re-shard check ->
        scaling decision. Tests drive this directly (no thread)."""
        if self._stop.is_set():
            return
        self._check_crashes()
        self._check_reshard()
        self._check_scaling()

    # -- crash detection + bounded relaunch ------------------------------------

    def _check_crashes(self) -> None:
        for mr in self._managed_list():
            if mr.retiring:
                continue
            rc = mr.proc.proc.poll()
            hung = (rc is None and self.unhealthy_deadline_s > 0
                    and mr.replica.down_for() > self.unhealthy_deadline_s)
            if rc is None and not hung:
                continue
            reason = (f"exited rc {rc}" if rc is not None else
                      f"probe-dead > {self.unhealthy_deadline_s:.3g}s "
                      "(hung)")
            self._handle_crash(mr, reason)

    def _handle_crash(self, mr: ManagedReplica, reason: str) -> None:
        reg = telemetry.registry()
        reg.counter("fleet.scale.crashes").inc(
            label="hung" if "hung" in reason else "exited")
        obs_instant("fleet.scale.crash", replica=mr.name,
                    reason=reason)
        telemetry.flight_event("fleet.scale.crash", replica=mr.name,
                               reason=reason)
        mr.retiring = True
        self.router.remove_replica(mr.replica.name, drain=False)
        if mr.proc.proc.poll() is None:
            mr.proc.proc.kill()
            try:
                mr.proc.proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                pass   # kernel owns it now; the table no longer does
        with self._lock:
            if mr in self.managed:
                self.managed.remove(mr)
            self.retired.append({"name": mr.name,
                                 "rc": mr.proc.proc.poll(),
                                 "reason": f"crash: {reason}",
                                 "generation": mr.generation})
        if self.relaunch_budget > 0:
            self.relaunch_budget -= 1
            reg.counter("fleet.scale.relaunches").inc()
            obs_instant("fleet.scale.relaunch", replaces=mr.name,
                        budget_left=self.relaunch_budget)
            try:
                self.spawn_one(capacity=mr.capacity,
                               generation=mr.generation + 1)
                return
            except Exception as e:  # check: no-retry — a failed
                # relaunch is the budget's problem, not a crash loop
                obs_instant("fleet.scale.relaunch_failed",
                            error=f"{type(e).__name__}: {e}")
        # Budget exhausted (or relaunch failed): degraded smaller
        # fleet — recorded loudly, served quietly.
        self.degraded = True
        telemetry.registry().gauge("fleet.scale.degraded").set(1)
        telemetry.flight_event("fleet.scale.degraded",
                               replicas=len(self._managed_list()),
                               lost=mr.name)
        obs_instant("fleet.scale.degraded", lost=mr.name,
                    replicas=len(self._managed_list()))

    # -- staged shard re-split -------------------------------------------------

    def _check_reshard(self) -> None:
        if self.reshard_threshold is None or self._resharding \
                or self.spec is None:
            return
        for mr in self._managed_list():
            if mr.retiring:
                continue
            sig = mr.replica.last_corpus
            cap = mr.replica.capacity_rows
            if not sig or not cap:
                continue
            if sig["rows"] < self.reshard_threshold * cap:
                continue
            from dmlp_tpu.fleet import reshard
            self._resharding = True
            try:
                reshard.execute_resplit(self, mr,
                                        grow_factor=self.grow_factor)
            finally:
                self._resharding = False
            return           # staged: one split per tick, at most

    def force_resplit(self, mr: Optional[ManagedReplica] = None
                      ) -> Dict[str, Any]:
        """Operator/chaos hook: split now, threshold or not."""
        from dmlp_tpu.fleet import reshard
        target = mr or next((m for m in self._managed_list()
                             if not m.retiring), None)
        if target is None:
            return {"ok": False, "reason": "no managed replica"}
        self._resharding = True
        try:
            return reshard.execute_resplit(self, target,
                                           grow_factor=self.grow_factor)
        finally:
            self._resharding = False

    # -- scaling ---------------------------------------------------------------

    def offered_load(self) -> float:
        """Mean in-flight relays per available replica — derived from
        the router's existing per-replica load tracking (the estimate
        the ROADMAP item names)."""
        reps = [r for r in self.router.replica_list() if r.available()]
        if not reps:
            return 0.0
        return sum(r.load() for r in reps) / len(reps)

    def _check_scaling(self) -> None:
        if self.policy == "predictive" \
                and self._check_scaling_predictive():
            return
        load = (self.load_fn or self.offered_load)()
        self._load_window.append(float(load))
        if len(self._load_window) < self.scale_window:
            return
        current = len([m for m in self._managed_list()
                       if not m.retiring])
        target = target_replicas(list(self._load_window), current,
                                 self.min_replicas, self.max_replicas,
                                 self.scale_high, self.scale_low)
        telemetry.registry().gauge("fleet.scale.target_replicas").set(
            target)
        if target == current:
            return
        self._load_window.clear()     # re-observe after acting
        self._act_on_target(current, target, "reactive")

    def _check_scaling_predictive(self) -> bool:
        """The predictive arm: one scaling decision from the SLO
        evaluator's live burn-rate + slope signals. Returns False
        (-> reactive fallback) while the evaluator has no usable
        signal yet — an SLO engine that has not evaluated anything
        must not freeze scaling entirely."""
        if self.slo is None or not self.slo_objective:
            return False
        try:
            sig = self.slo.signals(self.slo_objective)
        except KeyError:
            return False
        if "burn_fast" not in sig:
            return False               # no evaluation tick yet
        current = len([m for m in self._managed_list()
                       if not m.retiring])
        target = predictive_target_replicas(
            sig, current, self.min_replicas, self.max_replicas,
            lead_time_s=self.lead_time_s)
        telemetry.registry().gauge("fleet.scale.target_replicas").set(
            target)
        if target != current:
            self._act_on_target(current, target, "predictive")
        return True

    def _act_on_target(self, current: int, target: int,
                       policy: str) -> None:
        """Shared one-step actuation for both policies: spawn or
        retire, with the policy stamped on the trace event so the A/B
        arms are attributable in the merged fleet trace."""
        reg = telemetry.registry()
        if target > current:
            reg.counter("fleet.scale.up").inc(label=policy)
            obs_instant("fleet.scale.up", replicas=target,
                        policy=policy)
            try:
                self.spawn_one()
            except Exception as e:  # check: no-retry — scale-up is
                # best-effort; the next window re-decides
                obs_instant("fleet.scale.up_failed",
                            error=f"{type(e).__name__}: {e}")
        else:
            victim = next((m for m in reversed(self._managed_list())
                           if not m.retiring), None)
            if victim is not None:
                reg.counter("fleet.scale.down").inc(label=policy)
                obs_instant("fleet.scale.down", replica=victim.name,
                            policy=policy)
                self.retire(victim, drain=True, reason="scale_down")
