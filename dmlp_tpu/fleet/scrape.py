"""Fleet OpenMetrics aggregation: N replica scrapes -> one exposition.

Every daemon replica exposes the PR 9 registry on its
``--telemetry-port`` (or snapshot file). The fleet view merges them by
metric semantics, not by string concatenation:

- **counters** sum — the fleet served the sum of what its replicas
  served (per-``key`` labeled totals sum per key);
- **histograms** merge BUCKET-WISE: every replica shares the registry's
  fixed log-spaced bounds, so per-``le`` cumulative counts convert to
  per-bucket deltas, sum across replicas over the union of emitted
  bounds, and re-emit cumulative (``_sum``/``_count`` sum) — fleet
  quantiles keep the same ~5.9% worst-case error as a single replica's;
- **gauges** stay PER-REPLICA, labeled ``{replica="..."}`` — averaging
  a corpus-rows or headroom gauge across replicas would manufacture a
  number no process reports.

The merged exposition passes ``obs.telemetry.validate_openmetrics``
(asserted in tests and the fleet smoke); ``tools/fleet_scrape.py`` is
the CLI, and the router's ``--telemetry-port`` serves the same merge
live — through a :class:`ScrapeCache`, so a replica whose live scrape
fails keeps its last-good counters in the fleet totals but the reuse
is stamped per replica (``fleet_replica_scrape_age_s`` /
``fleet_replica_scrape_stale``), never silently merged as fresh.
"""

from __future__ import annotations

import math
import re
import threading
import time
import urllib.request
from typing import Callable, Dict, List, Optional, Tuple

_TYPE_RE = re.compile(
    r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) (counter|gauge|histogram)$")
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(\{(?P<labels>[^{}]*)\})? (?P<value>\S+)$")


class ParsedExposition:
    """One scrape, decomposed by metric base name."""

    def __init__(self):
        self.kinds: Dict[str, str] = {}
        self.help: Dict[str, str] = {}
        # counters/gauges: base -> {label_body ("" = unlabeled): value}
        self.samples: Dict[str, Dict[str, float]] = {}
        # histograms: base -> {"buckets": [(le, cum)], "sum": x,
        #                      "count": n}
        self.hists: Dict[str, Dict[str, object]] = {}
        self.problems: List[str] = []


def parse_exposition(text: str) -> ParsedExposition:
    """Parse an OpenMetrics text exposition into merge-ready structure
    (tolerant: malformed lines are recorded as problems, not raised —
    a half-written snapshot must not take down the fleet view)."""
    out = ParsedExposition()
    for i, line in enumerate(text.splitlines(), 1):
        if not line or line == "# EOF":
            continue
        if line.startswith("#"):
            m = _TYPE_RE.match(line)
            if m:
                out.kinds[m.group(1)] = m.group(2)
            elif line.startswith("# HELP "):
                parts = line.split(" ", 3)
                if len(parts) == 4:
                    out.help[parts[2]] = parts[3]
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            out.problems.append(f"line {i}: malformed sample {line!r}")
            continue
        name, labels = m.group("name"), m.group("labels") or ""
        try:
            value = float(m.group("value"))
        except ValueError:
            out.problems.append(f"line {i}: non-numeric {line!r}")
            continue
        # A declared name wins over suffix stripping: a GAUGE legally
        # named ..._count must not be misfiled under a stripped base.
        if name in out.kinds:
            base, kind = name, out.kinds[name]
        else:
            base = re.sub(r"(_total|_bucket|_sum|_count)$", "", name)
            kind = out.kinds.get(base)
        if kind == "histogram":
            h = out.hists.setdefault(
                base, {"buckets": [], "sum": 0.0, "count": 0})
            if name.endswith("_bucket"):
                le = math.inf
                lm = re.search(r'le="([^"]*)"', labels)
                if lm and lm.group(1) != "+Inf":
                    le = float(lm.group(1))
                h["buckets"].append((le, int(value)))
            elif name.endswith("_sum"):
                h["sum"] = value
            elif name.endswith("_count"):
                h["count"] = int(value)
            continue
        tgt = name if kind is None else base
        out.samples.setdefault(tgt, {})[labels] = value
        if kind is None:
            out.problems.append(f"line {i}: sample {name!r} has no "
                                "preceding # TYPE")
    return out


def _bucket_deltas(buckets: List[Tuple[float, int]]
                   ) -> Dict[float, int]:
    """(le, cumulative) pairs (sparse render) -> per-bucket deltas."""
    deltas: Dict[float, int] = {}
    prev = 0
    for le, cum in sorted(buckets, key=lambda b: b[0]):
        deltas[le] = cum - prev
        prev = cum
    return deltas


def _om_num(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def merge_expositions(texts: List[str],
                      replica_names: Optional[List[str]] = None
                      ) -> Tuple[str, List[str]]:
    """Merge N scrapes into one fleet exposition; returns (text,
    problems). Kind conflicts across replicas (impossible with one
    codebase, detected anyway) keep the first registration and report."""
    names = replica_names or [f"r{i}" for i in range(len(texts))]
    parsed = [parse_exposition(t) for t in texts]
    problems: List[str] = []
    for name, p in zip(names, parsed):
        problems.extend(f"{name}: {x}" for x in p.problems)

    kinds: Dict[str, str] = {}
    helps: Dict[str, str] = {}
    for p in parsed:
        for base, kind in p.kinds.items():
            if base in kinds and kinds[base] != kind:
                problems.append(
                    f"kind conflict for {base}: {kinds[base]} vs {kind}")
                continue
            kinds.setdefault(base, kind)
            if base in p.help:
                helps.setdefault(base, p.help[base])

    lines: List[str] = []
    for base in sorted(kinds):
        kind = kinds[base]
        lines.append(f"# TYPE {base} {kind}")
        if base in helps:
            lines.append(f"# HELP {base} {helps[base]}")
        if kind == "counter":
            totals: Dict[str, float] = {}
            for p in parsed:
                for labels, v in p.samples.get(base, {}).items():
                    totals[labels] = totals.get(labels, 0.0) + v
            for labels in sorted(totals):
                lab = f"{{{labels}}}" if labels else ""
                lines.append(
                    f"{base}_total{lab} {_om_num(totals[labels])}")
        elif kind == "gauge":
            for rname, p in zip(names, parsed):
                for labels, v in sorted(p.samples.get(base, {}).items()):
                    lab = (f'{{{labels},replica="{rname}"}}' if labels
                           else f'{{replica="{rname}"}}')
                    lines.append(f"{base}{lab} {_om_num(v)}")
        else:                                           # histogram
            deltas: Dict[float, int] = {}
            total_sum = 0.0
            total_count = 0
            for p in parsed:
                h = p.hists.get(base)
                if not h:
                    continue
                for le, d in _bucket_deltas(h["buckets"]).items():
                    deltas[le] = deltas.get(le, 0) + d
                total_sum += float(h["sum"])
                total_count += int(h["count"])
            deltas.setdefault(math.inf, 0)
            cum = 0
            for le in sorted(deltas):
                cum += deltas[le]
                le_s = "+Inf" if le == math.inf else _om_num(le)
                lines.append(f'{base}_bucket{{le="{le_s}"}} {cum}')
            lines.append(f"{base}_sum {_om_num(total_sum)}")
            lines.append(f"{base}_count {total_count}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n", problems


def counter_total(parsed: ParsedExposition, name: str) -> float:
    """Sum of one counter family's samples across its labels — the
    merged-scrape read path the router's fleet-level availability
    objective samples from (``obs.slo``). Accepts the registry's
    dotted name or the OpenMetrics underscore name."""
    return sum(parsed.samples.get(name.replace(".", "_"), {}).values())


def scrape_url(url: str, timeout_s: float = 10.0) -> str:
    with urllib.request.urlopen(url, timeout=timeout_s) as r:
        return r.read().decode()


class ScrapeCache:
    """Last-good scrape per replica + its monotonic stamp — the
    staleness fix: when a replica's live scrape fails, the merged
    fleet view REUSES the cached exposition (so fleet counter totals
    don't collapse the moment one replica blips) but the reuse is
    STAMPED, never silent — the router publishes
    ``fleet_replica_scrape_age_s`` (seconds since the last good
    scrape, 0 when live) and ``fleet_replica_scrape_stale`` (1 when
    the merge is running on a cached exposition) gauges per replica.
    A replica never scraped contributes nothing (no data to go stale).
    """

    def __init__(self, clock: Callable[[], float] = time.monotonic,
                 fetch: Callable[[str], str] = scrape_url):
        self._clock = clock
        self._fetch = fetch
        self._lock = threading.Lock()      # leaf: guards the dict only
        self._cache: Dict[str, Tuple[str, float]] = {}

    def fetch(self, name: str, url: str
              ) -> Tuple[Optional[str], float, bool]:
        """-> (exposition text | None, age_s, stale?). The network
        call runs outside the lock (check rule R703)."""
        try:
            text = self._fetch(url)
        except OSError:
            with self._lock:
                cached = self._cache.get(name)
            if cached is None:
                return None, 0.0, True
            text, stamp = cached
            return text, max(self._clock() - stamp, 0.0), True
        with self._lock:
            self._cache[name] = (text, self._clock())
        return text, 0.0, False

    def forget(self, name: str) -> None:
        """Drop a retired replica's cache (its counters must not haunt
        the merge after the routing table drops it)."""
        with self._lock:
            self._cache.pop(name, None)


def fleet_view(sources: List[str],
               replica_names: Optional[List[str]] = None,
               timeout_s: float = 10.0) -> Tuple[str, List[str]]:
    """One aggregated exposition from per-replica sources (``http://``
    URLs are scraped, anything else reads as a snapshot file path);
    unreachable replicas become problems, never exceptions — the fleet
    view must degrade, not vanish, when one replica is down."""
    texts: List[str] = []
    names: List[str] = []
    problems: List[str] = []
    wanted = replica_names or [f"r{i}" for i in range(len(sources))]
    for name, src in zip(wanted, sources):
        try:
            if src.startswith(("http://", "https://")):
                texts.append(scrape_url(src, timeout_s=timeout_s))
            else:
                with open(src) as f:
                    texts.append(f.read())
            names.append(name)
        except OSError as e:
            problems.append(f"{name}: unreachable ({e})")
    merged, merge_problems = merge_expositions(texts, names)
    return merged, problems + merge_problems
