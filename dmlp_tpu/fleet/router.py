"""The fleet front end: one line-JSON TCP port over N daemon replicas.

The router speaks the daemon's wire protocol on both sides and stays
deliberately thin — it never parses query payloads beyond the ``op``
field, and it relays each replica's response LINE verbatim, so the
bytes a client sees are exactly the bytes one daemon produced (fleet
byte-identity reduces to daemon byte-identity).

Routing policy (the part the tests pin down):

- **Queries** go to the least-loaded healthy, non-draining replica.
  They are idempotent pure reads, so a replica failing MID-REQUEST
  (connection refused/reset/EOF — classified via the resilience
  transient table) is retried on a DIFFERENT replica, bounded by the
  replica count; the client still receives exactly one response. A
  ``rejected: draining`` response is replica-local, not backpressure:
  it retries elsewhere too. Every OTHER rejection — admission sheds
  (``memory``/``queue_full``/``injected_squeeze``), shape/k caps — is
  the fleet's explicit backpressure signal and propagates to the
  client UNRETRIED (re-offering shed load elsewhere would defeat
  admission control under correlated pressure).
- **Ingest** fans out to EVERY live replica (all serve the same
  corpus; a partial ingest would fork the fleet's corpus, so any
  failure reports which replicas diverged — never silently retried:
  ingest is not idempotent).
- **stats** aggregates per-replica stats with the router's own
  counters; **drain** propagates to every replica, then drains the
  router itself (rc 0 — the smoke's drain contract).

Health: a background prober calls each replica's ``stats`` op on an
interval; request-path failures mark a replica down immediately, and a
marked-down replica rejoins only after ``revive_probes`` CONSECUTIVE
healthy probes (revive hysteresis — a flapping replica used to rejoin
on its first good probe and eat a retry budget per flap). The prober
also compares the replicas' corpus signatures (rows + rolling
checksum, exposed in ``stats``) and, on divergence observed across
consecutive probe rounds, drives the checksum-driven consistency
repair (``fleet/consistency.py``): targeted re-ingest of the delta
into the lagging replica, with unrepairable divergence escalating to
QUARANTINE (marked down, never revived —
``fleet.consistency.{divergences,repairs,unrepairable}`` counters).

The replica table is DYNAMIC: :meth:`FleetRouter.add_replica` /
:meth:`FleetRouter.remove_replica` let the auto-scaling supervisor
(``fleet/autoscale.py``) and the re-shard choreography
(``fleet/reshard.py``) grow, shrink, and atomically swap entries while
traffic flows. Replica connections are PER-REQUEST (no shared
sockets), so no thread ever blocks on I/O while holding a lock — check
rule R703 stays clean by construction.
"""

from __future__ import annotations

import json
import socket
import socketserver
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from dmlp_tpu.obs import telemetry
from dmlp_tpu.obs import trace as obs_trace
from dmlp_tpu.obs.trace import span as obs_span
from dmlp_tpu.resilience.retry import classify

#: request-line cap mirrored from the daemon protocol
from dmlp_tpu.serve.protocol import MAX_LINE_BYTES, encode


class Replica:
    """One backend daemon endpoint + its guarded health/load state.

    I/O is connection-per-call: ``call`` opens a fresh socket, sends
    one line, reads one line — never under any lock (leaf ``_lock``
    guards pure state only)."""

    def __init__(self, host: str, port: int,
                 scrape_port: Optional[int] = None, index: int = 0,
                 revive_probes: int = 1):
        self.host, self.port = host, int(port)
        self.scrape_port = scrape_port
        self.index = index
        self.name = f"{host}:{port}"
        self.revive_probes = max(int(revive_probes), 1)
        self._lock = threading.Lock()
        self._healthy = True
        self._draining = False
        self._force_drain = False      # router-side freeze: sticky
        #                                against probe updates
        self._quarantined = False
        self._streak = 0               # consecutive healthy probes
        self._down_since: Optional[float] = None
        self._inflight = 0
        self._requests = 0
        self._failures = 0
        self._last_error: Optional[str] = None
        #: last probed corpus signature ({rows, checksum, epoch}) and
        #: engine capacity — the consistency/re-shard inputs
        self.last_corpus: Optional[Dict[str, int]] = None
        self.capacity_rows: Optional[int] = None

    # -- guarded state ---------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"replica": self.name, "healthy": self._healthy,
                    "draining": self._draining,
                    "quarantined": self._quarantined,
                    "inflight": self._inflight,
                    "requests": self._requests,
                    "failures": self._failures,
                    "last_error": self._last_error,
                    "corpus": dict(self.last_corpus)
                    if self.last_corpus else None,
                    "capacity_rows": self.capacity_rows}

    def mark(self, healthy: Optional[bool] = None,
             draining: Optional[bool] = None,
             error: Optional[str] = None) -> None:
        with self._lock:
            if healthy is not None:
                self._healthy = healthy
                self._streak = 0       # request-path verdicts reset
                #                        the revive hysteresis either way
                self._down_since = None if healthy else (
                    self._down_since or time.monotonic())
            if draining is not None:
                # An explicit mark is the ROUTER's decision (re-shard
                # freeze, drain propagation) and must survive probe
                # refreshes — the probed replica's own admission state
                # says nothing about a router-side freeze.
                self._draining = draining
                self._force_drain = draining
            if error is not None:
                self._last_error = error
                self._failures += 1

    def probe_ok(self, draining: bool = False,
                 corpus: Optional[Dict[str, int]] = None,
                 capacity_rows: Optional[int] = None) -> None:
        """One healthy probe. A marked-down replica needs
        ``revive_probes`` CONSECUTIVE healthy probes before it routes
        again — a flapping replica must not rejoin on its first good
        answer and eat a retry budget per flap. Quarantine never
        revives (the consistency escalation is terminal), and a
        router-side drain freeze (``mark(draining=True)``) is sticky —
        the probed daemon's admission state cannot un-freeze it."""
        with self._lock:
            self._draining = draining or self._force_drain
            if corpus is not None:
                self.last_corpus = corpus
            if capacity_rows is not None:
                self.capacity_rows = int(capacity_rows)
            if self._quarantined:
                return
            if not self._healthy:
                self._streak += 1
                if self._streak >= self.revive_probes:
                    self._healthy = True
                    self._streak = 0
                    self._down_since = None
            else:
                self._down_since = None

    def probe_fail(self, error: str) -> None:
        with self._lock:
            self._healthy = False
            self._streak = 0
            self._down_since = self._down_since or time.monotonic()
            self._last_error = error
            self._failures += 1

    def quarantine(self, reason: str) -> None:
        """Terminal mark-down: unrepairable divergence. The prober
        keeps probing but never revives a quarantined replica."""
        with self._lock:
            self._quarantined = True
            self._healthy = False
            self._streak = 0
            self._last_error = f"quarantined: {reason}"

    def down_for(self) -> float:
        """Seconds this replica has been continuously marked down
        (0 while healthy) — the supervisor's hung-replica deadline."""
        with self._lock:
            if self._down_since is None:
                return 0.0
            return max(time.monotonic() - self._down_since, 0.0)

    def available(self) -> bool:
        with self._lock:
            return (self._healthy and not self._draining
                    and not self._quarantined)

    def load(self) -> int:
        with self._lock:
            return self._inflight

    def _begin(self, probe: bool) -> None:
        with self._lock:
            self._inflight += 1
            if not probe:
                self._requests += 1

    def _end(self) -> None:
        with self._lock:
            self._inflight -= 1

    # -- the wire --------------------------------------------------------------

    def call(self, line: bytes, timeout_s: float = 600.0,
             probe: bool = False) -> bytes:
        """One request line -> the replica's raw response line. Raises
        OSError/ConnectionError on transport failure (the router
        classifies and retries); inflight accounting brackets the call
        so least-loaded picking sees in-progress work. ``probe=True``
        (health probes, drain propagation) keeps the per-replica
        ``requests`` stat CLIENT traffic only — a replica that served
        nothing must not look busy because the prober pinged it."""
        self._begin(probe)
        try:
            with socket.create_connection((self.host, self.port),
                                          timeout=timeout_s) as sock:
                sock.sendall(line)
                with sock.makefile("rb") as rf:
                    resp = rf.readline(MAX_LINE_BYTES + 1)
            if not resp:
                raise ConnectionError(
                    f"replica {self.name} closed the connection "
                    "mid-request")
            return resp
        finally:
            self._end()


class _RouterHandler(socketserver.StreamRequestHandler):
    """One client connection: requests answered strictly in line order
    (mirrors the daemon handler's framing and size-cap discipline)."""

    def handle(self):  # noqa: D102 (socketserver API)
        router: FleetRouter = self.server.router
        while True:
            raw = self.rfile.readline(MAX_LINE_BYTES + 1)
            if not raw:
                break
            if len(raw) > MAX_LINE_BYTES:
                self.wfile.write(encode(
                    {"ok": False,
                     "error": "request line exceeds the size cap"}))
                break
            if not raw.strip():
                continue
            router._track_inflight(+1)
            try:
                resp_line, closing = router.handle_line(raw)
                self.wfile.write(resp_line)
                self.wfile.flush()
            finally:
                router._track_inflight(-1)
            if closing:
                break


class _Server(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True


class FleetRouter:
    """Lifecycle owner: replica table + health prober + TCP front end
    + aggregated telemetry endpoint."""

    def __init__(self, replicas: List[Tuple[str, int]],
                 scrape_ports: Optional[List[Optional[int]]] = None,
                 port: int = 0, health_interval_s: float = 1.0,
                 request_timeout_s: float = 600.0,
                 telemetry_port: Optional[int] = None,
                 revive_probes: int = 1, repair: bool = True,
                 divergence_probes: int = 2,
                 allow_empty: bool = False,
                 trace_path: Optional[str] = None,
                 objectives: Optional[List[Any]] = None,
                 slo_trend_metrics: Optional[List[str]] = None):
        scrape_ports = scrape_ports or [None] * len(replicas)
        if len(scrape_ports) != len(replicas):
            raise ValueError("one scrape port per replica (or none)")
        if not replicas and not allow_empty:
            raise ValueError("a fleet needs at least one replica "
                             "(allow_empty is the supervised-spawn "
                             "bootstrap only)")
        # The registry is process-global but stats() divides by THIS
        # router's lifetime: zero the fleet.* counters so a second
        # router in one process (tests, embedders) doesn't inherit the
        # first one's retries/rejections — same discipline as the
        # daemon's serve.* reset.
        telemetry.registry().reset(prefix="fleet")
        self.revive_probes = max(int(revive_probes), 1)
        self.repair = bool(repair)
        self.divergence_probes = max(int(divergence_probes), 1)
        # The replica TABLE is mutable (autoscale/reshard add, remove,
        # and swap entries live); mutations run under _lock, iteration
        # sites take a list() snapshot (atomic under the GIL) and never
        # hold the lock across I/O.
        self.replicas = [Replica(h, p, scrape_port=sp, index=i,
                                 revive_probes=self.revive_probes)
                         for i, ((h, p), sp)
                         in enumerate(zip(replicas, scrape_ports))]
        self._next_index = len(self.replicas)
        #: optional fleet supervisor (autoscale.FleetSupervisor sets
        #: itself here so stats() can expose its snapshot)
        self.supervisor = None
        self.request_timeout_s = request_timeout_s
        self.health_interval_s = health_interval_s
        self._lock = threading.Lock()     # guards _rr + _draining +
        #                                   replica-table mutations only
        self._rr = 0
        self._draining = False
        self._div_streak = 0              # health-thread-local state
        self._scrape_cache = None         # lazy fleet.scrape.ScrapeCache
        self._drain_event = threading.Event()
        self._inflight = 0
        self._inflight_cond = threading.Condition()
        self._stop_health = threading.Event()
        self._health_thread: Optional[threading.Thread] = None
        self._server = _Server(("127.0.0.1", port), _RouterHandler)
        self._server.router = self
        self.port = self._server.server_address[1]
        self._server_thread: Optional[threading.Thread] = None
        self._telemetry_port = telemetry_port
        self._telemetry_httpd = None
        self._t_ready: Optional[float] = None
        # Request tracing opt-in (same contract as the daemon's):
        # process-wide Tracer + the clock-sync marker the fleet merge
        # aligns on; written at drain/close.
        self.trace_path = trace_path
        self._tracer = None
        if trace_path:
            self._tracer = obs_trace.install(obs_trace.Tracer())
            self._tracer.sync_instant("fleet.clock_sync")
        # Fleet-level SLO objectives (obs.slo): latency objectives read
        # the router's own end-to-end fleet.request_latency_ms windowed
        # histogram; availability objectives sample good/total counters
        # from the MERGED replica scrape (the fleet served what its
        # replicas served). Evaluated on the health thread's cadence.
        self.slo = None
        if objectives:
            from dmlp_tpu.obs import slo as obs_slo
            objs: List[Any] = []
            for spec in objectives:
                obj = obs_slo.parse_objective(spec) \
                    if isinstance(spec, str) else spec
                if obj.kind == "availability" \
                        and obj.sample_fn is None:
                    obj.sample_fn = self._scrape_availability(obj)
                objs.append(obj)
            self.slo = obs_slo.SLOEvaluator(
                objs, telemetry.REGISTRY,
                trend_metrics=list(slo_trend_metrics or []))

    # -- the dynamic replica table ---------------------------------------------

    def replica_list(self) -> List[Replica]:
        """Snapshot of the live table (iteration never holds _lock)."""
        return list(self.replicas)

    def find_replica(self, name: str) -> Optional[Replica]:
        return next((r for r in self.replica_list() if r.name == name),
                    None)

    def add_replica(self, host: str, port: int,
                    scrape_port: Optional[int] = None) -> Replica:
        """Register a new backend (autoscale scale-up / re-shard swap-
        in). Probed once BEFORE it enters the table so the first client
        request never lands on a replica we have not seen answer."""
        rep = Replica(host, port, scrape_port=scrape_port,
                      revive_probes=self.revive_probes)
        self._probe(rep)
        with self._lock:
            rep.index = self._next_index
            self._next_index += 1
            self.replicas.append(rep)
        telemetry.registry().counter("fleet.scale.table").inc(
            label="add")
        return rep

    def remove_replica(self, name: str,
                       drain: bool = False) -> Optional[Replica]:
        """Drop a backend from the table (scale-down retire / crash /
        re-shard swap-out); ``drain=True`` also sends the in-band drain
        op (the daemon finishes queued work and exits 0 — the caller
        owns waiting on the process). In-flight relays complete on
        their own per-request connections either way."""
        with self._lock:
            rep = next((r for r in self.replicas if r.name == name),
                       None)
            if rep is not None:
                self.replicas.remove(rep)
        if rep is None:
            return None
        rep.mark(draining=True)
        if drain:
            try:
                rep.call(b'{"op": "drain"}\n', timeout_s=30.0,
                         probe=True)
            except OSError:
                pass   # already gone: that IS drained
        if self._scrape_cache is not None:
            self._scrape_cache.forget(rep.name)
        # The freshness gauges describe live table entries: a retired
        # replica's labels must leave the merged exposition too, or a
        # long-lived supervised fleet accumulates one dead label pair
        # per retirement.
        reg = telemetry.registry()
        reg.gauge("fleet.replica_scrape_age_s").remove(rep.name)
        reg.gauge("fleet.replica_scrape_stale").remove(rep.name)
        reg.counter("fleet.scale.table").inc(label="remove")
        return rep

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> None:
        self._probe_all()
        stop = self._stop_health
        self._health_thread = threading.Thread(
            target=self._health_loop, args=(stop,), name="fleet-health",
            daemon=True)
        self._health_thread.start()
        self._server_thread = threading.Thread(
            target=self._server.serve_forever, name="fleet-accept",
            daemon=True)
        self._server_thread.start()
        if self._telemetry_port is not None:
            self._start_telemetry_http(self._telemetry_port)
        self._t_ready = time.monotonic()
        telemetry.registry().gauge("fleet.ready").set(1)

    def run_until_drained(self) -> None:
        while not self._drain_event.wait(timeout=0.2):
            pass
        self.drain()

    def request_drain(self) -> None:
        self._drain_event.set()

    def drain(self, propagate: bool = True) -> None:
        """Shed new work, propagate the drain to every replica, wait
        for in-flight relays to finish, close. rc 0 — not a crash."""
        with self._lock:
            self._draining = True
        telemetry.registry().gauge("fleet.ready").set(0)
        if propagate:
            for rep in self.replica_list():
                try:
                    rep.call(b'{"op": "drain"}\n', timeout_s=30.0,
                             probe=True)
                except OSError:
                    pass   # already gone: that IS drained
                rep.mark(draining=True)
        # shutdown() blocks on serve_forever's ack — which never comes
        # if start() was never called (embedders, tests): skip it then.
        if self._server_thread is not None:
            self._server.shutdown()
        self._wait_inflight_drained()
        self._stop_health.set()
        self._write_trace()
        if self._telemetry_httpd is not None:
            self._telemetry_httpd.shutdown()
        self._server.server_close()

    def _write_trace(self) -> None:
        if self._tracer is None:
            return
        try:
            self._tracer.write(self.trace_path,
                               process_name=f"router:{self.port}")
        except Exception:  # check: no-retry — traces never kill a drain
            pass
        if obs_trace.active() is self._tracer:
            obs_trace.uninstall()
        self._tracer = None

    def close(self) -> None:
        """Abrupt teardown for tests (no drain propagation)."""
        with self._lock:
            self._draining = True
        self._drain_event.set()
        self._stop_health.set()
        if self._server_thread is not None:
            self._server.shutdown()
        self._write_trace()
        if self._telemetry_httpd is not None:
            self._telemetry_httpd.shutdown()
        self._server.server_close()

    def _track_inflight(self, delta: int) -> None:
        with self._inflight_cond:
            self._inflight += delta
            if self._inflight <= 0:
                self._inflight_cond.notify_all()

    def _wait_inflight_drained(self, timeout: float = 30.0) -> None:
        deadline = time.monotonic() + timeout
        with self._inflight_cond:
            while self._inflight > 0:
                left = deadline - time.monotonic()
                if left <= 0:
                    return
                self._inflight_cond.wait(timeout=left)

    # -- health ----------------------------------------------------------------

    def _probe(self, rep: Replica) -> None:
        try:
            raw = rep.call(b'{"op": "stats"}\n', timeout_s=10.0,
                           probe=True)
            doc = json.loads(raw)
            st = doc.get("stats", {}) if isinstance(doc, dict) else {}
            draining = bool(st.get("admission", {}).get("draining"))
            corpus = st.get("corpus")
            cap = st.get("engine", {}).get("capacity_rows")
            rep.probe_ok(draining=draining,
                         corpus=corpus if isinstance(corpus, dict)
                         else None,
                         capacity_rows=cap if isinstance(cap, int)
                         else None)
        except (OSError, ValueError) as e:
            rep.probe_fail(f"probe: {e}")

    def _probe_all(self) -> None:
        reps = self.replica_list()
        for rep in reps:
            self._probe(rep)
        telemetry.registry().gauge("fleet.replicas_healthy").set(
            sum(1 for r in reps if r.available()))
        if self.repair:
            self._consistency_tick()

    def _consistency_tick(self) -> None:
        """Compare the probed corpus signatures; on divergence seen on
        ``divergence_probes`` CONSECUTIVE rounds (one round can catch a
        fan-out mid-flight — replicas legitimately disagree for the
        milliseconds between sequential ingests), drive the targeted
        delta re-ingest; unrepairable divergence quarantines. Runs on
        the health thread, no locks held across the repair I/O."""
        from dmlp_tpu.fleet import consistency as ccs
        reg = telemetry.registry()
        sigs = [(r, r.last_corpus) for r in self.replica_list()
                if r.available() and r.last_corpus]
        if len(sigs) < 2:
            self._div_streak = 0
            return
        verdict = ccs.diagnose([(r.name, sig) for r, sig in sigs])
        if verdict is None:
            self._div_streak = 0
            return
        self._div_streak += 1
        if self._div_streak < self.divergence_probes:
            return
        self._div_streak = 0
        reg.counter("fleet.consistency.divergences").inc()
        by_name = {r.name: r for r, _sig in sigs}
        ref = by_name.get(verdict["reference"])
        if ref is None:
            return
        from dmlp_tpu.obs.trace import instant as obs_instant
        obs_instant("fleet.consistency.divergence",
                    reference=verdict["reference"],
                    rows=verdict["rows"],
                    divergent=",".join(verdict["divergent"]))
        for name in verdict["divergent"]:
            tgt = by_name.get(name)
            if tgt is None:
                continue
            res = ccs.repair_replica(ref, tgt)
            if res["repaired"]:
                reg.counter("fleet.consistency.repairs").inc()
                reg.counter("fleet.consistency.repaired_rows").inc(
                    res["replayed_rows"])
                obs_instant("fleet.consistency.repair", replica=name,
                            rows=res["replayed_rows"],
                            rounds=res["rounds"])
            else:
                # Escalation: a replica the repair cannot converge is
                # marked down FOR GOOD — serving two truths is the one
                # failure byte-identity cannot absorb.
                reg.counter("fleet.consistency.unrepairable").inc()
                tgt.quarantine(res.get("reason", "divergence"))
                telemetry.flight_event(
                    "fleet.consistency.unrepairable", replica=name,
                    reason=res.get("reason", ""))

    def _scrape_availability(self, obj):
        """Cumulative (good, total) for one availability objective,
        summed across every replica via the merged fleet scrape."""
        from dmlp_tpu.fleet import scrape as fscrape

        def sample():
            parsed = fscrape.parse_exposition(self.fleet_metrics_text())
            return (fscrape.counter_total(parsed, obj.good),
                    fscrape.counter_total(parsed, obj.total))

        return sample

    def _health_loop(self, stop: threading.Event) -> None:
        while not stop.wait(timeout=self.health_interval_s):
            self._probe_all()
            if self.slo is not None:
                try:
                    self.slo.tick()
                except Exception:  # check: no-retry — a failing SLO
                    pass           # tick must not stop health probing

    # -- routing ---------------------------------------------------------------

    def _pick(self, exclude) -> Optional[Replica]:
        """Least-inflight available replica, round-robin on ties."""
        avail = [r for r in self.replica_list()
                 if r not in exclude and r.available()]
        if not avail:
            return None
        with self._lock:
            self._rr += 1
            rr = self._rr
        return min(avail,
                   key=lambda r: (r.load(), (r.index - rr) % 1009))

    def _draining_now(self) -> bool:
        with self._lock:
            return self._draining

    def handle_line(self, raw: bytes) -> Tuple[bytes, bool]:
        """One client line -> (response line, close-connection?)."""
        reg = telemetry.registry()
        t0 = time.monotonic()
        rid = ""
        try:
            obj = json.loads(raw)
            op = obj.get("op", "query") if isinstance(obj, dict) \
                else "invalid"
            if isinstance(obj, dict):
                rid = str(obj.get("rid", "") or "")
        except ValueError:
            op = "query"    # let a daemon produce the protocol error
        rargs = {"rid": rid} if rid else {}
        reg.counter("fleet.requests").inc(label=str(op))
        if op == "stats":
            return encode({"ok": True, "stats": self.stats()}), False
        if op == "drain":
            self._drain_event.set()
            return encode({"ok": True, "draining": True}), True
        if self._draining_now():
            reg.counter("fleet.rejected").inc(label="draining")
            # A rejected request still gets its terminal router span —
            # the merged causal tree must explain every rid, shed ones
            # included.
            with obs_span("fleet.route", op=str(op),
                          outcome="rejected_draining", **rargs):
                pass
            return encode({"ok": False, "error": "rejected: draining",
                           "draining": True}), True
        with obs_span("fleet.route", op=str(op), **rargs) as sp:
            if op == "ingest":
                resp = self._route_ingest(raw, rid)
                sp.set(outcome="done")
            else:
                resp, hops, outcome = self._route_query(raw, rid)
                sp.set(outcome=outcome, hops=hops)
        reg.histogram("fleet.request_latency_ms", unit="ms").observe(
            (time.monotonic() - t0) * 1e3, exemplar=rid or None)
        return resp, False

    def _route_query(self, raw: bytes,
                     rid: str = "") -> Tuple[bytes, int, str]:
        """Bounded retry-on-replica-failure: transport failures and
        replica-local draining rejections move on to the next replica
        (queries are idempotent reads — exactly one response either
        way); everything else relays verbatim. Returns (response line,
        hops, outcome): ``hops`` counts replica attempts, recorded in
        the ``fleet.retry_hops`` histogram and — for retried requests
        only, so the single-hop relay stays byte-verbatim — surfaced
        as ``"hops"`` in the response envelope (the re-encode is
        byte-stable: the daemon used the same sort_keys encoder)."""
        reg = telemetry.registry()
        tried: set = set()
        last_error = "no healthy replica"
        rargs = {"rid": rid} if rid else {}
        hops = 0
        for _attempt in range(max(len(self.replicas), 1)):
            rep = self._pick(tried)
            if rep is None:
                break
            tried.add(rep)
            hops += 1
            with obs_span("fleet.hop", attempt=hops, replica=rep.name,
                          **rargs) as hop:
                try:
                    resp = rep.call(raw,
                                    timeout_s=self.request_timeout_s)
                except OSError as e:
                    # The resilience classification decides
                    # retryability: connection refused/reset/EOF/
                    # timeouts all classify transient — mark the
                    # replica down (the prober revives it) and retry
                    # on a healthy one.
                    kind = classify(e)
                    rep.mark(healthy=False, error=str(e))
                    reg.counter("fleet.replica_failures").inc(
                        label=rep.name)
                    last_error = f"replica {rep.name}: {e}"
                    hop.set(outcome=f"error_{kind}")
                    if kind not in ("transient", "oom"):
                        break
                    reg.counter("fleet.retries").inc(label="failure")
                    continue
                try:
                    doc = json.loads(resp)
                except ValueError:
                    doc = {}
                err = str(doc.get("error", ""))
                if doc.get("ok") is False and "draining" in err:
                    # Replica-local shutdown, not fleet backpressure.
                    rep.mark(draining=True)
                    reg.counter("fleet.retries").inc(label="draining")
                    last_error = f"replica {rep.name}: draining"
                    hop.set(outcome="draining")
                    continue
                if doc.get("ok") is False and err.startswith("rejected"):
                    # Admission shed: the explicit backpressure signal,
                    # propagated unretried.
                    reg.counter("fleet.rejected").inc(label="admission")
                    outcome = "rejected_admission"
                else:
                    outcome = "ok" if doc.get("ok") else "relayed"
                hop.set(outcome=outcome)
            reg.histogram("fleet.retry_hops").observe(
                hops, exemplar=rid or None)
            if hops > 1 and doc:
                doc["hops"] = hops
                resp = encode(doc)
            return resp, hops, outcome
        reg.counter("fleet.rejected").inc(label="unavailable")
        reg.histogram("fleet.retry_hops").observe(
            max(hops, 1), exemplar=rid or None)
        out = {"ok": False, "error": f"rejected: {last_error}"}
        if hops > 1:
            out["hops"] = hops
        return encode(out), hops, "unavailable"

    def _route_ingest(self, raw: bytes, rid: str = "") -> bytes:
        """Fan-out to every available replica; ALL must accept (a
        partial ingest forks the fleet corpus — the response names the
        divergent replicas instead of hiding them)."""
        reg = telemetry.registry()
        targets = [r for r in self.replica_list() if r.available()]
        if not targets:
            reg.counter("fleet.rejected").inc(label="unavailable")
            return encode({"ok": False,
                           "error": "rejected: no healthy replica"})
        oks: List[bytes] = []
        failures: List[str] = []
        rargs = {"rid": rid} if rid else {}
        for rep in targets:
            with obs_span("fleet.hop", replica=rep.name, fanout=True,
                          **rargs) as hop:
                try:
                    resp = rep.call(raw,
                                    timeout_s=self.request_timeout_s)
                    doc = json.loads(resp)
                except (OSError, ValueError) as e:
                    rep.mark(healthy=False, error=str(e))
                    failures.append(f"{rep.name}: {e}")
                    hop.set(outcome="error_transport")
                    continue
                if doc.get("ok"):
                    oks.append(resp)
                    hop.set(outcome="ok")
                else:
                    failures.append(f"{rep.name}: {doc.get('error')}")
                    hop.set(outcome="error_replica")
        if failures or not oks:
            reg.counter("fleet.ingest_divergence").inc()
            return encode({"ok": False, "error":
                           "ingest diverged: " + "; ".join(failures),
                           "accepted_replicas": len(oks)})
        return oks[0]

    # -- stats + telemetry -----------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        reg = telemetry.registry()
        reps = self.replica_list()
        elapsed = (time.monotonic() - self._t_ready) \
            if self._t_ready else 0.0
        out: Dict[str, Any] = {
            "fleet": True,
            "replicas": [r.snapshot() for r in reps],
            "healthy_replicas": sum(1 for r in reps
                                    if r.available()),
            "draining": self._draining_now(),
            "uptime_s": round(elapsed, 3),
            "requests": reg.counter("fleet.requests").by_label(),
            "retries": reg.counter("fleet.retries").by_label(),
            "rejected": reg.counter("fleet.rejected").by_label(),
            "consistency": {
                "divergences": int(reg.counter(
                    "fleet.consistency.divergences").total()),
                "repairs": int(reg.counter(
                    "fleet.consistency.repairs").total()),
                "repaired_rows": int(reg.counter(
                    "fleet.consistency.repaired_rows").total()),
                "unrepairable": int(reg.counter(
                    "fleet.consistency.unrepairable").total()),
            },
            "scale": {
                "up": int(reg.counter("fleet.scale.up").total()),
                "down": int(reg.counter("fleet.scale.down").total()),
                "crashes": int(reg.counter(
                    "fleet.scale.crashes").total()),
                "relaunches": int(reg.counter(
                    "fleet.scale.relaunches").total()),
                "splits": int(reg.counter(
                    "fleet.reshard.splits").total()),
            },
        }
        if self.supervisor is not None:
            try:
                out["supervisor"] = self.supervisor.snapshot()
            except Exception:  # check: no-retry — stats never fail
                pass
        if self.slo is not None:
            try:
                out["slo"] = self.slo.snapshot()
            except Exception:  # check: no-retry — stats never fail
                pass
        h = reg.get("fleet.request_latency_ms")
        if h is not None and h.count:
            out["request_latency_ms"] = {
                "p50": round(h.quantile(0.5), 3),
                "p95": round(h.quantile(0.95), 3),
                "p99": round(h.quantile(0.99), 3),
                "count": h.count,
            }
        return out

    def fleet_metrics_text(self) -> str:
        """The aggregated fleet OpenMetrics view: every replica's live
        scrape (those with a scrape port) merged by fleet.scrape, plus
        the router's own registry as one more 'replica'. A replica
        whose live scrape fails keeps its LAST-GOOD exposition in the
        merge — stamped, never silent: the router publishes per-replica
        ``fleet_replica_scrape_age_s`` (0 when live) and
        ``fleet_replica_scrape_stale`` gauges alongside the merged
        counters, so a dashboard can tell fresh fleet totals from ones
        coasting on a cached scrape."""
        from dmlp_tpu.fleet import scrape as fscrape
        if self._scrape_cache is None:
            self._scrape_cache = fscrape.ScrapeCache()
        reg = telemetry.registry()
        texts: List[str] = []
        names: List[str] = []
        for rep in self.replica_list():
            if rep.scrape_port is None:
                continue
            text, age_s, stale = self._scrape_cache.fetch(
                rep.name,
                f"http://{rep.host}:{rep.scrape_port}/metrics")
            if text is None:
                continue   # never scraped: nothing to go stale
            reg.gauge("fleet.replica_scrape_age_s").set(
                round(age_s, 3), label=rep.name)
            reg.gauge("fleet.replica_scrape_stale").set(
                int(stale), label=rep.name)
            texts.append(text)
            names.append(rep.name)
        # The router's own registry is SNAPSHOTTED after the loop so
        # the freshness gauges just written land in this very
        # exposition (merge order is cosmetic).
        texts.insert(0, reg.to_openmetrics())
        names.insert(0, "router")
        merged, _problems = fscrape.merge_expositions(texts, names)
        return merged

    def _start_telemetry_http(self, port: int) -> None:
        import http.server

        router = self

        class _MetricsHandler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                if self.path.rstrip("/") not in ("", "/metrics"):
                    self.send_error(404)
                    return
                body = router.fleet_metrics_text().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "application/openmetrics-text")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # silence per-scrape stderr
                pass

        class _Httpd(http.server.ThreadingHTTPServer):
            daemon_threads = True

        self._telemetry_httpd = _Httpd(("127.0.0.1", port),
                                       _MetricsHandler)
        self.telemetry_port = self._telemetry_httpd.server_address[1]
        threading.Thread(target=self._telemetry_httpd.serve_forever,
                         name="fleet-metrics", daemon=True).start()
        telemetry.registry().gauge("fleet.telemetry_http_port").set(
            self.telemetry_port)
