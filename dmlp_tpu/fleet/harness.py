"""Fleet process harness — spawn/await/drain for replicas + router.

Shared by ``tools/fleet_smoke.py`` and ``tools/fleet_bench.py`` (the
same discipline serve.client's daemon-lifecycle helpers establish for
one daemon, extended to a fleet): every subprocess gets its own stderr
log, readiness is file-based, the per-replica telemetry HTTP port is
read back from the snapshot gauge, and teardown is SIGTERM-drain with
the rc-0 contract (kill only on timeout, loudly).
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Dict, List, Optional

from dmlp_tpu.serve import client as sc


class FleetProc:
    """One spawned fleet process + its artifacts."""

    def __init__(self, name: str, proc, ready_path: str, errlog: str,
                 telemetry_path: Optional[str] = None):
        self.name = name
        self.proc = proc
        self.ready_path = ready_path
        self.errlog = errlog
        self.telemetry_path = telemetry_path
        self.ready: Dict = {}
        self.scrape_port: Optional[int] = None


def _repo_env(extra: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=repo + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    env.update(extra or {})
    return env


def spawn_replica(corpus_path: str, out_dir: str, name: str,
                  warm_spec: str, batch_cap: int = 32,
                  flags: Optional[List[str]] = None,
                  env_extra: Optional[Dict[str, str]] = None,
                  record: Optional[str] = None,
                  compile_cache: Optional[str] = None) -> FleetProc:
    ready = os.path.join(out_dir, f"{name}_ready.json")
    telem = os.path.join(out_dir, f"{name}_telemetry.prom")
    errlog = os.path.join(out_dir, f"{name}.err")
    for stale in (ready, telem):
        if os.path.exists(stale):
            os.remove(stale)
    cmd = [sys.executable, "-m", "dmlp_tpu.serve",
           "--corpus", corpus_path, "--port", "0",
           "--ready-file", ready, "--warm-buckets", warm_spec,
           "--max-batch-queries", str(batch_cap),
           "--telemetry", telem, "--telemetry-port", "0",
           "--tick-ms", "2"] + (flags or [])
    if record:
        cmd += ["--record", record]
    if compile_cache:
        # First-class form; $DMLP_TPU_COMPILE_CACHE also rides the
        # inherited environment (_repo_env copies os.environ), so a
        # harness can warm a whole replica tree either way.
        cmd += ["--compile-cache", compile_cache]
    with open(errlog, "w") as ef:
        proc = subprocess.Popen(cmd, stderr=ef,
                                stdout=subprocess.DEVNULL,
                                env=_repo_env(env_extra), cwd=out_dir)
    return FleetProc(name, proc, ready, errlog, telemetry_path=telem)


def await_replica(fp: FleetProc, timeout_s: float = 600.0) -> Dict:
    """Block until the replica is ready AND its telemetry HTTP port is
    announced in the snapshot gauge (the router's scrape source)."""
    fp.ready = sc.await_ready(fp.proc, fp.ready_path,
                              timeout_s=timeout_s, errlog=fp.errlog)
    deadline = time.monotonic() + 60
    while fp.scrape_port is None:
        if os.path.exists(fp.telemetry_path):
            for ln in open(fp.telemetry_path).read().splitlines():
                if ln.startswith("telemetry_http_port"):
                    fp.scrape_port = int(float(ln.split()[-1]))
        if fp.scrape_port is None:
            if fp.proc.poll() is not None:
                raise RuntimeError(f"replica {fp.name} died before its "
                                   f"scrape port; see {fp.errlog}")
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"replica {fp.name}: no telemetry_http_port in "
                    f"{fp.telemetry_path}")
            time.sleep(0.1)
    return fp.ready


def spawn_router(out_dir: str, replicas: List[FleetProc],
                 record: Optional[str] = None,
                 flags: Optional[List[str]] = None) -> FleetProc:
    ready = os.path.join(out_dir, "router_ready.json")
    errlog = os.path.join(out_dir, "router.err")
    if os.path.exists(ready):
        os.remove(ready)
    endpoints = ",".join(f"127.0.0.1:{fp.ready['port']}"
                         for fp in replicas)
    scrapes = ",".join(str(fp.scrape_port) if fp.scrape_port else "-"
                       for fp in replicas)
    cmd = [sys.executable, "-m", "dmlp_tpu.fleet",
           "--replicas", endpoints, "--scrape-ports", scrapes,
           "--port", "0", "--ready-file", ready,
           "--telemetry-port", "0"] + (flags or [])
    if record:
        cmd += ["--record", record]
    with open(errlog, "w") as ef:
        proc = subprocess.Popen(cmd, stderr=ef,
                                stdout=subprocess.DEVNULL,
                                env=_repo_env(), cwd=out_dir)
    fp = FleetProc("router", proc, ready, errlog)
    fp.ready = sc.await_ready(proc, ready, timeout_s=120,
                              errlog=errlog)
    fp.scrape_port = fp.ready.get("telemetry_port")
    return fp


def drain_fleet(router: FleetProc, replicas: List[FleetProc],
                timeout_s: float = 120.0) -> None:
    """The orderly fleet shutdown: one in-band ``drain`` to the router
    propagates to every replica; ALL processes must exit 0."""
    cli = sc.ServeClient(router.ready["port"])
    try:
        cli.drain()
    finally:
        cli.close()
    for fp in replicas + [router]:
        rc = fp.proc.wait(timeout=timeout_s)
        if rc != 0:
            raise RuntimeError(
                f"{fp.name} drain exited {rc}; see {fp.errlog}")


def kill_all(procs: List[FleetProc]) -> None:
    for fp in procs:
        if fp.proc.poll() is None:
            fp.proc.kill()
            fp.proc.wait(timeout=30)
