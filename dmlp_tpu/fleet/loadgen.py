"""Open-loop SLO harness: p99 under OFFERED load, as a gated series.

Closed-loop replay (serve.client.replay) measures throughput at the
pace the daemon sets — useful, but it cannot say "at 2× today's load,
p99 is still X ms", because a closed-loop client slows down exactly
when the server does. This module drives
:func:`dmlp_tpu.serve.client.replay_open_loop` over a sweep of speed
multipliers of a paced trace and emits one ledger-gated RunRecord per
level (kind "fleet" -> ``fleet/<level>/<metric>`` series, gated by
``tools/perf_gate.py``): requests fire on the trace's schedule whether
or not earlier ones completed, so daemon-side queueing shows up in the
latency quantiles instead of silently stretching the experiment.

``reps >= 3`` gives each level's quantiles a real noise band in the
ledger (obs.ledger qualifies A/B comparisons on raw trial lists).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from dmlp_tpu.obs.run import RunRecord, current_device
from dmlp_tpu.serve import client as sc


def offered_qps(requests: List[Dict[str, Any]],
                speed: float = 1.0) -> Optional[float]:
    """The load a paced replay OFFERS: total queries over the trace's
    t_ms span (compressed by ``speed``), independent of how fast the
    daemon answers. None for an unpaced trace."""
    ts = [float(r.get("t_ms", 0)) for r in requests]
    span_s = (max(ts) / 1e3 / max(speed, 1e-9)) if ts else 0.0
    queries = sum(int(r["nq"]) for r in requests)
    if span_s <= 0:
        return None
    return round(queries / span_s, 3)


def run_level(port: int, header: Dict[str, Any],
              requests: List[Dict[str, Any]], speed: float,
              reps: int = 1) -> Dict[str, Any]:
    """``reps`` open-loop replays at one speed multiplier -> metrics:
    per-rep p50/p95/p99/max client latency (measured from the
    SCHEDULED fire time — queue delay included), dispatch lag, error
    and rejection counts, achieved vs offered qps. Scalar metrics are
    the median across reps; ``*_reps`` carry the raw per-rep lists so
    the ledger can qualify noise bands."""
    per_rep: Dict[str, List[float]] = {
        "p50_ms": [], "p95_ms": [], "p99_ms": [], "max_ms": [],
        "lag_p95_ms": [], "achieved_qps": []}
    errors = 0
    rejected = 0
    total = 0
    for _rep in range(max(reps, 1)):
        res = sc.replay_open_loop(port, header, requests, speed=speed)
        total += len(res)
        ok = [r for r in res if r.get("ok")]
        errors += sum(1 for r in res
                      if not r.get("ok")
                      and not str(r.get("error", "")).startswith(
                          "rejected"))
        rejected += sum(1 for r in res
                        if not r.get("ok")
                        and str(r.get("error", "")).startswith(
                            "rejected"))
        lat = np.asarray([r["client_ms"] for r in ok], np.float64)
        lag = np.asarray([r.get("lag_ms", 0.0) for r in res],
                         np.float64)
        if lat.size:
            per_rep["p50_ms"].append(float(np.percentile(lat, 50)))
            per_rep["p95_ms"].append(float(np.percentile(lat, 95)))
            per_rep["p99_ms"].append(float(np.percentile(lat, 99)))
            per_rep["max_ms"].append(float(lat.max()))
        if lag.size:
            per_rep["lag_p95_ms"].append(float(np.percentile(lag, 95)))
        # Achieved = completed queries over the wall span actually
        # taken (first scheduled fire to last completion).
        if ok:
            span_ms = max(float(r.get("t_ms", 0)) / max(speed, 1e-9)
                          + q["client_ms"]
                          for r, q in zip(requests, res) if q.get("ok"))
            done_q = sum(int(r["nq"]) for r, q in zip(requests, res)
                         if q.get("ok"))
            if span_ms > 0:
                per_rep["achieved_qps"].append(
                    round(done_q / (span_ms / 1e3), 3))
    metrics: Dict[str, Any] = {
        "requests": total, "errors": errors, "rejected": rejected,
    }
    for key, vals in per_rep.items():
        if not vals:
            continue
        metrics[key] = round(float(np.median(vals)), 3)
        if len(vals) > 1:
            metrics[f"{key}_reps"] = [round(v, 3) for v in vals]
    return metrics


def level_tag(speed: float) -> str:
    s = f"{speed:g}".replace(".", "p")
    return f"x{s}"


def run_levels(port: int, header: Dict[str, Any],
               requests: List[Dict[str, Any]],
               speeds: Sequence[float], reps: int = 1,
               replicas: int = 1, trace: str = "",
               tool: str = "dmlp_tpu.fleet.loadgen"
               ) -> List[RunRecord]:
    """The p99-vs-offered-load curve: one RunRecord per speed level,
    slowest level first (a warm daemon sees rising load, like
    production). Each record's config pins the level tag the ledger
    keys the series by (``fleet/x2/p99_ms``), the offered qps, and the
    fleet topology."""
    device = current_device()
    out: List[RunRecord] = []
    for speed in sorted(speeds):
        metrics = run_level(port, header, requests, speed, reps=reps)
        oq = offered_qps(requests, speed)
        if oq is not None:
            metrics["offered_qps"] = oq
        out.append(RunRecord(
            kind="fleet", tool=tool,
            config={"level": level_tag(speed), "speed": speed,
                    "replicas": replicas, "trace": trace,
                    "mode": "open_loop",
                    "requests_per_rep": len(requests), "reps": reps},
            metrics=metrics, device=device))
    return out
