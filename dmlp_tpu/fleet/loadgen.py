"""Open-loop SLO harness: p99 under OFFERED load, as a gated series.

Closed-loop replay (serve.client.replay) measures throughput at the
pace the daemon sets — useful, but it cannot say "at 2× today's load,
p99 is still X ms", because a closed-loop client slows down exactly
when the server does. This module drives
:func:`dmlp_tpu.serve.client.replay_open_loop` over a sweep of speed
multipliers of a paced trace and emits one ledger-gated RunRecord per
level (kind "fleet" -> ``fleet/<level>/<metric>`` series, gated by
``tools/perf_gate.py``): requests fire on the trace's schedule whether
or not earlier ones completed, so daemon-side queueing shows up in the
latency quantiles instead of silently stretching the experiment.

``reps >= 3`` gives each level's quantiles a real noise band in the
ledger (obs.ledger qualifies A/B comparisons on raw trial lists).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from dmlp_tpu.obs.run import RunRecord, current_device
from dmlp_tpu.serve import client as sc


def offered_qps(requests: List[Dict[str, Any]],
                speed: float = 1.0) -> Optional[float]:
    """The load a paced replay OFFERS: total queries over the trace's
    t_ms span (compressed by ``speed``), independent of how fast the
    daemon answers. None for an unpaced trace."""
    ts = [float(r.get("t_ms", 0)) for r in requests]
    span_s = (max(ts) / 1e3 / max(speed, 1e-9)) if ts else 0.0
    queries = sum(int(r["nq"]) for r in requests)
    if span_s <= 0:
        return None
    return round(queries / span_s, 3)


def run_level(port: int, header: Dict[str, Any],
              requests: List[Dict[str, Any]], speed: float,
              reps: int = 1) -> Dict[str, Any]:
    """``reps`` open-loop replays at one speed multiplier -> metrics:
    per-rep p50/p95/p99/max client latency (measured from the
    SCHEDULED fire time — queue delay included), dispatch lag, error
    and rejection counts, achieved vs offered qps. Scalar metrics are
    the median across reps; ``*_reps`` carry the raw per-rep lists so
    the ledger can qualify noise bands."""
    per_rep: Dict[str, List[float]] = {
        "p50_ms": [], "p95_ms": [], "p99_ms": [], "max_ms": [],
        "lag_p95_ms": [], "achieved_qps": []}
    errors = 0
    rejected = 0
    total = 0
    for _rep in range(max(reps, 1)):
        res = sc.replay_open_loop(port, header, requests, speed=speed)
        total += len(res)
        ok = [r for r in res if r.get("ok")]
        errors += sum(1 for r in res
                      if not r.get("ok")
                      and not str(r.get("error", "")).startswith(
                          "rejected"))
        rejected += sum(1 for r in res
                        if not r.get("ok")
                        and str(r.get("error", "")).startswith(
                            "rejected"))
        lat = np.asarray([r["client_ms"] for r in ok], np.float64)
        lag = np.asarray([r.get("lag_ms", 0.0) for r in res],
                         np.float64)
        if lat.size:
            per_rep["p50_ms"].append(float(np.percentile(lat, 50)))
            per_rep["p95_ms"].append(float(np.percentile(lat, 95)))
            per_rep["p99_ms"].append(float(np.percentile(lat, 99)))
            per_rep["max_ms"].append(float(lat.max()))
        if lag.size:
            per_rep["lag_p95_ms"].append(float(np.percentile(lag, 95)))
        # Achieved = completed queries over the wall span actually
        # taken (first scheduled fire to last completion).
        if ok:
            span_ms = max(float(r.get("t_ms", 0)) / max(speed, 1e-9)
                          + q["client_ms"]
                          for r, q in zip(requests, res) if q.get("ok"))
            done_q = sum(int(r["nq"]) for r, q in zip(requests, res)
                         if q.get("ok"))
            if span_ms > 0:
                per_rep["achieved_qps"].append(
                    round(done_q / (span_ms / 1e3), 3))
    metrics: Dict[str, Any] = {
        "requests": total, "errors": errors, "rejected": rejected,
    }
    for key, vals in per_rep.items():
        if not vals:
            continue
        metrics[key] = round(float(np.median(vals)), 3)
        if len(vals) > 1:
            metrics[f"{key}_reps"] = [round(v, 3) for v in vals]
    return metrics


def level_tag(speed: float) -> str:
    s = f"{speed:g}".replace(".", "p")
    return f"x{s}"


def run_levels(port: int, header: Dict[str, Any],
               requests: List[Dict[str, Any]],
               speeds: Sequence[float], reps: int = 1,
               replicas: int = 1, trace: str = "",
               tool: str = "dmlp_tpu.fleet.loadgen"
               ) -> List[RunRecord]:
    """The p99-vs-offered-load curve: one RunRecord per speed level,
    slowest level first (a warm daemon sees rising load, like
    production). Each record's config pins the level tag the ledger
    keys the series by (``fleet/x2/p99_ms``), the offered qps, and the
    fleet topology."""
    device = current_device()
    out: List[RunRecord] = []
    for speed in sorted(speeds):
        metrics = run_level(port, header, requests, speed, reps=reps)
        oq = offered_qps(requests, speed)
        if oq is not None:
            metrics["offered_qps"] = oq
        out.append(RunRecord(
            kind="fleet", tool=tool,
            config={"level": level_tag(speed), "speed": speed,
                    "replicas": replicas, "trace": trace,
                    "mode": "open_loop",
                    "requests_per_rep": len(requests), "reps": reps},
            metrics=metrics, device=device))
    return out


# -- the SLO ramp (predictive-vs-reactive A/B) --------------------------------

def _fleet_slo_stats(port: int) -> Dict[str, Any]:
    """One ``stats`` op against the front-end: the router's SLO
    snapshot + replica count, shape-normalized for the ramp record."""
    st = sc.ServeClient(port).stats().get("stats", {})
    out: Dict[str, Any] = {
        "replicas": st.get("healthy_replicas"),
        "objectives": {},
    }
    for name, o in (st.get("slo") or {}).get("objectives", {}).items():
        out["objectives"][name] = {
            "state": o.get("state"), "cycles": o.get("cycles", 0),
            "burn_fast": o.get("burn_fast", 0.0),
            "burn_slow": o.get("burn_slow", 0.0)}
    return out


def run_ramp(port: int, header: Dict[str, Any],
             requests: List[Dict[str, Any]],
             speeds: Sequence[float], *,
             settle_s: float = 0.0,
             stats_fn: Optional[Callable[[], Dict[str, Any]]] = None
             ) -> List[Dict[str, Any]]:
    """The escalating-load ramp the SLO engine is judged on: each
    speed level runs one open-loop replay (ASCENDING — the fleet sees
    load rise, which is what a leading autoscale signal must get ahead
    of), then the front-end's stats op is sampled for the SLO
    snapshot. ``settle_s`` idles between levels so a predictive
    scale-up spawned mid-level can become ready before the next step
    (the lead time the policy is buying). Returns one step dict per
    level: the replay metrics plus ``slo`` (per-objective state /
    burn / completed alert cycles) and the live replica count."""
    steps: List[Dict[str, Any]] = []
    for speed in list(speeds):
        metrics = run_level(port, header, requests, speed, reps=1)
        oq = offered_qps(requests, speed)
        if oq is not None:
            metrics["offered_qps"] = oq
        step: Dict[str, Any] = {"speed": speed,
                                "level": level_tag(speed),
                                "metrics": metrics}
        try:
            step["slo"] = (stats_fn or
                           (lambda: _fleet_slo_stats(port)))()
        except Exception as e:  # check: no-retry — a stats blip must
            # not abort the ramp mid-experiment
            step["slo"] = {"error": f"{type(e).__name__}: {e}"}
        steps.append(step)
        if settle_s > 0:
            import time
            time.sleep(settle_s)
    return steps


def ramp_record(arm: str, objective: str,
                steps: List[Dict[str, Any]], *,
                replicas: int = 1, trace: str = "",
                tool: str = "dmlp_tpu.fleet.loadgen") -> RunRecord:
    """One kind="slo" RunRecord summarizing a ramp arm (ledger series
    ``slo/<arm>/<metric>``, gated by ``tools/perf_gate.py``). The A/B
    contract the smoke asserts lives in these metrics: the predictive
    arm's ``breach_cycles`` stays 0 (and ``max_burn_fast`` <= 1)
    at ramp levels where the reactive arm's breach fires."""
    peak = steps[-1]["metrics"] if steps else {}
    max_burn_fast = 0.0
    max_burn_slow = 0.0
    breach_cycles = 0
    worst = 0                     # 0 ok / 1 pending / 2 firing
    replicas_final = replicas
    for step in steps:
        slo = step.get("slo") or {}
        if slo.get("replicas"):
            replicas_final = int(slo["replicas"])
        # Burn maxima are scoped to the DECLARED objective: a canary
        # objective the predictive policy follows is EXPECTED to burn
        # (that is the lead it buys) and must not pollute the gated
        # customer-objective series.
        target = (slo.get("objectives") or {}).get(objective, {})
        max_burn_fast = max(max_burn_fast,
                            float(target.get("burn_fast", 0.0)))
        max_burn_slow = max(max_burn_slow,
                            float(target.get("burn_slow", 0.0)))
        breach_cycles = max(breach_cycles,
                            int(target.get("cycles", 0)))
        state = str(target.get("state", "ok"))
        worst = max(worst, {"ok": 0, "pending": 1,
                            "firing": 2}.get(state, 0))
        if state == "firing":
            breach_cycles = max(breach_cycles, 1)
    metrics: Dict[str, Any] = {
        "levels": len(steps),
        "breach_cycles": breach_cycles,
        "worst_state_level": worst,
        "max_burn_fast": round(max_burn_fast, 4),
        "max_burn_slow": round(max_burn_slow, 4),
        "replicas_final": replicas_final,
    }
    for key in ("p99_ms", "p95_ms", "p50_ms", "offered_qps",
                "achieved_qps"):
        if key in peak:
            metrics[f"peak_{key}"] = peak[key]
    errors = sum(int(s["metrics"].get("errors", 0)) for s in steps)
    rejected = sum(int(s["metrics"].get("rejected", 0)) for s in steps)
    metrics["errors"] = errors
    metrics["rejected"] = rejected
    return RunRecord(
        kind="slo", tool=tool,
        config={"arm": arm, "objective": objective, "mode": "ramp",
                "levels": [s["level"] for s in steps],
                "replicas": replicas, "trace": trace},
        metrics=metrics, device=current_device())


def main(argv: Optional[Sequence[str]] = None) -> int:
    """``python -m dmlp_tpu.fleet.loadgen`` — drive one arm of the
    ramp against a running front-end and append its kind="slo"
    RunRecord (``slo/<arm>/...`` ledger series)."""
    import argparse
    import json
    import sys
    p = argparse.ArgumentParser(prog="dmlp_tpu.fleet.loadgen")
    p.add_argument("--port", type=int, required=True,
                   help="front-end (or daemon) port to drive")
    p.add_argument("--trace", required=True,
                   help="paced replay trace (serve.client.load_trace)")
    p.add_argument("--ramp", required=True, metavar="S,S,S",
                   help="ascending speed multipliers, e.g. 1,2,4")
    p.add_argument("--arm", required=True,
                   help="A/B arm tag recorded in the slo/ series "
                        "(e.g. predictive, reactive)")
    p.add_argument("--objective", required=True, metavar="ID",
                   help="objective id the ramp verdict keys on")
    p.add_argument("--record", default=None, metavar="FILE",
                   help="append the arm's kind=slo RunRecord here")
    p.add_argument("--settle-s", type=float, default=0.0)
    p.add_argument("--replicas", type=int, default=1)
    args = p.parse_args(argv)
    header, reqs = sc.load_trace(args.trace)
    speeds = [float(s) for s in args.ramp.split(",") if s.strip()]
    steps = run_ramp(args.port, header, reqs, speeds,
                     settle_s=args.settle_s)
    rec = ramp_record(args.arm, args.objective, steps,
                      replicas=args.replicas, trace=args.trace)
    if args.record:
        rec.append_jsonl(args.record)
    json.dump({"arm": args.arm, "metrics": rec.metrics},
              sys.stdout, indent=1, sort_keys=True)
    sys.stdout.write("\n")
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
