"""Mesh-resident serving engine: the sharded corpus held resident.

:class:`MeshResidentEngine` is the fleet's corpus-scale pillar — the
serving counterpart of the batch mesh engines the way
:class:`~dmlp_tpu.serve.engine.ResidentEngine` is the serving
counterpart of the single-chip engine. It differs from a per-request
:class:`~dmlp_tpu.engine.sharded.ShardedEngine` solve in exactly the
ways a persistent multi-chip server needs:

- **Per-shard resident chunk buffers.** The corpus is staged ONCE at
  construction into the chunk layout the mesh chunk-fold programs
  consume: chunk ``t`` is one ``(R * chunk_rows, A)`` device array
  sharded ``P("data", None)`` holding every shard's ``t``-th piece,
  padded to a power-of-two capacity. Global row ids stay the affine
  ``rr * shard_rows + toff + j`` the fold programs derive on device
  from the ``[n, toff, shard_rows]`` scalar — ``n`` is DATA, so the
  corpus can grow without recompiling any solve program.
- **The merge collective as the micro-batch epilogue.** Every
  coalesced micro-batch runs the per-chunk fold over the resident
  buffers and then the engines' existing allgather/ring candidate
  merge (``_chunk_merge_fn``) — followed by the unchanged host
  float64 finalize + boundary-hazard repair, so every served response
  is byte-identical to the solo solve over the same corpus AND the
  golden oracle.
- **Resident per-(shard, chunk) summaries.** The pruned two-stage
  solve's block summaries (PR 13) are built once over the shard-local
  chunk ranges and kept resident; each micro-batch scores them
  (host-side — the summaries are O(blocks * a)) into per-chunk live
  masks, so chunks every shard pruned are never dispatched at all.
  Ingest rebuilds exactly the touched blocks' summaries.
- **Shard-routed ingest.** Appended rows land at their global row
  positions — i.e. in the owning shard's span of the touched chunk
  buffers — via a full restage of exactly those fixed-shape chunk
  arrays (data inputs, never shapes: zero solve recompilation,
  asserted by the compile counter like the single-chip resident
  engine).

Configs whose plan does not select the extraction kernel fall back to
a resident MONOLITHIC layout: the full capacity-padded
``(R * shard_rows, A)`` dataset + label/id arrays staged once, solved
by the engines' merged ``_fn`` program (the allgather/ring merge runs
inside it). Both layouts share the one global-row-id contract.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from dmlp_tpu.config import EngineConfig
from dmlp_tpu.engine.finalize import (boundary_overflow, finalize_host,
                                      lowp_eps, repair_boundary_overflow,
                                      staging_eps)
from dmlp_tpu.engine.sharded import ShardedEngine, _np_staging_dtype
from dmlp_tpu.engine.single import (_BF16_AUTO_K_CAP, ChunkThrottle,
                                    MeasuredIters, flush_measured_iters,
                                    plan_chunks, resilient_get, resolve_kcap,
                                    round_up)
from dmlp_tpu.io.grammar import KNNInput, Params
from dmlp_tpu.io.report import QueryResult
from dmlp_tpu.obs import counters as obs_counters
from dmlp_tpu.obs import memwatch, telemetry
from dmlp_tpu.obs.comms import engine_comms
from dmlp_tpu.obs.trace import span as obs_span
from dmlp_tpu.parallel.mesh import DATA_AXIS, QUERY_AXIS, make_mesh
from dmlp_tpu.serve.engine import (CapacityError, ResidentServingCore,
                                   k_bucket, query_bucket)
from dmlp_tpu.tune.cache import shape_bucket


class _MeshBucket:
    """One (qpad, k-bucket) shape bucket of the mesh-resident engine:
    the resolved candidate width and the chosen path ("extract" folds
    the resident chunks, "stream" runs the monolithic merged program)."""

    __slots__ = ("qpad", "kb", "kcap", "path", "qloc")

    def __init__(self, qpad: int, kb: int, kcap: int, path: str,
                 qloc: int):
        self.qpad, self.kb, self.kcap = qpad, kb, kcap
        self.path = path
        self.qloc = qloc

    @property
    def key(self) -> str:
        return f"q{self.qpad}k{self.kb}"


class MeshResidentEngine(ResidentServingCore, ShardedEngine):
    """Compile-once mesh-resident engine for the serving daemon.

    Drop-in for :class:`~dmlp_tpu.serve.engine.ResidentEngine` behind
    the daemon's batcher/admission surface (``solve_batch``/``ingest``/
    ``warmup``/``bucket_plan``/``bucket_stats``); ``mesh_shape`` (or an
    explicit ``mesh``) picks the 2D grid, ``merge`` the candidate-merge
    collective ("allgather" | "ring" | "auto" — "auto" hands the
    cross-shard merge to the GSPMD partitioner via the engines' "gspmd"
    chunk-merge program; no analytic comms model, see obs.comms).
    """

    def __init__(self, corpus: KNNInput, config: EngineConfig = None,
                 mesh=None, mesh_shape: Optional[Tuple[int, int]] = None,
                 capacity: Optional[int] = None,
                 merge: str = "allgather", gate_carry: bool = True):
        if merge == "auto":
            merge = "gspmd"     # the engine-internal strategy name
        if merge not in ("allgather", "ring", "gspmd"):
            raise ValueError(f"unknown merge strategy {merge!r}")
        cfg = config or EngineConfig(mode="sharded")
        if mesh is None:
            shape = mesh_shape or cfg.mesh_shape
            if shape is not None:
                # An explicit shape needs only shape-many devices — a
                # replica pinned to 2 of a host's 8 virtual devices is
                # the normal fleet deployment, not an error.
                import jax as _jax
                r0, c0 = shape
                mesh = make_mesh(shape,
                                 devices=_jax.devices()[:r0 * c0])
            else:
                mesh = make_mesh(None)
        super().__init__(cfg, mesh)
        self._merge_strategy = merge
        r, c = self.mesh.devices.shape
        n = corpus.params.num_data
        na = corpus.params.num_attrs
        if n < 1:
            raise ValueError("resident corpus must have at least one row")
        cap = capacity or shape_bucket(n)
        if cap < n:
            raise ValueError(f"capacity {cap} < corpus rows {n}")
        self.num_attrs = na
        # First-pass precision PLAN, frozen at construction like the
        # single-chip resident engine's: bucket kcaps and the active
        # cast both derive from it (_active_prec clamps the per-batch
        # resolve to the plan), so an env flip mid-serve can disable
        # the bf16 pass but never run it against f32-planned windows.
        self._precision_plan = cfg.resolve_precision()
        self.last_precision = None
        # Cross-request fused-gate warm-up, mesh edition (ROADMAP
        # follow-on (e)): the single-chip hot-block histogram doesn't
        # port 1:1 — here heat is tracked PER (shard, chunk), and the
        # fold schedule (one dispatch covers every shard's piece of
        # chunk t) orders chunks by their across-shard aggregate heat.
        # The carried state is a winner histogram, never a threshold:
        # within a request thresholds only tighten, so the fold is
        # sound in any order and carry on/off stay byte-identical
        # (boundary repair makes candidate-edge ties exact).
        self.gate_carry = bool(gate_carry)
        self.last_gated_fraction = None
        self._pending_gate: Optional[Tuple] = None

        # -- per-shard chunk plan at CAPACITY shape (fixed for life) ---------
        self._extract_ok = (cfg.use_pallas and cfg.resolve_select(
            round_up(max(-(-cap // r), 1), 8)) == "extract")
        granule = cfg.resolve_granule("extract") if self._extract_ok else 8
        shard_rows, nchunks, chunk_rows = plan_chunks(
            max(-(-cap // r), 1), granule, cfg.data_block)
        self._shard_rows = shard_rows
        self._nchunks = nchunks
        self._chunk_rows = chunk_rows       # per-shard rows per chunk
        self.capacity_rows = r * shard_rows
        if self._extract_ok:
            from dmlp_tpu.ops.pallas_distance import native_pallas_backend
            self._interpret = not native_pallas_backend()
        else:
            self._interpret = True

        # -- host originals (the float64 finalize rescore reads these) -------
        self._host_attrs = np.zeros((self.capacity_rows, na), np.float64)
        self._host_attrs[:n] = corpus.data_attrs
        self._host_labels = np.full(self.capacity_rows, -1, np.int32)
        self._host_labels[:n] = corpus.labels
        self.n_real = n
        self._sig_init()
        # Corpus max squared norm for the boundary-repair eps — cached
        # (an O(n*a) host pass per micro-batch would sit in every
        # request's tail latency at corpus scale), updated
        # incrementally on the append-only ingest.
        self._dn_max_cache: Optional[float] = None

        # -- resident device state -------------------------------------------
        self._csh = NamedSharding(self.mesh, P(DATA_AXIS, None))
        self._lsh = NamedSharding(self.mesh, P(DATA_AXIS))
        self._qsh = NamedSharding(self.mesh, P(QUERY_AXIS, None))
        self._rsh = NamedSharding(self.mesh, P())
        self._lab_dev = jax.device_put(
            np.ascontiguousarray(self._host_labels), self._rsh)
        self._ones_live = jax.device_put(np.ones(r, np.int32), self._lsh)
        self._chunks: Optional[List] = None
        self._sc_dev: Optional[List] = None
        self._mono = None              # (attrs, labels, ids) when staged
        if self._extract_ok:
            self._stage_chunks()
        else:
            self._ensure_monolithic()

        # -- resident per-(shard, chunk) summaries ---------------------------
        self._summ = None
        self.summary_rebuilds = 0
        self.last_prune_fraction = None
        if self._chunks is not None:
            self._build_summaries()
        # Gate-carry state: per-(shard, chunk) winner histogram.
        self._block_hits = np.zeros((r, max(self._nchunks, 1)), np.int64)
        telemetry.registry().gauge("serve.gate.carry_enabled").set(
            int(self.gate_carry))

        # -- bucket registry + compile bookkeeping ---------------------------
        self._buckets: Dict[Tuple[int, int], _MeshBucket] = {}
        self.compile_count = 0
        self.cold_start_compile_ms: Optional[float] = None
        self.bucket_compile_ms: Dict[str, float] = {}
        reg = telemetry.registry()
        reg.gauge("serve.corpus_rows").set(n)
        reg.gauge("serve.capacity_rows").set(self.capacity_rows)
        reg.gauge("serve.mesh_shards").set(r)

    # -- resident staging -----------------------------------------------------

    def _block_span(self, rr: int, t: int) -> Tuple[int, int]:
        """Global row range of shard ``rr``'s piece of chunk ``t`` —
        the ONE derivation shared by staging, summaries, and ingest
        routing (mirrors the fold programs' on-device ``_chunk_span``)."""
        lo = rr * self._shard_rows + t * self._chunk_rows
        hi = min(lo + self._chunk_rows, (rr + 1) * self._shard_rows,
                 self.n_real)
        return lo, max(hi, lo)

    def _chunk_host(self, t: int) -> np.ndarray:
        """Chunk ``t``'s (R * chunk_rows, A) staging buffer from the
        current host rows (each shard's span in its slot, pad zeroed)."""
        r, _ = self.mesh.devices.shape
        cr = self._chunk_rows
        sdt = _np_staging_dtype(self._staging)
        a = np.zeros((r * cr, self.num_attrs), sdt)
        for rr in range(r):
            lo, hi = self._block_span(rr, t)
            if hi > lo:
                a[rr * cr: rr * cr + (hi - lo)] = self._host_attrs[lo:hi]
        return a

    def _stage_chunks(self) -> None:
        with obs_span("fleet.stage_resident", chunks=self._nchunks,
                      mesh=list(self.mesh.devices.shape)):
            self._chunks = [jax.device_put(self._chunk_host(t), self._csh)
                            for t in range(self._nchunks)]
        self._refresh_scalars()

    def _refresh_scalars(self) -> None:
        """The per-chunk ``[n, toff, shard_rows]`` fold scalars; rebuilt
        on ingest (``n`` is the only moving part — a data input, so the
        fold programs never recompile)."""
        self._sc_dev = [
            jax.device_put(np.asarray(
                [self.n_real, t * self._chunk_rows, self._shard_rows],
                np.int32), self._rsh)
            for t in range(self._nchunks)]

    def _ensure_monolithic(self) -> None:
        """The streaming paths' resident layout: full capacity-padded
        (attrs, labels, ids) staged once, sharded over "data"."""
        if self._mono is not None:
            return
        sdt = _np_staging_dtype(self._staging)
        attrs = np.zeros((self.capacity_rows, self.num_attrs), sdt)
        attrs[:self.n_real] = self._host_attrs[:self.n_real]
        ids = np.full(self.capacity_rows, -1, np.int32)
        ids[:self.n_real] = np.arange(self.n_real, dtype=np.int32)
        with obs_span("fleet.stage_monolithic", rows=self.capacity_rows):
            self._mono = (
                jax.device_put(attrs, self._csh),
                jax.device_put(self._host_labels, self._lsh),
                jax.device_put(ids, self._lsh))

    # -- resident summaries (pruned two-stage solve, stage 0) -----------------

    def _block_ranges(self) -> List[Tuple[int, int]]:
        r, _ = self.mesh.devices.shape
        return [self._block_span(rr, t)
                for rr in range(r) for t in range(self._nchunks)]

    def _build_summaries(self) -> None:
        from dmlp_tpu.ops import summaries as osum
        r, _ = self.mesh.devices.shape
        if r * self._nchunks <= 1 or not osum.prune_enabled():
            return
        with obs_span("fleet.summary_build", blocks=r * self._nchunks):
            self._summ = osum.build_summaries(self._host_attrs,
                                              self._block_ranges())
        telemetry.registry().gauge("prune.summary_blocks").set(
            r * self._nchunks)

    def _rebuild_summary_blocks(self, blocks) -> None:
        """Ingest invalidation: rebuild exactly the touched (shard,
        chunk) blocks' summaries from their current host rows — a stale
        summary could keep a block pruned whose NEW rows belong in a
        top-k (the one failure mode the repair cannot catch)."""
        from dmlp_tpu.ops import summaries as osum
        if self._summ is None:
            return
        blocks = list(blocks)
        for rr, t in blocks:
            lo, hi = self._block_span(rr, t)
            b = rr * self._nchunks + t
            osum.update_block(self._summ, b, self._host_attrs[lo:hi],
                              lo_hi=(lo, hi))
        self.summary_rebuilds += len(blocks)
        telemetry.registry().counter("prune.summary_rebuilds").inc(
            len(blocks))

    def _prune_live(self, inp: KNNInput):
        """Per-micro-batch stage 1: score the RESIDENT summaries (host
        f64 — they are tiny) into an (R, T) live mask + stats, or
        (None, None) for a dense fold. Sound per ops.summaries: a
        pruned block provably contributes nothing below the staging-eps
        margin, and the exact stage is unchanged."""
        from dmlp_tpu.ops import summaries as osum
        if (self._summ is None or not self.config.exact
                or not osum.prune_enabled()
                or inp.params.num_queries == 0):
            return None, None
        r, _ = self.mesh.devices.shape
        with obs_span("fleet.prune_score", blocks=r * self._nchunks,
                      **self._rid_args()):
            keep, stats = osum.prune_mask(inp.query_attrs, inp.ks,
                                          self._summ,
                                          staging=self._staging,
                                          precision=self._active_prec())
        self.last_prune_fraction = stats["pruned_fraction"]
        return keep.reshape(r, self._nchunks), stats

    # -- shape buckets --------------------------------------------------------

    @property
    def query_granule(self) -> int:
        if self._extract_ok:
            from dmlp_tpu.ops.pallas_extract import QUERY_TILE
            return QUERY_TILE
        return 8

    @property
    def max_k(self) -> int:
        cap = self.capacity_rows
        if self._staging == "bfloat16":
            cap = min(cap, _BF16_AUTO_K_CAP)
        return cap

    def bucket_shape(self, nq: int, kmax: int) -> Tuple[int, int]:
        _r, c = self.mesh.devices.shape
        qloc = query_bucket(max(-(-max(nq, 1) // c), 1),
                            self.query_granule)
        return (c * qloc, k_bucket(kmax))

    def bucket_plan(self, nq: int, kmax: int) -> Tuple[int, int, int]:
        """(qpad, k-bucket, kcap) — the ONE candidate-width derivation
        admission pricing and the memwatch model share with the solve."""
        qpad, kb = self.bucket_shape(nq, kmax)
        kcap = resolve_kcap(self.config, kb, "extract",
                            self.capacity_rows, staging=self._staging,
                            precision=self._precision_plan)
        return qpad, kb, kcap

    def _active_prec(self) -> str:
        """Per-batch active first-pass precision: the config resolve
        (env kill switch included, read per call) clamped to the
        construction-time plan. No resilience ladder here — the mesh
        engines solve without run_ladder — so there is no rung gate."""
        prec = self.config.resolve_precision()
        return prec if prec == self._precision_plan == "bf16" else "f32"

    def _build_bucket(self, qpad: int, kb: int) -> _MeshBucket:
        _r, c = self.mesh.devices.shape
        qloc = qpad // c
        kcap = resolve_kcap(self.config, kb, "extract",
                            self.capacity_rows, staging=self._staging,
                            precision=self._precision_plan)
        path = "stream"
        if self._extract_ok and kcap <= 512:
            from dmlp_tpu.ops import pallas_fused
            kern, _ = pallas_fused.resolve_topk_kernel(
                qloc, self._chunk_rows, self.num_attrs, kcap)
            if kern is not None:
                path = "extract"
        if path == "stream":
            self._ensure_monolithic()
        return _MeshBucket(qpad, kb, kcap, path, qloc)

    # -- resident solves ------------------------------------------------------

    def _batch_input(self, query_attrs: np.ndarray,
                     ks: np.ndarray) -> KNNInput:
        nq = len(ks)
        return KNNInput(
            Params(self.n_real, nq, self.num_attrs),
            self._host_labels[:self.n_real],
            self._host_attrs[:self.n_real],
            np.asarray(ks, np.int32),
            np.asarray(query_attrs, np.float64))

    def _stage_queries(self, inp: KNNInput, qpad: int):
        nq = inp.params.num_queries
        q = np.zeros((qpad, self.num_attrs), np.float32)
        q[:nq] = inp.query_attrs
        np_dtype = self._np_dtype()
        return jax.device_put(q.astype(np_dtype, copy=False), self._qsh)

    def _solve_resident_chunks(self, inp: KNNInput, entry: _MeshBucket):
        """The mesh-resident hot path: fold the resident chunk buffers
        (pruned chunks dropped per the live masks) and merge across the
        data axis — the batch engines' chunked driver minus every
        staging transfer."""
        from dmlp_tpu.ops.summaries import note_scan
        r, c = self.mesh.devices.shape
        k, cr = entry.kcap, self._chunk_rows
        impl = self._extract_impl("extract", entry.qloc, cr,
                                  self.num_attrs, k)
        prec = self._active_prec()  # resolved outside the jits (R2)
        q_dev = self._stage_queries(inp, entry.qpad)
        keep_m, prune_stats = self._prune_live(inp)
        cd, ci = self._chunk_init_fn(r, entry.qpad, k)()
        step = self._chunk_fold_fn(k, self._interpret, impl, prec)
        item = np.dtype(self._np_dtype()).itemsize
        # Pre-walk the fold schedule so the one-time dispatch record
        # can claim the count that will ACTUALLY dispatch — claiming
        # nchunks would overstate the modeled fold work exactly when
        # pruning (or a part-empty capacity tail) is doing its job.
        # The walk follows _chunk_order(): hottest chunks first when
        # gate carry-over is on, so every query's k-th-best thresholds
        # tighten before the cold chunks' tiles reach the MXU gate.
        schedule = []
        scanned = 0
        for t in self._chunk_order():
            live_col = None if keep_m is None else keep_m[:, t]
            spans = [self._block_span(rr, t) for rr in range(r)]
            real = [hi > lo for lo, hi in spans]
            if not any(real):
                continue            # capacity tail: no resident rows yet
            if live_col is not None and not (live_col & real).any():
                continue            # every shard pruned this chunk
            for rr, (lo, hi) in enumerate(spans):
                if hi > lo and (live_col is None or live_col[rr]):
                    scanned += (hi - lo) * self.num_attrs * item
            schedule.append((t, live_col))
        dispatched = 0
        throttle = ChunkThrottle()
        mi = MeasuredIters(self, "fleet.chunk_fold",
                           (entry.qloc, cr, self.num_attrs, k),
                           kernel=impl)
        self._last_select = "extract"
        gz = None
        ntiles = 0
        with obs_span("fleet.solve_resident", qpad=entry.qpad, kcap=k,
                      chunks=self._nchunks, scheduled=len(schedule),
                      impl=impl, mesh=[r, c],
                      carry=self.gate_carry, **self._rid_args()):
            for t, live_col in schedule:
                lv = self._ones_live if live_col is None \
                    else jax.device_put(np.asarray(live_col, np.int32),
                                        self._lsh)
                if dispatched == 0:
                    obs_counters.record_dispatch(
                        step, (cd, ci, self._chunks[t], q_dev,
                               self._sc_dev[t], lv),
                        count=len(schedule), site="fleet.chunk_fold")
                cd, ci, its = step(cd, ci, self._chunks[t], q_dev,
                                   self._sc_dev[t], lv)
                mi.add(its)
                # Gate effectiveness: a 0-iteration tile was gated (or
                # skip-gated) outright — summed on device, read back
                # once per micro-batch in _after_batch. The tile COUNT
                # is static shape metadata, no transfer.
                z = jnp.sum(its == 0)
                gz = z if gz is None else gz + z
                ntiles += math.prod(its.shape)
                dispatched += 1
                throttle.tick(cd)
                telemetry.sample_memory_now()
        mi.done()
        self._pending_gate = (gz, ntiles) if gz is not None else None
        blocks_total = sum(1 for rr in range(r)
                           for t in range(self._nchunks)
                           if self._block_span(rr, t)[1]
                           > self._block_span(rr, t)[0])
        note_scan(self, scanned_bytes=scanned,
                  dense_bytes=self.n_real * self.num_attrs * item,
                  blocks_total=(prune_stats or {}).get("blocks_total",
                                                       blocks_total),
                  blocks_pruned=(prune_stats or {}).get("blocks_pruned",
                                                        0))
        self.last_comms = engine_comms(self._merge_strategy, (r, c),
                                       entry.qpad // c, k)
        merge_fn = self._chunk_merge_fn(k)
        obs_counters.record_dispatch(merge_fn, (cd, ci, self._lab_dev),
                                     site="fleet.chunk_merge")
        with obs_span("fleet.merge", mesh=[r, c], kc=k,
                      **self._rid_args()) as sp:
            top = merge_fn(cd, ci, self._lab_dev)
            sp.fence(top.dists)
        return top

    def _chunk_order(self) -> List[int]:
        """Fold order over the resident chunks: every dispatch of chunk
        ``t`` covers ALL shards' ``t``-th pieces, so the schedule is one
        permutation of ``t`` — ordered by the chunks' across-shard
        aggregate winner count (hottest first) when gate carry-over is
        on, natural otherwise. Stable sort: cold chunks keep their
        natural relative order."""
        with obs_span("fleet.fold_schedule", chunks=self._nchunks,
                      carry=self.gate_carry, **self._rid_args()):
            if not self.gate_carry:
                return list(range(self._nchunks))
            heat = self._block_hits.sum(axis=0)
            return list(np.argsort(-heat[:self._nchunks], kind="stable"))

    def _after_batch(self, results: List[QueryResult]) -> None:
        """Cross-request gate bookkeeping (the single-chip resident
        engine's discipline, per-shard): flush the pending gated-tile
        readback, then credit each winner row's owning (shard, chunk)
        block in the carried histogram."""
        if self._pending_gate is not None:
            gz, ntiles = self._pending_gate
            self._pending_gate = None
            try:
                gated = int(jax.device_get(gz))  # check: allow-host-sync
                frac = gated / max(ntiles, 1)
                self.last_gated_fraction = frac
                reg = telemetry.registry()
                reg.gauge("serve.gate.gated_fraction").set(round(frac, 6))
                reg.counter("serve.gate.tiles_total").inc(ntiles)
                reg.counter("serve.gate.tiles_gated").inc(gated)
            except Exception:  # check: no-retry — stats never fail a batch
                pass
        if self.gate_carry and self._nchunks and results:
            ids = np.concatenate(
                [np.asarray(r.neighbor_ids, np.int64) for r in results])
            ids = ids[ids >= 0]
            if ids.size:
                r, _ = self.mesh.devices.shape
                rr = ids // self._shard_rows
                t = (ids - rr * self._shard_rows) // self._chunk_rows
                hits = np.bincount(rr * self._nchunks + t,
                                   minlength=r * self._nchunks)
                self._block_hits += hits.reshape(r, self._nchunks)

    def _solve_resident_stream(self, inp: KNNInput, entry: _MeshBucket):
        """Streaming fallback on the resident MONOLITHIC arrays: the
        engines' merged program (collective epilogue inside the jit)."""
        from dmlp_tpu.ops.summaries import note_scan
        self._ensure_monolithic()
        d_attrs, d_labels, d_ids = self._mono
        q_dev = self._stage_queries(inp, entry.qpad)
        with obs_span("fleet.solve_stream", qpad=entry.qpad,
                      kcap=entry.kcap, **self._rid_args()):
            top = self.solve_global(d_attrs, d_labels, d_ids, q_dev,
                                    kmax=entry.kb)
        dense = self.n_real * self.num_attrs \
            * np.dtype(self._np_dtype()).itemsize
        note_scan(self, scanned_bytes=dense, dense_bytes=dense,
                  blocks_total=self.mesh.devices.shape[0],
                  blocks_pruned=0)
        return top

    # -- the serving entry ----------------------------------------------------

    def solve_batch(self, query_attrs, ks) -> List[QueryResult]:
        """One coalesced micro-batch end to end over the mesh: bucket,
        fold the resident shards, merge across "data", fetch, float64
        finalize + boundary repair. Results carry query ids 0..nq-1 in
        batch order — the batcher slices per request."""
        inp = self._batch_input(np.asarray(query_attrs, np.float64),
                                np.asarray(ks, np.int32))
        n = self.n_real
        nq = inp.params.num_queries
        kmax = int(inp.ks.max()) if nq else 1
        self.last_phase_ms = {}
        self.last_comms = []
        self._pending_iters = []
        self.last_extract_impl = None
        self.last_prune = None
        self.last_prune_fraction = None
        self._pending_gate = None
        prec = self._active_prec()
        self.last_precision = {"active": prec,
                               "configured": self._precision_plan}
        memwatch.note_engine_model(self, inp)
        entry = self._bucket_entry(nq, kmax)
        if entry.path == "extract":
            top = self._solve_resident_chunks(inp, entry)
        else:
            top = self._solve_resident_stream(inp, entry)
        telemetry.sample_memory_now()
        self.last_repairs = 0
        with obs_span("fleet.fetch", **self._rid_args()):
            od, ol, oi = resilient_get((top.dists, top.labels, top.ids),
                                       site="sharded.fetch")
            dists = np.asarray(od, np.float64)[:nq]
            labels = ol[:nq]
            ids = oi[:nq]
        with obs_span("fleet.finalize", exact=self.config.exact,
                      **self._rid_args()):
            results = finalize_host(dists, labels, ids, inp.ks,
                                    inp.query_attrs, inp.data_attrs,
                                    exact=self.config.exact)
            if self._last_select in ("sort", "topk", "seg", "extract") \
                    and dists.shape[1] < n:
                # Same per-shard-truncation hazard test as the batch
                # mesh engines (engine.sharded._run): the merged kcap-th
                # bounds every shard's horizon, so the eps-widened
                # boundary test covers per-shard truncation too.
                dn_max = self._dn_max()
                qn = np.einsum("qa,qa->q", inp.query_attrs,
                               inp.query_attrs)
                eps = staging_eps(
                    np.asarray(dists[:, -1], np.float64), qn, dn_max,
                    self._staging, self.num_attrs)
                if prec == "bf16" and self._last_select == "extract":
                    # The bf16 first pass perturbs device distances
                    # beyond the staging model — widen the hazard test
                    # by its analytic bound (finalize.lowp_eps).
                    eps = eps + lowp_eps("bf16", qn, dn_max)
                suspects = np.nonzero(
                    boundary_overflow(dists, inp.ks, eps))[0]
                if suspects.size:
                    repair_boundary_overflow(results, suspects, inp)
                    self.last_repairs += int(suspects.size)
        flush_measured_iters(self)
        self._after_batch(results)
        return results

    # -- incremental shard-routed ingestion -----------------------------------

    def ingest(self, labels, attrs, start: Optional[int] = None) -> int:
        """Write rows behind the row-count mask. Rows land at their
        global positions — the owning shard's span of the touched chunk
        buffers — by restaging exactly those fixed-shape device arrays
        (and the touched blocks' summaries). ``start=None`` appends;
        ``start <= n_real`` is the idempotent row-write keyed by global
        row id the fleet's consistency repair replays. No solve program
        sees a new shape: zero recompilation, counter-asserted."""
        labels = np.asarray(labels, np.int32).reshape(-1)
        attrs = np.asarray(attrs, np.float64)
        if attrs.ndim != 2 or attrs.shape[1] != self.num_attrs:
            raise ValueError(
                f"ingest rows must be (m, {self.num_attrs}), "
                f"got {attrs.shape}")
        m = attrs.shape[0]
        if m != labels.shape[0]:
            raise ValueError("labels/attrs row-count mismatch")
        if m == 0:
            return self.n_real
        at = self.n_real if start is None else int(start)
        if at < 0 or at > self.n_real:
            raise ValueError(
                f"ingest start {at} beyond resident rows "
                f"{self.n_real} (row-writes may overwrite or append, "
                "never leave gaps)")
        end = at + m
        new_n = max(self.n_real, end)
        if end > self.capacity_rows:
            raise CapacityError(
                f"ingest of {m} rows at {at} exceeds capacity "
                f"{self.capacity_rows} (resident: {self.n_real})")
        r, _ = self.mesh.devices.shape
        sr, cr = self._shard_rows, self._chunk_rows
        with obs_span("fleet.ingest", rows=m, corpus_rows=new_n):
            self._host_attrs[at:end] = attrs
            self._host_labels[at:end] = labels
            self.n_real = new_n
            self._note_ingested_norms(attrs)
            self._sig_update(at, end)
            # Touched (shard, chunk) blocks from the [at, end)
            # span by block arithmetic — never a per-row Python loop
            # (a corpus-scale append would stall the solve loop).
            touched = []
            for rr in range(r):
                lo = max(at, rr * sr)
                hi = min(end, (rr + 1) * sr)
                if hi <= lo:
                    continue
                t_lo = (lo - rr * sr) // cr
                t_hi = (hi - 1 - rr * sr) // cr
                touched.extend(
                    (rr, min(t, self._nchunks - 1))
                    for t in range(t_lo, t_hi + 1))
            touched = sorted(set(touched))
            if self._chunks is not None:
                for t in sorted({t for _rr, t in touched}):
                    self._chunks[t] = jax.device_put(
                        self._chunk_host(t), self._csh)
                self._refresh_scalars()
                self._rebuild_summary_blocks(touched)
            self._lab_dev = jax.device_put(
                np.ascontiguousarray(self._host_labels), self._rsh)
            if self._mono is not None:
                self._mono = None
                self._ensure_monolithic()
        reg = telemetry.registry()
        reg.counter("serve.ingested_rows").inc(m)
        reg.gauge("serve.corpus_rows").set(new_n)
        return new_n

    # -- memory-model hooks (ResidentServingCore contract) --------------------

    def mem_model(self, nq: int = 0, kmax: int = 0) -> Dict[str, object]:
        """Per-device fleet model at this engine's own bucket_plan;
        batch terms included iff ``nq > 0``."""
        r, c = self.mesh.devices.shape
        qloc = kcap = 0
        if nq > 0:
            qpad, _kb, kcap = self.bucket_plan(nq, max(kmax, 1))
            qloc = qpad // c
        return memwatch.fleet_engine_model(
            mesh_shape=(r, c), shard_rows=self._shard_rows,
            na=self.num_attrs, staging=self._staging,
            chunks=(self._nchunks if self._chunks is not None else 0),
            chunk_rows=self._chunk_rows,
            monolithic=self._mono is not None,
            capacity_rows=self.capacity_rows,
            summary_blocks=(r * self._nchunks if self._summ is not None
                            else 0),
            qloc=qloc, kcap=kcap, merge=self._merge_strategy)

    def batch_model_bytes(self, nq: int, kmax: int) -> int:
        """Marginal per-device bytes of one micro-batch bucket on top
        of the resident floor (query shard + local lists + the merge
        buffer — the allgather merge materializes all R shards' lists)."""
        terms = self.mem_model(nq, kmax)["terms"]
        return int(terms.get("query_shard", 0)
                   + terms.get("local_topk", 0)
                   + terms.get("merge_buffer", 0))

    def resident_state_key(self):
        # The per-device floor moves when the monolithic layout stages
        # lazily (a stream-path bucket on an extract-capable config
        # adds a second full corpus copy per device).
        return (self._chunks is not None, self._mono is not None)

    # -- introspection --------------------------------------------------------

    def bucket_stats(self) -> Dict[str, object]:
        entries = list(self._buckets.values())
        lp = self.last_prune
        r, c = self.mesh.devices.shape
        return {
            "buckets": sorted(e.key for e in entries),
            "paths": {e.key: e.path for e in entries},
            # Mesh buckets dispatch shard_map jits through the jit
            # cache — there is no per-bucket jax.stages.Compiled handle
            # to fingerprint (the single-chip ResidentEngine's
            # stream-path map is the populated case); explicit marker,
            # not silence.
            "hlo_schedule": {},
            "hlo_unavailable": "mesh buckets have no AOT-compiled "
                               "stream handle",
            "compile_count": self.compile_count,
            "bucket_compile_ms": dict(self.bucket_compile_ms),
            "cold_start_compile_ms": self.cold_start_compile_ms,
            "corpus_rows": self.n_real,
            "capacity_rows": self.capacity_rows,
            "gate_carry": self.gate_carry,
            "last_gated_fraction": self.last_gated_fraction,
            "extract_chunks": self._nchunks if self._chunks else 0,
            "summary_blocks": (r * self._nchunks if self._summ else 0),
            "summary_rebuilds": self.summary_rebuilds,
            "last_prune_fraction": self.last_prune_fraction,
            "last_prune": dict(lp) if isinstance(lp, dict) else None,
            "precision_plan": self._precision_plan,
            "last_precision": dict(self.last_precision)
            if isinstance(self.last_precision, dict) else None,
            "mesh": [r, c],
            "merge": self._merge_strategy,
            "shard_rows": self._shard_rows,
        }
