import sys

from dmlp_tpu.cli import main

sys.exit(main())
