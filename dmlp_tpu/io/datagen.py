"""Seeded synthetic input generator — byte-identical to the reference's.

Reference: generate_input.py:6-23 (grammar emitter) and :26-66 (CLI). The
canonical benchmark inputs (inputs/input1..3.in) are absent from the snapshot
(.MISSING_LARGE_BLOBS:1), so regenerating them with this fully-seeded
generator *is* the benchmark-input protocol (survey §6). This module draws
from Python's ``random`` in the exact same call sequence as the reference so
that, for equal arguments and seed, the emitted text is byte-identical.

Usage (same flags as the reference CLI)::

    python -m dmlp_tpu.io.datagen --num_data 1000 --num_queries 100 \
        --num_attrs 16 --min 0 --max 100 --minK 1 --maxK 16 \
        --num_labels 10 --output input.in [--seed 42]
"""

from __future__ import annotations

import argparse
import random
import sys


def generate_input_text(num_data: int, num_queries: int, num_attrs: int,
                        attr_min: float, attr_max: float, min_k: int,
                        max_k: int, num_labels: int, seed: int = 42) -> str:
    """Generate one problem instance in the input grammar.

    Seeds ``random`` and replays generate_input.py:13-21's draw order exactly:
    per data point one ``randint`` label then ``num_attrs`` uniforms; per
    query one ``randint`` k in [minK, min(maxK, num_data)] then the uniforms.
    Returns the text *including* the trailing newline the reference CLI
    appends on write (generate_input.py:64).
    """
    random.seed(seed)
    lines = [f"{num_data} {num_queries} {num_attrs}"]
    for _ in range(num_data):
        label = random.randint(0, num_labels - 1)
        attrs = [f"{random.uniform(attr_min, attr_max):.6f}" for _ in range(num_attrs)]
        lines.append(f"{label} " + " ".join(attrs))
    for _ in range(num_queries):
        k = random.randint(min_k, min(max_k, num_data))
        attrs = [f"{random.uniform(attr_min, attr_max):.6f}" for _ in range(num_attrs)]
        lines.append("Q " + f"{k} " + " ".join(attrs))
    return "\n".join(lines) + "\n"


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description="Generate input for the KNN engine.")
    parser.add_argument("--num_data", type=int, required=True)
    parser.add_argument("--num_queries", type=int, required=True)
    parser.add_argument("--num_attrs", type=int, required=True)
    parser.add_argument("--min", type=float, required=True, dest="attr_min")
    parser.add_argument("--max", type=float, required=True, dest="attr_max")
    parser.add_argument("--minK", type=int, required=True)
    parser.add_argument("--maxK", type=int, required=True)
    parser.add_argument("--num_labels", type=int, required=True)
    parser.add_argument("--output", type=str, required=True)
    parser.add_argument("--seed", type=int, default=42)
    args = parser.parse_args(argv)

    # Same validation as generate_input.py:43-48.
    if args.attr_min >= args.attr_max:
        sys.exit("Error: --min must be less than --max")
    if args.minK > args.maxK:
        sys.exit("Error: --minK must be <= --maxK")
    if args.num_labels <= 0:
        sys.exit("Error: --num_labels must be positive")

    text = generate_input_text(args.num_data, args.num_queries, args.num_attrs,
                               args.attr_min, args.attr_max, args.minK,
                               args.maxK, args.num_labels, seed=args.seed)
    with open(args.output, "w") as f:
        f.write(text)
    print(f"Input file '{args.output}' generated successfully.")


if __name__ == "__main__":
    main()
