"""I/O layer: the reference's harness contract (grammar, checksum, report)."""

from dmlp_tpu.io.grammar import (  # noqa: F401
    Params,
    KNNInput,
    parse_input,
    parse_input_text,
    format_input,
    parse_update,
)
from dmlp_tpu.io.checksum import fnv1a_checksum, FNV_BASIS, FNV_PRIME  # noqa: F401
from dmlp_tpu.io.report import format_results, QueryResult  # noqa: F401
from dmlp_tpu.io.datagen import generate_input_text  # noqa: F401
