"""ctypes bridge to the native C++ grammar parser (native/fastparse.cpp).

The reference's harness is native C++ (common.cpp); the analog here is a
C++ tokenizer for the same grammar that fills the flat SoA arrays the
device pipeline consumes — ~20x the pure-Python parser on benchmark-size
inputs, bit-identical output (strtod and Python float() round identically).

The shared library is built on demand with g++ (no pybind11 in the image;
plain ``extern "C"`` + ctypes). Everything degrades gracefully: if the
toolchain or the build is unavailable, callers fall back to the Python
parser (grammar.parse_input_text).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

from dmlp_tpu.io.grammar import KNNInput, Params, ParseError

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "fastparse.cpp")
_LIB = os.path.join(_REPO_ROOT, "native", "_fastparse.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    """g++-compile the parser if the .so is missing or stale."""
    if not os.path.exists(_SRC):
        return False
    if (os.path.exists(_LIB)
            and os.path.getmtime(_LIB) >= os.path.getmtime(_SRC)):
        return True
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-o", _LIB, _SRC],
            check=True, capture_output=True, timeout=120)
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if os.environ.get("DMLP_TPU_NO_NATIVE"):
            return None
        # check: allow-concurrency=R703 — the g++ build under the lock
        # is the once-guard: this runs at most ONCE per process (_tried
        # flips first), and concurrent parsers must block until the .so
        # exists rather than racing the compiler or falling back to the
        # slow Python parser mid-build.
        if not _build():
            return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            # Corrupt/wrong-arch/half-written .so: degrade to the Python
            # parser rather than poisoning every large parse_input call.
            return None
        lib.dmlp_parse_header.restype = ctypes.c_int
        lib.dmlp_parse_header.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_long)]
        lib.dmlp_parse_body.restype = ctypes.c_int
        lib.dmlp_parse_body.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_long, ctypes.c_long,
            ctypes.c_long,
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
            ctypes.c_char_p, ctypes.c_size_t]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


def parse_input_text_native(text) -> KNNInput:
    """Parse via the C++ tokenizer; raises ValueError like the Python parser
    (same messages: "Line is empty" / "Line is wrongly formatted").

    Accepts str or bytes; pass bytes for large payloads to skip a full
    decode/encode round-trip (the C parser works on the raw buffer).
    """
    lib = _load()
    if lib is None:
        raise RuntimeError("native parser unavailable")
    raw = text if isinstance(text, bytes) else text.encode("ascii")
    hdr = (ctypes.c_long * 3)()
    if lib.dmlp_parse_header(raw, len(raw), hdr) != 0:
        raise ParseError("malformed header line", line=1, byte_offset=0)
    nd, nq, na = int(hdr[0]), int(hdr[1]), int(hdr[2])
    if nd < 0 or nq < 0 or na < 0:
        raise ParseError("negative sizes in header", line=1, byte_offset=0)

    labels = np.empty(nd, np.int32)
    data_attrs = np.empty((nd, na), np.float64)
    ks = np.empty(nq, np.int32)
    query_attrs = np.empty((nq, na), np.float64)
    errbuf = ctypes.create_string_buffer(128)
    rc = lib.dmlp_parse_body(raw, len(raw), nd, nq, na, labels,
                             data_attrs.reshape(-1), ks,
                             query_attrs.reshape(-1), errbuf, len(errbuf))
    if rc != 0:
        raise _located_error(errbuf.value.decode("ascii"), rc)
    return KNNInput(Params(nd, nq, na), labels, data_attrs, ks, query_attrs)


def _located_error(msg: str, rc: int) -> ParseError:
    """The C side reports '<message> (byte offset N)' (fastparse.cpp
    set_err); lift the offset into the structured ParseError field so
    Python callers need no string parsing. Unknown shapes (an old .so
    from before offsets existed) degrade to an unlocated ParseError."""
    import re
    m = re.search(r"^(.*) \(byte offset (\d+)\)$", msg or "")
    if m:
        return ParseError(m.group(1), byte_offset=int(m.group(2)))
    return ParseError(msg or f"parse error {rc}")
