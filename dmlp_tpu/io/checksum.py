"""The order-sensitive FNV-1a result checksum — the correctness contract.

Reference: common.cpp:57-71. The oracle folds, in order:

1. the predicted label (cast to unsigned 64-bit);
2. each neighbor id **+ 1** ("+1 to distinguish from -1 sentinel",
   common.cpp:66), in report order (distance asc, tie -> larger id,
   engine.cpp:334-338).

Any deviation in k-selection, tie-breaking, vote, or ordering changes the
value, which is what makes it a differential-testing oracle (survey §4).
"""

from __future__ import annotations

from typing import Iterable, Sequence

import numpy as np

FNV_BASIS = 1469598103934665603  # common.cpp:59
FNV_PRIME = 1099511628211        # common.cpp:62
_MASK = (1 << 64) - 1


def fnv1a_checksum(label: int, neighbor_ids: Iterable[int]) -> int:
    """Checksum of one query result (exact reimplementation of common.cpp:57-71).

    ``label`` and ids are folded with C++ ``static_cast<unsigned long long>``
    semantics: negative values wrap mod 2**64 (so the -1 sentinel id folds in
    as (+1 =) 0, and a -1 label folds as 2**64-1).
    """
    c = FNV_BASIS
    c ^= int(label) & _MASK
    c = (c * FNV_PRIME) & _MASK
    for idx in neighbor_ids:
        c ^= (int(idx) + 1) & _MASK
        c = (c * FNV_PRIME) & _MASK
    return c


def fnv1a_checksum_batch(labels: Sequence[int], neighbor_ids: np.ndarray,
                         valid_counts: Sequence[int]) -> np.ndarray:
    """Vectorized checksums for a batch of query results.

    Args:
      labels: (Q,) predicted labels.
      neighbor_ids: (Q, Kmax) neighbor ids in report order; entries at or
        beyond each query's valid count are ignored.
      valid_counts: (Q,) number of reported neighbors per query (its k).

    Returns:
      (Q,) uint64-valued Python-int array (dtype object to avoid overflow
      surprises in downstream formatting).
    """
    out = np.empty(len(labels), dtype=object)
    ids = np.asarray(neighbor_ids)
    for qi in range(len(labels)):
        out[qi] = fnv1a_checksum(int(labels[qi]), ids[qi, : int(valid_counts[qi])])
    return out
