"""Result reporting — both output modes of the reference harness.

Reference common.cpp:57-79:

- release mode: one line per query, ``Query <id> checksum: <c>`` on stdout;
- ``-DDEBUG`` mode (Makefile:14-15, mirrored by benchmarks/bench.debug):
  ``Label for Query <id> : <label>``, ``Top-<k> neighbors:``, then one
  ``<id> : <dist>`` line per neighbor.

stdout is the results channel, stderr the metrics channel (``Time taken:``,
common.cpp:130); :mod:`dmlp_tpu.utils.timing` owns the stderr side.
"""

from __future__ import annotations

import dataclasses
from typing import List, Sequence

import numpy as np

from dmlp_tpu.io.checksum import fnv1a_checksum


@dataclasses.dataclass
class QueryResult:
    """Final result for one query, in report order.

    ``neighbor_ids``/``neighbor_dists`` are length-k, sorted by
    (distance asc, tie -> larger id) per engine.cpp:334-338, padded with the
    id = -1 sentinel (common.cpp:66) when fewer than k candidates exist.
    """

    query_id: int
    k: int
    predicted_label: int
    neighbor_ids: np.ndarray
    neighbor_dists: np.ndarray

    def checksum(self) -> int:
        return fnv1a_checksum(self.predicted_label, self.neighbor_ids)


def _format_double(v: float) -> str:
    # C++ `std::cout << double` default formatting: 6 significant digits,
    # fixed/scientific whichever is shorter — i.e. printf %g.
    return "%g" % v


def format_results(results: Sequence[QueryResult], debug: bool = False) -> str:
    """Render the stdout channel for a batch of query results."""
    out: List[str] = []
    if not debug:
        for r in results:
            out.append(f"Query {r.query_id} checksum: {r.checksum()}")
    else:
        for r in results:
            out.append(f"Label for Query {r.query_id} : {r.predicted_label}")
            out.append(f"Top-{r.k} neighbors:")
            for nid, nd in zip(r.neighbor_ids, r.neighbor_dists):
                out.append(f"{int(nid)} : {_format_double(float(nd))}")
    return "\n".join(out) + ("\n" if out else "")
