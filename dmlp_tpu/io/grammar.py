"""The stdin input grammar of the reference harness, as array-producing parsers.

Grammar (reference common.cpp:12-55, generate_input.py:11-21)::

    line 0:                "num_data num_queries num_attrs"
    lines 1..num_data:     "label a1 a2 ... aA"          (one data point each)
    next num_queries:      "Q k a1 a2 ... aA"            (one query each)

Data-point ids are implicit line order (gid, common.cpp:103), query ids are
implicit order among query lines (common.cpp:110). Tokens are whitespace
separated; attribute values are decimals (the generator emits %.6f).

Unlike the reference, which parses into per-record structs
(common.h:10-20) on rank 0 only (common.cpp:93-117), we parse straight into
flat NumPy arrays — the layout the TPU engine feeds to the device. Struct
views are still available for tests/tools.

Error behavior mirrors common.cpp:100-115: empty data line -> "Line is
empty"; query line not starting with 'Q' -> "Line is wrongly formatted".
"""

from __future__ import annotations

import dataclasses
from typing import IO, List, Union

import numpy as np


class ParseError(ValueError):
    """Malformed or truncated input, located by line and byte offset.

    Subclasses ValueError (the historical raise type) so existing
    handlers and tests keep working; the message keeps the reference's
    exact phrasing ("Line is empty" / "Line is wrongly formatted") and
    appends the location — a truncated pipe or corrupted payload names
    WHERE the grammar broke instead of surfacing an uncaught
    struct/index error from the array-filling loop."""

    def __init__(self, message: str, line: int = None,
                 byte_offset: int = None):
        self.line = line
        self.byte_offset = byte_offset
        loc = []
        if line is not None:
            loc.append(f"line {line}")
        if byte_offset is not None:
            loc.append(f"byte offset {byte_offset}")
        super().__init__(message + (f" ({', '.join(loc)})" if loc else ""))


@dataclasses.dataclass(frozen=True)
class Params:
    """Problem-size header (reference common.h:4-8)."""

    num_data: int
    num_queries: int
    num_attrs: int


@dataclasses.dataclass(frozen=True)
class Update:
    """An attribute-update record (reference common.h:22-25, common.cpp:46-55).

    Present in the reference's grammar/data model but never consumed by its
    engine; kept for full contract parity.
    """

    id: int
    new_attrs: np.ndarray


@dataclasses.dataclass
class KNNInput:
    """A fully parsed problem instance in array (SoA) form.

    The reference keeps AoS vectors of structs and flattens them right before
    each MPI scatter (engine.cpp:79-96,154-184); we keep SoA from the start —
    ids are simply ``arange`` (implicit line order), so they are not stored.
    """

    params: Params
    labels: np.ndarray        # (num_data,)  int32
    data_attrs: np.ndarray    # (num_data, num_attrs)  float64
    ks: np.ndarray            # (num_queries,)  int32
    query_attrs: np.ndarray   # (num_queries, num_attrs)  float64

    @property
    def data_ids(self) -> np.ndarray:
        return np.arange(self.params.num_data, dtype=np.int32)

    @property
    def query_ids(self) -> np.ndarray:
        return np.arange(self.params.num_queries, dtype=np.int32)


def subset_queries(inp: KNNInput, idx: np.ndarray) -> KNNInput:
    """A view of ``inp`` restricted to the query rows in ``idx`` (the data
    side is shared, not copied). Used by the heterogeneous-k router
    (engine.single) and the hazard repair (engine.finalize)."""
    return KNNInput(
        Params(inp.params.num_data, len(idx), inp.params.num_attrs),
        inp.labels, inp.data_attrs, inp.ks[idx], inp.query_attrs[idx])


def _strict_int(tok: str) -> int:
    """int() minus PEP 515 underscores. Python would read "1_0" as 10; the
    reference's unchecked ``ss >> val`` stops at the underscore and
    silently misparses (subsequent extractions fail, leaving stale
    values — common.cpp:17-29 never checks the stream). Neither behavior
    is worth copying: both parsers here (this one and the native C++
    tokenizer's end-of-token check) reject such tokens loudly instead.
    Generator-produced inputs never contain them, so this is a strictness
    choice, not a contract requirement."""
    if "_" in tok:
        raise ValueError(f"invalid integer token {tok!r}")
    return int(tok)


def parse_params(line: str) -> Params:
    """Parse the header line (reference common.cpp:12-15)."""
    toks = line.split()
    try:
        return Params(_strict_int(toks[0]), _strict_int(toks[1]),
                      _strict_int(toks[2]))
    except (IndexError, ValueError):
        raise ParseError("malformed header line (want 'num_data "
                         "num_queries num_attrs')", line=1,
                         byte_offset=0) from None


def parse_update(line: str) -> Update:
    """Parse an update line "id v1 v2 ..." (reference common.cpp:46-55)."""
    toks = line.split()
    return Update(int(toks[0]), np.array([float(t) for t in toks[1:]], dtype=np.float64))


_NATIVE_THRESHOLD_BYTES = 1 << 20  # native parser pays off past ~1 MB


def parse_input(stream: Union[IO[str], IO[bytes]]) -> KNNInput:
    """Parse a full problem instance from a text or binary stream.

    Large inputs route through the native C++ tokenizer
    (dmlp_tpu.io.native, bit-identical results) when it is buildable;
    anything else uses the pure-Python parser below.

    Registered injection site ``io.parse`` (resilience.inject): a
    ``corrupt`` fault truncates the payload before parsing, the grammar
    raises :class:`ParseError`, and the pristine in-memory payload is
    re-parsed — corruption detected at the parse boundary recovers with
    byte-identical results (stdin is consumed, but the bytes are not).
    """
    data = stream.read()
    from dmlp_tpu.resilience import inject as rs_inject
    actions = rs_inject.fire("io.parse") or ()
    if "corrupt" in actions:
        try:
            _parse_payload(rs_inject.corrupt_bytes(data))
        except ParseError:
            from dmlp_tpu.obs import trace as obs_trace
            from dmlp_tpu.resilience import stats as rs_stats
            rs_stats.record_retry("io.parse")
            obs_trace.instant("resilience.retry", site="io.parse",
                              attempt=1, error="ParseError")
        # The pristine in-memory payload is authoritative either way:
        # a corrupted payload's parse result is never returned, even
        # if it somehow parsed (silently changed answers are the one
        # unforgivable failure mode).
    return _parse_payload(data)


def _parse_payload(data: Union[str, bytes]) -> KNNInput:
    if len(data) >= _NATIVE_THRESHOLD_BYTES:
        from dmlp_tpu.io import native
        if native.native_available():
            # bytes pass straight to the C parser — no decode round-trip.
            return native.parse_input_text_native(data)
    if isinstance(data, bytes):
        data = data.decode("ascii")
    return parse_input_text(data)


def parse_input_text(text: str) -> KNNInput:
    """Parse a full problem instance from a string.

    Mirrors the rank-0 ingest loop at common.cpp:93-117, including its error
    messages, but produces flat arrays. Uses a single bulk tokenizer pass for
    the numeric payload instead of per-line stringstreams — the reference's
    rank-0 ingest is its host-side bottleneck (survey §7 "host input
    pipeline"); this parser is the pure-Python fallback for the native C++
    one in :mod:`dmlp_tpu.io.native`.
    """
    # Split on '\n' EXACTLY (not splitlines(), which also splits on
    # \r, \x0b, \x85, ...): the grammar is '\n'-separated like the
    # native cursor parser, and the incremental byte offsets below are
    # only honest if every separator is one byte of real input — a
    # stray \r rides inside its line (whitespace to the tokenizer) and
    # is counted, not silently split on.
    lines = text.split("\n")
    if lines and lines[-1] == "":
        lines.pop()   # trailing terminator, not an empty final line
    if not lines:
        raise ParseError("empty input", byte_offset=0)
    params = parse_params(lines[0])
    nd, nq, na = params.num_data, params.num_queries, params.num_attrs
    if len(lines) < 1 + nd + nq:
        raise ParseError(
            f"input has {len(lines) - 1} record lines, expected {nd + nq}"
            " — truncated input?", line=len(lines),
            byte_offset=len(text))

    # Line-start byte offsets, tracked incrementally — exact, because
    # the split above consumes exactly one '\n' per line.
    off = len(lines[0]) + 1

    labels = np.empty(nd, dtype=np.int32)
    data_attrs = np.empty((nd, na), dtype=np.float64)
    for i in range(nd):
        line = lines[1 + i]
        if not line:
            raise ParseError("Line is empty", line=2 + i,  # common.cpp:101
                             byte_offset=off)
        if "_" in line:
            # Python's float()/int() accept PEP 515 underscores ("1_0" ->
            # 10.0); the reference's unchecked stringstream extraction
            # silently misparses them instead (see _strict_int). Reject
            # loudly — matching the native C++ parser, not the reference's
            # silent-garbage behavior.
            raise ParseError("Line is wrongly formatted", line=2 + i,
                             byte_offset=off)
        toks = line.split()
        try:
            if len(toks) < 1 + na:
                # A short row with exactly one attr token would
                # otherwise numpy-broadcast across the whole row —
                # silent misparse, the worst failure mode.
                raise IndexError
            labels[i] = int(toks[0])
            data_attrs[i] = [float(t) for t in toks[1 : 1 + na]]
        except (IndexError, ValueError):
            # Short rows, non-numeric garbage — the uncaught-index-error
            # class a corrupted stdin used to surface raw.
            raise ParseError("Line is wrongly formatted", line=2 + i,
                             byte_offset=off) from None
        off += len(line) + 1

    ks = np.empty(nq, dtype=np.int32)
    query_attrs = np.empty((nq, na), dtype=np.float64)
    for i in range(nq):
        line = lines[1 + nd + i]
        if not line or line[0] != "Q":
            raise ParseError("Line is wrongly formatted",  # common.cpp:114
                             line=2 + nd + i, byte_offset=off)
        if "_" in line:
            raise ParseError("Line is wrongly formatted",
                             line=2 + nd + i, byte_offset=off)
        toks = line[1:].split()
        try:
            if len(toks) < 1 + na:
                raise IndexError   # see the data-row short-row guard
            ks[i] = int(toks[0])
            query_attrs[i] = [float(t) for t in toks[1 : 1 + na]]
        except (IndexError, ValueError):
            raise ParseError("Line is wrongly formatted",
                             line=2 + nd + i, byte_offset=off) from None
        off += len(line) + 1

    return KNNInput(params, labels, data_attrs, ks, query_attrs)


def format_input(inp: KNNInput, precision: int = 6) -> str:
    """Serialize a problem instance back to the input grammar.

    Inverse of :func:`parse_input_text`; matches generate_input.py:11-21
    formatting (%.6f attributes) so round-trips are byte-stable for
    generator-produced data.
    """
    out: List[str] = [
        f"{inp.params.num_data} {inp.params.num_queries} {inp.params.num_attrs}"
    ]
    fmt = f"%.{precision}f"
    for i in range(inp.params.num_data):
        attrs = " ".join(fmt % v for v in inp.data_attrs[i])
        out.append(f"{int(inp.labels[i])} {attrs}")
    for i in range(inp.params.num_queries):
        attrs = " ".join(fmt % v for v in inp.query_attrs[i])
        out.append(f"Q {int(inp.ks[i])} {attrs}")
    return "\n".join(out) + "\n"
