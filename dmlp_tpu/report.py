"""Perf trajectory report: render the ledger (obs.ledger) for humans + CI.

``python -m dmlp_tpu.report`` ingests every perf artifact at the repo
root — schema RunRecords and the grandfathered legacy ``BENCH_*/
SWEEP_*/TRAINBENCH_*/...`` shapes — into one versioned ledger and
renders it:

- a coverage block (how many artifacts parsed; the unparseable ones
  NAMED — never silently dropped);
- per-series round-over-round trajectories for every multi-round
  series, with noise-aware deltas (MAD bands over per-trial samples;
  explicit ``insufficient_trials`` / ``device_mismatch`` markers where
  an honest comparison is impossible);
- a roofline section summarizing the counters-era records
  (%-of-roof, extraction term provenance, obs overhead).

Usage::

    python -m dmlp_tpu.report [--root .] [--out LEDGER.json]
        [--md REPORT.md] [--json] [--min-coverage 0.9]

With no output flags the markdown report prints to stdout. ``--out``
writes the full ledger JSON (the machine-readable artifact
``tools/perf_gate.py`` and future rounds consume); ``--min-coverage``
exits nonzero when the parsed fraction drops below the floor — the
ledger-build smoke in ``make perf-gate``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List

from dmlp_tpu.obs.ledger import (LEDGER_SCHEMA, build_ledger,
                                 series_deltas)


def _fmt(v) -> str:
    if v is None:
        return "—"
    if isinstance(v, float):
        return f"{v:,.4g}" if abs(v) < 1e6 else f"{v:,.3e}"
    return str(v)


def _delta_cell(cmp: Dict[str, Any]) -> str:
    marker = cmp.get("marker")
    pct = cmp.get("delta_pct")
    pct_s = f"{pct:+.1f}%" if pct is not None else "n/a"
    if marker:
        return f"{pct_s} ({marker})"
    if cmp.get("regressed"):
        return f"**{pct_s} REGRESSED** (band ±{cmp['noise_band']:.3g})"
    if cmp.get("improved"):
        return f"{pct_s} improved (band ±{cmp['noise_band']:.3g})"
    return f"{pct_s} within noise (band ±{cmp['noise_band']:.3g})"


def _roofline_rows(ledger: Dict[str, Any]) -> List[str]:
    """Roofline/counters summary lines pulled from roofline-family
    series and RunRecord counters blocks."""
    rows = []
    for name, pts in sorted(ledger.get("series", {}).items()):
        if name.startswith("roofline/") or "pct_of_roof" in name \
                or "obs_overhead_pct" in name:
            for p in pts:
                rows.append(
                    f"- `{name}` r{p.get('round', '?')}: "
                    f"{_fmt(p['value'])}"
                    + (f" ({p['device']})"
                       if p.get("device") not in (None, "unspecified")
                       else ""))
    return rows


def _memory_rows(ledger: Dict[str, Any]) -> List[str]:
    """Memory section: the telemetry-era peak-HBM watermark series —
    analytic model, measured watermark (basis-labeled), the
    model-vs-measured delta, and the telemetry overhead A/B. Explicit
    ``mem_stats_unavailable`` marker series render as markers, never
    vanish."""
    rows = []
    for name, pts in sorted(ledger.get("series", {}).items()):
        low = name.lower()
        if not (low.startswith("telemetry/") and (
                "peak_hbm" in low or "mem_" in low
                or "overhead" in low or "watermark" in low)):
            continue
        for p in pts:
            val = p["value"]
            unit = ""
            if "bytes" in low and isinstance(val, (int, float)) \
                    and val > 1 << 20:
                unit = f" ({val / (1 << 20):.1f} MiB)"
            rows.append(
                f"- `{name}` r{p.get('round', '?')}: {_fmt(val)}{unit}"
                + (f" ({p['device']})"
                   if p.get("device") not in (None, "unspecified")
                   else ""))
    return rows


def _slo_rows(ledger: Dict[str, Any]) -> List[str]:
    """SLO section: the ``slo/<arm>/...`` ramp A/B series
    (fleet.loadgen.ramp_record) — per-arm breach cycles, worst burn
    rates, and peak-level latency, so the predictive-vs-reactive
    verdict reads off the report round-over-round."""
    rows = []
    for name, pts in sorted(ledger.get("series", {}).items()):
        if not name.startswith("slo/"):
            continue
        for p in pts:
            rows.append(
                f"- `{name}` r{p.get('round', '?')}: {_fmt(p['value'])}"
                + (f" ({p['device']})"
                   if p.get("device") not in (None, "unspecified")
                   else ""))
    return rows


def render_markdown(ledger: Dict[str, Any]) -> str:
    cov = ledger["coverage"]
    lines = ["# dmlp_tpu perf ledger", ""]
    lines.append(f"Ledger schema {LEDGER_SCHEMA} — {cov['files']} "
                 f"artifacts, {cov['parsed']} parsed "
                 f"({cov['fraction'] * 100:.0f}% coverage), "
                 f"{cov['unparseable']} unparseable.")
    if cov["unparseable_sources"]:
        lines.append("")
        lines.append("Unparseable (explicit, not dropped): "
                     + ", ".join(f"`{s}`"
                                 for s in cov["unparseable_sources"]))
    fams: Dict[str, int] = {}
    for e in ledger["entries"]:
        fams[e.get("family", "?")] = fams.get(e.get("family", "?"), 0) + 1
    lines += ["", "| family | artifacts |", "|---|---|"]
    lines += [f"| {f} | {n} |" for f, n in sorted(fams.items())]

    deltas = series_deltas(ledger)
    lines += ["", "## Round-over-round trajectories", ""]
    if deltas:
        lines += ["| series | rounds | prev → cur | delta |", "|---|---|---|---|"]
        for cmp in deltas:
            rounds = "→".join(f"r{r:02d}" for r in cmp["rounds"][-4:])
            lines.append(
                f"| `{cmp['series']}` | {rounds} "
                f"| {_fmt(cmp['prev'])} → {_fmt(cmp['cur'])} "
                f"| {_delta_cell(cmp)} |")
    else:
        lines.append("(no series spans more than one round yet)")
    single = sum(1 for pts in ledger["series"].values()
                 if len({p.get('round') for p in pts}) < 2)
    lines.append("")
    lines.append(f"{len(ledger['series'])} series total; {single} are "
                 "single-round (tracked, not yet comparable).")

    roof = _roofline_rows(ledger)
    if roof:
        lines += ["", "## Roofline & observability-cost records", ""]
        lines += roof

    slo = _slo_rows(ledger)
    if slo:
        lines += ["", "## SLO", "",
                  "Ramp A/B (fleet.loadgen --ramp): per-arm breach "
                  "cycles and burn rates under escalating offered "
                  "load — the predictive arm's contract is zero "
                  "breach cycles at levels where the reactive arm "
                  "fires.", ""]
        lines += slo

    mem = _memory_rows(ledger)
    if mem:
        lines += ["", "## Memory", "",
                  "Peak-HBM watermarks: analytic model (obs.memwatch) "
                  "vs measured basis, plus the telemetry overhead A/B "
                  "(tools/telemetry_smoke.py).", ""]
        lines += mem
    lines.append("")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="dmlp_tpu.report",
                                 description=__doc__)
    ap.add_argument("--root", default=".",
                    help="directory scanned for perf artifacts")
    ap.add_argument("--out", default=None, metavar="LEDGER.json",
                    help="write the full ledger JSON here")
    ap.add_argument("--md", default=None, metavar="REPORT.md",
                    help="write the markdown report here (default: "
                         "stdout when --out/--json are absent)")
    ap.add_argument("--json", action="store_true",
                    help="print the ledger JSON to stdout")
    ap.add_argument("--min-coverage", type=float, default=None,
                    help="exit 1 if the parsed fraction is below this "
                         "floor (the ledger-build smoke)")
    args = ap.parse_args(argv)

    # With --json, stdout carries ONLY the ledger document (consumers
    # json.loads it); all narration goes to stderr — same contract as
    # check_trace.py --dist --json.
    def say(msg: str) -> None:
        print(msg, file=sys.stderr if args.json else sys.stdout)

    ledger = build_ledger(args.root)
    md = render_markdown(ledger)
    if args.out:
        tmp = args.out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(ledger, f, indent=1, sort_keys=True)
        os.replace(tmp, args.out)
        cov = ledger["coverage"]
        say(f"report: ledger -> {args.out} ({cov['parsed']}/"
            f"{cov['files']} artifacts parsed, "
            f"{len(ledger['series'])} series)")
    if args.md:
        tmp = args.md + ".tmp"
        with open(tmp, "w") as f:
            f.write(md)
        os.replace(tmp, args.md)
        say(f"report: markdown -> {args.md}")
    if args.json:
        print(json.dumps(ledger, indent=1, sort_keys=True))
    if not (args.out or args.md or args.json):
        print(md)
    if args.min_coverage is not None:
        frac = ledger["coverage"]["fraction"]
        if frac < args.min_coverage:
            print(f"report: FAIL: ledger coverage {frac:.2f} < "
                  f"--min-coverage {args.min_coverage} "
                  f"(unparseable: "
                  f"{ledger['coverage']['unparseable_sources']})",
                  file=sys.stderr)
            return 1
        say(f"report: coverage {frac:.2f} >= {args.min_coverage} ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
