"""Engine configuration.

The reference spreads configuration over three mechanisms (survey §5.6):
compile-time ``-DDEBUG`` (Makefile:14), the generator's argparse CLI
(generate_input.py:27-40), and hardcoded SLURM configs in run_bench.sh:77-162.
Here everything is one dataclass; problem-size parameters still travel in-band
as the input header (common.cpp:12-15), exactly like the reference.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Configuration for the KNN engines.

    Attributes:
      mode: "single" | "sharded" | "ring" | "auto" — which engine to
        run. "single" is the one-chip engine; "sharded" is the 2D-mesh
        all-gather-merge engine (analog of the reference's grid +
        MPI_Gather merge, engine.cpp:40-57,282-308); "ring" streams data
        shards around the mesh ring with a running top-k (the
        long-context / memory-bounded variant); "auto" is the
        compiler-sharded engine (engine.auto): the same solve as pure
        jit + NamedSharding constraints, with GSPMD choosing the
        collective schedule instead of the hand-written merges.
      mesh_shape: (data_axis_size, query_axis_size). None = auto from
        available devices (mirrors MPI_Dims_create at engine.cpp:41).
      data_block: data points processed per inner step on one chip.
        Bounds the live distance-tile to query_block x data_block.
        None = pick per select strategy (2048 for "sort", whose per-step
        cost grows superlinearly in the block; 65536 for "topk", which
        prefers large tiles).
      query_block: queries processed per outer step.
      dtype: on-device staging/distance dtype ("auto", "float32" or
        "bfloat16"). The reference computes in float64 (engine.cpp:12);
        TPU MXU is f32/bf16, so strict-parity runs add host rescoring
        (``exact``). "auto" resolves to bfloat16 on TPU backends in exact
        mode — staging in bf16 halves the host->device bytes that bound
        the end-to-end solve on a transfer-limited link (measured 2.3x at
        200k x 10k, BENCH_BF16_r04.json) while the f64 rescore + the
        tie-overflow repair keep results identical — and to float32
        everywhere else (CPU bf16 is emulated and slower; fast mode's
        output IS the device ordering, so it never changes dtype
        implicitly).
      exact: if True, rescore the top-(k+margin) candidates on host in
        float64 and re-select — restores float64 ordering (and hence
        checksum parity with the golden model) while keeping the O(Q*N*A)
        work on the MXU.
      margin: extra candidates (beyond max-k) carried to the host rescore.
      select: device k-selection strategy. "sort" = strict total-order
        multi-operand sort (reference tie semantics on device, slow);
        "topk" = ``lax.top_k`` partial reduce, ~4x faster but
        tie-order-blind — engines detect candidate lists where a
        distance-tie group hit the boundary and recompute those queries
        exactly on host (engine.finalize.boundary_overflow), so ``run()``
        parity holds on either path; "seg" = segment-min threshold
        selection (ops.topk.step_seg): reduces each 128-column segment
        to its min and runs top_k on ~(k+16)*128 gathered candidates
        instead of the whole tile — exact by distance, with an in-jit
        fallback to "topk" when segment-min ties make the threshold
        inconclusive; "extract" = fused Pallas distance + in-VMEM
        iterative-extraction running top-k (ops.pallas_extract) — the
        distance tile never reaches HBM; exact by distance with the same
        lowest-position tie behavior (and host repair) as "topk";
        "auto" = "sort" for small inputs (ties can be adversarial there,
        cost is negligible), then "extract" with use_pallas (fastest,
        119 ms vs 231/400 at the benchmark shape on v5e) or "topk"
        without, once the padded dataset exceeds AUTO_SELECT_THRESHOLD.
      debug: human-readable output instead of checksums — the -DDEBUG
        build of the reference (common.cpp:72-78).
      use_pallas: use the fused Pallas distance kernel where available.
      precision: FIRST-PASS dot precision for the extract-path kernels
        ("auto" | "f32" | "bf16"). "bf16" casts the streamed q/d tiles
        before the MXU dot (one pass vs HIGHEST-precision f32's ~3)
        with f32 accumulation kept; the engines widen every candidate
        window / prune threshold / hazard test by the analytic
        engine.finalize.lowp_eps bound so the unchanged f64 rescore +
        boundary repair keeps results byte-identical to the f32 dense
        scan. Active only in exact mode on the resilience ladder's top
        "lowp" rung (fast mode's output IS the device ordering — no
        repair backstop). "auto" resolves to "f32" (opt-in: the win is
        MXU throughput, which a CPU container cannot show).
        $DMLP_TPU_PRECISION overrides at resolve time ("f32" = kill
        switch, "bf16" = force). int8 is the gated follow-on (ROADMAP):
        its bound needs data-dependent quantization scales.
    """

    AUTO_SELECT_THRESHOLD = 8192

    mode: str = "single"
    mesh_shape: Optional[Tuple[int, int]] = None
    data_block: Optional[int] = None
    query_block: int = 1024
    dtype: str = "auto"
    exact: bool = True
    margin: int = 16
    select: str = "auto"
    debug: bool = False
    use_pallas: bool = False
    precision: str = "auto"

    def __post_init__(self) -> None:
        if self.mode not in ("single", "sharded", "ring", "auto"):
            raise ValueError(f"unknown mode {self.mode!r}")
        if self.dtype not in ("auto", "float32", "bfloat16"):
            raise ValueError(f"unsupported dtype {self.dtype!r}")
        if self.precision not in ("auto", "f32", "bf16"):
            raise ValueError(
                f"unsupported first-pass precision {self.precision!r} "
                "(int8 is the gated follow-on — see ROADMAP)")
        if self.select not in ("auto", "sort", "topk", "seg", "extract"):
            raise ValueError(f"unknown select {self.select!r}")
        if (self.data_block is not None and self.data_block <= 0) \
                or self.query_block <= 0:
            raise ValueError("block sizes must be positive")
        if self.margin < 0:
            raise ValueError("margin must be >= 0")

    def resolve_dtype(self) -> str:
        """Concrete staging dtype ("float32" | "bfloat16") for this run.

        Resolved at engine construction (first backend touch), not in
        __post_init__, so building a config never initializes JAX.
        """
        if self.dtype != "auto":
            return self.dtype
        if not self.exact:
            return "float32"
        import jax
        try:
            platform = jax.devices()[0].platform
        except Exception:
            return "float32"
        return "bfloat16" if platform == "tpu" else "float32"

    def resolve_precision(self) -> str:
        """Concrete first-pass precision ("f32" | "bf16") for this run,
        env override included: ``$DMLP_TPU_PRECISION`` wins when set to
        a legal value ("f32" doubles as the kill switch, "bf16" forces
        the low-precision pass on), else the configured value, with
        "auto" resolving to "f32". Read per call (no import-time
        snapshot) so tests and operators can flip the env without
        re-imports — the engines resolve it OUTSIDE every jit and key
        their compiled programs on the result (R2 discipline). Fast
        mode always runs "f32": the low-precision pass is only sound
        with the f64 rescore + boundary repair behind it."""
        import os
        if not self.exact:
            return "f32"
        env = os.environ.get("DMLP_TPU_PRECISION")
        if env in ("f32", "bf16"):
            return env
        return "f32" if self.precision == "auto" else self.precision

    def resolve_select(self, padded_rows: int) -> str:
        """Concrete selection strategy for a dataset of ``padded_rows``."""
        if self.select != "auto":
            return self.select
        if padded_rows <= self.AUTO_SELECT_THRESHOLD:
            return "sort"
        # Measured on TPU v5e (204800x10240x64, k=40): "extract" (fused
        # distance + in-VMEM iterative extraction, ops.pallas_extract)
        # 119 ms; "seg" with the fused producer 231 ms; XLA "topk" ~400 ms.
        # The engines gate "extract" on pallas_extract.supports() per shape
        # and fall back to "seg"/"topk" when it cannot tile.
        return "extract" if self.use_pallas else "topk"

    def resolve_streaming_select(self, padded_rows: int) -> str:
        """Like resolve_select, for paths that fold blocks with arbitrary
        (non-affine) id arrays — the chunk-fold driver's fallback, the
        multi-host per-shard programs: the extraction kernel needs
        affine per-shard ids, so "extract" maps to the best array-ids
        strategy there. Paths that DO satisfy the affine-ids contract
        (engine.single's chunk loop, the mesh engines' contiguous shards)
        run "extract" natively and legitimately record it as
        _last_select; every run() tie-repair gate lists "extract"
        alongside "topk"/"seg" (same tie semantics)."""
        select = self.resolve_select(padded_rows)
        if select == "extract":
            from dmlp_tpu.ops.topk import streaming_fallback
            return streaming_fallback(self.use_pallas)
        return select

    def resolve_granule(self, select: str) -> int:
        """data_block granularity: whole 1024-column Pallas tiles for the
        fused seg producer, whole 128-column segments for XLA seg, whole
        extraction blocks (pallas_extract.BLOCK_ROWS) for "extract",
        8 rows otherwise (must stay in sync with
        ops.pallas_distance/pallas_extract supports)."""
        if select == "seg":
            return 1024 if self.use_pallas else 128
        if select == "extract":
            # Whole extraction blocks (ops.pallas_extract._TN): a merely
            # lane-divisible size can have no large divisor (200000 pads to
            # 512*391, 391 = 17*23, so the largest tileable block would be
            # 512 — measured 4x slower). Padding to whole blocks wastes
            # < _TN sentinel rows (~2% at the benchmark shape) and keeps
            # the block size maximal.
            from dmlp_tpu.ops.pallas_extract import BLOCK_ROWS
            return BLOCK_ROWS
        return 8

    def resolve_data_block(self, select: str) -> int:
        if self.data_block is not None:
            return self.data_block
        return 65536 if select in ("topk", "seg", "extract") else 2048
