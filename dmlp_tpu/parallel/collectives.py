"""Collective merge patterns over the mesh — the MPI choreography, reconceived.

Two ways to combine per-shard top-k lists across the ``"data"`` axis, both
exact because the selection key is a strict total order (dmlp_tpu.ops.topk):

- :func:`allgather_merge_topk` — one ``all_gather`` + re-select; the direct
  analog of the reference's candidate gather + root merge
  (engine.cpp:282-308), except every rank gets the result (no root, no
  second broadcast) and the fan-in covers the full data axis (the reference
  sized this with the wrong grid axis — survey §7 quirk Q4 — which the
  declarative form cannot even express).
- :func:`ring_allreduce_topk` — a ring all-reduce with top-k-merge as the
  combiner: R-1 ``ppermute`` hops of the O(k) accumulator. Peak memory O(k)
  instead of O(R*k), and each hop moves k candidates over one ICI link —
  the blockwise-ring pattern the survey (§5.7) maps to ring attention.

Both run inside ``shard_map`` over the mesh from dmlp_tpu.parallel.mesh.
"""

from __future__ import annotations

import jax

from dmlp_tpu.ops.topk import TopK, merge_topk, select_topk
from dmlp_tpu.utils.compat import axis_size


def allgather_merge_topk(local: TopK, k: int, axis_name: str) -> TopK:
    """All-gather per-shard candidates along ``axis_name`` and re-select k."""
    gathered = jax.tree.map(
        # check: comms-model=allgather_topk_traffic
        lambda x: jax.lax.all_gather(x, axis_name, axis=0, tiled=False), local)
    # (R, Q, K) -> (Q, R*K): per query, concatenate all shards' candidates.
    def flatten(x):
        r, q, kk = x.shape
        return x.transpose(1, 0, 2).reshape(q, r * kk)
    return select_topk(flatten(gathered.dists), flatten(gathered.labels),
                       flatten(gathered.ids), k)


def ring_allreduce_topk(local: TopK, k: int, axis_name: str) -> TopK:
    """Ring all-reduce with merge-top-k as the combiner.

    Invariant: after step t, rank r's accumulator covers shards
    {r-t, ..., r}; merging the incoming accumulator (shards
    {r-1-t, ..., r-1}) with rank r's own list extends coverage by one and
    never duplicates a shard (windows can't wrap in R-1 steps; shards are
    disjoint, so no candidate appears twice — duplicates would be able to
    evict genuine top-k entries).
    """
    n = axis_size(axis_name)
    if n == 1:
        # Still re-select: both merges promise selection-ordered output,
        # and the extraction kernel's per-shard lists arrive UNSORTED —
        # downstream tie-hazard checks and report_order read positions.
        return select_topk(local.dists, local.labels, local.ids, k)
    perm = [(j, (j + 1) % n) for j in range(n)]

    def body(acc: TopK, _):
        incoming = jax.tree.map(
            # check: comms-model=ring_topk_traffic
            lambda x: jax.lax.ppermute(x, axis_name, perm), acc)
        return merge_topk(incoming, local, k), None

    acc, _ = jax.lax.scan(body, local, None, length=n - 1)
    return acc
