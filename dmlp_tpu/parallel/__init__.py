from dmlp_tpu.parallel.mesh import make_mesh, balanced_dims, DATA_AXIS, QUERY_AXIS  # noqa: F401
from dmlp_tpu.parallel.collectives import ring_allreduce_topk, allgather_merge_topk  # noqa: F401
