"""Multi-host runtime: process bring-up and per-process sharded input feed.

The reference's multi-node story is mpirun spawning ranks across 2 SLURM
nodes (run_bench.sh:78-84), rank 0 reading the entire input and Scatterv-ing
it (common.cpp:93-117, engine.cpp:62-209) — a single-host ingest bottleneck
the survey (§7 "host input pipeline") flags. The TPU-native story:

- :func:`initialize` wraps ``jax.distributed.initialize`` (the
  MPI_Init/Finalize analog, survey §5.8) — call once per process before
  device use; no-op for single-process runs.
- :func:`shard_bounds` + :func:`read_data_shard` let every process parse
  only its own slice of the same input file (offset-indexed: one cheap
  newline scan, then the native/Python parser on the local byte range),
  preserving global ids by line order.
- :func:`make_global_dataset` assembles the per-process arrays into global
  jax.Arrays laid out on the ("data", "query") mesh via
  ``jax.make_array_from_process_local_data`` — the declarative Scatterv.

The sharded engines consume the resulting global arrays unchanged: on one
host this path is exercised end-to-end by tests; on a pod each process
feeds only its shard and XLA never moves the full dataset through one host.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import numpy as np

from dmlp_tpu.engine.finalize import boundary_hazard, staging_eps
from dmlp_tpu.parallel.mesh import DATA_AXIS, QUERY_AXIS
from dmlp_tpu.resilience import inject as rs_inject
from dmlp_tpu.resilience import retry as rs_retry


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               auto: bool = False) -> None:
    """Bring up the multi-process runtime (the MPI_Init analog).

    Explicit form: pass coordinator/num_processes/process_id (like mpirun
    passing rank/size). Auto form: ``auto=True`` calls bare
    ``jax.distributed.initialize()`` so managed environments (Cloud TPU
    pods, SLURM) self-detect topology. With neither, this is a no-op —
    suitable only for genuinely single-process runs; a pod launcher that
    skips both forms would silently see local chips only, so multi-host
    entry points should pass ``auto=True``.
    """
    if auto:
        jax.distributed.initialize()
        return
    if num_processes is None or num_processes <= 1:
        return
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def shard_bounds(n: int, num_shards: int, shard: int) -> Tuple[int, int]:
    """[start, stop) of shard's block in a length-n axis (remainder spread
    over the leading shards — balanced, unlike the reference's
    all-remainder-to-rank-0 choice at engine.cpp:62-63)."""
    base, rem = divmod(n, num_shards)
    start = shard * base + min(shard, rem)
    return start, start + base + (1 if shard < rem else 0)


def line_offsets(data: bytes) -> np.ndarray:
    """Byte offset of every line start (one vectorized newline scan)."""
    nl = np.flatnonzero(np.frombuffer(data, np.uint8) == ord("\n"))
    return np.concatenate([[0], nl + 1]).astype(np.int64)


def read_row_range(path: str, start: int, stop: int):
    """Parse data rows [start, stop) (plus all query lines) from the
    canonical input file — one vectorized newline scan over an mmap, then
    the native/Python parser on just the local byte range.

    The mmap keeps per-process HELD memory proportional to the local
    shard: the newline scan touches every page once (an index must see
    every byte), but pages stay in the evictable OS cache rather than a
    process-private heap buffer, and only the local rows + queries are
    ever copied out.

    Returns (params, local_labels, local_attrs, ks, query_attrs); queries
    are replicated (they are small and every process needs them to build
    the query-axis feed and to finalize).
    """
    import mmap

    from dmlp_tpu.io.grammar import parse_params

    with open(path, "rb") as f:
        raw = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
    try:
        offs = line_offsets(raw)
        header = raw[offs[0]:offs[1]].decode("ascii")
        params = parse_params(header)
        nd = params.num_data
        stop = min(stop, nd)
        start = min(start, stop)

        # Reassemble a small instance: header + local data lines + queries
        # (slicing an mmap copies just those byte ranges).
        local_bytes = (
            f"{stop - start} {params.num_queries} {params.num_attrs}\n"
            .encode("ascii")
            + raw[offs[1 + start]:offs[1 + stop]]
            + raw[offs[1 + nd]:])
    finally:
        raw.close()
    # io.BytesIO -> parse_input routes large shards through the native C++
    # tokenizer (bytes pass straight through, no decode round-trip).
    import io as _io
    from dmlp_tpu.io.grammar import parse_input
    sub = parse_input(_io.BytesIO(local_bytes))
    return params, sub.labels, sub.data_attrs, sub.ks, sub.query_attrs


def read_data_shard(path: str, num_shards: int, shard: int):
    """Parse only this shard's (balanced, shard_bounds) data lines plus all
    query lines. Returns (params, labels, attrs, start, ks, query_attrs)."""
    with open(path, "rb") as f:
        header = f.readline().decode("ascii")
    from dmlp_tpu.io.grammar import parse_params
    nd = parse_params(header).num_data
    start, stop = shard_bounds(nd, num_shards, shard)
    params, labels, attrs, ks, q_attrs = read_row_range(path, start, stop)
    return params, labels, attrs, start, ks, q_attrs


def process_slice(sharding, global_shape) -> Tuple[int, int]:
    """This process's contiguous [start, stop) block along axis 0, derived
    from the sharding itself.

    ``shard_bounds(process_id)``-style arithmetic silently assumes process
    boundaries align with mesh-axis positions; on a mesh where one
    process's devices span several positions of an axis (2 processes x 4
    devices on a (4, 2) mesh) that assumption feeds the wrong rows. The
    sharding's own ``addressable_devices_indices_map`` is the ground truth
    for what this process must supply. Raises if the addressable block is
    not contiguous (a mesh/process layout this feed does not support).
    """
    imap = sharding.addressable_devices_indices_map(tuple(global_shape))
    spans = sorted({(idx[0].start or 0,
                     global_shape[0] if idx[0].stop is None else idx[0].stop)
                    for idx in imap.values()})
    lo, hi = spans[0][0], max(e for _, e in spans)
    cur = lo
    for s, e in spans:
        if s > cur:
            raise ValueError(
                f"process-addressable block not contiguous: gap [{cur},{s}) "
                f"(spans {spans}); choose a mesh whose data/query axes align "
                "with process boundaries")
        cur = max(cur, e)
    return lo, hi


def build_global(sharding, global_shape, local_np: np.ndarray, lo: int):
    """Assemble a global array from this process's local block.

    ``local_np`` holds rows [lo, lo + len) of axis 0 (the process's
    process_slice block); every other axis is full-size. The callback form
    serves exactly the shards this process's devices need — the declarative
    Scatterv, correct for any process-to-mesh layout.
    """
    def cb(index):
        sl = index[0]
        start = sl.start or 0
        stop = global_shape[0] if sl.stop is None else sl.stop
        return local_np[start - lo:stop - lo]

    return jax.make_array_from_callback(tuple(global_shape), sharding, cb)


def padded_shard(labels: np.ndarray, attrs: np.ndarray, start: int,
                 uniform_rows: int):
    """Pad one process's data rows to ``uniform_rows`` with sentinel rows
    (label = id = -1, masked to +inf by the distance kernel) — every
    process must contribute identical local shapes to
    jax.make_array_from_process_local_data. Global ids come from ``start``
    (the shard's first global line index)."""
    n, na = attrs.shape
    assert n <= uniform_rows
    out_attrs = np.zeros((uniform_rows, na), np.float32)
    out_attrs[:n] = attrs
    out_labels = np.full(uniform_rows, -1, np.int32)
    out_labels[:n] = labels
    out_ids = np.full(uniform_rows, -1, np.int32)
    out_ids[:n] = np.arange(start, start + n, dtype=np.int32)
    return out_attrs, out_labels, out_ids


def plan_shapes(engine, n: int, nq: int):
    """Global padded shapes for the sharded feed — identical on every
    process (pure function of the header + engine config/mesh)."""
    from dmlp_tpu.engine.single import round_up

    cfg = engine.config
    r, c = engine.mesh.devices.shape
    rows_est = round_up(max(-(-n // r), 1), 8)
    qgran = 8
    if cfg.data_block is None and cfg.resolve_select(rows_est) == "extract":
        # The per-shard solver (_plan_shard) will pick the extraction
        # kernel when these shapes support it, so pad shards to whole
        # extraction blocks and query shards to whole query tiles — a
        # merely-lane-divisible shard would tile degenerately (see
        # config.resolve_granule). If _plan_shard later falls back (e.g.
        # kcap past the kernel's cap), the streaming selects still accept
        # these shapes, just at non-ideal blocking — slower, never wrong.
        from dmlp_tpu.ops.pallas_extract import QUERY_TILE
        granule = cfg.resolve_granule("extract")
        qgran = QUERY_TILE
    else:
        granule = cfg.resolve_granule(cfg.resolve_streaming_select(rows_est))
    shard_rows = round_up(max(-(-n // r), 1), granule)
    qpad = c * round_up(max(-(-nq // c), 1), qgran)
    return r * shard_rows, shard_rows, qpad


def read_local_inputs(path: str, engine) -> dict:
    """Per-process sharded file read (host parse only, no device work).

    Each process derives its data/query blocks from the shardings
    themselves (process_slice), parses only those file rows, and returns
    everything place_global_inputs needs — no host ever ingests the full
    dataset (the survey's rank-0 bottleneck, common.cpp:93-117). Split
    from placement so the contract timer can start after parsing, like the
    reference's (common.cpp: parse, barrier, then start_time)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = engine.mesh
    with open(path, "rb") as f:
        header = f.readline().decode("ascii")
    from dmlp_tpu.io.grammar import parse_params
    hdr = parse_params(header)
    n, nq, na = hdr.num_data, hdr.num_queries, hdr.num_attrs
    npad, shard_rows, qpad = plan_shapes(engine, n, nq)

    dsh2 = NamedSharding(mesh, P(DATA_AXIS, None))
    qsh = NamedSharding(mesh, P(QUERY_AXIS, None))

    dlo, dhi = process_slice(dsh2, (npad, na))
    params, labels, attrs, ks, q_attrs = read_row_range(path, dlo, dhi)
    p_attrs, p_labels, p_ids = padded_shard(labels, attrs, dlo, dhi - dlo)

    qlo, qhi = process_slice(qsh, (qpad, na))
    q_local = np.zeros((qhi - qlo, na), np.float32)
    src = q_attrs[qlo:min(qhi, nq)]
    q_local[:src.shape[0]] = src

    local = {"data_attrs": attrs, "data_labels": labels, "offset": dlo,
             "shard_rows": shard_rows, "query_attrs": q_attrs}
    return {"params": params, "ks": ks, "local": local,
            "npad": npad, "qpad": qpad, "na": na,
            "p_attrs": p_attrs, "p_labels": p_labels, "p_ids": p_ids,
            "dlo": dlo, "q_local": q_local, "qlo": qlo}


def place_global_inputs(engine, parsed: dict):
    """Parsed per-process blocks -> global mesh arrays (the Scatterv
    analog, engine.cpp:62-209 — device placement only, belongs inside the
    contract's timed region). Returns (ga, gl, gi, gq)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    ga, gl, gi = place_global_data(engine, parsed)
    qsh = NamedSharding(engine.mesh, P(QUERY_AXIS, None))
    gq = build_global(qsh, (parsed["qpad"], parsed["na"]),
                      parsed["q_local"].astype(
                          engine._np_dtype(), copy=False),
                      parsed["qlo"])
    return ga, gl, gi, gq


def place_global_data(engine, parsed: dict):
    """Data-side placement only (attrs/labels/ids) — the heterogeneous-k
    router shares this across query segments instead of paying an unused
    full-query placement inside the timed region."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = engine.mesh
    npad, na = parsed["npad"], parsed["na"]
    dsh2 = NamedSharding(mesh, P(DATA_AXIS, None))
    dsh1 = NamedSharding(mesh, P(DATA_AXIS))
    # Stage attrs in the engine's resolved dtype: each process converts
    # its own shard on host, so bf16 halves the per-host feed bytes (the
    # DCN-side analog of the single-chip staging win, BENCH_BF16_r04).
    np_dtype = engine._np_dtype()
    ga = build_global(dsh2, (npad, na),
                      parsed["p_attrs"].astype(np_dtype, copy=False),
                      parsed["dlo"])
    gl = build_global(dsh1, (npad,), parsed["p_labels"], parsed["dlo"])
    gi = build_global(dsh1, (npad,), parsed["p_ids"], parsed["dlo"])
    return ga, gl, gi


def stage_global_inputs(path: str, engine):
    """Sharded file read + global mesh placement in one call.

    Returns (ga, gl, gi, gq, params, ks, local), where ``local`` carries
    what finalization needs later: this process's f64 data block + offset
    and the full f64 query attrs.
    """
    parsed = read_local_inputs(path, engine)
    ga, gl, gi, gq = place_global_inputs(engine, parsed)
    return ga, gl, gi, gq, parsed["params"], parsed["ks"], parsed["local"]


def place_query_subset(engine, q64: np.ndarray, idx: np.ndarray,
                       qgran: int):
    """Global query-axis placement of the query rows in ``idx``.

    Queries are replicated on every process (read_row_range), so each
    process can serve any slice of the padded subset directly — used by
    the heterogeneous-k router to feed each segment its own query array
    while the (large) data placement is shared. Returns (global_array,
    qpad)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dmlp_tpu.engine.single import round_up

    mesh = engine.mesh
    c = mesh.devices.shape[1]
    na = q64.shape[1]
    nqs = len(idx)
    qpad = c * round_up(max(-(-nqs // c), 1), qgran)
    # Stage through f32 like every other site (f64 -> f32 -> bf16): a
    # direct f64 -> bf16 round can differ in the last ulp near a bf16
    # midpoint, and staged bytes stay bit-identical across paths.
    qh = np.zeros((qpad, na), np.float32)
    qh[:nqs] = q64[idx]
    qh = qh.astype(engine._np_dtype(), copy=False)
    qsh = NamedSharding(mesh, P(QUERY_AXIS, None))
    return jax.make_array_from_callback(
        (qpad, na), qsh, lambda ix: qh[ix]), qpad


def sharded_solve_from_file(path: str, engine):
    """Whole multi-host feed: sharded read -> global arrays -> the engine's
    compiled sharded (merged) program. Returns (TopK, params, ks) — the
    caller finalizes. For the full contract run (distributed f64 rescore +
    rank-0 report) use distributed_contract_run instead.
    """
    from dmlp_tpu.engine.single import staging_for_k

    parsed = read_local_inputs(path, engine)
    params, ks = parsed["params"], parsed["ks"]
    kmax = int(ks.max()) if params.num_queries else 1
    # Wide-k solves stage f32 under dtype="auto" (engine.single
    # .staging_for_k): the context spans placement AND solve so the
    # staged wire dtype, the kcap margin, and the hazard eps agree.
    with staging_for_k(engine, kmax):
        ga, gl, gi, gq = place_global_inputs(engine, parsed)
        top = engine.solve_global(ga, gl, gi, gq, kmax)
    from dmlp_tpu.engine.single import flush_measured_iters
    flush_measured_iters(engine)
    return top, params, ks


def _exact_shard_topk(q64: np.ndarray, d64: np.ndarray, labels: np.ndarray,
                      id_base: np.ndarray, k: int):
    """Exact f64 top-k of one query over one data shard, by the selection
    total order (dist asc, id desc — the measured label-free
    oracle-binary comparator, golden.reference). The per-query repair for
    f32 tie-boundary hazards — all inputs are local to the owning process.
    """
    diff = d64 - q64[None, :]
    dist = np.einsum("na,na->n", diff, diff)
    order = np.lexsort((-id_base, dist))[:k]
    out_d = np.full(k, np.inf)
    out_l = np.full(k, -1, np.int32)
    out_i = np.full(k, -1, np.int32)
    m = len(order)
    out_d[:m] = dist[order]
    out_l[:m] = labels[order]
    out_i[:m] = id_base[order]
    return out_d, out_l, out_i


def rescore_local_shards(top, local, ks: np.ndarray, nq: int,
                         staging: str = "float32"):
    """Distributed float64 rescore: each process rescores the candidates of
    the data shards it owns, using only its own f64 rows.

    ``top`` is the (R, Qpad, K) per-shard TopK from solve_local_shards.
    Returns (R, Qpad, K) numpy arrays (f64 dists / labels / ids) holding
    this process's cells, +inf/-1 elsewhere — elementwise min/max across
    processes then reconstructs the full tensors (each cell has exactly one
    owner). Per-shard f32 tie-boundary hazards (candidate truncation, see
    engine.finalize.boundary_overflow) are repaired here from local f64
    data, so no golden-model pass over the full dataset is ever needed.
    """
    r_axis, qpad, kcap = top.dists.shape
    my_d = np.full((r_axis, qpad, kcap), np.inf)
    my_l = np.full((r_axis, qpad, kcap), -1, np.int32)
    my_i = np.full((r_axis, qpad, kcap), -1, np.int32)

    attrs64 = np.asarray(local["data_attrs"], np.float64)
    labels_loc = np.asarray(local["data_labels"])
    offset, shard_rows = local["offset"], local["shard_rows"]
    q64 = np.asarray(local["query_attrs"], np.float64)
    nreal = attrs64.shape[0]
    ks = np.asarray(ks)

    d_shards = {(s.index[0].start or 0, s.index[1].start or 0): s
                for s in top.dists.addressable_shards}
    l_shards = {(s.index[0].start or 0, s.index[1].start or 0): s
                for s in top.labels.addressable_shards}
    for s in top.ids.addressable_shards:
        r0 = s.index[0].start or 0
        q0 = s.index[1].start or 0
        qs = s.index[1]
        q1 = qpad if qs.stop is None else qs.stop
        ids_blk = np.array(s.data)[0]                      # (qloc, K), owned
        f32_blk = np.asarray(d_shards[(r0, q0)].data)[0]
        lab_blk = np.array(l_shards[(r0, q0)].data)[0]
        qrows = np.arange(q0, q1)

        if nreal == 0 or nq == 0:
            # All-padding shard (small n on a wide mesh) or no queries:
            # every candidate is a sentinel; nothing to rescore.
            my_d[r0, q0:q1] = np.inf
            my_l[r0, q0:q1] = -1
            my_i[r0, q0:q1] = -1
            continue

        # f64 rescore of this shard's candidates (ids are global rows in
        # [offset, offset + nreal) or -1); padded query rows (>= nq) score
        # against query 0 and are discarded at finalize.
        safe = np.clip(ids_blk - offset, 0, nreal - 1)
        gathered = attrs64[safe]                           # (qloc, K, A)
        qv = q64[np.minimum(qrows, nq - 1)]                # (qloc, A)
        diff = gathered - qv[:, None, :]
        d64 = np.einsum("qka,qka->qk", diff, diff)
        d64[ids_blk < 0] = np.inf

        # Per-shard tie-boundary repair, from local f64 data only. The
        # truncation gate uses THIS shard's real row count (sh_hi - sh_lo):
        # a candidate list that already holds every real row of the shard
        # cannot have truncated anything, even when the process's full
        # block (nreal rows across several shards) is wider than kcap.
        sh_lo = r0 * shard_rows - offset
        sh_hi = min(sh_lo + shard_rows, nreal)
        ks_blk = np.minimum(ks[np.minimum(qrows, max(nq - 1, 0))], kcap)
        kth = f32_blk[np.arange(q1 - q0), np.clip(ks_blk - 1, 0, kcap - 1)]
        # eps-widened truncation test (engine.finalize.staging_eps): a
        # staging dtype with non-monotone rounding (bf16) can displace a
        # true neighbor past the shard horizon without an exact tie. The
        # shard's own f64 rows bound the missed point's norm — it lives
        # in this shard by construction.
        qn_blk = np.einsum("qa,qa->q", qv, qv)
        dn_max_sh = (float(np.einsum("na,na->n", attrs64[sh_lo:sh_hi],
                                     attrs64[sh_lo:sh_hi]).max())
                     if sh_hi > sh_lo else 0.0)
        last_blk = np.asarray(f32_blk[:, -1], np.float64)
        eps = staging_eps(last_blk, qn_blk, dn_max_sh, staging,
                          attrs64.shape[1])
        hazard = boundary_hazard(kth, last_blk, eps) \
            & (qrows < nq) & (kcap < sh_hi - sh_lo)
        if hazard.any():
            base_ids = np.arange(offset + sh_lo, offset + sh_hi,
                                 dtype=np.int32)
            for j in np.nonzero(hazard)[0]:
                d64[j], lab_blk[j], ids_blk[j] = _exact_shard_topk(
                    q64[qrows[j]], attrs64[sh_lo:sh_hi],
                    labels_loc[sh_lo:sh_hi], base_ids, kcap)

        my_d[r0, q0:q1] = d64
        my_l[r0, q0:q1] = lab_blk
        my_i[r0, q0:q1] = ids_blk
    return my_d, my_l, my_i


def distributed_contract_run(path: str, engine, out=None, err=None,
                             warmup: bool = False):
    """The end-to-end multi-host contract run — the TPU-native form of
    ``mpirun ./engine < input`` (common.cpp:81-135 + run_bench.sh:82-84).

    Per process: sharded file read (no rank-0 ingest) -> per-shard device
    top-k (no f32 cross-shard merge) -> distributed f64 rescore + tie
    repair on the owning process -> host all-gather of the tiny candidate
    tensors -> every process merges/finalizes, process 0 prints the
    canonical stdout in query order + the ``Time taken`` stderr line.
    No host ever touches the full f64 dataset.
    """
    import sys
    import time

    from dmlp_tpu.engine.finalize import finalize_host
    from dmlp_tpu.io.report import format_results
    from dmlp_tpu.obs import dist_trace
    from dmlp_tpu.obs.trace import span as obs_span

    out = out if out is not None else sys.stdout
    err = err if err is not None else sys.stderr

    # Parse outside the timed region (the reference starts its timer after
    # rank-0 stdin ingest, common.cpp:119-124); device placement — the
    # Scatterv analog — happens inside solve(), which IS timed there.
    with obs_span("dist.read_local_inputs"):
        parsed = read_local_inputs(path, engine)
    params, ks, local = parsed["params"], parsed["ks"], parsed["local"]

    def solve_segment(ga, gl, gi, gq, ks_seg, q64_seg, idx):
        """Per-shard solve + distributed f64 rescore + host all-gather +
        finalize for one query segment (the whole query set when idx is
        None)."""
        nqs = len(ks_seg)
        kmax = int(ks_seg.max()) if nqs else 1

        def _solve_op():
            rs_inject.fire("dist.rank_solve", rank=jax.process_index())
            return engine.solve_local_shards(ga, gl, gi, gq, kmax)

        with obs_span("dist.solve_local_shards", nq=nqs, kmax=kmax) as sp:
            # Re-dispatch on the same placed global arrays is idempotent;
            # a transient per-rank dispatch failure retries locally
            # (collective-free per-shard solve) instead of failing the
            # whole cluster.
            top = rs_retry.call_with_retry(_solve_op, "dist.rank_solve")
            sp.fence(top.dists)
        # The fence above synchronized the per-shard solve: drain the
        # measured extract-iters queue now (scalar readback) so the
        # multi-host path's counters also report extraction_term=
        # measured when a probe is installed.
        from dmlp_tpu.engine.single import flush_measured_iters
        flush_measured_iters(engine)
        local_s = dict(local, query_attrs=q64_seg)
        with obs_span("dist.rescore_local_shards", nq=nqs):
            my_d, my_l, my_i = rescore_local_shards(
                top, local_s, ks_seg, nqs,
                staging=engine._staging)

        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            # nbytes is the REAL payload; the shape args let
            # tools/merge_traces.py recompute the analytic expectation
            # (obs.comms.host_allgather_candidates_traffic) and
            # reconcile the two per rank.
            def _gather_op():
                rs_inject.fire("dist.allgather", rank=jax.process_index())
                return (multihost_utils.process_allgather(my_d),
                        multihost_utils.process_allgather(my_l),
                        multihost_utils.process_allgather(my_i))

            with obs_span("dist.allgather_candidates",
                          nbytes=int(my_d.nbytes + my_l.nbytes
                                     + my_i.nbytes),
                          ranks=int(jax.process_count()),
                          r_shards=int(my_d.shape[0]),
                          qpad=int(my_d.shape[1]),
                          kcap=int(my_d.shape[2]),
                          itemsizes=[int(my_d.dtype.itemsize),
                                     int(my_l.dtype.itemsize),
                                     int(my_i.dtype.itemsize)]):
                all_d, all_l, all_i = rs_retry.call_with_retry(
                    _gather_op, "dist.allgather")
            my_d = all_d.min(axis=0)
            my_l = all_l.max(axis=0)
            my_i = all_i.max(axis=0)

        # (R, Qpad, K) -> (Q, R*K): per query, all shards' candidates.
        r_axis, qpad, kcap = my_d.shape
        flat = lambda x: x.transpose(1, 0, 2).reshape(qpad, r_axis * kcap)  # noqa: E731
        with obs_span("dist.finalize", nq=nqs):
            return finalize_host(flat(my_d)[:nqs], flat(my_l)[:nqs],
                                 flat(my_i)[:nqs], ks_seg, q64_seg, None,
                                 exact=False, query_ids=idx)

    def solve():
        from dmlp_tpu.engine.single import hetk_split, round_up

        nq = params.num_queries
        n = params.num_data
        r = engine.mesh.devices.shape[0]
        split = hetk_split(engine.config, engine._staging,
                           ks, n, round_up(max(-(-n // r), 1), 8))
        if split is None:
            with obs_span("dist.place_global_inputs"):
                ga, gl, gi, gq = place_global_inputs(engine, parsed)
            return solve_segment(ga, gl, gi, gq, ks,
                                 local["query_attrs"], None)

        # Heterogeneous-k routing, multi-host form: the (large) data
        # placement is shared; each segment gets its own query-axis feed
        # (queries are replicated per process) — bulk on the per-shard
        # extraction kernel, wide-k outliers on the streaming select.
        from dmlp_tpu.ops.pallas_extract import QUERY_TILE
        bulk_idx, out_idx = split
        ga, gl, gi = place_global_data(engine, parsed)
        merged = [None] * nq
        q64 = local["query_attrs"]
        for idx, qgran in ((bulk_idx, QUERY_TILE), (out_idx, 8)):
            gq_s, _ = place_query_subset(engine, q64, idx, qgran)
            for res in solve_segment(ga, gl, gi, gq_s, ks[idx],
                                     q64[idx], idx):
                merged[res.query_id] = res
        return merged

    from dmlp_tpu.engine.single import staging_for_k
    kmax_all = int(ks.max()) if params.num_queries else 0
    with staging_for_k(engine, kmax_all):
        if warmup:
            with obs_span("dist.warmup"):
                solve()
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("dmlp_tpu.contract.start")
        # The barrier releases every rank within network latency of one
        # wall instant — the clock-sync stamp merge_traces aligns rank
        # timelines on. Single-process runs stamp here too (zero-offset
        # reference point, so the merge tool needs no special case).
        dist_trace.clock_sync()
        t0 = time.perf_counter()
        with obs_span("dist.solve", rank=jax.process_index(),
                      nq=params.num_queries, n=params.num_data):
            results = solve()
        elapsed_ms = (time.perf_counter() - t0) * 1e3
    if jax.process_index() == 0:
        out.write(format_results(results, debug=engine.config.debug))
        err.write(f"Time taken: {int(round(elapsed_ms))} ms\n")
    return results


def make_global_dataset(mesh: jax.sharding.Mesh, local_attrs: np.ndarray,
                        local_labels: np.ndarray, local_ids: np.ndarray):
    """Per-process data shards -> global arrays sharded on the "data" axis.

    Each process passes the rows it read (padded so every process
    contributes the same row count — jax.make_array_from_process_local_data
    requires uniform shards). Returns (attrs, labels, ids) global arrays
    placed P("data", None) / P("data") on the mesh.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    sh2 = NamedSharding(mesh, P(DATA_AXIS, None))
    sh1 = NamedSharding(mesh, P(DATA_AXIS))
    return (jax.make_array_from_process_local_data(sh2, local_attrs),
            jax.make_array_from_process_local_data(sh1, local_labels),
            jax.make_array_from_process_local_data(sh1, local_ids))


def make_global_queries(mesh: jax.sharding.Mesh, local_q_attrs: np.ndarray):
    """Per-process query shards -> a global array sharded on "query"."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    qsh = NamedSharding(mesh, P(QUERY_AXIS, None))
    return jax.make_array_from_process_local_data(qsh, local_q_attrs)
