"""Multi-host runtime: process bring-up and per-process sharded input feed.

The reference's multi-node story is mpirun spawning ranks across 2 SLURM
nodes (run_bench.sh:78-84), rank 0 reading the entire input and Scatterv-ing
it (common.cpp:93-117, engine.cpp:62-209) — a single-host ingest bottleneck
the survey (§7 "host input pipeline") flags. The TPU-native story:

- :func:`initialize` wraps ``jax.distributed.initialize`` (the
  MPI_Init/Finalize analog, survey §5.8) — call once per process before
  device use; no-op for single-process runs.
- :func:`shard_bounds` + :func:`read_data_shard` let every process parse
  only its own slice of the same input file (offset-indexed: one cheap
  newline scan, then the native/Python parser on the local byte range),
  preserving global ids by line order.
- :func:`make_global_dataset` assembles the per-process arrays into global
  jax.Arrays laid out on the ("data", "query") mesh via
  ``jax.make_array_from_process_local_data`` — the declarative Scatterv.

The sharded engines consume the resulting global arrays unchanged: on one
host this path is exercised end-to-end by tests; on a pod each process
feeds only its shard and XLA never moves the full dataset through one host.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import numpy as np

from dmlp_tpu.parallel.mesh import DATA_AXIS, QUERY_AXIS


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               auto: bool = False) -> None:
    """Bring up the multi-process runtime (the MPI_Init analog).

    Explicit form: pass coordinator/num_processes/process_id (like mpirun
    passing rank/size). Auto form: ``auto=True`` calls bare
    ``jax.distributed.initialize()`` so managed environments (Cloud TPU
    pods, SLURM) self-detect topology. With neither, this is a no-op —
    suitable only for genuinely single-process runs; a pod launcher that
    skips both forms would silently see local chips only, so multi-host
    entry points should pass ``auto=True``.
    """
    if auto:
        jax.distributed.initialize()
        return
    if num_processes is None or num_processes <= 1:
        return
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def shard_bounds(n: int, num_shards: int, shard: int) -> Tuple[int, int]:
    """[start, stop) of shard's block in a length-n axis (remainder spread
    over the leading shards — balanced, unlike the reference's
    all-remainder-to-rank-0 choice at engine.cpp:62-63)."""
    base, rem = divmod(n, num_shards)
    start = shard * base + min(shard, rem)
    return start, start + base + (1 if shard < rem else 0)


def line_offsets(data: bytes) -> np.ndarray:
    """Byte offset of every line start (one vectorized newline scan)."""
    nl = np.flatnonzero(np.frombuffer(data, np.uint8) == ord("\n"))
    return np.concatenate([[0], nl + 1]).astype(np.int64)


def read_data_shard(path: str, num_shards: int, shard: int):
    """Parse only this shard's data lines (plus all query lines) from the
    canonical input file.

    Returns (params, local_labels, local_attrs, local_start, ks,
    query_attrs): data arrays cover rows [local_start, local_stop) of the
    global dataset; queries are replicated (they are small and every
    process needs them to build the query-axis feed).
    """
    from dmlp_tpu.io.grammar import parse_params

    with open(path, "rb") as f:
        raw = f.read()
    offs = line_offsets(raw)
    header = raw[offs[0]:offs[1]].decode("ascii")
    params = parse_params(header)
    nd = params.num_data

    start, stop = shard_bounds(nd, num_shards, shard)
    # Reassemble a small instance: header + local data lines + queries.
    local_bytes = (f"{stop - start} {params.num_queries} {params.num_attrs}\n"
                   .encode("ascii")
                   + raw[offs[1 + start]:offs[1 + stop]]
                   + raw[offs[1 + nd]:])
    # io.BytesIO -> parse_input routes large shards through the native C++
    # tokenizer (bytes pass straight through, no decode round-trip).
    import io as _io
    from dmlp_tpu.io.grammar import parse_input
    sub = parse_input(_io.BytesIO(local_bytes))
    return (params, sub.labels, sub.data_attrs, start, sub.ks,
            sub.query_attrs)


def padded_shard(labels: np.ndarray, attrs: np.ndarray, start: int,
                 uniform_rows: int):
    """Pad one process's data rows to ``uniform_rows`` with sentinel rows
    (label = id = -1, masked to +inf by the distance kernel) — every
    process must contribute identical local shapes to
    jax.make_array_from_process_local_data. Global ids come from ``start``
    (the shard's first global line index)."""
    n, na = attrs.shape
    assert n <= uniform_rows
    out_attrs = np.zeros((uniform_rows, na), np.float32)
    out_attrs[:n] = attrs
    out_labels = np.full(uniform_rows, -1, np.int32)
    out_labels[:n] = labels
    out_ids = np.full(uniform_rows, -1, np.int32)
    out_ids[:n] = np.arange(start, start + n, dtype=np.int32)
    return out_attrs, out_labels, out_ids


def sharded_solve_from_file(path: str, engine, num_processes: int = 1,
                            process_id: int = 0):
    """Whole multi-host feed: offset-indexed shard read -> uniform padding
    -> global mesh arrays -> the engine's compiled sharded program.

    Each process parses only its slice of the input file and contributes it
    via make_global_dataset — no host ever ingests the full dataset (the
    survey's rank-0 bottleneck). Queries are replicated per process and
    sharded over the "query" axis. Returns (TopK, params, ks) — the caller
    finalizes (on one host with the full f64 data for exact mode, or
    per-shard in fast mode).
    """
    from dmlp_tpu.engine.single import round_up

    mesh = engine.mesh
    r, c = mesh.devices.shape
    params, labels, attrs, start, ks, q_attrs = read_data_shard(
        path, num_processes, process_id)
    # Uniform local rows, and the r mesh shards must divide the global row
    # count: round the per-process rows so num_processes * rows % r == 0.
    rows = round_up(-(-params.num_data // num_processes), 8 * r)
    p_attrs, p_labels, p_ids = padded_shard(labels, attrs, start, rows)
    ga, gl, gi = make_global_dataset(mesh, p_attrs, p_labels, p_ids)

    nq = params.num_queries
    qpad = c * round_up(max(-(-nq // c), 1), 8)
    assert qpad % num_processes == 0, \
        f"padded query count {qpad} must divide across {num_processes} procs"
    q_local = np.zeros((qpad // num_processes, q_attrs.shape[1]), np.float32)
    lo, hi = shard_bounds(qpad, num_processes, process_id)
    src = q_attrs[lo:min(hi, nq)]
    q_local[:src.shape[0]] = src
    gq = make_global_queries(mesh, q_local)

    kmax = int(ks.max()) if nq else 1
    top = engine.solve_global(ga, gl, gi, gq, kmax)
    return top, params, ks


def make_global_dataset(mesh: jax.sharding.Mesh, local_attrs: np.ndarray,
                        local_labels: np.ndarray, local_ids: np.ndarray):
    """Per-process data shards -> global arrays sharded on the "data" axis.

    Each process passes the rows it read (padded so every process
    contributes the same row count — jax.make_array_from_process_local_data
    requires uniform shards). Returns (attrs, labels, ids) global arrays
    placed P("data", None) / P("data") on the mesh.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    sh2 = NamedSharding(mesh, P(DATA_AXIS, None))
    sh1 = NamedSharding(mesh, P(DATA_AXIS))
    return (jax.make_array_from_process_local_data(sh2, local_attrs),
            jax.make_array_from_process_local_data(sh1, local_labels),
            jax.make_array_from_process_local_data(sh1, local_ids))


def make_global_queries(mesh: jax.sharding.Mesh, local_q_attrs: np.ndarray):
    """Per-process query shards -> a global array sharded on "query"."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    qsh = NamedSharding(mesh, P(QUERY_AXIS, None))
    return jax.make_array_from_process_local_data(qsh, local_q_attrs)
