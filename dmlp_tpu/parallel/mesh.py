"""Device-mesh construction — the declarative replacement for the MPI grid.

The reference hand-rolls a 2D process grid: ``MPI_Dims_create`` →
``MPI_Cart_create`` → ``MPI_Cart_sub`` row/col communicators
(engine.cpp:40-57). On TPU the same topology is one
``jax.sharding.Mesh((R, C), ("data", "query"))``: rows shard the dataset,
columns shard the queries, and per-axis collectives replace the
sub-communicators. The ICI/DCN hierarchy (the reference's
intra-node/inter-node split, run_bench.sh -N 2) comes for free from device
order within the mesh.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh

DATA_AXIS = "data"    # shards the dataset (the reference's grid rows)
QUERY_AXIS = "query"  # shards the queries (the reference's grid columns)


def balanced_dims(n: int) -> Tuple[int, int]:
    """Near-square factorization R x C = n with R >= C.

    The analog of ``MPI_Dims_create(size, 2, dims)`` (engine.cpp:41): the
    data axis gets the larger factor (datasets are usually bigger than query
    batches).
    """
    c = int(n ** 0.5)
    while c > 1 and n % c != 0:
        c -= 1
    return n // c, c


def make_mesh(shape: Optional[Tuple[int, int]] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build the 2D ("data", "query") mesh.

    ``shape=None`` auto-factorizes over all available devices (or the
    ``devices`` given). Explicit shapes must multiply to the device count
    used.
    """
    devices = list(devices if devices is not None else jax.devices())
    if shape is None:
        shape = balanced_dims(len(devices))
    r, c = shape
    if r * c != len(devices):
        raise ValueError(f"mesh shape {shape} != device count {len(devices)}")
    import numpy as np
    return Mesh(np.asarray(devices).reshape(r, c), (DATA_AXIS, QUERY_AXIS))
