"""Training extension — the north-star data/tensor-parallel train loop.

The reference repo's distributed substrate (2D MPI grid + collectives) only
ever served a KNN solve; BASELINE.json's north star asks for the same
substrate carrying a training loop (fwd/bwd matmuls + optimizer, gradient
all-reduce over the mesh). This package provides that, TPU-native: a
pure-JAX MLP classifier, a jitted train step whose gradient sync is
declarative (param shardings make XLA insert the dp all-reduce — the
MPI_Allreduce analog), optax optimizers, orbax checkpoint/resume, and
samples/sec/chip + MFU metrics.
"""

from dmlp_tpu.train.model import init_mlp, mlp_apply  # noqa: F401
from dmlp_tpu.train.step import TrainState, make_train_step  # noqa: F401
